"""Device A/B: Word2Vec embedding-gradient accumulation formulations.

The roofline audit put the SGNS stage at 5% of its ~40M pairs/s bound
and attributed it to the per-step row scatters (49k rows x 512 B
payloads into [vocab, dim]) sort-lowering. The scatter here is
matmul-shaped (one_hot(ids)^T @ grads is a true matrix-matrix product
at d=128), but a materialized one-hot costs bs x vocab x 4 B per table
per step — only an XLA-fused one-hot wins. This probe measures, at the
bench shape (vocab 32k, d=128, bs 8192, 5 negatives):

  scatter  — .at[ids].add(rows) (the product trainer's formulation)
  onehot   — jnp.einsum('bv,bd->vd', one_hot(ids), rows): does XLA fuse
             the iota-compare into the dot operand or materialize 1 GB?
  segsum   — jax.ops.segment_sum over rows (same scatter class, checks
             whether the lowering differs from .at[].add)

Prints ms/step per formulation; a winner >=2x faster than `scatter`
justifies a gated product variant.
"""

import time

import numpy as np

from flinkml_tpu.utils.device_lock import device_client_lock

VOCAB, DIM, BS, N_NEG, STEPS = 32_768, 128, 8_192, 5, 100


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    n_rows = BS * (1 + N_NEG)   # ctx + negatives (the u-table update)
    ids = jnp.asarray(rng.integers(0, VOCAB, size=n_rows).astype(np.int32))
    rows = jnp.asarray(rng.normal(size=(n_rows, DIM)).astype(np.float32))

    def loop(accum_fn):
        @jax.jit
        def run(rows):
            def body(i, acc):
                return acc + accum_fn(rows * (1.0 + 1e-6 * i))[0, 0]
            return jax.lax.fori_loop(0, STEPS, body, jnp.float32(0))
        return run

    variants = {
        "scatter": lambda r: jnp.zeros((VOCAB, DIM)).at[ids].add(r),
        "onehot": lambda r: jnp.einsum(
            "bv,bd->vd",
            jax.nn.one_hot(ids, VOCAB, dtype=jnp.float32), r,
        ),
        "segsum": lambda r: jax.ops.segment_sum(
            r, ids, num_segments=VOCAB
        ),
    }
    for name, fn in variants.items():
        run = loop(fn)
        try:
            np.asarray(run(rows))       # compile + warm
            t0 = time.perf_counter()
            np.asarray(run(rows))
            dt = time.perf_counter() - t0
            print(f"{name:8s}: {dt * 1e3 / STEPS:8.3f} ms/step", flush=True)
        except Exception as e:  # noqa: BLE001 — e.g. OOM on materialized OH
            print(f"{name:8s}: FAILED ({type(e).__name__}: {e})", flush=True)


if __name__ == "__main__":
    with device_client_lock():
        main()
