"""Device A/B: sparse gradient layouts at the Criteo profile.

Runs the PRODUCT bucketed trainer (`_sparse_trainer_bucketed`, the exact
program `LinearModel.fit` and bench's sparse stage dispatch) at the bench
sparse shape (262k rows x 39 nnz, dim = 1e6) once per layout:

  unsorted — per-step segment_sum (round-4 measured winner: 69.1 ms/step)
  sorted   — round-3 pack-sorted + indices_are_sorted (90.9 ms/step)
  cumsum   — round-5 sort-free layout: pack-time column-sorted cells with
             values + row ids; step = small mult-gather, one running sum,
             boundary differences, <=max_d sorted unique adds.

Prints ms/step + samples/s per layout; the winner sets the product
default (the measured-defaults discipline of BASELINE.md). A second
cumsum run uses Zipf(1.2) column ids — the realistic Criteo frequency
profile — to check the layout's sensitivity to run-length distribution
(uniform ids produce ~cells distinct runs; Zipf produces hot runs).
"""

import time

import numpy as np

from flinkml_tpu.utils.device_lock import device_client_lock

N, NNZ, DIM, STEPS = 262_144, 39, 1_000_000, 50


def make_csr(col_dist, seed=0):
    from bench import make_criteo_csr

    indptr, indices, values, y, w = make_criteo_csr(N, DIM, NNZ, seed)
    if col_dist == "zipf":  # the Criteo-like frequency skew
        rng = np.random.default_rng(seed + 1)
        indices = np.minimum(
            rng.zipf(1.2, size=N * NNZ) - 1, DIM - 1
        ).astype(np.int32)
    return indptr, indices, values, y, w


def run(layout, col_dist):
    import jax.numpy as jnp
    from flinkml_tpu.models import _linear_sgd
    from flinkml_tpu.parallel import DeviceMesh

    indptr, indices, values, y, w = make_csr(col_dist)
    mesh = DeviceMesh()
    t0 = time.perf_counter()
    data_args, local_bss = _linear_sgd.prepare_sparse_buckets(
        indptr, indices, values, DIM, y, w, mesh, N, seed=0, layout=layout,
    )
    pack_s = time.perf_counter() - t0
    trainer = _linear_sgd._sparse_trainer_bucketed(
        mesh.mesh, "logistic", local_bss, DeviceMesh.DATA_AXIS, DIM, layout,
    )
    f32 = lambda v: jnp.asarray(v, jnp.float32)
    carry0 = (
        jnp.zeros(DIM, jnp.float32),
        jnp.asarray(0, jnp.int32),
        jnp.asarray(jnp.inf, jnp.float32),
    )
    hy = (f32(0.1), f32(0.0), f32(0.0), f32(0.0))
    np.asarray(trainer(*carry0, *data_args, *hy,
                       jnp.asarray(3, jnp.int32))[0])  # compile + warm
    t0 = time.perf_counter()
    coef, steps_out, _ = trainer(
        *carry0, *data_args, *hy, jnp.asarray(STEPS, jnp.int32)
    )
    np.asarray(coef)
    dt = time.perf_counter() - t0
    assert int(steps_out) == STEPS, int(steps_out)
    bs = sum(local_bss) * mesh.axis_size()
    print(
        f"{layout:9s} {col_dist:8s}: {dt * 1e3 / STEPS:8.2f} ms/step  "
        f"-> {bs * STEPS / dt / 1e6:8.2f}M samples/s  "
        f"(pack {pack_s:.1f}s)",
        flush=True,
    )


def main():
    for layout in ("unsorted", "cumsum", "sorted"):
        run(layout, "uniform")
    run("cumsum", "zipf")
    run("unsorted", "zipf")


if __name__ == "__main__":
    with device_client_lock():
        main()
