"""Tiny device health probe: init + one transfer + one matmul.

Exit 0 and print HEALTHY if the device answers; used by the health-watch
loop and as a preflight before any device work. Takes the single-tenant
device-client lock so it can never itself be the second client that
wedges the tunnel (BASELINE.md round-2 "Tunnel wedge observed").
"""

import sys
import time


def main() -> int:
    from flinkml_tpu.utils.device_lock import device_client_lock

    with device_client_lock(timeout_s=60.0):
        t0 = time.time()
        import jax
        import jax.numpy as jnp
        import numpy as np

        devices = jax.devices()
        t1 = time.time()
        x = jnp.ones((1024, 1024))
        r = np.asarray(x @ x)
        t2 = time.time()
        print(
            f"HEALTHY devices={devices} init={t1 - t0:.1f}s "
            f"matmul={t2 - t1:.1f}s checksum={float(r[0, 0])}",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
