"""Characterize the d >= 512 compile-time ceiling (VERDICT r2 item 6).

Times COMPILATION (not execution) of the exact product programs the bench
could not fit at MNIST-784 shapes — the whole-loop KMeans trainer and the
dense-LR trainer — across widths, on the current backend. Run twice:

    JAX_PLATFORMS=cpu python tools/compile_ceiling_probe.py   # XLA:CPU
    python tools/compile_ceiling_probe.py                     # device

If the CPU curve stays flat while the device curve blows up, the cost is
in the TPU backend (Mosaic/XLA:TPU lowering or the tunnel), not in the
program structure; if both blow up, the program shape itself is the
problem and needs restructuring (e.g. shape bucketing).

Each (workload, d) compile runs in a CHILD process with a fresh, empty
compile cache dir so times are cold and one hang cannot kill the sweep.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

_INNER = "_COMPILE_PROBE_INNER"


def _inner(spec: str) -> None:
    kind, d_str = spec.split(":")
    d = int(d_str)
    cache = tempfile.mkdtemp(prefix="compile-probe-cache-")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache
    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from flinkml_tpu.parallel import DeviceMesh

    mesh = DeviceMesh()
    t0 = time.perf_counter()
    if kind == "kmeans":
        from flinkml_tpu.models.kmeans import (
            _kmeans_trainer,
            prepare_kmeans_data,
        )

        n, k = 65_536, 64
        x = np.zeros((n, d), np.float32)
        xd, wd, _ = prepare_kmeans_data(x, mesh)
        trainer = _kmeans_trainer(mesh.mesh, k, DeviceMesh.DATA_AXIS)
        lowered = trainer.lower(
            xd, wd, jnp.zeros((k, d), jnp.float32),
            jnp.asarray(3, jnp.int32),
        )
        t_lower = time.perf_counter() - t0
        t1 = time.perf_counter()
        lowered.compile()
        t_compile = time.perf_counter() - t1
    else:  # dense LR
        from flinkml_tpu.models import _linear_sgd
        from flinkml_tpu.models.logistic_regression import _device_trainer

        n = 65_536
        p = mesh.axis_size()
        local_bs = _linear_sgd.align_local_bs(8_192, p, n // p)
        trainer = _device_trainer(mesh.mesh, local_bs, DeviceMesh.DATA_AXIS)
        xd = mesh.shard_batch(np.zeros((n, d), np.float32))
        yd = mesh.shard_batch(np.zeros(n, np.float32))
        wd = mesh.shard_batch(np.ones(n, np.float32))
        f32 = lambda v: jnp.asarray(v, jnp.float32)
        lowered = trainer.lower(
            jnp.zeros(d, jnp.float32), jnp.asarray(0, jnp.int32),
            jnp.asarray(jnp.inf, jnp.float32),
            xd, yd, wd, f32(0.1), f32(0.0), f32(0.0), f32(0.0),
            jnp.asarray(10, jnp.int32),
        )
        t_lower = time.perf_counter() - t0
        t1 = time.perf_counter()
        lowered.compile()
        t_compile = time.perf_counter() - t1
    print(json.dumps({
        "kind": kind, "d": d, "lower_s": round(t_lower, 2),
        "compile_s": round(t_compile, 2),
        "platform": jax.devices()[0].platform,
    }))


def main() -> None:
    from flinkml_tpu.utils.device_lock import device_client_lock

    per_case_timeout = float(os.environ.get("COMPILE_PROBE_TIMEOUT", "900"))
    cases = [
        f"{kind}:{d}"
        for kind in ("kmeans", "dense")
        for d in (128, 256, 512, 784)
    ]
    with device_client_lock():
        for spec in cases:
            t0 = time.perf_counter()
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    env={**os.environ, _INNER: spec},
                    timeout=per_case_timeout,
                    stdout=subprocess.PIPE, text=True,
                )
                out = proc.stdout.strip().splitlines()
                print(out[-1] if out else f"{spec}: rc={proc.returncode}",
                      flush=True)
            except subprocess.TimeoutExpired:
                print(json.dumps({
                    "case": spec, "timeout_s": per_case_timeout,
                    "elapsed": round(time.perf_counter() - t0, 1),
                }), flush=True)


if __name__ == "__main__":
    if os.environ.get(_INNER):
        _inner(os.environ[_INNER])
    else:
        main()
