"""Microbenchmark: gather/scatter bounds-check modes at Criteo shapes.

The sparse LR step is gather/scatter-bound (BASELINE.md round-4
sorted-scatter A/B). Both hot ops run in XLA's default CLIP mode even
though the ELL ids are in-bounds by construction (pack pads with real
column ids); PROMISE_IN_BOUNDS removes the clamp from the hot loop.
Compares one full forward+scatter step (gather coef[ids] -> weighted
reduce -> segment_sum back to [dim]) across the 2x2 of modes.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.utils.device_lock import device_client_lock

n_rows, nnz, dim, steps = 262_144, 39, 1_000_000, 20
rng = np.random.default_rng(0)
ids2d = rng.integers(0, dim, (n_rows, nnz)).astype(np.int32)
vals2d = rng.normal(size=(n_rows, nnz)).astype(np.float32)
PIB = jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS


def loop(gather_pib: bool, scatter_pib: bool):
    ids_d = jnp.asarray(ids2d)
    vals_d = jnp.asarray(vals2d)
    flat_ids = ids_d.reshape(-1)

    @jax.jit
    def run(coef):
        def body(i, c):
            if gather_pib:
                g = c.at[ids_d].get(mode=PIB)
            else:
                g = c[ids_d]
            dot = jnp.sum(vals_d * g, axis=1)
            contrib = (vals_d * dot[:, None]).reshape(-1)
            grad = jax.ops.segment_sum(
                contrib, flat_ids, num_segments=dim,
                mode=PIB if scatter_pib else None,
            )
            return c - 1e-9 * grad

        return jax.lax.fori_loop(0, steps, body, coef)

    return run


def main():
    coef = jnp.zeros(dim, jnp.float32)
    for name, gp, sp in [
        ("clip gather, clip scatter (today)", False, False),
        ("PIB  gather, clip scatter       ", True, False),
        ("clip gather, PIB  scatter       ", False, True),
        ("PIB  gather, PIB  scatter       ", True, True),
    ]:
        fn = loop(gp, sp)
        np.asarray(fn(coef))  # compile + warm
        t0 = time.perf_counter()
        np.asarray(fn(coef))
        dt = time.perf_counter() - t0
        print(f"{name}: {dt*1e3/steps:7.2f} ms/step -> "
              f"{n_rows*steps/dt/1e6:6.2f}M samples/s", flush=True)


if __name__ == "__main__":
    with device_client_lock():
        main()
