#!/bin/bash
# Persistent tunnel watcher. Probes every WATCH_INTERVAL seconds (default
# 900); on the FIRST healthy probe runs the device evidence in PRIORITY
# order — the staged bench first (the round's headline number), then the
# sorted-scatter A/B, then the compile-ceiling sweep — and exits. The
# 2026-07-31 session burned its only healthy window (~1 min) on the A/B
# probes; the bench-first order is the lesson. Logs everything to
# tools/device_watch_<UTC>.log. Single device client at all times.
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:/root/.axon_site${PYTHONPATH:+:$PYTHONPATH}"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
LOG="tools/device_watch_${STAMP}.log"
exec > >(tee "$LOG") 2>&1
INTERVAL="${WATCH_INTERVAL:-900}"
DEADLINE="${WATCH_DEADLINE_EPOCH:-0}"   # 0 = watch forever

echo "=== device watch ${STAMP} (interval ${INTERVAL}s) ==="
while :; do
    if [ "$DEADLINE" != 0 ] && [ "$(date +%s)" -ge "$DEADLINE" ]; then
        echo "$(date -u +%FT%TZ) deadline reached; tunnel never healed"
        exit 1
    fi
    if timeout 90 python tools/device_probe.py; then
        echo "$(date -u +%FT%TZ) HEALTHY — capturing evidence (bench first)"
        break
    fi
    echo "$(date -u +%FT%TZ) probe failed; sleeping ${INTERVAL}s"
    sleep "$INTERVAL"
done

echo "--- 1. full staged bench ---"
# The watcher can afford a bigger budget than the driver's 1680 s
# default: 13 stages on a cold compile cache took ~50 min in the
# round-4 healthy window. bench still reserves headroom internally.
FLINKML_BENCH_TIMEOUT="${FLINKML_BENCH_TIMEOUT:-3300}" \
timeout $(( ${FLINKML_BENCH_TIMEOUT:-3300} + 600 )) python bench.py \
    || echo "bench FAILED rc=$?"

echo "--- 2. sparse layout A/B (1200 s cap) ---"
timeout 1200 python tools/sparse_layout_probe.py \
    || echo "sparse_layout_probe FAILED rc=$?"

echo "--- 2b. GBT histogram layout A/B (900 s cap) ---"
timeout 900 python tools/gbt_hist_probe.py \
    || echo "gbt_hist_probe FAILED rc=$?"

echo "--- 2c. ALS reduction A/B (900 s cap) ---"
timeout 900 python tools/als_reduction_probe.py \
    || echo "als_reduction_probe FAILED rc=$?"

echo "--- 2d. W2V scatter-formulation A/B (600 s cap) ---"
timeout 600 python tools/w2v_scatter_probe.py \
    || echo "w2v_scatter_probe FAILED rc=$?"

echo "--- 3. gather/scatter bounds-mode A/B (600 s cap) ---"
timeout 600 python tools/sparse_pib_probe.py \
    || echo "sparse_pib_probe FAILED rc=$?"

echo "--- 4. bf16 dense profile trace (600 s cap) ---"
timeout 600 python tools/bf16_profile_probe.py \
    || echo "bf16_profile_probe FAILED rc=$?"

echo "=== done; transcribe results into BASELINE.md (log: $LOG) ==="
