"""Device A/B: GBT per-level histogram layouts at the bench shape.

The roofline audit (BASELINE.md "rooflines") measured the GBT stage at
0.22% of its streaming bound and diagnosed the per-level sort-based
``segment_sum`` over n·d cells — the same class as sparse LR. The
``cumsum`` layout sorts cells ONCE at pack time by the static
(feature, bin) key and reduces each level's 2^level-wide node-one-hot
expansion with chunked run totals (streaming passes, no sort).

Runs the bench GBT stage (262k rows, 16 features, 32 bins, depth 4,
20 trees) once per layout through the product builder; the winner sets
the FLINKML_TPU_GBT_HISTOGRAM default. Forests are verified identical
(same split features across layouts) before timing is trusted.
"""

import time

import numpy as np

from flinkml_tpu.utils.device_lock import device_client_lock

N, D, BINS, DEPTH, TREES = 262_144, 16, 32, 4, 20


def run(layout):
    import jax
    import jax.numpy as jnp
    from flinkml_tpu.models.gbt import (
        _forest_builder, bin_features, quantile_bin_edges,
        sharded_hist_args,
    )
    from flinkml_tpu.parallel import DeviceMesh

    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, size=(N, D)).astype(np.float32)
    y = (x[:, 0] * x[:, 1] > 0).astype(np.float32)
    w = np.ones(N, dtype=np.float32)
    binned = bin_features(x, quantile_bin_edges(x, BINS))
    mesh = DeviceMesh()
    builder = _forest_builder(
        mesh.mesh, DeviceMesh.DATA_AXIS, D, BINS, DEPTH, TREES, True,
        hist_layout=layout,
    )
    f32 = lambda v: jnp.asarray(v, jnp.float32)
    hist_args = sharded_hist_args(binned, mesh, BINS, layout)
    args = (
        mesh.shard_batch(binned), mesh.shard_batch(y), mesh.shard_batch(w),
        f32(0.0), f32(0.2), f32(1.0), f32(1.0), jax.random.PRNGKey(0),
    ) + hist_args
    feats = np.asarray(builder(*args)[0])       # compile + warm
    t0 = time.perf_counter()
    np.asarray(builder(*args)[2])
    dt = time.perf_counter() - t0
    print(
        f"{layout:8s}: {dt:6.2f}s/forest -> "
        f"{N * TREES / dt / 1e3:9.1f}k row-trees/s",
        flush=True,
    )
    return feats


def main():
    f_seg = run("segment")
    f_cum = run("cumsum")
    same = (f_seg == f_cum).mean()
    print(f"split-feature agreement: {same:.4f}", flush=True)
    assert same > 0.99, "layouts built different forests — timing invalid"


if __name__ == "__main__":
    with device_client_lock():
        main()
