"""Device A/B: ALS normal-equation reductions at the bench shape.

The roofline audit (BASELINE.md "rooflines") measured the ALS stage at
1.4% of its streaming bound — the sort-based ``segment_sum`` dragging a
4 KB-per-rating payload through a sort every chunk. The ``cumsum``
reduction sorts the COO by target once at pack time and reduces at
static run boundaries (streaming passes + a runs-sized sorted scatter).

Runs the bench ALS stage (16k x 16k, 2M ratings, rank 32, 10 iters)
through the public ``ALS.fit`` once per layout; the winner sets the
FLINKML_TPU_ALS_REDUCTION default.
"""

import os
import time

import numpy as np

from flinkml_tpu.utils.device_lock import device_client_lock

N_USERS, N_ITEMS, NNZ, RANK, ITERS = 16_384, 16_384, 1 << 21, 32, 10


def run(layout):
    from flinkml_tpu.models.als import ALS
    from flinkml_tpu.table import Table

    os.environ["FLINKML_TPU_ALS_REDUCTION"] = layout
    rng = np.random.default_rng(0)
    table = Table({
        "user": rng.integers(0, N_USERS, size=NNZ).astype(np.int32),
        "item": rng.integers(0, N_ITEMS, size=NNZ).astype(np.int32),
        "rating": rng.uniform(1, 5, size=NNZ).astype(np.float32),
    })
    ALS().set_rank(RANK).set_max_iter(1).set_seed(0).fit(table)  # warm
    t0 = time.perf_counter()
    m = ALS().set_rank(RANK).set_max_iter(ITERS).set_seed(0).fit(table)
    dt = time.perf_counter() - t0
    print(
        f"{layout:8s}: {dt:6.2f}s -> "
        f"{NNZ * 2 * ITERS / dt / 1e6:8.2f}M rating-visits/s",
        flush=True,
    )
    return m._user_factors


def main():
    u_seg = run("segment")
    u_cum = run("cumsum")
    diff = float(np.abs(u_seg - u_cum).max())
    print(f"factor max |diff|: {diff:.2e}", flush=True)
    assert diff < 1e-3, "layouts diverged — timing invalid"


if __name__ == "__main__":
    with device_client_lock():
        main()
