#!/bin/bash
# One-command CI (the reference's tools/ci/ role): lint, full suite,
# 8-device sharding dryrun, bench smoke, example smoke — everything runs
# on the host CPU (FLINKML_BENCH_SKIP_DEVICE=1 keeps the bench off the
# single-tenant tunnel), so this is safe to run any time, including
# while a device capture is in flight.
#
#   bash tools/ci.sh            # full run (suite ~8 min)
#   CI_FAST=1 bash tools/ci.sh  # skip the full pytest suite (rest ~3 min)
#
# Exit code 0 = every stage green. Log: tools/ci_<UTC>.log
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:/root/.axon_site${PYTHONPATH:+:$PYTHONPATH}"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
LOG="tools/ci_${STAMP}.log"
exec > >(tee "$LOG") 2>&1

FAIL=0
stage() {  # stage <name> <cmd...>
    local name=$1; shift
    echo "=== ci: $name ==="
    local t0=$SECONDS
    if "$@"; then
        echo "=== ci: $name OK ($((SECONDS - t0))s) ==="
    else
        echo "=== ci: $name FAILED rc=$? ($((SECONDS - t0))s) ==="
        FAIL=1
    fi
}

stage "lint (compileall)" python -m compileall -q \
    flinkml_tpu tests tools examples bench.py __graft_entry__.py

# Ahead-of-time analysis gate (docs/development/static_analysis.md):
# examples must lint clean (all three passes, device-free), and the
# seeded fixtures must FAIL — proving the gate has teeth.
stage "analysis gate (examples clean)" env JAX_PLATFORMS=cpu \
    python -m flinkml_tpu.analysis examples/ --fail-on-findings
analysis_fixture_gate() {
    if env JAX_PLATFORMS=cpu python -m flinkml_tpu.analysis \
        tests/analysis_fixtures/ --no-selfcheck --fail-on-findings; then
        echo "analysis gate passed the seeded-findings fixtures (it must flag them)"
        return 1
    fi
    return 0
}
stage "analysis gate (fixtures flagged)" analysis_fixture_gate

if [ "${CI_FAST:-0}" != 1 ]; then
    stage "full suite" python -m pytest tests/ -x -q
fi

stage "8-device dryrun" env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -c "
import jax
jax.config.update('jax_platforms', 'cpu')
import __graft_entry__ as g
g.entry()
g.dryrun_multichip(8)
"

bench_smoke() {
    local out
    out=$(FLINKML_BENCH_SKIP_DEVICE=1 timeout 600 python bench.py) || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
assert {'metric', 'value', 'unit', 'vs_baseline'} <= set(rec), rec
assert 'cpu_fallback' in rec['metric'], rec['metric']
print('bench smoke: parseable result line:', rec['metric'], rec['value'])
"
}
stage "bench smoke (CPU, no tunnel)" bench_smoke

# End-to-end serving demo (ISSUE 3 acceptance): fit → publish v1 → serve
# concurrent clients with bitwise parity → publish v2+ from a running
# unbounded training stream → hot-swap with zero dropped/mis-versioned
# responses and zero steady-state retraces (guard-verified in-script).
serving_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        timeout 420 python examples/serve_pipeline.py || return 1
    local out
    out=$(_FLINKML_BENCH_INNER=serving_cpu timeout 420 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
assert {'serving_rows_per_sec', 'serving_p50_ms', 'serving_p99_ms',
        'serving_batch_occupancy'} <= set(rec), rec
print('serving smoke: rows/s', rec['serving_rows_per_sec'],
      'p50', rec['serving_p50_ms'], 'p99', rec['serving_p99_ms'],
      'occupancy', rec['serving_batch_occupancy'])
"
}
stage "serving smoke (CPU)" serving_smoke

# Serving scale-out smoke (ISSUE 8 acceptance): a device-free 4-replica
# ReplicaPool serves concurrent closed-loop clients with bitwise parity
# and correct version tags; ONE replica is killed mid-traffic through
# the serving.replica fault seam — zero dropped and zero mis-versioned
# responses (the router retries the dead replica's traffic on healthy
# ones), the replica is retired, and the pool keeps serving. Then the
# serving_scaleout_cpu bench stage must emit per-replica rows/s and the
# continuous-vs-FIFO p50 comparison.
serving_scaleout_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 420 python - <<'EOF' || return 1
import threading, time, tempfile

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu import faults
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.serving import ModelRegistry, ReplicaPool, ServingConfig
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
x = rng.normal(size=(200, 6))
y = (x @ rng.normal(size=6) > 0).astype(np.float64)
train = Table({"features": x, "label": y})
sc = (StandardScaler().set(StandardScaler.INPUT_COL, "features")
      .set(StandardScaler.OUTPUT_COL, "scaled").fit(train))
(t2,) = sc.transform(train)
lr = (LogisticRegression().set(LogisticRegression.FEATURES_COL, "scaled")
      .set(LogisticRegression.LABEL_COL, "label").set_max_iter(3).fit(t2))
pm = PipelineModel([sc, lr])

with tempfile.TemporaryDirectory() as td:
    reg = ModelRegistry(td)
    reg.publish(pm)
    pool = ReplicaPool(
        reg, Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=64, max_queue_rows=512,
                             max_wait_ms=1.0),
        n_replicas=4, output_cols=("prediction",), name="ci_pool",
    ).start()
    pool.follow_registry()
    errors, served, stop = [], [0], threading.Event()

    def client(tid):
        crng = np.random.default_rng(tid)
        try:
            while not stop.is_set():
                rows = int(crng.integers(1, 7))
                lo = int(crng.integers(0, x.shape[0] - rows))
                sl = x[lo:lo + rows]
                resp = pool.predict({"features": sl})
                assert resp.version == 1, f"mis-versioned: {resp.version}"
                (ref,) = pm.transform(Table({"features": sl}))
                np.testing.assert_array_equal(
                    np.asarray(ref.column("prediction")),
                    resp.column("prediction"))
                served[0] += 1
        except BaseException as e:
            errors.append(e)

    with faults.armed(faults.FaultPlan(
            faults.ReplicaDown("r1", at_batch=2))) as plan:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if pool.stats()["per_replica"]["r1"]["state"] == "unhealthy":
                break
            time.sleep(0.05)
        at_kill = served[0]
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert not errors, errors[:3]
    st = pool.stats()
    assert st["per_replica"]["r1"]["state"] == "unhealthy", st["per_replica"]
    assert st["healthy"] == 3
    assert served[0] > at_kill, "pool stopped serving after the kill"
    assert st["router"].get("failovers", 0) >= 1
    assert any(site == "serving.replica" for site, _, _ in plan.log)
    pool.stop()
    print(f"serving scaleout smoke: {served[0]} responses, kill r1 ->",
          "0 dropped / 0 mis-versioned, pool continued on 3 replicas")
EOF
    local out
    out=$(_FLINKML_BENCH_INNER=serving_scaleout_cpu timeout 420 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
assert {'serving_scaleout_rows_per_sec', 'serving_rows_per_sec_per_replica',
        'pool_p50_ms', 'pool_p99_ms', 'fifo_p50_ms',
        'continuous_p50_ms'} <= set(rec), rec
per = rec['serving_rows_per_sec_per_replica']
assert per and all(v > 0 for v in per.values()), per
# Regression tripwire, not the acceptance measurement: observed gap is
# ~12x in continuous batching's favor, but a loaded/starved CI box can
# jitter near-equal p50s, so allow slack instead of hard-failing noise.
assert rec['continuous_p50_ms'] <= rec['fifo_p50_ms'] * 1.25, (
    'continuous batching p50 regressed above FIFO packing', rec)
print('serving scaleout smoke: rows/s', rec['serving_scaleout_rows_per_sec'],
      'per-replica', per, 'p50/p99', rec['pool_p50_ms'], rec['pool_p99_ms'],
      'cont-vs-fifo p50', rec['continuous_vs_fifo_p50'],
      'speedup', rec['pool_speedup_vs_single_engine'],
      f\"({rec['replicas']} replicas on {rec['host_cpu_count']} cores)\")
"
}
stage "serving scaleout smoke (4-replica chaos + bench)" serving_scaleout_smoke

# Gray-failure smoke (ISSUE 19 acceptance): a device-free 4-replica pool
# under closed-loop load has ONE replica stalled ~100x per batch through
# the serving.replica seam (StallDispatch — alive, passing dispatches,
# dragging tail latency). The GrayFailGuard must quarantine it (SLOW, out
# of routing WITHOUT killing it), the pool must keep serving with zero
# lost / zero mis-served responses, p99 must recover, and the replica
# must rejoin via canary probes once the stall clears. The new fault
# specs are fixture-gated (JSON round-trip + deterministic jitter), then
# the serving_grayfail_cpu bench stage must emit the pinned keys.
grayfail_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 420 python - <<'EOF' || return 1
import threading, time

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu import faults
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.recovery.fuzz import serving_grayfail_policy
from flinkml_tpu.serving import ReplicaPool, ServingConfig
from flinkml_tpu.serving.health import ReplicaState
from flinkml_tpu.table import Table

# -- fixture gate: the new fault specs must survive a JSON round-trip
# and replay deterministically (they are what soak repros commit).
for name in ("StallDispatch", "JitterDispatch", "SlowRamp"):
    assert name in faults.fault_types(), name
plan = faults.FaultPlan(
    faults.StallDispatch("r1", at_batch=2, delay_s=0.05, for_batches=3),
    faults.JitterDispatch("r0", p=0.5, delay_s=0.0, seed=7),
    faults.SlowRamp("r2", at_batch=1, step_s=0.01, max_s=0.1),
)
clone = faults.plan_from_json(faults.plan_to_json(plan))
assert [faults.fault_to_spec(f) for f in clone.faults] == \
    [faults.fault_to_spec(f) for f in plan.faults]
ctx = {"engine": "pool/r0"}
assert [plan.faults[1].should_fire(ctx) for _ in range(32)] == \
    [clone.faults[1].should_fire(ctx) for _ in range(32)], \
    "jitter draws not deterministic in the committed seed"

rng = np.random.default_rng(0)
x = rng.normal(size=(256, 6))
model = (StandardScaler().set(StandardScaler.INPUT_COL, "features")
         .set(StandardScaler.OUTPUT_COL, "scaled")
         .fit(Table({"features": x})))
(ref,) = model.transform(Table({"features": x}))
expected = np.asarray(ref.column("scaled"))

pool = ReplicaPool(
    model, Table({"features": x[:4]}),
    config=ServingConfig(max_batch_rows=64, max_queue_rows=512,
                         max_wait_ms=1.0, default_timeout_ms=15_000.0),
    n_replicas=4, output_cols=("scaled",), name="ci_gf_pool",
    grayfail=serving_grayfail_policy(),
).start()
guard = pool.grayfail_guard(interval_s=0.05).start()
errors, served, stop = [], [0], threading.Event()
lat, lat_lock = [], threading.Lock()

def client(tid):
    crng = np.random.default_rng(tid)
    try:
        while not stop.is_set():
            lo = int(crng.integers(0, x.shape[0] - 4))
            t0 = time.perf_counter()
            resp = pool.predict({"features": x[lo:lo + 4]},
                                timeout_ms=5000.0)
            with lat_lock:
                lat.append((time.perf_counter(),
                            (time.perf_counter() - t0) * 1e3))
            np.testing.assert_array_equal(
                np.asarray(resp.columns["scaled"]), expected[lo:lo + 4])
            served[0] += 1
            time.sleep(0.002)
    except BaseException as e:
        errors.append(e)

def p99_since(t0):
    with lat_lock:
        vals = sorted(ms for (tc, ms) in lat if tc >= t0)
    return vals[min(len(vals) - 1, int(np.ceil(0.99 * len(vals))) - 1)]

threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
for t in threads:
    t.start()
base_t0 = time.perf_counter()
time.sleep(1.0)
p99_base = p99_since(base_t0)

# ~100x a CPU batch: the scaler batch is ~2 ms, the stall is 200 ms.
with faults.armed(faults.FaultPlan(faults.StallDispatch("r1", delay_s=0.2))):
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if pool.replicas[1].health.state is ReplicaState.SLOW:
            break
        time.sleep(0.02)
    assert pool.replicas[1].health.state is ReplicaState.SLOW, \
        "guard never quarantined the stalled replica"
    assert pool.stats()["healthy"] == 3
    at_quarantine = served[0]
    time.sleep(0.5)
    assert served[0] > at_quarantine, "pool stopped serving post-quarantine"

deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    if pool.replicas[1].health.state is ReplicaState.HEALTHY:
        break
    time.sleep(0.02)
rejoin_t = time.perf_counter()
time.sleep(0.5)
stop.set()
for t in threads:
    t.join(timeout=60)
assert not errors, errors[:3]
assert pool.replicas[1].health.state is ReplicaState.HEALTHY, \
    "replica never rejoined after the stall cleared"
gc = guard._metrics.snapshot()["counters"]
assert gc.get("quarantines_total", 0) >= 1, gc
assert gc.get("rejoins_total", 0) >= 1, gc
p99_after = p99_since(rejoin_t)
assert p99_after <= max(2.0 * p99_base, p99_base + 50.0), \
    (p99_base, p99_after)
guard.stop()
pool.stop(drain=False, timeout=30.0)
print(f"grayfail smoke: {served[0]} responses, stall r1 200ms -> SLOW in "
      f"<30s, 0 lost / 0 mis-served, rejoined; p99 {p99_base:.1f}ms -> "
      f"{p99_after:.1f}ms")
EOF
    local out
    out=$(_FLINKML_BENCH_INNER=serving_grayfail_cpu timeout 420 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
assert {'p99_during_stall_ms', 'time_to_quarantine_s', 'hedge_win_fraction',
        'baseline_p99_ms', 'recovered_p99_ms',
        'quarantines_total'} <= set(rec), rec
assert rec['quarantines_total'] >= 1, rec
assert rec['time_to_quarantine_s'] is not None, rec
base, recov = rec['baseline_p99_ms'], rec['recovered_p99_ms']
assert recov is not None and recov <= max(2.0 * base, base + 50.0), rec
print('grayfail smoke bench: stall p99', rec['p99_during_stall_ms'], 'ms,',
      'quarantine in', rec['time_to_quarantine_s'], 's,',
      'hedge win fraction', rec['hedge_win_fraction'],
      f\"(recovered {recov} vs baseline {base} ms)\")
"
}
stage "gray-failure smoke (stall quarantine + bench)" grayfail_smoke

# Chaos smoke (ISSUE 4 acceptance): kill an online LR fit under a
# scripted fault plan, corrupt the newest committed snapshot, resume from
# the prior valid one, and require the final model bit-identical to the
# uninterrupted run. Device-free (JAX_PLATFORMS=cpu).
chaos_smoke() {
    JAX_PLATFORMS=cpu timeout 300 python - <<'EOF'
import tempfile

import numpy as np

from flinkml_tpu import faults
from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.models import OnlineLogisticRegression
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
true = rng.normal(size=6) * 2
batches = []
for _ in range(12):
    x = rng.normal(size=(64, 6))
    batches.append(Table({"features": x,
                          "label": (x @ true > 0).astype(np.float64)}))

def fit(**kw):
    return OnlineLogisticRegression().set_alpha(0.5).fit_stream(batches, **kw)

golden = fit()

with tempfile.TemporaryDirectory() as td:
    mgr = CheckpointManager(td, max_to_keep=10)
    plan = faults.FaultPlan(faults.RaiseAtEpoch(7))
    try:
        with faults.armed(plan):
            fit(checkpoint_manager=mgr, checkpoint_interval=2)
        raise SystemExit("injected crash did not fire")
    except faults.FaultInjected:
        pass
    assert mgr.latest_epoch() == 6, mgr.all_epochs()
    corrupted = faults.corrupt_latest(mgr, target="arrays")
    recovered = fit(checkpoint_manager=mgr, checkpoint_interval=2,
                    resume=True)
    assert np.array_equal(recovered.coefficient, golden.coefficient), \
        "resumed model != uninterrupted model"
    assert recovered.model_version == golden.model_version == 12
    print("chaos smoke: killed at epoch 7, corrupted snapshot", corrupted,
          "-> resumed from epoch 4, bit-exact parity")
EOF
}
stage "chaos smoke (kill+corrupt+resume)" chaos_smoke

# Elasticity chaos (ISSUE 6 acceptance): a synthetic-source online LR
# fed by the world-parallel ElasticFeed is killed at world 4 through the
# rank.lost seam (watchdog shrink path: clean stop + terminal snapshot),
# the survivors agree a resume point over the rendezvous, and the run
# resumes at world 2 AND world 8 with batch-sequence parity and a
# bit-identical model. Device-free (JAX_PLATFORMS=cpu).
elasticity_chaos() {
    JAX_PLATFORMS=cpu timeout 300 python - <<'EOF'
import shutil, tempfile, os

import numpy as np

from flinkml_tpu import faults
from flinkml_tpu.data import Dataset, ElasticFeed
from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.models import OnlineLogisticRegression
from flinkml_tpu.table import Table
from flinkml_tpu.utils.preemption import PreemptionWatchdog

B, DIM = 12, 6
TRUE = np.arange(1.0, DIM + 1.0)

def mk(i, rng):
    x = rng.normal(size=(64, DIM))
    return Table({"features": x, "label": (x @ TRUE > 0).astype(np.float64)})

def feed(world):
    return ElasticFeed(
        lambda shard: Dataset.synthetic(mk, B, seed=5, shard=shard), world)

def fit(world, **kw):
    return OnlineLogisticRegression().set_alpha(0.5).fit_stream(
        feed(world), **kw)

# Batch-sequence parity of the feed itself: one canonical global order.
def keys(world):
    return [float(np.asarray(b.column("features"))[0, 0])
            for b in feed(world)]
golden_seq = keys(1)
assert keys(4) == golden_seq and keys(2) == golden_seq and \
    keys(8) == golden_seq, "ElasticFeed global order is world-dependent"

golden = fit(1)

with tempfile.TemporaryDirectory() as td:
    kill_dir = os.path.join(td, "kill")
    mgr = CheckpointManager(kill_dir, max_to_keep=10, rescale="reshard")
    wd = PreemptionWatchdog(signals=())
    with wd:
        with faults.armed(faults.FaultPlan(faults.RankLost(epoch=7,
                                                           rank=2))):
            partial = fit(4, checkpoint_manager=mgr, checkpoint_interval=3)
    assert wd.shrink_requested and wd.lost_ranks == [2]
    assert partial.model_version == 7
    assert mgr.latest_epoch() == 7, mgr.all_epochs()
    plan = wd.plan_elastic_resume(mgr, world=4)
    assert (plan.epoch, plan.old_world, plan.new_world) == (7, 4, 3)
    for world in (2, 8):
        wdir = os.path.join(td, f"w{world}")
        shutil.copytree(kill_dir, wdir)
        m = CheckpointManager(wdir, max_to_keep=10, rescale="reshard")
        rec = fit(world, checkpoint_manager=m, checkpoint_interval=3,
                  resume=True)
        assert np.array_equal(rec.coefficient, golden.coefficient), \
            f"world-{world} resumed model != uninterrupted model"
        assert rec.model_version == golden.model_version == B
        cur = m.last_restored_extra["data_cursor"]
        assert cur["num_shards"] == 4 and cur["emitted"] == 7
    print("elasticity chaos: rank 2 lost at world 4 (epoch 7, snapshot",
          "committed) -> resumed at world 2 and world 8, batch-sequence",
          "parity + bit-exact model")
EOF
}
stage "elasticity chaos (kill@world4 -> resume@2/@8)" elasticity_chaos

# Chaos soak (ISSUE 9 acceptance): a fixed-seed FuzzPlan samples >=25
# fault schedules across the trainer-loop seams (crashes, torn writes,
# snapshot corruption, rank loss, source failures, and the train.step
# numerics faults), runs a self-healing online LR under each one with
# orchestrator-style restarts, and asserts the recovery invariants —
# finite final model, version == batches - quarantined (no silent fresh
# start), bit-parity with the quarantine-excluded golden run, ledger
# naming exactly the poisoned batches. Then shrink-to-repro is
# demonstrated on a seeded failing schedule (self-healing disabled):
# the 3-fault schedule minimizes to the single poison and the written
# FaultPlan artifact replays. Device-free. Finally the recovery bench
# stage must show sentinel overhead < 2%.
chaos_soak() {
    JAX_PLATFORMS=cpu timeout 420 python - <<'EOF' || return 1
import json, os, tempfile

from flinkml_tpu import faults
from flinkml_tpu.recovery.fuzz import (
    GoldenCache, run_schedule, run_soak, shrink_schedule,
)

report = run_soak(seed=7, budget=25, wall_budget_s=300)
assert report.ok, [
    (r.index, r.faults, r.failures) for r in report.failures
] or f"soak truncated: {report.skipped} schedules skipped"
restarts = sum(r.restarts for r in report.results)
quarantined = sum(len(r.quarantined) for r in report.results)
print(f"chaos soak: {len(report.results)} schedules green in "
      f"{report.elapsed_s}s ({restarts} restarts, {quarantined} "
      "quarantined batches, invariants held)")

# Shrink demo: a seeded failing schedule (healing OFF) minimizes to the
# poison alone, and the committed repro artifact replays.
golden = GoldenCache(0)
plan = faults.FaultPlan(faults.TornWrite(3), faults.PoisonBatch(5),
                        faults.RaiseAtEpoch(7))
_, failures, _ = run_schedule(plan, golden, self_heal=False)
assert failures, "seeded schedule did not fail with healing disabled"
minimal = shrink_schedule(
    plan, lambda p: bool(run_schedule(p, golden, self_heal=False)[1]))
assert [f.describe() for f in minimal.faults] == \
    ["PoisonBatch(at_batch=5)"], [f.describe() for f in minimal.faults]
with tempfile.TemporaryDirectory() as td:
    path = os.path.join(td, "fuzz_repro_demo.json")
    with open(path, "w") as f:
        f.write(faults.plan_to_json(minimal, extra={
            "failures": failures, "seed": "demo"}))
    with open(path) as f:
        replay = faults.plan_from_json(f.read())
    _, refailures, _ = run_schedule(replay, golden, self_heal=False)
    assert refailures, "minimal repro did not reproduce the failure"
    _, healed, _ = run_schedule(replay, golden, self_heal=True)
    assert not healed, healed
print("shrink demo: 3-fault failing schedule -> minimal repro "
      "[PoisonBatch(at_batch=5)], artifact replays, heals under policy")
EOF
    local out
    out=$(_FLINKML_BENCH_INNER=recovery_cpu timeout 420 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
assert {'recovery_rows_per_sec_sentinel_off',
        'recovery_rows_per_sec_sentinel_on',
        'sentinel_overhead_frac', 'sentinel_check_frac_of_step'} \
    <= set(rec), rec
# The 2% acceptance bound is asserted on the DIRECT per-check cost
# (median verdict+sync wall / per-batch step wall — stable ~0.5%); the
# end-to-end paired fit ratio keeps a 5% tripwire because ~1s fits on
# this time-shared box see 10-20% multiplicative scheduler noise (the
# same reasoning as the serving stage's continuous-vs-FIFO tripwire).
assert rec['sentinel_check_frac_of_step'] < 0.02, (
    'sentinel per-step cost exceeds the 2% acceptance bound', rec)
assert rec['sentinel_overhead_frac'] < 0.05, (
    'end-to-end sentinel overhead tripwire (5%) exceeded', rec)
print('recovery bench: sentinel off', rec['recovery_rows_per_sec_sentinel_off'],
      'rows/s, on', rec['recovery_rows_per_sec_sentinel_on'],
      'rows/s, per-step cost',
      f\"{rec['sentinel_check_frac_of_step']*100:.2f}%\",
      f\"({rec['sentinel_check_ms']} ms/check), end-to-end\",
      f\"{rec['sentinel_overhead_frac']*100:.2f}%\",
      '| heal p50', rec['time_to_recover_p50_ms'], 'ms')
"
}
stage "chaos soak (25 schedules + shrink demo + sentinel bench)" chaos_soak

# Input-pipeline smoke (ISSUE 5 acceptance): a shuffled CSV-glob Dataset
# drives the fused 5-stage chain through the bucketed async prefetcher
# with ZERO retraces after warmup (TransferRetraceGuard-verified), and a
# pipeline killed mid-stream by an injected source fault resumes from
# its cursor to the exact uninterrupted batch sequence. Device-free.
input_pipeline_smoke() {
    JAX_PLATFORMS=cpu timeout 300 python - <<'EOF'
import tempfile, os

import numpy as np

from flinkml_tpu import faults
from flinkml_tpu.analysis.guard import TransferRetraceGuard
from flinkml_tpu.data import Dataset
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import (
    MaxAbsScaler, MinMaxScaler, RobustScaler, StandardScaler,
)
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
d = 6
with tempfile.TemporaryDirectory() as td:
    for fi in range(4):
        rows = 96 + 32 * fi
        x = rng.normal(size=(rows, d))
        y = (x @ np.arange(1.0, d + 1) > 0).astype(np.float64)
        header = ",".join([f"f{j}" for j in range(d)] + ["label"])
        body = "\n".join(
            ",".join(f"{v:.17g}" for v in row) + f",{yy:.0f}"
            for row, yy in zip(x, y)
        )
        with open(os.path.join(td, f"part-{fi}.csv"), "w") as f:
            f.write(header + "\n" + body + "\n")

    def make_ds():
        return (
            Dataset.from_csv(os.path.join(td, "part-*.csv"), batch_size=48)
            .map(lambda t: Table({
                "features": np.stack([t.column(f"f{j}") for j in range(d)], 1),
                "label": t.column("label"),
            }))
            .shuffle(3, seed=11)
        )

    # Fit the canonical 5-stage all-kernel chain on the full feed.
    full = None
    for b in make_ds():
        full = b if full is None else full.concat(b)
    stages, cur, prev = [], full, "features"
    for i, cls in enumerate(
        (StandardScaler, MinMaxScaler, MaxAbsScaler, RobustScaler), start=1
    ):
        m = cls().set(cls.INPUT_COL, prev).set(cls.OUTPUT_COL, f"s{i}").fit(cur)
        (cur,) = m.transform(cur)
        prev = f"s{i}"
        stages.append(m)
    stages.append(
        LogisticRegression().set(LogisticRegression.FEATURES_COL, prev)
        .set(LogisticRegression.LABEL_COL, "label").set_max_iter(2).fit(cur)
    )
    model = PipelineModel(stages)

    # Warm every bucket the feed will hit, then demand zero retraces.
    fed = make_ds().prefetch(depth=2)
    buckets = set()
    batches = []
    for t in fed:
        batches.append(t)
    for t in batches:
        from flinkml_tpu.pipeline_fusion import row_bucket
        buckets.add(row_bucket(t.num_rows))
    (out,) = model.transform(batches[0])
    out.column("prediction")
    for t in batches[1:]:
        (out,) = model.transform(t)
        out.column("prediction")
    with TransferRetraceGuard(allow_compiles=0, allow_new_buckets=False,
                              location="ci:input_pipeline_smoke"):
        preds = []
        for t in make_ds().prefetch(depth=2):
            (out,) = model.transform(t)
            preds.append(np.asarray(out.column("prediction")))
    n_pred = sum(len(p) for p in preds)
    assert n_pred == full.num_rows, (n_pred, full.num_rows)

    # Kill mid-stream at the data.read seam, resume from the cursor:
    # the delivered sequence must equal the uninterrupted one exactly.
    golden = [np.asarray(b.column("features")) for b in make_ds()]
    it = make_ds().iterate()
    got = []
    try:
        with faults.armed(faults.FaultPlan(faults.RaiseAtRead(at_read=7))):
            for b in it:
                got.append(np.asarray(b.column("features")))
        raise SystemExit("injected read fault did not fire")
    except faults.FaultInjected:
        pass
    cursor = it.cursor()
    it.close()
    for b in make_ds().iterate(cursor):
        got.append(np.asarray(b.column("features")))
    assert len(got) == len(golden), (len(got), len(golden))
    for g, h in zip(golden, got):
        assert np.array_equal(g, h), "resumed batch sequence diverged"
    print(f"input-pipeline smoke: {len(batches)} shuffled CSV batches, "
          f"buckets {sorted(buckets)}, zero retraces, kill@read7 + cursor "
          "resume -> exact batch-sequence parity")
EOF
}
stage "input-pipeline smoke (CPU)" input_pipeline_smoke

# Sharding smoke (ISSUE 7 acceptance): device-free, 8 host-platform
# devices. A parameter + momentum pytree whose replicated per-device
# footprint provably exceeds a configured HBM budget (a) is refused
# pre-compile for the replicated plan (FML503), (b) is routed to FSDP
# by infer_plan, (c) trains FSDP-sharded to the replicated baseline's
# numerics, (d) checkpoints with PLAN-derived layout tags and resumes
# at a different world, and the seeded FML5xx plan fixtures are flagged
# by the analysis CLI. Then the sharded_train_cpu bench stage must emit
# sharded_samples_per_sec per plan preset.
sharding_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 300 python - <<'EOF' || return 1
import json, os, subprocess, sys, tempfile

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.sharding import (
    BATCH_PARALLEL, FSDP, REPLICATED, infer_plan, per_device_state_bytes,
)
from flinkml_tpu.sharding.apply import PlanValidationError, train_linear_plan

dim, n = 64, 96
rng = np.random.default_rng(0)
x = rng.normal(size=(n, dim)).astype(np.float32)
y = (x @ rng.normal(size=dim) > 0).astype(np.float32)

budget = int(dim * 4 * 2 * 0.75)  # coef + momentum replicated: over
assert per_device_state_bytes(
    BATCH_PARALLEL, {"data": 8}, {"coef": (dim,)}) > budget
mesh = DeviceMesh.for_plan(FSDP)
plan = infer_plan(mesh, {"coef": (dim,)}, budget)
assert plan.name == "fsdp"
try:
    train_linear_plan(x, y, None, BATCH_PARALLEL,
                      DeviceMesh.for_plan(BATCH_PARALLEL), max_iter=1,
                      hbm_budget_bytes=budget)
    raise SystemExit("over-budget replicated plan was not refused")
except PlanValidationError as e:
    assert "FML503" in str(e)

golden = train_linear_plan(x, y, None, REPLICATED,
                           DeviceMesh.for_plan(REPLICATED),
                           max_iter=10, learning_rate=0.5)
with tempfile.TemporaryDirectory() as td:
    mgr = CheckpointManager(td, rescale="reshard")
    coef = train_linear_plan(
        x, y, None, plan, mesh, max_iter=10, learning_rate=0.5,
        hbm_budget_bytes=budget, checkpoint_manager=mgr,
        checkpoint_interval=5,
    )
    np.testing.assert_allclose(coef, golden, rtol=1e-5, atol=1e-7)
    with open(os.path.join(td, "ckpt-10", "meta.json")) as fh:
        meta = json.load(fh)
    assert meta["layouts"] == ["sharded:0", "sharded:0"], meta["layouts"]
    assert meta["world_size"] == 8
    mesh2 = DeviceMesh.for_plan(FSDP, devices=jax.devices()[:2])
    coef2 = train_linear_plan(
        x, y, None, FSDP, mesh2, max_iter=10, learning_rate=0.5,
        checkpoint_manager=CheckpointManager(td, rescale="reshard"),
        checkpoint_interval=5, resume=True,
    )
    assert np.array_equal(coef2, coef), "world-2 resume != world-8 model"

rc = subprocess.run(
    [sys.executable, "-m", "flinkml_tpu.analysis",
     "tests/analysis_fixtures/bad_plan_fml502_indivisible.plan.json",
     "--no-selfcheck"], stdout=subprocess.DEVNULL,
).returncode
assert rc == 1, "seeded FML5xx plan fixture was not flagged"
print("sharding smoke: infer->fsdp, FML503 refusal pre-compile, FSDP",
      "parity vs replicated, plan-tagged snapshot resumed at world 2,",
      "FML5xx fixtures flagged")
EOF
    local out
    out=$(_FLINKML_BENCH_INNER=sharded_train_cpu timeout 420 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
rates = rec['sharded_samples_per_sec']
assert {'replicated', 'batch_parallel', 'fsdp', 'fsdp_tp'} <= set(rates), rates
assert all(v > 0 for v in rates.values()), rates
print('sharding smoke: sharded_samples_per_sec per preset:', rates)
"
}
stage "sharding smoke (FSDP parity + FML5xx gate)" sharding_smoke

# Sharded-embedding acceptance, device-free (ISSUE 14): an over-HBM-
# budget synthetic vocab is (a) refused replicated by FML503, (b) routed
# to the embedding plan by infer_plan, (c) trained sharded on the 8-CPU
# mesh through the exchange primitive (loss must fall, numerics vs the
# dense scatter reference), (d) snapshotted with plan-derived sharded:0
# tags and resumed bit-equal at world 2, and (e) served through a
# 2-replica slice-mesh pool under mixed_inference with bitwise-stable
# predictions. Then the sharded_embedding_cpu bench stage must emit
# finite lookup/update rows/s with per-step exchange traffic
# proportional to batch size, not vocab size.
embedding_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 420 python - <<'EOF' || return 1
import json, os, tempfile

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu.analysis.sharding_check import check_plan
from flinkml_tpu.embeddings import EmbeddingTable
from flinkml_tpu.embeddings.serving import EmbeddingLookupModel
from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.serving.engine import ServingConfig
from flinkml_tpu.serving.pool import ReplicaPool, slice_meshes
from flinkml_tpu.sharding import EMBEDDING, REPLICATED, infer_plan
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
vocab, dim = 300_000, 16          # deliberately not a power of two
budget = 6 << 20                  # replicated 38.4 MB, /4 9.6 MB, /8 4.8 MB
param = {"smoke/embedding": (vocab, dim)}

# (a) replicated placement refused by FML503 ...
mesh = DeviceMesh.for_plan(EMBEDDING)
refusal = check_plan(REPLICATED, mesh, param_shapes=param,
                     hbm_budget_bytes=budget, optimizer_slots=1)
assert any(f.rule == "FML503" for f in refusal), refusal
# ... (b) and infer_plan routes past fsdp to the embedding plan.
plan = infer_plan(mesh, param, budget, optimizer_slots=1)
assert plan.name == "embedding", plan.name

# (c) train sharded: SGD on the exchange primitive toward random target
# rows for a hot id subset; the sharded trajectory must match the dense
# numpy scatter reference and the loss must fall.
table = EmbeddingTable("smoke", vocab, dim, mesh=mesh, plan=plan,
                       hbm_budget_bytes=budget, optimizer_slots=1)
ref = np.zeros((vocab, dim), np.float32)
hot = rng.integers(0, vocab, 4096).astype(np.int32)
target = rng.normal(size=(4096, dim)).astype(np.float32)
losses = []
for step in range(6):
    sel = rng.integers(0, 4096, 2048)
    ids = hot[sel]
    cur = np.asarray(table.lookup(ids))
    grad = cur - target[sel]
    losses.append(float((grad * grad).mean()))
    table.scatter_add(ids, (-0.5 * grad).astype(np.float32))
    np.add.at(ref, ids, -0.5 * grad)
assert losses[-1] < losses[0], losses
np.testing.assert_allclose(table.to_host(), ref, rtol=1e-4, atol=1e-5)

with tempfile.TemporaryDirectory() as td:
    # (d) snapshot with plan-derived tags; resume bit-equal at world 2.
    mgr = CheckpointManager(td, rescale="reshard")
    table.save(mgr, 6)
    with open(os.path.join(td, "ckpt-6", "meta.json")) as fh:
        meta = json.load(fh)
    assert meta["layouts"] == ["sharded:0", "sharded:0"], meta["layouts"]
    mesh2 = DeviceMesh.for_plan(EMBEDDING, devices=jax.devices()[:2])
    table2, epoch = EmbeddingTable.restore(
        mgr, "smoke", vocab, dim, mesh=mesh2, plan=EMBEDDING,
        optimizer_slots=1)
    assert epoch == 6 and table2.n_shards == 2
    assert table2.to_host().tobytes() == table.to_host().tobytes(), \
        "world-2 resume is not bit-equal"

# (e) serve through a 2-replica slice-mesh pool, bf16 mixed_inference.
model = EmbeddingLookupModel(table.to_host(), plan=EMBEDDING,
                             precision="mixed_inference", name="smoke")
qids = rng.integers(0, vocab, size=(64, 4)).astype(np.int32)
qids[qids % 7 == 0] = -1
pool = ReplicaPool(
    model, Table({"ids": qids[:8]}),
    config=ServingConfig(max_batch_rows=64, max_wait_ms=1.0),
    meshes=slice_meshes(2, plan=EMBEDDING), output_cols=("vector",),
    name="emb_smoke",
).start()
try:
    v1 = pool.predict({"ids": qids}).columns["vector"]
    v2 = pool.predict({"ids": qids}).columns["vector"]
finally:
    pool.stop()
assert v1.tobytes() == v2.tobytes(), "pool predictions not bitwise-stable"
assert np.isfinite(v1).all() and np.abs(v1).sum() > 0
print("embedding smoke: FML503 refusal, infer->embedding, sharded train",
      "parity vs dense scatter, world-2 bit-equal resume, 2-replica",
      "bf16 pool serving bitwise-stable")
EOF
    local out
    out=$(_FLINKML_BENCH_INNER=sharded_embedding_cpu timeout 420 \
        python bench.py) || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
lk, up = rec['embedding_lookup_rows_per_sec'], rec['embedding_update_rows_per_sec']
assert {'ring', 'all_to_all'} <= set(lk) and {'ring', 'all_to_all'} <= set(up)
assert all(v > 0 for v in list(lk.values()) + list(up.values())), (lk, up)
per_row = rec['exchange_bytes_per_row']
assert all(v < rec['vocab'] for v in per_row.values()), per_row
assert rec['plan'] == 'embedding', rec['plan']
print('embedding smoke: lookup rows/s', lk, 'update rows/s', up,
      'exchange B/row', per_row, '(dense psum would move',
      rec['dense_psum_bytes_per_step'], 'B/step)')
"
}
stage "embedding smoke (sharded train/resume/serve + bench)" embedding_smoke

# Mixed-precision acceptance, device-free (ISSUE 10): (a) a deliberately
# bf16-ACCUMULATING SGD step (bf16 storage under the 'mixed' policy) is
# refused pre-compile with FML601/FML603 typed findings, (b) the
# policy-correct variant (f32 storage, bf16 compute, f32 accum) trains
# on the 8-CPU-device mesh to a finite model within tolerance of its
# f32 twin, (c) the fused inference chain under "mixed_inference"
# reproduces the f32 predictions, (d) the seeded FML6xx policy fixtures
# are flagged by the analysis CLI (--format json), and (e) the
# precision_cpu bench stage emits bf16_vs_f32_samples_per_sec_ratio
# (reported, not gated — CPU bf16 is emulation, the TPU ratio is the
# device stage's job).
precision_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 300 python - <<'EOF' || return 1
import json, subprocess, sys

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.precision import MIXED, PrecisionValidationError
from flinkml_tpu.sharding.plan import REPLICATED
from flinkml_tpu.sharding.apply import train_linear_plan
from flinkml_tpu.table import Table
from flinkml_tpu import pipeline_fusion

dim, n = 64, 256
rng = np.random.default_rng(0)
x = rng.normal(size=(n, dim)).astype(np.float32)
y = (x @ rng.normal(size=dim) > 0).astype(np.float32) * 2 - 1
mesh = DeviceMesh.for_plan(REPLICATED)

# (a) bf16-accumulating step refused BEFORE any compile.
try:
    train_linear_plan(x, y, None, REPLICATED, mesh, max_iter=1,
                      dtype="bfloat16", precision=MIXED)
    raise SystemExit("bf16-accumulating SGD step was not refused")
except PrecisionValidationError as e:
    rules = {f.rule for f in e.findings}
    assert "FML601" in rules and "FML603" in rules, rules

# (b) the policy-correct variant: finite + tolerance-bounded vs f32.
golden = train_linear_plan(x, y, None, REPLICATED, mesh, max_iter=20,
                           learning_rate=0.5)
mixed = train_linear_plan(x, y, None, REPLICATED, mesh, max_iter=20,
                          learning_rate=0.5, precision="mixed")
assert np.isfinite(mixed).all(), "mixed trainer went non-finite"
np.testing.assert_allclose(mixed, golden, atol=2e-2)

# (c) fused inference chain under the serving policy: probabilities
# within bf16 tolerance of f32, decisions equal away from the 0.5
# boundary (this heredoc runs AMBIENT float32 — exact pred equality is
# an x64-only contract; see .claude/skills/verify/SKILL.md).
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import StandardScaler
t = Table({"features": x.astype(np.float64), "label": (y > 0).astype(np.float64)})
sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
                     .set(StandardScaler.OUTPUT_COL, "scaled").fit(t)
(st,) = sc.transform(t)
lr = LogisticRegression().set(LogisticRegression.FEATURES_COL, "scaled") \
                         .set(LogisticRegression.LABEL_COL, "label") \
                         .set(LogisticRegression.SEED, 7) \
                         .set_max_iter(2).fit(st)
pm = PipelineModel([sc, lr])
(o32,) = pm.transform(t)
p32 = np.asarray(o32.column("prediction"))
r32 = np.asarray(o32.column("rawPrediction")).astype(np.float64)
with pipeline_fusion.precision_scope("mixed_inference"):
    (obf,) = pm.transform(t)
    pbf = np.asarray(obf.column("prediction"))
    rbf = np.asarray(obf.column("rawPrediction")).astype(np.float64)
np.testing.assert_allclose(r32, rbf, atol=2e-2)
decisive = np.abs(r32[:, 1] - 0.5) > 2e-2
assert decisive.any()
assert np.array_equal(p32[decisive], pbf[decisive]), \
    "bf16 fused predictions diverged away from the decision boundary"

# (d) seeded FML6xx policy fixtures flagged, machine-readably.
out = subprocess.run(
    [sys.executable, "-m", "flinkml_tpu.analysis",
     "tests/analysis_fixtures/bad_precision_fml601_bf16_accum_sgd.policy.json",
     "--no-selfcheck", "--format", "json"],
    stdout=subprocess.PIPE, text=True,
)
assert out.returncode == 1, "seeded FML6xx policy fixture was not flagged"
rules = {f["rule"] for f in json.loads(out.stdout)}
assert "FML601" in rules, rules
print("precision smoke: FML601/603 refusal pre-compile, mixed SGD",
      "within 2e-2 of f32, bf16 fused probs within 2e-2 + decisions",
      "pinned off-boundary, FML6xx fixtures flagged via --format json")
EOF
    local out
    out=$(_FLINKML_BENCH_INNER=precision_cpu timeout 560 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
ratios = rec['bf16_vs_f32_samples_per_sec_ratio']
assert {'fused_chain', 'sgd_train'} <= set(ratios), ratios
assert all(v > 0 for v in ratios.values()), ratios
dev = rec['sgd_coef_max_abs_dev']
import math
assert math.isfinite(dev) and dev < 2e-2, dev
print('precision smoke: bf16_vs_f32_samples_per_sec_ratio:', ratios,
      'sgd coef max|d|', dev)
"
}
stage "precision smoke (FML6xx gate + bf16 A/B)" precision_smoke

# Zero-cold-start acceptance, device-free (ISSUE 11): (a) the
# cold_start_cpu bench stage must show a warm AOT cache beating a cold
# one on time-to-first-prediction for the fused 5-stage chain AND a
# 2-replica pool spin-up, with predictions bitwise-equal to the plain
# jit path (the stage itself refuses to emit on a parity violation);
# the CI floor is a deliberate tripwire BELOW the >=3x the bench shows
# on an idle box — near-equal jitter on a starved CI host must not
# hard-fail CI (the serving-stage precedent). (b) A corrupt/torn cache
# entry must fall back loudly to a fresh compile and still serve
# bitwise-correct predictions. (c) The committed tuning table must pass
# the schema check (measured candidates present for every knob).
cold_start_smoke() {
    local out
    out=$(_FLINKML_BENCH_INNER=cold_start_cpu timeout 560 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
assert rec['parity_bitwise'] == 1, rec
assert rec['aot_entries'] > 0, rec
assert rec['ttfp_speedup'] >= 1.5, \
    f'warm cache did not beat cold by the 1.5x CI floor: {rec}'
assert rec['pool_speedup'] >= 1.1, \
    f'warm pool spin-up did not beat cold by the 1.1x CI floor: {rec}'
print('cold-start smoke: engine cold', rec['cold_ttfp_s'], 's -> warm',
      rec['warm_ttfp_s'], 's (', rec['ttfp_speedup'], 'x ), pool cold',
      rec['pool_cold_s'], 's -> warm', rec['pool_warm_s'], 's (',
      rec['pool_speedup'], 'x ),', rec['aot_entries'],
      'artifacts, bitwise parity')
" || return 1
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 300 python - <<'EOF' || return 1
import os, tempfile

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu import compile_cache, pipeline_fusion
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.table import Table
from flinkml_tpu.utils.metrics import metrics

rng = np.random.default_rng(3)
x = rng.normal(size=(300, 9))
y = (x @ rng.normal(size=9) > 0).astype(np.float64)
t = Table({"features": x, "label": y})
sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
                     .set(StandardScaler.OUTPUT_COL, "scaled").fit(t)
(st,) = sc.transform(t)
lr = LogisticRegression().set(LogisticRegression.FEATURES_COL, "scaled") \
                         .set(LogisticRegression.LABEL_COL, "label") \
                         .set_max_iter(2).fit(st)
pm = PipelineModel([sc, lr])

def outputs():
    (out,) = pm.transform(t)
    return {c: np.asarray(out.column(c))
            for c in out.column_names if c not in ("features", "label")}

baseline = outputs()  # plain jit path

d = tempfile.mkdtemp(prefix="ci-coldstart-")
compile_cache.configure(d)
pipeline_fusion.reset_cache()
outputs()  # populate the store
paths = [os.path.join(r, f) for r, _, fs in os.walk(d)
         for f in fs if f.endswith(".aot")]
assert paths, "no AOT artifacts were stored"
for p in paths:  # tear every entry mid-file (disk-rot / killed writer)
    with open(p, "r+b") as fh:
        fh.truncate(max(1, os.path.getsize(p) // 2))

compile_cache.reset()
compile_cache.configure(d)
pipeline_fusion.reset_cache()
served = outputs()  # must recompile loudly, never crash
counters = metrics.group("compile_cache").snapshot()["counters"]
assert counters.get("corrupt_entries", 0) >= len(paths), counters
for c in baseline:
    assert baseline[c].tobytes() == served[c].tobytes(), c
print("cold-start smoke: corrupt-entry run recompiled loudly and served",
      f"bitwise-correct predictions ({int(counters['corrupt_entries'])}",
      "corrupt entries detected + replaced)")
EOF
    JAX_PLATFORMS=cpu timeout 120 \
        python -m flinkml_tpu.autotune --check || return 1
}
stage "cold-start smoke (AOT cache A/B + corrupt entry + table check)" \
    cold_start_smoke

# Pallas smoke (ISSUE 13 acceptance): interpret-mode bitwise parity for
# all three Pallas kernels (fused chain, padded-ELL segment-sum +
# sorted specialization, bucketed top-k) against their XLA references
# on the 8-CPU mesh; the gate's OFF default asserted (every site
# resolves to xla with no env override — Pallas is opt-in by
# measurement); explicit-request refusal on an unsupported dtype; then
# the pallas_cpu bench stage must emit a finite per-site
# kernel_vs_xla_samples_per_sec_ratio with its own parity tripwire
# (parity_bitwise == 1 or the stage refuses to emit).
pallas_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 300 python - <<'EOF' || return 1
import numpy as np
import jax
import jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu import kernels, pipeline_fusion
from flinkml_tpu.table import Table

# Gate-off default: every site resolves to XLA (the committed table's
# cpu/cpu/8 kernel_backend_* entries are xla — interpret-mode pallas
# must never be a silent default).
for site in kernels.SITES:
    assert kernels.backend_for(site) == "xla", site

rng = np.random.default_rng(0)

# segment-sum: unsorted + sorted-specialized, flat + row payloads.
ids = jnp.asarray(rng.integers(0, 257, 2_048), jnp.int32)
vals = jnp.asarray(rng.normal(size=2_048).astype(np.float32))
a = np.asarray(jax.ops.segment_sum(vals, ids, num_segments=257))
b = np.asarray(kernels.segment_sum(vals, ids, 257, backend="pallas"))
assert a.tobytes() == b.tobytes(), "unsorted segment_sum parity"
sids = jnp.sort(ids)
a = np.asarray(jax.ops.segment_sum(vals, sids, num_segments=257,
                                   indices_are_sorted=True))
b = np.asarray(kernels.segment_sum(vals, sids, 257,
                                   indices_are_sorted=True,
                                   backend="pallas"))
assert a.tobytes() == b.tobytes(), "sorted segment_sum parity"
rows = jnp.asarray(rng.normal(size=(512, 8)).astype(np.float32))
a = np.asarray(jax.ops.segment_sum(rows, ids[:512], num_segments=257))
b = np.asarray(kernels.segment_sum(rows, ids[:512], 257, backend="pallas"))
assert a.tobytes() == b.tobytes(), "row-payload segment_sum parity"

# top-k: tied values, non-tile-multiple rows, 1-D.
x = jnp.asarray(rng.normal(size=(37, 129)).astype(np.float32))
x = x.at[0, 5].set(x[0, 2])
rv, ri = jax.lax.top_k(x, 9)
pv, pi = kernels.top_k(x, 9, backend="pallas")
assert np.asarray(rv).tobytes() == np.asarray(pv).tobytes()
assert np.asarray(ri).tobytes() == np.asarray(pi).tobytes()

# fused chain: the canonical scaler->logistic chain through the REAL
# fused executor under each backend, bitwise per column per bucket.
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import StandardScaler, MinMaxScaler
from flinkml_tpu.pipeline import PipelineModel
import os
xs = rng.normal(size=(200, 5))
ys = (xs @ np.arange(1.0, 6.0) > 0).astype(np.float64)
t = Table({"features": xs, "label": ys})
sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
    .set(StandardScaler.OUTPUT_COL, "s1").fit(t)
(st,) = sc.transform(t)
mm = MinMaxScaler().set(MinMaxScaler.INPUT_COL, "s1") \
    .set(MinMaxScaler.OUTPUT_COL, "s2").fit(st)
(mt,) = mm.transform(st)
lr = LogisticRegression().set(LogisticRegression.FEATURES_COL, "s2") \
    .set(LogisticRegression.LABEL_COL, "label").set_max_iter(2).fit(mt)
pm = PipelineModel([sc, mm, lr])
for rows_n in (6, 200):
    sub = Table({"features": xs[:rows_n], "label": ys[:rows_n]})
    pipeline_fusion.reset_cache()
    (ref,) = pm.transform(sub)
    cols = [c for c in ref.column_names if c not in ("features", "label")]
    ref_cols = {c: np.asarray(ref.column(c)) for c in cols}
    os.environ["FLINKML_TPU_KERNELS"] = "fused_chain=pallas"
    pipeline_fusion.reset_cache()
    (got,) = pm.transform(sub)
    del os.environ["FLINKML_TPU_KERNELS"]
    for c in cols:
        assert ref_cols[c].tobytes() == np.asarray(got.column(c)).tobytes(), \
            (rows_n, c)

# loud refusal on an explicitly-requested unsupported dtype.
try:
    kernels.top_k(jnp.arange(10), 3, backend="pallas")
    raise SystemExit("integer top_k was not refused")
except kernels.KernelUnsupportedError:
    pass
print("pallas smoke: 3-kernel interpret parity bitwise, gate defaults",
      "off, unsupported dtype refused loudly")
EOF
    local out
    out=$(_FLINKML_BENCH_INNER=pallas_cpu timeout 560 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, math, sys
rec = json.loads(sys.stdin.read())
assert rec['parity_bitwise'] == 1, rec
ratios = rec['kernel_vs_xla_samples_per_sec_ratio']
assert {'fused_chain', 'segment_sum', 'topk'} <= set(ratios), ratios
assert all(math.isfinite(v) and v > 0 for v in ratios.values()), ratios
assert rec['interpret'] == 1, rec
print('pallas smoke: kernel_vs_xla_samples_per_sec_ratio:', ratios,
      '(interpret-mode pallas; device stage queued in bench stage_order)')
"
}
stage "pallas smoke (3-kernel interpret parity + gate-off + bench ratio)" \
    pallas_smoke

# Sparse smoke (ISSUE 16 acceptance): interpret-mode bitwise parity for
# the two sorted-hot-loop kernels — the multi-block segment-sum on a
# grid with cells > BLOCK_CELLS (above the retired one-block ceiling)
# and the CSR SpMV chain kernel vs its JITTED XLA twin (the parity
# contract — eager XLA fuses the reduce tree differently in the last
# f32 bit; docs/development/kernels.md); the typed ceiling refusal must
# name MAX_COMPILED_CELLS; the FML404 sorted-scatter fixtures must be
# flagged (bad) and pass (good) by name; then the sparse_hot_loops_cpu
# bench stage is parsed with a >=1.0x no-regression tripwire on sorted
# sparse-LR rows/s vs the densified baseline (measured ~16x on an idle
# box — the floor only guards against the sparse path LOSING to
# densification on a starved CI host).
sparse_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 420 python - <<'EOF' || return 1
import os

import numpy as np
import jax
import jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")

from flinkml_tpu import kernels
from flinkml_tpu.kernels import segsum as _segsum

rng = np.random.default_rng(0)

# Multi-block segment-sum: cells > BLOCK_CELLS grids over >1 block with
# a ragged tail; unsorted + sorted-specialized, bitwise vs XLA.
cells = _segsum.BLOCK_CELLS + 1000
nseg = 1 << 10
ids = jnp.asarray(np.sort(rng.integers(0, nseg, cells)), jnp.int32)
uids = jnp.asarray(rng.integers(0, nseg, cells), jnp.int32)
vals = jnp.asarray(rng.normal(size=cells).astype(np.float32))
a = np.asarray(jax.ops.segment_sum(vals, uids, num_segments=nseg))
b = np.asarray(kernels.segment_sum(vals, uids, nseg, backend="pallas"))
assert a.tobytes() == b.tobytes(), "multi-block unsorted segsum parity"
a = np.asarray(jax.ops.segment_sum(vals, ids, num_segments=nseg,
                                   indices_are_sorted=True))
b = np.asarray(kernels.segment_sum(vals, ids, nseg,
                                   indices_are_sorted=True,
                                   backend="pallas"))
assert a.tobytes() == b.tobytes(), "multi-block sorted segsum parity"

# CSR SpMV vs the JITTED XLA twin, bitwise.
ib = jnp.asarray(rng.integers(0, 512, size=(256, 16)), jnp.int32)
vb = jnp.asarray(rng.normal(size=(256, 16)).astype(np.float32))
w = jnp.asarray(rng.normal(size=512).astype(np.float32))
twin = jax.jit(lambda i, v, w: jnp.sum(v * jnp.take(w, i, axis=0), axis=1))
a = np.asarray(twin(ib, vb, w))
b = np.asarray(kernels.spmv(ib, vb, w, backend="pallas"))
assert a.tobytes() == b.tobytes(), "spmv parity vs jitted XLA twin"

# Typed ceiling refusal on the compiled path: the OUTPUT ceiling
# (num_segments * k > MAX_COMPILED_CELLS) must refuse loudly, naming
# the constant — never a silent fallback for an explicit request.
os.environ[kernels.ENV_INTERPRET_VAR] = "0"
try:
    kernels.segment_sum(vals[:8], ids[:8],
                        _segsum.MAX_COMPILED_CELLS + 1, backend="pallas")
    raise SystemExit("over-ceiling explicit pallas was not refused")
except kernels.KernelUnsupportedError as e:
    assert "MAX_COMPILED_CELLS" in str(e), e
finally:
    del os.environ[kernels.ENV_INTERPRET_VAR]
print("sparse smoke: multi-block segsum + spmv interpret parity bitwise,"
      " ceiling refusal typed and named")
EOF
    # The FML404 sorted-scatter gate has teeth: the seeded fixture must
    # be flagged by name, and the policy-correct twin must pass clean.
    if env JAX_PLATFORMS=cpu python -m flinkml_tpu.analysis \
        tests/analysis_fixtures/bad_scatter_fml404_unsorted_flag_on_sorted_input.scatter.json \
        --no-selfcheck --fail-on-findings >/dev/null 2>&1; then
        echo "FML404 sorted-scatter fixture was NOT flagged"
        return 1
    fi
    env JAX_PLATFORMS=cpu python -m flinkml_tpu.analysis \
        tests/analysis_fixtures/good_scatter_sorted_flag_on_sorted_input.scatter.json \
        --no-selfcheck --fail-on-findings || return 1
    local out
    out=$(_FLINKML_BENCH_INNER=sparse_hot_loops_cpu timeout 560 \
        python bench.py) || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, math, sys
rec = json.loads(sys.stdin.read())
assert {'sparse_sorted_rows_per_sec', 'densified_rows_per_sec',
        'sparse_vs_densified_ratio'} <= set(rec), rec
assert all(math.isfinite(rec[k]) and rec[k] > 0 for k in
           ('sparse_sorted_rows_per_sec', 'densified_rows_per_sec')), rec
assert rec['sparse_vs_densified_ratio'] >= 1.0, (
    'sorted sparse hot loop lost to the densified baseline', rec)
print('sparse smoke: sorted sparse-LR', rec['sparse_sorted_rows_per_sec'],
      'rows/s vs densified', rec['densified_rows_per_sec'],
      'rows/s (', rec['sparse_vs_densified_ratio'], 'x ) at dim',
      rec['dim'], 'nnz/row', rec['nnz_per_row'])
"
}
stage "sparse smoke (multi-block segsum + spmv parity + FML404 + bench)" \
    sparse_smoke

# Autoscale smoke (ISSUE 15 acceptance, device-free): (1) closed-loop
# load triple → the autoscaler scales up on its own, scale-up replicas
# join warm, zero requests lost, the backlog signal recovers, and p99
# holds a starved-box tripwire (the CPU mesh's virtual devices share one
# executor, so strict recovery is the queued DEVICE stage's number — the
# 2x bound catches the >10x pad-compile failure mode this PR fixed; the
# in-process capacity ceiling itself is lifted by the worker-pool stage,
# "cluster smoke" below, where each replica is a real process);
# (2) a batch-tier job over its SLO share is refused TYPED while the
# interactive tier keeps serving; (3) the int8 PTQ tier's predictions
# sit within the pinned tolerance of f32; (4) the seeded FML606 fixture
# is flagged; then parses bench.py serving_autoscale_cpu (rows/s per
# replica, scale-event count, int8-vs-bf16 rows/s ratio floor).
autoscale_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        timeout 420 python - <<'PY' || return 1
import threading
import time

import numpy as np

from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.serving import (
    BATCH, INTERACTIVE, AutoscaleConfig, MultiModelPool, PoolAutoscaler,
    ReplicaPool, ServingConfig, SLOAdmissionError,
)
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
d = 32
x = rng.normal(size=(400, d))
y = (x @ rng.normal(size=d) > 0).astype(np.float64)
train = Table({"features": x, "label": y})
sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
    .set(StandardScaler.OUTPUT_COL, "scaled").fit(train)
(t2,) = sc.transform(train)
lr = LogisticRegression().set(LogisticRegression.FEATURES_COL, "scaled") \
    .set(LogisticRegression.LABEL_COL, "label").set_max_iter(3).fit(t2)
pm = PipelineModel([sc, lr])
example = Table({"features": x[:4]})

# -- (1) closed loop: load triple -> scale-up -> recovery --------------------
pool = ReplicaPool(
    pm, example,
    config=ServingConfig(max_batch_rows=32, max_queue_rows=512,
                         max_wait_ms=1.0),
    n_replicas=1, output_cols=("prediction",), name="ci_autoscale",
).start()
scaler = PoolAutoscaler(pool, AutoscaleConfig(
    min_replicas=1, max_replicas=3, scale_up_backlog=0.05,
    up_consecutive=10, down_consecutive=10_000, cooldown_s=0.3,
    interval_s=0.1,
)).start()
stop = threading.Event()
lat, lock, errors = [], threading.Lock(), []

def client(tid):
    r = np.random.default_rng(tid)
    while not stop.is_set():
        rows = int(r.integers(8, 25))
        lo = int(r.integers(0, 370))
        t0 = time.perf_counter()
        try:
            pool.predict({"features": x[lo:lo + rows]})
        except Exception as e:  # noqa: BLE001
            errors.append(e)
            return
        with lock:
            lat.append((time.perf_counter(),
                        (time.perf_counter() - t0) * 1e3))

def p99(t0, t1=None):
    with lock:
        vals = [ms for (tc, ms) in lat
                if tc >= t0 and (t1 is None or tc < t1)]
    return float(np.percentile(vals, 99)) if vals else None

light = [threading.Thread(target=client, args=(i,)) for i in range(2)]
[t.start() for t in light]
time.sleep(0.8)
spike_t0 = time.perf_counter()
heavy = [threading.Thread(target=client, args=(10 + i,)) for i in range(4)]
[t.start() for t in heavy]
deadline = time.monotonic() + 40
while time.monotonic() < deadline and len(pool.replicas) < 2:
    time.sleep(0.05)
assert len(pool.replicas) >= 2, f"no scale-up: {scaler.stats()}"
backlog_at_scale = scaler.stats()["backlog_ewma"]
spike_p99 = p99(spike_t0, time.perf_counter())
stable_since, last = time.monotonic(), len(pool.replicas)
while time.monotonic() < deadline:
    if len(pool.replicas) != last:
        last, stable_since = len(pool.replicas), time.monotonic()
    if time.monotonic() - stable_since >= 1.0:
        break
    time.sleep(0.05)
settle_t0 = time.perf_counter()
time.sleep(1.5)
rec_p99 = p99(settle_t0)
stop.set()
[t.join(timeout=60) for t in light + heavy]
st = scaler.stats()
scaler.stop()
pool.stop()
assert not errors, errors[:3]
assert st["counters"].get("scale_events_total", 0) >= 1, st
assert st["backlog_ewma"] <= backlog_at_scale * 0.75, (
    st["backlog_ewma"], backlog_at_scale)
assert spike_p99 and rec_p99 and rec_p99 <= spike_p99 * 2.0, (
    spike_p99, rec_p99)

# -- (2) batch tier cannot starve interactive --------------------------------
mm = MultiModelPool(
    example,
    config=ServingConfig(max_batch_rows=32, max_queue_rows=64,
                         max_wait_ms=1.0),
    name="ci_mm",
)
mm.add_model("rank", pm, slo=INTERACTIVE, n_replicas=2)
mm.add_model("offline", pm, slo=BATCH, n_replicas=1)
mm.start()
capacity = sum(r.engine.config.max_queue_rows for r in mm.replicas)
mm._ledgers["batch"].outstanding_rows = int(0.5 * capacity)
try:
    mm.predict("offline", {"features": x[:4]})
    raise SystemExit("batch over its SLO share was admitted")
except SLOAdmissionError:
    pass
resp = mm.predict("rank", {"features": x[:4]})  # interactive untouched
assert resp.columns["prediction"].shape == (4,)
mm._ledgers["batch"].outstanding_rows = 0
mm.stop()

# -- (3) int8 tier quality tolerance -----------------------------------------
import os

from flinkml_tpu import pipeline_fusion

os.environ["FLINKML_TPU_INT8_MIN_CONST"] = "16"  # quantize d=32 consts
(apply32,) = pm.transform(Table({"features": x}))
p32 = np.asarray(apply32.column("prediction"))
r32 = np.asarray(apply32.column("rawPrediction")).astype(np.float64)
with pipeline_fusion.precision_scope("int8_inference"):
    (applyq,) = pm.transform(Table({"features": x}))
    pq = np.asarray(applyq.column("prediction"))
    rq = np.asarray(applyq.column("rawPrediction")).astype(np.float64)
dev = float(np.max(np.abs(rq - r32)))
assert 0.0 < dev < 5e-3, dev
agree = float(np.mean(p32 == pq))
assert agree >= 0.99, agree  # only boundary points inside dev may flip

print("autoscale smoke: load triple -> scale events",
      int(st["counters"]["scale_events_total"]), "replicas",
      st["replicas"], f"backlog {backlog_at_scale:.2f}->"
      f"{st['backlog_ewma']:.2f}, p99 {spike_p99:.1f}->{rec_p99:.1f}ms;",
      "batch SLO share refused typed, interactive served;",
      f"int8 quality dev {dev:.2e} (label agreement {agree:.3f})")
PY
    # The seeded FML606 fixture must be flagged (the integer-width gate
    # has teeth) — the dir-walk fixture gate covers it too; this is the
    # named assert.
    if env JAX_PLATFORMS=cpu python -m flinkml_tpu.analysis \
        tests/analysis_fixtures/bad_precision_fml606_int8_unscaled_accum.policy.json \
        --no-selfcheck --fail-on-findings >/dev/null 2>&1; then
        echo "FML606 fixture was NOT flagged"
        return 1
    fi
    local out
    out=$(_FLINKML_BENCH_INNER=serving_autoscale_cpu timeout 560 \
        python bench.py) || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, math, sys
rec = json.loads(sys.stdin.read())
assert rec['scale_events_total'] >= 1, rec
per = rec['serving_rows_per_sec_per_replica']
assert len(per) >= 2 and all(
    math.isfinite(v) and v >= 0 for v in per.values()), per
# Starved-box tripwire (strict recovery is the device stage's number;
# the 4x bound catches the >10x pad-compile failure mode).
assert rec['autoscale_recovery_ratio'] is None or \
    rec['autoscale_recovery_ratio'] <= 4.0, rec
# The int8 tier must BEAT bf16 mixed_inference rows/s on the CPU mesh
# (bf16 is emulated there; measured 1.5-1.8x on an idle box — 1.1x
# floor absorbs a starved box) within the pinned quality tolerance.
assert rec['int8_vs_bf16_rows_per_sec_ratio'] >= 1.1, rec
assert rec['int8_vs_f32_max_raw_dev'] < 0.1, rec
print('autoscale smoke: rows/s', rec['serving_autoscale_rows_per_sec'],
      'scale events', rec['scale_events_total'],
      'recovery ratio', rec['autoscale_recovery_ratio'],
      'int8/bf16', rec['int8_vs_bf16_rows_per_sec_ratio'],
      'int8 dev', rec['int8_vs_f32_max_raw_dev'],
      '(device stage queued in bench stage_order)')
"
}
stage "autoscale smoke (load-triple scale-up + SLO admission + int8 tier)" \
    autoscale_smoke

# Memory-pass acceptance, device-free (ISSUE 17): (a) the seeded
# FML70{1..4} fixtures are each flagged by rule id via --format json;
# (b) an embedding config over budget at f32 is FML701-refused
# pre-compile, rerouted by memory-aware infer_plan to an int8 tier
# that fits, served under that tier with >=99% label identity, and an
# over-budget hot-swap is refused while the old model keeps serving;
# (c) FML703 fires live on a real undonated carry-update and goes
# quiet once the state is donated; (d) the --rules catalog and the
# docs rule table agree row-for-row; (e) the bench memory_cpu stage's
# static estimate sits inside the pinned 0.5x-2.0x band of XLA's
# Compiled.memory_analysis() on BOTH calibration twins.
memory_smoke() {
    local fx rule
    for rule in fml701 fml702 fml703 fml704; do
        fx=$(ls tests/analysis_fixtures/bad_memory_${rule}_*.memory.json) \
            || return 1
        # --fail-on-findings: FML703 is a warning, which alone would
        # exit 0 under the errors-only default.
        JAX_PLATFORMS=cpu python -m flinkml_tpu.analysis "$fx" \
            --no-selfcheck --fail-on-findings --format json \
            > /tmp/ci_mem_${rule}.json
        if [ $? -ne 1 ]; then
            echo "memory fixture $fx did not exit 1"
            return 1
        fi
        python - "$rule" "/tmp/ci_mem_${rule}.json" <<'PY' || return 1
import json, sys
with open(sys.argv[2]) as fh:
    rules = {f["rule"] for f in json.load(fh)}
want = sys.argv[1].upper()
assert want in rules, (want, rules)
print("memory smoke: fixture flagged", want)
PY
    done

    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 300 python - <<'EOF' || return 1
import os

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from flinkml_tpu.analysis.memory import check_memory_fn
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.sharding.plan import FSDP, infer_plan

# -- (b) over-budget at f32 -> FML701 pre-compile -> int8 reroute ------------
axes = {"data": 1, "fsdp": 8}
shapes = {"emb/embedding": (1 << 16, 64)}
budget = 700_000  # int8 slice ~512 KiB fits; bf16 1 MiB and f32 2 MiB do not
state = {"emb/embedding": jnp.zeros(shapes["emb/embedding"], jnp.float32)}

def decay(state):
    return {"emb/embedding": state["emb/embedding"] * 0.99}

findings = check_memory_fn(
    decay, state, plan=FSDP, mesh=axes, hbm_budget_bytes=budget,
    param_argnums=(0,), donate_argnums=(0,), program="emb_decay",
)
rules = {f.rule for f in findings}
assert "FML701" in rules, rules  # refused before any compile

plan, tier = infer_plan(axes, shapes, budget, optimizer_slots=0,
                        quant_tiers=True)
assert tier == "int8", (plan.name, tier)

# -- (b cont.) serve under the routed tier: >=99% label identity -------------
from flinkml_tpu import pipeline_fusion
from flinkml_tpu.models.logistic_regression import (
    LogisticRegression, LogisticRegressionModel)
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.table import Table

os.environ["FLINKML_TPU_INT8_MIN_CONST"] = "16"
rng = np.random.default_rng(17)
dim, n = 32, 512
x = rng.normal(size=(n, dim))
y = (x @ rng.normal(size=dim) > 0).astype(np.float64)
t = Table({"features": x, "label": y})
sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
                     .set(StandardScaler.OUTPUT_COL, "scaled").fit(t)
(st,) = sc.transform(t)
lr = LogisticRegression().set(LogisticRegression.FEATURES_COL, "scaled") \
                         .set(LogisticRegression.LABEL_COL, "label") \
                         .set(LogisticRegression.SEED, 17) \
                         .set_max_iter(5).fit(st)
pm = PipelineModel([sc, lr])
(o32,) = pm.transform(t)
p32 = np.asarray(o32.column("prediction"))
with pipeline_fusion.precision_scope("int8_inference"):
    (oq,) = pm.transform(t)
    pq = np.asarray(oq.column("prediction"))
agree = float(np.mean(p32 == pq))
assert agree >= 0.99, agree

# -- (b cont.) over-budget swap refused, old model keeps serving -------------
import tempfile

from flinkml_tpu.serving import (
    ModelRegistry, ServingConfig, ServingEngine, ServingMemoryError)

big = LogisticRegressionModel().set(
    LogisticRegressionModel.FEATURES_COL, "features")
big.set_model_data(Table({"coefficient": np.ones((1, 1 << 20))}))
with tempfile.TemporaryDirectory() as tmp:
    reg = ModelRegistry(os.path.join(tmp, "reg"))
    small = LogisticRegression().set(
        LogisticRegression.FEATURES_COL, "features"
    ).set(LogisticRegression.LABEL_COL, "label").set_max_iter(3).fit(t)
    v1 = reg.publish(small)
    eng = ServingEngine(
        reg, Table({"features": x[:4]}),
        ServingConfig(max_batch_rows=64, warmup_row_counts=(4,),
                      hbm_budget_bytes=1 << 20),
        output_cols=("prediction",),
    ).start()
    try:
        assert eng.predict(Table({"features": x[:4]})).version == v1
        v2 = reg.publish(big)
        try:
            eng.swap_to(v2)
            raise SystemExit("over-budget swap was not refused")
        except ServingMemoryError:
            pass
        assert eng.predict(Table({"features": x[:4]})).version == v1
    finally:
        eng.stop()

# -- (c) FML703 live on a real undonated carry-update ------------------------
from flinkml_tpu.sharding.apply import init_linear_state, linear_step_fn

mesh = DeviceMesh.for_plan(FSDP)
lstate = init_linear_state(2048, "sgd", np.float32)
step = linear_step_fn(loss="logistic", optimizer="sgd",
                      dtype_name="float32", learning_rate=0.1,
                      momentum=0.9, reg_l2=0.0, reg_l1=0.0)
args = (lstate, jnp.zeros((n, 2048), jnp.float32),
        jnp.asarray(y, jnp.float32), jnp.ones((n,), jnp.float32))
undonated = {f.rule for f in check_memory_fn(
    step, *args, plan=FSDP, mesh=mesh, param_argnums=(0,))}
assert "FML703" in undonated, undonated
donated = {f.rule for f in check_memory_fn(
    step, *args, plan=FSDP, mesh=mesh, param_argnums=(0,),
    donate_argnums=(0,))}
assert "FML703" not in donated, donated

print("memory smoke: FML701 pre-compile refusal, infer_plan ->",
      f"({plan.name!r}, {tier!r}), int8 label agreement {agree:.3f},",
      "over-budget swap refused (old model kept serving), FML703",
      "live+donation-quiet")
EOF

    # (d) --rules catalog and docs rule table agree row-for-row.
    JAX_PLATFORMS=cpu python - <<'EOF' || return 1
import re, subprocess, sys

out = subprocess.run(
    [sys.executable, "-m", "flinkml_tpu.analysis", "--rules"],
    stdout=subprocess.PIPE, text=True, check=True).stdout
cli = set(re.findall(r"^(FML\d{3})\b", out, re.MULTILINE))
docs = set(re.findall(
    r"^\|\s*(FML\d{3})\s*\|",
    open("docs/development/static_analysis.md").read(), re.MULTILINE))
assert cli == docs, (sorted(cli - docs), sorted(docs - cli))
print(f"memory smoke: --rules vs docs table: {len(cli)} rules, in sync")
EOF

    # (e) calibration tripwire: the pinned 0.5x-2.0x band vs XLA's
    # Compiled.memory_analysis() on both twins, plus the live FML703
    # demo the stage re-runs on every CI invocation.
    local out
    out=$(_FLINKML_BENCH_INNER=memory_cpu timeout 560 python bench.py) \
        || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
ratios = rec['memory_calibration_ratio']
assert {'fused_chain', 'sgd_step'} <= set(ratios), ratios
for name, r in ratios.items():
    assert 0.5 <= r <= 2.0, (name, r, rec['memory_estimate_bytes'],
                             rec['xla_memory_analysis_bytes'])
assert rec['fml703_live_finding'], rec
assert not rec['fml703_after_donation'], rec
print('memory smoke: calibration ratios', ratios,
      'FML703 live leaves', rec['fml703_live_finding'])
"
}
stage "memory smoke (FML70x gate + int8 reroute + calibration band)" \
    memory_smoke

# Freshness smoke, device-free (ISSUE 18 acceptance): a hashed-id FM
# trained from an unbounded stream reaches a 2-replica pool via row
# deltas only — zero full republishes after the base version, staleness
# lag pinned at 0 after every synchronous roll (batch-count watermarks,
# no wall clock), delta-published predictions bitwise-equal to a full
# snapshot of the same state, and a mid-patch ReplicaDown loses zero
# client requests. Then: the seeded FML505 fixture must be flagged
# (hash/vocab width gate has teeth) and the feature_freshness_cpu bench
# stage must emit rows/s, the delta-vs-snapshot ratio, and the
# time-to-freshness distribution.
freshness_smoke() {
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout 420 python - <<'EOF' || return 1
import tempfile, threading, time

import numpy as np

from flinkml_tpu import faults
from flinkml_tpu.features import (
    DeltaPublisher, StreamingHashedFMTrainer, hash_buckets,
)
from flinkml_tpu.serving import ModelRegistry, ReplicaPool, ServingConfig
from flinkml_tpu.table import Table

B, L, SEED = 256, 3, 5
rng = np.random.default_rng(1)

def batch(n=32):
    keys = rng.integers(0, 10_000, size=(n, L))
    ids = hash_buckets(keys.reshape(-1), seed=SEED,
                       num_buckets=B).reshape(n, L)
    return ids, (keys.sum(axis=1) % 2).astype(np.float32)

tr = StreamingHashedFMTrainer(num_buckets=B, factor_size=4,
                              hash_seed=SEED, learning_rate=0.1)
with tempfile.TemporaryDirectory() as td:
    reg = ModelRegistry(td)
    pub = DeltaPublisher(reg, tr, every_n_batches=1, max_depth=64,
                         name="ci_freshness")
    ids, labels = batch()
    tr.fit_batch(ids, labels)
    pub.publish_now()  # the base snapshot
    pool = ReplicaPool(
        reg, Table({"hashed_ids": np.zeros((2, L), np.int32)}),
        config=ServingConfig(max_batch_rows=64, max_wait_ms=1.0),
        n_replicas=2, name="ci_freshness",
    ).start().follow_registry()
    try:
        N = 12
        for _ in range(N):
            ids, labels = batch()
            tr.fit_batch(ids, labels)
            assert pub.maybe_publish() is not None
            lag = pool.freshness_lag(tr.watermark)
            assert lag == 0, lag  # bound held after every roll
        cur = reg.current_version()
        assert pool.versions() == {"r0": cur, "r1": cur}
        for r in pool.replicas:  # zero full republishes after the base
            c = r.engine._metrics.snapshot()["counters"]
            assert c["full_loads"] == 1 and c["delta_swaps"] == N, (r.name, c)
        rc = reg._metrics.snapshot()["counters"]
        assert rc["full_publishes"] == 1 and rc["delta_publishes"] == N, rc
        # Delta-chain predictions bitwise == a full snapshot's.
        full = tr.make_model()
        ids, _ = batch(8)
        resp = pool.predict({"hashed_ids": ids})
        (want,) = full.transform(Table({"hashed_ids": ids}))
        np.testing.assert_array_equal(
            resp.column("prediction"),
            np.asarray(want.column("prediction")))
        # Chaos variant: r0 dies mid-patch, clients lose zero requests.
        errors, stop = [], threading.Event()

        def client(tid):
            crng = np.random.default_rng(50 + tid)
            try:
                while not stop.is_set():
                    keys = crng.integers(0, 10_000, size=(4, L))
                    cid = hash_buckets(keys.reshape(-1), seed=SEED,
                                       num_buckets=B).reshape(4, L)
                    out = pool.predict({"hashed_ids": cid})
                    assert out.columns["prediction"].shape == (4,)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        with faults.armed(faults.FaultPlan(
                faults.ReplicaDown("r0", at_batch=2))):
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for _ in range(4):
                ids, labels = batch()
                tr.fit_batch(ids, labels)
                pub.maybe_publish()
            deadline = time.monotonic() + 60
            while (time.monotonic() < deadline and
                   pool.stats()["per_replica"]["r0"]["state"]
                   != "unhealthy"):
                time.sleep(0.05)
            time.sleep(0.3)  # must keep serving after the kill
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not errors, errors[:3]
        assert pool.stats()["per_replica"]["r0"]["state"] == "unhealthy"
        cur = reg.current_version()
        assert pool.versions()["r1"] == cur  # survivor kept patching
        pool.revive("r0")
        assert pool.versions() == {"r0": cur, "r1": cur}
        assert pool.freshness_lag(tr.watermark) == 0
    finally:
        pool.stop()
print("freshness loop: %d delta publishes, zero full republishes after "
      "base; lag 0 held; chaos kill lost zero requests" % N)
EOF
    # The seeded FML505 fixture must be flagged (the hash/vocab mismatch
    # gate has teeth) — the dir-walk fixture gate covers it too; this is
    # the named assert.
    if env JAX_PLATFORMS=cpu python -m flinkml_tpu.analysis \
        tests/analysis_fixtures/bad_hash_fml505_bucket_vocab_mismatch.features.json \
        --no-selfcheck --fail-on-findings >/dev/null 2>&1; then
        echo "FML505 fixture was NOT flagged"
        return 1
    fi
    local out
    out=$(_FLINKML_BENCH_INNER=feature_freshness_cpu timeout 420 \
        python bench.py) || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rec = json.loads(sys.stdin.read())
assert rec['full_publishes'] == 1 and rec['delta_publishes'] >= 16, rec
assert 0 < rec['delta_ratio'] < 0.5, rec
assert rec['freshness_lag_batches'] == 0, rec
assert rec['time_to_freshness_ms_p99'] >= rec['time_to_freshness_ms_p50'] > 0, rec
print('freshness smoke: train rows/s', rec['train_rows_per_sec'],
      'delta ratio', rec['delta_ratio'],
      'ttf p50/p99 ms', rec['time_to_freshness_ms_p50'],
      rec['time_to_freshness_ms_p99'],
      '(device stage queued in bench stage_order)')
"
}
stage "freshness smoke (hashed stream -> delta-only pool + chaos kill)" \
    freshness_smoke

# Cluster smoke (ISSUE 20 acceptance, device-free): "N replicas" means
# N worker PROCESSES. (1) tests/_cluster_child.py runs the whole
# multi-process scenario in a clean interpreter: 2 spawned workers
# serve sha256-bitwise-identically to the in-process engine, a
# WorkerCrash (real os._exit) armed OVER the transport kills one
# mid-closed-loop-traffic with ZERO lost requests (typed
# WorkerDiedError -> router failover), the respawn rejoins WARM from
# the pool's shared artifact store (aot loads, zero new XLA compiles),
# and a slice lease held inside a worker revoke->releases over the
# wire. (2) A short worker-crash chaos soak: trainer incarnations are
# supervised CHILD processes, restarts resume from the checkpoint
# family (no silent fresh start, ledger parity vs golden). (3) Parses
# bench.py multiproc_pool_cpu — rows/s-per-worker plus the
# worker-vs-thread speedup ratio; the >= 1.5x acceptance ratio is
# asserted only when >= 8 host cores back the workers (on a starved
# box the ratio measures the OS scheduler, not the pool — parity and
# zero-loss assert unconditionally).
cluster_smoke() {
    local out
    out=$(JAX_PLATFORMS=cpu PYTHONPATH=. timeout 420 \
        python tests/_cluster_child.py) || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, sys
rep = json.loads(sys.stdin.read())
assert rep['parity_bitwise'] is True, rep
assert rep['sha_ref'] == rep['sha_pool'], rep
assert rep['crashed_rc'] == 23, rep
assert rep['requests_ok'] > 0 and rep['requests_lost'] == 0, rep
assert rep['respawned'], rep
assert rep['respawn_fusion']['compiles'] == 0.0, rep
assert rep['respawn_fusion']['aot_loads'] > 0, rep
assert rep['post_respawn_parity'] is True, rep
assert rep['lease_reclaimed'] and all(
    l['released'] for l in rep['lease_reclaimed']), rep
assert rep['workers_alive_gauge'] == 2.0, rep
print('cluster smoke: parity sha', rep['sha_pool'][:12],
      '| crash rc', rep['crashed_rc'], '->', rep['requests_ok'],
      'requests ok,', rep['requests_lost'], 'lost',
      '| respawn compiles', rep['respawn_fusion']['compiles'],
      'aot_loads', rep['respawn_fusion']['aot_loads'],
      '| lease released', len(rep['lease_reclaimed']))
" || return 1
    JAX_PLATFORMS=cpu timeout 420 \
        python -m flinkml_tpu.recovery.fuzz --worker --seed 7 --budget 4 \
        --wall-budget-s 300 || return 1
    out=$(_FLINKML_BENCH_INNER=multiproc_pool_cpu timeout 560 \
        python bench.py) || return 1
    printf '%s\n' "$out" | tail -1 | python -c "
import json, math, sys
rec = json.loads(sys.stdin.read())
assert rec['parity_bitwise'] is True, rec
per = rec['multiproc_rows_per_sec_per_worker']
assert math.isfinite(per) and per > 0, rec
if (rec['host_cpu_count'] or 0) >= 8:
    assert rec['worker_vs_thread_speedup'] >= 1.5, (
        'process pool lost to the in-process pool on a full host', rec)
print('cluster smoke bench:', rec['multiproc_rows_per_sec'], 'rows/s',
      '(', per, 'per worker ) worker/thread',
      rec['worker_vs_thread_speedup'], 'x on',
      rec['host_cpu_count'], 'cores (device stage queued in bench',
      'stage_order)')
"
}
stage "cluster smoke (2-proc parity + kill-mid-traffic + warm respawn)" \
    cluster_smoke

example_smoke() {
    local ex
    for ex in parallel_primitives checkpoint_resume sparse_high_cardinality; do
        echo "--- example: $ex ---"
        JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
            timeout 420 python "examples/${ex}.py" || return 1
    done
}
stage "example smoke (CPU mesh)" example_smoke

if [ "$FAIL" = 0 ]; then
    echo "=== ci: ALL STAGES GREEN (log: $LOG) ==="
else
    echo "=== ci: FAILURES — see $LOG ==="
fi
exit $FAIL
