"""Microbenchmark: per-step sort cost of the sparse gradient scatter.

Compares segment_sum at Criteo shapes ([1e7] cells -> [1e6] segments):
  A) unsorted ids (the current trainer: XLA sorts every step)
  B) pre-sorted ids + indices_are_sorted=True (sort paid once at pack)
  C) pre-sorted ids WITHOUT the flag (is the flag or the order what wins?)
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.utils.device_lock import device_client_lock

n_cells, dim, steps = 262_144 * 39, 1_000_000, 20
rng = np.random.default_rng(0)
ids = rng.integers(0, dim, n_cells).astype(np.int32)
vals = rng.normal(size=n_cells).astype(np.float32)
order = np.argsort(ids, kind="stable")
ids_sorted = ids[order]
vals_sorted = vals[order]


def loop(ids_dev, flag):
    @jax.jit
    def run(v):
        def body(i, acc):
            seg = jax.ops.segment_sum(
                v * (1.0 + 1e-6 * i), ids_dev, num_segments=dim,
                indices_are_sorted=flag,
            )
            return acc + seg[0]
        return jax.lax.fori_loop(0, steps, body, jnp.float32(0))
    return run


def main():
    for name, i_np, v_np, flag in [
        ("unsorted         ", ids, vals, False),
        ("sorted+flag      ", ids_sorted, vals_sorted, True),
        ("sorted, no flag  ", ids_sorted, vals_sorted, False),
    ]:
        i_dev = jnp.asarray(i_np)
        v_dev = jnp.asarray(v_np)
        fn = loop(i_dev, flag)
        np.asarray(fn(v_dev))          # compile + warm
        t0 = time.perf_counter()
        np.asarray(fn(v_dev))
        dt = time.perf_counter() - t0
        sps = 262_144 * steps / dt
        print(f"{name}: {dt*1e3/steps:7.2f} ms/step  -> "
              f"{sps/1e6:8.2f}M samples/s", flush=True)


if __name__ == "__main__":
    with device_client_lock():
        main()
