"""Device probe: where does bf16's missing 2x go? (VERDICT r4 weak #5)

Round-4 measured the bf16 dense stage at 835M samples/s — 50% of its
1.66G/s byte-bound roofline — while f32 hits 66-71% of its own bound.
BASELINE.md attributes the gap to per-step fixed costs (loop control,
the [d] coefficient-update chain, reduction epilogues) that don't shrink
when the streamed bytes halve; this probe MEASURES that attribution:

1. The product dense trainer at d = 123 (the bench shape), 512, and
   1024, f32 vs bf16. If the bf16/f32 ratio grows toward 2x with d, the
   d=123 gap is the fixed-cost share, not a bf16-path defect.
2. A stream-only kernel (same rotating window + psum, coefficient chain
   removed) at the same shapes — the achievable ceiling for the access
   pattern; the delta to (1) is the per-step update-chain cost.

Output: one ms/step line per (variant, d, dtype) — transcribe into
BASELINE.md's bf16 section.
"""

import time

import numpy as np

from flinkml_tpu.utils.device_lock import device_client_lock

N, BS, STEPS = 1_000_000, 262_144, 200


def data(dim, dtype):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, dim)).astype(np.float32)
    true_coef = rng.normal(size=dim).astype(np.float32)
    y = (x @ true_coef > 0).astype(np.float32)
    w = np.ones(N, dtype=np.float32)
    return x.astype(dtype), y.astype(dtype), w.astype(dtype)


def run_trainer(dim, dtype_name):
    import jax.numpy as jnp
    from flinkml_tpu.models import _linear_sgd
    from flinkml_tpu.models.logistic_regression import (
        _device_trainer,
        _shard_training_data,
    )
    from flinkml_tpu.parallel import DeviceMesh

    dtype = jnp.bfloat16 if dtype_name == "bf16" else np.float32
    x, y, w = data(dim, dtype)
    mesh = DeviceMesh()
    p = mesh.axis_size()
    xd, yd, wd = _shard_training_data(x, y, w, mesh)
    local_bs = _linear_sgd.align_local_bs(BS, p, xd.shape[0] // p)
    trainer = _device_trainer(mesh.mesh, local_bs, DeviceMesh.DATA_AXIS)
    f = lambda v: jnp.asarray(v, xd.dtype)
    carry0 = (jnp.zeros(xd.shape[1], xd.dtype), jnp.asarray(0, jnp.int32),
              jnp.asarray(jnp.inf, xd.dtype))
    args = (xd, yd, wd, f(0.1), f(0.0), f(0.0), f(0.0))
    np.asarray(trainer(*carry0, *args, jnp.asarray(5, jnp.int32))[0])
    t0 = time.perf_counter()
    coef, steps_out, _ = trainer(*carry0, *args, jnp.asarray(STEPS, jnp.int32))
    np.asarray(coef)
    dt = time.perf_counter() - t0
    assert int(steps_out) == STEPS
    print(f"trainer     d={dim:5d} {dtype_name}: {dt * 1e3 / STEPS:7.3f} "
          f"ms/step -> {local_bs * p * STEPS / dt / 1e6:8.1f}M samples/s",
          flush=True)


def run_stream_only(dim, dtype_name):
    """Ceiling: the same per-step x window read + matvec + psum, with the
    coefficient update chain replaced by a scalar carry."""
    import jax
    import jax.numpy as jnp
    from flinkml_tpu.models import _linear_sgd
    from flinkml_tpu.parallel import DeviceMesh
    from jax.sharding import PartitionSpec as P

    dtype = jnp.bfloat16 if dtype_name == "bf16" else np.float32
    x, _, _ = data(dim, dtype)
    mesh = DeviceMesh()
    p = mesh.axis_size()
    pad = (-x.shape[0]) % p
    if pad:
        x = np.concatenate([x, x[:pad]])
    local_bs = _linear_sgd.align_local_bs(BS, p, x.shape[0] // p)
    probe_vec = jnp.ones((dim,), dtype)

    def per_device(acc, xl, n_steps):
        def body(i, acc):
            xb = _linear_sgd._window(xl, i, local_bs)
            s = jnp.sum((xb @ probe_vec).astype(jnp.float32))
            return acc + jax.lax.psum(s, DeviceMesh.DATA_AXIS)
        return jax.lax.fori_loop(0, n_steps, body, acc)

    fn = jax.jit(jax.shard_map(
        per_device, mesh=mesh.mesh,
        in_specs=(P(), P(DeviceMesh.DATA_AXIS), P()),
        out_specs=P(),
    ))
    xd = mesh.shard_batch(x)
    np.asarray(fn(jnp.float32(0), xd, jnp.asarray(5, jnp.int32)))
    t0 = time.perf_counter()
    np.asarray(fn(jnp.float32(0), xd, jnp.asarray(STEPS, jnp.int32)))
    dt = time.perf_counter() - t0
    print(f"stream-only d={dim:5d} {dtype_name}: {dt * 1e3 / STEPS:7.3f} "
          f"ms/step -> {local_bs * p * STEPS / dt / 1e6:8.1f}M samples/s",
          flush=True)


def main():
    for dim in (123, 512, 1024):
        for dt in ("f32", "bf16"):
            run_trainer(dim, dt)
            run_stream_only(dim, dt)


if __name__ == "__main__":
    with device_client_lock():
        main()
