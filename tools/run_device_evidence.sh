#!/bin/bash
# One-shot device-evidence capture for the moment the tunnel heals —
# the same suite as device_watch.sh, without the watching loop:
#   1. health probe (aborts early if the tunnel is still wedged)
#   2. full staged bench -> JSON result line (the round's headline numbers)
#   3. sparse layout 3-way A/B           (VERDICT r4 item 2 — decides the
#      cumsum-vs-unsorted product default)
#   4. gather/scatter bounds-mode A/B
#   5. bf16 gap attribution sweep        (VERDICT r4 item 6)
# All output lands in tools/device_evidence_<UTC>.log; append the numbers
# to BASELINE.md afterwards. Never run concurrently with another device
# client (each step takes the single-tenant device lock itself).
set -u
cd "$(dirname "$0")/.."
# Tools import flinkml_tpu; keep the axon site dir so device access works.
export PYTHONPATH="$PWD:/root/.axon_site${PYTHONPATH:+:$PYTHONPATH}"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
LOG="tools/device_evidence_${STAMP}.log"
exec > >(tee "$LOG") 2>&1

echo "=== device evidence run ${STAMP} ==="

echo "--- 1. health probe (90 s cap) ---"
if ! timeout 90 python tools/device_probe.py; then
    echo "PROBE FAILED: tunnel still wedged; aborting (log: $LOG)"
    exit 1
fi

echo "--- 2. full staged bench (FLINKML_BENCH_TIMEOUT=${FLINKML_BENCH_TIMEOUT:-3300} s) ---"
# Outer kill-cap tracks the bench's own budget (+10 min of slack) so an
# operator raising FLINKML_BENCH_TIMEOUT doesn't get SIGKILLed mid-run.
# 3300 s default here (vs the driver's 1680): 13 stages on a cold
# compile cache took ~50 min in the round-4 healthy window.
FLINKML_BENCH_TIMEOUT="${FLINKML_BENCH_TIMEOUT:-3300}" \
timeout $(( ${FLINKML_BENCH_TIMEOUT:-3300} + 600 )) python bench.py \
    || echo "bench FAILED rc=$?"

echo "--- 3. sparse layout A/B (1200 s cap) ---"
timeout 1200 python tools/sparse_layout_probe.py \
    || echo "sparse_layout_probe FAILED rc=$?"

echo "--- 4. gather/scatter bounds-mode A/B (600 s cap) ---"
timeout 600 python tools/sparse_pib_probe.py \
    || echo "sparse_pib_probe FAILED rc=$?"

echo "--- 5. bf16 dense profile sweep (600 s cap) ---"
timeout 600 python tools/bf16_profile_probe.py \
    || echo "bf16_profile_probe FAILED rc=$?"

echo "=== done; transcribe results into BASELINE.md (log: $LOG) ==="
