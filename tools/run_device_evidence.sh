#!/bin/bash
# One-shot device-evidence capture for the moment the tunnel heals.
# Runs, in order, with generous but bounded timeouts and full logging:
#   1. health probe (aborts early if the tunnel is still wedged)
#   2. sorted-scatter A/B at Criteo shapes (VERDICT r3 item 4a)
#   3. compile-ceiling sweep, device half   (VERDICT r3 item 4b)
#   4. full staged bench -> one JSON line   (the round's headline number)
# All output lands in tools/device_evidence_<UTC>.log; append the numbers
# to BASELINE.md afterwards. Never run concurrently with another device
# client (each step takes the single-tenant device lock itself).
set -u
cd "$(dirname "$0")/.."
# Tools import flinkml_tpu; keep the axon site dir so device access works.
export PYTHONPATH="$PWD:/root/.axon_site${PYTHONPATH:+:$PYTHONPATH}"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
LOG="tools/device_evidence_${STAMP}.log"
exec > >(tee "$LOG") 2>&1

echo "=== device evidence run ${STAMP} ==="

echo "--- 1. health probe (90 s cap) ---"
if ! timeout 90 python tools/device_probe.py; then
    echo "PROBE FAILED: tunnel still wedged; aborting (log: $LOG)"
    exit 1
fi

echo "--- 2. sorted-scatter A/B (600 s cap) ---"
timeout 600 python tools/sorted_scatter_probe.py \
    || echo "sorted_scatter_probe FAILED rc=$?"

echo "--- 3. compile-ceiling sweep, device half (1800 s cap) ---"
timeout 1800 python tools/compile_ceiling_probe.py \
    || echo "compile_ceiling_probe FAILED rc=$?"

echo "--- 4. full staged bench (FLINKML_BENCH_TIMEOUT=${FLINKML_BENCH_TIMEOUT:-2100} s) ---"
# Outer kill-cap tracks the bench's own budget (+10 min of slack) so an
# operator raising FLINKML_BENCH_TIMEOUT doesn't get SIGKILLed mid-run.
timeout $(( ${FLINKML_BENCH_TIMEOUT:-2100} + 600 )) python bench.py \
    || echo "bench FAILED rc=$?"

echo "=== done; transcribe results into BASELINE.md (log: $LOG) ==="
