"""Benchmark: LogisticRegression training throughput (samples/sec/chip)
plus epochs-to-converge — both halves of BASELINE.json's metric.

Emits JSON lines of the shape {"metric", "value", "unit", "vs_baseline",
"extras"}; the LAST line on stdout is the result. A provisional line
(CPU fallback + hardware-independent epochs-to-tol + a pointer to the
newest committed device capture) prints BEFORE any tunnel contact, so a
driver kill mid-hunt still leaves a parseable artifact — rounds 1-4 all
ended rc=124 with nothing on stdout; this is the fix. The final line
re-prints with per-chip numbers when the device phase succeeds.

The north-star metric (BASELINE.json): samples/sec/chip for
LogisticRegression.fit. The reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured against a faithful reimplementation of the
reference's execution model run on this host's CPU: record-at-a-time SGD
with per-record BLAS dot/axpy (``LogisticGradient.java:50-96`` iterates
records in a Java loop over netlib BLAS; the numpy equivalent below gives it
the benefit of C-speed vector ops per record). Both sides time the same
work: epochs of global-batch gradient steps at identical batch size/dim.

Tunnel-hardening (round-2): the device in this image sits behind a proxy
that can hang indefinitely on jax init or the first transfer. Every device
measurement therefore runs in a child process and is STAGED:

  stage 1 (probe):   a tiny program — device init + one small compile +
                     one dispatch. Fails fast (bounded timeout) if the
                     tunnel is down, without burning the full budget.
  stage 2 (measure): the real run. Only entered after the probe passes,
                     with its own bounded timeout.

Each stage retries once. Children share a persistent XLA compilation cache
so a retry never re-pays the first compile. On total failure the CPU
baseline is emitted under an explicitly different metric name
(`..._cpu_fallback`) so a fallback can never be mistaken for a per-chip
measurement. The roofline analysis justifying the device number by
bytes/step and flops/step (not just a wall clock) is in BASELINE.md
("Roofline" section).
"""

import glob
import json
import math
import os
import re
import subprocess
import sys
import time

import numpy as np

_INNER_ENV = "_FLINKML_BENCH_INNER"
_CACHE_DIR = "/tmp/jax_bench_cache"


def _force_cpu():
    """Pin this (child) process to the host CPU backend. The axon TPU
    plugin prepends itself to ``jax_platforms`` at import time, overriding
    the JAX_PLATFORMS env var, so stages that must never touch the tunnel
    (the provisional convergence run) force CPU via config as well."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def make_data(n, dim, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(dtype)
    true_coef = rng.normal(size=dim).astype(dtype)
    y = (x @ true_coef > 0).astype(dtype)
    w = np.ones(n, dtype=dtype)
    return x, y, w


def make_criteo_csr(n, dim=1_000_000, nnz=39, seed=0, n_active=256):
    """Synthetic Criteo-profile CSR: ``nnz`` uniform-random columns per
    row over ``dim``, labels planted by a sparse true model with
    ``n_active`` nonzero coefficients. ONE definition shared by the
    sparse throughput stage, the sparse convergence stage, and
    ``tools/sparse_layout_probe.py`` so every sparse measurement sees
    the same distribution."""
    rng = np.random.default_rng(seed)
    indptr = np.arange(n + 1, dtype=np.int64) * nnz
    indices = rng.integers(0, dim, size=n * nnz).astype(np.int32)
    values = rng.normal(size=n * nnz).astype(np.float32)
    active = rng.choice(dim, size=n_active, replace=False)
    beta = np.zeros(dim, dtype=np.float32)
    beta[active] = rng.normal(size=n_active)
    margins = (
        values.reshape(n, nnz) * beta[indices.reshape(n, nnz)]
    ).sum(axis=1)
    y = (margins > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    return indptr, indices, values, y, w


def _log(msg):
    sys.stderr.write(f"[bench] {msg}\n")
    sys.stderr.flush()


def _setup_jax_cache():
    import jax

    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def _dense_trainer_setup(x, y, w, global_batch_size, tol,
                         loss="logistic", reg_l2=0.0, reg_l1=0.0):
    """Shared setup for the dense throughput, convergence, and proximal
    (SVC) measurements: mesh, product-path sharding and batch alignment
    (round-1 finding: a hand-computed local_bs here could disagree with
    the product program), trainer, initial carry, and the hyperparameter
    args. One definition so the measurements can never drift onto
    different programs."""
    import jax.numpy as jnp
    from flinkml_tpu.models import _linear_sgd
    from flinkml_tpu.models.logistic_regression import _shard_training_data
    from flinkml_tpu.parallel import DeviceMesh

    mesh = DeviceMesh()
    p = mesh.axis_size()
    xd, yd, wd = _shard_training_data(x, y, w, mesh)
    local_bs = _linear_sgd.align_local_bs(
        global_batch_size, p, xd.shape[0] // p
    )
    trainer = _linear_sgd._dense_trainer(
        mesh.mesh, loss, local_bs, DeviceMesh.DATA_AXIS
    )
    f32 = lambda v: jnp.asarray(v, xd.dtype)
    carry0 = (
        jnp.zeros(xd.shape[1], xd.dtype),
        jnp.asarray(0, jnp.int32),
        jnp.asarray(jnp.inf, xd.dtype),
    )
    args = (xd, yd, wd, f32(0.1), f32(reg_l2), f32(reg_l1), f32(tol))
    return trainer, carry0, args, local_bs, p


def bench_tpu(x, y, w, global_batch_size, n_steps):
    """Steady-state training throughput with the dataset resident in HBM —
    the analog of the reference's steady state, which trains from data
    cached in ListState (LogisticRegression.java:375-376) after epoch 0.

    Timing: one dispatch of the whole training loop, synchronized by
    materializing the result on host (np.asarray) — block_until_ready alone
    is NOT reliable over this image's tunneled device (BASELINE.md)."""
    import jax.numpy as jnp

    trainer, carry0, args, local_bs, p = _dense_trainer_setup(
        x, y, w, global_batch_size, tol=0.0
    )
    _log("compiling + warm-up dispatch ...")
    np.asarray(trainer(*carry0, *args, jnp.asarray(10, jnp.int32))[0])
    _log("measuring ...")
    start = time.perf_counter()
    coef_out, steps_out, _ = trainer(
        *carry0, *args, jnp.asarray(n_steps, jnp.int32)
    )
    np.asarray(coef_out)
    elapsed = time.perf_counter() - start
    # The while_loop can exit early (tol hit, or a NaN loss — NaN > tol is
    # False); throughput must count the steps that actually ran, and a
    # short-circuited run must never masquerade as a fast one.
    steps_ran = int(steps_out)
    if steps_ran != n_steps:
        raise RuntimeError(
            f"trainer stopped after {steps_ran}/{n_steps} steps "
            "(diverged or converged); measurement invalid"
        )
    return local_bs * p * steps_ran / elapsed


def bench_tpu_sparse(indptr, indices, values, dim, y, w,
                     global_batch_size, n_steps):
    """Sparse (Criteo-profile) training throughput: nnz-bucketed ELL
    blocks resident in HBM, whole loop in one dispatch (same timing
    discipline as :func:`bench_tpu`)."""
    import jax.numpy as jnp
    from flinkml_tpu.models import _linear_sgd
    from flinkml_tpu.parallel import DeviceMesh

    mesh = DeviceMesh()
    p = mesh.axis_size()
    # Same pack/pad/shard/batching policy as the product fit path —
    # including the FLINKML_TPU_SPARSE_LAYOUT A/B gate, so setting it
    # really benchmarks the selected gradient layout.
    layout = _linear_sgd._sparse_layout()
    data_args, local_bss = _linear_sgd.prepare_sparse_buckets(
        indptr, indices, values, dim, y, w, mesh, global_batch_size,
        seed=0, layout=layout,
    )
    trainer = _linear_sgd._sparse_trainer_bucketed(
        mesh.mesh, "logistic", local_bss, DeviceMesh.DATA_AXIS, int(dim),
        layout,
    )
    f32 = lambda v: jnp.asarray(v, jnp.float32)
    carry0 = (
        jnp.zeros(dim, jnp.float32),
        jnp.asarray(0, jnp.int32),
        jnp.asarray(jnp.inf, jnp.float32),
    )
    hy = (f32(0.1), f32(0.0), f32(0.0), f32(0.0))
    _log("sparse: compiling + warm-up dispatch ...")
    np.asarray(trainer(*carry0, *data_args, *hy,
                       jnp.asarray(10, jnp.int32))[0])
    _log("sparse: measuring ...")
    start = time.perf_counter()
    coef_out, steps_out, _ = trainer(
        *carry0, *data_args, *hy, jnp.asarray(n_steps, jnp.int32)
    )
    np.asarray(coef_out)
    elapsed = time.perf_counter() - start
    steps_ran = int(steps_out)
    if steps_ran != n_steps:
        raise RuntimeError(
            f"sparse trainer stopped after {steps_ran}/{n_steps} steps; "
            "measurement invalid"
        )
    return sum(local_bss) * p * steps_ran / elapsed


def bench_convergence(x, y, w, global_batch_size, tol, max_steps):
    """Epochs/wall-clock to convergence — the other half of BASELINE.json's
    north-star metric ("samples/sec/chip + epochs-to-converge").

    Runs the SAME whole-loop device program as :func:`bench_tpu` but with a
    positive ``tol``: the on-device while_loop exits as soon as the epoch's
    mean logistic loss reaches ``tol`` (TerminateOnMaxIterOrTol semantics —
    the contract `LogisticRegressionTest.java:60-90` pins at fixture scale).
    Returns ``(steps_ran, elapsed_s)``; the caller converts steps to epochs
    via ``steps * global_batch_size / n``."""
    import jax.numpy as jnp

    trainer, carry0, args, _, _ = _dense_trainer_setup(
        x, y, w, global_batch_size, tol
    )
    _log("converge: compiling + warm-up dispatch ...")
    np.asarray(trainer(*carry0, *args, jnp.asarray(2, jnp.int32))[0])
    _log("converge: measuring steps-to-tol ...")
    start = time.perf_counter()
    coef_out, steps_out, loss_out = trainer(
        *carry0, *args, jnp.asarray(max_steps, jnp.int32)
    )
    np.asarray(coef_out)
    elapsed = time.perf_counter() - start
    steps_ran = int(steps_out)
    final_loss = float(loss_out)
    if steps_ran >= max_steps or not math.isfinite(final_loss):
        raise RuntimeError(
            f"did not converge: steps={steps_ran}/{max_steps} "
            f"loss={final_loss} tol={tol}"
        )
    return steps_ran, elapsed


def bench_reference_style_cpu(x, y, w, global_batch_size, budget_s=10.0):
    """The reference's per-record execution model (LogisticGradient.java:50-96):
    one dot + one axpy per record per epoch, coefficient update per epoch."""
    n, dim = x.shape
    x64, y64, w64 = x.astype(np.float64), y.astype(np.float64), w.astype(np.float64)
    coef = np.zeros(dim)
    rng = np.random.default_rng(0)
    processed = 0
    start = time.perf_counter()
    grad = np.zeros(dim)
    while time.perf_counter() - start < budget_s:
        idx = rng.integers(0, n, size=global_batch_size)
        grad[:] = 0.0
        wsum = 0.0
        for i in idx:  # record-at-a-time, as the reference's Java loop
            xi = x64[i]
            dot = float(xi @ coef)
            ys = 2.0 * y64[i] - 1.0
            mult = w64[i] * (-ys / (math.exp(dot * ys) + 1.0))
            grad += mult * xi  # BLAS.axpy per record
            wsum += w64[i]
        coef -= (0.1 / wsum) * grad
        processed += global_batch_size
    return processed / (time.perf_counter() - start)


# -- inner (child-process) stages -------------------------------------------

def _inner_probe() -> float:
    """Stage 1: smallest realistic program. Exists to bound how long a hung
    tunnel can cost: device init + data transfer + small compile + one
    dispatch. Returns a (meaningless) throughput so stdout parsing is
    uniform."""
    _setup_jax_cache()
    n, dim = 65_536, 123
    x, y, w = make_data(n, dim)
    return bench_tpu(x, y, w, global_batch_size=8_192, n_steps=20)


def _dense_stage(dtype=None) -> float:
    """The dense measurement — a9a-like width (BASELINE.json config #1),
    dataset resident in HBM, whole loop in one dispatch. One definition
    for every dtype so f32 and bf16 always measure the same workload."""
    _setup_jax_cache()
    n, dim = 1_000_000, 123
    x, y, w = make_data(n, dim)
    if dtype is not None:
        x, y, w = x.astype(dtype), y.astype(dtype), w.astype(dtype)
    return bench_tpu(x, y, w, global_batch_size=262_144, n_steps=400)


def _inner_dense() -> float:
    return _dense_stage()


def _inner_svc() -> float:
    """Stage: LinearSVC proximal SGD (BASELINE.json config #3) — hinge
    loss with an elastic-net proximal step (both L1 and L2 active so the
    soft-threshold path is really measured), same a9a-like workload and
    timing discipline as the dense stage, through the loss-generic
    product trainer (`_linear_sgd._dense_trainer`)."""
    _setup_jax_cache()
    import jax.numpy as jnp

    n, dim, gbs, n_steps = 1_000_000, 123, 262_144, 400
    x, y, w = make_data(n, dim)
    trainer, carry0, args, local_bs, p = _dense_trainer_setup(
        x, y, w, gbs, tol=0.0, loss="hinge", reg_l2=1e-4, reg_l1=1e-4
    )
    _log("svc: compiling + warm-up dispatch ...")
    np.asarray(trainer(*carry0, *args, jnp.asarray(10, jnp.int32))[0])
    _log("svc: measuring ...")
    start = time.perf_counter()
    coef_out, steps_out, _ = trainer(
        *carry0, *args, jnp.asarray(n_steps, jnp.int32)
    )
    np.asarray(coef_out)
    elapsed = time.perf_counter() - start
    if int(steps_out) != n_steps:
        raise RuntimeError(
            f"svc trainer stopped after {int(steps_out)}/{n_steps} steps"
        )
    return local_bs * p * n_steps / elapsed


def _inner_ftrl() -> float:
    """Stage: OnlineLogisticRegression FTRL (BASELINE.json config #4) —
    steady-state per-batch step throughput of the unbounded online path.
    Batches are pre-resident and the (z, n, coef) state chains through
    async dispatches with ONE end-of-run synchronization, so the number
    measures the architecture (per-batch dispatch + FTRL algebra +
    psum), not tunnel latency — the same discipline as feed_overlap."""
    _setup_jax_cache()
    import jax.numpy as jnp
    from flinkml_tpu.models.online_logistic_regression import (
        _ftrl_sharded_fn,
    )
    from flinkml_tpu.parallel import DeviceMesh

    n_batches, bs, dim, passes = 64, 16_384, 123, 8
    rng = np.random.default_rng(0)
    true_coef = rng.normal(size=dim).astype(np.float32)
    mesh = DeviceMesh()
    step = _ftrl_sharded_fn(mesh.mesh, DeviceMesh.DATA_AXIS)
    batches = []
    for _ in range(n_batches):
        x = rng.normal(size=(bs, dim)).astype(np.float32)
        y = (x @ true_coef > 0).astype(np.float32)
        batches.append((
            mesh.shard_batch(x), mesh.shard_batch(y),
            mesh.shard_batch(np.ones(bs, np.float32)),
        ))
    import jax

    jax.block_until_ready(batches)
    f32 = lambda v: jnp.asarray(v, jnp.float32)
    hy = (f32(0.1), f32(1.0), f32(0.001), f32(0.001))
    zeros = jnp.zeros(dim, jnp.float32)

    def run(n_passes):
        z, nacc, coef = zeros, zeros, zeros
        for _ in range(n_passes):
            for xb, yb, wb in batches:
                z, nacc, coef, _ = step(xb, yb, wb, z, nacc, coef, *hy)
        np.asarray(coef)  # single synchronization
        return coef

    _log("ftrl: compiling + warm-up pass ...")
    run(1)
    _log("ftrl: measuring ...")
    start = time.perf_counter()
    run(passes)
    elapsed = time.perf_counter() - start
    return n_batches * bs * passes / elapsed


def _inner_dense_bf16() -> float:
    """Same workload, bf16-resident. Measured round-2: ~1.02x over f32 —
    at d=123 the per-step fixed costs are a comparable term to the x
    traffic, so halving streamed bytes does not approach the naive ~2x
    byte-bound ceiling (BASELINE.md "Round-2 full-bench measurements").
    Reductions still accumulate in f32 (_linear_sgd._acc_dt)."""
    import jax.numpy as jnp

    return _dense_stage(jnp.bfloat16)


def _kmeans_stage(n, dim, k, iters) -> float:
    """Stage: KMeans Lloyd throughput — the whole loop (assignment on
    the MXU + one-hot aggregation + psum + update) in one dispatch.

    Two profiles: d=128/k=64 (the round-2 measured table's shape, kept
    for cross-round continuity) and MNIST-784/k=10 (BASELINE.json
    config #2 — restored in round 4 after the device half of
    tools/compile_ceiling_probe.py showed d<=784 compiles in ~1-1.5 s;
    the round-2 ">=10 min at d>=512" observation was the tunnel wedge,
    not the compiler)."""
    _setup_jax_cache()
    import jax.numpy as jnp
    from flinkml_tpu.models.kmeans import _kmeans_trainer, prepare_kmeans_data
    from flinkml_tpu.parallel import DeviceMesh
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    mesh = DeviceMesh()
    # Same pad/mask/shard + kernel gate as the product fit path.
    xd, wd, _ = prepare_kmeans_data(x, mesh)
    cent0 = jnp.asarray(x[rng.choice(n, size=k, replace=False)])
    trainer = _kmeans_trainer(mesh.mesh, k, DeviceMesh.DATA_AXIS)
    _log("kmeans: compiling + warm-up dispatch ...")
    np.asarray(trainer(xd, wd, cent0, jnp.asarray(3, jnp.int32)))
    _log("kmeans: measuring ...")
    start = time.perf_counter()
    np.asarray(trainer(xd, wd, cent0, jnp.asarray(iters, jnp.int32)))
    elapsed = time.perf_counter() - start
    return n * iters / elapsed


def _inner_kmeans() -> float:
    return _kmeans_stage(n=262_144, dim=128, k=64, iters=100)


def _inner_kmeans_mnist() -> float:
    """BASELINE.json config #2: MNIST-784 vectors, k=10 classes."""
    return _kmeans_stage(n=65_536, dim=784, k=10, iters=100)


def _inner_sparse() -> float:
    """Stage 3: Criteo-profile sparse LR (BASELINE.json config #5):
    dim = 1e6, 39 nnz per row, nnz-bucketed ELL resident in HBM."""
    _setup_jax_cache()
    n, dim = 262_144, 1_000_000
    indptr, indices, values, y, w = make_criteo_csr(n, dim)
    return bench_tpu_sparse(
        indptr, indices, values, dim, y, w,
        global_batch_size=262_144, n_steps=200,
    )


def _inner_gbt() -> float:
    """Stage 5: histogram GBT — the whole forest (scan over trees,
    per-level segment-sum histograms) in one device program. Metric:
    row-tree builds per second (n * numTrees / elapsed)."""
    _setup_jax_cache()
    import jax

    from flinkml_tpu.models.gbt import (
        _forest_builder, _hist_layout, bin_features, quantile_bin_edges,
        sharded_hist_args,
    )
    from flinkml_tpu.parallel import DeviceMesh

    # Compile cost over the tunneled device scales hard with the
    # unrolled depth and (nodes x features x bins) segment space; this
    # profile keeps the whole-forest program within the stage cap.
    n, d, bins, depth, trees = 262_144, 16, 32, 4, 20
    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, size=(n, d)).astype(np.float32)
    y = (x[:, 0] * x[:, 1] > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    edges = quantile_bin_edges(x, bins)
    binned = bin_features(x, edges)
    mesh = DeviceMesh()
    # Same FLINKML_TPU_GBT_HISTOGRAM gate as the product fit path.
    hist_layout = _hist_layout()
    builder = _forest_builder(
        mesh.mesh, DeviceMesh.DATA_AXIS, d, bins, depth, trees, True,
        hist_layout=hist_layout,
    )
    import jax.numpy as jnp

    f32 = lambda v: jnp.asarray(v, jnp.float32)
    hist_args = sharded_hist_args(binned, mesh, bins, hist_layout)
    args = (
        mesh.shard_batch(binned), mesh.shard_batch(y), mesh.shard_batch(w),
        f32(0.0), f32(0.2), f32(1.0), f32(1.0), jax.random.PRNGKey(0),
    ) + hist_args
    _log("gbt: compiling + warm-up dispatch ...")
    np.asarray(builder(*args)[2])
    _log("gbt: measuring ...")
    start = time.perf_counter()
    np.asarray(builder(*args)[2])
    elapsed = time.perf_counter() - start
    return n * trees / elapsed


def _inner_als() -> float:
    """Stage: ALS-WR normal-equation half-steps through the product path
    (`ALS.fit`: chunked COO -> segment-sum normal equations -> batched
    Cholesky). Metric: rating visits per second (nnz x 2 sides x iters)."""
    _setup_jax_cache()
    from flinkml_tpu.models.als import ALS
    from flinkml_tpu.table import Table

    n_users, n_items, nnz, rank, iters = 16_384, 16_384, 1 << 21, 32, 10
    rng = np.random.default_rng(0)
    users = rng.integers(0, n_users, size=nnz).astype(np.int32)
    items = rng.integers(0, n_items, size=nnz).astype(np.int32)
    ratings = rng.uniform(1, 5, size=nnz).astype(np.float32)
    table = Table({"user": users, "item": items, "rating": ratings})
    _log("als: compiling + warm-up fit ...")
    ALS().set_rank(rank).set_max_iter(1).set_seed(0).fit(table)
    _log("als: measuring ...")
    start = time.perf_counter()
    ALS().set_rank(rank).set_max_iter(iters).set_seed(0).fit(table)
    elapsed = time.perf_counter() - start
    return nnz * 2 * iters / elapsed


def _inner_word2vec() -> float:
    """Stage: skip-gram negative-sampling SGD through the product trainer
    (`word2vec._sgns_trainer`: whole loop in one dispatch, dense psum of
    embedding grads). Metric: (center, context) pairs per second."""
    _setup_jax_cache()
    import jax
    import jax.numpy as jnp
    from flinkml_tpu.models.word2vec import _sgns_trainer, _w2v_accum
    from flinkml_tpu.parallel import DeviceMesh

    vocab, dim, n_pairs, bs, n_neg, steps = 32_768, 128, 1 << 20, 8_192, 5, 200
    rng = np.random.default_rng(0)
    centers = rng.integers(0, vocab, size=n_pairs).astype(np.int32)
    contexts = rng.integers(0, vocab, size=n_pairs).astype(np.int32)
    pool = rng.integers(0, vocab, size=1 << 17).astype(np.int32)
    v0 = (rng.random((vocab, dim)) - 0.5).astype(np.float32) / dim
    u0 = np.zeros((vocab, dim), np.float32)
    mesh = DeviceMesh()
    local_bs = max(1, bs // mesh.axis_size())
    # The gradient-accumulation gate (FLINKML_TPU_W2V_ACCUM) rides into
    # the measurement, so the probe's winner is benchable the same day.
    trainer = _sgns_trainer(mesh.mesh, DeviceMesh.DATA_AXIS, local_bs,
                            n_neg, _w2v_accum())
    args = (
        mesh.shard_batch(centers), mesh.shard_batch(contexts),
        mesh.shard_batch(np.ones(n_pairs, np.float32)),
        jnp.asarray(pool), jnp.asarray(v0), jnp.asarray(u0),
        jnp.asarray(0.025, jnp.float32),
    )
    key = jax.random.PRNGKey(0)
    _log("word2vec: compiling + warm-up dispatch ...")
    np.asarray(trainer(*args, jnp.asarray(5, jnp.int32), key)[0])
    _log("word2vec: measuring ...")
    start = time.perf_counter()
    np.asarray(trainer(*args, jnp.asarray(steps, jnp.int32), key)[0])
    elapsed = time.perf_counter() - start
    return local_bs * mesh.axis_size() * steps / elapsed


def _five_stage_model(n=100_000, d=32, seed=0):
    """The bench's canonical all-kernel chain (StandardScaler →
    MinMaxScaler → MaxAbsScaler → RobustScaler → LogisticRegressionModel),
    fitted on seeded data; shared by the pipeline_fused and serving
    stages so both measure the same program. Returns
    ``(pipeline_model, x)``."""
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import (
        MaxAbsScaler, MinMaxScaler, RobustScaler, StandardScaler,
    )
    from flinkml_tpu.pipeline import PipelineModel
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    train = Table({"features": x, "label": y})
    stages, cur, prev = [], train, "features"
    for i, cls in enumerate(
        (StandardScaler, MinMaxScaler, MaxAbsScaler, RobustScaler), start=1
    ):
        m = cls().set(cls.INPUT_COL, prev).set(cls.OUTPUT_COL, f"s{i}")
        m = m.fit(cur)
        (cur,) = m.transform(cur)
        prev = f"s{i}"
        stages.append(m)
    lr = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, prev)
        .set(LogisticRegression.LABEL_COL, "label")
        .set_max_iter(2)
        .fit(cur)
    )
    stages.append(lr)
    return PipelineModel(stages), x


def _pipeline_fused_stage(n=100_000, d=32, reps=5) -> dict:
    """Stage: fused pipeline inference throughput — a 5-stage all-kernel
    chain (StandardScaler → MinMaxScaler → MaxAbsScaler → RobustScaler →
    LogisticRegressionModel) through ``PipelineModel.transform``, fused
    (one XLA program, device-resident intermediates, shape-bucketed
    compile cache) vs unfused (the per-stage path: N host↔device round
    trips and four host numpy scaler passes). Metric:
    ``pipeline_transform_rows_per_sec`` for both executions, plus the
    speedup — the per-stage-materialization overhead the fused executor
    (flinkml_tpu/pipeline_fusion.py) exists to delete."""
    from flinkml_tpu import pipeline_fusion
    from flinkml_tpu.table import Table

    pipeline_model, x = _five_stage_model(n, d)
    apply_table = Table({"features": x})

    def rows_per_sec():
        # Warm-up covers compiles on both paths; each timed call ends by
        # materializing the prediction column on host (the device→host
        # sync; block_until_ready alone is unreliable over the tunnel).
        np.asarray(
            pipeline_model.transform(apply_table)[0].column("prediction")
        )
        start = time.perf_counter()
        for _ in range(reps):
            out = pipeline_model.transform(apply_table)[0]
            np.asarray(out.column("prediction"))
        return n * reps / (time.perf_counter() - start)

    pipeline_fusion.set_enabled(False)
    try:
        unfused = rows_per_sec()
    finally:
        pipeline_fusion.set_enabled(True)
    pipeline_fusion.reset_cache()
    fused = rows_per_sec()
    return {
        "pipeline_transform_rows_per_sec": round(fused, 1),
        "pipeline_transform_rows_per_sec_unfused": round(unfused, 1),
        "fused_speedup": round(fused / unfused, 2),
        "rows": n,
        "dim": d,
        "stages": 5,
    }


def _inner_pipeline_fused() -> dict:
    _setup_jax_cache()
    return _pipeline_fused_stage()


def _inner_pipeline_fused_cpu() -> dict:
    """The same fused-vs-unfused measurement pinned to the host CPU
    backend: tunnel-immune, so the provisional line always carries the
    fusion trajectory (ISSUE-1 acceptance tracks the CPU-fallback
    speedup; device numbers ride the device phase when healthy)."""
    _force_cpu()
    return _pipeline_fused_stage()


def _serving_stage(n_clients=8, duration_s=4.0, max_batch_rows=256,
                   n=50_000, d=32) -> dict:
    """Stage: online serving throughput/latency — synthetic closed-loop
    clients (each thread issues its next request the moment the previous
    response lands) against the 5-stage fused chain behind a
    ``ServingEngine``: adaptive micro-batching into the fused compile
    cache's row buckets, per-bucket warmup, zero steady-state retraces.
    Metrics: ``serving_rows_per_sec`` (aggregate served rows),
    ``serving_p50_ms`` / ``serving_p99_ms`` (per-request latency,
    enqueue→complete), and mean batch occupancy (rows / bucket rows —
    padding waste of the bucketing policy under this load)."""
    import threading

    from flinkml_tpu.serving import ServingConfig, ServingEngine
    from flinkml_tpu.table import Table

    model, x = _five_stage_model(n, d)
    engine = ServingEngine(
        model,
        example=Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=max_batch_rows,
                             max_wait_ms=1.0),
        output_cols=("prediction",),
        name="bench",
    ).start()

    stop = threading.Event()
    served_rows = [0] * n_clients
    errors = []

    def client(tid):
        rng = np.random.default_rng(tid)
        try:
            while not stop.is_set():
                rows = int(rng.integers(1, 33))
                lo = int(rng.integers(0, n - rows))
                engine.predict({"features": x[lo:lo + rows]})
                served_rows[tid] += rows
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(n_clients)
    ]
    _log(f"serving: {n_clients} closed-loop clients for {duration_s}s ...")
    start = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    elapsed = time.perf_counter() - start
    stats = engine.stats()
    engine.stop()
    if errors:
        raise errors[0]
    counters = stats["counters"]
    occupancy = (
        counters["batch_rows"] / counters["batch_padded_rows"]
        if counters.get("batch_padded_rows") else 0.0
    )
    return {
        "serving_rows_per_sec": round(sum(served_rows) / elapsed, 1),
        "serving_p50_ms": round(stats["gauges"]["p50_ms"], 3),
        "serving_p99_ms": round(stats["gauges"]["p99_ms"], 3),
        "serving_batch_occupancy": round(occupancy, 3),
        "requests": int(counters["requests"]),
        "batches": int(counters["batches"]),
        "clients": n_clients,
        "stages": 5,
    }


def _serving_scaleout_stage(n_replicas=8, n_clients=None, duration_s=3.0,
                            max_batch_rows=128, max_wait_ms=2.0,
                            n=50_000, d=32) -> dict:
    """Stage: serving scale-out — the ROADMAP item 3 / ISSUE 8 number.

    Three measurements against the 5-stage fused chain, same closed-loop
    offered load (``n_clients`` threads, 1-32 rows per request):

      1. ONE ServingEngine (continuous batching) — the PR 3 shape;
      2. an ``n_replicas`` ReplicaPool with FIFO whole-request packing;
      3. the same pool with continuous batching (the product default).

    Emits ``serving_scaleout_rows_per_sec`` plus
    ``serving_rows_per_sec_per_replica`` (so the per-chip number
    survives the dead device tunnel), pool-level p50/p99 (client-side,
    enqueue→complete), the pool-vs-single speedup (acceptance: >= 4x on
    the 8-CPU-device mesh — requires >= 8 host cores backing the 8
    virtual devices; ``host_cpu_count`` is recorded so a 2-core CI box's
    number is never mistaken for the acceptance measurement), and the
    FIFO-vs-continuous p50 delta at the same offered load (acceptance:
    continuous measurably lower)."""
    import threading

    from flinkml_tpu.serving import ReplicaPool, ServingConfig, ServingEngine
    from flinkml_tpu.table import Table

    if n_clients is None:
        n_clients = 2 * n_replicas
    model, x = _five_stage_model(n, d)
    example = Table({"features": x[:4]})

    def cfg(**kw):
        return ServingConfig(max_batch_rows=max_batch_rows,
                             max_wait_ms=max_wait_ms, **kw)

    def run_load(predict, label):
        stop = threading.Event()
        rows_served = [0] * n_clients
        lat_ms = [[] for _ in range(n_clients)]
        errors = []

        def client(tid):
            rng = np.random.default_rng(tid)
            try:
                while not stop.is_set():
                    rows = int(rng.integers(1, 33))
                    lo = int(rng.integers(0, n - rows))
                    t0 = time.perf_counter()
                    predict({"features": x[lo:lo + rows]})
                    lat_ms[tid].append((time.perf_counter() - t0) * 1e3)
                    rows_served[tid] += rows
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_clients)
        ]
        _log(f"serving_scaleout[{label}]: {n_clients} closed-loop clients "
             f"for {duration_s}s ...")
        start = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        elapsed = time.perf_counter() - start
        if errors:
            raise errors[0]
        lats = np.concatenate([np.asarray(l) for l in lat_ms if l])
        p50, p99 = np.percentile(lats, [50, 99])
        return {
            "rows_per_sec": round(sum(rows_served) / elapsed, 1),
            "p50_ms": round(float(p50), 3),
            "p99_ms": round(float(p99), 3),
            "requests": int(lats.size),
        }, elapsed

    # 1. Single engine (continuous): the PR 3 baseline shape.
    engine = ServingEngine(
        model, example, cfg(), output_cols=("prediction",),
        name="scaleout_single",
    ).start()
    single, _ = run_load(engine.predict, "single")
    engine.stop()

    # 2. Pool, FIFO packing: isolates the continuous-batching delta.
    pool = ReplicaPool(
        model, example, config=cfg(batching="fifo"),
        n_replicas=n_replicas, output_cols=("prediction",),
        name="scaleout_fifo",
    ).start()
    fifo, _ = run_load(pool.predict, "pool_fifo")
    pool.stop()

    # 3. Pool, continuous batching: the product configuration.
    pool = ReplicaPool(
        model, example, config=cfg(),
        n_replicas=n_replicas, output_cols=("prediction",),
        name="scaleout",
    ).start()
    cont, elapsed = run_load(pool.predict, "pool_continuous")
    stats = pool.stats()
    per_replica = {
        rname: round(rec["counters"].get("rows", 0.0) / elapsed, 1)
        for rname, rec in stats["per_replica"].items()
    }
    pool.stop()

    import jax

    return {
        "serving_scaleout_rows_per_sec": cont["rows_per_sec"],
        "serving_rows_per_sec_per_replica": per_replica,
        "pool_p50_ms": cont["p50_ms"],
        "pool_p99_ms": cont["p99_ms"],
        "pool_speedup_vs_single_engine": round(
            cont["rows_per_sec"] / single["rows_per_sec"], 2
        ),
        "single_engine_rows_per_sec": single["rows_per_sec"],
        "fifo_pool_rows_per_sec": fifo["rows_per_sec"],
        "fifo_p50_ms": fifo["p50_ms"],
        "continuous_p50_ms": cont["p50_ms"],
        "continuous_vs_fifo_p50": round(
            cont["p50_ms"] / fifo["p50_ms"], 3
        ) if fifo["p50_ms"] else None,
        "batching_window_ms": max_wait_ms,
        "replicas": n_replicas,
        "clients": n_clients,
        "devices": len(jax.devices()),
        "host_cpu_count": os.cpu_count(),
    }


def _inner_serving_scaleout() -> dict:
    _setup_jax_cache()
    return _serving_scaleout_stage()


def _inner_serving_scaleout_cpu() -> dict:
    """The scale-out measurement pinned to an 8-virtual-device host CPU
    mesh — tunnel-immune (CI's serving-scaleout stage parses it), so the
    rows/s-per-replica trajectory is always observable; the device
    variant runs the same programs when the tunnel returns.

    Replica count is capped at the HOST core count: each replica's
    device executor needs a core behind it, and running 8 executors on a
    2-core CI box measures the OS scheduler (observed: ~100 ms CFS
    timeslice stalls inside 2 ms programs), not the pool. On the
    acceptance host (>= 8 cores) this is exactly the 8-replica config."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    _setup_jax_cache()
    return _serving_scaleout_stage(
        n_replicas=max(2, min(8, os.cpu_count() or 2))
    )


def _multiproc_pool_stage(n_workers=2, duration_s=3.0, n=50_000, d=32,
                          max_batch_rows=128, max_wait_ms=2.0) -> dict:
    """Stage: multi-process worker pool vs the SAME-size in-process
    replica pool (ISSUE 20) — what "N replicas" buys when each replica
    is a real process with its own GIL and XLA executor pool instead of
    a thread behind the shared ones.

    Same closed-loop offered load against both shapes; emits total and
    per-worker rows/s, the worker-vs-thread speedup ratio (acceptance:
    >= 1.5x at 2 workers on a >= 8-core host — ``host_cpu_count`` is
    recorded so a starved box's ratio, where transport overhead buys no
    parallelism, is never mistaken for the acceptance measurement), and
    a bitwise parity check across the process boundary."""
    import threading

    from flinkml_tpu.cluster import ClusterPool
    from flinkml_tpu.serving import ReplicaPool, ServingConfig
    from flinkml_tpu.table import Table

    n_clients = 2 * n_workers
    model, x = _five_stage_model(n, d)
    example = Table({"features": x[:4]})
    cfg = ServingConfig(max_batch_rows=max_batch_rows,
                        max_wait_ms=max_wait_ms)

    def run_load(predict, label):
        stop = threading.Event()
        rows_served = [0] * n_clients
        lat_ms = [[] for _ in range(n_clients)]
        errors = []

        def client(tid):
            rng = np.random.default_rng(tid)
            try:
                while not stop.is_set():
                    rows = int(rng.integers(1, 33))
                    lo = int(rng.integers(0, n - rows))
                    t0 = time.perf_counter()
                    predict({"features": x[lo:lo + rows]})
                    lat_ms[tid].append((time.perf_counter() - t0) * 1e3)
                    rows_served[tid] += rows
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        _log(f"multiproc_pool[{label}]: {n_clients} closed-loop clients "
             f"for {duration_s}s ...")
        start = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        elapsed = time.perf_counter() - start
        if errors:
            raise errors[0]
        lats = np.concatenate([np.asarray(l) for l in lat_ms if l])
        p50, p99 = np.percentile(lats, [50, 99])
        return {
            "rows_per_sec": round(sum(rows_served) / elapsed, 1),
            "p50_ms": round(float(p50), 3),
            "p99_ms": round(float(p99), 3),
        }

    # 1. In-process replica pool: N engines behind ONE GIL.
    tpool = ReplicaPool(
        model, example, config=cfg, n_replicas=n_workers,
        output_cols=("prediction",), name="mp_threads",
    ).start()
    ref_out = np.asarray(
        tpool.predict({"features": x[:32]}).columns["prediction"]
    )
    threaded = run_load(tpool.predict, "threads")
    tpool.stop()

    # 2. Process pool: the same router over worker processes.
    cpool = ClusterPool(
        model, example, config=cfg, n_workers=n_workers,
        output_cols=("prediction",), name="mp_workers",
    ).start()
    pool_out = np.asarray(
        cpool.predict({"features": x[:32]}).columns["prediction"]
    )
    proc = run_load(cpool.predict, "workers")
    cpool.stop()

    import jax

    return {
        "multiproc_rows_per_sec": proc["rows_per_sec"],
        "multiproc_rows_per_sec_per_worker": round(
            proc["rows_per_sec"] / n_workers, 1
        ),
        "threaded_rows_per_sec": threaded["rows_per_sec"],
        "worker_vs_thread_speedup": round(
            proc["rows_per_sec"] / threaded["rows_per_sec"], 2
        ) if threaded["rows_per_sec"] else None,
        "multiproc_p50_ms": proc["p50_ms"],
        "multiproc_p99_ms": proc["p99_ms"],
        "threaded_p50_ms": threaded["p50_ms"],
        "parity_bitwise": bool(np.array_equal(ref_out, pool_out)),
        "workers": n_workers,
        "clients": n_clients,
        "devices": len(jax.devices()),
        "host_cpu_count": os.cpu_count(),
    }


def _inner_multiproc_pool() -> dict:
    _setup_jax_cache()
    return _multiproc_pool_stage()


def _inner_multiproc_pool_cpu() -> dict:
    """The worker-vs-thread measurement pinned to the host CPU backend —
    tunnel-immune (CI's cluster smoke stage parses it). The speedup
    ratio is only meaningful with >= 8 host cores (2 workers x their
    executor pools + clients); the record carries host_cpu_count so a
    1-core box's ratio is read as the transport-overhead floor it is."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    _setup_jax_cache()
    return _multiproc_pool_stage()


def _serving_autoscale_stage(duration_s=2.0, n=20_000, d=32,
                             max_replicas=None) -> dict:
    """Stage: autoscaling multi-tenant serving — the ROADMAP item 3 /
    ISSUE 15 numbers. Two measurements against the 5-stage fused chain:

      1. **Closed loop**: a 1-replica pool under light load; offered
         load TRIPLES; a PoolAutoscaler (thresholds from the committed
         tuning table) scales the pool with no operator in the loop.
         Emits the pre-scale spike p99, the post-scale recovered p99,
         scale-event counts, and rows/s per replica. On a host-platform
         CPU mesh the virtual devices share one executor pool, so
         recovered-vs-spike is a REGRESSION TRIPWIRE here (the
         unbounded pad-compile bug this PR fixed degraded it >10x); the
         true recovery ratio is the device variant's number (each
         replica owns a chip).
      2. **Precision tiers**: the same chain served single-engine under
         f32, bf16 ``mixed_inference``, and the int8 PTQ tier
         (``d`` >= the committed ``int8_min_const_elems`` threshold, so
         every model constant really quantizes). Emits rows/s per tier,
         ``int8_vs_bf16_rows_per_sec_ratio`` (the acceptance ratio: on
         CPU bf16 is emulated while the int8 tier's dequant-fused
         compute runs native f32 — int8 must WIN), and the
         int8-vs-f32 max |raw deviation| (the quality contract).
    """
    import threading

    from flinkml_tpu.serving import (
        AutoscaleConfig,
        PoolAutoscaler,
        ReplicaPool,
        ServingConfig,
        ServingEngine,
    )
    from flinkml_tpu.table import Table

    model, x = _five_stage_model(n, d)
    example = Table({"features": x[:4]})
    if max_replicas is None:
        max_replicas = max(2, min(4, (os.cpu_count() or 2) // 2))

    # -- 1. the closed loop ------------------------------------------------
    pool = ReplicaPool(
        model, example,
        config=ServingConfig(max_batch_rows=128, max_queue_rows=256,
                             max_wait_ms=1.0),
        n_replicas=1, output_cols=("prediction",), name="autoscale_bench",
    ).start()
    scaler = PoolAutoscaler(pool, AutoscaleConfig(
        min_replicas=1, max_replicas=max_replicas,
        up_consecutive=10, down_consecutive=10_000,
        cooldown_s=0.3, interval_s=0.1,
    )).start()
    lat: list = []
    lat_lock = threading.Lock()
    stop = threading.Event()
    rows_served = [0]

    def client(tid):
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            rows = int(rng.integers(16, 49))
            lo = int(rng.integers(0, n - rows))
            t0 = time.perf_counter()
            try:
                pool.predict({"features": x[lo:lo + rows]})
            except Exception:  # noqa: BLE001 — overload during the spike
                continue
            with lat_lock:
                lat.append((time.perf_counter(),
                            (time.perf_counter() - t0) * 1e3))
                rows_served[0] += rows

    def p99_window(t0, t1=None):
        with lat_lock:
            vals = [ms for (tc, ms) in lat
                    if tc >= t0 and (t1 is None or tc < t1)]
        return round(float(np.percentile(vals, 99)), 3) if vals else None

    light = [threading.Thread(target=client, args=(i,)) for i in range(2)]
    for t in light:
        t.start()
    time.sleep(duration_s / 2)
    spike_t0 = time.perf_counter()
    heavy = [threading.Thread(target=client, args=(10 + i,))
             for i in range(4)]
    for t in heavy:
        t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and len(pool.replicas) < 2:
        time.sleep(0.05)
    first_scale_t = time.perf_counter()
    spike_p99 = p99_window(spike_t0, first_scale_t)
    # Let scaling settle, then measure the recovered steady state.
    stable_since, last_count = time.monotonic(), len(pool.replicas)
    while time.monotonic() < deadline:
        if len(pool.replicas) != last_count:
            last_count = len(pool.replicas)
            stable_since = time.monotonic()
        if time.monotonic() - stable_since >= 1.0:
            break
        time.sleep(0.05)
    settle_t0 = time.perf_counter()
    time.sleep(duration_s)
    recovered_p99 = p99_window(settle_t0)
    measure_end = time.perf_counter()
    stop.set()
    for t in light + heavy:
        t.join(timeout=60)
    st = scaler.stats()
    pool_stats = pool.stats()
    per_replica = {
        rname: round(
            rec["counters"].get("rows", 0.0)
            / (measure_end - spike_t0), 1
        )
        for rname, rec in pool_stats["per_replica"].items()
    }
    scaler.stop()
    pool.stop()

    # -- 2. precision tiers ------------------------------------------------
    # Transform throughput (the PR 10 `precision` stage's measurement
    # shape): device work dominates, so the tier ratios measure the
    # tiers, not per-dispatch overhead. Serving inherits them through
    # ServingConfig.precision — same programs, same cache keys.
    from flinkml_tpu import pipeline_fusion
    from flinkml_tpu.table import Table as _T

    apply_table = _T({"features": x})
    reps = 3

    def tier_rate(policy):
        with pipeline_fusion.precision_scope(policy):
            np.asarray(  # warmup: compile this tier's program
                model.transform(apply_table)[0].column("prediction")
            )
            t0 = time.perf_counter()
            for _ in range(reps):
                out = model.transform(apply_table)[0]
                np.asarray(out.column("prediction"))
            return n * reps / (time.perf_counter() - t0)

    _log("serving_autoscale: precision tier A/B (f32 / bf16 / int8) ...")
    f32_rate = tier_rate(None)
    bf16_rate = tier_rate("mixed_inference")
    # The canonical d=32 chain's constants sit under the committed
    # cpu/cpu/8 int8_min_const_elems threshold (256 — quantizing tiny
    # vectors measured pure overhead on a CPU mesh), so the A/B pins the
    # threshold via the sanctioned env gate: this measurement IS the
    # quantizing path, or the ratio would be f32-vs-bf16 in disguise.
    _prev_thr = os.environ.get("FLINKML_TPU_INT8_MIN_CONST")
    os.environ["FLINKML_TPU_INT8_MIN_CONST"] = "16"
    try:
        int8_rate = tier_rate("int8_inference")

        # Quality: int8 vs f32 deviation, probed on the 4th scaler
        # output (the LR sigmoid saturates, so rawPrediction would
        # understate the tier's true error).
        probe = _T({"features": x[:512]})
        (o32,) = model.transform(probe)
        r32 = np.asarray(o32.column("s4")).astype(np.float64)
        with pipeline_fusion.precision_scope("int8_inference"):
            (oq,) = model.transform(probe)
            rq = np.asarray(oq.column("s4")).astype(np.float64)
        int8_dev = float(np.max(np.abs(rq - r32)))
    finally:
        if _prev_thr is None:
            os.environ.pop("FLINKML_TPU_INT8_MIN_CONST", None)
        else:
            os.environ["FLINKML_TPU_INT8_MIN_CONST"] = _prev_thr

    import jax

    return {
        "serving_autoscale_rows_per_sec": round(
            sum(per_replica.values()), 1
        ),
        "serving_rows_per_sec_per_replica": per_replica,
        "autoscale_spike_p99_ms": spike_p99,
        "autoscale_recovered_p99_ms": recovered_p99,
        "autoscale_recovery_ratio": (
            round(recovered_p99 / spike_p99, 3)
            if spike_p99 and recovered_p99 else None
        ),
        "scale_events_total": int(
            st["counters"].get("scale_events_total", 0)
        ),
        "replicas_final": len(pool_stats["per_replica"]),
        "backlog_ewma_final": round(st["backlog_ewma"] or 0.0, 4),
        "f32_rows_per_sec": round(f32_rate, 1),
        "bf16_rows_per_sec": round(bf16_rate, 1),
        "int8_rows_per_sec": round(int8_rate, 1),
        "int8_vs_bf16_rows_per_sec_ratio": round(
            int8_rate / bf16_rate, 3
        ) if bf16_rate else None,
        "int8_vs_f32_rows_per_sec_ratio": round(
            int8_rate / f32_rate, 3
        ) if f32_rate else None,
        "int8_vs_f32_max_raw_dev": int8_dev,
        "dim": d,
        "devices": len(jax.devices()),
        "host_cpu_count": os.cpu_count(),
    }


def _serving_grayfail_stage(duration_s=1.5, n=20_000, d=32) -> dict:
    """Stage: gray-failure defense — the ISSUE 19 numbers. A 4-replica
    pool serves the 5-stage fused chain under closed-loop load with the
    GrayFailGuard running; one replica is stalled ~100x (a 0.2 s
    ``StallDispatch`` on every batch — alive, passing dispatches,
    dragging tail latency). Measures the defense end to end:

    - ``p99_during_stall_ms`` — client-observed p99 from the moment the
      stall arms until it clears. Abandonment + hedging bound this to
      roughly the attempt deadline, NOT the 200 ms stall.
    - ``time_to_quarantine_s`` — stall armed -> the guard's MAD outlier
      test trips and the replica goes SLOW (out of routing, not killed).
    - ``hedge_win_fraction`` — hedges_won / hedges_dispatched: how often
      the second dispatch beat a straggling first attempt.
    - ``recovered_p99_ms`` — p99 after the stall clears and the replica
      rejoins via canary probes; the acceptance tripwire is
      recovered <= max(2x baseline, baseline + 50 ms).
    """
    import threading

    from flinkml_tpu import faults
    from flinkml_tpu.recovery.fuzz import serving_grayfail_policy
    from flinkml_tpu.serving import ReplicaPool, ServingConfig
    from flinkml_tpu.table import Table

    model, x = _five_stage_model(n, d)
    example = Table({"features": x[:4]})
    pool = ReplicaPool(
        model, example,
        config=ServingConfig(max_batch_rows=128, max_queue_rows=512,
                             max_wait_ms=1.0, default_timeout_ms=15_000.0),
        n_replicas=4, output_cols=("prediction",), name="grayfail_bench",
        grayfail=serving_grayfail_policy(),
    ).start()
    guard = pool.grayfail_guard(interval_s=0.05).start()
    lat: list = []
    lat_lock = threading.Lock()
    stop = threading.Event()
    rows_served = [0]

    def client(tid):
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            rows = int(rng.integers(16, 49))
            lo = int(rng.integers(0, n - rows))
            t0 = time.perf_counter()
            try:
                pool.predict({"features": x[lo:lo + rows]})
            except Exception:  # noqa: BLE001 — shed/timeout under stall
                continue
            with lat_lock:
                lat.append((time.perf_counter(),
                            (time.perf_counter() - t0) * 1e3))
                rows_served[0] += rows

    def p99_window(t0, t1=None):
        with lat_lock:
            vals = [ms for (tc, ms) in lat
                    if tc >= t0 and (t1 is None or tc < t1)]
        return round(float(np.percentile(vals, 99)), 3) if vals else None

    from flinkml_tpu.serving.health import ReplicaState

    clients = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in clients:
        t.start()
    base_t0 = time.perf_counter()
    time.sleep(duration_s)  # healthy baseline (also seeds attempt rings)
    baseline_p99 = p99_window(base_t0)

    _log("serving_grayfail: stalling r1 (0.2 s per batch) ...")
    stall_t0 = time.perf_counter()
    quarantine_t = None
    with faults.armed(faults.FaultPlan(
        faults.StallDispatch("r1", delay_s=0.2)
    )):
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if pool.replicas[1].health.state is ReplicaState.SLOW:
                quarantine_t = time.perf_counter()
                break
            time.sleep(0.02)
        # Keep the stall up briefly post-quarantine so the stall window
        # has post-detection traffic too (the steady state the defense
        # actually buys), then clear it.
        time.sleep(duration_s / 2)
    stall_t1 = time.perf_counter()
    stall_p99 = p99_window(stall_t0, stall_t1)

    rejoin_t = None
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if pool.replicas[1].health.state is ReplicaState.HEALTHY:
            rejoin_t = time.perf_counter()
            break
        time.sleep(0.02)
    time.sleep(duration_s / 2)
    recovered_p99 = p99_window(rejoin_t if rejoin_t else stall_t1)
    measure_end = time.perf_counter()
    stop.set()
    for t in clients:
        t.join(timeout=60)
    router = pool.stats()["router"]
    gcount = guard._metrics.snapshot()["counters"]
    guard.stop()
    pool.stop(drain=False, timeout=30.0)

    hedged = router.get("hedges_dispatched", 0.0)
    import jax

    return {
        "serving_grayfail_rows_per_sec": round(
            rows_served[0] / (measure_end - base_t0), 1
        ),
        "baseline_p99_ms": baseline_p99,
        "p99_during_stall_ms": stall_p99,
        "recovered_p99_ms": recovered_p99,
        "time_to_quarantine_s": (
            round(quarantine_t - stall_t0, 3) if quarantine_t else None
        ),
        "time_to_rejoin_s": (
            round(rejoin_t - stall_t1, 3) if rejoin_t else None
        ),
        "hedge_win_fraction": (
            round(router.get("hedges_won", 0.0) / hedged, 3)
            if hedged else 0.0
        ),
        "hedges_dispatched": int(hedged),
        "abandoned_attempts": int(router.get("abandoned_attempts", 0.0)),
        "quarantines_total": int(gcount.get("quarantines_total", 0)),
        "rejoins_total": int(gcount.get("rejoins_total", 0)),
        "dim": d,
        "devices": len(jax.devices()),
        "host_cpu_count": os.cpu_count(),
    }


def _inner_serving_grayfail() -> dict:
    _setup_jax_cache()
    return _serving_grayfail_stage()


def _inner_serving_grayfail_cpu() -> dict:
    """Tunnel-immune CPU-mesh variant (CI's ``gray-failure smoke`` stage
    parses it): quarantine timing, hedge accounting, and the
    recovered-vs-baseline p99 tripwire are all observable without the
    device — the 0.2 s stall dwarfs any CPU-mesh noise."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _serving_grayfail_stage()


def _inner_serving_autoscale() -> dict:
    _setup_jax_cache()
    return _serving_autoscale_stage()


def _inner_serving_autoscale_cpu() -> dict:
    """Tunnel-immune CPU-mesh variant (CI's ``autoscale smoke`` stage
    parses it): the control loop, the scale-event counts, and the
    int8-vs-bf16 ratio are all observable without the device; the
    recovery RATIO is a tripwire here (shared-executor CPU mesh — see
    the stage docstring) and a real recovery number on the device."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _serving_autoscale_stage()


def _inner_serving() -> dict:
    _setup_jax_cache()
    return _serving_stage()


def _inner_serving_cpu() -> dict:
    """The serving measurement pinned to the host CPU backend —
    tunnel-immune (runs under JAX_PLATFORMS=cpu / CI), so the serving
    trajectory is always observable; device numbers ride the device
    phase when healthy."""
    _force_cpu()
    return _serving_stage()


def _inner_feed_overlap(n_batches=32, bs=8_192, dim=128, k=512,
                        inner_iters=256) -> dict:
    """Stage: feed-overlap efficiency — the architecture-meaningful
    replacement for the retired ``kmeans_stream`` device stage (which
    measured 160 synchronous per-batch round trips over the tunnel,
    i.e. WAN latency, not the framework — VERDICT r4 "weak" #4).

    Measures ``fed_s / resident_s``: wall clock to push N large batches
    through a compute-heavy jitted step when batches arrive via the
    PrefetchingDeviceFeed (host -> device copy on a worker thread,
    overlapped with compute) vs. when they are pre-resident in HBM.
    Both modes dispatch per batch WITHOUT intermediate synchronization
    (one materialization at the end), so link latency appears once, not
    per batch; the step is sized so compute per batch dominates transfer
    at any plausible link bandwidth. A ratio near 1.0 means the feed
    pipeline fully hides the copy; the gap above 1.0 is the framework's
    streaming overhead (queue handoff + unhidden copy tail)."""
    _setup_jax_cache()
    import jax
    import jax.numpy as jnp
    from flinkml_tpu.iteration.datacache import PrefetchingDeviceFeed

    rng = np.random.default_rng(0)
    host_batches = [
        rng.normal(size=(bs, dim)).astype(np.float32)
        for _ in range(n_batches)
    ]
    cent0 = jnp.asarray(rng.normal(size=(k, dim)).astype(np.float32))

    @jax.jit
    def step(x, c):
        xsq = (x * x).sum(1, keepdims=True)

        def one(c, _):
            d = xsq - 2.0 * (x @ c.T) + (c * c).sum(1)[None, :]
            oh = jax.nn.one_hot(jnp.argmin(d, axis=1), c.shape[0],
                                dtype=x.dtype)
            counts = oh.sum(0)[:, None]
            newc = (oh.T @ x) / jnp.maximum(counts, 1.0)
            return jnp.where(counts > 0, newc, c), None

        c, _ = jax.lax.scan(one, c, None, length=inner_iters)
        return c

    _log("feed_overlap: compiling + warm-up dispatch ...")
    np.asarray(step(jnp.asarray(host_batches[0]), cent0))

    def run(batch_iter):
        start = time.perf_counter()
        c = cent0
        for b in batch_iter:
            c = step(b, c)
        np.asarray(c)  # single synchronization: latency appears once
        return time.perf_counter() - start

    _log("feed_overlap: resident pass ...")
    dev_batches = [jax.device_put(b) for b in host_batches]
    jax.block_until_ready(dev_batches)
    resident_s = run(dev_batches)
    del dev_batches
    _log("feed_overlap: fed pass ...")
    feed = PrefetchingDeviceFeed(iter(host_batches), depth=2)
    try:
        fed_s = run(feed)
    finally:
        feed.close()
    return {
        "ratio": round(fed_s / resident_s, 3),
        "resident_s": round(resident_s, 3),
        "fed_s": round(fed_s, 3),
    }


def _input_pipeline_stage(n=262_144, d=64, bs=4_096,
                          inner_iters=48) -> dict:
    """Stage: input-pipeline throughput — a shuffled
    ``flinkml_tpu.data.Dataset`` (array source → seeded shuffle buffer →
    bucketed async device prefetch) feeding a compute-heavy jitted step,
    the subsystem's production shape (ISSUE 5). All batches share one
    power-of-two row bucket, so the steady state is zero-retrace; the
    prefetcher's double buffering is what keeps the step from ever
    waiting on ingest. Metrics: ``input_rows_per_sec`` (consumer-side,
    first batch → final sync) and ``prefetch_stall_fraction`` (fraction
    of consumer wall spent blocked on the queue — the 'is the producer
    keeping up' number)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu.data import Dataset
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    ds = (
        Dataset.from_arrays(Table({"features": x}), bs)
        .shuffle(8, seed=0)
        .prefetch(depth=2, metrics_group="data.prefetch.bench")
    )

    @jax.jit
    def step(xb, acc):
        def one(a, _):
            return a + 1e-3 * jnp.tanh(xb.T @ (xb @ a)), None

        a, _ = jax.lax.scan(one, acc, None, length=inner_iters)
        return a

    acc0 = jnp.zeros(d, jnp.float32)
    warm = next(iter(ds.iterate()))
    np.asarray(step(warm.device_column_padded("features", bs), acc0))

    it = ds.iterate()
    acc = acc0
    rows = 0
    start = time.perf_counter()
    for t in it:
        # The prefetcher's buffers are exactly bucket-height, so this is
        # a zero-copy handoff into the compiled step (no per-batch
        # slicing, no retrace).
        acc = step(t.device_column_padded("features", bs), acc)
        rows += t.num_rows
    np.asarray(acc)  # single end-of-run synchronization
    elapsed = time.perf_counter() - start
    stall = it._prefetcher.stall_fraction if it._prefetcher else 0.0
    return {
        "input_rows_per_sec": round(rows / elapsed, 1),
        "prefetch_stall_fraction": round(stall, 4),
        "rows": rows,
        "batch_size": bs,
        "shuffle_buffer": 8,
    }


def _inner_input_pipeline() -> dict:
    _setup_jax_cache()
    return _input_pipeline_stage()


def _inner_input_pipeline_cpu() -> dict:
    """The input-pipeline measurement pinned to the host CPU backend —
    tunnel-immune (CI's smoke stage parses it), so the ingest
    trajectory is always observable."""
    _force_cpu()
    return _input_pipeline_stage()


def _sharded_train_stage(n=16_384, dim=512, iters=24) -> dict:
    """Stage: plan-sharded training throughput — full-batch momentum-SGD
    logreg through ``sharding.apply.train_linear_plan`` under each plan
    preset (dp / FSDP / FSDP×TP), one number per preset
    (``sharded_samples_per_sec``). The ISSUE-7 trajectory: the same
    jitted plan-sharded step the product trains with, batch sharded
    along the plan's batch axes, parameters + momentum sharded per the
    plan, GSPMD collectives included in the wall. The replicated preset
    is measured too so the sharding overhead/benefit is one division
    away."""
    import jax

    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding import PRESETS
    from flinkml_tpu.sharding.apply import train_linear_plan

    x, y, w = make_data(n, dim)
    rates = {}
    for name in ("replicated", "batch_parallel", "fsdp", "fsdp_tp"):
        plan = PRESETS[name]
        mesh = DeviceMesh.for_plan(plan)

        def run(max_iter):
            return train_linear_plan(
                x, y, w, plan, mesh, loss="logistic", optimizer="sgd",
                max_iter=max_iter, learning_rate=0.1,
            )

        run(2)  # compile + warm the window upload path
        start = time.perf_counter()
        coef = run(iters)
        elapsed = time.perf_counter() - start
        assert np.isfinite(coef).all()
        rates[name] = round(n * iters / elapsed, 1)
        _log(f"sharded_train[{name}]: {rates[name]} samples/s "
             f"({len(jax.devices())} devices)")
    return {
        "sharded_samples_per_sec": rates,
        "rows": n,
        "dim": dim,
        "devices": len(jax.devices()),
    }


def _inner_sharded_train() -> dict:
    _setup_jax_cache()
    return _sharded_train_stage()


def _inner_sharded_train_cpu() -> dict:
    """The plan-preset measurement pinned to an 8-virtual-device host
    CPU mesh — tunnel-immune (CI's sharding stage parses it), so every
    preset's trajectory is always observable; the device variant above
    runs the same programs when the tunnel returns."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _sharded_train_stage()


def _sharded_embedding_stage(vocab=1 << 20, dim=16, batch=1 << 13,
                             reps=8, budget=24 << 20) -> dict:
    """Stage: sharded-embedding lookup+update rows/s (ISSUE 14).

    The vocab is chosen to PROVABLY exceed the per-device budget
    replicated (table + one optimizer slot = 2 x vocab x dim x 4 B >
    ``budget``) AND under fsdp-only row sharding (/4 on the 8-device
    mesh still exceeds it), so the stage first proves the contract:
    FML503 refuses the replicated placement, ``infer_plan`` routes past
    fsdp to the embedding plan (the full fsdp x tp product), and the
    per-shard slice fits. Then each exchange strategy's
    lookup and update rates are measured through the real
    ``EmbeddingTable`` programs, with the analytic per-step exchange
    traffic emitted next to them — linear in ``batch``, independent of
    vocab (the number that makes "never a vocab-sized psum" auditable;
    the dense placement's psum bytes are emitted for contrast)."""
    import jax

    from flinkml_tpu.analysis.sharding_check import check_plan
    from flinkml_tpu.embeddings import EmbeddingTable
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding import EMBEDDING, REPLICATED, infer_plan

    rng = np.random.default_rng(0)
    mesh = DeviceMesh.for_plan(EMBEDDING)
    param = {"bench/embedding": (vocab, dim)}
    replicated_bytes = vocab * dim * 4 * 2
    assert replicated_bytes > budget, "vocab does not exceed the budget"
    refusal = check_plan(REPLICATED, mesh, param_shapes=param,
                         hbm_budget_bytes=budget, optimizer_slots=1)
    assert any(f.rule == "FML503" for f in refusal), \
        "FML503 must refuse the replicated placement"
    plan = infer_plan(mesh, param, budget, optimizer_slots=1)
    assert plan.name == "embedding", plan.name

    ids = rng.integers(0, vocab, batch).astype(np.int32)
    delta = (rng.normal(size=(batch, dim)) * 1e-3).astype(np.float32)
    lookup_rates, update_rates, traffic = {}, {}, {}
    table = None
    for strategy in ("ring", "all_to_all"):
        table = EmbeddingTable(
            "bench", vocab, dim, mesh=mesh, plan=plan,
            hbm_budget_bytes=budget, optimizer_slots=1, scale=0.01,
        )
        np.asarray(table.lookup(ids))                     # compile
        table.scatter_add(ids, delta, strategy=strategy)  # compile
        start = time.perf_counter()
        for _ in range(reps):
            np.asarray(table.lookup(ids))
        lookup_rates[strategy] = round(
            batch * reps / (time.perf_counter() - start), 1)
        start = time.perf_counter()
        for _ in range(reps):
            table.scatter_add(ids, delta, strategy=strategy)
        np.asarray(table.lookup(ids[:1]))                 # sync
        update_rates[strategy] = round(
            batch * reps / (time.perf_counter() - start), 1)
        traffic[strategy] = table.exchange_bytes_per_step(batch, strategy)
        _log(f"sharded_embedding[{strategy}]: lookup "
             f"{lookup_rates[strategy]} rows/s, update "
             f"{update_rates[strategy]} rows/s "
             f"({len(jax.devices())} devices)")
    assert np.isfinite(table.to_host()).all()
    return {
        "embedding_lookup_rows_per_sec": lookup_rates,
        "embedding_update_rows_per_sec": update_rates,
        "exchange_bytes_per_step": traffic,
        "exchange_bytes_per_row": {
            s: round(b / batch, 1) for s, b in traffic.items()
        },
        "dense_psum_bytes_per_step": 2 * vocab * dim * 4,
        "vocab": vocab,
        "dim": dim,
        "batch": batch,
        "per_device_budget_bytes": budget,
        "replicated_bytes": replicated_bytes,
        "per_shard_bytes": table.per_device_bytes(),
        "plan": plan.name,
        "n_shards": table.n_shards,
        "devices": len(jax.devices()),
    }


def _inner_sharded_embedding() -> dict:
    """The DEVICE sharded-embedding measurement (queued in stage_order
    for the tunnel's return — real ICI is what decides ring vs
    all_to_all; the CPU mesh number stands alone until then)."""
    _setup_jax_cache()
    return _sharded_embedding_stage()


def _inner_sharded_embedding_cpu() -> dict:
    """Tunnel-immune 8-virtual-device CPU-mesh variant — what CI's
    ``embedding smoke`` stage parses."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _sharded_embedding_stage()


def _recovery_stage(n_batches=24, rows=16_384, dim=256, reps=5) -> dict:
    """Stage: numerics-sentinel overhead + time-to-recover (ISSUE 9).

    The sentinel's armed cost is one fused verdict reduction + one
    scalar transfer per epoch boundary, on a loop that already syncs a
    host loss every epoch — the acceptance number is <2% throughput
    overhead on a realistic online-batch shape (measured check cost:
    ~0.2 ms vs a ~30 ms step). Measures the SAME
    OnlineLogisticRegression.fit_stream with the sentinel off vs on,
    INTERLEAVED (off/on alternating per round, best-of-``reps`` each) —
    two sequential blocks would fold host-load drift between them into
    the ratio, which is exactly the 20%-either-direction noise the
    interleaving cancels. Then demos a full heal — a NaN batch
    mid-stream under the recovery policy — and reports the
    rollback-to-retrained time-to-recover.
    """
    from flinkml_tpu.models import OnlineLogisticRegression
    from flinkml_tpu.recovery import NumericsSentinel, RecoveryPolicy
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(0)
    true = rng.normal(size=dim)
    batches = []
    for _ in range(n_batches):
        x = rng.normal(size=(rows, dim))
        batches.append(Table({
            "features": x, "label": (x @ true > 0).astype(np.float64),
        }))

    def fit(sentinel=None):
        return OnlineLogisticRegression().set_alpha(0.5).fit_stream(
            batches, sentinel=sentinel,
        )

    fit()                              # compile the FTRL step
    fit(sentinel=NumericsSentinel())   # compile the verdict program

    def timed(mk_sentinel):
        start = time.perf_counter()
        model = fit(sentinel=mk_sentinel())
        wall = time.perf_counter() - start
        assert np.isfinite(model.coefficient).all()
        return wall

    walls_off, walls_on = [], []
    for _ in range(reps):
        walls_off.append(timed(lambda: None))
        walls_on.append(timed(NumericsSentinel))
    wall_off, wall_on = min(walls_off), min(walls_on)
    # Per-round PAIRED ratios (adjacent off/on fits see the same host
    # conditions), best round taken: a ~1s fit on a time-shared CI box
    # sees 10-20% multiplicative scheduler noise, so the mean/median of
    # the paired ratios still jitters past any honest bound — the least
    # contended round is the measurement (the same reasoning that made
    # the serving stage's continuous-vs-FIFO assert a slack tripwire,
    # CHANGES PR 8). The direct per-check cost below is the noise-free
    # ground truth the ratio must agree with.
    overhead = max(0.0, min(on / off for off, on
                            in zip(walls_off, walls_on)) - 1.0)
    total_rows = n_batches * rows
    off_rps = total_rows / wall_off
    on_rps = total_rows / wall_on

    # Ground truth for the acceptance bound: the sentinel's per-check
    # cost measured directly (one fused verdict + one scalar sync;
    # median-of-calls — a scheduler stall inflates a mean) against the
    # per-batch step wall.
    import jax.numpy as jnp

    from flinkml_tpu.recovery.sentinel import NumericsSentinel as _S

    probe = _S()
    carry = {"z": jnp.zeros(dim), "n": jnp.zeros(dim),
             "coef": jnp.zeros(dim), "version": 0}
    probe.check(carry, 0.5, epoch=0, source_index=0)  # compile
    n_checks = 200
    calls = []
    for i in range(n_checks):
        start = time.perf_counter()
        probe.check(carry, 0.5, epoch=i, source_index=i)
        calls.append(time.perf_counter() - start)
    check_ms = sorted(calls)[n_checks // 2] * 1000.0
    step_ms = wall_off / n_batches * 1000.0
    check_frac = check_ms / step_ms
    _log(f"recovery: sentinel off {off_rps:,.0f} rows/s, on "
         f"{on_rps:,.0f} rows/s, best-paired overhead "
         f"{overhead * 100:.2f}% (direct check cost {check_ms:.3f} ms "
         f"vs {step_ms:.1f} ms/step = {check_frac * 100:.2f}%)")

    # Heal demo: poison one mid-stream batch, measure the healed fit and
    # the engine's recorded time-to-recover.
    import tempfile

    from flinkml_tpu.iteration import CheckpointManager

    poisoned = list(batches)
    p = n_batches // 2
    poisoned[p] = Table({
        "features": np.full((rows, dim), np.nan),
        "label": np.zeros(rows),
    })
    with tempfile.TemporaryDirectory(prefix="bench-recovery-") as td:
        mgr = CheckpointManager(td, max_to_keep=4)
        start = time.perf_counter()
        healed = OnlineLogisticRegression().set_alpha(0.5).fit_stream(
            poisoned, checkpoint_manager=mgr, checkpoint_interval=4,
            recovery=RecoveryPolicy(backoff_s=0.0),
        )
        heal_wall = time.perf_counter() - start
    assert np.isfinite(healed.coefficient).all()
    assert healed.recovery_summary["quarantined"] == [p]
    from flinkml_tpu.utils.metrics import metrics

    ttr = metrics.group("recovery").snapshot()["gauges"].get(
        "time_to_recover_p50_ms"
    )
    return {
        "recovery_rows_per_sec_sentinel_off": round(off_rps, 1),
        "recovery_rows_per_sec_sentinel_on": round(on_rps, 1),
        "sentinel_overhead_frac": round(overhead, 5),
        "sentinel_check_ms": round(check_ms, 4),
        "sentinel_check_frac_of_step": round(check_frac, 5),
        "healed_fit_wall_s": round(heal_wall, 3),
        "time_to_recover_p50_ms": (None if ttr is None
                                   else round(float(ttr), 2)),
        "rows": rows,
        "dim": dim,
        "batches": n_batches,
    }


def _inner_recovery() -> dict:
    _setup_jax_cache()
    return _recovery_stage()


def _inner_recovery_cpu() -> dict:
    """The sentinel-overhead measurement pinned to the host CPU backend
    — tunnel-immune (CI's chaos-soak stage parses it and asserts the
    <2% acceptance bound); the device variant runs the same programs
    when the tunnel returns."""
    _force_cpu()
    return _recovery_stage()


# Epoch-mean logistic-loss target for the convergence stage. Calibrated on
# the seeded a9a-shaped config (CPU, f32): loss 0.599 after 1 epoch, 0.219
# after 25, 0.169 after 50 — tol 0.20 lands at ~30 epochs: long enough to
# be a convergence measurement, short enough to fit any stage cap.
_CONVERGE_TOL = 0.20


def _converge_stage() -> dict:
    """Stage: dense LR epochs/wall-to-converge on the a9a-shaped config
    (n=65_536, d=123, global batch 8_192), seeded, to fixed tol. Steps
    and epochs are hardware-independent (same seeded program); wall_s is
    the device's half of the metric."""
    _setup_jax_cache()
    n, dim, gbs = 65_536, 123, 8_192
    x, y, w = make_data(n, dim)
    steps, wall = bench_convergence(
        x, y, w, gbs, tol=_CONVERGE_TOL, max_steps=4_000
    )
    return {
        "epochs_to_tol": round(steps * gbs / n, 2),
        "wall_s_to_tol": round(wall, 3),
        "tol": _CONVERGE_TOL,
        "steps": steps,
    }


def _inner_converge() -> dict:
    return _converge_stage()


def _inner_converge_sparse() -> dict:
    """Stage: sparse (Criteo-profile) LR epochs/wall-to-converge — dim =
    1e6, 39 nnz/row, n=65_536, global batch 16_384, lr=20, seeded. Tol
    calibrated on the seeded config (CPU, f32): loss 0.693 at start,
    0.265 after 80 epochs, 0.153 after 160 — tol 0.25 lands at ~85
    epochs. Uses the product sparse trainer at the product layout gate,
    so the number tracks the active layout."""
    _setup_jax_cache()
    import jax.numpy as jnp
    from flinkml_tpu.models import _linear_sgd
    from flinkml_tpu.parallel import DeviceMesh

    n, dim, gbs, tol, max_steps = 65_536, 1_000_000, 16_384, 0.25, 2_000
    indptr, indices, values, y, w = make_criteo_csr(n, dim)
    mesh = DeviceMesh()
    layout = _linear_sgd._sparse_layout()
    data_args, local_bss = _linear_sgd.prepare_sparse_buckets(
        indptr, indices, values, dim, y, w, mesh, gbs, seed=0,
        layout=layout,
    )
    trainer = _linear_sgd._sparse_trainer_bucketed(
        mesh.mesh, "logistic", local_bss, DeviceMesh.DATA_AXIS, dim, layout,
    )
    f32 = lambda v: jnp.asarray(v, jnp.float32)
    carry0 = (
        jnp.zeros(dim, jnp.float32),
        jnp.asarray(0, jnp.int32),
        jnp.asarray(jnp.inf, jnp.float32),
    )
    hy = (f32(20.0), f32(0.0), f32(0.0), f32(tol))
    _log("converge_sparse: compiling + warm-up dispatch ...")
    np.asarray(trainer(*carry0, *data_args, *hy,
                       jnp.asarray(2, jnp.int32))[0])
    _log("converge_sparse: measuring steps-to-tol ...")
    start = time.perf_counter()
    coef_out, steps_out, loss_out = trainer(
        *carry0, *data_args, *hy, jnp.asarray(max_steps, jnp.int32)
    )
    np.asarray(coef_out)
    wall = time.perf_counter() - start
    steps = int(steps_out)
    if steps >= max_steps or not math.isfinite(float(loss_out)):
        raise RuntimeError(
            f"sparse did not converge: steps={steps}/{max_steps} "
            f"loss={float(loss_out)} tol={tol}"
        )
    return {
        "epochs_to_tol": round(steps * gbs / n, 2),
        "wall_s_to_tol": round(wall, 3),
        "tol": tol,
        "steps": steps,
        "layout": layout,
    }


def _inner_converge_cpu() -> dict:
    """The same convergence program pinned to the host CPU backend: never
    touches the tunnel, so the provisional line can always carry
    epochs_to_tol (hardware-independent); its wall_s is labeled _cpu."""
    _force_cpu()
    return _converge_stage()


def _precision_stage(n=65_536, d=64, reps=3, train_n=16_384, train_dim=256,
                     iters=24) -> dict:
    """Stage: policy-gated mixed precision A/B — the VERDICT item 7
    bf16-roofline-gap attribution number. Two measurements, each a
    same-program ratio:

      - the fused 5-stage chain (4 scalers + LogisticRegressionModel)
        under ``precision_scope("mixed_inference")`` vs no policy;
      - the plan-sharded SGD trainer under ``precision="mixed"`` (bf16
        compute, f32 accum + params) vs no policy.

    Emits ``bf16_vs_f32_samples_per_sec_ratio`` per path plus the bf16
    trainer's max-abs coefficient deviation from its f32 twin (what the
    CI smoke stage asserts is finite and tolerance-bounded). On the CPU
    mesh the ratio measures XLA's CPU bf16 lowering (often < 1 — CPUs
    emulate bf16), NOT the TPU MXU story; the number exists so the
    trajectory is observable through the dead device tunnel, and the
    device variant runs the same programs when the tunnel returns."""
    import jax

    from flinkml_tpu import pipeline_fusion
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding.plan import REPLICATED
    from flinkml_tpu.sharding.apply import train_linear_plan
    from flinkml_tpu.table import Table

    # -- fused 5-stage chain ------------------------------------------------
    model, x = _five_stage_model(n, d)
    apply_table = Table({"features": x})

    def chain_rows_per_sec():
        np.asarray(
            model.transform(apply_table)[0].column("prediction")
        )  # warm-up: compiles + upload
        start = time.perf_counter()
        for _ in range(reps):
            out = model.transform(apply_table)[0]
            np.asarray(out.column("prediction"))
        return n * reps / (time.perf_counter() - start)

    full_chain = chain_rows_per_sec()
    with pipeline_fusion.precision_scope("mixed_inference"):
        bf16_chain = chain_rows_per_sec()
    _log(f"precision[fused_chain]: f32 {full_chain:.0f} rows/s, "
         f"bf16 {bf16_chain:.0f} rows/s "
         f"(ratio {bf16_chain / full_chain:.3f})")

    # -- plan-sharded SGD trainer ------------------------------------------
    xt, yt, wt = make_data(train_n, train_dim)
    mesh = DeviceMesh.for_plan(REPLICATED)

    def train(precision, max_iter):
        return train_linear_plan(
            xt, yt, wt, REPLICATED, mesh, loss="logistic", optimizer="sgd",
            max_iter=max_iter, learning_rate=0.1, precision=precision,
        )

    rates = {}
    coefs = {}
    for label, precision in (("f32", None), ("bf16", "mixed")):
        train(precision, 2)  # compile + window upload
        start = time.perf_counter()
        coefs[label] = train(precision, iters)
        rates[label] = train_n * iters / (time.perf_counter() - start)
    coef_dev = float(np.max(np.abs(coefs["bf16"] - coefs["f32"])))
    assert np.isfinite(coefs["bf16"]).all(), "bf16 trainer went non-finite"
    _log(f"precision[sgd_train]: f32 {rates['f32']:.0f} samples/s, "
         f"bf16 {rates['bf16']:.0f} samples/s "
         f"(ratio {rates['bf16'] / rates['f32']:.3f}, "
         f"coef max|Δ| {coef_dev:.2e})")

    return {
        "bf16_vs_f32_samples_per_sec_ratio": {
            "fused_chain": round(bf16_chain / full_chain, 3),
            "sgd_train": round(rates["bf16"] / rates["f32"], 3),
        },
        "fused_chain_rows_per_sec": {
            "f32": round(full_chain, 1), "bf16": round(bf16_chain, 1),
        },
        "sgd_train_samples_per_sec": {
            "f32": round(rates["f32"], 1), "bf16": round(rates["bf16"], 1),
        },
        "sgd_coef_max_abs_dev": coef_dev,
        "rows": n,
        "dim": d,
        "devices": len(jax.devices()),
    }


def _inner_precision() -> dict:
    _setup_jax_cache()
    return _precision_stage()


def _inner_precision_cpu() -> dict:
    """The mixed-precision A/B pinned to an 8-virtual-device host CPU
    mesh — tunnel-immune (CI's precision smoke stage parses it); the
    device variant runs the same programs when the tunnel returns."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _precision_stage(n=16_384, train_n=8_192, train_dim=128)


def _inner_cold_start_child() -> dict:
    """One cold-start measurement process: load the published model from
    the registry the parent stage set up, then (mode ``engine``) start a
    serving engine — load + per-bucket warmup, the compile or cache-load
    cost — and take one prediction, or (mode ``pool``) spin a 2-replica
    pool the same way. Reports time-to-first-prediction plus a sha256
    over the prediction bytes (the parent's bitwise-parity check across
    cache modes). Whether this process compiles (cold), loads AOT
    artifacts (warm), or runs the plain jit path (parity baseline) is
    decided entirely by the ``FLINKML_TPU_COMPILE_CACHE`` env var the
    parent did or didn't set; each mode runs in its own process so one
    phase's in-memory artifacts can never subsidize the other's
    measurement."""
    import hashlib

    if os.environ.get("_FLINKML_COLDSTART_CPU") == "1":
        _force_cpu()
    mode = os.environ.get("_FLINKML_COLDSTART_MODE", "engine")
    from flinkml_tpu.serving.engine import ServingConfig, ServingEngine
    from flinkml_tpu.serving.pool import ReplicaPool
    from flinkml_tpu.serving.registry import ModelRegistry
    from flinkml_tpu.table import Table

    registry = ModelRegistry(os.environ["_FLINKML_COLDSTART_REGISTRY"])
    rng = np.random.default_rng(7)
    x = rng.normal(size=(256, 16))
    example = Table({"features": x[:4], "label": np.zeros(4)})
    req = {"features": x[:37], "label": np.zeros(37)}
    cfg = ServingConfig(max_batch_rows=2048, max_wait_ms=1.0)

    def sha(columns: dict) -> str:
        h = hashlib.sha256()
        for name in sorted(columns):
            h.update(name.encode())
            h.update(np.ascontiguousarray(columns[name]).tobytes())
        return h.hexdigest()

    if mode == "pool":
        t0 = time.perf_counter()
        pool = ReplicaPool(registry, example, config=cfg, n_replicas=2,
                           name="coldpool")
        pool.start()
        resp = pool.predict(req)
        ttfp = time.perf_counter() - t0
        digest = sha(resp.columns)
        pool.stop(drain=False)
    else:
        t0 = time.perf_counter()
        engine = ServingEngine(registry, example, cfg,
                               name="coldstart").start()
        resp = engine.predict(req)
        ttfp = time.perf_counter() - t0
        digest = sha(resp.columns)
        engine.stop()
    return {"ttfp_s": round(ttfp, 4), "pred_sha": digest}


def _cold_start_stage(cpu: bool) -> dict:
    """Cold-vs-warm time-to-first-prediction for the fused 5-stage chain
    behind a serving engine, and for a 2-replica pool spin-up — the
    tentpole's acceptance measurement (ROADMAP item 5). Publishes the
    chain once, then runs THREE fresh child processes over one shared
    AOT cache directory:

      1. parity baseline — no compile cache (the plain jit path);
      2. cold — empty cache: full XLA compiles, artifacts stored;
      3. warm — the same cache: every program loads from disk.

    Fresh processes, because that IS the scenario (replica spin-up,
    rolling swap, recovery restart); the children share no jit caches.
    Asserts the three runs' predictions are bitwise identical before
    reporting, so a speedup can never come from computing something
    else."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="flinkml-coldstart-")
    try:
        reg_dir = os.path.join(tmp, "registry")
        cache_dir = os.path.join(tmp, "aot")
        from flinkml_tpu.serving.registry import ModelRegistry

        pm, _ = _five_stage_model(n=4_096, d=16)
        ModelRegistry(reg_dir).publish(pm)

        def child(mode: str, cache: "str | None") -> dict:
            env = dict(os.environ)
            env[_INNER_ENV] = "cold_start_child"
            env["_FLINKML_COLDSTART_REGISTRY"] = reg_dir
            env["_FLINKML_COLDSTART_MODE"] = mode
            if cpu:
                env["_FLINKML_COLDSTART_CPU"] = "1"
                env["JAX_PLATFORMS"] = "cpu"
                flags = env.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    env["XLA_FLAGS"] = (
                        flags + " --xla_force_host_platform_device_count=8"
                    ).strip()
            if cache is not None:
                env["FLINKML_TPU_COMPILE_CACHE"] = cache
            else:
                env.pop("FLINKML_TPU_COMPILE_CACHE", None)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=420,
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"cold-start child ({mode}) failed "
                    f"rc={proc.returncode}:\n{proc.stderr[-2000:]}"
                )
            return json.loads(proc.stdout.strip().splitlines()[-1])

        # Engine and pool get DISJOINT cache dirs: the pool's cold run
        # must pay real compiles, not reads of the engine runs' entries.
        engine_cache = os.path.join(cache_dir, "engine")
        pool_cache = os.path.join(cache_dir, "pool")
        baseline = child("engine", None)
        cold = child("engine", engine_cache)
        warm = child("engine", engine_cache)
        pool_cold = child("pool", pool_cache)
        pool_warm = child("pool", pool_cache)
        shas = {r["pred_sha"] for r in
                (baseline, cold, warm, pool_cold, pool_warm)}
        if len(shas) != 1:
            raise RuntimeError(
                "cold-start parity violation: predictions differ across "
                f"jit/cold/warm engine+pool runs ({sorted(shas)})"
            )
        aot_entries = sum(
            1 for _, _, files in os.walk(cache_dir)
            for f in files if f.endswith(".aot")
        )
        return {
            "jit_ttfp_s": baseline["ttfp_s"],
            "cold_ttfp_s": cold["ttfp_s"],
            "warm_ttfp_s": warm["ttfp_s"],
            "ttfp_speedup": round(cold["ttfp_s"] / warm["ttfp_s"], 2),
            "pool_cold_s": pool_cold["ttfp_s"],
            "pool_warm_s": pool_warm["ttfp_s"],
            "pool_speedup": round(
                pool_cold["ttfp_s"] / pool_warm["ttfp_s"], 2
            ),
            "parity_bitwise": 1,
            "aot_entries": aot_entries,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _inner_cold_start() -> dict:
    _setup_jax_cache()
    return _cold_start_stage(cpu=False)


def _inner_cold_start_cpu() -> dict:
    """Tunnel-immune cold-start A/B on the 8-virtual-device CPU host —
    what CI's cold-start smoke stage parses; the device variant runs the
    same children against the real backend when the tunnel returns."""
    _force_cpu()
    return _cold_start_stage(cpu=True)


def _inner_autotune() -> dict:
    """The DEVICE re-tune of every autotuned knob (ROADMAP item 5 /
    VERDICT top_next: the four sort-class cumsum defaults are settled by
    measurement, and the committed CPU-mesh winners must be re-measured
    on real hardware when the tunnel returns). Emits each knob's
    measured winner next to what the committed table carries for THIS
    mesh, so a divergence is visible in the bench artifact before
    anyone commits it."""
    _setup_jax_cache()
    from flinkml_tpu.autotune import load_table, mesh_key
    from flinkml_tpu.autotune.search import search_knobs

    results = search_knobs(quick=False)
    table = load_table()
    mesh = mesh_key()
    return {
        knob: {
            "winner": rec["value"],
            "committed": table.value(mesh, knob),
            "candidates": rec["candidates"],
        }
        for knob, rec in results.items()
    }


def _inner_autotune_cpu() -> dict:
    """Smoke-size CPU-mesh knob search (CI parses it; the committed
    table's values come from the full `python -m flinkml_tpu.autotune
    --commit` run, not from this)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    from flinkml_tpu.autotune import load_table, mesh_key
    from flinkml_tpu.autotune.search import search_knobs

    results = search_knobs(quick=True)
    table = load_table()
    mesh = mesh_key()
    return {
        knob: {
            "winner": rec["value"],
            "committed": table.value(mesh, knob),
            "candidates": rec["candidates"],
        }
        for knob, rec in results.items()
    }


def _pallas_stage() -> dict:
    """Kernel-vs-XLA A/B for the four Pallas sites (ROADMAP item 2 /
    ISSUEs 13, 16): per-site ``pallas/xla`` throughput ratio through the same
    measurers the autotune search commits from, gated by a bitwise
    parity probe per site — a wrong kernel must never emit a ratio. On
    the CPU mesh the Pallas candidates run under the interpreter
    (``interpret: 1`` in the record — the number audits the harness,
    not the hardware); the device variant of this stage IS the queued
    kernel-backend re-tune."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from flinkml_tpu import kernels, pipeline_fusion
    from flinkml_tpu.autotune.search import (
        _env,
        _serving_model,
        measure_kernel_backend_fused_chain,
        measure_kernel_backend_segment_sum,
        measure_kernel_backend_spmv,
        measure_kernel_backend_topk,
    )
    from flinkml_tpu.table import Table

    # -- parity gates (bitwise at f32) --------------------------------
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 512, 4096), jnp.int32)
    vals = jnp.asarray(rng.normal(size=4096).astype(np.float32))
    a = np.asarray(jax.ops.segment_sum(vals, ids, num_segments=512))
    b = np.asarray(kernels.segment_sum(vals, ids, 512, backend="pallas"))
    assert a.tobytes() == b.tobytes(), "segment_sum parity violation"
    sids = jnp.sort(ids)
    a = np.asarray(jax.ops.segment_sum(
        vals, sids, num_segments=512, indices_are_sorted=True))
    b = np.asarray(kernels.segment_sum(
        vals, sids, 512, indices_are_sorted=True, backend="pallas"))
    assert a.tobytes() == b.tobytes(), "sorted segment_sum parity violation"
    sib = jnp.asarray(rng.integers(0, 512, (256, 16)), jnp.int32)
    svb = jnp.asarray(rng.normal(size=(256, 16)).astype(np.float32))
    sw = jnp.asarray(rng.normal(size=512).astype(np.float32))
    # Parity contract is vs the JITTED reference (the product path is
    # always jitted; eager XLA's unfused reduce can differ in the last
    # f32 bit).
    a = np.asarray(jax.jit(
        lambda i, v, w: jnp.sum(v * jnp.take(w, i, axis=0), axis=1)
    )(sib, svb, sw))
    b = np.asarray(kernels.spmv(sib, svb, sw, backend="pallas"))
    assert a.tobytes() == b.tobytes(), "spmv parity violation"
    xq = jnp.asarray(rng.normal(size=(64, 512)).astype(np.float32))
    rv, ri = jax.lax.top_k(xq, 8)
    pv, pi = kernels.top_k(xq, 8, backend="pallas")
    assert np.asarray(rv).tobytes() == np.asarray(pv).tobytes() and \
        np.asarray(ri).tobytes() == np.asarray(pi).tobytes(), \
        "topk parity violation"
    model, xs = _serving_model()
    batch = Table({"features": xs[:256], "label": np.zeros(256)})

    def chain_outputs():
        pipeline_fusion.reset_cache()
        (out,) = model.transform(batch)
        return {c: np.asarray(out.column(c)) for c in out.column_names
                if c not in ("features", "label")}

    with _env("FLINKML_TPU_KERNELS", "fused_chain=xla"):
        ref = chain_outputs()
    with _env("FLINKML_TPU_KERNELS", "fused_chain=pallas"):
        got = chain_outputs()
    pipeline_fusion.reset_cache()
    for c in ref:
        assert ref[c].tobytes() == got[c].tobytes(), \
            f"fused_chain parity violation on column {c!r}"

    # -- ratios -------------------------------------------------------
    sites = {
        "fused_chain": measure_kernel_backend_fused_chain,
        "segment_sum": measure_kernel_backend_segment_sum,
        "spmv": measure_kernel_backend_spmv,
        "topk": measure_kernel_backend_topk,
    }
    ratios, rates = {}, {}
    for site, measure in sites.items():
        cand = measure(True)
        ratios[site] = round(cand["pallas"] / cand["xla"], 4)
        rates[site] = {name: round(v, 1) for name, v in cand.items()}
    return {
        "kernel_vs_xla_samples_per_sec_ratio": ratios,
        "rates": rates,
        "parity_bitwise": 1,
        "interpret": int(kernels.interpret_mode()),
    }


def _inner_pallas() -> dict:
    """The DEVICE kernel-backend re-tune (queued in stage_order for the
    tunnel's return): compiled Mosaic kernels vs XLA on real hardware —
    the measurement that can flip a committed ``kernel_backend_*``
    default."""
    _setup_jax_cache()
    return _pallas_stage()


def _inner_pallas_cpu() -> dict:
    """Tunnel-immune CPU-mesh variant (interpret-mode pallas) — what
    CI's ``pallas smoke`` stage parses."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _pallas_stage()


def _sparse_hot_loops_stage() -> dict:
    """Sorted-by-design sparse hot loops (ISSUE 16): sparse-LR rows/s
    through the SortedSparseColumn stream (prefetcher pack + gated SpMV
    forward + sorted segment-sum gradient, zero densify / zero runtime
    sort) against the PRODUCT densified baseline (the same batches as
    ``[n, dim]`` through the dense stream trainer). Moderate ``dim`` so
    the densified baseline is feasible to run at all; the ratio is the
    headline — CI's ``sparse smoke`` trips if the sorted path ever
    loses to densification (>= 1.0 expected: the sparse step moves and
    multiplies O(nnz), the dense one O(n*dim))."""
    import numpy as np

    from flinkml_tpu.data.prefetch import pad_place_table
    from flinkml_tpu.linalg import SparseVector
    from flinkml_tpu.models._linear_sgd import (
        train_linear_model_sorted_stream,
        train_linear_model_stream,
    )
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.table import Table

    n_batches, batch, dim, nnz = 8, 512, 4_096, 16
    epochs = 3
    rng = np.random.default_rng(0)
    host_tables, dense_batches = [], []
    for _ in range(n_batches):
        vecs = np.empty(batch, object)
        xd = np.zeros((batch, dim), np.float32)
        for i in range(batch):
            idx = np.sort(rng.choice(dim, size=nnz, replace=False))
            val = rng.normal(size=nnz).astype(np.float32)
            vecs[i] = SparseVector(dim, idx, val)
            xd[i, idx] = val
        y = (rng.random(batch) > 0.5).astype(np.float32)
        w = np.ones(batch, np.float32)
        host_tables.append(Table({"features": vecs, "y": y, "w": w}))
        dense_batches.append({"x": xd, "y": y, "w": w})
    dev_tables = [pad_place_table(t) for t in host_tables]
    mesh = DeviceMesh()
    hyper = dict(loss="logistic", learning_rate=0.5, reg=1e-4,
                 elastic_net=0.0, tol=0.0)

    def sorted_fit(iters):
        return train_linear_model_sorted_stream(
            list(dev_tables), "features", "y", "w", max_iter=iters, **hyper,
        )

    def dense_fit(iters):
        return train_linear_model_stream(
            iter([dict(b) for b in dense_batches]), mesh=mesh,
            max_iter=iters, **hyper,
        )

    rows = n_batches * batch
    out = {"dim": dim, "nnz_per_row": nnz, "rows_per_epoch": rows,
           "epochs_timed": epochs}
    for name, fit in (("sparse_sorted", sorted_fit),
                      ("densified", dense_fit)):
        fit(1)  # compile + warm (module-level stepper caches persist)
        t0 = time.perf_counter()
        fit(epochs)
        out[f"{name}_rows_per_sec"] = round(
            rows * epochs / (time.perf_counter() - t0), 1
        )
    out["sparse_vs_densified_ratio"] = round(
        out["sparse_sorted_rows_per_sec"] / out["densified_rows_per_sec"], 4
    )
    return out


def _inner_sparse_hot_loops() -> dict:
    """The DEVICE sorted-sparse measurement (queued in stage_order):
    the sorted-column stream vs densification on real hardware."""
    _setup_jax_cache()
    return _sparse_hot_loops_stage()


def _inner_sparse_hot_loops_cpu() -> dict:
    """Tunnel-immune CPU-mesh variant — what CI's ``sparse smoke``
    stage parses for the no-regression tripwire."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _sparse_hot_loops_stage()


def _memory_stage(n=8192, d=32, state_dim=65536) -> dict:
    """Stage: memory-model calibration — the pass-7 static peak-live
    estimate (flinkml_tpu.analysis.memory) measured against XLA's own
    ``Compiled.memory_analysis()`` (temp + argument + output bytes) on
    two real programs: (1) the bench's fused 5-stage chain math
    (4 scalers + logistic head, the ``pipeline_fused`` spine) and
    (2) the plan-sharded SGD step on the 8-way mesh. CI pins both
    ratios inside a 0.5x-2.0x band, so the static model is measured,
    not guessed. Also demonstrates the FML703 donation finding LIVE on
    the real (deliberately undonated) step, and its absence once the
    state buffer is donated."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu.analysis.memory import (
        check_memory_fn,
        estimate_fn_memory,
    )
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding import FSDP
    from flinkml_tpu.sharding.apply import (
        batch_sharding,
        init_linear_state,
        linear_step_fn,
        state_shardings,
    )

    def _xla_bytes(compiled):
        ma = compiled.memory_analysis()
        return (int(ma.temp_size_in_bytes)
                + int(ma.argument_size_in_bytes)
                + int(ma.output_size_in_bytes))

    # -- twin 1: the fused 5-stage chain (single device) -------------------
    def chain(x, mean, std, dmin, dmax, maxabs, median, rng_, coef):
        h = (x - mean) / std
        h = (h - dmin) / (dmax - dmin)
        h = h / maxabs
        h = (h - median) / rng_
        return jax.nn.sigmoid(h @ coef)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    row = np.ones((1, d), np.float32)
    coef = rng.normal(size=(d,)).astype(np.float32)
    chain_args = (x, row, row, 0 * row, row, row, 0 * row, row, coef)
    chain_actual = _xla_bytes(jax.jit(chain).lower(*chain_args).compile())
    chain_est = estimate_fn_memory(chain, *chain_args).peak_bytes

    # -- twin 2: the plan-sharded SGD step (8-way mesh) --------------------
    mesh = DeviceMesh.for_plan(FSDP)
    step = linear_step_fn(
        loss="logistic", optimizer="sgd", dtype_name="float32",
        learning_rate=0.1, momentum=0.9, reg_l2=0.0, reg_l1=0.0,
    )
    state = init_linear_state(state_dim, "sgd", np.float32)
    bs = 256
    xb = rng.normal(size=(bs, state_dim)).astype(np.float32)
    yb = (rng.random(bs) > 0.5).astype(np.float32)
    wb = np.ones(bs, np.float32)
    b_shard = batch_sharding(FSDP, mesh)
    compiled = jax.jit(
        step,
        in_shardings=(state_shardings(FSDP, mesh, state),
                      b_shard, b_shard, b_shard),
        donate_argnums=(0,),
    ).lower(state, xb, yb, wb).compile()
    axes = dict(mesh.mesh.shape)
    sgd_actual = _xla_bytes(compiled)
    sgd_est = estimate_fn_memory(
        step, state, xb, yb, wb, plan=FSDP, mesh=axes,
        param_argnums=(0,), donate_argnums=(0,),
    ).peak_bytes

    # -- FML703 live: the same step, donated vs not ------------------------
    undonated = check_memory_fn(
        step, state, xb, yb, wb, plan=FSDP, mesh=axes,
        param_argnums=(0,), program="sgd_step",
    )
    donated = check_memory_fn(
        step, state, xb, yb, wb, plan=FSDP, mesh=axes,
        param_argnums=(0,), donate_argnums=(0,), program="sgd_step",
    )
    return {
        "memory_calibration_ratio": {
            "fused_chain": round(chain_est / chain_actual, 3),
            "sgd_step": round(sgd_est / sgd_actual, 3),
        },
        "memory_estimate_bytes": {
            "fused_chain": int(chain_est), "sgd_step": int(sgd_est),
        },
        "xla_memory_analysis_bytes": {
            "fused_chain": int(chain_actual), "sgd_step": int(sgd_actual),
        },
        "fml703_live_finding": sorted(
            f.column for f in undonated if f.rule == "FML703"
        ),
        "fml703_after_donation": sorted(
            f.column for f in donated if f.rule == "FML703"
        ),
        "rows": n,
        "state_dim": state_dim,
    }


def _inner_memory_cpu() -> dict:
    """Tunnel-immune CPU-mesh calibration — what CI's ``memory smoke``
    stage parses for the 0.5x-2.0x ratio tripwire."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _force_cpu()
    return _memory_stage()


def _feature_freshness_stage() -> dict:
    """Stage: the streaming feature platform's train-to-serve freshness
    loop end-to-end (ISSUE 18). A hashed-id FM trainer consumes a
    synthetic click stream, publishes incremental row deltas, and a
    2-replica pool follows the registry through in-place row patches.
    Reports trainer throughput, the delta-vs-snapshot payload ratio, and
    the time-to-freshness distribution (publish call until EVERY replica
    serves the new version — the roll is synchronous in the publishing
    thread, so each sample times the full save + patch fan-out)."""
    _setup_jax_cache()
    import tempfile

    from flinkml_tpu.features import (
        DeltaPublisher,
        StreamingHashedFMTrainer,
        hash_buckets,
    )
    from flinkml_tpu.serving.engine import ServingConfig
    from flinkml_tpu.serving.pool import ReplicaPool
    from flinkml_tpu.serving.registry import ModelRegistry
    from flinkml_tpu.table import Table
    from flinkml_tpu.utils.metrics import metrics

    num_buckets, rows, length, publishes = 1 << 16, 512, 4, 32
    rng = np.random.default_rng(0)
    trainer = StreamingHashedFMTrainer(
        num_buckets=num_buckets, factor_size=16, hash_seed=7,
        learning_rate=0.05,
    )

    def batch():
        keys = rng.integers(0, 1 << 22, size=(rows, length))
        ids = hash_buckets(
            keys.reshape(-1), seed=7, num_buckets=num_buckets,
        ).reshape(rows, length)
        labels = (keys.sum(axis=1) % 2).astype(np.float32)
        return ids, labels

    with tempfile.TemporaryDirectory() as root:
        registry = ModelRegistry(os.path.join(root, "reg"))
        publisher = DeltaPublisher(
            registry, trainer, every_n_batches=1, max_depth=publishes + 1,
            name="bench_freshness",
        )
        ids, labels = batch()
        trainer.fit_batch(ids, labels)
        publisher.publish_now()  # the base snapshot
        example = Table({"hashed_ids": np.zeros((2, length), np.int32)})
        pool = ReplicaPool(
            registry, example,
            config=ServingConfig(max_batch_rows=256, max_wait_ms=1.0),
            n_replicas=2, name="bench_freshness",
        ).start().follow_registry()
        try:
            t_train = 0.0
            fresh_ms = []
            for _ in range(publishes):
                ids, labels = batch()
                t0 = time.perf_counter()
                trainer.fit_batch(ids, labels)
                t_train += time.perf_counter() - t0
                t0 = time.perf_counter()
                publisher.publish_now()  # delta + synchronous 2-replica roll
                fresh_ms.append((time.perf_counter() - t0) * 1e3)
            lag = pool.freshness_lag(trainer.watermark)
        finally:
            pool.stop()
        reg_counters = registry._metrics.snapshot()["counters"]
    gauges = metrics.group(
        "features.publisher", labels={"publisher": "bench_freshness"},
    ).snapshot()["gauges"]
    return {
        "train_rows_per_sec": round(rows * publishes / t_train, 1),
        "delta_publishes": int(reg_counters.get("delta_publishes", 0)),
        "full_publishes": int(reg_counters.get("full_publishes", 0)),
        "delta_bytes": int(gauges["delta_bytes"]),
        "full_snapshot_bytes": int(gauges["full_bytes"]),
        "delta_ratio": round(float(gauges["delta_ratio"]), 4),
        "time_to_freshness_ms_p50": round(
            float(np.percentile(fresh_ms, 50)), 2),
        "time_to_freshness_ms_p99": round(
            float(np.percentile(fresh_ms, 99)), 2),
        "freshness_lag_batches": lag,
        "num_buckets": num_buckets,
    }


def _inner_feature_freshness() -> dict:
    return _feature_freshness_stage()


def _inner_feature_freshness_cpu() -> dict:
    """Tunnel-immune CPU variant — what CI's ``freshness smoke`` bench
    companion parses. The trainer/publisher/pool path is host-resident,
    so this IS the product path, not a proxy; the device variant exists
    to time the roll when replicas hold device-placed tables."""
    _force_cpu()
    return _feature_freshness_stage()


_INNER_STAGES = {
    "probe": _inner_probe,
    "dense": _inner_dense,
    "dense_bf16": _inner_dense_bf16,
    "svc": _inner_svc,
    "ftrl": _inner_ftrl,
    "sparse": _inner_sparse,
    "kmeans": _inner_kmeans,
    "kmeans_mnist": _inner_kmeans_mnist,
    "pipeline_fused": _inner_pipeline_fused,
    "pipeline_fused_cpu": _inner_pipeline_fused_cpu,
    "serving": _inner_serving,
    "serving_cpu": _inner_serving_cpu,
    "serving_scaleout": _inner_serving_scaleout,
    "serving_scaleout_cpu": _inner_serving_scaleout_cpu,
    "multiproc_pool": _inner_multiproc_pool,
    "multiproc_pool_cpu": _inner_multiproc_pool_cpu,
    "serving_autoscale": _inner_serving_autoscale,
    "serving_autoscale_cpu": _inner_serving_autoscale_cpu,
    "serving_grayfail": _inner_serving_grayfail,
    "serving_grayfail_cpu": _inner_serving_grayfail_cpu,
    "feed_overlap": _inner_feed_overlap,
    "input_pipeline": _inner_input_pipeline,
    "input_pipeline_cpu": _inner_input_pipeline_cpu,
    "sharded_train": _inner_sharded_train,
    "sharded_train_cpu": _inner_sharded_train_cpu,
    "sharded_embedding": _inner_sharded_embedding,
    "sharded_embedding_cpu": _inner_sharded_embedding_cpu,
    "precision": _inner_precision,
    "precision_cpu": _inner_precision_cpu,
    "cold_start": _inner_cold_start,
    "cold_start_cpu": _inner_cold_start_cpu,
    "cold_start_child": _inner_cold_start_child,
    "autotune": _inner_autotune,
    "autotune_cpu": _inner_autotune_cpu,
    "pallas": _inner_pallas,
    "pallas_cpu": _inner_pallas_cpu,
    "sparse_hot_loops": _inner_sparse_hot_loops,
    "sparse_hot_loops_cpu": _inner_sparse_hot_loops_cpu,
    "memory_cpu": _inner_memory_cpu,
    "feature_freshness": _inner_feature_freshness,
    "feature_freshness_cpu": _inner_feature_freshness_cpu,
    "recovery": _inner_recovery,
    "recovery_cpu": _inner_recovery_cpu,
    "converge": _inner_converge,
    "converge_cpu": _inner_converge_cpu,
    "converge_sparse": _inner_converge_sparse,
    "gbt": _inner_gbt,
    "als": _inner_als,
    "word2vec": _inner_word2vec,
}


def _last_device_evidence() -> "dict | None":
    """Newest per-chip measurement from the committed capture logs
    (tools/device_watch_*.log, tools/bench_manual_*.log). The provisional
    JSON line points at this so a wedged-tunnel round still surfaces the
    device evidence captured in an earlier healthy window of the same
    image (VERDICT r4 missing #1: BENCH_r04 said nothing while the
    committed watcher log held the numbers)."""
    best = None
    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    paths = glob.glob(os.path.join(tools_dir, "device_watch_*.log")) + \
        glob.glob(os.path.join(tools_dir, "bench_manual_*.log"))

    def stamp(path):
        m = re.search(r"(\d{8}T\d{6}Z)", os.path.basename(path))
        return m.group(1) if m else ""

    for path in sorted(paths, key=stamp):  # newest UTC stamp wins
        try:
            with open(path, "r", errors="replace") as fh:
                text = fh.read()
        except OSError:
            continue
        for m in re.finditer(
            r'\{"metric": "logreg_train_samples_per_sec_per_chip".*\}', text
        ):
            try:
                rec = json.loads(m.group(0))
            except ValueError:
                continue
            best = {
                "file": os.path.join("tools", os.path.basename(path)),
                "logreg_train_samples_per_sec_per_chip": rec["value"],
                "vs_baseline": rec.get("vs_baseline"),
            }
    return best


def _run_stage(stage: str, timeout_s: float, deadline: float, retries: int = 1):
    """Run one inner stage in a child process; returns ``(value, timed_out)``.

    ``value`` is the stage's float result or None; ``timed_out`` is True
    iff the LAST attempt hit its timeout (a hung tunnel), as opposed to a
    fast stage-specific failure — callers use it to decide whether a
    wedge-check probe is warranted.

    A child is the unit of failure isolation: a hung device tunnel takes
    the child (killed at timeout), never the bench. Retries are cheap
    because children share the persistent XLA compilation cache.
    ``timeout_s`` bounds the WHOLE stage (all attempts share one stage
    deadline — a hung stage must not consume 2x its cap), and no attempt
    starts past ``deadline`` (the FLINKML_BENCH_TIMEOUT total budget)."""
    stage_deadline = time.monotonic() + timeout_s
    timed_out = False
    for attempt in range(retries + 1):
        timeout_s = min(stage_deadline, deadline) - time.monotonic()
        if timeout_s <= 5:
            _log(f"stage={stage} skipped: stage/total budget exhausted")
            return None, timed_out
        _log(f"stage={stage} attempt={attempt + 1} timeout={timeout_s:.0f}s")
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env={**os.environ, _INNER_ENV: stage},
                stdout=subprocess.PIPE,
                stderr=sys.stderr,  # stream child progress live
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            _log(f"stage={stage} timed out after {timeout_s:.0f}s "
                 "(device tunnel hung?)")
            timed_out = True
            continue
        timed_out = False
        dt = time.perf_counter() - t0
        if proc.returncode == 0:
            try:
                last = proc.stdout.strip().splitlines()[-1]
                # Scalar stages print one float; structured stages
                # (converge, feed_overlap) print one JSON object.
                value = (json.loads(last) if last.startswith("{")
                         else float(last))
                _log(f"stage={stage} ok in {dt:.1f}s -> {value}")
                return value, False
            except (ValueError, IndexError):
                _log(f"stage={stage} unparseable output: {proc.stdout!r}")
        else:
            _log(f"stage={stage} failed rc={proc.returncode}")
    return None, timed_out


def _hunt_device(deadline: float, attempt_timeout: float,
                 spacing: float) -> "float | None":
    """Probe repeatedly, spaced, until success or the total budget is gone.

    The tunnel's observed failure mode is a *transient* wedge (BASELINE.md
    round-2/3): a healthy probe costs ~6 s, so one dead attempt must not
    abandon the device for the session — round 3's single 360 s probe
    timeout left ~1,740 s of its 2,100 s budget unused. Every attempt is
    timestamped (UTC) to stderr, so a fully-dead session leaves N spaced
    forensics proving the tunnel was down all session, not sampled once."""
    attempts = []
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 30:  # not enough left to run any stage anyway
            break
        t = min(attempt_timeout, remaining - 10)
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        _log(f"probe attempt={len(attempts) + 1} at={stamp} "
             f"timeout={t:.0f}s budget_left={remaining:.0f}s")
        value, _ = _run_stage("probe", t, deadline, retries=0)
        attempts.append(stamp)
        if value is not None:
            return value
        sleep = min(spacing, max(0.0, deadline - time.monotonic() - 35))
        if sleep > 0:
            _log(f"probe: device unreachable; re-probing in {sleep:.0f}s")
            time.sleep(sleep)
    _log(
        "probe forensic: tunnel dead all session — "
        f"{len(attempts)} spaced attempts all failed: {', '.join(attempts)}"
    )
    return None


def main():
    from flinkml_tpu.utils.device_lock import device_client_lock

    inner = os.environ.get(_INNER_ENV)
    if inner:
        # Stage children inherit the parent's held-lock marker and skip
        # re-acquiring; a stage run standalone takes the lock itself.
        # converge_cpu is pinned to the host backend and never touches
        # the tunnel, so it must not contend for the single-tenant lock
        # (it runs while a watcher capture may hold the device).
        if inner in ("converge_cpu", "pipeline_fused_cpu", "serving_cpu",
                     "serving_scaleout_cpu", "serving_autoscale_cpu",
                     "serving_grayfail_cpu", "multiproc_pool_cpu",
                     "input_pipeline_cpu",
                     "sharded_train_cpu", "sharded_embedding_cpu",
                     "precision_cpu", "cold_start_cpu", "cold_start_child",
                     "autotune_cpu", "pallas_cpu", "sparse_hot_loops_cpu",
                     "memory_cpu", "feature_freshness_cpu"):
            out = _INNER_STAGES[inner]()
        else:
            with device_client_lock():
                out = _INNER_STAGES[inner]()
        print(json.dumps(out) if isinstance(out, dict) else f"{out:.1f}")
        return

    # FLINKML_BENCH_TIMEOUT is the TOTAL bench wall-clock budget. The
    # device phase gets that MINUS a reserve: rounds 1-4 all ended with
    # the driver killing bench mid-hunt (rc=124) and an empty BENCH_rNN
    # artifact, because the hunt's deadline equaled the total budget and
    # the driver's own kill fired first. The reserve keeps the device
    # phase >=180 s clear of the budget so the final line always prints;
    # the default total (1680 s) sits ~2 min under the observed ~1800 s
    # driver kill of round 4. Each stage attempt is additionally capped
    # at FLINKML_BENCH_STAGE_TIMEOUT so one pathological compile cannot
    # starve every stage behind it.
    t_start = time.monotonic()
    total_budget = float(os.environ.get("FLINKML_BENCH_TIMEOUT", "1680"))
    reserve = max(180.0, 0.1 * total_budget)
    probe_timeout = float(os.environ.get("FLINKML_BENCH_PROBE_TIMEOUT", "240"))
    probe_spacing = float(os.environ.get("FLINKML_BENCH_PROBE_SPACING", "60"))
    stage_cap = float(os.environ.get("FLINKML_BENCH_STAGE_TIMEOUT", "600"))
    deadline = t_start + max(60.0, total_budget - reserve)

    # ---- provisional phase: a parseable line BEFORE any tunnel contact.
    # Everything here is tunnel-immune (numpy CPU baseline + a CPU-pinned
    # convergence child), so even a driver kill mid-hunt leaves an honest
    # record on stdout: the CPU fallback, the hardware-independent
    # epochs-to-tol, and a pointer to the newest committed device capture.
    _log("measuring CPU reference-style baseline ...")
    x_cpu, y_cpu, w_cpu = make_data(200_000, 123)
    cpu_sps = bench_reference_style_cpu(x_cpu, y_cpu, w_cpu, 16_384)
    evidence = _last_device_evidence()
    conv_cpu, _ = _run_stage(
        "converge_cpu", 300.0, t_start + total_budget - 60, retries=0
    )
    pf_cpu, _ = _run_stage(
        "pipeline_fused_cpu", 300.0, t_start + total_budget - 60, retries=0
    )
    provisional_extras = {"provisional": 1}
    if conv_cpu is not None:
        provisional_extras["convergence_cpu"] = conv_cpu
    if pf_cpu is not None:
        provisional_extras["pipeline_transform_cpu"] = pf_cpu
    if evidence is not None:
        provisional_extras["last_device_evidence"] = evidence
    print(json.dumps({
        "metric": "logreg_train_samples_per_sec_cpu_fallback",
        "value": round(cpu_sps, 1),
        "unit": "samples/sec",
        "vs_baseline": 1.0,
        "extras": provisional_extras,
    }), flush=True)
    _log("provisional line emitted; starting device phase "
         f"(deadline in {deadline - time.monotonic():.0f}s)")

    # Stage order is cheap-compile-first: the tunnel's observed failure
    # mode (BASELINE.md round-4 session-2 log) is wedging UNDER a heavy
    # compile, and the dim=1e6 sparse stage is the heaviest compile in
    # the bench — it runs LAST so a wedge it triggers cannot starve the
    # stages behind it. After any stage TIMEOUT (fast stage-specific
    # failures don't qualify), a quick probe decides whether the tunnel
    # is wedged (skip the rest immediately instead of burning stage_cap
    # on each) or the hang was stage-specific.
    # converge_sparse and sparse run LAST: the dim=1e6 compiles are the
    # heaviest in the bench and the tunnel's observed failure mode is
    # wedging UNDER a heavy compile.
    stage_order = ["dense", "dense_bf16", "svc", "converge", "ftrl",
                   "kmeans", "kmeans_mnist", "pipeline_fused",
                   "feed_overlap", "input_pipeline", "sharded_train",
                   "sharded_embedding", "precision", "cold_start",
                   "autotune", "pallas", "sparse_hot_loops",
                   "serving_autoscale", "serving_grayfail",
                   "multiproc_pool",
                   "feature_freshness", "gbt",
                   "als", "word2vec", "converge_sparse", "sparse"]
    results = {}
    # Hold the single-tenant device mutex across ALL device stages: two
    # concurrent clients wedged the tunnel for 8+ hours in round 2
    # (BASELINE.md). Children inherit the held marker via os.environ.
    # Wait up to 900 s for the lock, clamped to the remaining total
    # budget: the holder may be tools/device_watch.sh mid-capture on a
    # freshly healed tunnel, and inheriting the healthy device after it
    # finishes beats skipping to the CPU fallback.
    skip_device = os.environ.get("FLINKML_BENCH_SKIP_DEVICE") == "1"
    if skip_device:
        # CI smoke mode: never touch the (single-tenant, wedge-prone)
        # tunnel — no lock, no probes, no forensic line (the forensic
        # trail must only record sessions that actually probed). The
        # fallback line above stands as the result.
        _log("FLINKML_BENCH_SKIP_DEVICE=1: skipping the device phase")
        deadline = None  # device block below is guarded out
    lock_wait = (0.0 if skip_device else
                 min(900.0, max(0.0, deadline - time.monotonic() - 40)))
    try:
        if not skip_device:
            with device_client_lock(timeout_s=lock_wait):
                if _hunt_device(deadline, probe_timeout, probe_spacing) is not None:
                    for i, name in enumerate(stage_order):
                        results[name], stage_timed_out = _run_stage(
                            name, stage_cap, deadline)
                        if stage_timed_out and i + 1 < len(stage_order):
                            remaining = deadline - time.monotonic()
                            if remaining <= 40:
                                _log("total budget exhausted; skipping remaining "
                                     f"stages: {', '.join(stage_order[i + 1:])}")
                                break
                            _log(f"stage={name} timed out; quick probe to check "
                                 "whether the tunnel wedged mid-bench")
                            probe_val, _ = _run_stage(
                                "probe", min(90.0, remaining - 10),
                                deadline, retries=0)
                            if probe_val is None:
                                skipped = stage_order[i + 1:]
                                _log("tunnel wedged mid-bench; skipping "
                                     f"remaining stages: {', '.join(skipped)}")
                                break
                else:
                    _log("probe failed; skipping device measurement")
    except TimeoutError as e:
        _log(f"device busy: {e}; skipping device measurement")
    device_sps = results.get("dense")

    if device_sps is None:
        # Device unreachable: re-emit the fallback as the FINAL line so
        # the last parseable line is still honest, under a DIFFERENT
        # metric name so a CPU fallback can never be mistaken for a
        # per-chip measurement.
        _log(
            "note: a CPU fallback reflects THIS run's tunnel state only — "
            "check BASELINE.md's round tunnel log for device evidence "
            "captured in earlier healthy windows of the same round."
        )
        metric = "logreg_train_samples_per_sec_cpu_fallback"
        value = cpu_sps
    else:
        metric = "logreg_train_samples_per_sec_per_chip"
        value = device_sps

    record = {
        "metric": metric,
        "value": round(value, 1),
        "unit": "samples/sec",
        "vs_baseline": round(value / cpu_sps, 2),
    }
    # Secondary measurements kept inside the single JSON line; each key
    # maps a results[] stage to its extras name. The workload for each is
    # documented on its _inner_* stage.
    extras = {}
    scalar_stages = {
        "sparse": "sparse_logreg_samples_per_sec_per_chip",
        "svc": "svc_proximal_samples_per_sec_per_chip",
        "ftrl": "ftrl_online_samples_per_sec_per_chip",
        "dense_bf16": "dense_bf16_logreg_samples_per_sec_per_chip",
        "kmeans": "kmeans_points_per_sec_per_chip",
        "kmeans_mnist": "kmeans_mnist_points_per_sec_per_chip",
        "gbt": "gbt_row_trees_per_sec_per_chip",
        "als": "als_rating_visits_per_sec_per_chip",
        "word2vec": "word2vec_pairs_per_sec_per_chip",
    }
    for stage, key in scalar_stages.items():
        if results.get(stage) is not None:
            extras[key] = round(results[stage], 1)
    if results.get("pipeline_fused") is not None:
        # Fused vs per-stage PipelineModel.transform rows/sec — the
        # ISSUE-1 fused-executor trajectory (workload on
        # _pipeline_fused_stage).
        extras["pipeline_transform"] = results["pipeline_fused"]
    elif pf_cpu is not None:
        extras["pipeline_transform_cpu"] = pf_cpu
    if results.get("feed_overlap") is not None:
        # fed/resident wall ratio — the streaming-architecture overhead,
        # latency-insensitive (single end-of-run synchronization).
        extras["feed_overlap"] = results["feed_overlap"]
    if results.get("input_pipeline") is not None:
        # Shuffled Dataset → bucketed prefetch → jitted consumer rows/s
        # + stall fraction — the ISSUE-5 input-pipeline trajectory.
        extras["input_pipeline"] = results["input_pipeline"]
    if results.get("sharded_train") is not None:
        # Plan-sharded trainer samples/s per preset (dp/FSDP/FSDP×TP) —
        # the ISSUE-7 sharding trajectory (workload on
        # _sharded_train_stage).
        extras["sharded_train"] = results["sharded_train"]
    if results.get("precision") is not None:
        # bf16-vs-f32 same-program ratios (fused chain + SGD trainer) —
        # the VERDICT item 7 roofline-gap attribution (workload on
        # _precision_stage).
        extras["precision"] = results["precision"]
    if results.get("cold_start") is not None:
        # Cold-vs-warm time-to-first-prediction through the persistent
        # AOT compile cache (fused chain engine + 2-replica pool) — the
        # ISSUE-11 zero-cold-start trajectory (workload on
        # _cold_start_stage).
        extras["cold_start"] = results["cold_start"]
    if results.get("autotune") is not None:
        # The device re-tune of every autotuned knob vs the committed
        # tuning table (the four sort-class cumsum defaults, infer_plan
        # order, serving bucket/window) — ROADMAP item 5 / VERDICT
        # top_next.
        extras["autotune"] = results["autotune"]
    if results.get("pallas") is not None:
        # Per-site Pallas-vs-XLA kernel ratios on real hardware — the
        # queued kernel-backend device re-tune (ROADMAP item 2 /
        # ISSUE 13; workload on _pallas_stage).
        extras["pallas"] = results["pallas"]
    if results.get("feature_freshness") is not None:
        # Streaming feature platform: hashed-FM train rows/s, delta-vs-
        # snapshot payload ratio, and time-to-freshness p50/p99 through
        # the registry's row-delta fan-out (ISSUE 18; workload on
        # _feature_freshness_stage).
        extras["feature_freshness"] = results["feature_freshness"]
    if results.get("converge") is not None:
        # Epochs + wall to fixed tol on device — the second half of
        # BASELINE.json's "samples/sec/chip + epochs-to-converge".
        extras["convergence"] = results["converge"]
    elif conv_cpu is not None:
        extras["convergence_cpu"] = conv_cpu
    if results.get("converge_sparse") is not None:
        extras["convergence_sparse"] = results["converge_sparse"]
    if device_sps is None and evidence is not None:
        extras["last_device_evidence"] = evidence
    if extras:
        record["extras"] = extras
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
