"""Benchmark: LogisticRegression training throughput (samples/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The north-star metric (BASELINE.json): samples/sec/chip for
LogisticRegression.fit. The reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured against a faithful reimplementation of the
reference's execution model run on this host's CPU: record-at-a-time SGD
with per-record BLAS dot/axpy (``LogisticGradient.java:50-96`` iterates
records in a Java loop over netlib BLAS; the numpy equivalent below gives it
the benefit of C-speed vector ops per record). Both sides time the same
work: epochs of global-batch gradient steps at identical batch size/dim.
"""

import json
import math
import time

import numpy as np


def make_data(n, dim, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(dtype)
    true_coef = rng.normal(size=dim).astype(dtype)
    y = (x @ true_coef > 0).astype(dtype)
    w = np.ones(n, dtype=dtype)
    return x, y, w


def bench_tpu(x, y, w, global_batch_size, n_steps):
    """Steady-state training throughput with the dataset resident in HBM —
    the analog of the reference's steady state, which trains from data
    cached in ListState (LogisticRegression.java:375-376) after epoch 0."""
    import jax
    import jax.numpy as jnp
    from flinkml_tpu.models.logistic_regression import (
        _device_trainer,
        _shard_training_data,
    )
    from flinkml_tpu.parallel import DeviceMesh

    mesh = DeviceMesh()
    xd, yd, wd = _shard_training_data(x, y, w, mesh)
    local_bs = min(global_batch_size // mesh.axis_size(), xd.shape[0] // mesh.axis_size())
    trainer = _device_trainer(mesh.mesh, local_bs, DeviceMesh.DATA_AXIS)
    f32 = lambda v: jnp.asarray(v, xd.dtype)
    args = (xd, yd, wd, f32(0.1), f32(0.0), f32(0.0), f32(0.0))
    # Warm-up compiles the whole-run program.
    np.asarray(trainer(*args, jnp.asarray(10, jnp.int32)))
    start = time.perf_counter()
    np.asarray(trainer(*args, jnp.asarray(n_steps, jnp.int32)))
    elapsed = time.perf_counter() - start
    return local_bs * mesh.axis_size() * n_steps / elapsed


def bench_reference_style_cpu(x, y, w, global_batch_size, budget_s=10.0):
    """The reference's per-record execution model (LogisticGradient.java:50-96):
    one dot + one axpy per record per epoch, coefficient update per epoch."""
    n, dim = x.shape
    x64, y64, w64 = x.astype(np.float64), y.astype(np.float64), w.astype(np.float64)
    coef = np.zeros(dim)
    rng = np.random.default_rng(0)
    processed = 0
    start = time.perf_counter()
    grad = np.zeros(dim)
    while time.perf_counter() - start < budget_s:
        idx = rng.integers(0, n, size=global_batch_size)
        grad[:] = 0.0
        wsum = 0.0
        for i in idx:  # record-at-a-time, as the reference's Java loop
            xi = x64[i]
            dot = float(xi @ coef)
            ys = 2.0 * y64[i] - 1.0
            mult = w64[i] * (-ys / (math.exp(dot * ys) + 1.0))
            grad += mult * xi  # BLAS.axpy per record
            wsum += w64[i]
        coef -= (0.1 / wsum) * grad
        processed += global_batch_size
    return processed / (time.perf_counter() - start)


def _run_device_bench() -> float:
    """Device-side measurement, run in a child process so a hung device
    tunnel (jax init can block forever if the TPU proxy is down) cannot
    take the whole bench with it."""
    n, dim = 1_000_000, 123  # a9a-like width (BASELINE.json config #1)
    global_batch_size = 262_144
    x, y, w = make_data(n, dim)
    return bench_tpu(x, y, w, global_batch_size, n_steps=400)


def main():
    import os
    import subprocess
    import sys

    if os.environ.get("_FLINKML_BENCH_INNER") == "1":
        print(f"{_run_device_bench():.1f}")
        return

    timeout_s = float(os.environ.get("FLINKML_BENCH_TIMEOUT", "1500"))
    device_sps = None
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**os.environ, "_FLINKML_BENCH_INNER": "1"},
            capture_output=True, text=True, timeout=timeout_s,
        )
        if proc.returncode == 0:
            device_sps = float(proc.stdout.strip().splitlines()[-1])
        else:
            sys.stderr.write(
                f"device bench failed (rc={proc.returncode}):\n{proc.stderr}\n"
            )
    except subprocess.TimeoutExpired:
        sys.stderr.write(
            f"device bench timed out after {timeout_s}s (device tunnel hung?)\n"
        )
    except (ValueError, IndexError):
        sys.stderr.write(
            f"device bench produced unparseable output:\n{proc.stdout!r}\n"
        )

    n_cpu = 200_000
    x, y, w = make_data(n_cpu, 123)
    cpu_sps = bench_reference_style_cpu(x, y, w, 16_384)

    if device_sps is None:
        # Device unreachable: still emit one JSON line so the driver
        # records something, but under a DIFFERENT metric name so a CPU
        # fallback can never be mistaken for a per-chip measurement.
        metric = "logreg_train_samples_per_sec_cpu_fallback"
        device_sps = cpu_sps
    else:
        metric = "logreg_train_samples_per_sec_per_chip"

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(device_sps, 1),
                "unit": "samples/sec",
                "vs_baseline": round(device_sps / cpu_sps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
