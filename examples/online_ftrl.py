"""Online learning: FTRL-proximal logistic regression over an unbounded
stream, warm-started from an offline model — the reference's
OnlineLogisticRegression workflow (continuous mini-batch updates with a
model version per update).

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/online_ftrl.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from flinkml_tpu.models import LogisticRegression, OnlineLogisticRegression
from flinkml_tpu.table import Table

rng = np.random.default_rng(1)
d = 16
true_coef = rng.normal(size=d)


def make_batch(n):
    x = rng.normal(size=(n, d))
    y = (x @ true_coef + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
    return Table({"features": x, "label": y})


# --- Offline warm start ---------------------------------------------------
offline_table = make_batch(2000)
offline = (
    LogisticRegression().set_seed(0).set_max_iter(100)
    .set_global_batch_size(2000).fit(offline_table)
)

# --- Online phase: one FTRL update per arriving batch ---------------------
online = (
    OnlineLogisticRegression()
    .set_alpha(0.1)
    .set_beta(1.0)
    .set_reg(0.001)
    .set_elastic_net(0.5)
    .set_initial_model_data(*offline.get_model_data())
)
stream = (make_batch(256) for _ in range(50))  # a live one-shot stream
model = online.fit_stream(stream)
print("model version after stream:", model.model_version)

# --- The refreshed model still predicts the concept -----------------------
test = make_batch(1000)
(out,) = model.transform(test)
acc = float(np.mean(out["prediction"] == test["label"]))
print(f"online-updated accuracy: {acc:.3f}")
assert acc > 0.9
