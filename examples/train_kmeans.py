"""KMeans example: k-means++ init, whole Lloyd loop in one XLA program,
save/load, cluster-quality check against sklearn.

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_kmeans.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import os
import tempfile

import numpy as np

from flinkml_tpu.models import KMeans, KMeansModel
from flinkml_tpu.table import Table

# --- Three well-separated blobs ------------------------------------------
rng = np.random.default_rng(7)
centers = np.array([[0.0, 0.0], [6.0, 6.0], [-6.0, 5.0]])
x = np.concatenate([c + rng.normal(scale=0.7, size=(400, 2)) for c in centers])
table = Table({"features": x})

# --- Fit: the entire Lloyd iteration is ONE device dispatch --------------
kmeans = (
    KMeans()
    .set_k(3)
    .set_max_iter(30)
    .set_seed(0)
    .set_init_mode("k-means++")
)
model = kmeans.fit(table)

(out,) = model.transform(table)
assign = np.asarray(out["prediction"])
print("cluster sizes:", np.bincount(assign.astype(int)))

# Each learned centroid should sit on one true blob center.
learned = np.sort(model.centroids, axis=0)
print("learned centroids (sorted):\n", np.round(learned, 2))

# --- sklearn agreement (adjusted Rand index = 1.0 on separated blobs) -----
try:
    from sklearn.cluster import KMeans as SkKMeans
    from sklearn.metrics import adjusted_rand_score

    sk = SkKMeans(n_clusters=3, n_init=5, random_state=0).fit(x)
    ari = adjusted_rand_score(sk.labels_, assign)
    print(f"adjusted Rand vs sklearn: {ari:.3f}")
except ImportError:
    pass

# --- Persist and reload --------------------------------------------------
with tempfile.TemporaryDirectory() as td:
    path = os.path.join(td, "kmeans_model")
    model.save(path)
    reloaded = KMeansModel.load(path)
    (again,) = reloaded.transform(table)
    assert np.array_equal(np.asarray(again["prediction"]), assign)
    print("save/load round-trip OK")
