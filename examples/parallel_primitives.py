"""The distributed primitives, used directly: mesh, AllReduce, broadcast,
keyed aggregation, mapPartition, host barrier — the building blocks every
estimator trains through (SURVEY.md §2.5's checklist), exposed for writing
custom distributed algorithms.

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/parallel_primitives.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from flinkml_tpu.parallel import DeviceMesh, host_barrier
from flinkml_tpu.parallel.broadcast_utils import (
    get_broadcast_variable,
    with_broadcast,
)
from flinkml_tpu.parallel.collectives import (
    all_reduce_sum,
    keyed_aggregate,
    map_partition,
)

mesh = DeviceMesh()  # 1-D "data" axis over every device
P = mesh.axis_size()
print(f"mesh: {P} devices on axis '{mesh.DATA_AXIS}'")

# --- AllReduce: per-device partial sums -> identical global sum -----------
# (replaces the reference's 3-hop chunked shuffle, AllReduceImpl.java:52)
parts = np.arange(P * 4, dtype=np.float64).reshape(P, 4)
total = np.asarray(all_reduce_sum(mesh, mesh.shard_batch(parts)))
np.testing.assert_array_equal(total, parts.sum(axis=0))
print("all_reduce_sum:", total)

# --- Broadcast variables: replicate a model to every device ---------------
# (replaces BroadcastUtils.withBroadcastStream / BroadcastContext; inside
# the function the variable is read by name, the reference's
# getBroadcastVariable idiom)
rows = np.arange(P * 8, dtype=np.float64).reshape(P * 8, 1)


def scorer(x_batch):
    model = get_broadcast_variable("model")
    return x_batch * model["bias"]


scored = with_broadcast(
    scorer, (rows,),
    broadcast_variables={"model": {"coef": np.ones(4), "bias": np.array(2.0)}},
    mesh=mesh,
)
np.testing.assert_array_equal(np.asarray(scored), rows * 2.0)
print("with_broadcast: ok")

# --- Keyed aggregation: segment-sum + psum (the keyBy + reduce analog) ----
values = np.ones((P * 8, 2))
keys = np.tile(np.arange(4), P * 2)
sums = np.asarray(keyed_aggregate(
    mesh, mesh.shard_batch(values), mesh.shard_batch(keys.astype(np.int32)),
    num_segments=4,
))
np.testing.assert_array_equal(sums, np.full((4, 2), 2.0 * P))
print("keyed_aggregate:", sums[:, 0])

# --- mapPartition: run a function once per shard --------------------------
data = np.arange(P * 8, dtype=np.float64)


def per_partition(shard):
    # Each device sees its local rows; emit a per-row normalized value.
    return shard - shard.mean()


centered = np.asarray(map_partition(mesh, per_partition, mesh.shard_batch(data)))
assert centered.shape == data.shape
print("map_partition: per-shard mean removed")

# --- Host barrier: all hosts rendezvous (multi-host control plane) --------
participants = host_barrier(mesh, tag=1)
print("host_barrier participants:", participants)
