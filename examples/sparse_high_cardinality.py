"""High-cardinality categorical features, end to end: sparse one-hot
encoding (SparseVector per row) into the nnz-bucketed sparse
LogisticRegression trainer. The dense one-hot layout would need
n x cardinality floats; everything here is O(nnz).

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/sparse_high_cardinality.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from flinkml_tpu.models import LogisticRegression, OneHotEncoder
from flinkml_tpu.pipeline import Pipeline
from flinkml_tpu.table import Table

CARDINALITY = 1_000_000
rng = np.random.default_rng(11)
n = 2000

categories = rng.integers(0, CARDINALITY, size=n).astype(np.float64)
categories[0] = CARDINALITY - 1  # pin the max so the fitted size is full
labels = (categories >= CARDINALITY // 2).astype(np.float64)
table = Table({"cat": categories, "label": labels})

dense_gib = n * CARDINALITY * 8 / 2**30
print(f"dense one-hot would be {dense_gib:,.0f} GiB; sparse is O(n)")

pipeline = Pipeline([
    OneHotEncoder()
    .set_input_cols(["cat"])
    .set_output_cols(["features"])
    .set_drop_last(False)
    .set_output_format("sparse"),   # reference SparseVector encoding
    LogisticRegression()
    .set_seed(0)
    .set_max_iter(200)
    .set_learning_rate(5.0)
    .set_global_batch_size(n),      # full batch: memorization regime
])
model = pipeline.fit(table)
(out,) = model.transform(table)
acc = float(np.mean(out["prediction"] == labels))
print(f"train accuracy at cardinality {CARDINALITY:,}: {acc:.3f}")
assert acc > 0.95

# -- the streamed variant: datasets LARGER THAN RAM at the same dim -------
# SparseVector feature streams cache and train AS CSR (O(nnz) disk/HBM —
# a densifying path would cache n x dim floats). Same estimator, same
# params; the input is an iterable of batch Tables instead of one Table.
from flinkml_tpu.linalg import Vectors

def sparse_batches(n_batches=4, rows=256):
    r = np.random.default_rng(7)
    for _ in range(n_batches):
        cats = r.integers(0, CARDINALITY, size=rows)
        vecs = np.array(
            [Vectors.sparse(CARDINALITY, [c], [1.0]) for c in cats],
            dtype=object,
        )
        y = (cats >= CARDINALITY // 2).astype(np.float64)
        yield Table({"features": vecs, "label": y})

streamed = (
    LogisticRegression()
    .set_seed(0).set_max_iter(30).set_learning_rate(5.0)
    .fit(sparse_batches())
)
coef = streamed.get_model_data()[0].column("coefficient")[0]
print(f"streamed sparse fit at cardinality {CARDINALITY:,}: "
      f"coef shape {np.asarray(coef).shape} (cache cost is O(nnz))")
