"""Topic modeling end to end: Tokenizer -> CountVectorizer -> LDA,
with topic descriptions mapped back through the fitted vocabulary.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/topic_modeling.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from flinkml_tpu import Pipeline
from flinkml_tpu.models import LDA, CountVectorizer, Tokenizer
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
sports = ["game", "team", "score", "coach", "season", "player"]
cooking = ["recipe", "oven", "flour", "butter", "sauce", "bake"]
travel = ["flight", "hotel", "beach", "passport", "luggage", "tour"]
docs = []
for _ in range(600):
    pool = [sports, cooking, travel][int(rng.integers(0, 3))]
    docs.append(" ".join(rng.choice(pool, size=12)))
t = Table({"text": np.asarray(docs)})

prep = Pipeline([
    Tokenizer().set_input_col("text").set_output_col("tok"),
    CountVectorizer().set_input_col("tok").set_output_col("features"),
]).fit(t)
(tf,) = prep.transform(t)
vocab = prep.stages[1].vocabulary

lda = LDA().set_k(3).set_max_iter(30).set_seed(0).fit(tf)
desc = lda.describe_topics(4)
for r in range(3):
    words = [vocab[i] for i in desc["termIndices"][r]]
    weights = np.round(desc["termWeights"][r], 3)
    print(f"topic {r}: {list(zip(words, weights))}")

(out,) = lda.transform(tf)
print("doc 0 mixture:", np.round(out["topicDistribution"][0], 3))
