"""Topic modeling end to end: Tokenizer -> CountVectorizer -> LDA,
with topic descriptions mapped back through the fitted vocabulary.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/topic_modeling.py
"""

import numpy as np

from flinkml_tpu import Pipeline
from flinkml_tpu.models import LDA, CountVectorizer, Tokenizer
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
sports = ["game", "team", "score", "coach", "season", "player"]
cooking = ["recipe", "oven", "flour", "butter", "sauce", "bake"]
travel = ["flight", "hotel", "beach", "passport", "luggage", "tour"]
docs = []
for _ in range(600):
    pool = [sports, cooking, travel][int(rng.integers(0, 3))]
    docs.append(" ".join(rng.choice(pool, size=12)))
t = Table({"text": np.asarray(docs)})

prep = Pipeline([
    Tokenizer().set_input_col("text").set_output_col("tok"),
    CountVectorizer().set_input_col("tok").set_output_col("features"),
]).fit(t)
(tf,) = prep.transform(t)
vocab = prep.stages[1].vocabulary

lda = LDA().set_k(3).set_max_iter(30).set_seed(0).fit(tf)
desc = lda.describe_topics(4)
for r in range(3):
    words = [vocab[i] for i in desc["termIndices"][r]]
    weights = np.round(desc["termWeights"][r], 3)
    print(f"topic {r}: {list(zip(words, weights))}")

(out,) = lda.transform(tf)
print("doc 0 mixture:", np.round(out["topicDistribution"][0], 3))
