"""Multi-host pod training recipe.

The production shape of distributed training with this framework — the
round-3 promotion of the test-only worker (``tests/_dist_worker.py``)
into a user-facing example (VERDICT r2 item 7). The reference's analog
is its MiniCluster system tests driving the JobManager-resident
``SharedProgressAligner`` (``SharedProgressAligner.java:127-158``); here
the control plane is ``jax.distributed`` over DCN and the data plane is
XLA collectives.

On a real pod, run ONE copy of this script per host:

    JAX_COORDINATOR_ADDRESS=<host0>:8476 \
    JAX_NUM_PROCESSES=<hosts> \
    JAX_PROCESS_ID=<this host's index> \
    python multihost_pod.py

(On Cloud TPU pod slices `jax.distributed.initialize()` can autodetect
all three — the env vars are the explicit/portable form.)

The recipe, per host:

  1. **Join the pod**: ``init_distributed()`` reads the env vars and
     joins the coordination service; a no-op single-process, so the same
     script runs anywhere.
  2. **Global mesh**: ``DeviceMesh()`` spans every device of every host.
  3. **Ingest a slice**: ``process_slice(n)`` gives this host's
     contiguous rows; ``mesh.global_batch(local_rows)`` assembles the
     global sharded array from each host's local shard — no host ever
     materializes the full dataset.
  4. **Train**: the jitted SGD step runs SPMD — gradients ``psum`` over
     ICI within a host and DCN across hosts, placed by the compiler.
     Every host computes identical replicated coefficients (the
     reference needed head/tail/alignment RPC for this lockstep; SPMD
     gives it by construction).
  5. **Checkpoint with commit ordering**: every host syncs at a
     ``host_barrier`` before process 0 commits the manifest, then a
     second barrier publishes it — the two-phase commit the reference
     delegates to Flink's checkpoint coordinator.

Run ``python multihost_pod.py --local-demo`` to see the whole flow as a
2-process Gloo pod on localhost CPU (exactly how ``tests/test_examples
_multihost.py`` runs it in CI).
"""

import json
import os
import sys
import tempfile

import numpy as np


def worker(workdir: str) -> None:
    import jax

    from flinkml_tpu.iteration.checkpoint import CheckpointManager
    from flinkml_tpu.parallel import (
        DeviceMesh,
        host_barrier,
        init_distributed,
        process_slice,
        synced_loop,
    )

    def log(msg):
        print(f"[worker {os.environ.get('JAX_PROCESS_ID', '?')}] {msg}",
              flush=True)

    # 1. Join the pod (env-var driven; no-op when single-process).
    pid, nproc = init_distributed()
    log(f"joined pod ({pid}/{nproc})")

    # 2. Global mesh over every host's devices.
    mesh = DeviceMesh()
    log(f"mesh over {mesh.num_devices} devices")

    # 3. Each host ingests ONLY its slice of the (here: synthetic) dataset.
    n_global, dim = 4096, 16
    rng = np.random.default_rng(0)
    true_coef = rng.normal(size=dim).astype(np.float32)
    sl = process_slice(n_global)
    # Per-host deterministic generation of just this host's rows — a real
    # pipeline would read files/shards assigned by the same slice.
    row_rng = np.random.default_rng(1234)
    x_all = row_rng.normal(size=(n_global, dim)).astype(np.float32)
    x_local = x_all[sl]
    y_local = (x_local @ true_coef > 0).astype(np.float32)

    # Assemble the global sharded batch from per-host local rows.
    xg = mesh.global_batch(x_local)
    yg = mesh.global_batch(y_local)
    log("global batch assembled")

    # 4. SPMD logistic-SGD step: grad psum rides ICI + DCN automatically.
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = DeviceMesh.DATA_AXIS

    def step(coef, xb, yb, lr):
        margins = xb @ coef
        mult = jax.nn.sigmoid(margins) - yb
        grad = jax.lax.psum(xb.T @ mult, axis)
        count = jax.lax.psum(jnp.asarray(xb.shape[0], jnp.float32), axis)
        return coef - (lr / count) * grad

    stepper = jax.jit(jax.shard_map(
        step, mesh=mesh.mesh,
        in_specs=(P(), P(axis), P(axis), P()),
        out_specs=P(),
    ))

    coef = jnp.zeros(dim, jnp.float32)
    lr = jnp.asarray(1.0, jnp.float32)

    # synced_loop bounds in-flight cross-process dispatches (the framework's
    # backpressure policy — see flinkml_tpu.parallel.dispatch): a bare
    # `for` loop that enqueues all 60 collective steps without host sync
    # wedges the multi-process backend permanently.
    def one_step(c, i):
        c = stepper(c, xg, yg, lr)
        if i == 0:
            log("first step compiled + ran")
        return c

    coef = synced_loop(60, one_step, coef)
    coef_host = np.asarray(coef)
    log("training done")

    # Replicated lockstep check: every host holds identical coefficients.
    acc = float(np.mean((x_local @ coef_host > 0) == y_local))
    assert acc > 0.9, f"host {pid}: failed to learn (acc={acc})"

    # 4b. The estimator catalog trains the same way (round 4: EVERY
    # streamed and online fit accepts per-process stream partitions —
    # agreed SPMD schedules, vocabulary/moment agreements through the
    # device fabric, failure agreement instead of hangs). One example of
    # each flavor on this pod:
    from flinkml_tpu.models.kmeans import train_kmeans_stream
    from flinkml_tpu.models.online_logistic_regression import (
        OnlineLogisticRegression,
    )
    from flinkml_tpu.table import Table

    cents = train_kmeans_stream(
        iter({"x": x_local[s : s + 64]} for s in range(0, len(x_local), 64)),
        k=4, mesh=mesh, max_iter=3, seed=0,
    )
    assert np.isfinite(cents).all()
    log("streamed KMeans over per-host partitions done")
    olr_model = OnlineLogisticRegression(mesh=mesh).fit_stream(iter(
        Table({"features": x_local[s : s + 64],
               "label": y_local[s : s + 64].astype(np.float64)})
        for s in range(0, len(x_local), 64)
    ))
    assert np.isfinite(olr_model.coefficient).all()
    log("online FTRL over per-host streams done")

    # 5. Barrier-ordered checkpoint commit (two-phase: shards → barrier →
    # manifest by host 0 → barrier → visible everywhere).
    shard_path = os.path.join(workdir, f"coef-shard-{pid}.npy")
    np.save(shard_path, coef_host)
    log("shard written; entering barrier 1")
    host_barrier(mesh, tag=1)
    log("barrier 1 passed")
    manifest = os.path.join(workdir, "manifest.json")
    if pid == 0:
        mgr = CheckpointManager(
            os.path.join(workdir, "ckpt"), world_size=mesh.num_devices
        )
        mgr.save({"coef": coef_host}, epoch=60)
        tmp = manifest + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": 60, "hosts": nproc}, f)
        os.replace(tmp, manifest)
    host_barrier(mesh, tag=2)
    assert os.path.exists(manifest), "commit must be visible after barrier"
    print(f"POD_OK host={pid}/{nproc} devices={mesh.num_devices} "
          f"acc={acc:.3f}", flush=True)


def _local_demo() -> None:
    """Spawn a 2-process localhost pod (Gloo over CPU) running worker()."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    workdir = tempfile.mkdtemp(prefix="multihost-pod-")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", workdir],
            env=env,
        ))
    try:
        codes = [p.wait(timeout=600) for p in procs]
    finally:
        # Never leak workers: a timeout/interrupt must not leave the pair
        # parked on a barrier holding the rendezvous port.
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(codes):
        raise SystemExit(f"worker exit codes: {codes}")
    print("LOCAL DEMO OK (2 hosts x 2 devices)")


if __name__ == "__main__":
    # Runnable standalone from any cwd (including the spawned --worker
    # subprocesses, whose sys.path[0] is examples/): put the repo root on
    # sys.path when flinkml_tpu isn't already importable.
    try:
        import flinkml_tpu  # noqa: F401
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    if "--worker" in sys.argv:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            jax.config.update("jax_platforms", "cpu")
        worker(sys.argv[sys.argv.index("--worker") + 1])
    elif "--local-demo" in sys.argv:
        _local_demo()
    else:
        worker(tempfile.mkdtemp(prefix="multihost-pod-"))
