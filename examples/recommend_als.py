"""ALS recommendation end to end: synthetic taste clusters -> implicit
ALS -> top-k recommendations + explicit-mode rating prediction.

Run: PYTHONPATH=. python examples/recommend_als.py
(CPU mesh: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from flinkml_tpu.models import ALS, RegressionEvaluator
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)

# -- explicit ratings from a low-rank taste model ---------------------------
n_users, n_items, rank = 100, 80, 5
u = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
v = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
full = 3.0 + 1.5 * (u @ v.T)
mask = rng.uniform(size=full.shape) < 0.3
users, items = np.nonzero(mask)
ratings = full[users, items] + 0.05 * rng.normal(size=len(users))
train = Table({"user": users, "item": items, "rating": ratings})

model = (
    ALS().set_rank(8).set_max_iter(12).set_reg_param(0.05).set_seed(0)
    .fit(train)
)
(scored,) = model.transform(train)
(metrics,) = (
    RegressionEvaluator().set_label_col("rating")
    .set_metrics_names(["rmse"]).transform(scored)
)
print(f"explicit ALS in-sample RMSE: {metrics['rmse'][0]:.4f}")

# -- implicit feedback: click counts -> top-k recommendations ---------------
clicks_u, clicks_i, counts = [], [], []
for usr in range(n_users):
    liked = np.argsort(-full[usr])[:10]          # true taste
    for it in rng.choice(liked, size=6):
        clicks_u.append(usr)
        clicks_i.append(it)
        counts.append(float(rng.integers(1, 8)))
implicit_train = Table({
    "user": np.asarray(clicks_u), "item": np.asarray(clicks_i),
    "rating": np.asarray(counts),
})
imp = (
    ALS().set_rank(8).set_max_iter(10).set_reg_param(0.1)
    .set_implicit_prefs(True).set_alpha(10.0).set_seed(0)
    .fit(implicit_train)
)
rec_items, rec_scores = imp.recommend_for_all_users(5)
hit = np.mean([
    len(set(rec_items[usr]) & set(np.argsort(-full[usr])[:10])) / 5
    for usr in range(n_users)
])
print(f"implicit ALS top-5 hit rate vs true taste: {hit:.2f}")
