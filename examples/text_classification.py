"""Text classification end to end: tokenize -> stop words -> TF-IDF ->
sparse LogisticRegression, all in one Pipeline, with cross-validated
vocabulary pruning.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/text_classification.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from flinkml_tpu import CrossValidator, ParamGridBuilder, Pipeline
from flinkml_tpu.models import (
    BinaryClassificationEvaluator,
    CountVectorizer,
    IDF,
    LogisticRegression,
    StopWordsRemover,
    Tokenizer,
)
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
pos = ["great", "superb", "loved", "excellent", "wonderful"]
neg = ["awful", "boring", "hated", "terrible", "dreadful"]
filler = ["the", "movie", "was", "and", "a", "it", "film", "plot"]
docs, labels = [], []
for _ in range(400):
    y = int(rng.integers(0, 2))
    words = list(rng.choice(pos if y else neg, 3)) + list(
        rng.choice(filler, 6))
    rng.shuffle(words)
    docs.append(" ".join(words))
    labels.append(float(y))
data = Table({"text": np.asarray(docs), "label": np.asarray(labels)})

cv_stage = CountVectorizer().set_input_col("clean").set_output_col("tf")
pipe = Pipeline([
    Tokenizer().set_input_col("text").set_output_col("tok"),
    StopWordsRemover().set_input_cols(["tok"]).set_output_cols(["clean"]),
    cv_stage,
    IDF().set_input_col("tf").set_output_col("features"),
    LogisticRegression().set_max_iter(60).set_global_batch_size(512)
    .set_learning_rate(1.0).set_seed(0),
])

# minDF as a fraction: 0.45 requires terms in 45% of documents, which
# drops the (class-specific, ~30%-frequency) sentiment words and keeps
# only filler — cross-validation must catch that over-pruning.
grid = (
    ParamGridBuilder()
    .add_grid(cv_stage, CountVectorizer.MIN_DF, [1.0, 0.45])
    .build()
)
tuner = CrossValidator(pipe, grid, BinaryClassificationEvaluator())
tuner.set_num_folds(3).set_seed(0)
model = tuner.fit(data)
(pred,) = model.transform(data)
acc = (pred["prediction"] == data["label"]).mean()
print(f"best grid point: {model.param_maps_description[model.best_index]}")
print(f"cv AUCs: {[round(m, 4) for m in model.avg_metrics]}")
print(f"in-sample accuracy: {acc:.3f}")
