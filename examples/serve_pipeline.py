"""End-to-end online serving: fit → publish v1 → serve concurrent clients
→ publish v2+ from a STILL-RUNNING unbounded training stream → hot-swap
with zero dropped or mis-versioned responses and zero steady-state
retraces (guard-verified).

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/serve_pipeline.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import functools
import tempfile
import threading

import numpy as np

from flinkml_tpu.analysis.guard import TransferRetraceGuard
from flinkml_tpu.models import KMeans, KMeansModel, StandardScaler
from flinkml_tpu.models.kmeans import train_kmeans_stream
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.pipeline import Pipeline, PipelineModel
from flinkml_tpu.serving import (
    ModelRegistry,
    ServingConfig,
    ServingEngine,
    SnapshotPublisher,
)
from flinkml_tpu.table import Table

# --- Synthesize clustered data -------------------------------------------
rng = np.random.default_rng(0)
n, d, k = 4_000, 8, 4
x = rng.normal(size=(n, d)) + rng.integers(0, k, size=(n, 1)) * 3.0
train = Table({"features": x})

# --- Fit v1: scale → cluster (both stages fuse into one XLA program) -----
pipe = Pipeline([
    StandardScaler().set(StandardScaler.INPUT_COL, "features")
                    .set(StandardScaler.OUTPUT_COL, "scaled"),
    KMeans().set(KMeans.FEATURES_COL, "scaled").set(KMeans.K, k)
            .set(KMeans.MAX_ITER, 3).set(KMeans.SEED, 7),
])
model_v1 = pipe.fit(train)
scaler = model_v1.stages[0]

# --- Publish v1 into a versioned registry --------------------------------
registry = ModelRegistry(tempfile.mkdtemp(prefix="flinkml_registry_"))
v1 = registry.publish(model_v1)
print(f"published v{v1}; registry versions: {registry.versions()}")

# --- Serve: engine warms every row bucket at load, then follows the
# registry (each publish hot-swaps with zero downtime) --------------------
engine = ServingEngine(
    registry,
    example=Table({"features": x[:4]}),
    config=ServingConfig(max_batch_rows=64, max_wait_ms=1.0),
    output_cols=("prediction",),
    name="example",
).start().follow_registry()


@functools.lru_cache(maxsize=16)
def reference_model(version):
    """The fingerprint-verified registry copy of a version (for parity)."""
    return registry.get(version)[1]


stop = threading.Event()
errors, versions_seen = [], set()
completed = [0] * 6


def client(tid):
    crng = np.random.default_rng(tid)
    try:
        while not stop.is_set():
            rows = int(crng.integers(1, 9))
            lo = int(crng.integers(0, n - rows))
            req = x[lo:lo + rows]
            resp = engine.predict({"features": req})
            versions_seen.add(resp.version)
            # Bitwise parity against the version that claims the response.
            (ref,) = reference_model(resp.version).transform(
                Table({"features": req})
            )
            np.testing.assert_array_equal(
                ref.column("prediction"), resp.column("prediction")
            )
            completed[tid] += 1
    except BaseException as e:  # noqa: BLE001 — reported by the main thread
        errors.append(e)


# --- Mid-stream publication: an unbounded Lloyd loop emits a versioned
# snapshot every 3 epochs WITHOUT stopping; the engine swaps live --------
(scaled_train,) = scaler.transform(train)
sx = np.asarray(scaled_train.column("scaled"), np.float32)
stream_batches = [{"x": sx[i::8]} for i in range(8)]


def make_model(centroids):
    m = KMeansModel().set(KMeansModel.FEATURES_COL, "scaled") \
                     .set(KMeansModel.K, k)
    m.set_model_data(
        Table({"centroids": np.asarray(centroids, np.float64)[None]})
    )
    return PipelineModel([scaler, m])


publisher = SnapshotPublisher(registry, make_model, every_n_epochs=3)

# Steady state must be retrace-free: after the engine's load-time warmup,
# client traffic AND hot swaps compile nothing (same-shape model data
# reuses the compiled programs — constants are traced arguments).
with TransferRetraceGuard(allow_compiles=0, location="serve_pipeline"):
    threads = [threading.Thread(target=client, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    final_centroids = train_kmeans_stream(
        stream_batches, k=k, mesh=DeviceMesh(), max_iter=9, seed=7,
        listeners=[publisher],
    )
    stop.set()
    for t in threads:
        t.join(timeout=120)

assert not any(t.is_alive() for t in threads), "client threads hung"
assert not errors, errors[:3]
assert len(versions_seen) >= 2, (
    f"clients never observed a hot swap: {versions_seen}"
)
print(f"mid-stream published versions: {[v for _, v in publisher.published]}")
print(f"clients served {sum(completed)} requests across model versions "
      f"{sorted(versions_seen)} — zero dropped, zero mis-versioned, "
      "zero steady-state retraces")

stats = engine.stats()
print(f"p50={stats['gauges']['p50_ms']:.2f}ms "
      f"p99={stats['gauges']['p99_ms']:.2f}ms "
      f"batches={stats['counters']['batches']:.0f} "
      f"avg_occupancy="
      f"{stats['counters']['batch_rows'] / stats['counters']['batch_padded_rows']:.2f}")
engine.stop()
assert registry.current_version() == registry.versions()[-1]
print("serving example OK")
