"""Five clustering algorithms on two datasets that tell them apart:
blobs (everyone succeeds) and concentric rings (only affinity-based
clustering can).

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/clustering_tour.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np
from sklearn.metrics import adjusted_rand_score

from flinkml_tpu.models import (
    AgglomerativeClustering,
    BisectingKMeans,
    GaussianMixture,
    KMeans,
    PowerIterationClustering,
)
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)

# -- dataset 1: three gaussian blobs ----------------------------------------
x_blobs = np.concatenate([
    rng.normal(size=(80, 2)) * 0.5 + c for c in ([0, 0], [5, 0], [0, 5])
])
y_blobs = np.repeat([0, 1, 2], 80)
t_blobs = Table({"features": x_blobs})

results = {}
(km,) = KMeans().set_k(3).set_init_mode("k-means++").set_seed(0).fit(
    t_blobs).transform(t_blobs)
results["KMeans"] = adjusted_rand_score(y_blobs, km["prediction"])
(bk,) = BisectingKMeans().set_k(3).set_seed(0).fit(t_blobs).transform(t_blobs)
results["BisectingKMeans"] = adjusted_rand_score(y_blobs, bk["prediction"])
(gm,) = GaussianMixture().set_k(3).set_seed(0).set_max_iter(80).fit(
    t_blobs).transform(t_blobs)
results["GaussianMixture"] = adjusted_rand_score(y_blobs, gm["prediction"])
(ag,) = AgglomerativeClustering().set_num_clusters(3).transform(t_blobs)
results["Agglomerative"] = adjusted_rand_score(y_blobs, ag["prediction"])
print("blobs:", {k: round(v, 3) for k, v in results.items()})

# -- dataset 2: concentric rings --------------------------------------------
theta = rng.uniform(0, 2 * np.pi, 200)
r = np.concatenate([np.full(100, 1.0), np.full(100, 4.0)])
r += 0.1 * rng.normal(size=200)
x_rings = np.stack([r * np.cos(theta), r * np.sin(theta)], axis=1)
y_rings = np.repeat([0, 1], 100)

(km2,) = KMeans().set_k(2).set_seed(0).fit(
    Table({"features": x_rings})).transform(Table({"features": x_rings}))
km2_ari = adjusted_rand_score(y_rings, km2["prediction"])

# kNN affinity graph for PIC.
d2 = ((x_rings[:, None] - x_rings[None]) ** 2).sum(-1)
np.fill_diagonal(d2, np.inf)
knn = np.argsort(d2, axis=1)[:, :8]
src = np.repeat(np.arange(200), 8)
dst = knn.ravel()
edges = Table({"src": src, "dst": dst,
               "w": np.exp(-d2[src, dst] / 0.5)})
(pic,) = (
    PowerIterationClustering().set_k(2).set_max_iter(50)
    .set_weight_col("w").set_seed(0).transform(edges)
)
order = np.argsort(pic["id"])
pic_ari = adjusted_rand_score(y_rings, pic["prediction"][order])
print(f"rings: KMeans ARI={km2_ari:.3f}  PIC ARI={pic_ari:.3f}  "
      "(affinity clustering handles non-convex shapes)")
