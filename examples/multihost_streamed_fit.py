"""Multi-process streamed (out-of-core) training across a pod.

Round-4 capability: the streamed fits (linear family, KMeans,
GaussianMixture, MLP/FM) train across a multi-process mesh from
PER-PROCESS stream partitions — the reference's per-subtask stream
partitions (`ReplayOperator.java:62-250` replays each subtask's cached
partition), without any single host ever holding the global dataset.

Each host feeds only its own batches; `iteration/stream_sync.py` agrees
the SPMD schedule (fixed batch height, per-epoch step count — short
hosts dispatch zero-weight dummy steps), pools init samples across
hosts, and commits checkpoints rank-0-write + barrier. The fitted model
is replicated and bit-identical on every host.

Run on a real pod (once per host, standard launcher env vars):

    JAX_COORDINATOR_ADDRESS=<host0>:8476 \
    JAX_NUM_PROCESSES=<hosts> \
    JAX_PROCESS_ID=<this host> \
    python multihost_streamed_fit.py --worker <shared-dir>

or as a self-contained 2-process localhost demo (CPU devices):

    python multihost_streamed_fit.py --local-demo
"""

import os
import sys
import tempfile


def worker(workdir: str) -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flinkml_tpu.models import KMeans, LogisticRegression
    from flinkml_tpu.parallel import (
        DeviceMesh,
        init_distributed,
        process_slice,
    )
    from flinkml_tpu.table import Table

    pid, nproc = init_distributed()
    mesh = DeviceMesh()
    print(f"[proc {pid}] {jax.local_device_count()} local / "
          f"{jax.device_count()} global devices")

    # A "too big for one host" dataset: this host materializes ONLY its
    # process_slice, as a stream of batch Tables (in production: read
    # your shard of files and yield batches).
    n, d = 100_000, 16
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=d).astype(np.float32)
    sl = process_slice(n)
    my_batches = []
    for start in range(sl.start, sl.stop, 8192):
        rows = min(8192, sl.stop - start)
        r = np.random.default_rng(1000 + start)  # seeded by global offset
        x = r.normal(size=(rows, d)).astype(np.float32)
        y = (x @ w_true > 0).astype(np.float32)
        my_batches.append(Table({"features": x, "label": y}))

    model = (
        LogisticRegression(mesh=mesh)
        .set_max_iter(20).set_learning_rate(0.5).set_reg(1e-4)
        .fit(iter(my_batches))
    )
    coef = np.asarray(model.get_model_data()[0].column("coefficient"))
    # Direction recovery (labels are noiseless): cosine with the truth.
    cos = float(
        coef @ w_true / (np.linalg.norm(coef) * np.linalg.norm(w_true))
    )
    print(f"[proc {pid}] LR cosine(coef, w_true) = {cos:.4f}")
    assert cos > 0.95, cos

    km = (
        KMeans(mesh=mesh).set_k(8).set_max_iter(10).set_seed(3)
        .fit(iter(
            Table({"features": t.column("features")}) for t in my_batches
        ))
    )
    cents = np.asarray(km.get_model_data()[0].column("centroids"))
    print(f"[proc {pid}] KMeans centroids {cents.shape}, "
          f"norm {np.linalg.norm(cents):.3f}")

    np.save(os.path.join(workdir, f"coef_{pid}.npy"), coef)
    print(f"[proc {pid}] done")


def _local_demo() -> None:
    """Spawn a 2-process localhost pod (Gloo over CPU) running worker()."""
    import socket
    import subprocess

    import numpy as np

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    workdir = tempfile.mkdtemp(prefix="multihost-stream-")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", workdir],
            env=env,
        ))
    try:
        codes = [p.wait(timeout=600) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert codes == [0, 0], codes
    a = np.load(os.path.join(workdir, "coef_0.npy"))
    b = np.load(os.path.join(workdir, "coef_1.npy"))
    assert np.array_equal(a, b)
    print("local demo OK: both hosts fitted the identical model from "
          "disjoint stream partitions")


if __name__ == "__main__":
    # Standalone-runnable (python examples/multihost_streamed_fit.py):
    # worker subprocesses get sys.path[0]=examples/, so put the repo root
    # on sys.path when flinkml_tpu isn't already importable.
    try:
        import flinkml_tpu  # noqa: F401
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    if "--worker" in sys.argv:
        worker(sys.argv[sys.argv.index("--worker") + 1])
    else:
        _local_demo()
