"""End-to-end example: CSV → pipeline (assemble + scale + LR) → evaluate →
save/load. Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_logistic_regression.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import os
import tempfile

import numpy as np

from flinkml_tpu.io import read_csv_table
from flinkml_tpu.models import (
    BinaryClassificationEvaluator,
    LogisticRegression,
    StandardScaler,
    VectorAssembler,
)
from flinkml_tpu.pipeline import Pipeline, PipelineModel
from flinkml_tpu.table import Table

# --- Synthesize a CSV (stand-in for your data file) ----------------------
rng = np.random.default_rng(0)
n, d = 5000, 12
x = rng.normal(size=(n, d))
y = (x @ rng.normal(size=d) + 0.3 * rng.normal(size=n) > 0).astype(int)
header = ",".join([f"f{i}" for i in range(d)] + ["label"])
rows = "\n".join(
    ",".join(f"{v:.6g}" for v in row) + f",{lab}" for row, lab in zip(x, y)
)
csv_path = os.path.join(tempfile.gettempdir(), "example_train.csv")
with open(csv_path, "w") as f:
    f.write(header + "\n" + rows + "\n")

# --- Ingest (native multithreaded parser) --------------------------------
table = read_csv_table(csv_path)

# --- Pipeline: assemble feature columns → standardize → train ------------
pipe = Pipeline([
    VectorAssembler().set_input_cols([f"f{i}" for i in range(d)])
                     .set(VectorAssembler.OUTPUT_COL, "input"),
    StandardScaler(),
    LogisticRegression().set_features_col("output").set_label_col("label")
                        .set_max_iter(100).set_learning_rate(0.5)
                        .set_global_batch_size(4096).set_reg(0.01)
                        .set_seed(42),
])
model = pipe.fit(table)

# --- Score + evaluate ----------------------------------------------------
(scored,) = model.transform(table)
(metrics,) = (
    BinaryClassificationEvaluator()
    .set(BinaryClassificationEvaluator.METRICS_NAMES,
         ["areaUnderROC", "accuracy"])
    .transform(scored)
)
print("AUC:", float(metrics.column("areaUnderROC")[0]))
print("accuracy:", float(metrics.column("accuracy")[0]))

# --- Persist and reload --------------------------------------------------
with tempfile.TemporaryDirectory() as td:
    path = os.path.join(td, "model")
    model.save(path)
    reloaded = PipelineModel.load(path)
    (rescored,) = reloaded.transform(table)
    assert np.array_equal(
        np.asarray(rescored.column("prediction")),
        np.asarray(scored.column("prediction")),
    )
    print("save/load round-trip OK")
