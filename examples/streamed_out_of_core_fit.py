"""Out-of-core training: fit from a one-shot stream of batches with a
memory budget — epoch 0 trains while caching (spilling past the budget to
disk segments), later epochs replay the cache through a prefetching device
feed. The ReplayOperator/DataCache workflow of the reference, as a fit
path.

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/streamed_out_of_core_fit.py
"""

import tempfile

import numpy as np

from flinkml_tpu.models import LogisticRegression
from flinkml_tpu.table import Table

rng = np.random.default_rng(5)
d = 24
true_coef = rng.normal(size=d)


def batch_stream(n_batches, rows_each):
    """A one-shot generator — the data does NOT fit in memory at once."""
    for _ in range(n_batches):
        x = rng.normal(size=(rows_each, d)).astype(np.float32)
        y = (x @ true_coef > 0).astype(np.float32)
        yield Table({"features": x, "label": y})


with tempfile.TemporaryDirectory() as cache_dir:
    lr = LogisticRegression(
        cache_dir=cache_dir,
        # Tiny budget on purpose: most batches spill to disk segments.
        cache_memory_budget_bytes=256 * 1024,
    ).set_max_iter(20).set_learning_rate(0.5).set_tol(0.0)

    # fit() with an iterable streams: epoch 0 caches + trains, epochs
    # 1..19 replay the (mostly on-disk) cache.
    model = lr.fit(batch_stream(n_batches=40, rows_each=512))

    # Score a fresh sample.
    x = rng.normal(size=(2048, d)).astype(np.float32)
    y = (x @ true_coef > 0).astype(np.float32)
    (out,) = model.transform(Table({"features": x, "label": y}))
    acc = float(np.mean(out["prediction"] == y))
    print(f"held-out accuracy after out-of-core fit: {acc:.3f}")
    assert acc > 0.95
