"""Out-of-core training: fit from a one-shot stream of batches with a
memory budget — epoch 0 trains while caching (spilling past the budget to
disk segments), later epochs replay the cache through a prefetching device
feed. The ReplayOperator/DataCache workflow of the reference, as a fit
path.

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/streamed_out_of_core_fit.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import tempfile

import numpy as np

from flinkml_tpu.models import LogisticRegression
from flinkml_tpu.table import Table

rng = np.random.default_rng(5)
d = 24
true_coef = rng.normal(size=d)


def batch_stream(n_batches, rows_each):
    """A one-shot generator — the data does NOT fit in memory at once."""
    for _ in range(n_batches):
        x = rng.normal(size=(rows_each, d)).astype(np.float32)
        y = (x @ true_coef > 0).astype(np.float32)
        yield Table({"features": x, "label": y})


with tempfile.TemporaryDirectory() as cache_dir:
    lr = LogisticRegression(
        cache_dir=cache_dir,
        # Tiny budget on purpose: most batches spill to disk segments.
        cache_memory_budget_bytes=256 * 1024,
    ).set_max_iter(20).set_learning_rate(0.5).set_tol(0.0)

    # fit() with an iterable streams: epoch 0 caches + trains, epochs
    # 1..19 replay the (mostly on-disk) cache.
    model = lr.fit(batch_stream(n_batches=40, rows_each=512))

    # Score a fresh sample.
    x = rng.normal(size=(2048, d)).astype(np.float32)
    y = (x @ true_coef > 0).astype(np.float32)
    (out,) = model.transform(Table({"features": x, "label": y}))
    acc = float(np.mean(out["prediction"] == y))
    print(f"held-out accuracy after out-of-core fit: {acc:.3f}")
    assert acc > 0.95

# Every streamed estimator follows the same pattern — the out-of-core
# path is a FRAMEWORK guarantee, not a per-family feature (round 4):
# LogisticRegression/LinearSVC/LinearRegression, KMeans, GaussianMixture,
# GBTClassifier/GBTRegressor, ALS, LDA, Word2Vec, MLPClassifier/
# MLPRegressor (and PCA, which needs only one accumulation pass). A taste
# of the recommendation family on the same cache discipline:
from flinkml_tpu.models.als import ALS  # noqa: E402

with tempfile.TemporaryDirectory() as cache_dir:
    n_users, n_items, rank = 60, 40, 3
    uf = rng.normal(size=(n_users, rank))
    vf = rng.normal(size=(n_items, rank))

    def rating_stream(n_batches, rows_each):
        for _ in range(n_batches):
            u = rng.integers(0, n_users, rows_each)
            i = rng.integers(0, n_items, rows_each)
            yield Table({
                "user": u, "item": i,
                "rating": np.einsum("nk,nk->n", uf[u], vf[i])
                .astype(np.float32),
            })

    als_model = (
        ALS(cache_dir=cache_dir, cache_memory_budget_bytes=256 * 1024)
        .set_rank(4).set_max_iter(8).set_reg_param(0.05).set_seed(0)
        .fit(rating_stream(n_batches=12, rows_each=512))
    )
    u = rng.integers(0, n_users, 1024)
    i = rng.integers(0, n_items, 1024)
    (pred,) = als_model.transform(Table({"user": u, "item": i}))
    rmse = float(np.sqrt(np.mean(
        (pred["prediction"] - np.einsum("nk,nk->n", uf[u], vf[i])) ** 2
    )))
    print(f"ALS streamed-fit RMSE vs ground-truth factors: {rmse:.3f}")
    assert rmse < 0.3
