"""Gradient-boosted trees on a nonlinear task a linear model cannot fit.

Run: PYTHONPATH=. python examples/gbt_nonlinear.py
(CPU mesh: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from flinkml_tpu.models import (
    BinaryClassificationEvaluator,
    GBTClassifier,
    LogisticRegression,
    RandomSplitter,
)
from flinkml_tpu.table import Table

rng = np.random.default_rng(0)
n = 4000
x = rng.uniform(-2, 2, size=(n, 6))
# XOR-of-signs interaction + a sinusoid: zero linear signal.
logits = 3.0 * (x[:, 0] * x[:, 1] > 0) - 1.5 + np.sin(3 * x[:, 2])
y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
data = Table({"features": x, "label": y})
train, test = RandomSplitter().set_weights([0.8, 0.2]).set_seed(0).transform(data)

gbt = (
    GBTClassifier().set_num_trees(40).set_max_depth(4)
    .set_learning_rate(0.2).set_seed(0)
)
model = gbt.fit(train)
(pred,) = model.transform(test)

lr = (
    LogisticRegression().set_max_iter(60).set_global_batch_size(1024)
    .set_learning_rate(1.0).set_seed(0)
)
(lr_pred,) = lr.fit(train).transform(test)

ev = BinaryClassificationEvaluator().set_metrics_names(["areaUnderROC"])
(gbt_auc,) = ev.transform(pred)
(lr_auc,) = ev.transform(lr_pred)
print(f"GBT holdout AUC: {gbt_auc['areaUnderROC'][0]:.3f}   "
      f"(linear baseline: {lr_auc['areaUnderROC'][0]:.3f})")
