"""Fault-tolerant training: chunked device-loop checkpointing + exact resume.

The trainer runs K epochs per device dispatch and snapshots the carry
(coefficient, epoch, loss) between dispatches; a crash loses at most one
chunk, and the resumed run re-enters the SAME compiled executable, so the
final model is bit-identical to an uninterrupted run.

Runs on TPU, or on a virtual CPU mesh with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/checkpoint_resume.py
"""

# Runnable standalone from any cwd: put the repo root on sys.path when
# flinkml_tpu isn't already importable (pip-installed or PYTHONPATH set).
import os as _os
import sys as _sys

try:
    import flinkml_tpu  # noqa: F401
except ImportError:
    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )

# Honor JAX_PLATFORMS even on images whose TPU plugin overrides it at
# import time (the documented CPU-mesh invocation must actually run on
# CPU): re-pin the platform from the env var explicitly.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import tempfile

import numpy as np

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.models.logistic_regression import train_logistic_regression
from flinkml_tpu.parallel import DeviceMesh

rng = np.random.default_rng(0)
n, d = 4096, 32
x = rng.normal(size=(n, d)).astype(np.float32)
y = (x @ rng.normal(size=d) > 0).astype(np.float32)
w = np.ones(n, dtype=np.float32)

mesh = DeviceMesh()
hyper = dict(
    mesh=mesh, max_iter=60, learning_rate=0.5, global_batch_size=n,
    reg=0.0, tol=0.0, seed=42,
)

# --- Golden run: no failures, whole loop in one dispatch ------------------
golden = train_logistic_regression(x, y, w, **hyper)

with tempfile.TemporaryDirectory() as td:
    mgr = CheckpointManager(td)

    # --- "Crash" after 24 epochs (checkpoint every 12) --------------------
    train_logistic_regression(
        x, y, w, **{**hyper, "max_iter": 24},
        checkpoint_manager=mgr, checkpoint_interval=12,
    )
    print("checkpoints on disk:", mgr.all_epochs())  # [12, 24]

    # --- Resume: restores the epoch-24 carry, finishes to 60 --------------
    resumed = train_logistic_regression(
        x, y, w, **hyper,
        checkpoint_manager=mgr, checkpoint_interval=12, resume=True,
    )

np.testing.assert_allclose(resumed, golden, rtol=1e-12)
print("resumed coefficients are exactly the uninterrupted result")
