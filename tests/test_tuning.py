"""ParamGridBuilder / CrossValidator / TrainValidationSplit."""

import numpy as np
import pytest

from flinkml_tpu import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    Pipeline,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from flinkml_tpu.models import (
    BinaryClassificationEvaluator,
    GBTRegressor,
    LogisticRegression,
    RegressionEvaluator,
    StandardScaler,
)
from flinkml_tpu.table import Table


def _binary_data(n=400, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 5))
    y = (x[:, 0] + 0.5 * x[:, 1] + 0.3 * rng.normal(size=n) > 0).astype(float)
    return Table({"features": x, "label": y})


def _lr(max_iter=30):
    return (
        LogisticRegression().set_max_iter(max_iter).set_global_batch_size(512)
        .set_learning_rate(1.0).set_seed(0)
    )


def test_param_grid_builder_cartesian():
    lr = _lr()
    grid = (
        ParamGridBuilder()
        .add_grid(lr, LogisticRegression.REG, [0.0, 0.1, 1.0])
        .add_grid(lr, LogisticRegression.MAX_ITER, [10, 20])
        .build()
    )
    assert len(grid) == 6
    assert all(len(m) == 2 for m in grid)
    with pytest.raises(ValueError, match="empty"):
        ParamGridBuilder().add_grid(lr, LogisticRegression.REG, [])
    with pytest.raises(ValueError, match="not defined"):
        ParamGridBuilder().add_grid(lr, GBTRegressor.NUM_TREES, [5])


def test_cross_validator_picks_sane_reg(tmp_path):
    t = _binary_data()
    lr = _lr()
    grid = (
        ParamGridBuilder()
        .add_grid(lr, LogisticRegression.REG, [0.0, 100.0])
        .build()
    )
    cv = CrossValidator(lr, grid, BinaryClassificationEvaluator())
    cv.set_num_folds(3).set_seed(0)
    model = cv.fit(t)
    # Absurd regularization must lose.
    assert model.best_index == 0
    assert len(model.avg_metrics) == 2
    assert model.avg_metrics[0] > model.avg_metrics[1]
    assert model.param_maps_description[1]["LogisticRegression.reg"] == 100.0
    (pred,) = model.transform(t)
    assert (pred["prediction"] == t["label"]).mean() > 0.85
    # Persistence: wrapper + inner model.
    model.save(str(tmp_path / "cv"))
    loaded = CrossValidatorModel.load(str(tmp_path / "cv"))
    assert loaded.best_index == 0
    assert loaded.avg_metrics == model.avg_metrics
    (p2,) = loaded.transform(t)
    np.testing.assert_array_equal(p2["prediction"], pred["prediction"])


def test_cross_validator_validation_errors():
    t = _binary_data(n=20)
    lr = _lr()
    grid = ParamGridBuilder().add_grid(lr, LogisticRegression.REG, [0.0]).build()
    with pytest.raises(ValueError, match="estimator and evaluator"):
        CrossValidator(None, grid, None).fit(t)
    cv = CrossValidator(lr, grid, BinaryClassificationEvaluator())
    with pytest.raises(ValueError, match="rows < numFolds"):
        cv.set_num_folds(30).fit(t)


def test_train_validation_split_smaller_better_metric(tmp_path):
    rng = np.random.default_rng(1)
    x = rng.uniform(-2, 2, size=(600, 4))
    y = np.where(x[:, 0] > 0, 2.0, -1.0) + x[:, 1] ** 2
    t = Table({"features": x, "label": y})
    gbt = GBTRegressor().set_learning_rate(0.2).set_seed(0)
    grid = (
        ParamGridBuilder()
        .add_grid(gbt, GBTRegressor.NUM_TREES, [1, 40])
        .build()
    )
    tvs = TrainValidationSplit(
        gbt, grid, RegressionEvaluator().set_metrics_names(["rmse"])
    )
    tvs.set_larger_better(False).set_seed(0)
    model = tvs.fit(t)
    assert model.best_index == 1        # 40 trees beats 1 tree on rmse
    model.save(str(tmp_path / "tvs"))
    loaded = TrainValidationSplitModel.load(str(tmp_path / "tvs"))
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_allclose(p2["prediction"], p1["prediction"])


def test_tuning_over_pipeline_inner_stage():
    t = Table({
        "input": np.random.default_rng(2).normal(size=(300, 4)),
    })
    y = (t["input"][:, 0] > 0).astype(float)
    t = t.with_column("label", y)
    lr = _lr().set_features_col("features")
    pipe = Pipeline([
        StandardScaler().set_output_col("features"),
        lr,
    ])
    grid = (
        ParamGridBuilder()
        .add_grid(lr, LogisticRegression.REG, [0.0, 50.0])
        .build()
    )
    cv = CrossValidator(pipe, grid, BinaryClassificationEvaluator())
    cv.set_num_folds(2).set_seed(0)
    model = cv.fit(t)
    assert model.best_index == 0
    (pred,) = model.transform(t)
    assert (pred["prediction"] == y).mean() > 0.9


def test_metric_name_selection():
    t = _binary_data(seed=3)
    lr = _lr()
    grid = ParamGridBuilder().add_grid(lr, LogisticRegression.REG, [0.0]).build()
    cv = CrossValidator(
        lr, grid,
        BinaryClassificationEvaluator().set_metrics_names(
            ["areaUnderPR", "areaUnderROC"]
        ),
    )
    cv.set_metric_name("areaUnderROC").set_num_folds(2).set_seed(0)
    model = cv.fit(t)
    assert 0.5 < model.avg_metrics[0] <= 1.0
