"""Pass 2 (collective-order checker) tests.

Covers: jaxpr collective extraction (plain, jitted, shard_map, loop
bodies), cross-rank order divergence (FML301), the PR 1 threaded-kmeans
deadlock fixture (FML302 on the unlocked shape, silence on the locked
shape), per-mesh tracked locks, and the live integration: a real
threaded ``train_kmeans_stream`` run records a dispatch trace the
checker certifies safe — the lock is analyzer-verified, not assumed.
"""

import threading

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.analysis import (
    CollectiveOp,
    DispatchEvent,
    check_dispatch_trace,
    check_rank_order,
    extract_collectives,
    load_trace,
)
from flinkml_tpu.parallel import dispatch
from flinkml_tpu.parallel.dispatch import (
    TrackedRLock,
    held_lock_tokens,
    local_execution_lock,
)

DEADLOCK_TRACE = "tests/analysis_fixtures/kmeans_threaded_deadlock.trace.json"
LOCKED_TRACE = "tests/analysis_fixtures/kmeans_threaded_locked.trace.json"
POOL_TRACE = "tests/analysis_fixtures/pool_slice_unlocked.trace.json"


# ---------------------------------------------------------------------------
# jaxpr extraction
# ---------------------------------------------------------------------------

def test_extract_collectives_order_and_axes():
    def f(x):
        s = jax.lax.psum(x, "data")
        m = jax.lax.pmax(s, "data")
        return jax.lax.pmin(m, "data")

    # axis_env form: trace with a bound axis.
    closed = jax.make_jaxpr(f, axis_env=[("data", 4)])(jnp.ones(3))
    from flinkml_tpu.analysis.collectives import _walk_jaxpr
    out = []
    _walk_jaxpr(closed.jaxpr, out)
    assert [c.primitive for c in out] == ["psum", "pmax", "pmin"]
    assert all(c.axes == ("data",) for c in out)


def test_extract_collectives_through_jit_shard_map_and_loops(mesh):
    """The real framework shape: a jitted shard_map program with
    collectives inside a fori_loop body — extraction recurses into every
    sub-jaxpr and reports the loop body's sequence once."""
    from flinkml_tpu.models.kmeans import _kmeans_partial_fn
    from flinkml_tpu.parallel.mesh import DeviceMesh

    fn = _kmeans_partial_fn(mesh.mesh, 3, DeviceMesh.DATA_AXIS)
    x = jnp.ones((16, 4))
    w = jnp.ones(16)
    c = jnp.ones((3, 4))
    seq = extract_collectives(fn, x, w, c)
    assert [op.primitive for op in seq] == ["psum", "psum"]
    assert all(op.axes == (DeviceMesh.DATA_AXIS,) for op in seq)


def test_rank_order_divergence_fml301():
    a = (CollectiveOp("psum", ("data",)), CollectiveOp("all_gather", ("data",)))
    b = (CollectiveOp("all_gather", ("data",)), CollectiveOp("psum", ("data",)))
    assert not check_rank_order({0: a, 1: a})
    findings = check_rank_order({0: a, 1: b}, program="step")
    assert len(findings) == 1 and findings[0].rule == "FML301"
    assert "rank 1" in findings[0].message


# ---------------------------------------------------------------------------
# the PR 1 deadlock fixture
# ---------------------------------------------------------------------------

def test_deadlock_fixture_flagged_and_locked_fixture_passes():
    """Satellite acceptance: the checker flags the unlocked threaded-
    kmeans program shape (two threads, shared 8-device mesh, no common
    lock) and passes the identical schedule under the per-mesh lock."""
    unlocked = load_trace(DEADLOCK_TRACE)
    findings = check_dispatch_trace(unlocked, location=DEADLOCK_TRACE)
    assert [f.rule for f in findings] == ["FML302"]
    assert "kmeans.lloyd_epoch" in findings[0].message

    locked = load_trace(LOCKED_TRACE)
    assert check_dispatch_trace(locked, location=LOCKED_TRACE) == []


def test_dispatch_trace_rules():
    def ev(thread, devices, locks=()):
        return DispatchEvent(thread=thread, program="p", devices=devices,
                             locks=tuple(locks))

    # Single-device programs never rendezvous across devices: no finding.
    assert not check_dispatch_trace([ev("a", (0,)), ev("b", (0,))])
    # Disjoint device sets: no finding.
    assert not check_dispatch_trace([ev("a", (0, 1)), ev("b", (2, 3))])
    # Same thread: ordered by program order: no finding.
    assert not check_dispatch_trace([ev("a", (0, 1)), ev("a", (0, 1))])
    # Overlapping multi-device, different threads, no common lock: flagged.
    assert check_dispatch_trace([ev("a", (0, 1)), ev("b", (1, 2))])
    # A shared lock token clears it; different locks do not.
    assert not check_dispatch_trace(
        [ev("a", (0, 1), ["L"]), ev("b", (1, 2), ["L"])]
    )
    assert check_dispatch_trace(
        [ev("a", (0, 1), ["L1"]), ev("b", (1, 2), ["L2"])]
    )


def test_pool_slice_overlap_fml303():
    """The FML302 pair machinery specializes to FML303 when one side is
    a serving replica-pool slice dispatch (program prefix
    ``serving.pool/``): the unlocked shape is flagged with the
    pool-specific rule and fix hint, a shared slice lock clears it, and
    the seeded bad-trace fixture is flagged through the file loader."""
    def ev(thread, program, devices, locks=()):
        return DispatchEvent(thread=thread, program=program,
                             devices=devices, locks=tuple(locks))

    pool_ev = ev("serving-p0/r0", "serving.pool/p0/r0.batch", (0, 1))
    train = ev("trainer", "kmeans.lloyd_epoch", (0, 1, 2, 3),
               ["lock:mesh:0,1,2,3"])
    findings = check_dispatch_trace([pool_ev, train])
    assert [f.rule for f in findings] == ["FML303"]
    assert "serving.pool/p0/r0.batch" in findings[0].message
    assert "slice" in findings[0].fix_hint

    # The replica holding its slice lock composes with the overlapping
    # training lock (overlap => the trainer's composite includes it).
    locked_pool = ev("serving-p0/r0", "serving.pool/p0/r0.batch", (0, 1),
                     ["lock:mesh:0,1"])
    locked_train = ev("trainer", "kmeans.lloyd_epoch", (0, 1, 2, 3),
                      ["lock:mesh:0,1,2,3", "lock:mesh:0,1"])
    assert check_dispatch_trace([locked_pool, locked_train]) == []

    # Single-device replicas dispatch no collectives: never flagged.
    assert check_dispatch_trace(
        [ev("serving-p0/r0", "serving.pool/p0/r0.batch", (0,)), train]
    ) == []

    # Two pool replicas over overlapping slices without a shared lock is
    # the same hazard (a misconfigured pool): also FML303.
    other = ev("serving-p0/r1", "serving.pool/p0/r1.batch", (1, 2))
    assert [f.rule for f in check_dispatch_trace([pool_ev, other])] == [
        "FML303"
    ]

    fixture = load_trace(POOL_TRACE)
    flagged = check_dispatch_trace(fixture, location=POOL_TRACE)
    assert [f.rule for f in flagged] == ["FML303"]


def test_local_execution_lock_accepts_device_sequences():
    """Per-slice lock composition without a mesh object: a plain device
    (id) sequence keys the same tracked lock as an identical mesh set,
    so pool replicas and trainers compose through one registry."""
    locks_before = set(dispatch._MESH_LOCKS)
    try:
        lock_a = local_execution_lock([901, 902])
        lock_b = local_execution_lock((902, 901))
        with lock_a:
            tokens = held_lock_tokens()
            assert "lock:mesh:901,902" in tokens
        # Identical set -> the same TrackedRLock instance.
        assert lock_a is lock_b or getattr(lock_a, "token", None) == getattr(
            lock_b, "token", None
        )
        # Overlapping sets compose: acquiring the overlap holds both
        # tokens.
        composite = local_execution_lock([902, 903])
        with composite:
            tokens = held_lock_tokens()
            assert "lock:mesh:901,902" in tokens
            assert "lock:mesh:902,903" in tokens
    finally:
        # The fake id sets must not linger in the process-wide registry
        # (a global-lock holder would acquire them forever after).
        with dispatch._MESH_LOCKS_GUARD:
            for key in set(dispatch._MESH_LOCKS) - locks_before:
                del dispatch._MESH_LOCKS[key]


# ---------------------------------------------------------------------------
# tracked locks + live recording
# ---------------------------------------------------------------------------

def test_tracked_lock_tokens_and_reentrancy():
    lock = TrackedRLock("lock:test")
    assert "lock:test" not in held_lock_tokens()
    with lock:
        assert "lock:test" in held_lock_tokens()
        with lock:  # reentrant
            assert "lock:test" in held_lock_tokens()
        assert "lock:test" in held_lock_tokens()
    assert "lock:test" not in held_lock_tokens()


def test_per_mesh_lock_registry(mesh):
    # Same device set -> same lock object.
    assert local_execution_lock(mesh) is local_execution_lock(mesh)
    mesh_token = local_execution_lock(mesh).token
    assert mesh_token.startswith("lock:mesh:")
    # mesh=None is globally exclusive: it acquires the process lock AND
    # every registered mesh lock, so it shares a token with any
    # concurrent mesh-keyed fit (the FML302-safe shape).
    with local_execution_lock():
        tokens = set(held_lock_tokens())
    assert "lock:process" in tokens
    assert mesh_token in tokens


def test_overlapping_mesh_locks_share_a_component():
    """Overlapping-but-unequal device sets must still exclude each other:
    the later request gets a composite acquiring every intersecting lock
    (in canonical order), so any two overlapping fits share a token — the
    shape the FML302 check certifies."""

    class FakeDev:
        def __init__(self, i):
            self.id = i

    class FakeMesh:
        def __init__(self, ids):
            self.devices = np.array([FakeDev(i) for i in ids], dtype=object)

    a = local_execution_lock(FakeMesh([100, 101]))
    b = local_execution_lock(FakeMesh([101, 102]))  # overlaps a
    c = local_execution_lock(FakeMesh([200, 201]))  # disjoint from both

    with a:
        tokens_a = set(held_lock_tokens())
    with b:
        tokens_b = set(held_lock_tokens())
    with c:
        tokens_c = set(held_lock_tokens())
    assert tokens_a & tokens_b, "overlapping sets must share a lock token"
    assert not (tokens_c & (tokens_a | tokens_b)), "disjoint sets must not"

    # And the shared component actually excludes: b cannot be acquired
    # while a is held.
    entered = []
    with a:
        t = threading.Thread(target=lambda: (b.acquire(), entered.append(1),
                                             b.release()))
        t.start()
        t.join(timeout=0.3)
        assert not entered, "composite must block while the base lock is held"
    t.join(timeout=5)
    assert entered


def test_process_lock_excludes_mesh_locks():
    """mesh=None must serialize against mesh-keyed fits: its composite
    holds every registered mesh lock, so a mesh fit cannot start while a
    process-wide loop runs (and vice versa)."""

    class FakeDev:
        def __init__(self, i):
            self.id = i

    class FakeMesh:
        def __init__(self, ids):
            self.devices = np.array([FakeDev(i) for i in ids], dtype=object)

    mesh_lock = local_execution_lock(FakeMesh([300, 301]))
    entered = []
    with local_execution_lock():  # globally exclusive
        assert mesh_lock.token in held_lock_tokens()
        t = threading.Thread(
            target=lambda: (mesh_lock.acquire(), entered.append(1),
                            mesh_lock.release())
        )
        t.start()
        t.join(timeout=0.3)
        assert not entered, "mesh fit must wait for the process-wide holder"
    t.join(timeout=5)
    assert entered


def test_record_collective_dispatch_unlocked_vs_locked(mesh):
    """The synthetic reproduction of the PR 1 shape through the REAL
    recording machinery: two threads record epoch dispatches over the
    mesh — without the lock the checker flags FML302, with it the trace
    is clean."""
    device_ids = tuple(d.id for d in mesh.mesh.devices.flatten())

    def run(locked):
        events = []
        dispatch.add_dispatch_observer(events.append)
        try:
            def fit(name):
                if locked:
                    with local_execution_lock(mesh):
                        dispatch.record_collective_dispatch(
                            "kmeans.lloyd_epoch", device_ids
                        )
                else:
                    dispatch.record_collective_dispatch(
                        "kmeans.lloyd_epoch", device_ids
                    )

            threads = [
                threading.Thread(target=fit, args=(f"fit-{i}",))
                for i in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            dispatch.remove_dispatch_observer(events.append)
        return [DispatchEvent.from_map(e) for e in events]

    unlocked = run(locked=False)
    assert [f.rule for f in check_dispatch_trace(unlocked)] == ["FML302"]
    locked = run(locked=True)
    assert check_dispatch_trace(locked) == []


def test_threaded_train_kmeans_stream_trace_is_analyzer_safe(mesh):
    """Integration: two genuinely concurrent train_kmeans_stream fits
    record a dispatch trace that the collective-order checker certifies
    deadlock-free — the per-mesh lock PR 1 introduced is now verified by
    the analyzer instead of trusted."""
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    rng = np.random.default_rng(5)
    x = rng.normal(size=(64, 3)).astype(np.float32)
    init = np.ascontiguousarray(x[:2])

    def batches():
        for off in range(0, 64, 32):
            yield {"x": x[off:off + 32]}

    events = []
    dispatch.add_dispatch_observer(events.append)
    try:
        threads = [
            threading.Thread(
                target=train_kmeans_stream,
                args=(iter(list(batches())),),
                kwargs=dict(k=2, mesh=mesh, max_iter=2, seed=0,
                            initial_centroids=init),
            )
            for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        dispatch.remove_dispatch_observer(events.append)

    trace = [DispatchEvent.from_map(e) for e in events]
    # Both fits recorded their epochs (2 threads x 2 epochs)...
    assert len(trace) == 4
    assert all(e.locks for e in trace), "epochs must dispatch under a lock"
    # ...and the recorded shape is the safe one.
    assert check_dispatch_trace(trace) == []


# ---------------------------------------------------------------------------
# FML304 — slice leases (training/serving colocation, ISSUE 15)
# ---------------------------------------------------------------------------

LEASE_TRACE = "tests/analysis_fixtures/pool_lease_unreclaimed.trace.json"


def test_pool_lease_unreclaimed_fixture_fml304():
    """The seeded fixture: a pool dispatch on a still-leased slice is
    FML304 even though it HOLDS the shared slice lock — leases are a
    capacity contract, orthogonal to rendezvous locking."""
    events = load_trace(LEASE_TRACE)
    findings = check_dispatch_trace(events, location=LEASE_TRACE)
    assert [f.rule for f in findings] == ["FML304"]
    assert "lease:trainer:0,1" in findings[0].message
    assert "request_revoke" in (findings[0].fix_hint or "")


def test_fml304_live_lease_recording_and_release():
    """Live shape: dispatch events record active FOREIGN leases over
    their devices; the holder's own dispatches do not carry the token;
    releasing the lease clears later events (the reclaim handshake's
    observable end state)."""
    lease = dispatch.lease_devices([0, 1], holder="trainer304")
    events = []
    dispatch.add_dispatch_observer(events.append)
    try:
        # Holder thread: its own dispatch carries no foreign lease.
        dispatch.record_collective_dispatch("train_step", [0, 1])

        def pool_dispatch():
            dispatch.record_collective_dispatch(
                "serving.pool/p304/r0.batch", [1, 2]
            )

        t = threading.Thread(target=pool_dispatch)
        t.start()
        t.join()
        lease.release()
        t2 = threading.Thread(target=pool_dispatch)
        t2.start()
        t2.join()
    finally:
        dispatch.remove_dispatch_observer(events.append)
        lease.release()
    assert events[0]["leases"] == ()
    assert events[1]["leases"] == (lease.token,)
    assert events[2]["leases"] == ()  # released: reclaimed slice is clean
    trace = [DispatchEvent.from_map(e) for e in events]
    rules = [f.rule for f in check_dispatch_trace(trace)]
    assert rules.count("FML304") == 1


def test_fml304_non_pool_dispatch_on_lease_not_flagged():
    """A second TRAINER overlapping a lease is a scheduling question,
    not the serving-steals-leased-slice shape — FML304 is pool-only
    (FML302 still covers the locking side)."""
    events = [
        DispatchEvent(thread="t1", program="train_a", devices=(0, 1),
                      locks=("lock:mesh:0,1",),
                      leases=("lease:other:0,1",)),
    ]
    assert [f.rule for f in check_dispatch_trace(events)] == []


def test_lease_registry_duplicate_refused():
    lease = dispatch.lease_devices([4, 5], holder="dup")
    try:
        with pytest.raises(ValueError, match="already registered"):
            dispatch.lease_devices([4, 5], holder="dup")
    finally:
        lease.release()
    # Released: the same slice can be leased again.
    again = dispatch.lease_devices([4, 5], holder="dup")
    again.release()
