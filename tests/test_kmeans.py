"""KMeans tests — mirrors the reference's KMeansTest."""

import numpy as np
import pytest

from flinkml_tpu.models import KMeans, KMeansModel
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


def blob_table(rng, centers, n_per=50, scale=0.3):
    pts = np.concatenate(
        [rng.normal(loc=c, scale=scale, size=(n_per, len(c))) for c in centers]
    )
    return Table({"features": pts}), np.repeat(np.arange(len(centers)), n_per)


def test_param_defaults():
    km = KMeans()
    assert km.get_k() == 2
    assert km.get_max_iter() == 20
    assert km.get_distance_measure() == "euclidean"
    assert km.get_features_col() == "features"
    assert km.get_prediction_col() == "prediction"


def test_fit_two_blobs(rng):
    table, truth = blob_table(rng, [(0.0, 0.0), (10.0, 10.0)])
    model = KMeans().set_seed(1).fit(table)
    (out,) = model.transform(table)
    pred = out["prediction"]
    # Clusters must perfectly separate the two blobs (up to label swap).
    a, b = pred[truth == 0], pred[truth == 1]
    assert len(np.unique(a)) == 1 and len(np.unique(b)) == 1
    assert a[0] != b[0]
    # Centroids near blob centers.
    c = np.sort(model.centroids[:, 0])
    np.testing.assert_allclose(c, [0.0, 10.0], atol=0.5)


def test_against_sklearn_inertia(rng):
    from sklearn.cluster import KMeans as SkKMeans

    table, _ = blob_table(rng, [(0, 0), (5, 5), (0, 6)], n_per=60)
    x = table["features"]
    ours = (
        KMeans().set_seed(3).set_k(3).set_max_iter(50)
        .set_init_mode("k-means++").fit(table)
    )
    sk = SkKMeans(n_clusters=3, n_init=10, random_state=0).fit(x)

    def inertia(centroids):
        d2 = ((x[:, None, :] - centroids[None, :, :]) ** 2).sum(-1)
        return d2.min(axis=1).sum()

    assert inertia(ours.centroids) <= inertia(sk.cluster_centers_) * 1.10


def test_multi_device(rng):
    table, truth = blob_table(rng, [(0.0, 0.0), (8.0, 8.0)], n_per=101)
    model = KMeans(mesh=DeviceMesh()).set_seed(5).set_max_iter(30).fit(table)
    (out,) = model.transform(table)
    pred = out["prediction"]
    assert len(np.unique(pred[truth == 0])) == 1
    assert len(np.unique(pred[truth == 1])) == 1


def test_empty_cluster_keeps_centroid(rng):
    # k=3 on data with 2 tight blobs: one centroid may end up empty; the
    # fit must not produce NaNs.
    table, _ = blob_table(rng, [(0.0, 0.0), (10.0, 10.0)], n_per=20, scale=0.01)
    model = KMeans().set_seed(2).set_k(3).set_max_iter(30).fit(table)
    assert np.isfinite(model.centroids).all()


def test_k_exceeds_points():
    table = Table({"features": np.zeros((3, 2))})
    with pytest.raises(ValueError, match="k="):
        KMeans().set_k(5).fit(table)


def test_k_must_exceed_one():
    # Parity: KMeansModelParams declares k with gt(1).
    with pytest.raises(ValueError):
        KMeans().set_k(1)


def test_save_load(tmp_path, rng):
    table, _ = blob_table(rng, [(0, 0), (9, 9)])
    model = KMeans().set_seed(7).fit(table)
    p = str(tmp_path / "km")
    model.save(p)
    loaded = KMeansModel.load(p)
    np.testing.assert_array_equal(loaded.centroids, model.centroids)
    (a,) = model.transform(table)
    (b,) = loaded.transform(table)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])


def test_model_data_round_trip(rng):
    table, _ = blob_table(rng, [(0, 0), (9, 9)])
    model = KMeans().set_seed(7).fit(table)
    other = KMeansModel().set_model_data(*model.get_model_data())
    np.testing.assert_array_equal(other.centroids, model.centroids)


def test_deterministic(rng):
    table, _ = blob_table(rng, [(0, 0), (9, 9)])
    c1 = KMeans().set_seed(11).fit(table).centroids
    c2 = KMeans().set_seed(11).fit(table).centroids
    np.testing.assert_array_equal(c1, c2)
