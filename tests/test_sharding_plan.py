"""flinkml_tpu.sharding (ISSUE 7): the declarative ShardingPlan layer.

Promotes the MULTICHIP dryrun shardings into pinned tests — each
sharding family the ``MULTICHIP_r05.json`` dryrun proves (dp, tp, fsdp,
fsdp×tp) becomes an equivalent :class:`ShardingPlan` that must compile
and match the replicated run's numerics on the 8-CPU-device mesh — and
covers the plan value itself (families, presets, truncation, JSON),
``infer_plan``'s budget arithmetic, the FML5xx validation pass, the
checkpoint ``save(plan=...)`` single-source-of-truth integration, and
THE acceptance scenario: a parameter + optimizer pytree whose
replicated per-device footprint provably exceeds a configured HBM
budget trains under FSDP, converges to the replicated baseline, and
checkpoints with plan-derived layout tags.
"""

import json
import os

import jax
import numpy as np
import pytest

from flinkml_tpu.analysis.sharding_check import (
    check_cross_plan,
    check_plan,
    check_plan_file,
    check_program,
    plan_collective_signature,
)
from flinkml_tpu.iteration import CheckpointManager, LayoutConflictError
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.sharding import (
    BATCH_PARALLEL,
    FSDP,
    FSDP_TP,
    NoFeasiblePlanError,
    PRESETS,
    REPLICATED,
    ShardingPlan,
    infer_plan,
    layouts_for,
    per_device_state_bytes,
)
from flinkml_tpu.sharding.apply import (
    PlanValidationError,
    batch_world,
    init_linear_state,
    shard_state,
    state_shardings,
    train_linear_plan,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def _mesh(plan, n=None, tp_size=None):
    devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return DeviceMesh.for_plan(plan, devices=devices, tp_size=tp_size)


def _data(n=128, dim=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim))
    true = rng.normal(size=dim)
    y = (x @ true > 0).astype(x.dtype)
    return x, y


# ---------------------------------------------------------------------------
# The plan value
# ---------------------------------------------------------------------------

def test_family_matching_first_rule_wins_and_default_replicates():
    plan = ShardingPlan(
        "custom",
        rules=(("embed*", (("fsdp", "tp"), None)), ("*_bias", ()),
               ("*", ("fsdp",))),
        batch_axes=("data",),
    )
    assert plan.spec_for("embedding_table") == (("fsdp", "tp"), None)
    assert plan.spec_for("dense_bias") == ()
    assert plan.spec_for("coef") == ("fsdp",)
    # Key-path names match on the last component too.
    assert plan.spec_for("layer0/dense_bias") == ()
    # Unmatched names take the default (replicated unless overridden).
    narrow = ShardingPlan("narrow", rules=(("coef", ("fsdp",)),))
    assert narrow.spec_for("other") == ()


def test_spec_truncates_to_parameter_rank():
    # The rule that lets one FSDP_TP table serve matrices AND vectors.
    assert FSDP_TP.spec_for("w", ndim=2) == ("fsdp", "tp")
    assert FSDP_TP.spec_for("w", ndim=1) == ("fsdp",)
    assert FSDP_TP.spec_for("step", ndim=0) == ()
    assert FSDP_TP.layout_tag("step", ndim=0) == "replicated"


def test_presets_catalog_and_required_axes():
    from flinkml_tpu.sharding import EMBEDDING

    assert set(PRESETS) == {"replicated", "batch_parallel", "fsdp",
                            "fsdp_tp", "embedding"}
    assert REPLICATED.required_axes() == ()
    assert BATCH_PARALLEL.required_axes() == ("data",)
    assert FSDP.required_axes() == ("data", "fsdp")
    assert FSDP_TP.required_axes() == ("data", "fsdp", "tp")
    assert EMBEDDING.required_axes() == ("data", "fsdp", "tp")
    assert FSDP.layout_tag("coef", ndim=1) == "sharded:0"
    assert REPLICATED.layout_tag("coef", ndim=1) == "replicated"
    # The embedding family shards the VOCAB dim over the fsdp x tp
    # PRODUCT with rows whole; non-family params fall through to the
    # FSDP_TP-style rule.
    assert EMBEDDING.spec_for("w2v/center_embedding", ndim=2) == \
        (("fsdp", "tp"),)
    assert EMBEDDING.spec_for("dense_w", ndim=2) == ("fsdp", "tp")
    assert EMBEDDING.layout_tag("w2v/center_embedding", ndim=2) == \
        "sharded:0"


def test_plan_json_roundtrip():
    plan = ShardingPlan(
        "rt",
        rules=(("embed*", (("fsdp", "tp"), None)), ("*", ("fsdp",))),
        batch_axes=("data", "fsdp"),
        default_spec=(None, "tp"),
    )
    back = ShardingPlan.from_json_dict(
        json.loads(json.dumps(plan.to_json_dict()))
    )
    assert back == plan
    assert hash(back) == hash(plan)  # usable as a compile-cache key


def test_layouts_for_derives_tags_per_leaf():
    state = init_linear_state(64, "adam", np.float32)
    tags = layouts_for(FSDP, state)
    assert tags == {"coef": "sharded:0", "m": "sharded:0",
                    "v": "sharded:0", "step": "replicated"}
    assert layouts_for(BATCH_PARALLEL, state) == {
        "coef": "replicated", "m": "replicated", "v": "replicated",
        "step": "replicated",
    }


def test_mesh_for_plan_shapes():
    assert dict(_mesh(REPLICATED).mesh.shape) == {"data": 8}
    assert dict(_mesh(BATCH_PARALLEL).mesh.shape) == {"data": 8}
    assert dict(_mesh(FSDP).mesh.shape) == {"data": 1, "fsdp": 8}
    assert dict(_mesh(FSDP_TP).mesh.shape) == {"data": 1, "fsdp": 4,
                                               "tp": 2}
    assert dict(_mesh(FSDP_TP, tp_size=4).mesh.shape) == {
        "data": 1, "fsdp": 2, "tp": 4}
    with pytest.raises(ValueError, match="does not divide"):
        _mesh(FSDP_TP, tp_size=3)


# ---------------------------------------------------------------------------
# infer_plan: cheapest plan whose footprint fits
# ---------------------------------------------------------------------------

def test_per_device_state_bytes_counts_optimizer_slots():
    mesh = {"data": 1, "fsdp": 8}
    shapes = {"coef": (8000,)}
    # replicated sgd: 8000 * 4 B * (1 param + 1 momentum)
    assert per_device_state_bytes(BATCH_PARALLEL, mesh, shapes) == 64_000
    # adam: 3 same-shaped slots
    assert per_device_state_bytes(BATCH_PARALLEL, mesh, shapes,
                                  optimizer_slots=2) == 96_000
    # fsdp divides by the fsdp axis
    assert per_device_state_bytes(FSDP, mesh, shapes) == 8_000


def test_infer_plan_picks_cheapest_fitting_preset():
    mesh = {"data": 1, "fsdp": 4, "tp": 2}
    shapes = {"w": (64, 64)}  # 4096 elems -> 32768 B replicated w/ slot
    assert infer_plan(mesh, shapes, 32_768).name == "batch_parallel"
    # Too small for replication, fits /4 under fsdp (8192 B).
    assert infer_plan(mesh, shapes, 10_000).name == "fsdp"
    # Only the full fsdp x tp factoring (/8 -> 4096 B) fits.
    assert infer_plan(mesh, shapes, 5_000).name == "fsdp_tp"
    with pytest.raises(NoFeasiblePlanError, match="no sharding plan fits"):
        infer_plan(mesh, shapes, 1_000)
    # A mesh without fsdp axes can only batch-parallel; the error says
    # which candidates were skipped and why.
    with pytest.raises(NoFeasiblePlanError, match="mesh lacks axes"):
        infer_plan({"data": 8}, shapes, 10_000)


def test_infer_plan_accepts_device_mesh():
    mesh = _mesh(FSDP)
    plan = infer_plan(mesh, {"coef": (8192,)}, 40_000)
    assert plan.name == "fsdp"


# ---------------------------------------------------------------------------
# FML5xx: plan validation before compile
# ---------------------------------------------------------------------------

def test_fml501_unknown_and_duplicate_axes():
    bad = ShardingPlan("bad", rules=(("*", ("model",)),),
                       batch_axes=("batch",))
    rules = [f.rule for f in check_plan(bad, {"data": 8})]
    assert rules == ["FML501", "FML501"]  # batch axis + family axis
    dup = ShardingPlan("dup", rules=(("*", ("fsdp", "fsdp")),))
    findings = check_plan(dup, {"data": 1, "fsdp": 8})
    assert [f.rule for f in findings] == ["FML501"]
    assert "at most once" in findings[0].message


def test_fml502_axis_size_must_divide_shard_dim():
    findings = check_plan(FSDP, {"data": 1, "fsdp": 8},
                          param_shapes={"coef": (4090,)})
    assert [f.rule for f in findings] == ["FML502"]
    assert "does not divide" in findings[0].message
    assert check_plan(FSDP, {"data": 1, "fsdp": 8},
                      param_shapes={"coef": (4096,)}) == []


def test_fml503_replicated_but_huge_vs_hbm_budget():
    shapes = {"coef": (8192,)}
    findings = check_plan(BATCH_PARALLEL, {"data": 8}, param_shapes=shapes,
                          hbm_budget_bytes=16_384)
    assert [f.rule for f in findings] == ["FML503"]
    # The fix the finding suggests — sharding — really clears it.
    assert check_plan(FSDP, {"data": 1, "fsdp": 8}, param_shapes=shapes,
                      hbm_budget_bytes=16_384) == []


def test_fml504_conflicting_plans_compose_with_fml301_checker():
    mesh = {"data": 1, "fsdp": 8}
    shapes = {"coef": (4096,)}
    # The derived signatures are CollectiveOp sequences — the FML301
    # comparator's currency.
    sig = plan_collective_signature(FSDP, mesh, shapes)
    assert [c.primitive for c in sig] == ["all_gather", "reduce_scatter"]
    assert plan_collective_signature(BATCH_PARALLEL, mesh, shapes)[0] \
        .primitive == "psum"
    findings = check_cross_plan([FSDP, BATCH_PARALLEL], mesh, shapes)
    assert [f.rule for f in findings] == ["FML504"]
    # Identical family tables agree: no findings.
    assert check_cross_plan([FSDP, FSDP], mesh, shapes) == []
    assert check_program([FSDP], mesh, shapes) == []


def test_fml504_fires_for_distinct_plans_sharing_a_name():
    """Two conflicting plans that happen to share a name must not
    collapse into one comparator entry."""
    mesh = {"data": 1, "fsdp": 8}
    shapes = {"coef": (4096,)}
    a = ShardingPlan("p", rules=(("*", ("fsdp",)),),
                     batch_axes=("data", "fsdp"))
    b = ShardingPlan("p", rules=(("*", ()),), batch_axes=("data", "fsdp"))
    findings = check_cross_plan([a, b], mesh, shapes)
    assert [f.rule for f in findings] == ["FML504"]
    # Two literally identical plans still agree.
    assert check_cross_plan([a, ShardingPlan(
        "p", rules=(("*", ("fsdp",)),), batch_axes=("data", "fsdp"),
    )], mesh, shapes) == []


@pytest.mark.parametrize("rule", ["FML501", "FML502", "FML503", "FML504"])
def test_seeded_plan_fixtures_are_flagged(rule):
    path = {
        "FML501": "bad_plan_fml501_unknown_axis.plan.json",
        "FML502": "bad_plan_fml502_indivisible.plan.json",
        "FML503": "bad_plan_fml503_replicated_huge.plan.json",
        "FML504": "bad_plan_fml504_conflicting.plan.json",
    }[rule]
    findings = check_plan_file(os.path.join(FIXTURES, path))
    assert [f.rule for f in findings] == [rule]


def test_seeded_embedding_plan_fixture_flags_fml502_and_fml503():
    """The embedding fixture seeds BOTH failure modes of a 100M-row
    table: an indivisible vocab axis (FML502, with the embedding-
    specific padding hint) and a per-SHARD footprint that still exceeds
    the budget (the FML503 branch this subsystem added — the original
    rule only caught replicated params)."""
    findings = check_plan_file(
        os.path.join(FIXTURES, "bad_plan_fml50x_embedding.plan.json")
    )
    assert sorted(f.rule for f in findings) == ["FML502", "FML503"]
    by_rule = {f.rule: f for f in findings}
    assert "pads its vocab" in by_rule["FML502"].message
    assert "per-device shard still costs" in by_rule["FML503"].message


def test_fml503_counts_sharded_embedding_footprint():
    """A SHARDED embedding table whose per-shard slice (params +
    optimizer slots) exceeds the budget is refused — sharding is not a
    free pass, the shard itself must fit."""
    from flinkml_tpu.sharding import EMBEDDING

    mesh = {"data": 1, "fsdp": 4, "tp": 2}
    shapes = {"big/embedding": (1 << 20, 64)}
    per_shard = (1 << 17) * 64 * 4 * 3  # /8 rows, f32, 2 Adam slots
    over = check_plan(EMBEDDING, mesh, param_shapes=shapes,
                      hbm_budget_bytes=per_shard - 1, optimizer_slots=2)
    assert [f.rule for f in over] == ["FML503"]
    fits = check_plan(EMBEDDING, mesh, param_shapes=shapes,
                      hbm_budget_bytes=per_shard, optimizer_slots=2)
    assert fits == []


def test_infer_plan_embedding_routing():
    """An embedding-family parameter universe skips row-splitting plans
    (FSDP_TP) and lands on the embedding preset when only the full
    fsdp x tp product fits."""
    from flinkml_tpu.sharding import EMBEDDING  # noqa: F401

    mesh = {"data": 1, "fsdp": 4, "tp": 2}
    shapes = {"w2v/center_embedding": (1 << 16, 16)}
    rep_bytes = (1 << 16) * 16 * 4 * 2
    # Fits /4: fsdp keeps its seat (rows stay whole under fsdp).
    assert infer_plan(mesh, shapes, rep_bytes // 3).name == "fsdp"
    # Only /8 fits: fsdp_tp would split rows -> embedding takes it.
    assert infer_plan(mesh, shapes, rep_bytes // 6).name == "embedding"
    # Nothing fits: the error names WHY fsdp_tp was skipped.
    with pytest.raises(NoFeasiblePlanError,
                       match="splits embedding rows"):
        infer_plan(mesh, shapes, rep_bytes // 20)


def test_cli_runs_the_sharding_pass():
    from flinkml_tpu.analysis.__main__ import main

    fixture = os.path.join(FIXTURES, "bad_plan_fml502_indivisible.plan.json")
    assert main([fixture, "--no-selfcheck"]) == 1


def test_unreadable_plan_file_fails_loudly(tmp_path):
    bad = tmp_path / "broken.plan.json"
    bad.write_text("{not json")
    findings = check_plan_file(str(bad))
    assert [f.rule for f in findings] == ["FML501"]
    empty = tmp_path / "empty.plan.json"
    empty.write_text("{}")
    assert [f.rule for f in check_plan_file(str(empty))] == ["FML501"]


# ---------------------------------------------------------------------------
# Promoted dryrun shardings: each MULTICHIP family's equivalent plan
# compiles and matches the replicated numerics on the 8-device mesh
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
@pytest.mark.parametrize("preset", ["batch_parallel", "fsdp", "fsdp_tp"])
def test_dryrun_promoted_plan_matches_replicated_numerics(preset, optimizer):
    """dp (batch_parallel) and fsdp(, x tp) from the MULTICHIP dryrun as
    pinned plans: same seeded program, full-batch windows, so the only
    difference from REPLICATED is the sharding — numerics must agree to
    float-associativity."""
    x, y = _data()
    plan = PRESETS[preset]

    def run(p):
        return train_linear_plan(
            x, y, None, p, _mesh(p), loss="logistic", optimizer=optimizer,
            max_iter=8, learning_rate=0.5,
        )

    golden = run(REPLICATED)
    coef = run(plan)
    assert np.isfinite(coef).all()
    np.testing.assert_allclose(coef, golden, rtol=1e-9, atol=1e-12)


def test_dryrun_promoted_tp_matmul_plan_matches_replicated():
    """The tp dryrun family as a plan: a 2-layer MLP forward whose
    weights shard Megatron-style (W1 columns / W2 rows over ``tp``) via
    plan-derived in_shardings; output must equal the replicated (and
    host numpy) forward."""
    plan = ShardingPlan(
        "tp_mlp",
        rules=(("w1", (None, "tp")), ("w2", ("tp", None))),
        batch_axes=(),
    )
    mesh = DeviceMesh({"data": 1, "tp": 8})
    assert check_plan(plan, mesh,
                      param_shapes={"w1": (16, 32), "w2": (32, 16)}) == []
    rng = np.random.default_rng(3)
    xh = rng.normal(size=(24, 16))
    params = {"w1": rng.normal(size=(16, 32)),
              "w2": rng.normal(size=(32, 16))}
    sharded = shard_state(plan, mesh, params)

    def forward(p, xb):
        return np.tanh(xb @ p["w1"]) @ p["w2"]

    import jax.numpy as jnp

    def jforward(p, xb):
        return jnp.tanh(xb @ p["w1"]) @ p["w2"]

    out = jax.jit(
        jforward,
        in_shardings=(state_shardings(plan, mesh, params), None),
    )(sharded, xh)
    np.testing.assert_allclose(np.asarray(out), forward(params, xh),
                               rtol=1e-9)


def test_batch_world_and_state_placement():
    mesh = _mesh(FSDP)
    assert batch_world(FSDP, mesh) == 8
    assert batch_world(REPLICATED, mesh) == 1
    state = shard_state(FSDP, mesh, init_linear_state(64, "sgd", np.float64))
    # Each device holds 1/8th of every sharded leaf.
    shard_rows = {s.data.shape[0]
                  for s in state["coef"].addressable_shards}
    assert shard_rows == {8}
    assert state["momentum"].sharding.spec == \
        state["coef"].sharding.spec


def test_estimator_accepts_sharding_plan_and_rejects_unaware_paths():
    """The user-facing ask (ROADMAP item 1): an estimator takes a plan.
    The dense binomial LR path trains through it; plan-unaware paths
    (sparse features, streamed fits) refuse loudly instead of silently
    replicating."""
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.table import Table

    x, y = _data(n=64, dim=16, seed=2)
    table = Table({"features": x, "label": y})
    est = LogisticRegression(sharding_plan=FSDP)
    est.set(LogisticRegression.MAX_ITER, 5)
    model = est.fit(table)
    (out,) = model.transform(Table({"features": x}))
    pred = np.asarray(out.column("prediction"))
    assert pred.shape == (64,) and np.isfinite(pred).all()
    # Convergence sanity: the plan-trained model separates the data.
    baseline = LogisticRegression()
    baseline.set(LogisticRegression.MAX_ITER, 5)
    base_pred = np.asarray(
        baseline.fit(table).transform(Table({"features": x}))[0]
        .column("prediction")
    )
    assert np.mean(pred == y) >= np.mean(base_pred == y) - 0.2

    with pytest.raises(ValueError, match="streamed"):
        LogisticRegression(sharding_plan=FSDP).fit(iter([table]))


def test_plan_unaware_estimators_refuse_the_knob_at_construction():
    """A silently-ignored plan would train replicated — the OOM the
    user configured the plan to avoid — so plan-unaware estimators
    refuse the knob up front; the whole linear family accepts it."""
    from flinkml_tpu.models.kmeans import KMeans
    from flinkml_tpu.models.linear_regression import LinearRegression
    from flinkml_tpu.models.linear_svc import LinearSVC
    from flinkml_tpu.table import Table

    with pytest.raises(ValueError, match="does not support sharding_plan"):
        KMeans(sharding_plan=FSDP)

    x, y = _data(n=64, dim=16, seed=4)
    table = Table({"features": x, "label": y})
    svc = LinearSVC(sharding_plan=FSDP)
    svc.set(LinearSVC.MAX_ITER, 3)
    assert np.isfinite(
        np.asarray(svc.fit(table)._coefficient)
    ).all()
    reg = LinearRegression(sharding_plan=FSDP)
    reg.set(LinearRegression.MAX_ITER, 3)
    assert np.isfinite(
        np.asarray(reg.fit(Table({"features": x,
                                  "label": x @ np.ones(16)}))._coefficient)
    ).all()
    normal = LinearRegression(sharding_plan=FSDP)
    normal.set(LinearRegression.SOLVER, "normal")
    with pytest.raises(ValueError, match="solver='sgd'"):
        normal.fit(Table({"features": x, "label": y}))


# ---------------------------------------------------------------------------
# Checkpoint integration: plan-derived layout tags, one source of truth
# ---------------------------------------------------------------------------

def test_save_plan_records_derived_layout_tags(tmp_path):
    mgr = CheckpointManager(str(tmp_path), world_size=8)
    state = init_linear_state(64, "adam", np.float32)
    mgr.save(state, 1, plan=FSDP)
    with open(tmp_path / "ckpt-1" / "meta.json") as fh:
        meta = json.load(fh)
    # dict leaves flatten in sorted key order: coef, m, step, v.
    assert meta["layouts"] == ["sharded:0", "sharded:0", "replicated",
                               "sharded:0"]
    assert meta["world_size"] == 8


def test_save_plan_conflicting_explicit_layouts_raise_typed(tmp_path):
    """Satellite bugfix: stale hand-written layouts used to win silently
    over the plan; now the plan is authoritative and a conflicting
    override is a typed, named refusal."""
    mgr = CheckpointManager(str(tmp_path))
    state = init_linear_state(64, "sgd", np.float32)
    with pytest.raises(LayoutConflictError, match="authoritative") as exc:
        mgr.save(state, 1, plan=FSDP, layouts="replicated")
    assert "coef" in str(exc.value)  # names the first conflicting leaf
    assert mgr.all_epochs() == []  # nothing committed
    # An AGREEING explicit override is redundant but legal.
    mgr.save(state, 2, plan=FSDP,
             layouts={"coef": "sharded:0", "momentum": "sharded:0"})
    assert mgr.all_epochs() == [2]


def test_save_plan_through_save_agreed(tmp_path):
    from flinkml_tpu.iteration.checkpoint import save_agreed

    mgr = CheckpointManager(str(tmp_path), world_size=8)
    save_agreed(mgr, init_linear_state(64, "sgd", np.float32), 3,
                plan=FSDP)
    with open(tmp_path / "ckpt-3" / "meta.json") as fh:
        assert json.load(fh)["layouts"] == ["sharded:0", "sharded:0"]


# ---------------------------------------------------------------------------
# THE acceptance scenario: over-budget replicated -> FSDP trains,
# checkpoints with plan tags, resumes at a different world
# ---------------------------------------------------------------------------

def test_over_budget_model_trains_under_fsdp_and_resumes_elsewhere(tmp_path):
    dim = 64
    x, y = _data(n=96, dim=dim, seed=1)
    dt = x.dtype  # f64 under the test config's x64
    # Provably over budget replicated: coef + momentum = 2 leaves.
    budget = int(dim * dt.itemsize * 2 * 0.75)
    assert per_device_state_bytes(
        REPLICATED, {"data": 8}, {"coef": (dim,)},
        dtype_bytes=dt.itemsize) > budget
    # infer_plan picks FSDP as the cheapest fitting plan...
    mesh8 = _mesh(FSDP)
    plan = infer_plan(mesh8, {"coef": (dim,)}, budget,
                      dtype_bytes=dt.itemsize)
    assert plan.name == "fsdp"
    # ... and the pre-compile gate refuses the replicated plan outright.
    with pytest.raises(PlanValidationError, match="FML503"):
        train_linear_plan(x, y, None, BATCH_PARALLEL,
                          _mesh(BATCH_PARALLEL), max_iter=1,
                          hbm_budget_bytes=budget)

    golden = train_linear_plan(
        x, y, None, REPLICATED, _mesh(REPLICATED), max_iter=12,
        learning_rate=0.5,
    )
    mgr = CheckpointManager(str(tmp_path), max_to_keep=10, rescale="reshard")
    coef8 = train_linear_plan(
        x, y, None, plan, mesh8, max_iter=12, learning_rate=0.5,
        hbm_budget_bytes=budget, checkpoint_manager=mgr,
        checkpoint_interval=4,
    )
    np.testing.assert_allclose(coef8, golden, rtol=1e-9, atol=1e-12)
    with open(tmp_path / "ckpt-12" / "meta.json") as fh:
        meta = json.load(fh)
    assert meta["layouts"] == ["sharded:0", "sharded:0"]
    assert meta["world_size"] == 8

    # Resume the final snapshot at world 2: the plan-derived sharded:0
    # tags make the reshard legal, and continuing for 0 further epochs
    # returns the same (global) coefficient.
    mesh2 = _mesh(FSDP, n=2)
    coef2 = train_linear_plan(
        x, y, None, FSDP, mesh2, max_iter=12, learning_rate=0.5,
        checkpoint_manager=mgr, checkpoint_interval=4, resume=True,
    )
    np.testing.assert_allclose(coef2, coef8, rtol=0, atol=0)


# ---------------------------------------------------------------------------
# shard_slice_elems at uneven-shard budget boundaries (ISSUE 17)
# ---------------------------------------------------------------------------

def test_uneven_shard_boundary_accepted_exactly_at_budget():
    """An UNEVEN vocab (1001 rows over 8 shards -> ceil to 126-row
    slices) whose padded slice fits EXACTLY at the budget must be
    accepted by infer_plan — the static footprint model uses the same
    per-dim ceil the runtime padded layout allocates, so the boundary
    cannot be off by one padded row."""
    from flinkml_tpu.sharding import EMBEDDING
    from flinkml_tpu.sharding.plan import shard_slice_elems

    mesh = {"data": 1, "fsdp": 4, "tp": 2}
    vocab, dim = 1001, 16
    name = "emb/embedding"
    slice_elems = shard_slice_elems(EMBEDDING, mesh, name, (vocab, dim))
    assert slice_elems == 126 * dim  # ceil(1001 / 8), not 1001 // 8
    exact = slice_elems * 4 * 2  # f32, 1 optimizer slot
    plan = infer_plan(mesh, {name: (vocab, dim)}, exact)
    assert plan.name == "embedding"
    with pytest.raises(NoFeasiblePlanError):
        infer_plan(mesh, {name: (vocab, dim)}, exact - 1)


def test_uneven_shard_bytes_match_embedding_table_padded_layout():
    """The static model's bytes ARE the EmbeddingTable padded layout's
    bytes: ceil-divided rows x dim x width x (1 + slots), so FML503,
    infer_plan, and the runtime placement agree at every boundary."""
    from flinkml_tpu.embeddings import EmbeddingTable
    from flinkml_tpu.sharding import EMBEDDING
    from flinkml_tpu.sharding.plan import shard_slice_elems

    vocab, dim, slots = 1001, 16, 1
    table = EmbeddingTable("emb", vocab, dim, plan=EMBEDDING,
                           optimizer_slots=slots)
    axis_sizes = dict(table.mesh.mesh.shape)
    static = shard_slice_elems(
        EMBEDDING, axis_sizes, table.param_name, (vocab, dim)
    ) * table.dtype.itemsize * (1 + slots)
    assert table.per_device_bytes() == static
    assert table.padded_vocab == 126 * table.n_shards
