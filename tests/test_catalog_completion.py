"""NGram / ANOVATest / FValueTest / VectorIndexer / MinHashLSH."""

import numpy as np
import pytest
from sklearn.feature_selection import f_regression as sk_f_regression

from flinkml_tpu.models import (
    ANOVATest,
    FValueTest,
    MinHashLSH,
    MinHashLSHModel,
    NGram,
    Tokenizer,
    VectorIndexer,
    VectorIndexerModel,
)
from flinkml_tpu.models.selectors import f_regression_test
from flinkml_tpu.linalg import SparseVector
from flinkml_tpu.table import Table


# -- NGram -------------------------------------------------------------------

def test_ngram_bigrams_and_short_rows():
    t = Table({"text": np.asarray(["a b c d", "x y", "solo"])})
    (tok,) = Tokenizer().set_input_col("text").set_output_col("tok").transform(t)
    (out,) = NGram().set_input_col("tok").set_output_col("ng").transform(tok)
    assert out["ng"][0] == ["a b", "b c", "c d"]
    assert out["ng"][1] == ["x y"]
    assert out["ng"][2] == []
    (tri,) = NGram().set_n(3).set_input_col("tok").set_output_col("ng").transform(tok)
    assert tri["ng"][0] == ["a b c", "b c d"]


# -- ANOVATest / FValueTest --------------------------------------------------

def test_anova_test_operator():
    rng = np.random.default_rng(0)
    y = rng.integers(0, 3, 300).astype(float)
    x = rng.normal(size=(300, 3))
    x[:, 1] += 2 * y
    (out,) = ANOVATest().transform(Table({"features": x, "label": y}))
    assert out.column_names == ["featureIndex", "pValue", "statistic"]
    assert out["pValue"][1] < 1e-6 < out["pValue"][0]


def test_f_value_test_matches_sklearn():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(250, 4))
    y = 2.0 * x[:, 2] + 0.5 * rng.normal(size=250)
    f, p = f_regression_test(x, y)
    f_ref, p_ref = sk_f_regression(x, y)
    np.testing.assert_allclose(f, f_ref, rtol=1e-9)
    np.testing.assert_allclose(p, p_ref, rtol=1e-7, atol=1e-14)
    (out,) = FValueTest().transform(Table({"features": x, "label": y}))
    assert out["pValue"][2] < 1e-10


# -- VectorIndexer -----------------------------------------------------------

def _vi_data():
    rng = np.random.default_rng(2)
    cont = rng.normal(size=100)
    cat = rng.choice([-1.0, 0.0, 5.0], size=100)
    binary = rng.choice([0.0, 1.0], size=100)
    return np.stack([cont, cat, binary], axis=1)


def test_vector_indexer_detects_and_indexes():
    x = _vi_data()
    t = Table({"input": x})
    model = VectorIndexer().set_max_categories(5).fit(t)
    assert set(model.category_maps) == {1, 2}
    (out,) = model.transform(t)
    o = out["output"]
    np.testing.assert_array_equal(o[:, 0], x[:, 0])   # continuous untouched
    # cat values -1,0,5 -> 0,1,2 by sorted order
    np.testing.assert_array_equal(np.unique(o[:, 1]), [0.0, 1.0, 2.0])
    assert np.all(o[x[:, 1] == -1.0, 1] == 0.0)
    assert np.all(o[x[:, 1] == 5.0, 1] == 2.0)


def test_vector_indexer_handle_invalid_and_roundtrip(tmp_path):
    x = _vi_data()
    t = Table({"input": x})
    model = VectorIndexer().set_max_categories(5).fit(t)
    probe = x[:3].copy()
    probe[0, 1] = 99.0   # unseen category
    pt = Table({"input": probe})
    with pytest.raises(ValueError, match="not seen"):
        model.transform(pt)
    (skipped,) = model.set_handle_invalid("skip").transform(pt)
    assert skipped.num_rows == 2
    (kept,) = model.set_handle_invalid("keep").transform(pt)
    assert kept["output"][0, 1] == 3.0   # catch-all index
    model.save(str(tmp_path / "vi"))
    loaded = VectorIndexerModel.load(str(tmp_path / "vi"))
    assert set(loaded.category_maps) == set(model.category_maps)
    clone = VectorIndexerModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    (a,) = clone.set_handle_invalid("keep").transform(pt)
    np.testing.assert_array_equal(a["output"], kept["output"])


# -- MinHashLSH --------------------------------------------------------------

def _sparse_row(size, idx):
    return SparseVector(size, np.asarray(idx), np.ones(len(idx)))


def test_minhash_identical_rows_same_hash_and_queries(tmp_path):
    size = 64
    rows = np.empty(5, dtype=object)
    rows[0] = _sparse_row(size, [1, 5, 9])
    rows[1] = _sparse_row(size, [1, 5, 9])           # identical to 0
    rows[2] = _sparse_row(size, [1, 5, 9, 11])       # close
    rows[3] = _sparse_row(size, [40, 41, 42])        # far
    rows[4] = _sparse_row(size, [2, 6])
    t = Table({"input": rows, "id": np.arange(5)})
    model = MinHashLSH().set_num_hash_tables(4).set_seed(0).fit(t)
    (hashed,) = model.transform(t)
    np.testing.assert_array_equal(hashed["output"][0], hashed["output"][1])
    assert not np.array_equal(hashed["output"][0], hashed["output"][3])

    nn = model.approx_nearest_neighbors(t, _sparse_row(size, [1, 5, 9]), 2)
    assert set(nn["id"][:2]) == {0, 1}
    np.testing.assert_allclose(nn["distCol"][:2], 0.0)

    join = model.approx_similarity_join(t, t, threshold=0.5)
    pairs = set(zip(join["idA"].tolist(), join["idB"].tolist()))
    assert (0, 1) in pairs and (0, 2) in pairs
    assert (0, 3) not in pairs

    model.save(str(tmp_path / "lsh"))
    loaded = MinHashLSHModel.load(str(tmp_path / "lsh"))
    (h2,) = loaded.transform(t)
    np.testing.assert_array_equal(h2["output"], hashed["output"])


def test_minhash_dense_input_and_recall():
    rng = np.random.default_rng(3)
    x = (rng.uniform(size=(200, 32)) < 0.2).astype(np.float64)
    x[1] = x[0]  # plant a duplicate
    t = Table({"input": x})
    model = MinHashLSH().set_num_hash_tables(8).set_seed(1).fit(t)
    nn = model.approx_nearest_neighbors(t, x[0], 2)
    assert nn["distCol"][0] == 0.0 and nn["distCol"][1] == 0.0


def test_vector_indexer_all_continuous_roundtrip():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(60, 3))  # everything continuous
    t = Table({"input": x})
    model = VectorIndexer().set_max_categories(3).fit(t)
    assert model.category_maps == {}
    clone = VectorIndexerModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    (out,) = clone.transform(t)
    np.testing.assert_array_equal(out["output"], x)


def test_vector_indexer_nan_handled_as_invalid():
    x = np.asarray([[0.0], [1.0], [np.nan]])
    t = Table({"input": x})
    model = VectorIndexer().set_max_categories(3).fit(t)
    with pytest.raises(ValueError, match="not seen"):
        model.transform(t)
    (kept,) = model.set_handle_invalid("keep").transform(t)
    np.testing.assert_array_equal(kept["output"][:, 0], [0.0, 1.0, 2.0])


def test_lsh_empty_join_result():
    rows_a = np.empty(1, dtype=object)
    rows_a[0] = _sparse_row(32, [0, 1])
    rows_b = np.empty(1, dtype=object)
    rows_b[0] = _sparse_row(32, [20, 21])
    model = MinHashLSH().set_num_hash_tables(2).set_seed(0).fit(
        Table({"input": rows_a})
    )
    join = model.approx_similarity_join(
        Table({"input": rows_a}), Table({"input": rows_b}), threshold=0.01
    )
    assert join.num_rows == 0
