"""Serving engine under concurrency: parity, retraces, liveness, overload.

The acceptance contract of the serving subsystem:

  1. ≥8 concurrent client threads get responses bitwise-identical to a
     single-request direct ``transform`` — micro-batch packing, bucket
     padding, and per-request slicing are invisible to clients.
  2. Steady state is zero-retrace: after the engine's load-time warmup,
     no fused-cache compile happens no matter how requests are packed
     (``no_retrace`` marker + TransferRetraceGuard).
  3. Serving coexists with a concurrently running ``train_kmeans_stream``
     over overlapping devices — no deadlock, and the recorded dispatch
     trace passes the analyzer's FML302 collective-interleaving check.
  4. Saturation degrades gracefully: a full bounded queue either sheds to
     the host path (correct results, ``shed=True``) or rejects with the
     typed overload error; deadlines produce ServingTimeoutError.
  5. Hot swap mid-traffic: every response carries the version that served
     it, and responses verify bitwise against THAT version's model — no
     dropped and no mis-versioned responses across the swap.
  6. Pool rolling swaps under racing registry writes: a rollback racing a
     publish across a following ReplicaPool converges EVERY replica to
     the registry's final CURRENT pointer, with zero mis-versioned
     responses throughout.
"""

import threading
import time

import numpy as np
import pytest

from flinkml_tpu import pipeline_fusion
from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import MinMaxScaler, StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.serving import (
    ModelRegistry,
    ServingConfig,
    ServingEngine,
    ServingOverloadError,
    ServingTimeoutError,
)
from flinkml_tpu.table import Table


def _data(n=200, d=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return x, y


def _three_stage_chain(x, y):
    """features -> scaled -> squashed -> prediction, all kernel-capable
    (fuses into one program per bucket)."""
    train = Table({"features": x, "label": y})
    sc = (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "scaled")
        .fit(train)
    )
    (t2,) = sc.transform(train)
    mm = (
        MinMaxScaler()
        .set(MinMaxScaler.INPUT_COL, "scaled")
        .set(MinMaxScaler.OUTPUT_COL, "squashed")
        .fit(t2)
    )
    (t3,) = mm.transform(t2)
    lr = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, "squashed")
        .set(LogisticRegression.LABEL_COL, "label")
        .set_max_iter(3)
        .fit(t3)
    )
    return PipelineModel([sc, mm, lr])


def _engine(source, x, name="default", **cfg):
    config = ServingConfig(**{
        "max_batch_rows": 64,
        "max_queue_rows": 512,
        "warmup_row_counts": None,  # every bucket up to max_batch_rows
        **cfg,
    })
    return ServingEngine(
        source, Table({"features": x[:4]}), config,
        output_cols=("prediction", "rawPrediction"),
        name=name,
    )


@pytest.mark.no_retrace(allow_compiles=1)
def test_eight_thread_parity_zero_retrace():
    """8 client threads, mixed row counts, vs single-request transform —
    bitwise. The whole test (warmup included) budgets ONE counted fused
    compile: the chain's first compile; every other bucket is a policy-
    allowed new-bucket compile, and steady state compiles nothing."""
    x, y = _data()
    pm = _three_stage_chain(x, y)
    pipeline_fusion.reset_cache()
    # A dedicated metrics-group name: the process-wide registry
    # accumulates across tests, and this test asserts EXACT counters.
    eng = _engine(pm, x, name="parity8").start()
    compiled_after_warmup = []
    pipeline_fusion.on_compile.append(compiled_after_warmup.append)
    errors = []

    def client(tid):
        rng = np.random.default_rng(tid)
        try:
            for _ in range(25):
                rows = int(rng.integers(1, 13))
                lo = int(rng.integers(0, x.shape[0] - rows))
                sl = x[lo:lo + rows]
                resp = eng.predict({"features": sl})
                (ref,) = pm.transform(Table({"features": sl}))
                for c in ("prediction", "rawPrediction"):
                    ev, av = ref.column(c), resp.column(c)
                    assert ev.dtype == av.dtype
                    np.testing.assert_array_equal(ev, av)
        except BaseException as e:  # noqa: BLE001 — surface to the main thread
            errors.append(e)

    try:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "client threads hung"
        assert not errors, errors[:3]
        # Zero steady-state retraces: the reference transforms above run
        # at row counts inside warmed buckets, so even they compile
        # nothing new.
        assert compiled_after_warmup == []
        stats = eng.stats()
        assert stats["counters"]["requests"] == 200
        assert stats["counters"]["rows"] == stats["counters"]["batch_rows"]
    finally:
        pipeline_fusion.on_compile.remove(compiled_after_warmup.append)
        eng.stop()


def test_serving_coexists_with_kmeans_stream():
    """Liveness: 4 serving client threads while train_kmeans_stream runs
    its whole Lloyd loop (holding the mesh lock) on overlapping devices.
    Single-device serving programs cannot interleave the multi-device
    collective rendezvous, so both must make progress; the recorded
    dispatch trace must pass the analyzer's FML302 check."""
    from flinkml_tpu.analysis.collectives import (
        DispatchEvent,
        check_dispatch_trace,
    )
    from flinkml_tpu.models.kmeans import train_kmeans_stream
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.parallel import dispatch as _dispatch

    x, y = _data(n=240)
    pm = _three_stage_chain(x, y)
    eng = _engine(pm, x).start()
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(512, 4)).astype(np.float32)
    batches = [{"x": xs[i::4]} for i in range(4)]
    mesh = DeviceMesh()

    events = []
    _dispatch.add_dispatch_observer(events.append)
    stop = threading.Event()
    errors = []
    served = [0]

    def client(tid):
        try:
            while not stop.is_set():
                rows = 1 + (tid % 4)
                resp = eng.predict({"features": x[tid * 3:tid * 3 + rows]})
                assert resp.columns["prediction"].shape == (rows,)
                served[0] += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    trainer_out = []

    def trainer():
        trainer_out.append(train_kmeans_stream(
            batches, k=3, mesh=mesh, max_iter=6, seed=0,
        ))

    try:
        clients = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        tt = threading.Thread(target=trainer)
        for t in clients:
            t.start()
        tt.start()
        tt.join(timeout=300)
        assert not tt.is_alive(), "training deadlocked against serving"
        time.sleep(0.2)
        stop.set()
        for t in clients:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in clients), "serving starved"
        assert not errors, errors[:3]
        assert trainer_out and trainer_out[0].shape == (3, 4)
        assert served[0] > 0
        # Analyzer audit of the real interleaving we just produced.
        trace = [
            DispatchEvent(
                thread=e["thread"], program=e["program"],
                devices=tuple(e["devices"]),
                collectives=tuple(e["collectives"]),
                locks=tuple(e["locks"]),
            )
            for e in events
        ]
        assert {e.program for e in trace} >= {
            "serving.batch", "kmeans.lloyd_epoch"
        }
        assert check_dispatch_trace(trace) == []
    finally:
        _dispatch.remove_dispatch_observer(events.append)
        eng.stop()


class _GatedStage(AlgoOperator):
    """Host stage that BLOCKS the dispatcher thread until released —
    deterministic queue saturation (no sleep races). Caller threads (the
    shed path, reference transforms) pass through untouched."""

    def __init__(self):
        super().__init__()
        self.entered = threading.Event()  # dispatcher is inside transform
        self.release = threading.Event()

    def transform(self, *inputs):
        if threading.current_thread().name.startswith("serving-"):
            self.entered.set()
            assert self.release.wait(timeout=120)
        return inputs


def _gated_engine(x, y, **cfg):
    pm = _three_stage_chain(x, y)
    gate = _GatedStage()
    gated = PipelineModel([gate, *pm.stages])
    eng = _engine(
        gated, x, max_batch_rows=8, max_queue_rows=8,
        warmup_row_counts=(1,), **cfg,
    )
    return eng, gate, gated


def _background_predict(eng, features):
    """Fire-and-forget client; shutdown errors are expected and muted."""

    def run():
        try:
            eng.predict(features)
        except Exception:  # noqa: BLE001 — rejected at shutdown, by design
            pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _saturate(eng, gate, x):
    """Park the dispatcher inside the gate, then fill the bounded queue
    to exactly max_queue_rows with a background request."""
    t1 = _background_predict(eng, {"features": x[:1]})
    assert gate.entered.wait(timeout=60)  # dispatcher blocked in-flight
    t2 = _background_predict(eng, {"features": x[:8]})
    deadline = time.monotonic() + 60
    while eng.stats()["queued_rows"] < 8:  # the 8-row filler is queued
        assert time.monotonic() < deadline
        time.sleep(0.005)
    return t1, t2


def test_overload_rejects_with_typed_error():
    x, y = _data()
    eng, gate, _ = _gated_engine(x, y, shed_on_overload=False)
    eng.start()
    try:
        _saturate(eng, gate, x)
        with pytest.raises(ServingOverloadError):
            eng.predict({"features": x[:1]})
        assert eng.stats()["counters"]["rejected"] >= 1
    finally:
        gate.release.set()
        eng.stop(drain=False)


def test_overload_sheds_to_host_path_with_parity():
    x, y = _data()
    eng, gate, gated = _gated_engine(x, y, shed_on_overload=True)
    eng.start()
    try:
        _saturate(eng, gate, x)
        resp = eng.predict({"features": x[:5]})
        assert resp.shed
        (ref,) = gated.transform(Table({"features": x[:5]}))
        np.testing.assert_array_equal(
            ref.column("prediction"), resp.column("prediction")
        )
        assert eng.stats()["counters"]["shed_requests"] >= 1
    finally:
        gate.release.set()
        eng.stop(drain=False)


def test_deadline_expiry_raises_timeout():
    x, y = _data()
    eng, gate, _ = _gated_engine(x, y, shed_on_overload=False)
    eng.start()
    try:
        # Park the dispatcher; the next request cannot be dispatched and
        # must fail by deadline — whether expired in-queue or while
        # waiting on the in-flight batch.
        _background_predict(eng, {"features": x[:1]})
        assert gate.entered.wait(timeout=60)
        with pytest.raises(ServingTimeoutError):
            eng.predict({"features": x[:1]}, timeout_ms=20.0)
        assert eng.stats()["counters"]["timeouts"] >= 1
    finally:
        gate.release.set()
        eng.stop(drain=False)


def test_hot_swap_mid_traffic_no_misversioned_responses(tmp_path):
    """Swap under load: every response verifies bitwise against the model
    of the version it claims, and nothing is dropped."""
    x, y = _data()
    pm1 = _three_stage_chain(x, y)
    pm2 = _three_stage_chain(x, -y + 1)
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(pm1)
    models = {1: pm1, 2: pm2}
    eng = _engine(reg, x).start()
    errors = []
    versions_seen = set()
    done = []  # one append per answered request (append is atomic)
    swapped = threading.Event()

    def client(tid):
        rng = np.random.default_rng(tid)

        def one_request():
            rows = int(rng.integers(1, 9))
            lo = int(rng.integers(0, x.shape[0] - rows))
            sl = x[lo:lo + rows]
            resp = eng.predict({"features": sl})
            versions_seen.add(resp.version)
            ref_model = models[resp.version]
            (ref,) = ref_model.transform(Table({"features": sl}))
            np.testing.assert_array_equal(
                ref.column("prediction"), resp.column("prediction")
            )
            done.append(1)

        try:
            # ≥30 requests each, then keep the traffic flowing until the
            # swap has landed — a fixed pre-swap sleep lost the race on
            # a warm box (all 180 requests finished before the swap).
            n = 0
            while n < 30 or (not swapped.is_set() and n < 3000):
                one_request()
                n += 1
            one_request()  # issued after swap_to returned: version 2
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    try:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        while len(done) < 30 and not errors:  # clients warm and mid-flight
            time.sleep(0.005)
        reg.publish(pm2)
        eng.swap_to(2)
        swapped.set()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
        assert not errors, errors[:3]
        assert len(done) >= 186  # zero dropped: every request answered
        assert versions_seen == {1, 2}
    finally:
        eng.stop()


def test_pool_rollback_races_publish_converges(tmp_path):
    """A rollback racing a publish across a following 3-replica pool:
    whatever order the registry commits them, every replica must converge
    to the FINAL CURRENT pointer (the registry serializes listener
    deliveries and re-reads the pointer per delivery; the pool's rolling
    swap re-reads it per replica), and every response served throughout
    must verify bitwise against the model of the version it claims."""
    from flinkml_tpu.serving import ReplicaPool

    x, y = _data()
    pm1 = _three_stage_chain(x, y)
    pm2 = _three_stage_chain(x, -y + 1)
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(pm1)
    models = {1: pm1, 2: pm2}
    pool = ReplicaPool(
        reg, Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=64, max_queue_rows=512,
                             max_wait_ms=1.0),
        n_replicas=3, output_cols=("prediction",), name="race_pool",
    ).start()
    pool.follow_registry()
    errors = []
    versions_seen = set()
    done = []  # one append per answered request (append is atomic)
    stop = threading.Event()

    def client(tid):
        rng = np.random.default_rng(tid)
        try:
            while not stop.is_set():
                rows = int(rng.integers(1, 9))
                lo = int(rng.integers(0, x.shape[0] - rows))
                sl = x[lo:lo + rows]
                resp = pool.predict({"features": sl})
                versions_seen.add(resp.version)
                (ref,) = models[resp.version].transform(
                    Table({"features": sl})
                )
                np.testing.assert_array_equal(
                    ref.column("prediction"), resp.column("prediction")
                )
                done.append(1)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    def publisher():
        reg.publish(pm2)

    def rollbacker():
        # Spin until v2 exists, then roll back — racing the publish's
        # listener delivery (and the pool's roll) as closely as possible.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if 2 in reg.versions():
                reg.rollback(1)
                return
            time.sleep(0.0005)

    try:
        clients = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        for t in clients:
            t.start()
        time.sleep(0.2)
        tp = threading.Thread(target=publisher)
        tr = threading.Thread(target=rollbacker)
        tp.start()
        tr.start()
        tp.join(timeout=120)
        tr.join(timeout=120)
        assert not tp.is_alive() and not tr.is_alive()
        time.sleep(0.3)  # let the last (serialized) delivery finish
        stop.set()
        for t in clients:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in clients)
        assert not errors, errors[:3]
        final = reg.current_version()
        assert final == 1  # the rollback ran after the publish committed
        assert pool.versions() == {"r0": final, "r1": final, "r2": final}, (
            "replicas did not converge to the registry pointer"
        )
        assert done  # at least one request answered during the race
        assert versions_seen <= {1, 2}
        assert pool.predict({"features": x[:2]}).version == final
    finally:
        pool.stop()
