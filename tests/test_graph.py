"""Graph/GraphBuilder tests — mirrors the reference's GraphTest
(``flink-ml-core/src/test/java/.../builder/GraphTest.java``)."""

import numpy as np
import pytest

from flinkml_tpu.graph import Graph, GraphBuilder, GraphModel
from flinkml_tpu.table import Table

from tests.example_stages import SumEstimator, SumModel, UnionAlgoOperator


def make_table(values):
    return Table({"value": np.asarray(values)})


def test_linear_graph_fit_transform():
    b = GraphBuilder()
    src = b.create_table_id()
    est = SumEstimator()
    out1 = b.add_estimator(est, src)
    model2 = SumModel().set_delta(7)
    out2 = b.add_algo_operator(model2, out1[0])
    graph = b.build_estimator([src], [out2[0]])

    gm = graph.fit(make_table([1, 2, 3]))  # delta 6
    (out,) = gm.transform(make_table([0]))
    assert out["value"][0] == 13  # 0 + 6 + 7


def test_dag_with_union():
    b = GraphBuilder()
    a = b.create_table_id()
    c = b.create_table_id()
    union = UnionAlgoOperator()
    merged = b.add_algo_operator(union, a, c)
    est = SumEstimator()
    out = b.add_estimator(est, merged[0])
    graph = b.build_estimator([a, c], [out[0]])
    gm = graph.fit(make_table([1]), make_table([2, 3]))
    (res,) = gm.transform(make_table([0]), make_table([0]))
    # fit: union=[1,2,3], delta=6; transform: union of [0],[0] + 6 each.
    assert np.array_equal(res["value"], [6, 6])


def test_graph_model_data_wiring():
    b = GraphBuilder()
    src = b.create_table_id()
    est = SumEstimator()
    out = b.add_estimator(est, src)
    model_data = b.get_model_data_from_estimator(est)
    graph = b.build_estimator([src], [out[0]], output_model_data=[model_data[0]])
    gm = graph.fit(make_table([1, 2, 3]))
    data = gm.get_model_data()
    assert int(data[0]["delta"][0]) == 6


def test_get_model_data_returns_only_wired_tables():
    b = GraphBuilder()
    src = b.create_table_id()
    m1 = SumModel().set_delta(1)
    m2 = SumModel().set_delta(2)
    o1 = b.add_algo_operator(m1, src)
    o2 = b.add_algo_operator(m2, o1[0])
    d2 = b.get_model_data_from_model(m2)
    # Only m2's model data is wired out.
    gm = b.build_model([src], [o2[0]], output_model_data=[d2[0]])
    gm.transform(make_table([0]))
    data = gm.get_model_data()
    assert len(data) == 1 and int(data[0]["delta"][0]) == 2


def test_get_model_data_unwired_raises():
    b = GraphBuilder()
    src = b.create_table_id()
    out = b.add_algo_operator(SumModel().set_delta(1), src)
    gm = b.build_model([src], [out[0]])
    with pytest.raises(ValueError):
        gm.get_model_data()


def test_set_model_data_arity_checked():
    b = GraphBuilder()
    src = b.create_table_id()
    md = b.create_table_id()
    model = SumModel()
    out = b.add_algo_operator(model, src)
    b.set_model_data_on_model(model, md)
    gm = b.build_model([src], [out[0]], input_model_data=[md])
    with pytest.raises(ValueError):
        gm.set_model_data(
            Table({"delta": np.array([1])}), Table({"delta": np.array([2])})
        )


def test_graph_set_model_data():
    b = GraphBuilder()
    src = b.create_table_id()
    model_data_in = b.create_table_id()
    model = SumModel()
    b.add_algo_operator(model, src)
    b.set_model_data_on_model(model, model_data_in)
    out_ids = b._stage_nodes[id(model)].output_ids
    gm = b.build_model([src], [out_ids[0]], input_model_data=[model_data_in])
    gm.set_model_data(Table({"delta": np.array([42])}))
    (out,) = gm.transform(make_table([1]))
    assert out["value"][0] == 43


def test_transform_without_required_model_data_raises():
    b = GraphBuilder()
    src = b.create_table_id()
    md = b.create_table_id()
    model = SumModel()
    out = b.add_algo_operator(model, src)
    b.set_model_data_on_model(model, md)
    gm = b.build_model([src], [out[0]], input_model_data=[md])
    with pytest.raises(ValueError, match="set_model_data"):
        gm.transform(make_table([1]))


def test_build_model_rejects_estimator_nodes():
    b = GraphBuilder()
    src = b.create_table_id()
    out = b.add_estimator(SumEstimator(), src)
    with pytest.raises(ValueError):
        b.build_model([src], [out[0]])


def test_unreachable_input_raises():
    b = GraphBuilder()
    src = b.create_table_id()
    orphan = b.create_table_id()
    out = b.add_algo_operator(SumModel().set_delta(1), orphan)
    graph = b.build_estimator([src], [out[0]])
    with pytest.raises(ValueError):
        graph.fit(make_table([1]))


def test_graph_save_load(tmp_path):
    b = GraphBuilder()
    src = b.create_table_id()
    out1 = b.add_estimator(SumEstimator(), src)
    out2 = b.add_algo_operator(SumModel().set_delta(7), out1[0])
    graph = b.build_estimator([src], [out2[0]])
    p = str(tmp_path / "graph")
    graph.save(p)
    loaded = Graph.load(p)
    gm = loaded.fit(make_table([1, 2, 3]))
    (out,) = gm.transform(make_table([0]))
    assert out["value"][0] == 13


def test_graph_model_save_load(tmp_path):
    b = GraphBuilder()
    src = b.create_table_id()
    out1 = b.add_estimator(SumEstimator(), src)
    graph = b.build_estimator([src], [out1[0]])
    gm = graph.fit(make_table([1, 2, 3]))
    p = str(tmp_path / "gm")
    gm.save(p)
    loaded = GraphModel.load(p)
    (out,) = loaded.transform(make_table([10]))
    assert out["value"][0] == 16
