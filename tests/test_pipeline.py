"""Pipeline semantics tests — mirrors the reference's PipelineTest
(``flink-ml-core/src/test/java/.../api/PipelineTest.java``) and the Python
``test_pipeline.py``."""

import numpy as np

from flinkml_tpu.pipeline import Pipeline, PipelineModel
from flinkml_tpu.table import Table

from tests.example_stages import SumEstimator, SumModel, UnionAlgoOperator


def make_table(values):
    return Table({"value": np.asarray(values)})


def test_pipeline_model_transform_chains():
    # Two SumModels with deltas 10 and 20: input + 30.
    m1 = SumModel().set_delta(10)
    m2 = SumModel().set_delta(20)
    pm = PipelineModel([m1, m2])
    (out,) = pm.transform(make_table([1, 2, 3]))
    assert np.array_equal(out["value"], [31, 32, 33])


def test_pipeline_fit_transforms_up_to_last_estimator():
    # Reference semantics (Pipeline.java:79-107): inputs advance through a
    # stage only while an Estimator remains downstream.
    # Stage 0: SumEstimator fit on [1,2,3] -> delta 6; transforms inputs to
    # [7,8,9] because stage 2 is an Estimator.
    # Stage 1: SumModel(delta=1): [8,9,10].
    # Stage 2: SumEstimator fit on [8,9,10] -> delta 27. No estimator after,
    # so inputs stop advancing.
    pipeline = Pipeline([SumEstimator(), SumModel().set_delta(1), SumEstimator()])
    model = pipeline.fit(make_table([1, 2, 3]))
    stages = model.stages
    assert stages[0].get_delta() == 6
    assert stages[2].get_delta() == 27
    # Full PipelineModel.transform applies all three: x + 6 + 1 + 27.
    (out,) = model.transform(make_table([0]))
    assert out["value"][0] == 34


def test_pipeline_save_load(tmp_path):
    pipeline = Pipeline([SumEstimator(), SumModel().set_delta(5)])
    p = str(tmp_path / "pipeline")
    pipeline.save(p)
    loaded = Pipeline.load(p)
    assert len(loaded.stages) == 2
    assert isinstance(loaded.stages[0], SumEstimator)
    assert loaded.stages[1].get_delta() == 5


def test_pipeline_model_save_load(tmp_path):
    pm = PipelineModel([SumModel().set_delta(10), SumModel().set_delta(20)])
    p = str(tmp_path / "pm")
    pm.save(p)
    loaded = PipelineModel.load(p)
    (out,) = loaded.transform(make_table([1]))
    assert out["value"][0] == 31


def test_nested_pipeline():
    inner = Pipeline([SumEstimator()])
    outer = Pipeline([inner, SumModel().set_delta(100)])
    model = outer.fit(make_table([1, 2]))
    (out,) = model.transform(make_table([0]))
    # inner delta = 3, then +100.
    assert out["value"][0] == 103


def test_multi_input_algo_operator():
    op = UnionAlgoOperator()
    (out,) = op.transform(make_table([1]), make_table([2, 3]))
    assert out.num_rows == 3
