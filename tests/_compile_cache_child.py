"""Clean-process scenarios behind ``tests/test_compile_cache.py``.

Why a child process: once jax's persistent compilation cache LOADS one
executable in a process, XLA:CPU registers that executable's jit-kernels
as resident-but-not-re-emittable, and every LATER compile sharing a
content-identical kernel serializes without it ("Symbols not found" at
deserialize — the store's post-serialize load check refuses such
artifacts by design). The suite's conftest enables that cache for speed,
so deterministic store round-trips must run in a process that never
touched it — which is also exactly the production cold-start shape the
subsystem exists for. This script runs every serialization-dependent
scenario in one fresh interpreter and prints a JSON report; the pytest
module asserts over it.
"""

import json
import logging
import os
import shutil
import sys
import tempfile
import threading
import time


def _outputs(model, table):
    import numpy as np

    (out,) = model.transform(table)
    return {
        c: np.asarray(out.column(c))
        for c in out.column_names if c not in ("features", "label")
    }


def _fitted_chain(n=520, d=11, seed=0):
    import numpy as np

    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import MinMaxScaler, StandardScaler
    from flinkml_tpu.pipeline import PipelineModel
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    train = Table({"features": x, "label": y})
    scaler = (StandardScaler().set(StandardScaler.INPUT_COL, "features")
              .set(StandardScaler.OUTPUT_COL, "s1").fit(train))
    (t1,) = scaler.transform(train)
    mm = (MinMaxScaler().set(MinMaxScaler.INPUT_COL, "s1")
          .set(MinMaxScaler.OUTPUT_COL, "s2").fit(t1))
    (t2,) = mm.transform(t1)
    lr = (LogisticRegression()
          .set(LogisticRegression.FEATURES_COL, "s2")
          .set(LogisticRegression.LABEL_COL, "label")
          .set_max_iter(2).fit(t2))
    return PipelineModel([scaler, mm, lr]), x


def main() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)  # match the suite semantics

    import numpy as np

    from flinkml_tpu import compile_cache, pipeline_fusion
    from flinkml_tpu.compile_cache.store import CompileCacheStore
    from flinkml_tpu.table import Table
    from flinkml_tpu.utils.metrics import metrics

    warnings: list = []
    handler = logging.Handler()
    handler.emit = lambda record: warnings.append(record.getMessage())
    logging.getLogger("flinkml_tpu.compile_cache").addHandler(handler)

    def counters():
        return dict(metrics.group("compile_cache").snapshot()["counters"])

    def fresh(store_dir):
        compile_cache.reset()
        if store_dir is not None:
            compile_cache.configure(store_dir)
        else:
            compile_cache.configure(None)
        pipeline_fusion.reset_cache()

    report: dict = {}
    root = tempfile.mkdtemp(prefix="cc-child-")
    model, x = _fitted_chain()
    table = Table({"features": x, "label": np.zeros(len(x))})

    # -- scenario: disk round trip + bitwise parity -------------------------
    fresh(None)
    baseline = _outputs(model, table)
    d1 = os.path.join(root, "roundtrip")
    before = counters()
    fresh(d1)
    cold = _outputs(model, table)
    after_cold = counters()
    fresh(d1)  # "fresh process": same dir, dropped memory + program caches
    warm = _outputs(model, table)
    after_warm = counters()
    report["roundtrip"] = {
        "stores": after_cold.get("stores", 0) - before.get("stores", 0),
        "aot_files": sum(1 for _, _, fs in os.walk(d1)
                         for f in fs if f.endswith(".aot")),
        "warm_hits": after_warm.get("hits", 0) - after_cold.get("hits", 0),
        "warm_extra_misses": after_warm.get("misses", 0)
        - after_cold.get("misses", 0),
        "cold_bitwise": all(baseline[c].tobytes() == cold[c].tobytes()
                            for c in baseline),
        "warm_bitwise": all(baseline[c].tobytes() == warm[c].tobytes()
                            for c in baseline),
    }

    # -- scenario: corrupt/torn entries fall back loudly --------------------
    paths = [os.path.join(r, f) for r, _, fs in os.walk(d1)
             for f in fs if f.endswith(".aot")]
    for p in paths:
        with open(p, "r+b") as fh:
            fh.truncate(max(1, os.path.getsize(p) // 2))
    fresh(d1)
    n_warn = len(warnings)
    before = counters()
    served = _outputs(model, table)
    after = counters()
    fresh(d1)  # the corrupt files must have been replaced by good ones
    before_reread = counters()
    _outputs(model, table)
    after_reread = counters()
    report["corrupt"] = {
        "corrupt_entries": after.get("corrupt_entries", 0)
        - before.get("corrupt_entries", 0),
        "torn_files": len(paths),
        "served_bitwise": all(baseline[c].tobytes() == served[c].tobytes()
                              for c in baseline),
        "warned": any("corrupt compile-cache entry" in w
                      for w in warnings[n_warn:]),
        "rewritten_hits": after_reread.get("hits", 0)
        - before_reread.get("hits", 0),
    }

    # -- scenario: env-fingerprint mismatch refuses a copied entry ----------
    store = compile_cache.active_store()
    env_dir = os.path.dirname(store.entry_path(("probe",)))
    entries = [f for f in os.listdir(env_dir) if f.endswith(".aot")]
    bumped = CompileCacheStore(d1)
    bumped._env = dict(store._environment())
    bumped._env["jax"] = "999.0.0"
    new_dir = os.path.dirname(bumped.entry_path(("probe",)))
    os.makedirs(new_dir, exist_ok=True)
    target = bumped.entry_path(("alien",))
    shutil.copy(os.path.join(env_dir, entries[0]), target)
    before = counters()
    refused = bumped._read_disk(("alien",)) is None
    after = counters()
    report["env_mismatch"] = {
        "namespaces_differ": new_dir != env_dir,
        "copied_entry_refused": refused,
        "env_mismatches": after.get("env_mismatches", 0)
        - before.get("env_mismatches", 0),
    }

    # -- scenario: racing compilers share one build -------------------------
    import jax.numpy as jnp

    builds: list = []

    def build():
        builds.append(threading.get_ident())
        time.sleep(0.05)
        return jax.jit(lambda v: jnp.sin(v * 1.2345678) * 2.0).lower(
            np.ones(19, np.float32)
        ).compile()

    race_store = CompileCacheStore(os.path.join(root, "race"))
    results: list = []
    threads = [
        threading.Thread(target=lambda: results.append(
            race_store.get_or_compile(("race-key",), build,
                                      device_ids=(0,))
        ))
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    builds_one_store = len(builds)
    # two independent stores (processes) racing on one path
    s1 = CompileCacheStore(os.path.join(root, "race"))
    s2 = CompileCacheStore(os.path.join(root, "race"))
    t1 = threading.Thread(target=s1.get_or_compile,
                          args=(("race-key-2",), build),
                          kwargs={"device_ids": (0,)})
    t2 = threading.Thread(target=s2.get_or_compile,
                          args=(("race-key-2",), build),
                          kwargs={"device_ids": (0,)})
    t1.start(); t2.start(); t1.join(); t2.join()
    fresh_store = CompileCacheStore(os.path.join(root, "race"))
    program, outcome = fresh_store.get_or_compile(
        ("race-key-2",), build, device_ids=(0,)
    )
    expect = np.sin(np.ones(19, np.float32) * 1.2345678) * 2.0
    report["race"] = {
        "racing_threads": 4,
        "results": len(results),
        "builds_one_store": builds_one_store,
        "compiled_outcomes": [o for _, o in results].count("compiled"),
        "reload_outcome": outcome,
        "reload_correct": bool(np.allclose(np.asarray(program(
            np.ones(19, np.float32))), expect, rtol=1e-6)),
    }

    # -- scenario: pool spin-up pays one compile per program ----------------
    from flinkml_tpu.serving.engine import ServingConfig
    from flinkml_tpu.serving.pool import ReplicaPool

    d2 = os.path.join(root, "pool")
    fresh(d2)
    before = counters()
    compiles: list = []
    pipeline_fusion.on_compile.append(compiles.append)
    pool = ReplicaPool(
        model, Table({"features": x[:4], "label": np.zeros(4)}),
        config=ServingConfig(max_batch_rows=16, max_wait_ms=1.0),
        n_replicas=4, name="cc-pool",
    ).start()
    n_programs = len(compiles)
    after = counters()
    resp = pool.predict({"features": x[:5], "label": np.zeros(5)})
    steady = len(compiles)
    direct = {c: v[:5] for c, v in _outputs(model, table).items()}
    pool_bitwise = all(
        resp.columns[c].tobytes() == direct[c].tobytes()
        for c in resp.columns
    )
    pool.stop(drain=False)
    pipeline_fusion.on_compile.remove(compiles.append)
    report["pool"] = {
        "programs": n_programs,
        "misses": after.get("misses", 0) - before.get("misses", 0),
        "hits": after.get("hits", 0) - before.get("hits", 0),
        "retarget_loads": after.get("retarget_loads", 0)
        - before.get("retarget_loads", 0),
        "steady_state_compiles": steady - n_programs,
        "bitwise_vs_direct": pool_bitwise,
    }

    # -- scenario: cross-device retargeted load parity ----------------------
    d3 = os.path.join(root, "retarget")
    fresh(d3)
    before = counters()
    _outputs(model, table)  # compile + store on the default device
    with jax.default_device(jax.devices()[3]):
        # A FRESH table: the shared one's device cache already holds
        # dev0-resident buffers, which would dodge the retarget path.
        pinned = _outputs(
            model, Table({"features": x, "label": np.zeros(len(x))})
        )
    after = counters()
    report["retarget"] = {
        "retarget_loads": after.get("retarget_loads", 0)
        - before.get("retarget_loads", 0),
        "bitwise": all(baseline[c].tobytes() == pinned[c].tobytes()
                       for c in baseline),
    }

    # -- scenario: the plan-sharded step round-trips ------------------------
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding.apply import (
        _plan_linear_step,
        train_linear_plan,
    )
    from flinkml_tpu.sharding.plan import FSDP

    rng = np.random.default_rng(0)
    px = rng.normal(size=(272, 48)).astype(np.float32)
    py = (px @ rng.normal(size=48).astype(np.float32) > 0).astype(np.float32)
    mesh = DeviceMesh.for_plan(FSDP)
    fresh(None)
    coef0 = train_linear_plan(px, py, None, FSDP, mesh, max_iter=4)
    d4 = os.path.join(root, "plan")
    fresh(d4)
    _plan_linear_step.cache_clear()
    before = counters()
    coef_cold = train_linear_plan(px, py, None, FSDP, mesh, max_iter=4)
    after_cold = counters()
    fresh(d4)
    _plan_linear_step.cache_clear()
    coef_warm = train_linear_plan(px, py, None, FSDP, mesh, max_iter=4)
    after_warm = counters()
    _plan_linear_step.cache_clear()
    report["plan_step"] = {
        "cold_misses": after_cold.get("misses", 0)
        - before.get("misses", 0),
        "cold_stores": after_cold.get("stores", 0)
        - before.get("stores", 0),
        "warm_hits": after_warm.get("hits", 0)
        - after_cold.get("hits", 0),
        "cold_equal": bool(np.array_equal(coef0, coef_cold)),
        "warm_equal": bool(np.array_equal(coef0, coef_warm)),
    }

    shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
