"""NaiveBayes tests — mirrors the reference's NaiveBayesTest, with a
hand-computed golden for the reference's exact smoothing formula and a
sklearn CategoricalNB comparison."""

import numpy as np
import pytest

from flinkml_tpu.models import NaiveBayes, NaiveBayesModel
from flinkml_tpu.table import Table


@pytest.fixture
def train_table():
    # 2 categorical features; labels 0/1.
    x = np.array(
        [
            [0, 0], [0, 1], [1, 0],  # label 0
            [1, 1], [2, 1], [2, 0], [2, 1],  # label 1
        ],
        dtype=np.float64,
    )
    y = np.array([0, 0, 0, 1, 1, 1, 1], dtype=np.float64)
    return Table({"features": x, "label": y})


def test_param_defaults():
    nb = NaiveBayes()
    assert nb.get_smoothing() == 1.0
    assert nb.get_features_col() == "features"


def test_fit_predict(train_table):
    model = NaiveBayes().fit(train_table)
    (out,) = model.transform(train_table)
    # The training points should mostly classify to their own labels.
    acc = np.mean(out["prediction"] == train_table["label"])
    assert acc >= 6 / 7


def test_exact_smoothing_formula(train_table):
    """Golden check of theta against GenerateModelFunction
    (NaiveBayes.java:322-339) computed by hand."""
    model = NaiveBayes().set_smoothing(1.0).fit(train_table)
    # Feature 0 categories {0,1,2}; label 0 rows: values [0,0,1] ->
    # counts {0:2, 1:1, 2:0}; docCount=3; theta = log(c+1) - log(3+3).
    theta = model._theta
    labels = model._labels
    i0 = int(np.where(labels == 0)[0][0])
    np.testing.assert_allclose(
        theta[i0, 0, :3],
        [np.log(3 / 6), np.log(2 / 6), np.log(1 / 6)],
        rtol=1e-12,
    )
    # pi (docCounts 3 and 4, F=2): log(l*F + s) - log(total*F + L*s)
    i1 = 1 - i0
    np.testing.assert_allclose(model._pi[i0], np.log(3 * 2 + 1) - np.log(14 + 2))
    np.testing.assert_allclose(model._pi[i1], np.log(4 * 2 + 1) - np.log(14 + 2))


def test_against_sklearn(rng):
    from sklearn.naive_bayes import CategoricalNB

    n = 300
    x = rng.integers(0, 4, size=(n, 3)).astype(np.float64)
    # Correlate label with feature 0.
    y = ((x[:, 0] >= 2) ^ (rng.random(n) < 0.15)).astype(np.float64)
    table = Table({"features": x, "label": y})
    model = NaiveBayes().set_smoothing(1.0).fit(table)
    (out,) = model.transform(table)

    sk = CategoricalNB(alpha=1.0).fit(x.astype(int), y)
    sk_pred = sk.predict(x.astype(int))
    agreement = np.mean(out["prediction"] == sk_pred)
    # Priors differ slightly (the reference's featureSize-weighted pi), but
    # predictions should agree nearly everywhere on balanced-ish data.
    assert agreement >= 0.97, agreement


def test_unseen_value_raises(train_table):
    model = NaiveBayes().fit(train_table)
    bad = Table({"features": np.array([[0.0, 99.0]])})
    with pytest.raises(ValueError, match="never seen"):
        model.transform(bad)


def test_non_integer_label_raises():
    t = Table({"features": np.zeros((2, 2)), "label": np.array([0.5, 1.0])})
    with pytest.raises(ValueError, match="indexed"):
        NaiveBayes().fit(t)


def test_feature_count_mismatch(train_table):
    model = NaiveBayes().fit(train_table)
    with pytest.raises(ValueError, match="features"):
        model.transform(Table({"features": np.zeros((1, 5))}))


def test_save_load(tmp_path, train_table):
    model = NaiveBayes().set_smoothing(2.0).fit(train_table)
    p = str(tmp_path / "nb")
    model.save(p)
    loaded = NaiveBayesModel.load(p)
    assert loaded.get_smoothing() == 2.0
    (a,) = model.transform(train_table)
    (b,) = loaded.transform(train_table)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])


def test_model_data_round_trip(train_table):
    model = NaiveBayes().fit(train_table)
    other = NaiveBayesModel().set_model_data(*model.get_model_data())
    (a,) = model.transform(train_table)
    (b,) = other.transform(train_table)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])
