"""Quantifies the streamed-GBT bin-edge approximation in its APPROXIMATE
regime (``reservoir_capacity << n`` — the only regime where the streamed
path matters; round-4 VERDICT item 8).

The reference bins nothing, so this contract is purely ours to prove:
edges from a seeded uniform row reservoir are approximate quantiles, and
the envelope below bounds (a) the rank error of those edges and (b) the
end-model accuracy drift vs exact edges. The measured numbers are
recorded in BASELINE.md ("Streamed-GBT edge approximation envelope").
"""

import numpy as np
import pytest

from flinkml_tpu.iteration.datacache import cache_stream

N = 40_000
BATCH = 2_000
D = 4
RESERVOIR = 1_024  # 2.6% of N — a genuinely approximate sample


def _data(seed=0):
    rng = np.random.default_rng(seed)
    # Mixed marginals so quantile edges differ across features: normal,
    # lognormal (heavy tail), uniform, bimodal.
    cols = [
        rng.normal(size=N),
        rng.lognormal(sigma=1.0, size=N),
        rng.uniform(-2, 2, size=N),
        np.concatenate([rng.normal(-3, 0.5, N // 2),
                        rng.normal(3, 0.5, N - N // 2)]),
    ]
    x = np.stack(cols, axis=1).astype(np.float32)
    raw = x[:, 0] * x[:, 3] + 0.8 * x[:, 2] - 0.3 * np.log1p(x[:, 1])
    y = (raw > np.median(raw)).astype(np.float32)
    return x, y


def _batches(x, y):
    for s in range(0, N, BATCH):
        yield {"x": x[s:s + BATCH], "y": y[s:s + BATCH],
               "w": np.ones(min(BATCH, N - s), np.float32)}


def test_reservoir_edge_rank_error_bounded(mesh):
    """Edges from a RESERVOIR-row sample sit within a small empirical-CDF
    (rank) distance of the exact quantile edges. Classic bound: a uniform
    m-sample's empirical CDF deviates by ~sqrt(ln(2/delta)/(2m)) (DKW);
    m=1024 gives ~0.042 at 97% confidence — we assert 0.06 with a fixed
    seed (deterministic)."""
    from flinkml_tpu.models.gbt import quantile_bin_edges
    from flinkml_tpu.utils.sampling import RowReservoir

    x, y = _data()
    max_bins = 32
    exact = quantile_bin_edges(x, max_bins)

    reservoir = RowReservoir(RESERVOIR, seed=0)
    for b in _batches(x, y):
        reservoir.add(b["x"])
    approx = quantile_bin_edges(reservoir.sample(), max_bins)

    worst = 0.0
    for j in range(D):
        xs = np.sort(x[:, j])
        for e_a, e_e in zip(approx[j], exact[j]):
            if not (np.isfinite(e_a) and np.isfinite(e_e)):
                continue
            # Rank (empirical CDF) positions of the two edges in the FULL
            # data — the scale-free measure of how far the split moved.
            r_a = np.searchsorted(xs, e_a) / N
            r_e = np.searchsorted(xs, e_e) / N
            worst = max(worst, abs(r_a - r_e))
    assert worst < 0.06, f"worst rank error {worst:.4f}"


def test_reservoir_model_accuracy_drift_bounded(mesh):
    """End-to-end: the forest trained on approximate edges loses < 1.5
    accuracy points vs the exact-edge forest on the same data."""
    from flinkml_tpu.models._gbt_stream import train_gbt_stream
    from flinkml_tpu.models.gbt import _walk_forest_per_tree

    x, y = _data()
    args = dict(
        mesh=mesh, logistic=True, num_trees=8, depth=3, max_bins=32,
        learning_rate=0.3, reg_lambda=1.0, subsample=1.0, seed=0,
    )

    def acc(result):
        feats, bins, gains, leaves, base, edges = result
        edges_inf = np.concatenate(
            [edges, np.full((edges.shape[0], 1), np.inf)], axis=1
        )
        thrs = edges_inf[feats, np.minimum(bins, edges_inf.shape[1] - 1)]
        contribs = _walk_forest_per_tree(
            x.astype(np.float64), feats, thrs, leaves, 3
        )
        margin = base + 0.3 * contribs.sum(axis=0)
        return float(((margin > 0) == y).mean())

    exact = train_gbt_stream(
        cache_stream(_batches(x, y)), reservoir_capacity=N, **args
    )
    approx = train_gbt_stream(
        cache_stream(_batches(x, y)), reservoir_capacity=RESERVOIR, **args
    )
    acc_exact, acc_approx = acc(exact), acc(approx)
    assert acc_exact > 0.9, acc_exact  # the task is learnable
    drift = acc_exact - acc_approx
    assert drift < 0.015, (acc_exact, acc_approx)
