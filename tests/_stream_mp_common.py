"""Shared fixtures for the multi-process streamed-fit IT.

Both the pytest parent (which computes the single-process expected
models) and the spawned workers (which train multi-process) import from
here, so the data and hyperparameters can never drift apart.

The equivalence contract under test: a multi-process streamed fit over
per-process stream partitions must match a single-process streamed fit
whose step-t batch is the concatenation of every process's step-t batch
(padded dummy rows are zero-weight no-ops), up to float reduction order.
"""

import numpy as np

N_ROWS = 600
N_FEATURES = 6
K_CLUSTERS = 4
DATA_SEED = 7

LINEAR_HP = dict(
    loss="logistic",
    max_iter=5,
    learning_rate=0.5,
    reg=0.01,
    elastic_net=0.0,
    tol=0.0,
)
KMEANS_HP = dict(max_iter=5, seed=3)

# Different per-process batch sizes on purpose: unequal batch heights AND
# unequal batch counts force the agreed-height padding and the dummy-step
# tail of the SPMD schedule.
BATCH_SIZES = {0: 17, 1: 29, 2: 23, 3: 13}


def global_data():
    rng = np.random.default_rng(DATA_SEED)
    x = rng.normal(size=(N_ROWS, N_FEATURES)).astype(np.float32)
    w_true = rng.normal(size=N_FEATURES).astype(np.float32)
    logits = x @ w_true
    y = (logits + rng.normal(scale=0.3, size=N_ROWS) > 0).astype(np.float32)
    return x, y


def slice_for(pid: int, nproc: int) -> slice:
    base, rem = divmod(N_ROWS, nproc)
    start = pid * base + min(pid, rem)
    return slice(start, start + base + (1 if pid < rem else 0))


def local_batches(pid: int, nproc: int):
    """This process's stream partition, in uneven batch sizes."""
    x, y = global_data()
    sl = slice_for(pid, nproc)
    xs, ys = x[sl], y[sl]
    bs = BATCH_SIZES[pid]
    return [
        {"x": xs[i : i + bs], "y": ys[i : i + bs]}
        for i in range(0, xs.shape[0], bs)
    ]


def combined_batches(nproc: int):
    """The single-process equivalent stream: step t concatenates every
    process's batch t (processes already exhausted contribute nothing)."""
    per_proc = [local_batches(p, nproc) for p in range(nproc)]
    steps = max(len(b) for b in per_proc)
    out = []
    for t in range(steps):
        parts = [b[t] for b in per_proc if t < len(b)]
        out.append(
            {
                "x": np.concatenate([p["x"] for p in parts]),
                "y": np.concatenate([p["y"] for p in parts]),
            }
        )
    return out


def initial_centroids():
    x, _ = global_data()
    return np.ascontiguousarray(x[:K_CLUSTERS])


GMM_MEANS = np.asarray([[-4.0, -4.0], [4.0, 4.0]])


def gmm_global_data(n=400):
    rng = np.random.default_rng(DATA_SEED + 1)
    a = rng.integers(0, 2, n)
    return (
        GMM_MEANS[a] + rng.normal(scale=0.5, size=(n, 2))
    ).astype(np.float32)


def gmm_local_batches(pid: int, nproc: int):
    x = gmm_global_data()
    base, rem = divmod(x.shape[0], nproc)
    start = pid * base + min(pid, rem)
    xs = x[start : start + base + (1 if pid < rem else 0)]
    bs = BATCH_SIZES[pid]
    return [xs[i : i + bs] for i in range(0, xs.shape[0], bs)]


LDA_VOCAB = 12


def lda_global_counts():
    """Two planted topics: even docs draw from the first vocab half, odd
    docs from the second — a fitted k=2 LDA must separate the halves."""
    rng = np.random.default_rng(11)
    docs = []
    for i in range(240):
        p = np.full(LDA_VOCAB, 0.01)
        if i % 2 == 0:
            p[: LDA_VOCAB // 2] = 1.0
        else:
            p[LDA_VOCAB // 2 :] = 1.0
        docs.append(rng.multinomial(40, p / p.sum()))
    return np.asarray(docs, np.float32)


def lda_local_batches(pid: int, nproc: int):
    c = lda_global_counts()
    base, rem = divmod(c.shape[0], nproc)
    start = pid * base + min(pid, rem)
    cs = c[start : start + base + (1 if pid < rem else 0)]
    bs = BATCH_SIZES[pid]
    return [cs[i : i + bs] for i in range(0, cs.shape[0], bs)]


ALS_USERS, ALS_ITEMS, ALS_RANK = 24, 18, 4


def als_global_ratings():
    """Low-rank planted ratings (noiseless): a rank-4 ALS fit must
    reconstruct the observed entries to small RMSE."""
    rng = np.random.default_rng(21)
    uf = rng.normal(size=(ALS_USERS, ALS_RANK)) / np.sqrt(ALS_RANK)
    vf = rng.normal(size=(ALS_ITEMS, ALS_RANK)) / np.sqrt(ALS_RANK)
    u, i = np.meshgrid(
        np.arange(ALS_USERS), np.arange(ALS_ITEMS), indexing="ij"
    )
    u, i = u.ravel(), i.ravel()
    keep = rng.random(u.shape[0]) < 0.6
    u, i = u[keep], i[keep]
    r = np.sum(uf[u] * vf[i], axis=1).astype(np.float32)
    return u.astype(np.int64), i.astype(np.int64), r


def als_local_batches(pid: int, nproc: int):
    """This process's ratings partition (by rating index, so a rank can
    see only a subset of the users/items — exercising the vocab union)."""
    u, i, r = als_global_ratings()
    base, rem = divmod(len(u), nproc)
    start = pid * base + min(pid, rem)
    sl = slice(start, start + base + (1 if pid < rem else 0))
    us, its, rs = u[sl], i[sl], r[sl]
    bs = BATCH_SIZES[pid]
    return [
        {"user": us[j : j + bs], "item": its[j : j + bs],
         "rating": rs[j : j + bs]}
        for j in range(0, len(us), bs)
    ]


def w2v_local_docs(pid: int, nproc: int):
    """Token documents with two co-occurrence groups (a* tokens appear
    together, b* tokens appear together): fitted vectors must place
    same-group tokens closer than cross-group ones."""
    rng = np.random.default_rng(31)
    group_a = [f"a{i}" for i in range(5)]
    group_b = [f"b{i}" for i in range(5)]
    docs = []
    for i in range(200):
        g = group_a if i % 2 == 0 else group_b
        docs.append(list(rng.choice(g, size=6)))
    mine = [d for j, d in enumerate(docs) if j % nproc == pid]
    bs = max(4, BATCH_SIZES[pid] // 4)
    return [mine[i : i + bs] for i in range(0, len(mine), bs)]


# --- round 5: sparse-native multi-process streaming -----------------------

SPARSE_DIM = 5_000


def _sparse_rows(lo: int, hi: int):
    """Deterministic per-GLOBAL-row sparse features + labels, so any
    partitioning of the row range yields the same underlying data."""
    rows = []
    for i in range(lo, hi):
        r = np.random.default_rng(1000 + i)
        nnz = 1 + int(r.integers(1, 7))
        idx = np.sort(r.choice(SPARSE_DIM, nnz, replace=False))
        rows.append((idx, r.normal(size=nnz), float(r.random() > 0.5)))
    return rows


def _sparse_tables_from(rows, bs):
    from flinkml_tpu.linalg import Vectors
    from flinkml_tpu.table import Table

    out = []
    for i in range(0, len(rows), bs):
        chunk = rows[i:i + bs]
        vecs = np.array(
            [Vectors.sparse(SPARSE_DIM, idx.tolist(), val)
             for idx, val, _ in chunk],
            dtype=object,
        )
        y = np.asarray([lab for _, _, lab in chunk])
        out.append(Table({"features": vecs, "label": y}))
    return out


def sparse_local_tables(pid: int, nproc: int):
    sl = slice_for(pid, nproc)
    return _sparse_tables_from(
        _sparse_rows(sl.start, sl.stop), BATCH_SIZES[pid]
    )


def sparse_combined_tables(nproc: int):
    """Single-process equivalent: step t concatenates every rank's batch
    t (same construction as :func:`combined_batches`)."""
    from flinkml_tpu.linalg import Vectors
    from flinkml_tpu.table import Table

    per = []
    for p in range(nproc):
        sl = slice_for(p, nproc)
        rows = _sparse_rows(sl.start, sl.stop)
        bs = BATCH_SIZES[p]
        per.append([rows[i:i + bs] for i in range(0, len(rows), bs)])
    steps = max(len(b) for b in per)
    out = []
    for t in range(steps):
        chunk = [r for b in per if t < len(b) for r in b[t]]
        vecs = np.array(
            [Vectors.sparse(SPARSE_DIM, idx.tolist(), val)
             for idx, val, _ in chunk],
            dtype=object,
        )
        y = np.asarray([lab for _, _, lab in chunk])
        out.append(Table({"features": vecs, "label": y}))
    return out


SPARSE_HP = dict(max_iter=4, learning_rate=0.5, reg=0.01, tol=0.0)
