"""Exact-seed determinism (SURVEY.md §4(c)): identical seeds must produce
bit-identical results across runs; the reference gets this from global
epoch alignment, SPMD gets it from identical replicated programs — these
tests guard against cross-run nondeterminism creeping in.
"""

import numpy as np

from flinkml_tpu.models import KMeans, LogisticRegression
from flinkml_tpu.models._linear_sgd import train_linear_model
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


def _data(n=200, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ rng.normal(size=d) > 0).astype(np.float32)
    return x, y


def test_lr_same_seed_bit_identical():
    x, y = _data()
    t = Table({"features": x, "label": y})

    def fit():
        m = (LogisticRegression().set_seed(7).set_max_iter(25)
             .set_learning_rate(0.5).set_global_batch_size(64).fit(t))
        return np.asarray(m.coefficient)

    c1, c2 = fit(), fit()
    np.testing.assert_array_equal(c1, c2)


def test_lr_different_seed_differs():
    x, y = _data(seed=1)
    t = Table({"features": x, "label": y})

    def fit(seed):
        m = (LogisticRegression().set_seed(seed).set_max_iter(25)
             .set_learning_rate(0.5).set_global_batch_size(64).fit(t))
        return np.asarray(m.coefficient)

    assert not np.array_equal(fit(1), fit(2))


def test_kmeans_same_seed_bit_identical():
    rng = np.random.default_rng(3)
    pts = rng.normal(size=(300, 6)).astype(np.float32)
    t = Table({"features": pts})

    def fit():
        return np.asarray(
            KMeans().set_k(4).set_seed(11).set_max_iter(10).fit(t).centroids
        )

    np.testing.assert_array_equal(fit(), fit())


def test_trainer_same_seed_across_losses_family():
    x, y = _data(seed=4)
    kw = dict(mesh=DeviceMesh(), max_iter=15, learning_rate=0.3,
              global_batch_size=64, reg=0.01, elastic_net=0.5, tol=0.0,
              seed=9)
    for loss in ("logistic", "hinge", "squared"):
        c1 = train_linear_model(x, y, np.ones(len(y), np.float32), loss, **kw)
        c2 = train_linear_model(x, y, np.ones(len(y), np.float32), loss, **kw)
        np.testing.assert_array_equal(c1, c2)
