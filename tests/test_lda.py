"""LDA: topic recovery on a synthetic corpus, doc mixtures, persistence."""

import numpy as np
import pytest

from flinkml_tpu.models import LDA, LDAModel
from flinkml_tpu.table import Table


def _synthetic_corpus(n_docs=400, vocab=60, k=3, doc_len=80, seed=0):
    """Docs drawn from k topics with disjoint dominant word blocks."""
    rng = np.random.default_rng(seed)
    block = vocab // k
    topics = np.full((k, vocab), 0.01 / vocab)
    for t in range(k):
        topics[t, t * block: (t + 1) * block] = 1.0
    topics /= topics.sum(axis=1, keepdims=True)
    counts = np.zeros((n_docs, vocab))
    dominant = np.zeros(n_docs, dtype=int)
    for d in range(n_docs):
        theta = rng.dirichlet([0.2] * k)
        dominant[d] = int(np.argmax(theta))
        words = rng.choice(vocab, size=doc_len,
                           p=theta @ topics)
        np.add.at(counts[d], words, 1.0)
    return counts, topics, dominant


def _lda(k=3, iters=30, seed=0):
    return (
        LDA().set_k(k).set_max_iter(iters).set_tol(1e-6).set_seed(seed)
    )


def _match_topics(learned, truth):
    """Greedy cosine matching; returns mean matched cosine."""
    sims = (learned / np.linalg.norm(learned, axis=1, keepdims=True)) @ (
        truth / np.linalg.norm(truth, axis=1, keepdims=True)
    ).T
    total, used = 0.0, set()
    for i in np.argsort(-sims.max(axis=1)):
        j = max(
            (jj for jj in range(truth.shape[0]) if jj not in used),
            key=lambda jj: sims[i, jj],
        )
        used.add(j)
        total += sims[i, j]
    return total / truth.shape[0]


def test_recovers_block_topics():
    counts, topics, dominant = _synthetic_corpus()
    t = Table({"features": counts})
    model = _lda().fit(t)
    assert _match_topics(model.topics_matrix, topics) > 0.9
    # Dominant-topic prediction agrees with the generator (up to topic
    # permutation — measured via clustering agreement).
    from sklearn.metrics import adjusted_rand_score

    (out,) = model.transform(t)
    assert adjusted_rand_score(dominant, out["prediction"]) > 0.7
    theta = out["topicDistribution"]
    np.testing.assert_allclose(theta.sum(axis=1), 1.0, rtol=1e-6)


def test_describe_topics_top_terms_in_block():
    counts, _, _ = _synthetic_corpus(seed=1)
    model = _lda().fit(Table({"features": counts}))
    desc = model.describe_topics(5)
    assert desc.num_rows == 3
    # Each topic's top terms live in one 20-word block.
    for row in range(3):
        terms = desc["termIndices"][row]
        blocks = set(terms // 20)
        assert len(blocks) == 1
    # All three blocks are covered.
    all_blocks = {int(desc["termIndices"][r][0] // 20) for r in range(3)}
    assert all_blocks == {0, 1, 2}


def test_persistence_and_validation(tmp_path):
    counts, _, _ = _synthetic_corpus(n_docs=100, seed=2)
    t = Table({"features": counts})
    model = _lda(iters=5).fit(t)
    model.save(str(tmp_path / "lda"))
    loaded = LDAModel.load(str(tmp_path / "lda"))
    np.testing.assert_allclose(loaded.topics_matrix, model.topics_matrix)
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_allclose(
        p2["topicDistribution"], p1["topicDistribution"]
    )
    with pytest.raises(ValueError, match="non-negative"):
        _lda().fit(Table({"features": -counts}))
    with pytest.raises(ValueError, match="vocab size"):
        model.transform(Table({"features": counts[:, :10]}))


def test_sparse_input_and_determinism():
    from flinkml_tpu.linalg import SparseVector

    counts, _, _ = _synthetic_corpus(n_docs=60, seed=3)
    rows = np.empty(len(counts), dtype=object)
    for i, row in enumerate(counts):
        nz = np.nonzero(row)[0]
        rows[i] = SparseVector(counts.shape[1], nz, row[nz])
    t_sparse = Table({"features": rows})
    t_dense = Table({"features": counts})
    m1 = _lda(iters=5, seed=4).fit(t_sparse)
    m2 = _lda(iters=5, seed=4).fit(t_dense)
    np.testing.assert_allclose(m1.topics_matrix, m2.topics_matrix)


def test_concentration_validation():
    counts, _, _ = _synthetic_corpus(n_docs=20, seed=5)
    with pytest.raises(ValueError, match="docConcentration"):
        LDA().set_doc_concentration(-1.0)
    with pytest.raises(ValueError, match="topicConcentration"):
        LDA().set_topic_concentration(0.0)
