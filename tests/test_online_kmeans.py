"""OnlineKMeans tests: decay rule exactness, warm start, drift tracking,
cold start, save/load, versioning. Counterpart of apache/flink-ml's
OnlineKMeans (decayed mini-batch k-means; the reference snapshot itself
ships only bounded KMeans, SURVEY.md §2.3)."""

import numpy as np
import pytest

from flinkml_tpu.models import KMeans, OnlineKMeans, OnlineKMeansModel
from flinkml_tpu.table import Table


def blob_table(rng, centers, n_each=60, scale=0.3):
    x = np.concatenate(
        [c + rng.normal(scale=scale, size=(n_each, len(c))) for c in centers]
    )
    return Table({"features": x})


def test_decay_rule_exact_single_centroid(rng):
    """k is validated > 1, so isolate one centroid's arithmetic with two
    far-apart clusters: n' = decay·n + count, c' = (decay·n·c + sum)/n'
    checked against a hand-rolled recurrence."""
    decay = 0.5
    far = np.array([[0.0, 0.0], [100.0, 100.0]])
    online = (
        OnlineKMeans().set_k(2).set_decay_factor(decay)
        .set_initial_model_data(
            *[Table({"centroids": far[None, :, :]})]
        )
    )
    batches = [
        Table({"features": np.full((4, 2), float(v))}) for v in (1, 2, 3)
    ]
    model = online.fit_stream(iter(batches))
    # Hand recurrence for centroid 0 (all batches land on it).
    c, n = np.array([0.0, 0.0]), 0.0
    for v in (1.0, 2.0, 3.0):
        s, cnt = np.full(2, v) * 4, 4.0
        n_new = decay * n + cnt
        c = (decay * n * c + s) / n_new
        n = n_new
    np.testing.assert_allclose(model.centroids[0], c, rtol=1e-12)
    # The empty centroid never moves.
    np.testing.assert_allclose(model.centroids[1], far[1])


def test_warm_start_tracks_drift(rng):
    warm = KMeans().set_k(2).set_seed(0).fit(
        blob_table(rng, [(0.0, 0.0), (5.0, 5.0)])
    )
    online = (
        OnlineKMeans().set_k(2).set_decay_factor(0.3)
        .set_initial_model_data(*warm.get_model_data())
    )
    # The clusters drift by +2 in both coordinates.
    drifted = [(2.0, 2.0), (7.0, 7.0)]
    model = online.fit_stream(
        blob_table(rng, drifted, n_each=40) for _ in range(25)
    )
    got = model.centroids[np.argsort(model.centroids[:, 0])]
    np.testing.assert_allclose(got, np.asarray(drifted), atol=0.3)
    assert model.model_version == 25


def test_cold_start_from_first_batch(rng):
    online = OnlineKMeans().set_k(2).set_seed(3).set_decay_factor(1.0)
    model = online.fit_stream(
        blob_table(rng, [(0.0, 0.0), (8.0, 8.0)]) for _ in range(10)
    )
    got = model.centroids[np.argsort(model.centroids[:, 0])]
    np.testing.assert_allclose(got, [[0, 0], [8, 8]], atol=0.5)


def test_fit_table_batches(rng):
    """fit(table) consumes the table as globalBatchSize mini-batches."""
    t = blob_table(rng, [(0.0, 0.0), (6.0, 6.0)], n_each=128)
    model = (
        OnlineKMeans().set_k(2).set_seed(1).set_global_batch_size(64)
        .set_decay_factor(1.0).fit(t)
    )
    (out,) = model.transform(t)
    assign = np.asarray(out["prediction"])
    # Two pure clusters of 128 points each.
    sizes = np.sort(np.bincount(assign.astype(int), minlength=2))
    np.testing.assert_array_equal(sizes, [128, 128])


def test_first_batch_smaller_than_k_raises(rng):
    online = OnlineKMeans().set_k(2).set_seed(0)
    with pytest.raises(ValueError, match="first batch"):
        online.fit_stream(iter([Table({"features": np.zeros((1, 2))})]))


def test_empty_stream_raises():
    with pytest.raises(ValueError, match="empty"):
        OnlineKMeans().set_k(2).fit_stream(iter([]))


def test_save_load_round_trip(rng, tmp_path):
    model = (
        OnlineKMeans().set_k(2).set_seed(5).set_decay_factor(0.5)
        .fit_stream(blob_table(rng, [(0.0, 0.0), (9.0, 9.0)]) for _ in range(5))
    )
    p = str(tmp_path / "okm")
    model.save(p)
    loaded = OnlineKMeansModel.load(p)
    np.testing.assert_array_equal(loaded.centroids, model.centroids)
    assert loaded.model_version == model.model_version == 5
    t = blob_table(rng, [(0.0, 0.0), (9.0, 9.0)])
    (a,) = model.transform(t)
    (b,) = loaded.transform(t)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])


def test_model_data_round_trip(rng):
    model = (
        OnlineKMeans().set_k(2).set_seed(5)
        .fit_stream(blob_table(rng, [(0.0, 0.0), (9.0, 9.0)]) for _ in range(3))
    )
    other = (
        OnlineKMeansModel()
        .set_model_data(*model.get_model_data())
    )
    np.testing.assert_array_equal(other.centroids, model.centroids)


def test_transform_requires_model():
    with pytest.raises(ValueError, match="Model data"):
        OnlineKMeansModel().transform(Table({"features": np.zeros((2, 2))}))