"""Tests for the numeric-CSV ingest (native parser + Python fallback).

Both paths run against the same fixtures; the native path is skipped
automatically when no compiler is available (compile_and_load returns
None and read_csv silently uses the fallback — asserted explicitly here).
"""

import numpy as np
import pytest

from flinkml_tpu.io.csv import _parse_python, read_csv, read_csv_table
from flinkml_tpu.io._native import compile_and_load
from flinkml_tpu.io.csv import _declare

NATIVE = compile_and_load("csv_parser", _declare) is not None

BASIC = b"a,b,c\n1,2,3\n4,5,6\n-1.5,2e3,0.25\n"
NO_HEADER = b"1,2\n3,4\n\n5,6\n"
MISSING = b"x,y\n1,\n,2\n"


@pytest.mark.parametrize("use_native", [False] + ([True] if NATIVE else []))
def test_header_and_values(use_native):
    names, mat = read_csv(BASIC, use_native=use_native)
    assert names == ["a", "b", "c"]
    np.testing.assert_allclose(
        mat, [[1, 2, 3], [4, 5, 6], [-1.5, 2000.0, 0.25]]
    )
    assert mat.flags.f_contiguous  # columns are contiguous views


@pytest.mark.parametrize("use_native", [False] + ([True] if NATIVE else []))
def test_no_header_auto_and_blank_lines(use_native):
    names, mat = read_csv(NO_HEADER, use_native=use_native)
    assert names is None
    np.testing.assert_allclose(mat, [[1, 2], [3, 4], [5, 6]])


@pytest.mark.parametrize("use_native", [False] + ([True] if NATIVE else []))
def test_missing_fields_become_nan(use_native):
    names, mat = read_csv(MISSING, use_native=use_native)
    assert names == ["x", "y"]
    assert np.isnan(mat[0, 1]) and np.isnan(mat[1, 0])
    assert mat[0, 0] == 1.0 and mat[1, 1] == 2.0


@pytest.mark.parametrize("use_native", [False] + ([True] if NATIVE else []))
def test_ragged_rows_rejected(use_native):
    with pytest.raises(ValueError, match="field count"):
        read_csv(b"1,2\n3,4,5\n", use_native=use_native)


@pytest.mark.parametrize("use_native", [False] + ([True] if NATIVE else []))
def test_malformed_field_rejected(use_native):
    with pytest.raises(ValueError, match="malformed|field count"):
        read_csv(b"1,2\n3,oops\n", header=False, use_native=use_native)


@pytest.mark.parametrize("use_native", [False] + ([True] if NATIVE else []))
def test_crlf_and_spaces(use_native):
    names, mat = read_csv(b"a,b\r\n 1 ,\t2\r\n", use_native=use_native)
    assert names == ["a", "b"]
    np.testing.assert_allclose(mat, [[1, 2]])


def test_table_with_and_without_header(tmp_path):
    p = tmp_path / "t.csv"
    p.write_bytes(BASIC)
    t = read_csv_table(str(p))
    assert set(t.column_names) == {"a", "b", "c"}
    np.testing.assert_allclose(t.column("b"), [2, 5, 2000.0])
    t2 = read_csv_table(NO_HEADER)
    assert set(t2.column_names) == {"c0", "c1"}


def test_header_mismatch_rejected():
    with pytest.raises(ValueError, match="header has"):
        read_csv(b"a,b,c\n1,2\n", header=True)


def test_empty_input():
    names, mat = read_csv(b"", header=False)
    assert mat.shape == (0, 0)
    names, mat = read_csv(b"a,b\n")
    assert names == ["a", "b"] and mat.shape == (0, 2)


@pytest.mark.skipif(not NATIVE, reason="no native compiler")
def test_native_matches_python_on_random_data():
    rng = np.random.default_rng(0)
    ref = rng.normal(size=(500, 7))
    body = "\n".join(
        ",".join(f"{v:.17g}" for v in row) for row in ref
    ).encode() + b"\n"
    _, nat = read_csv(body, header=False, use_native=True)
    _, py = read_csv(body, header=False, use_native=False)
    np.testing.assert_array_equal(nat, py)
    np.testing.assert_allclose(nat, ref)


def test_python_fallback_direct():
    mat = _parse_python(b"1,2\n3,4\n", ",")
    np.testing.assert_allclose(mat, [[1, 2], [3, 4]])


@pytest.mark.parametrize("use_native", [False] + ([True] if NATIVE else []))
def test_grammar_parity_edge_values(use_native):
    # Overflow saturates to inf, underflow to 0, like Python float().
    _, mat = read_csv(b"1e400,-1e400,1e-400\n", header=False,
                      use_native=use_native)
    assert np.isinf(mat[0, 0]) and mat[0, 0] > 0
    assert np.isinf(mat[0, 1]) and mat[0, 1] < 0
    assert mat[0, 2] == 0.0
    # Python-only '_' separators are rejected on BOTH paths.
    with pytest.raises(ValueError, match="malformed"):
        read_csv(b"1_0,2\n", header=False, use_native=use_native)


def test_multibyte_delimiter_rejected():
    with pytest.raises(ValueError, match="single-byte"):
        read_csv(b"1;2\n", delimiter=" ")


def test_duplicate_header_names_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        read_csv_table(b"a,a,b\n1,2,3\n")


@pytest.mark.skipif(not NATIVE, reason="no native compiler")
def test_long_overflow_field_native():
    # 400-char overflow field must saturate to inf, not error (grammar
    # parity with the fallback even past the stack-buffer length).
    body = ("1" + "0" * 400 + ",2\n").encode()
    _, nat = read_csv(body, header=False, use_native=True)
    _, py = read_csv(body, header=False, use_native=False)
    assert np.isinf(nat[0, 0]) and np.isinf(py[0, 0])
