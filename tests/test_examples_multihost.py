"""IT: the user-facing multi-host pod recipe actually runs as a 2-process
Gloo pod (VERDICT r2 item 7 'done' criterion).

The reference's analog is its MiniCluster system tests exercising the
multi-worker control plane (``SharedProgressAligner.java:127-158``,
SURVEY.md §4 tier 3).
"""

import os
import subprocess
import sys


def _run_example(name, args, token):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    example = os.path.join(repo_root, "examples", name)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # Share the suite's persistent XLA cache (see test_distributed.py).
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    out = subprocess.run(
        [sys.executable, example, *args],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert token in out.stdout, out.stdout


def test_multihost_pod_example_local_demo():
    _run_example("multihost_pod.py", ["--local-demo"], "LOCAL DEMO OK")


def test_multihost_streamed_fit_example_local_demo():
    """The round-4 multi-process streamed-fit recipe: 2 hosts, disjoint
    stream partitions, identical fitted models."""
    _run_example("multihost_streamed_fit.py", [], "local demo OK")
