"""PowerIterationClustering on block-structured affinity graphs."""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score

from flinkml_tpu.models import PowerIterationClustering
from flinkml_tpu.table import Table


def _block_graph(sizes=(40, 40), p_in=0.5, p_out=0.01, seed=0):
    """Random graph with dense within-block, sparse cross-block edges."""
    rng = np.random.default_rng(seed)
    n = sum(sizes)
    labels = np.repeat(np.arange(len(sizes)), sizes)
    src, dst = [], []
    for i in range(n):
        for j in range(i + 1, n):
            p = p_in if labels[i] == labels[j] else p_out
            if rng.uniform() < p:
                src.append(i)
                dst.append(j)
    return np.asarray(src), np.asarray(dst), labels


def test_recovers_two_blocks():
    src, dst, truth = _block_graph()
    t = Table({"src": src, "dst": dst})
    (out,) = (
        PowerIterationClustering().set_k(2).set_max_iter(30).set_seed(0)
        .transform(t)
    )
    assert out.num_rows == len(truth)
    order = np.argsort(out["id"])
    ari = adjusted_rand_score(truth, out[
        "prediction"][order])
    assert ari > 0.9, ari


def test_three_blocks_weighted():
    src, dst, truth = _block_graph(sizes=(30, 30, 30), p_in=0.6,
                                   p_out=0.02, seed=1)
    w = np.ones(len(src))
    t = Table({"src": src, "dst": dst, "w": w})
    (out,) = (
        PowerIterationClustering().set_k(3).set_max_iter(40)
        .set_weight_col("w").set_seed(0).transform(t)
    )
    order = np.argsort(out["id"])
    ari = adjusted_rand_score(truth, out["prediction"][order])
    assert ari > 0.8, ari


def test_string_vertex_ids_and_labeling():
    # Two triangles joined by one weak edge.
    src = np.asarray(["a", "b", "c", "x", "y", "z", "a"])
    dst = np.asarray(["b", "c", "a", "y", "z", "x", "x"])
    w = np.asarray([1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 0.01])
    t = Table({"src": src, "dst": dst, "w": w})
    (out,) = (
        PowerIterationClustering().set_k(2).set_max_iter(50)
        .set_weight_col("w").set_seed(0).transform(t)
    )
    by_id = dict(zip(out["id"], out["prediction"]))
    assert by_id["a"] == by_id["b"] == by_id["c"]
    assert by_id["x"] == by_id["y"] == by_id["z"]
    assert by_id["a"] != by_id["x"]
    assert by_id[sorted(by_id)[0]] == 0.0   # first-appearance labeling


def test_validation():
    t = Table({"src": np.asarray([0, 1]), "dst": np.asarray([1, 2]),
               "w": np.asarray([1.0, -1.0])})
    with pytest.raises(ValueError, match="non-negative"):
        (
            PowerIterationClustering().set_weight_col("w").set_k(2)
            .transform(t)
        )
    t2 = Table({"src": np.asarray([0]), "dst": np.asarray([1])})
    with pytest.raises(ValueError, match="vertices"):
        PowerIterationClustering().set_k(5).transform(t2)


def test_complete_graph_constant_embedding_single_cluster():
    # K4 with equal weights: the pseudo-eigenvector is constant; the 1-D
    # k-means must terminate (used to infinite-loop) with one cluster.
    src, dst = [], []
    for i in range(4):
        for j in range(i + 1, 4):
            src.append(i)
            dst.append(j)
    t = Table({"src": np.asarray(src), "dst": np.asarray(dst)})
    (out,) = (
        PowerIterationClustering().set_k(2).set_max_iter(60).set_seed(0)
        .transform(t)
    )
    assert set(np.unique(out["prediction"])) == {0.0}
