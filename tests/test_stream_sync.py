"""Unit tests for the multi-process streamed-fit agreement layer
(`iteration/stream_sync.py`). Single-process semantics here; the real
2-process behavior is exercised by
tests/test_distributed.py::test_two_process_streamed_fit."""

import numpy as np
import pytest

from flinkml_tpu.iteration.datacache import cache_stream
from flinkml_tpu.iteration.stream_sync import (
    SyncedReplayPlan,
    agree_max,
    gather_vectors,
    pooled_sample,
)
from flinkml_tpu.parallel import DeviceMesh


@pytest.fixture(scope="module")
def mesh():
    return DeviceMesh()


def test_agree_max_single_process_identity(mesh):
    assert agree_max(7, mesh) == 7
    assert agree_max(0, mesh) == 0


def test_gather_vectors_single_process_identity(mesh):
    v = np.asarray([1.5, -2.25, 1e12 + 0.125])
    out = gather_vectors(v, mesh)
    assert out.shape == (1, 3)
    np.testing.assert_array_equal(out[0], v)


def test_pooled_sample_single_process_identity(mesh):
    s = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    np.testing.assert_array_equal(pooled_sample(s, 100, 5, 0, mesh), s)


def test_plan_schedule_from_cache(mesh):
    batches = [{"x": np.zeros((n, 2), np.float32)} for n in (5, 17, 3)]
    cache = cache_stream(iter(batches))
    plan = SyncedReplayPlan.create(cache, mesh, row_tile=8)
    assert plan.global_steps == 3
    # height = max batch rows (17) rounded up to the tile
    assert plan.local_height == 24


def test_plan_epoch_batches_pads_with_dummies(mesh):
    batches = [{"x": np.zeros((4, 2), np.float32)} for _ in range(2)]
    cache = cache_stream(iter(batches))
    plan = SyncedReplayPlan.create(cache, mesh, row_tile=8)
    plan.global_steps = 5  # pretend another process has 5 batches
    out = list(plan.epoch_batches(cache.reader(), lambda: {"_dummy": True}))
    assert len(out) == 5
    assert sum("_dummy" in b for b in out) == 3
    assert all("_dummy" not in b for b in out[:2])


def test_plan_rejects_unsealed_overrun(mesh):
    batches = [{"x": np.zeros((4, 2), np.float32)} for _ in range(3)]
    cache = cache_stream(iter(batches))
    plan = SyncedReplayPlan.create(cache, mesh, row_tile=8)
    plan.global_steps = 2  # an impossible agreement for this cache
    with pytest.raises(RuntimeError, match="more batches than the agreed"):
        list(plan.epoch_batches(cache.reader(), lambda: {"_dummy": True}))


def test_plan_empty_cache_raises(mesh):
    cache = cache_stream(iter([]))
    with pytest.raises(ValueError, match="empty on every process"):
        SyncedReplayPlan.create(cache, mesh, row_tile=8)


def test_deferred_validation_call_skips_after_held_error():
    """`call` fuses extraction + validation and returns None once a
    failure is held, so callers skip accumulation that could itself
    raise rank-locally (e.g. a fixed-width reservoir add of a ragged
    batch) — the hang class the agreement layer exists to prevent."""
    from flinkml_tpu.iteration.stream_sync import DeferredValidation

    dv = DeferredValidation()
    assert dv.call(lambda v: v * 2, 21) == 42
    assert dv.err is None

    boom = ValueError("bad batch")

    def failing(_):
        raise boom

    assert dv.call(failing, 1) is None
    assert dv.err is boom
    # Held: later (healthy) steps are skipped entirely, first error wins.
    calls = []
    assert dv.call(lambda v: calls.append(v) or v, 2) is None
    assert calls == []
    assert dv.err is boom


def test_synced_stream_single_process_propagates_iterator_error(mesh):
    """Single-process there is no peer to strand: a raising source
    iterator propagates as-is (the multi-process fold-into-agreement
    behavior is pinned by the 2-process hang-guard IT)."""
    from flinkml_tpu.iteration.stream_sync import synced_stream

    def source():
        yield np.ones((2, 2), np.float32)
        raise IOError("injected")

    it = synced_stream(source(), mesh)
    assert next(it).shape == (2, 2)
    with pytest.raises(IOError, match="injected"):
        next(it)


def test_synced_padded_stream_pads_and_masks(mesh):
    from flinkml_tpu.iteration.stream_sync import synced_padded_stream

    items = [
        (np.ones((5, 3), np.float32), np.arange(5, dtype=np.float32)),
        (np.ones((9, 3), np.float32), np.arange(9, dtype=np.float32)),
    ]
    out = list(synced_padded_stream(
        iter(items), mesh, check=None, row_tile=8,
        dummy_cols=((3,), ()),
    ))
    assert len(out) == 2
    (x0, y0), w0, h0 = out[0]
    assert h0 == 8 and x0.shape == (8, 3) and y0.shape == (8,)
    assert w0.tolist() == [1.0] * 5 + [0.0] * 3
    assert np.all(x0[5:] == 0.0) and np.all(y0[5:] == 0.0)
    (x1, _y1), w1, h1 = out[1]
    assert h1 == 16 and x1.shape == (16, 3)
    assert w1.sum() == 9.0


def test_agree_id_vocab_single_process_identity(mesh):
    from flinkml_tpu.models.als import _agree_id_vocab

    ids = _agree_id_vocab(np.asarray([7, 3, 3, 11], np.int64), mesh)
    assert ids.dtype == np.int64
    assert ids.tolist() == [3, 7, 11]
    f = _agree_id_vocab(np.asarray([2.5, 1.5]), mesh)
    assert f.dtype == np.float64 and f.tolist() == [1.5, 2.5]


def test_agree_token_counts_single_process_identity(mesh):
    from flinkml_tpu.models.word2vec import _agree_token_counts

    merged = _agree_token_counts(["béta", "alpha"], [3, 5], mesh)
    assert merged == {"béta": 3, "alpha": 5}
    assert _agree_token_counts([], [], mesh) == {}
