"""Fault-injection layer semantics (ISSUE 4 tentpole).

Covers: plan arming/firing determinism, zero-overhead-when-disarmed,
the corrupt-snapshot fallback ladder in ``restore_latest``, torn-write
kills, kill-after-commit, transfer faults at the dispatch seam, dropped
registry publishes, and the preemption watchdog (final checkpoint +
engine drain)."""

import os
import signal

import numpy as np
import pytest

from flinkml_tpu import faults
from flinkml_tpu.iteration import (
    CheckpointIntegrityError,
    CheckpointManager,
    IterationConfig,
    TerminateOnMaxIter,
    iterate,
)
from flinkml_tpu.parallel.dispatch import DispatchGuard
from flinkml_tpu.utils.preemption import PreemptionWatchdog, active


def _count_step(state, data, epoch):
    return state + float(data), None


# ---------------------------------------------------------------------------
# Plan semantics
# ---------------------------------------------------------------------------

def test_raise_at_epoch_fires_once_and_logs():
    plan = faults.FaultPlan(faults.RaiseAtEpoch(2))
    with faults.armed(plan):
        with pytest.raises(faults.FaultInjected, match="epoch 2"):
            iterate(_count_step, 0.0, [1.0, 2.0, 3.0, 4.0],
                    IterationConfig(TerminateOnMaxIter(4)))
    assert faults.ACTIVE is None  # armed() always disarms
    assert plan.log == [
        ("iteration.epoch", "RaiseAtEpoch(2)", {"epoch": 2})
    ]
    # Epochs 0 and 1 completed before the injected crash.
    with faults.armed(faults.FaultPlan()):
        pass  # empty plan is legal


def test_crash_run_consumed_exactly_the_prefix():
    consumed = []

    def stream():
        for i in range(10):
            consumed.append(i)
            yield float(i)

    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(3))):
        with pytest.raises(faults.FaultInjected):
            iterate(_count_step, 0.0, stream(),
                    IterationConfig(TerminateOnMaxIter(10)))
    # The epoch-3 fault fires BEFORE batch 3 is consumed.
    assert consumed == [0, 1, 2]


def test_zero_overhead_when_disarmed(monkeypatch):
    """With no plan armed the seams are a None check: FaultPlan.fire must
    never be invoked anywhere."""
    calls = []
    orig = faults.FaultPlan.fire
    monkeypatch.setattr(
        faults.FaultPlan, "fire",
        lambda self, site, **ctx: calls.append(site) or orig(self, site, **ctx),
    )
    assert faults.ACTIVE is None
    iterate(_count_step, 0.0, [1.0, 2.0],
            IterationConfig(TerminateOnMaxIter(2)))
    guard = DispatchGuard(interval=1)
    guard.after_dispatch(np.zeros(2))
    guard.flush(np.zeros(2))
    assert calls == []


def test_armed_disarms_on_exception():
    with pytest.raises(RuntimeError, match="boom"):
        with faults.armed(faults.FaultPlan()):
            raise RuntimeError("boom")
    assert faults.ACTIVE is None


# ---------------------------------------------------------------------------
# Checkpoint faults + the fallback ladder
# ---------------------------------------------------------------------------

def _save_epochs(tmp_path, epochs, keep=10):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=keep)
    state = {"w": np.arange(4.0), "v": 0}
    for e in epochs:
        state = {"w": state["w"] + e, "v": e}
        mgr.save(state, e)
    return mgr, state


@pytest.mark.parametrize("target", ["arrays", "manifest", "truncate"])
def test_corrupt_latest_falls_back_to_previous_valid(tmp_path, target):
    mgr, _ = _save_epochs(tmp_path, [1, 2, 3])
    faults.corrupt_latest(mgr, target=target)
    like = {"w": np.zeros(4), "v": 0}
    state, epoch = mgr.restore_latest(like)
    assert epoch == 2
    np.testing.assert_array_equal(state["w"], np.arange(4.0) + 1 + 2)


def test_all_corrupt_raises_not_fresh_start(tmp_path):
    mgr, _ = _save_epochs(tmp_path, [1, 2])
    faults.corrupt_checkpoint(str(tmp_path / "ckpt" / "ckpt-1"), "arrays")
    faults.corrupt_checkpoint(str(tmp_path / "ckpt" / "ckpt-2"), "manifest")
    with pytest.raises(CheckpointIntegrityError, match="no valid checkpoint"):
        mgr.restore_latest({"w": np.zeros(4), "v": 0})


def test_restore_explicit_epoch_verifies_integrity(tmp_path):
    mgr, _ = _save_epochs(tmp_path, [1])
    faults.corrupt_latest(mgr, target="arrays")
    # Depending on where the flipped bytes land, damage surfaces as a
    # zip-CRC load failure or as a fingerprint mismatch — both must be
    # the integrity error the fallback ladder keys on.
    with pytest.raises(CheckpointIntegrityError,
                       match="integrity|unloadable"):
        mgr.restore(1, {"w": np.zeros(4), "v": 0})


def test_fingerprint_catches_swapped_arrays(tmp_path):
    """A VALID npz from a different epoch swapped under a manifest passes
    every structural check — only the sha256 fingerprint catches it."""
    import shutil

    mgr, _ = _save_epochs(tmp_path, [1, 2, 3])
    shutil.copy(
        str(tmp_path / "ckpt" / "ckpt-1" / "arrays.npz"),
        str(tmp_path / "ckpt" / "ckpt-3" / "arrays.npz"),
    )
    like = {"w": np.zeros(4), "v": 0}
    with pytest.raises(CheckpointIntegrityError, match="fingerprint"):
        mgr.restore(3, like)
    _, epoch = mgr.restore_latest(like)
    assert epoch == 2  # ladder falls back past the tampered snapshot


def test_empty_manager_restore_latest_is_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.restore_latest({"w": np.zeros(2)}) is None


def test_torn_write_never_commits(tmp_path):
    mgr, _ = _save_epochs(tmp_path, [1, 2])
    with faults.armed(faults.FaultPlan(faults.TornWrite(3))):
        with pytest.raises(faults.FaultInjected, match="torn"):
            mgr.save({"w": np.zeros(4), "v": 3}, 3)
    # Epoch 3 never became visible; the ladder restores epoch 2.
    assert mgr.latest_epoch() == 2
    _, epoch = mgr.restore_latest({"w": np.zeros(4), "v": 0})
    assert epoch == 2


def test_kill_after_checkpoint_commits_first(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    plan = faults.FaultPlan(faults.KillAfterCheckpoint(min_epoch=4))
    with faults.armed(plan):
        with pytest.raises(faults.FaultInjected, match="kill after"):
            iterate(
                _count_step, 0.0, [float(i) for i in range(10)],
                IterationConfig(TerminateOnMaxIter(10),
                                checkpoint_interval=2,
                                checkpoint_manager=mgr),
            )
    # The epoch-4 snapshot IS durable — the kill happened after commit.
    assert mgr.latest_epoch() == 4
    state, epoch = mgr.restore_latest(0.0)
    assert (state, epoch) == (0.0 + 0 + 1 + 2 + 3, 4)


def test_corrupt_then_kill_composes_in_plan_order(tmp_path):
    """The canonical acceptance scenario: the newest snapshot is corrupted
    AND the process dies at the same commit; recovery must use the prior
    snapshot."""
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    plan = faults.FaultPlan(
        faults.CorruptSnapshot(min_epoch=4, target="arrays"),
        faults.KillAfterCheckpoint(min_epoch=4),
    )
    with faults.armed(plan):
        with pytest.raises(faults.FaultInjected):
            iterate(
                _count_step, 0.0, [float(i) for i in range(10)],
                IterationConfig(TerminateOnMaxIter(10),
                                checkpoint_interval=2,
                                checkpoint_manager=mgr),
            )
    assert [s for s, _, _ in plan.log] == [
        "checkpoint.committed", "checkpoint.committed"
    ]
    state, epoch = mgr.restore_latest(0.0)
    assert epoch == 2  # epoch 4 is corrupt → ladder fell back
    assert state == 0.0 + 0 + 1


# ---------------------------------------------------------------------------
# Transfer + publish faults
# ---------------------------------------------------------------------------

def test_transfer_fault_fail():
    guard = DispatchGuard(interval=0)
    with faults.armed(faults.FaultPlan(faults.TransferFault(at_count=2))):
        guard.after_dispatch(np.zeros(2))
        with pytest.raises(faults.FaultInjected, match="transfer"):
            guard.after_dispatch(np.zeros(2))


def test_transfer_fault_delay_does_not_raise():
    guard = DispatchGuard(interval=0)
    plan = faults.FaultPlan(
        faults.TransferFault(at_count=1, mode="delay", delay_s=0.001)
    )
    with faults.armed(plan):
        guard.after_dispatch(np.zeros(2))
    assert plan.log and plan.log[0][0] == "dispatch.transfer"


def test_drop_publish_leaves_registry_untouched(tmp_path):
    from flinkml_tpu.models.online_kmeans import OnlineKMeansModel
    from flinkml_tpu.serving.registry import ModelRegistry

    model = OnlineKMeansModel()
    model._centroids = np.zeros((2, 3))
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(model)
    with faults.armed(faults.FaultPlan(faults.DropPublish(at_publish=1))):
        with pytest.raises(faults.FaultInjected, match="dropped publish"):
            reg.publish(model)
    assert reg.versions() == [1]
    assert reg.current_version() == 1
    # The next publish (plan disarmed) proceeds normally.
    assert reg.publish(model) == 2


# ---------------------------------------------------------------------------
# Preemption watchdog
# ---------------------------------------------------------------------------

class _DrainRecorder:
    def __init__(self):
        self.stopped = []

    def stop(self, drain=True, timeout=None):
        self.stopped.append(drain)


def test_watchdog_requests_final_checkpoint_and_drain(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    engine = _DrainRecorder()
    wd = PreemptionWatchdog(signals=())
    wd.register_engine(engine)

    fired = {"at": None}

    def step(state, data, epoch):
        if epoch == 3:
            wd.request("test preemption")
            fired["at"] = epoch
        return state + float(data), None

    with wd:
        assert active() is wd
        result = iterate(
            step, 0.0, [float(i) for i in range(10)],
            IterationConfig(TerminateOnMaxIter(10), checkpoint_interval=100,
                            checkpoint_manager=mgr),
        )
    assert active() is None
    assert result.preempted
    # Stopped at the epoch boundary after the request: 4 epochs ran.
    assert result.epochs == 4 and fired["at"] == 3
    # One final checkpoint committed, engines drained afterwards.
    assert mgr.latest_epoch() == 4
    assert engine.stopped == [True]
    state, epoch = mgr.restore_latest(0.0)
    assert (state, epoch) == (0.0 + 0 + 1 + 2 + 3, 4)


def test_watchdog_resume_completes_to_parity(tmp_path):
    golden = iterate(_count_step, 0.0, [float(i) for i in range(8)],
                     IterationConfig(TerminateOnMaxIter(8))).state

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    wd = PreemptionWatchdog(signals=())

    def step(state, data, epoch):
        if epoch == 4:
            wd.request()
        return state + float(data), None

    with wd:
        first = iterate(step, 0.0, [float(i) for i in range(8)],
                        IterationConfig(TerminateOnMaxIter(8),
                                        checkpoint_manager=mgr))
    assert first.preempted
    resumed = iterate(_count_step, 0.0, [float(i) for i in range(8)],
                      IterationConfig(TerminateOnMaxIter(8),
                                      checkpoint_manager=mgr),
                      resume=True)
    assert not resumed.preempted
    assert resumed.state == golden


def test_watchdog_sigterm_sets_flag():
    wd = PreemptionWatchdog(signals=(signal.SIGTERM,))
    with wd:
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython delivers the signal at a bytecode boundary; the wait
        # below both yields and bounds the test.
        assert wd._event.wait(timeout=5.0)
        assert wd.requested and wd.reason == f"signal {signal.SIGTERM}"
    # Handler restored: sending SIGTERM now would kill the process, so
    # just check the watchdog is no longer active.
    assert active() is None


def test_watchdog_finalize_idempotent():
    engine = _DrainRecorder()
    wd = PreemptionWatchdog(signals=())
    wd.register_engine(engine)
    wd.finalize()
    wd.finalize()
    assert engine.stopped == [True]


def test_watchdog_preemption_with_torn_final_write_falls_back(tmp_path):
    """Compound failure (ISSUE 6 satellite): SIGTERM arrives AND the
    preemption's final checkpoint write tears (``TornWrite`` at the
    ``checkpoint.write`` seam — the host dies mid-flush of its last
    snapshot). The torn commit must surface, the PRIOR interval commit
    must remain the restore point, and a resume must reach parity with
    the uninterrupted run."""
    stream = [float(i) for i in range(8)]
    golden = iterate(_count_step, 0.0, stream,
                     IterationConfig(TerminateOnMaxIter(8))).state

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    wd = PreemptionWatchdog(signals=(signal.SIGTERM,))

    def step(state, data, epoch):
        if epoch == 4:
            os.kill(os.getpid(), signal.SIGTERM)  # a REAL SIGTERM
        return state + float(data), None

    # Interval commits land at epochs 2 and 4; the preemption stop then
    # attempts a terminal snapshot at epoch 5, whose write tears.
    with wd:
        with faults.armed(faults.FaultPlan(faults.TornWrite(5))) as plan:
            with pytest.raises(faults.FaultInjected, match="torn"):
                iterate(
                    step, 0.0, stream,
                    IterationConfig(TerminateOnMaxIter(8),
                                    checkpoint_interval=2,
                                    checkpoint_manager=mgr),
                )
    assert ("checkpoint.write", "TornWrite(5)", {
        "epoch": 5, "directory": str(tmp_path / "ckpt"),
    }) in [(s, d, {k: v for k, v in c.items() if k != "path"})
           for s, d, c in plan.log]
    # The torn epoch-5 snapshot never became visible; epoch 4 survives.
    assert mgr.latest_epoch() == 4
    state, epoch = mgr.restore_latest(0.0)
    assert (state, epoch) == (0.0 + 0 + 1 + 2 + 3, 4)

    resumed = iterate(_count_step, 0.0, stream,
                      IterationConfig(TerminateOnMaxIter(8),
                                      checkpoint_interval=2,
                                      checkpoint_manager=mgr),
                      resume=True)
    assert not resumed.preempted
    assert resumed.state == golden
