"""flinkml_tpu.data (ISSUE 5): sources, ops, cursors, and the bucketed
async device prefetcher.

Covers the subsystem's contracts: deterministic replay (shuffle
included), cursor fast-forward == uninterrupted sequence, zero-retrace
prefetch into the fused executor, producer-latency overlap, worker
lifecycle (abandonment, raising sources), fault seams, and sharding.
"""

import gc
import os
import threading
import time

import numpy as np
import pytest

from flinkml_tpu import faults
from flinkml_tpu.data import (
    ArraySource,
    Cursor,
    Dataset,
    DevicePrefetcher,
    SyntheticSource,
)
from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.table import PaddedDeviceColumn, Table


def _table(n=40, d=3, seed=0):
    rng = np.random.default_rng(seed)
    return Table({"features": rng.normal(size=(n, d)),
                  "y": np.arange(float(n))})


def _ys(ds_or_it):
    return [np.asarray(b.column("y")) for b in ds_or_it]


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------

def test_array_source_batches_and_skip():
    src = ArraySource(_table(23), batch_size=5)
    rows = [b.num_rows for b in src.open()]
    assert rows == [5, 5, 5, 5, 3]
    full = [np.asarray(b.column("y")) for b in src.open()]
    skipped = [np.asarray(b.column("y")) for b in src.open(skip_batches=3)]
    assert all(np.array_equal(a, b) for a, b in zip(full[3:], skipped))
    it = src.open(2)
    next(it)
    assert it.position()["row_offset"] == 15


def test_array_source_sharding_partitions_rows():
    t = _table(25)
    parts = [ArraySource(t, 4, shard=(i, 3)) for i in range(3)]
    got = np.concatenate(
        [np.concatenate([b.column("y") for b in p.open()]) for p in parts]
    )
    np.testing.assert_array_equal(np.sort(got), np.arange(25.0))
    # Contiguous blocks, remainder on the leading shard.
    assert [sum(b.num_rows for b in p.open()) for p in parts] == [9, 8, 8]


def test_synthetic_source_global_index_determinism():
    def mk(i, rng):
        return Table({"v": rng.normal(size=(3, 2)) + i})

    whole = [np.asarray(b.column("v"))
             for b in SyntheticSource(mk, 8, seed=5).open()]
    # Sharded draws reproduce the same global batches.
    s0 = [np.asarray(b.column("v"))
          for b in SyntheticSource(mk, 8, seed=5, shard=(0, 2)).open()]
    s1 = [np.asarray(b.column("v"))
          for b in SyntheticSource(mk, 8, seed=5, shard=(1, 2)).open()]
    for i, arr in enumerate(whole):
        target = s0[i // 2] if i % 2 == 0 else s1[i // 2]
        np.testing.assert_array_equal(arr, target)


def test_csv_source_glob_skip_and_missing(tmp_path):
    for fi, rows in enumerate((7, 5, 9)):
        lines = ["a,b"] + [f"{fi * 100 + r},{r}" for r in range(rows)]
        (tmp_path / f"part-{fi}.csv").write_text("\n".join(lines) + "\n")
    ds = Dataset.from_csv(str(tmp_path / "part-*.csv"), batch_size=4)
    full = [np.asarray(b.column("a")) for b in ds]
    assert sum(len(x) for x in full) == 21
    assert full[0][0] == 0 and full[2][0] == 100  # sorted glob order
    tail = [np.asarray(b.column("a")) for b in ds.iterate_from(2)]
    assert all(np.array_equal(a, b) for a, b in zip(full[2:], tail))
    with pytest.raises(FileNotFoundError, match="glob"):
        Dataset.from_csv(str(tmp_path / "nope-*.csv"), batch_size=4)


def test_libsvm_source(tmp_path):
    (tmp_path / "p0.svm").write_text(
        "1 1:0.5 3:1.5\n-1 2:2.0\n1 1:1.0 2:1.0 3:1.0\n"
    )
    ds = Dataset.from_libsvm(str(tmp_path / "*.svm"), batch_size=2,
                             n_features=3)
    batches = list(ds)
    assert [b.num_rows for b in batches] == [2, 1]
    assert batches[0].column("features").shape == (2, 3)
    np.testing.assert_array_equal(batches[0].column("label"), [1.0, -1.0])


# ---------------------------------------------------------------------------
# Ops
# ---------------------------------------------------------------------------

def test_map_filter_rebatch_window():
    ds = Dataset.from_arrays(_table(30), batch_size=7)
    doubled = ds.map(lambda t: t.with_column("y", t.column("y") * 2))
    np.testing.assert_array_equal(
        np.concatenate(_ys(doubled)), np.arange(30.0) * 2
    )
    odd = ds.filter(lambda t: t.column("y") % 2 == 1)
    got = np.concatenate(_ys(odd))
    np.testing.assert_array_equal(got, np.arange(1.0, 30.0, 2))

    rb = ds.rebatch(8)
    assert [b.num_rows for b in rb] == [8, 8, 8, 6]
    np.testing.assert_array_equal(np.concatenate(_ys(rb)), np.arange(30.0))
    assert [b.num_rows for b in ds.rebatch(8, drop_remainder=True)] == [8] * 3

    w = ds.window(10, stride=5)
    starts = [b.column("y")[0] for b in w]
    assert starts == [0.0, 5.0, 10.0, 15.0, 20.0]
    assert all(b.num_rows == 10 for b in w)


def test_shuffle_is_deterministic_and_complete():
    ds = Dataset.from_arrays(_table(40), batch_size=5).shuffle(4, seed=3)
    a, b = _ys(ds), _ys(ds)
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    np.testing.assert_array_equal(
        np.sort(np.concatenate(a)), np.arange(40.0)
    )
    # A different seed produces a different order.
    c = _ys(Dataset.from_arrays(_table(40), batch_size=5).shuffle(4, seed=4))
    assert not all(np.array_equal(x, y) for x, y in zip(a, c))
    # And the order is actually shuffled.
    firsts = [x[0] for x in a]
    assert firsts != sorted(firsts)


def test_prefetch_must_be_last():
    ds = Dataset.from_arrays(_table(10), 5).prefetch()
    with pytest.raises(ValueError, match="LAST stage"):
        ds.map(lambda t: t)
    with pytest.raises(ValueError, match="already has a prefetch"):
        ds.prefetch()


# ---------------------------------------------------------------------------
# Cursors
# ---------------------------------------------------------------------------

def test_cursor_fast_skip_matches_replay_skip():
    # Skip-transparent chain (map only): skip is pushed to the source.
    ds = Dataset.from_arrays(_table(35), 5).map(
        lambda t: t.with_column("y", t.column("y") + 1)
    )
    assert ds.skip_transparent
    full = _ys(ds)
    tail = _ys(ds.iterate_from(4))
    assert all(np.array_equal(a, b) for a, b in zip(full[4:], tail))
    # Non-transparent chain (shuffle): functional replay, same contract.
    ds2 = ds.shuffle(3, seed=8)
    assert not ds2.skip_transparent
    full2 = _ys(ds2)
    tail2 = _ys(ds2.iterate_from(4))
    assert all(np.array_equal(a, b) for a, b in zip(full2[4:], tail2))


def test_cursor_snapshot_fields_and_in_flight():
    ds = Dataset.from_arrays(_table(40), 4).shuffle(3, seed=1)
    it = ds.iterate()
    for _ in range(3):
        next(it)
    cur = it.cursor()
    assert cur.emitted == 3
    assert cur.source["num_shards"] == 1
    # The shuffle buffer holds batches the consumer has not seen yet.
    assert cur.in_flight >= 1
    assert cur.shuffle is not None and "state" in cur.shuffle
    it.close()


def test_cursor_rides_checkpoint_manager(tmp_path):
    cur = Cursor(emitted=7, source={"row_offset": 35}, in_flight=2)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save({"w": np.arange(3.0), **cur.to_state()}, epoch=7)
    state, epoch = mgr.restore_latest(
        like={"w": 0, "cursor": 0}
    )
    assert epoch == 7
    restored = Cursor.from_state(state)
    assert restored == cur


def test_iterate_checkpoints_cursor_in_extra(tmp_path):
    """The runtime writes the Dataset cursor into every snapshot's extra
    manifest and reopens the pipeline from it on resume."""
    from flinkml_tpu.iteration import IterationConfig, TerminateOnMaxIter, iterate

    ds = Dataset.from_arrays(_table(40), 4).shuffle(3, seed=2)
    golden = []

    def record_golden(s, b, e):
        golden.append(np.asarray(b.column("y")))
        return s, None

    iterate(record_golden, 0, ds,
            IterationConfig(TerminateOnMaxIter(2**31 - 1)))

    mgr = CheckpointManager(str(tmp_path), max_to_keep=20)
    seen = []

    def step(s, b, e):
        seen.append(np.asarray(b.column("y")))
        if e == 6:
            raise faults.FaultInjected("scripted")
        return s, None

    with pytest.raises(faults.FaultInjected):
        iterate(step, 0, ds, IterationConfig(
            TerminateOnMaxIter(2**31 - 1), checkpoint_interval=2,
            checkpoint_manager=mgr,
        ))
    assert mgr.latest_epoch() == 6
    state, epoch = mgr.restore_latest(like=0)
    assert mgr.last_restored_extra["data_cursor"]["emitted"] == 6

    def step2(s, b, e):
        seen.append(np.asarray(b.column("y")))
        return s, None

    iterate(step2, 0, ds, IterationConfig(
        TerminateOnMaxIter(2**31 - 1), checkpoint_interval=2,
        checkpoint_manager=mgr,
    ), resume=True)
    # seen = 7 pre-crash batches (epoch 6's batch was consumed before the
    # raise) + the resumed tail from epoch 6: batches 6.. re-presented.
    resumed_tail = seen[7:]
    assert len(resumed_tail) == len(golden) - 6
    for g, h in zip(golden[6:], resumed_tail):
        np.testing.assert_array_equal(g, h)


# ---------------------------------------------------------------------------
# Prefetcher
# ---------------------------------------------------------------------------

def test_prefetch_parity_and_padded_columns():
    ds = Dataset.from_arrays(_table(37), 5)
    plain = [np.asarray(b.column("features")) for b in ds]
    fed = list(ds.prefetch(depth=2))
    assert len(fed) == len(plain)
    for t, ref in zip(fed, plain):
        col = t._raw_column("features")
        assert isinstance(col, PaddedDeviceColumn)
        assert col.buf.shape[0] >= col.rows
        assert (col.buf.shape[0] & (col.buf.shape[0] - 1)) == 0  # pow2
        np.testing.assert_array_equal(np.asarray(t.column("features")), ref)
        assert t.column("features").dtype == ref.dtype  # dtype preserved


@pytest.mark.no_retrace(allow_compiles=1)
def test_prefetched_feed_drives_fused_chain_with_zero_retraces():
    """ISSUE 5 acceptance: the bucketed prefetch feed drives a fused
    transform chain with zero retraces after warmup — varying row
    counts inside a bucket, and pre-warmed buckets, compile nothing.

    The budget of 1 covers the chain's FIRST warmup compile, which
    happens inside the test body (the second warmed bucket is a
    policy-allowed new-bucket compile); the prefetched loop itself must
    add zero."""
    from flinkml_tpu.models.scalers import MinMaxScaler, StandardScaler
    from flinkml_tpu.pipeline import PipelineModel

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4))
    train = Table({"features": x})
    s1 = StandardScaler().set_input_col("features").set_output_col("s1")
    m1 = s1.fit(train)
    (mid,) = m1.transform(train)
    m2 = MinMaxScaler().set_input_col("s1").set_output_col("s2").fit(mid)
    model = PipelineModel([m1, m2])

    # Varying batch sizes hitting buckets 8 and 16; warm both OUTSIDE
    # the guarded region (the marker's budget is zero compiles).
    def mk(i, rng_):
        rows = (5, 8, 7, 11, 16, 9)[i]
        return Table({"features": rng_.normal(size=(rows, 4))})

    ds = Dataset.synthetic(mk, 6, seed=1).prefetch(depth=2)
    for bucket in (8, 16):
        (out,) = model.transform(
            Table({"features": rng.normal(size=(bucket, 4))})
        )
        out.column("s2")

    host = []
    for t in ds:
        (out,) = model.transform(t)
        host.append(np.asarray(out.column("s2")))
    assert [len(h) for h in host] == [5, 8, 7, 11, 16, 9]
    # Bitwise parity with the pure host path (x64 golden config).
    for i, h in enumerate(host):
        rng_i = np.random.default_rng([1, i])
        (ref,) = model.transform(mk(i, rng_i))
        np.testing.assert_array_equal(h, np.asarray(ref.column("s2")))


def test_prefetch_overlaps_slow_source():
    """An injected-slow-source (DelayRead at the data.read seam)
    overlaps with consumer work: (a) the consumer's wall-clock (first
    batch delivered → exhaustion) is LESS than the sum of producer
    delays — the prefetcher hides producer latency behind the pipeline;
    (b) total wall sits near max(producer, consumer), not their sum."""
    # The pipeline hides ONE producer delay (the fill before the first
    # delivery), so the inequality's headroom is `delay` minus the
    # accumulated per-batch pad+upload+logging overhead (tens of ms
    # under pytest): keep n small and the delay comfortably larger.
    n, delay, work = 4, 0.25, 0.01
    import jax

    jax.block_until_ready(jax.device_put(np.zeros(4)))  # backend init

    def mk(i, rng_):
        return Table({"v": rng_.normal(size=(4, 2))})

    ds = Dataset.synthetic(mk, n, seed=0).prefetch(depth=2)
    with faults.armed(faults.FaultPlan(
        faults.DelayRead(delay_s=delay, site="data.read")
    )):
        it = ds.iterate()
        t_start = time.perf_counter()
        first = next(it)
        t_first = time.perf_counter()
        count = 1
        for _ in it:
            time.sleep(work)  # consumer compute the copy hides under
            count += 1
        t_end = time.perf_counter()
    assert count == n and first is not None
    producer_total = n * delay
    # (a) the acceptance inequality: consumer wall < Σ producer delays
    # (the prefetcher reads ahead, so one whole delay hides before the
    # consumer's clock starts and the rest overlap its drain).
    assert t_end - t_first < producer_total, (t_end - t_first, producer_total)

    # (b) overlap proper: with consumer work comparable to the producer
    # delay, the prefetched run beats the unprefetched one by a real
    # margin (serially they'd sum; overlapped, the slower side wins).
    delay2, work2 = 0.12, 0.12
    base = Dataset.synthetic(mk, n, seed=0)

    def consume(dataset):
        with faults.armed(faults.FaultPlan(
            faults.DelayRead(delay_s=delay2, site="data.read")
        )):
            t0 = time.perf_counter()
            for _ in dataset:
                time.sleep(work2)
            return time.perf_counter() - t0

    unfed = consume(base)
    fed = consume(base.prefetch(depth=2))
    assert fed < unfed - 2 * work2, (fed, unfed)


def test_prefetcher_abandoned_consumer_does_not_leak_thread():
    before = {t.name for t in threading.enumerate()}
    ds = Dataset.from_arrays(_table(400), 2).prefetch(depth=1)
    it = iter(ds)
    next(it)  # worker is alive and (likely) blocked on the full queue
    del it, ds
    gc.collect()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [
            t for t in threading.enumerate()
            if t.name.startswith("data-prefetch") and t.name not in before
        ]
        if not any(t.is_alive() for t in leaked):
            break
        time.sleep(0.05)
    assert not any(
        t.is_alive() for t in threading.enumerate()
        if t.name.startswith("data-prefetch") and t.name not in before
    ), "abandoned prefetch worker still alive"


def test_prefetcher_propagates_source_exception_with_traceback():
    def boom_source():
        yield Table({"v": np.zeros((2, 2))})
        raise ValueError("boom from the source")

    feed = DevicePrefetcher(boom_source(), depth=1)
    next(feed)
    with pytest.raises(ValueError, match="boom from the source") as ei:
        while True:
            next(feed)
    # Original producer traceback preserved on the re-raised exception.
    import traceback

    frames = "".join(traceback.format_tb(ei.value.__traceback__))
    assert "boom_source" in frames
    # Subsequent next() keeps raising, never hangs.
    with pytest.raises(ValueError, match="boom from the source"):
        next(feed)


def test_prefetcher_raise_at_prefetch_seam():
    ds = Dataset.from_arrays(_table(20), 4).prefetch(depth=1)
    with faults.armed(faults.FaultPlan(
        faults.RaiseAtRead(at_read=2, site="data.prefetch")
    )) as plan:
        it = ds.iterate()
        next(it)
        with pytest.raises(faults.FaultInjected, match="read #2"):
            for _ in it:
                pass
    assert [site for site, _, _ in plan.log] == ["data.prefetch"]


def test_prefetch_metrics_gauges_exported():
    from flinkml_tpu.utils.metrics import default_registry

    name = "data.prefetch.testgauges"
    ds = Dataset.from_arrays(_table(30), 5).prefetch(
        depth=2, metrics_group=name
    )
    for _ in ds:
        pass
    snap = default_registry().group(name).snapshot()
    assert snap["counters"]["batches_prefetched"] == 6
    assert snap["counters"]["rows_prefetched"] == 30
    assert "queue_depth" in snap["gauges"]
    assert 0.0 <= snap["gauges"]["stall_fraction"] <= 1.0
    assert "rows_per_sec" in snap["gauges"]
    # And the group renders through the Prometheus exposition path.
    assert "flinkml_batches_prefetched" in default_registry().render_text()


def test_datacache_feed_abandoned_consumer_does_not_leak_thread():
    """Satellite: the iteration-internal PrefetchingDeviceFeed gets the
    same abandonment guarantee as the data-plane prefetcher."""
    from flinkml_tpu.iteration.datacache import PrefetchingDeviceFeed

    batches = [{"x": np.zeros((4, 2))} for _ in range(200)]
    feed = PrefetchingDeviceFeed(iter(batches), depth=1)
    thread = feed._thread
    next(feed)
    del feed
    gc.collect()
    thread.join(timeout=5.0)
    assert not thread.is_alive(), "abandoned device-feed worker leaked"


def test_datacache_feed_context_manager_and_error_traceback():
    from flinkml_tpu.iteration.datacache import PrefetchingDeviceFeed

    def raising():
        yield {"x": np.ones((2, 2))}
        raise RuntimeError("producer exploded")

    with PrefetchingDeviceFeed(raising(), depth=1) as feed:
        next(feed)
        with pytest.raises(RuntimeError, match="producer exploded") as ei:
            while True:
                next(feed)
        import traceback

        frames = "".join(traceback.format_tb(ei.value.__traceback__))
        assert "raising" in frames
        # After the error surfaced, next() re-raises (never hangs).
        with pytest.raises(RuntimeError, match="producer exploded"):
            next(feed)
    assert not feed._thread.is_alive()


# ---------------------------------------------------------------------------
# Faults + trainer integration
# ---------------------------------------------------------------------------

def test_raise_at_read_seam_fires_mid_stream():
    ds = Dataset.from_arrays(_table(40), 4)
    with faults.armed(faults.FaultPlan(faults.RaiseAtRead(at_read=5))):
        it = ds.iterate()
        got = [next(it) for _ in range(4)]
        with pytest.raises(faults.FaultInjected, match="read #5"):
            next(it)
    assert len(got) == 4
    # Cursor after the failure resumes to the exact tail.
    cursor = it.cursor()
    it.close()
    assert cursor.emitted == 4
    tail = _ys(ds.iterate(cursor))
    np.testing.assert_array_equal(
        np.concatenate(tail), np.arange(16.0, 40.0)
    )


def test_dataset_feeds_streamed_estimator():
    """A Dataset drops in anywhere an iterable of batch Tables is
    accepted — here a streamed (out-of-core) KMeans fit."""
    from flinkml_tpu.models import KMeans

    rng = np.random.default_rng(0)
    centers = rng.uniform(-6, 6, size=(3, 4))
    x = np.concatenate([
        centers[i] + rng.normal(scale=0.3, size=(60, 4)) for i in range(3)
    ])
    ds = Dataset.from_arrays(Table({"features": x}), batch_size=32)
    model = KMeans().set_k(3).set_seed(7).set_max_iter(8).fit(ds)
    got = np.sort(np.asarray(model.centroids), axis=0)
    ref = KMeans().set_k(3).set_seed(7).set_max_iter(8).fit(
        Table({"features": x}).batches(32)
    )
    np.testing.assert_allclose(
        got, np.sort(np.asarray(ref.centroids), axis=0)
    )
