"""Crash-injection exact-resume ITs for the non-linear streamed trainers
(round-4: VERDICT r3 item 3 — fault tolerance as a framework guarantee,
not a per-family feature).

Contract (mirrors ``test_stream_fit.py::test_datacache_resume_exact`` for
the linear family): kill a streamed fit mid-run via a checkpoint manager
that raises after committing a snapshot, then resume from the durable
cache — the recovered model must equal the uninterrupted run EXACTLY.
Reference parity: ``KMeans.java:239-312`` ListState recovery,
``Checkpoints.java:43-211`` feedback-edge logging.
"""

import numpy as np
import pytest

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.iteration.datacache import cache_stream


def _crash_manager_cls(crash_at_epoch):
    class Crash(CheckpointManager):
        fired = False

        def save(self, state, epoch, extra=None, **kw):
            p = super().save(state, epoch, extra, **kw)
            if not Crash.fired and epoch >= crash_at_epoch:
                Crash.fired = True
                raise RuntimeError("injected crash")
            return p

    return Crash


def _blobs(n_batches=4, rows=64, d=5, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, size=(3, d)).astype(np.float32)
    out = []
    for _ in range(n_batches):
        assign = rng.integers(0, 3, size=rows)
        x = centers[assign] + rng.normal(scale=0.5, size=(rows, d)).astype(
            np.float32
        )
        out.append({"features": x.astype(np.float32)})
    return out


def test_kmeans_stream_resume_exact(tmp_path, mesh):
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    cache = cache_stream(iter(_blobs()))
    args = dict(k=3, mesh=mesh, max_iter=8, seed=7, column="features")

    golden = train_kmeans_stream(cache, **args)

    mgr = _crash_manager_cls(3)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        train_kmeans_stream(cache, checkpoint_manager=mgr,
                            checkpoint_interval=3, **args)
    assert mgr.latest_epoch() == 3

    recovered = train_kmeans_stream(cache, checkpoint_manager=mgr,
                                    checkpoint_interval=3, resume=True,
                                    **args)
    np.testing.assert_array_equal(recovered, golden)


def test_kmeans_stream_resume_requires_manager(mesh):
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    with pytest.raises(ValueError, match="requires a checkpoint_manager"):
        train_kmeans_stream(cache_stream(iter(_blobs())), k=3, mesh=mesh,
                            max_iter=2, seed=0, column="features",
                            resume=True)


def test_gmm_stream_resume_exact(tmp_path, mesh):
    from flinkml_tpu.models.gmm import GaussianMixture

    cache = cache_stream(iter(_blobs(seed=5)))

    def est(**kw):
        return (
            GaussianMixture(mesh=mesh, **kw)
            .set_k(3).set_max_iter(6).set_tol(0.0).set_seed(2)
        )

    golden = est().fit(cache)

    mgr = _crash_manager_cls(2)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        est(checkpoint_manager=mgr, checkpoint_interval=2).fit(cache)
    assert mgr.latest_epoch() == 2

    recovered = est(checkpoint_manager=mgr, checkpoint_interval=2,
                    resume=True).fit(cache)
    np.testing.assert_array_equal(recovered.weights, golden.weights)
    np.testing.assert_array_equal(recovered.means, golden.means)
    np.testing.assert_array_equal(recovered.covariances, golden.covariances)


def test_gmm_stream_resume_requires_manager(mesh):
    from flinkml_tpu.models.gmm import GaussianMixture

    with pytest.raises(ValueError, match="requires a checkpoint_manager"):
        GaussianMixture(mesh=mesh, resume=True).set_k(3).fit(
            cache_stream(iter(_blobs()))
        )


def _gbt_cache(seed=0):
    rng = np.random.default_rng(seed)
    batches = []
    for _ in range(4):
        x = rng.uniform(-1, 1, size=(96, 4)).astype(np.float32)
        y = (x[:, 0] * x[:, 1] + 0.5 * x[:, 2] > 0).astype(np.float32)
        batches.append({"x": x, "y": y, "w": np.ones(96, np.float32)})
    return cache_stream(iter(batches))


@pytest.mark.parametrize("subsample", [1.0, 0.7])
def test_gbt_stream_resume_exact(tmp_path, mesh, subsample):
    """Exact resume at a tree boundary; subsample=0.7 additionally proves
    the RNG fast-forward reproduces the uninterrupted run's masks."""
    from flinkml_tpu.models._gbt_stream import train_gbt_stream

    cache = _gbt_cache()
    args = dict(
        mesh=mesh, logistic=True, num_trees=6, depth=3, max_bins=16,
        learning_rate=0.3, reg_lambda=1.0, subsample=subsample, seed=0,
    )

    golden = train_gbt_stream(cache, **args)

    mgr = _crash_manager_cls(2)(str(tmp_path / f"ckpt{subsample}"))
    with pytest.raises(RuntimeError, match="injected"):
        train_gbt_stream(cache, checkpoint_manager=mgr,
                         checkpoint_interval=2, **args)
    assert mgr.latest_epoch() == 2  # trees completed before the crash

    recovered = train_gbt_stream(cache, checkpoint_manager=mgr,
                                 checkpoint_interval=2, resume=True, **args)
    for a, b in zip(golden, recovered):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gbt_estimator_resume_plumbing(tmp_path, mesh):
    """The estimator surface carries the checkpoint knobs into the
    streamed build (crash → resume through GBTClassifier itself).
    Resume requires the durable DataCache form of the input — a one-shot
    iterable is rejected (tested below)."""
    from flinkml_tpu.models.gbt import GBTClassifier

    rng = np.random.default_rng(1)
    batches = []
    for _ in range(3):
        x = rng.uniform(-1, 1, size=(64, 4)).astype(np.float32)
        batches.append({"features": x,
                        "label": (x[:, 0] > 0).astype(np.float32)})
    cache = cache_stream(iter(batches))

    def est(**kw):
        return (
            GBTClassifier(mesh=mesh, **kw)
            .set_num_trees(4).set_max_depth(2).set_max_bins(8)
            .set_seed(0)
        )

    golden = est().fit(cache)

    mgr = _crash_manager_cls(2)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        est(checkpoint_manager=mgr, checkpoint_interval=2).fit(cache)

    recovered = est(checkpoint_manager=mgr, checkpoint_interval=2,
                    resume=True).fit(cache)
    g = golden.get_model_data()[0]
    r = recovered.get_model_data()[0]
    for col in g.column_names:
        np.testing.assert_array_equal(
            np.asarray(g.column(col)), np.asarray(r.column(col))
        )


def test_streamed_resume_requires_durable_cache(tmp_path, mesh):
    """resume=True with a one-shot iterable (non-replayable) must be
    rejected — a partially-consumed generator would silently train the
    restored state on a truncated dataset."""
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    with pytest.raises(ValueError, match="durable DataCache"):
        train_kmeans_stream(iter(_blobs()), k=3, mesh=mesh, max_iter=2,
                            seed=0, column="features",
                            checkpoint_manager=mgr, resume=True)


def test_quickstart_crash_recovery_recipe(tmp_path, mesh):
    """The documented cross-process recovery flow (quickstart
    'Datasets bigger than memory'): persist the sealed cache, crash,
    recover BOTH halves (DataCacheSnapshot + CheckpointManager) in a
    'fresh process', resume — exact."""
    from flinkml_tpu.iteration.datacache import DataCacheSnapshot
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    cache = cache_stream(iter(_blobs(seed=17)),
                         directory=str(tmp_path / "cache"),
                         memory_budget_bytes=1)
    DataCacheSnapshot.persist(cache, str(tmp_path / "snap"))
    args = dict(k=3, mesh=mesh, max_iter=8, seed=3, column="features")
    golden = train_kmeans_stream(cache, **args)

    mgr = _crash_manager_cls(3)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        train_kmeans_stream(cache, checkpoint_manager=mgr,
                            checkpoint_interval=3, **args)

    # "Fresh process": everything reconstructed from disk paths only.
    recovered_cache = DataCacheSnapshot.recover(str(tmp_path / "snap"))
    final = train_kmeans_stream(
        recovered_cache, checkpoint_manager=CheckpointManager(
            str(tmp_path / "ckpt")
        ), checkpoint_interval=3, resume=True, **args,
    )
    np.testing.assert_array_equal(final, golden)


@pytest.mark.parametrize("crash_epochs", [(2, 5), (3, 6)])
def test_kmeans_stream_double_failure_recovery(tmp_path, mesh, crash_epochs):
    """Mirror of the reference's failoverCount-parameterized checkpoint
    ITCases (``BoundedAllRoundCheckpointITCase.java:75-103``): the fit
    crashes TWICE at different epochs, resumes each time, and the final
    model still matches the uninterrupted run exactly."""
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    cache = cache_stream(iter(_blobs(seed=13)))
    args = dict(k=3, mesh=mesh, max_iter=8, seed=5, column="features")
    golden = train_kmeans_stream(cache, **args)

    mgr_dir = str(tmp_path / "ckpt")
    for crash_at in crash_epochs:
        mgr = _crash_manager_cls(crash_at)(mgr_dir)
        with pytest.raises(RuntimeError, match="injected"):
            train_kmeans_stream(cache, checkpoint_manager=mgr,
                                checkpoint_interval=1, resume=True, **args)
        assert mgr.latest_epoch() == crash_at

    final = train_kmeans_stream(
        cache, checkpoint_manager=CheckpointManager(mgr_dir),
        checkpoint_interval=1, resume=True, **args,
    )
    np.testing.assert_array_equal(final, golden)


# Round-4 session 3 note: every streamed fit is now multi-process-capable
# — linear/KMeans/GMM/MLP/FM/GBT/PCA/LDA/ALS/Word2Vec (the former
# single-controller rejection test lived here; the multi-process behavior
# is pinned by tests/test_distributed.py::test_two_process_streamed_fit).


def test_gbt_stream_resume_after_completion_is_noop(tmp_path, mesh):
    """Resuming a finished run (terminal checkpoint present) must return
    the finished forest without building any more trees."""
    from flinkml_tpu.models._gbt_stream import train_gbt_stream

    cache = _gbt_cache()
    args = dict(
        mesh=mesh, logistic=True, num_trees=4, depth=2, max_bins=8,
        learning_rate=0.3, reg_lambda=1.0, subsample=1.0, seed=0,
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    done = train_gbt_stream(cache, checkpoint_manager=mgr,
                            checkpoint_interval=2, **args)
    assert mgr.latest_epoch() == 4
    again = train_gbt_stream(cache, checkpoint_manager=mgr,
                             checkpoint_interval=2, resume=True, **args)
    for a, b in zip(done, again):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
