"""Meta-lint over the analyzer itself: every rule id in
``analysis.findings.RULES`` must be documented in the rule catalog
table of ``docs/development/static_analysis.md`` AND exercised by at
least one seeded fixture or live-flagging test — the next FML404-style
rule cannot land undocumented or untested without failing here."""

import os
import re

from flinkml_tpu.analysis.findings import RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs", "development", "static_analysis.md")
TESTS = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(TESTS, "analysis_fixtures")


def _documented_rules():
    """Rule ids appearing as rows of the docs catalog table
    (``| FML101 | error | ... |``)."""
    with open(DOCS) as fh:
        text = fh.read()
    return set(re.findall(r"^\|\s*(FML\d{3})\s*\|", text, re.MULTILINE))


def test_every_rule_has_a_docs_catalog_row():
    documented = _documented_rules()
    missing = sorted(set(RULES) - documented)
    assert not missing, (
        f"rules missing from the docs/development/static_analysis.md "
        f"catalog table: {missing}"
    )
    stale = sorted(documented - set(RULES))
    assert not stale, (
        f"docs catalog rows without a RULES entry (removed rule ids are "
        f"permanent — mark them retired instead of deleting): {stale}"
    )


def test_every_rule_has_a_fixture_or_a_flagging_test():
    fixture_names = " ".join(os.listdir(FIXTURES)).lower()
    test_sources = ""
    for name in sorted(os.listdir(TESTS)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(TESTS, name)) as fh:
                test_sources += fh.read()
    unexercised = sorted(
        rule for rule in RULES
        if rule.lower() not in fixture_names
        and f'"{rule}"' not in test_sources
        and f"'{rule}'" not in test_sources
    )
    assert not unexercised, (
        f"rules with neither a seeded fixture (tests/analysis_fixtures/"
        f"*{'{'}rule{'}'}*) nor a test referencing them: {unexercised}"
    )
