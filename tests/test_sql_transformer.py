"""SQLTransformer tests — the restricted SELECT surface (upstream
flink-ml's SQLTransformer runs full Flink SQL; this one parses and
vectorizes the pipeline-relevant subset, loudly rejecting the rest)."""

import numpy as np
import pytest

from flinkml_tpu.models import SQLTransformer
from flinkml_tpu.table import Table


def _t():
    return Table({
        "a": np.asarray([1.0, 2.0, 3.0, 4.0]),
        "b": np.asarray([10.0, 20.0, 30.0, 40.0]),
        "name": np.asarray(["w", "x", "y", "z"]),
        "vec": np.arange(8.0).reshape(4, 2),
    })


def _sql(stmt):
    return SQLTransformer().set_statement(stmt)


def test_star_passthrough():
    (out,) = _sql("SELECT * FROM __THIS__").transform(_t())
    assert set(out.column_names) == {"a", "b", "name", "vec"}
    np.testing.assert_array_equal(out.column("a"), [1.0, 2.0, 3.0, 4.0])


def test_arithmetic_alias_and_functions():
    (out,) = _sql(
        "SELECT *, (a + b) / 2 AS mean_ab, SQRT(b) AS rb, "
        "POW(a, 2) AS a2 FROM __THIS__"
    ).transform(_t())
    np.testing.assert_allclose(out.column("mean_ab"), [5.5, 11, 16.5, 22])
    np.testing.assert_allclose(out.column("rb"), np.sqrt([10, 20, 30, 40]))
    np.testing.assert_allclose(out.column("a2"), [1, 4, 9, 16])


def test_default_output_name_is_expression():
    (out,) = _sql("SELECT a * 2 FROM __THIS__").transform(_t())
    assert out.column_names == ["a * 2"]
    np.testing.assert_allclose(out.column("a * 2"), [2, 4, 6, 8])


def test_where_filters_all_columns_including_vectors():
    (out,) = _sql(
        "SELECT * FROM __THIS__ WHERE a >= 2 AND NOT (b = 30)"
    ).transform(_t())
    np.testing.assert_array_equal(out.column("a"), [2.0, 4.0])
    assert out.column("name").tolist() == ["x", "z"]
    np.testing.assert_array_equal(
        out.column("vec"), np.asarray([[2.0, 3.0], [6.0, 7.0]])
    )


def test_operator_precedence_and_unary_minus():
    (out,) = _sql("SELECT a + b * 2 AS e, -a AS m FROM __THIS__").transform(
        _t()
    )
    np.testing.assert_allclose(out.column("e"), [21, 42, 63, 84])
    np.testing.assert_allclose(out.column("m"), [-1, -2, -3, -4])


def test_bare_column_projection_keeps_vector_and_string():
    (out,) = _sql("SELECT name, vec, a AS aa FROM __THIS__").transform(_t())
    assert out.column("vec").shape == (4, 2)
    assert out.column("name").tolist() == ["w", "x", "y", "z"]
    np.testing.assert_array_equal(out.column("aa"), [1.0, 2.0, 3.0, 4.0])


@pytest.mark.parametrize("stmt,match", [
    ("UPDATE x SET y = 1", "supports 'SELECT"),
    ("SELECT q FROM __THIS__", "unknown column"),
    ("SELECT name + 1 FROM __THIS__", "not a 1-D numeric"),
    ("SELECT FOO(a) FROM __THIS__", "unknown function"),
    ("SELECT a FROM __THIS__ WHERE a + 1", "boolean row predicate"),
    ("SELECT a b c FROM __THIS__", "trailing tokens"),
])
def test_rejects_unsupported(stmt, match):
    with pytest.raises(ValueError, match=match):
        _sql(stmt).transform(_t())


def test_save_load_roundtrip(tmp_path):
    est = _sql("SELECT a * 2 AS d FROM __THIS__")
    est.save(str(tmp_path / "sql"))
    loaded = SQLTransformer.load(str(tmp_path / "sql"))
    (out,) = loaded.transform(_t())
    np.testing.assert_allclose(out.column("d"), [2, 4, 6, 8])


def test_in_pipeline():
    from flinkml_tpu.pipeline import Pipeline
    from flinkml_tpu.models import StandardScaler, VectorAssembler

    stages = [
        _sql("SELECT *, a * b AS ab FROM __THIS__ WHERE a < 4"),
        VectorAssembler().set_input_cols(["a", "ab"]).set_output_col("f"),
        StandardScaler().set_input_col("f").set_output_col("s"),
    ]
    model = Pipeline(stages).fit(_t())
    (out,) = model.transform(_t())
    assert out.column("s").shape == (3, 2)


def test_constant_columns_and_constant_where():
    (out,) = _sql(
        "SELECT a, 1 AS one FROM __THIS__ WHERE 1 = 1"
    ).transform(_t())
    np.testing.assert_array_equal(out.column("one"), [1.0] * 4)
    np.testing.assert_array_equal(out.column("a"), [1.0, 2.0, 3.0, 4.0])


def test_where_filters_before_projection():
    """SQL semantics: a / b WHERE b <> 0 never divides by the excluded
    zeros (no warning, no inf in the result)."""
    t = Table({
        "a": np.asarray([6.0, 8.0, 9.0]),
        "b": np.asarray([2.0, 0.0, 3.0]),
    })
    with np.errstate(divide="raise"):
        (out,) = _sql(
            "SELECT a / b AS r FROM __THIS__ WHERE b != 0"
        ).transform(t)
    np.testing.assert_allclose(out.column("r"), [3.0, 3.0])


def test_duplicate_output_columns_rejected():
    """Upstream Flink SQL rejects duplicate output columns; last-wins
    overwriting would silently drop a projected column."""
    t = Table({"a": np.array([1.0, 2.0]), "b": np.array([3.0, 4.0])})
    for stmt in (
        "SELECT a, a FROM __THIS__",
        "SELECT a + b AS c, a - b AS c FROM __THIS__",
        "SELECT *, a FROM __THIS__",
    ):
        with pytest.raises(ValueError, match="duplicate output column"):
            _sql(stmt).transform(t)
    # The '*' merge itself stays legal.
    (out,) = _sql("SELECT * FROM __THIS__").transform(t)
    assert set(out.column_names) == {"a", "b"}


def test_duplicate_via_star_either_order():
    t = Table({"a": np.array([1.0]), "b": np.array([2.0])})
    with pytest.raises(ValueError, match="duplicate output column"):
        _sql("SELECT a - b AS a, * FROM __THIS__").transform(t)
    with pytest.raises(ValueError, match="duplicate output column"):
        _sql("SELECT *, * FROM __THIS__").transform(t)
