"""Clean-process ClusterPool scenario behind ``tests/test_cluster.py``.

Why a child process: the warm-respawn acceptance ("a respawned worker
rejoins via compile-cache retarget loads — zero new XLA compiles") is
serialization-dependent, and the suite conftest's jax persistent cache
poisons XLA:CPU executable serialization process-wide (the finding
documented in ``tests/_compile_cache_child.py``). This script runs the
whole multi-process scenario in a fresh interpreter — which is also the
production shape — and prints a JSON report the pytest module asserts
over.

The scenario, end to end:

1. fit a pipeline, serve it from an in-process reference engine AND a
   2-worker :class:`~flinkml_tpu.cluster.ClusterPool`; predictions must
   be sha256-bitwise identical across the process boundary;
2. arm a :class:`~flinkml_tpu.faults.WorkerCrash` inside one worker
   over the transport (``arm_faults``) and keep closed-loop traffic
   flowing: the worker hard-exits mid-traffic and ZERO requests are
   lost (typed ``WorkerDiedError`` → router failover to the survivor);
3. ``respawn_dead()``: the successor warms from the pool's shared
   artifact store (aot loads, zero new XLA compiles) and parity holds;
4. cross-process lease reclaim: a slice lease acquired INSIDE a worker
   is revoked and released over the wire (the revoke→release handshake
   carried across the boundary).
"""

import hashlib
import json
import os
import sys
import threading
import time


def main() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"

    import numpy as np

    from flinkml_tpu import faults
    from flinkml_tpu.cluster import ClusterPool, reclaim_worker_leases
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import StandardScaler
    from flinkml_tpu.pipeline import PipelineModel
    from flinkml_tpu.serving import ServingConfig, ServingEngine
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(0)
    x = rng.normal(size=(400, 8))
    y = (x @ rng.normal(size=8) > 0).astype(np.float64)
    train = Table({"features": x, "label": y})
    sc = (StandardScaler().set(StandardScaler.INPUT_COL, "features")
          .set(StandardScaler.OUTPUT_COL, "scaled").fit(train))
    (t2,) = sc.transform(train)
    lr = (LogisticRegression()
          .set(LogisticRegression.FEATURES_COL, "scaled")
          .set(LogisticRegression.LABEL_COL, "label")
          .set_max_iter(3).fit(t2))
    model = PipelineModel([sc, lr])
    example = Table({"features": x[:4]})
    cfg = ServingConfig(max_batch_rows=64, max_queue_rows=4096,
                        max_wait_ms=1.0, default_timeout_ms=10_000.0)

    ref = ServingEngine(model, example, cfg,
                        output_cols=("prediction",), name="ref").start()
    ref_out = np.asarray(
        ref.predict({"features": x[:32]}).column("prediction")
    )

    pool = ClusterPool(model, example, config=cfg, n_workers=2,
                       output_cols=("prediction",), name="smoke").start()
    out = np.asarray(
        pool.predict({"features": x[:32]}).column("prediction")
    )
    sha_ref = hashlib.sha256(ref_out.tobytes()).hexdigest()
    sha_pool = hashlib.sha256(out.tobytes()).hexdigest()

    # -- cross-process lease reclaim (stand a REAL lease up inside a
    # worker, then run the revoke→release handshake over the wire).
    client0 = pool.worker_clients()[0]
    acquired = client0.call("lease", {"cmd": "acquire", "n": 1,
                                      "holder": "child-trainer",
                                      "cooperative": True})
    reclaimed = reclaim_worker_leases(
        client0, device_ids=acquired["devices"], timeout_s=10.0
    )

    # -- kill one worker MID-TRAFFIC via the cluster.worker fault seam
    # (a scripted WorkerCrash armed over the transport — a real
    # os._exit, not a simulated death).
    victim = pool.replicas[0]
    marker = os.path.join(victim.engine.process.workdir, "crash.marker")
    plan_json = faults.plan_to_json(faults.FaultPlan(
        faults.WorkerCrash(at=1, key="request", exit_code=23,
                           marker=marker)
    ))
    errs, done = [], [0]
    stop = threading.Event()

    def client_loop():
        while not stop.is_set():
            try:
                r = pool.predict({"features": x[:8]})
                assert np.array_equal(
                    np.asarray(r.column("prediction")), ref_out[:8]
                )
                done[0] += 1
            except Exception as e:  # noqa: BLE001 — report, don't mask
                errs.append(repr(e))

    threads = [threading.Thread(target=client_loop) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    victim.engine.client.call("arm_faults", {"plan_json": plan_json})
    deadline = time.monotonic() + 20.0
    while victim.engine.process.alive and time.monotonic() < deadline:
        time.sleep(0.05)
    crashed_rc = victim.engine.process.returncode
    time.sleep(1.0)  # post-crash traffic rides the survivor
    stop.set()
    for t in threads:
        t.join()

    health = {r.name: r.health.state.name for r in pool.replicas}

    # -- warm respawn from the shared artifact store.
    replaced = pool.respawn_dead()
    stats = replaced[0].engine.worker_stats()
    fusion = stats["fusion_counters"]
    out3 = np.asarray(
        pool.predict({"features": x[:32]}).column("prediction")
    )

    snap = pool.cluster_metrics.snapshot()
    pool.stop()
    ref.stop()

    print(json.dumps({
        "sha_ref": sha_ref,
        "sha_pool": sha_pool,
        "parity_bitwise": bool(np.array_equal(ref_out, out)),
        "lease_reclaimed": [
            {"released": r["released"], "holder": r.get("holder")}
            for r in reclaimed
        ],
        "crashed_rc": crashed_rc,
        "requests_ok": done[0],
        "requests_lost": len(errs),
        "errors_sample": errs[:3],
        "health_after_crash": health,
        "respawned": [r.name for r in replaced],
        "respawn_fusion": {k: fusion.get(k, 0.0)
                           for k in ("compiles", "aot_loads")},
        "post_respawn_parity": bool(np.array_equal(ref_out, out3)),
        "workers_alive_gauge": snap["gauges"].get("workers_alive"),
        "transport_p99_ms": snap["gauges"].get("p99_ms"),
        "spawn_ms_samples": len(snap["histories"].get("spawn_ms", [])),
    }))


if __name__ == "__main__":
    sys.exit(main())
