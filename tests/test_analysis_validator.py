"""Pass 1 (graph validator) tests: schema flow, abstract kernel eval,
fingerprint stability, dtype hygiene, graph wiring, and the AST lint —
plus the regression tests for the two real findings the validator
surfaced on the shipped stages (silent float64 promotion in the scalers
and VectorAssembler; see FML106).
"""

import numpy as np
import pytest

from flinkml_tpu import pipeline_fusion
from flinkml_tpu.analysis import (
    analyze_graph,
    analyze_pipeline,
    lint_paths,
    lint_source,
    schema_of,
)
from flinkml_tpu.graph import GraphBuilder
from flinkml_tpu.models.kmeans import KMeans, KMeansModel
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.one_hot_encoder import OneHotEncoder
from flinkml_tpu.models.scalers import (
    MaxAbsScaler,
    MinMaxScaler,
    RobustScaler,
    StandardScaler,
)
from flinkml_tpu.models.vector_assembler import VectorAssembler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.table import Table


def _rules(report):
    return [f.rule for f in report]


def _data(n=40, d=5, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(dtype)
    y = (x @ rng.normal(size=d).astype(dtype) > 0).astype(dtype)
    return Table({"features": x, "label": y})


def _scaler(cls, t, in_col, out_col):
    return cls().set(cls.INPUT_COL, in_col).set(cls.OUTPUT_COL, out_col).fit(t)


def _fitted_chain(t):
    stages = []
    cur = t
    prev = "features"
    for i, cls in enumerate(
        (StandardScaler, MinMaxScaler, MaxAbsScaler, RobustScaler), start=1
    ):
        m = _scaler(cls, cur, prev, f"s{i}")
        (cur,) = m.transform(cur)
        prev = f"s{i}"
        stages.append(m)
    lr = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, prev)
        .set(LogisticRegression.LABEL_COL, "label")
        .fit(cur)
    )
    stages.append(lr)
    return stages


# ---------------------------------------------------------------------------
# schema flow
# ---------------------------------------------------------------------------

def test_clean_chain_has_no_findings():
    t = _data()
    report = analyze_pipeline(PipelineModel(_fitted_chain(t)), schema_of(t))
    assert not report.findings, report.render()


def test_missing_input_column_fml101():
    t = _data()
    m = _scaler(StandardScaler, t, "features", "out")
    m.set(StandardScaler.INPUT_COL, "nope")
    report = analyze_pipeline(PipelineModel([m]), schema_of(t))
    assert "FML101" in _rules(report)
    (f,) = [f for f in report if f.rule == "FML101"]
    assert f.column == "nope" and "features" in f.message


def test_output_collision_fml102():
    t = _data()
    a = _scaler(StandardScaler, t, "features", "out")
    b = _scaler(MaxAbsScaler, a.transform(t)[0], "out", "out")  # in-place
    report = analyze_pipeline(PipelineModel([a, b]), schema_of(t))
    assert "FML102" in _rules(report)
    # Overwriting source data is also a collision.
    c = _scaler(MinMaxScaler, t, "features", "label")
    report2 = analyze_pipeline(PipelineModel([c]), schema_of(t))
    assert "FML102" in _rules(report2)


def test_shape_mismatch_fml103():
    t = _data(d=4)
    s = _scaler(StandardScaler, t, "features", "scaled")
    km = KMeansModel().set(KMeansModel.FEATURES_COL, "scaled")
    km.set_model_data(
        Table({"centroids": np.zeros((1, 3, 7))})  # d=7 vs features d=4
    )
    report = analyze_pipeline(PipelineModel([s, km]), schema_of(t))
    assert "FML103" in _rules(report)


def test_fusion_break_fml104():
    from flinkml_tpu.api import AlgoOperator

    class HostStage(AlgoOperator):
        def transform(self, *inputs):
            return inputs

    t = _data()
    a = _scaler(StandardScaler, t, "features", "a")
    b = _scaler(MaxAbsScaler, a.transform(t)[0], "a", "b")
    report = analyze_pipeline(
        PipelineModel([a, HostStage(), b]), schema_of(t)
    )
    assert "FML104" in _rules(report)


def test_unstable_fingerprint_fml105():
    t = _data()
    base = _scaler(StandardScaler, t, "features", "a")
    b = _scaler(MaxAbsScaler, base.transform(t)[0], "a", "b")

    class Unstable(type(base)):
        _tick = [0]

        def transform_kernel(self):
            k = super().transform_kernel()
            self._tick[0] += 1
            import dataclasses
            return dataclasses.replace(
                k, fingerprint=k.fingerprint + (self._tick[0],)
            )

    u = Unstable()
    u.copy_params_from(base)
    u._mean, u._std = base._mean, base._std
    report = analyze_pipeline(PipelineModel([u, b]), schema_of(t))
    assert "FML105" in _rules(report)


def test_ordering_error_fml107_open_schema():
    # Open schema (AST-lint mode): consumer before producer is an error.
    t = _data()
    producer = _scaler(StandardScaler, t, "features", "scaled")
    consumer = _scaler(MaxAbsScaler, producer.transform(t)[0], "scaled", "z")
    report = analyze_pipeline([consumer, producer], schema=None)
    assert "FML107" in _rules(report)


# ---------------------------------------------------------------------------
# shipped models: kernel contract sweep + FML106 regressions
# ---------------------------------------------------------------------------

def test_every_shipped_kernel_validates_clean():
    """The full kernel-capable stage set flows through the validator with
    zero findings on its canonical wiring — the 'run the validator over
    every shipped model' gate."""
    t = _data()
    stages = _fitted_chain(t)
    km = (
        KMeans()
        .set(KMeans.FEATURES_COL, "features")
        .set(KMeans.K, 2)
        .set(KMeans.PREDICTION_COL, "cluster")
        .fit(t)
    )
    enc_train = Table({"c1": np.array([0.0, 1.0, 2.0])})
    enc = (
        OneHotEncoder()
        .set_input_cols(["c1"])
        .set_output_cols(["o1"])
        .set_handle_invalid("keep")
        .fit(enc_train)
    )
    t2 = t.with_column("c1", np.zeros(len(t)))
    report = analyze_pipeline(
        PipelineModel(stages + [km, enc]), schema_of(t2)
    )
    assert not report.findings, report.render()


def test_float32_scaler_chain_no_promotion():
    """Regression (real finding #1): scalers promoted float32 input to
    float64 on the CPU fallback path. They now preserve the input float
    dtype — validator-clean and bitwise fused==host at float32."""
    t = _data(dtype=np.float32)
    stages = _fitted_chain(t)[:4]  # the four scalers
    pm = PipelineModel(stages)
    report = analyze_pipeline(pm, schema_of(t))
    assert "FML106" not in _rules(report), report.render()

    pipeline_fusion.set_enabled(False)
    (host,) = pm.transform(t)
    pipeline_fusion.set_enabled(True)
    pipeline_fusion.reset_cache()
    (fused,) = pm.transform(t)
    for c in ("s1", "s2", "s3", "s4"):
        assert host.column(c).dtype == np.float32
        assert fused.column(c).dtype == np.float32
        np.testing.assert_array_equal(host.column(c), fused.column(c))


def test_float32_assembler_no_promotion():
    """Regression (real finding #2): VectorAssembler promoted every part
    to float64. All-float32 parts now assemble to float32 (host and
    fused, bitwise-equal); mixed width still promotes to the widest."""
    rng = np.random.default_rng(3)
    t = Table({
        "a": rng.normal(size=(20, 3)).astype(np.float32),
        "b": rng.normal(size=20).astype(np.float32),
    })
    va = (
        VectorAssembler()
        .set(VectorAssembler.INPUT_COLS, ["a", "b"])
        .set(VectorAssembler.HANDLE_INVALID, "keep")
        .set(VectorAssembler.OUTPUT_COL, "asm")
    )
    report = analyze_pipeline([va], schema_of(t))
    assert "FML106" not in _rules(report), report.render()
    (host,) = va.transform(t)
    assert host.column("asm").dtype == np.float32

    kernel = va.transform_kernel()
    fused = pipeline_fusion.execute_kernel_chain(t, [kernel])
    assert fused.column("asm").dtype == np.float32
    np.testing.assert_array_equal(host.column("asm"), fused.column("asm"))

    t64 = t.with_column("c", rng.normal(size=20))  # float64 part
    va64 = (
        VectorAssembler()
        .set(VectorAssembler.INPUT_COLS, ["a", "b", "c"])
        .set(VectorAssembler.HANDLE_INVALID, "keep")
        .set(VectorAssembler.OUTPUT_COL, "asm")
    )
    assert va64.transform(t64)[0].column("asm").dtype == np.float64


def test_object_vector_column_not_abstract_evaluated():
    """Row-wise Vector (object) feature columns are valid pipeline input
    — the host path densifies them and the runtime fuser skips them — so
    the validator must skip kernel abstract evaluation instead of
    reporting a false FML103."""
    from flinkml_tpu.linalg import DenseVector

    rng = np.random.default_rng(0)
    col = np.empty(10, dtype=object)
    for i in range(10):
        col[i] = DenseVector(rng.normal(size=3))
    t = Table({"features": col})
    dense = Table({"features": rng.normal(size=(10, 3))})
    m = _scaler(StandardScaler, dense, "features", "out")
    (expected,) = m.transform(t)  # the host path genuinely works
    assert expected.column("out").shape == (10, 3)
    report = analyze_pipeline(PipelineModel([m]), schema_of(t))
    assert "FML103" not in _rules(report), report.render()


def test_analyze_pipeline_accepts_iterator():
    t = _data()
    stages = _fitted_chain(t)
    report = analyze_pipeline(iter(stages), schema_of(t))
    assert not report.findings, report.render()


def test_float32_scaler_zero_guard_after_downcast():
    """Regression: with dtype-preserving transforms, a float64 fitted std
    that is positive but underflows to 0.0 in float32 must take the
    constant-feature branch (divide by 1), not divide by zero. The guard
    is applied AFTER the downcast, identically on host and fused paths."""
    from flinkml_tpu.models.scalers import StandardScalerModel

    m = (
        StandardScalerModel()
        .set(StandardScalerModel.INPUT_COL, "x")
        .set(StandardScalerModel.OUTPUT_COL, "out")
        .set(StandardScalerModel.WITH_MEAN, False)
    )
    # 5e-46 > 0 in float64, but rounds to 0.0 in float32.
    m.set_model_data(Table({
        "mean": np.zeros((1, 2)), "std": np.array([[5e-46, 1.0]]),
    }))
    t = Table({"x": np.ones((8, 2), dtype=np.float32)})
    (host,) = m.transform(t)
    assert np.isfinite(host.column("out")).all(), host.column("out")
    np.testing.assert_array_equal(host.column("out")[:, 0], 1.0)

    fused = pipeline_fusion.execute_kernel_chain(t, [m.transform_kernel()])
    assert host.column("out").dtype == fused.column("out").dtype == np.float32
    np.testing.assert_array_equal(host.column("out"), fused.column("out"))


def test_float64_promotion_still_flagged_fml106():
    """The rule itself keeps teeth: a kernel that hard-casts to float64
    over float32 input is flagged."""
    from flinkml_tpu.api import AlgoOperator, ColumnKernel

    class Promoter(AlgoOperator):
        def transform(self, *inputs):
            (t,) = inputs
            return (t.with_column("wide", t.column("x").astype(np.float64)),)

        def transform_kernel(self):
            import jax.numpy as jnp

            def fn(cols, consts, valid):
                return {"wide": cols["x"].astype(jnp.float64)}

            return ColumnKernel(("x",), ("wide",), fn,
                                fingerprint=("Promoter",))

    t = Table({"x": np.ones(8, dtype=np.float32)})
    report = analyze_pipeline([Promoter()], schema_of(t))
    assert "FML106" in _rules(report)


# ---------------------------------------------------------------------------
# graph wiring
# ---------------------------------------------------------------------------

def test_graph_wiring_clean_and_broken():
    t = _data()

    def build(missing_input):
        builder = GraphBuilder().set_max_output_table_num(1)
        src = builder.create_table_id()
        dangling = builder.create_table_id()  # never produced
        s = StandardScaler().set(StandardScaler.INPUT_COL, "features").set(
            StandardScaler.OUTPUT_COL, "scaled"
        )
        outs = builder.add_estimator(
            s, dangling if missing_input else src
        )
        return builder.build_estimator([src], outs)

    assert not analyze_graph(build(False)).findings
    report = analyze_graph(build(True))
    assert "FML201" in _rules(report)


def test_graph_unproduced_output_fml202():
    builder = GraphBuilder().set_max_output_table_num(1)
    src = builder.create_table_id()
    s = StandardScaler()
    builder.add_estimator(s, src)
    bogus = builder.create_table_id()
    g = builder.build_estimator([src], [bogus])
    assert "FML202" in _rules(analyze_graph(g))


def test_graph_duplicate_output_claim_fml203():
    builder = GraphBuilder().set_max_output_table_num(1)
    src = builder.create_table_id()
    (o1,) = builder.add_estimator(StandardScaler(), src)
    builder.add_estimator(StandardScaler(), src)
    g = builder.build_estimator([src], [o1])
    # Seed the defect _execute_nodes would hit at runtime: the second
    # node rewired to claim the first node's output id.
    g._nodes[1].output_ids = list(g._nodes[0].output_ids)
    assert "FML203" in _rules(analyze_graph(g))


# ---------------------------------------------------------------------------
# AST lint
# ---------------------------------------------------------------------------

def test_lint_shipped_examples_clean():
    report = lint_paths(["examples/"])
    assert not report.findings, report.render()


def test_lint_fixture_findings():
    report = lint_paths(["tests/analysis_fixtures/"])
    rules = _rules(report)
    assert "FML107" in rules and "FML102" in rules, report.render()


def test_lint_resolves_defaults_and_comprehensions():
    src = """
from flinkml_tpu.models import VectorAssembler, StandardScaler
from flinkml_tpu.pipeline import Pipeline
d = 3
pipe = Pipeline([
    VectorAssembler().set_input_cols([f"f{i}" for i in range(d)])
                     .set(VectorAssembler.OUTPUT_COL, "input"),
    StandardScaler(),  # default input -> output wiring
])
"""
    report = lint_source(src, "inline.py")
    assert not report.findings, report.render()

    # Breaking the default wiring is caught: assembler writes "xx", the
    # scaler's default input "input" is then produced by nobody — but in
    # open-schema mode that is only an ordering question, so instead break
    # ordering explicitly.
    src_bad = """
from flinkml_tpu.models import VectorAssembler, StandardScaler
from flinkml_tpu.pipeline import Pipeline
pipe = Pipeline([
    StandardScaler(),                      # reads "input"...
    VectorAssembler().set_input_cols(["a"])
                     .set(VectorAssembler.OUTPUT_COL, "input"),  # ...produced later
])
"""
    report_bad = lint_source(src_bad, "inline.py")
    assert "FML107" in _rules(report_bad), report_bad.render()


def test_cli_exit_codes():
    import subprocess
    import sys

    ok = subprocess.run(
        [sys.executable, "-m", "flinkml_tpu.analysis", "examples/",
         "--fail-on-findings", "--no-selfcheck"],
        capture_output=True, text=True, timeout=300,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "flinkml_tpu.analysis",
         "tests/analysis_fixtures/", "--fail-on-findings",
         "--no-selfcheck"],
        capture_output=True, text=True, timeout=300,
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "FML302" in bad.stdout  # the PR 1 deadlock fixture
