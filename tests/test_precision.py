"""Policy-gated mixed precision (ISSUE 10): the PrecisionPolicy value,
the FML6xx precision-flow pass (pass 5), and the three gated paths —
the fused transform executor, the plan-sharded SGD/Adam trainers, and
serving.

Covers: the policy value itself (presets, JSON round-trip, hashability,
resolution), FML601-605 each on a seeded fixture AND FML601/602/603 on
REAL in-repo jaxprs (the linear trainer step, the fused kernel chains),
typed pre-compile refusals carrying the findings, pinned-numerics /
convergence-tolerance equivalence vs the f32 baselines for every gated
path, bf16/f32 compile-cache non-aliasing (the would-have-aliased
regression), the shared FML106 dtype-flow path, and the CLI's
``--format json`` output.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from flinkml_tpu import pipeline_fusion
from flinkml_tpu.analysis.precision import (
    check_policy_file,
    check_policy_plan,
    check_precision_fn,
    promotion_findings,
    validate_precision,
)
from flinkml_tpu.api import ColumnKernel
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.precision import (
    FULL,
    INT8_INFERENCE,
    MIXED,
    MIXED_INFERENCE,
    PrecisionPolicy,
    PrecisionValidationError,
    cast_floats,
    dequantize_absmax,
    is_narrower,
    quantize_absmax,
    resolve_policy,
)
from flinkml_tpu.serving.engine import ServingConfig, ServingEngine
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.sharding.apply import (
    linear_step_fn,
    train_linear_plan,
    validate_linear_precision,
)
from flinkml_tpu.sharding.plan import FSDP, REPLICATED
from flinkml_tpu.table import Table

FIXDIR = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
BF16 = np.dtype(ml_dtypes.bfloat16)


# ---------------------------------------------------------------------------
# The policy value
# ---------------------------------------------------------------------------


def test_policy_presets_and_roundtrip():
    assert MIXED.compute == "bfloat16"
    assert MIXED.accum == MIXED.params == "float32"
    assert MIXED.mixed and not FULL.mixed
    assert not MIXED_INFERENCE.mixed or MIXED_INFERENCE.mixed  # defined
    again = PrecisionPolicy.from_json_dict(
        json.loads(json.dumps(MIXED.to_json_dict()))
    )
    assert again == MIXED
    assert hash(again) == hash(MIXED)  # compile-cache key material


def test_policy_accum_narrower_than_compute_refused():
    with pytest.raises(ValueError, match="accum"):
        PrecisionPolicy("bad", compute="float32", accum="bfloat16")


def test_policy_resolution_forms():
    assert resolve_policy(None) is None
    assert resolve_policy("mixed") is MIXED
    assert resolve_policy(MIXED) is MIXED
    assert resolve_policy(MIXED.to_json_dict()) == MIXED
    with pytest.raises(ValueError, match="preset"):
        resolve_policy("bf16-ish")
    with pytest.raises(TypeError):
        resolve_policy(3.14)


def test_narrowness_is_significand_ranked():
    # bf16 (8-bit significand) is NARROWER than f16 (11) despite equal
    # itemsize — accumulation correctness is a rounding question.
    assert is_narrower("bfloat16", "float16")
    assert is_narrower("float16", "float32")
    assert not is_narrower("float32", "float32")
    assert not is_narrower("int32", "float32")  # non-floats never narrow


def test_cast_floats_is_the_to_bf16_idiom():
    tree = {"coef": np.ones(3, np.float32), "step": np.int32(7)}
    down = cast_floats(tree, BF16)
    assert down["coef"].dtype == BF16
    assert down["step"].dtype == np.int32  # non-floats pass through


# ---------------------------------------------------------------------------
# The FML6xx pass on REAL in-repo jaxprs
# ---------------------------------------------------------------------------


def _sgd_step(dtype, policy=None):
    return linear_step_fn("logistic", "sgd", np.dtype(dtype).name,
                          0.1, 0.9, 0.0, 0.0, policy=policy)


def test_fml601_603_real_trainer_step_refused():
    """A deliberately mis-cast trainer step (bf16 STORAGE under the
    mixed policy) is refused pre-compile with both rules, typed."""
    with pytest.raises(PrecisionValidationError) as ei:
        validate_linear_precision(
            MIXED, _sgd_step(BF16), dim=8, rows=8, dt=BF16,
            optimizer="sgd",
        )
    rules = {f.rule for f in ei.value.findings}
    assert "FML601" in rules and "FML603" in rules
    # The typed error CARRIES the structured findings (CI annotates).
    assert all(f.severity == "error" for f in ei.value.findings)


def test_policy_correct_step_validates_clean():
    validate_linear_precision(
        MIXED, _sgd_step(np.float32, policy=MIXED), dim=8, rows=8,
        dt=np.float32, optimizer="sgd",
    )
    validate_linear_precision(
        MIXED, linear_step_fn("logistic", "adam", "float32", 0.1, 0.9,
                              0.0, 0.0, policy=MIXED),
        dim=8, rows=8, dt=np.float32, optimizer="adam",
    )


def test_fml602_stray_wide_constant_real_jaxpr():
    const = np.float32(1.5)  # STRONG f32 constant in a bf16 region

    def chain(x):
        return (x.astype(BF16) * 2.0) * const

    findings = check_precision_fn(
        chain, jax.ShapeDtypeStruct((8, 4), np.float32),
        policy=MIXED_INFERENCE,
    )
    assert {f.rule for f in findings} == {"FML602"}
    assert "promotes" in findings[0].message


def test_fml602_weak_constant_is_fine():
    def chain(x):
        return (x.astype(BF16) * 2.0) * 1.5  # python scalar: weak

    assert check_precision_fn(
        chain, jax.ShapeDtypeStruct((8, 4), np.float32),
        policy=MIXED_INFERENCE,
    ) == []


def test_fml604_narrow_collective_and_sanctioned_precast():
    def bad(g):
        return jax.lax.psum(g, "data")

    findings = check_precision_fn(
        bad, jax.ShapeDtypeStruct((8,), BF16), policy=MIXED,
        axis_env=[("data", 8)],
    )
    assert {f.rule for f in findings} == {"FML604"}

    def deliberate(g):
        # Explicit narrowing cast right before the collective declares
        # the bandwidth-for-precision trade — allowed.
        return jax.lax.psum(g.astype(BF16), "data")

    assert check_precision_fn(
        deliberate, jax.ShapeDtypeStruct((8,), np.float32), policy=MIXED,
        axis_env=[("data", 8)],
    ) == []


def test_fml605_plan_width_conflict():
    assert check_policy_plan(MIXED, dtype_bytes=2, plan_name="fsdp")[0] \
        .rule == "FML605"
    assert check_policy_plan(MIXED, dtype_bytes=4) == []
    assert check_policy_plan(MIXED, dtype_bytes=None) == []


def test_scan_carry_provenance_recurses():
    """A scan whose CARRY updates at bf16 is state math running narrow —
    the walker must tag carries through the scan body (FML601)."""
    def loop(x):
        def body(carry, t):
            return carry + t, ()

        out, _ = jax.lax.scan(
            body, x.astype(BF16), jnp.zeros((4,) + x.shape, BF16)
        )
        return out

    findings = check_precision_fn(
        loop, jax.ShapeDtypeStruct((8,), np.float32), policy=MIXED,
    )
    assert "FML601" in {f.rule for f in findings}


# ---------------------------------------------------------------------------
# Seeded fixtures + CLI
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,rule", [
    ("bad_precision_fml601_bf16_accum_sgd.policy.json", "FML601"),
    ("bad_precision_fml602_stray_constant.policy.json", "FML602"),
    ("bad_precision_fml603_bf16_master_weights.policy.json", "FML603"),
    ("bad_precision_fml604_bf16_psum.policy.json", "FML604"),
    ("bad_precision_fml605_plan_width_conflict.policy.json", "FML605"),
    ("bad_precision_fml606_int8_unscaled_accum.policy.json", "FML606"),
    ("bad_precision_fml607_int8_republished_full.policy.json", "FML607"),
])
def test_seeded_fixture_flagged(name, rule):
    findings = check_policy_file(os.path.join(FIXDIR, name))
    assert rule in {f.rule for f in findings}, [f.render() for f in findings]


def test_malformed_policy_file_fails_loudly(tmp_path):
    p = tmp_path / "broken.policy.json"
    p.write_text("{not json")
    findings = check_policy_file(str(p))
    assert findings and "unreadable or malformed" in findings[0].message
    p2 = tmp_path / "badprog.policy.json"
    p2.write_text(json.dumps({
        "policy": {"name": "mixed"}, "program": {"name": "nope"},
    }))
    assert "bad program" in check_policy_file(str(p2))[0].message
    # A program that constructs fine but fails at TRACE time (the loss
    # name is only checked inside the step) is still ONE finding — not a
    # traceback that aborts the CLI with later targets unchecked.
    p3 = tmp_path / "badloss.policy.json"
    p3.write_text(json.dumps({
        "policy": {"name": "mixed"},
        "program": {"name": "sgd_step", "loss": "bogus"},
    }))
    (f3,) = check_policy_file(str(p3))
    assert f3.rule == "FML601" and "bad program" in f3.message


def _run_cli(*args):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(
        [sys.executable, "-m", "flinkml_tpu.analysis", *args,
         "--no-selfcheck"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )


def test_cli_format_json_and_text():
    fixture = os.path.join(
        FIXDIR, "bad_precision_fml604_bf16_psum.policy.json"
    )
    out = _run_cli(fixture, "--format", "json")
    assert out.returncode == 1
    recs = json.loads(out.stdout)
    assert {"rule", "severity", "location", "message"} <= set(recs[0])
    assert {r["rule"] for r in recs} == {"FML604"}
    # Text stays the default.
    out_text = _run_cli(fixture)
    assert out_text.returncode == 1
    assert "FML604" in out_text.stdout
    with pytest.raises(json.JSONDecodeError):
        json.loads(out_text.stdout)


# ---------------------------------------------------------------------------
# Trainer gating (sharding/apply + the estimator surface)
# ---------------------------------------------------------------------------


def _train_data(n=192, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x @ rng.normal(size=dim) > 0).astype(np.float32) * 2 - 1
    return x, y


def test_train_linear_plan_refuses_bf16_accumulation():
    x, y = _train_data()
    mesh = DeviceMesh.for_plan(REPLICATED)
    with pytest.raises(PrecisionValidationError) as ei:
        train_linear_plan(x, y, None, REPLICATED, mesh, max_iter=1,
                          dtype="bfloat16", precision="mixed")
    assert "FML601" in {f.rule for f in ei.value.findings}


def test_train_linear_plan_refuses_policy_plan_width_conflict():
    x, y = _train_data()
    mesh = DeviceMesh.for_plan(REPLICATED)
    with pytest.raises(PrecisionValidationError) as ei:
        # f64 storage under params=float32: the plan's HBM math width
        # (8 B/elem) is not the policy's (4 B/elem).
        train_linear_plan(x, y, None, REPLICATED, mesh, max_iter=1,
                          dtype=np.float64, precision="mixed")
    assert "FML605" in {f.rule for f in ei.value.findings}


@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
def test_mixed_trainer_convergence_tolerance(optimizer):
    """The documented convergence-tolerance equivalence (precision.md):
    bf16-compute training lands within an explicit atol of its f32
    twin. Observed deviation ~3e-4; the bound is deliberately loose."""
    x, y = _train_data()
    mesh = DeviceMesh.for_plan(REPLICATED)
    kw = dict(loss="logistic", optimizer=optimizer, max_iter=20,
              learning_rate=0.3)
    golden = train_linear_plan(x, y, None, REPLICATED, mesh, **kw)
    mixed = train_linear_plan(x, y, None, REPLICATED, mesh,
                              precision="mixed", **kw)
    assert np.isfinite(mixed).all()
    np.testing.assert_allclose(mixed, golden, atol=2e-2)
    assert np.max(np.abs(mixed - golden)) > 0  # bf16 really ran


def test_mixed_trainer_fsdp_plan():
    x, y = _train_data()
    golden = train_linear_plan(
        x, y, None, REPLICATED, DeviceMesh.for_plan(REPLICATED),
        max_iter=15, learning_rate=0.3,
    )
    mixed = train_linear_plan(
        x, y, None, FSDP, DeviceMesh.for_plan(FSDP),
        max_iter=15, learning_rate=0.3, precision=MIXED,
    )
    np.testing.assert_allclose(mixed, golden, atol=2e-2)


def test_estimator_precision_knob():
    from flinkml_tpu.models.logistic_regression import LogisticRegression

    x, y = _train_data()
    t = Table({"features": x.astype(np.float64),
               "label": (y > 0).astype(np.float64)})

    def fit(**kw):
        est = LogisticRegression(**kw).set(
            LogisticRegression.FEATURES_COL, "features"
        ).set(LogisticRegression.LABEL_COL, "label").set_max_iter(10).set(
            LogisticRegression.GLOBAL_BATCH_SIZE, len(x)
        ).set(LogisticRegression.SEED, 7)
        model = est.fit(t)
        return np.asarray(model.get_model_data()[0].column("coefficient"))

    # FULL is the f32 twin at the SAME storage dtype (under x64 a
    # plan-only fit trains f64) — the A/B isolates the bf16 compute.
    base = fit(precision="full")
    mixed = fit(precision="mixed")  # no plan: rides REPLICATED
    assert np.isfinite(mixed).all()
    np.testing.assert_allclose(mixed, base, atol=2e-2)


def test_precision_unaware_estimator_refuses_at_construction():
    from flinkml_tpu.models.kmeans import KMeans

    with pytest.raises(ValueError, match="does not support precision"):
        KMeans(precision="mixed")


def test_precision_refused_on_sparse_and_host_paths():
    from flinkml_tpu.models._linear_sgd import train_linear_model_from_table
    from flinkml_tpu.models.logistic_regression import (
        train_logistic_regression,
    )
    from flinkml_tpu.linalg import SparseVector

    rows = [SparseVector(4, [0], [1.0]) for _ in range(4)]
    t = Table({"features": np.array(rows, dtype=object),
               "label": np.array([0.0, 1.0, 0.0, 1.0])})
    with pytest.raises(ValueError, match="dense path only"):
        train_linear_model_from_table(
            t, "features", "label", None, precision="mixed",
            loss="logistic", mesh=DeviceMesh(), max_iter=1,
            learning_rate=0.1, global_batch_size=4, reg=0.0,
            elastic_net=0.0, tol=0.0, seed=0,
        )
    x, y = _train_data(n=16, dim=4)
    with pytest.raises(ValueError, match="device"):
        train_logistic_regression(
            x, (y > 0).astype(np.float32), np.ones(16, np.float32),
            DeviceMesh(), 1, 0.1, 16, 0.0, 0.0, 0, mode="host",
            precision="mixed",
        )


# ---------------------------------------------------------------------------
# Fused executor gating
# ---------------------------------------------------------------------------


def _scaler_lr_pipeline(n=256, d=8, seed=3):
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import StandardScaler

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    t = Table({"features": x, "label": y})
    sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
                         .set(StandardScaler.OUTPUT_COL, "scaled").fit(t)
    (st,) = sc.transform(t)
    lr = LogisticRegression().set(
        LogisticRegression.FEATURES_COL, "scaled"
    ).set(LogisticRegression.LABEL_COL, "label").set_max_iter(2) \
     .set(LogisticRegression.SEED, 7).fit(st)
    return PipelineModel([sc, lr]), t


def _scaler_kmeans_pipeline(n=128, d=8, seed=4):
    from flinkml_tpu.models.kmeans import KMeans
    from flinkml_tpu.models.scalers import StandardScaler

    rng = np.random.default_rng(seed)
    t = Table({"features": rng.normal(size=(n, d))})
    sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
                         .set(StandardScaler.OUTPUT_COL, "scaled").fit(t)
    (st,) = sc.transform(t)
    km = KMeans().set(KMeans.K, 3).set(KMeans.FEATURES_COL, "scaled") \
                 .set(KMeans.SEED, 7).fit(st)
    return PipelineModel([sc, km]), t


def test_fused_chain_mixed_inference_equivalence():
    """Pinned-numerics equivalence (precision.md recipe): decisions
    exactly equal, probabilities within the documented bf16 atol."""
    pm, t = _scaler_lr_pipeline()
    (o32,) = pm.transform(t)
    p32 = np.asarray(o32.column("prediction"))
    r32 = np.asarray(o32.column("rawPrediction"))
    with pipeline_fusion.precision_scope("mixed_inference"):
        (obf,) = pm.transform(t)
        pbf = np.asarray(obf.column("prediction"))
        rbf = np.asarray(obf.column("rawPrediction"))
    assert rbf.dtype == BF16  # bf16 really ran end-to-end
    np.testing.assert_array_equal(p32, pbf)
    np.testing.assert_allclose(
        r32.astype(np.float64), rbf.astype(np.float64), atol=2e-2
    )


def test_fused_chain_strict_mixed_keeps_f32_accumulators():
    pm, t = _scaler_lr_pipeline()
    (o32,) = pm.transform(t)
    with pipeline_fusion.precision_scope(MIXED):
        (omx,) = pm.transform(t)
        raw = np.asarray(omx.column("rawPrediction"))
    # accum=float32: the sigmoid chain downstream of the f32-accumulated
    # matmul stays f32 — tighter than the all-bf16 path.
    assert raw.dtype == np.float32
    np.testing.assert_allclose(
        np.asarray(o32.column("rawPrediction")).astype(np.float64),
        raw.astype(np.float64), atol=3e-3,
    )
    np.testing.assert_array_equal(
        np.asarray(o32.column("prediction")),
        np.asarray(omx.column("prediction")),
    )


def test_fused_chain_bf16_accumulating_kernel_refused_under_mixed():
    """The KMeans distance kernel follows plain dtype propagation, so
    its bf16 dot accumulator is refused under the STRICT policy and
    admitted under mixed_inference — the gate, not the kernel, decides."""
    pm, t = _scaler_kmeans_pipeline()
    (o0,) = pm.transform(t)
    a0 = np.asarray(o0.column("prediction"))
    with pytest.raises(PrecisionValidationError) as ei:
        with pipeline_fusion.precision_scope(MIXED):
            pm.transform(t)[0].column("prediction")
    assert "FML601" in {f.rule for f in ei.value.findings}
    with pipeline_fusion.precision_scope(MIXED_INFERENCE):
        (o1,) = pm.transform(t)
        a1 = np.asarray(o1.column("prediction"))
    np.testing.assert_array_equal(a0, a1)


def test_refused_chain_caches_no_executable():
    pm, t = _scaler_kmeans_pipeline(seed=5)
    pipeline_fusion.reset_cache()
    with pytest.raises(PrecisionValidationError):
        with pipeline_fusion.precision_scope(MIXED):
            pm.transform(t)[0].column("prediction")
    assert pipeline_fusion.compiled_program_count() == 0


def test_bf16_and_f32_programs_never_alias():
    """The would-have-aliased regression: identical chain, identical
    specs, identical bucket — the ONLY difference is the active policy.
    Without the policy in the cache key the second transform would reuse
    the first executable and the A/B would be meaningless."""
    pm, t = _scaler_lr_pipeline(seed=6)
    pipeline_fusion.reset_cache()
    (a,) = pm.transform(t)
    np.asarray(a.column("rawPrediction"))
    n_after_f32 = pipeline_fusion.compiled_program_count()
    assert n_after_f32 >= 1
    with pipeline_fusion.precision_scope("mixed_inference"):
        (b,) = pm.transform(t)
        raw_bf = np.asarray(b.column("rawPrediction"))
    assert pipeline_fusion.compiled_program_count() > n_after_f32, \
        "policy-scoped transform aliased the f32 executable"
    assert raw_bf.dtype == BF16
    # And the f32 program is untouched by the scope having existed.
    (c,) = pm.transform(t)
    assert np.asarray(c.column("rawPrediction")).dtype != BF16


def test_lazy_column_traces_under_captured_policy():
    """A lazy column's deferred program must compile under the policy
    captured at TRANSFORM time, not the reader's ambient policy: kernels
    resolve active_policy() at trace time, and the trace happens at
    first read — possibly after the scope exited (direction A) or
    inside someone else's scope (direction B, which would cache a
    never-validated bf16 program under the policy=None key)."""
    from flinkml_tpu.models.scalers import StandardScaler

    pm, t = _scaler_lr_pipeline(seed=8)
    sc2 = StandardScaler().set(StandardScaler.INPUT_COL, "rawPrediction") \
                          .set(StandardScaler.OUTPUT_COL, "rawScaled") \
                          .fit(pm.transform(t)[0])
    pm3 = PipelineModel([*pm.stages, sc2])  # rawPrediction is now lazy

    with pipeline_fusion.precision_scope("mixed_inference"):
        (o_mix,) = pm3.transform(t)
    raw_mix = np.asarray(o_mix.column("rawPrediction"))  # read post-scope
    assert raw_mix.dtype == BF16, \
        "lazy column traced under the reader's ambient policy, not the " \
        "captured one"

    pipeline_fusion.reset_cache()
    (o_plain,) = pm3.transform(t)  # no policy captured
    with pipeline_fusion.precision_scope("mixed_inference"):
        raw_plain = np.asarray(o_plain.column("rawPrediction"))
    assert raw_plain.dtype != BF16
    # The policy=None key holds the full-width executable: a later plain
    # reader gets bit-identical values, not a smuggled bf16 program.
    (o_again,) = pm3.transform(t)
    np.testing.assert_array_equal(
        raw_plain, np.asarray(o_again.column("rawPrediction"))
    )


def test_plan_step_cache_is_policy_keyed():
    """Trainer-side non-aliasing: the jitted plan-step LRU keys on the
    policy, so the bf16 and f32 steps are distinct executables while
    same-policy lookups still hit."""
    from flinkml_tpu.sharding.apply import _inner_mesh, _plan_linear_step

    mesh = _inner_mesh(DeviceMesh.for_plan(REPLICATED))
    args = (mesh, REPLICATED, "logistic", "sgd", 8, "float32",
            0.1, 0.9, 0.0, 0.0)
    f32_step = _plan_linear_step(*args, None)
    mixed_step = _plan_linear_step(*args, MIXED)
    assert f32_step is not mixed_step
    assert _plan_linear_step(*args, None) is f32_step
    assert _plan_linear_step(*args, MIXED) is mixed_step


def test_precision_scope_nests_and_restores():
    assert pipeline_fusion.active_policy() is None
    with pipeline_fusion.precision_scope("mixed"):
        assert pipeline_fusion.active_policy() is MIXED
        with pipeline_fusion.precision_scope(None):
            assert pipeline_fusion.active_policy() is None
        assert pipeline_fusion.active_policy() is MIXED
    assert pipeline_fusion.active_policy() is None


def test_precision_scope_is_thread_local():
    """A serving dispatcher scoping ITS thread must not clobber a
    concurrently transforming trainer thread's policy (and vice versa)."""
    import threading

    seen = {}

    def other_thread():
        seen["initial"] = pipeline_fusion.active_policy()
        with pipeline_fusion.precision_scope("mixed_inference"):
            seen["scoped"] = pipeline_fusion.active_policy()
            barrier.wait()   # main thread reads while we hold our scope
            barrier.wait()
        seen["after"] = pipeline_fusion.active_policy()

    barrier = threading.Barrier(2)
    with pipeline_fusion.precision_scope(MIXED):
        worker = threading.Thread(target=other_thread)
        worker.start()
        barrier.wait()
        main_during = pipeline_fusion.active_policy()
        barrier.wait()
        worker.join()
    assert seen["initial"] is None      # main's scope never leaked over
    assert seen["scoped"] is MIXED_INFERENCE
    assert seen["after"] is None
    assert main_during is MIXED         # worker's scope never leaked back


# ---------------------------------------------------------------------------
# Serving gating
# ---------------------------------------------------------------------------


def _serving_cfg(**kw):
    return ServingConfig(max_batch_rows=64, max_wait_ms=1.0,
                         warmup_row_counts=(8,), **kw)


def test_serving_engine_policy_equivalence():
    pm, t = _scaler_lr_pipeline()
    example = Table({"features": np.asarray(t.column("features"))[:8]})
    req = Table({"features": np.asarray(t.column("features"))[:32]})
    e32 = ServingEngine(pm, example, _serving_cfg(), name="f32p").start()
    try:
        r32 = e32.predict(req)
    finally:
        e32.stop()
    ebf = ServingEngine(
        pm, example, _serving_cfg(precision="mixed_inference"),
        name="bf16p",
    ).start()
    try:
        rbf = ebf.predict(req)
    finally:
        ebf.stop()
    np.testing.assert_array_equal(
        r32.column("prediction"), rbf.column("prediction")
    )
    assert rbf.column("rawPrediction").dtype == BF16
    np.testing.assert_allclose(
        r32.column("rawPrediction").astype(np.float64),
        rbf.column("rawPrediction").astype(np.float64), atol=2e-2,
    )


def test_serving_load_refused_under_strict_policy():
    pm, t = _scaler_kmeans_pipeline(seed=7)
    example = Table({"features": np.asarray(t.column("features"))[:8]})
    with pytest.raises(PrecisionValidationError):
        ServingEngine(
            pm, example, _serving_cfg(precision=MIXED), name="strict",
        ).start()


def test_serving_refused_swap_keeps_old_model(tmp_path):
    """The refuse-at-LOAD contract: a policy-violating publish fails the
    swap with the typed error and the previous model keeps serving —
    the same shape as refuse_nonfinite."""
    good, t = _scaler_lr_pipeline(seed=8)
    bad, _ = _scaler_kmeans_pipeline(seed=8)
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(good)
    example = Table({"features": np.asarray(t.column("features"))[:8]})
    engine = ServingEngine(
        reg, example, _serving_cfg(precision=MIXED), name="swapper",
    ).start()
    try:
        assert engine.active_version == v1
        v2 = reg.publish(bad)
        with pytest.raises(PrecisionValidationError):
            engine.swap_to(v2)
        assert engine.active_version == v1
        resp = engine.predict(
            Table({"features": np.asarray(t.column("features"))[:16]})
        )
        assert resp.version == v1
    finally:
        engine.stop()


def test_replica_pool_inherits_policy():
    from flinkml_tpu.serving.pool import ReplicaPool

    pm, t = _scaler_lr_pipeline(seed=9)
    example = Table({"features": np.asarray(t.column("features"))[:8]})
    req = Table({"features": np.asarray(t.column("features"))[:16]})
    (o32,) = pm.transform(t)
    pool = ReplicaPool(
        pm, example, config=_serving_cfg(precision="mixed_inference"),
        n_replicas=2, name="bfpool",
    ).start()
    try:
        for r in pool.replicas:
            assert r.engine._policy is MIXED_INFERENCE
        resp = pool.predict(req)
        np.testing.assert_array_equal(
            resp.column("prediction"),
            np.asarray(o32.column("prediction"))[:16],
        )
        assert resp.column("rawPrediction").dtype == BF16
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# FML106 through the shared dtype-flow walk
# ---------------------------------------------------------------------------


def _promoting_kernel(in_col="a", out_col="b"):
    strong64 = np.float64(2.0)

    def fn(cols, consts, valid):
        return {out_col: cols[in_col] * strong64}

    return ColumnKernel(
        input_cols=(in_col,), output_cols=(out_col,), fn=fn, constants={},
        fingerprint=("PromoTest", in_col, out_col),
    )


def test_promotion_findings_localize_widening_site():
    k = _promoting_kernel()
    with jax.experimental.enable_x64(True):
        closed = jax.make_jaxpr(k.fn)(
            {"a": jax.ShapeDtypeStruct((8,), np.float32)}, {},
            jax.ShapeDtypeStruct((8,), np.float32),
        )
    findings = promotion_findings(
        closed, [np.dtype(np.float32)], {"b": np.dtype(np.float64)},
        stage="PromoTest",
    )
    assert [f.rule for f in findings] == ["FML106"]
    assert "widened at" in findings[0].message


def test_promotion_skips_wide_or_nonfloat_inputs():
    assert promotion_findings(
        None, [np.dtype(np.float64)], {"b": np.dtype(np.float64)}
    ) == []
    assert promotion_findings(
        None, [np.dtype(np.float32), np.dtype(np.int64)],
        {"b": np.dtype(np.float64)},
    ) == []
    assert promotion_findings(None, [], {"b": np.dtype(np.float64)}) == []


def test_validator_fml106_single_report_for_fused_chain():
    """Per-stage and fused-chain checks share one dtype-flow path and
    column-dedupe into ONE finding, with the widening site localized."""
    from flinkml_tpu.analysis import analyze_pipeline
    from flinkml_tpu.api import AlgoOperator

    class PromoStage(AlgoOperator):
        def __init__(self, in_col, out_col):
            super().__init__()
            self._k = _promoting_kernel(in_col, out_col)

        def transform(self, *tables):
            raise NotImplementedError

        def transform_kernel(self):
            return self._k

    from flinkml_tpu.analysis.validator import ColumnSpec

    schema = {"a": ColumnSpec(np.dtype(np.float32), ())}
    report = analyze_pipeline(
        [PromoStage("a", "b"), PromoStage("b", "c")], schema
    )
    fml106 = [f for f in report if f.rule == "FML106"]
    # b and c each flagged exactly once across both code paths.
    assert sorted(f.column for f in fml106) == ["b", "c"]
    assert all("widened at" in f.message for f in fml106)


# ---------------------------------------------------------------------------
# The int8 post-training-quantized tier (ISSUE 15)
# ---------------------------------------------------------------------------


@pytest.fixture()
def quantize_small_consts(monkeypatch):
    """Pin the int8 tier's size threshold BELOW this file's d=32 model
    constants: the committed cpu/cpu/8 table value is 256 (on a CPU
    mesh quantizing tiny vectors measured pure overhead — no HBM to
    save), which would make these quality/mechanism tests vacuous. The
    env gate is the sanctioned explicit override."""
    monkeypatch.setenv("FLINKML_TPU_INT8_MIN_CONST", "16")


def _wide_scaler_lr_pipeline(n=400, d=32, seed=11):
    """d >= the pinned quantization threshold so every model constant
    (scaler mean/scale vectors, the LR coefficient) actually
    quantizes."""
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import StandardScaler

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    t = Table({"features": x, "label": y})
    sc = StandardScaler().set(StandardScaler.INPUT_COL, "features") \
                         .set(StandardScaler.OUTPUT_COL, "scaled").fit(t)
    (st,) = sc.transform(t)
    lr = LogisticRegression().set(
        LogisticRegression.FEATURES_COL, "scaled"
    ).set(LogisticRegression.LABEL_COL, "label").set_max_iter(3) \
     .set(LogisticRegression.SEED, 7).fit(st)
    return PipelineModel([sc, lr]), t


def test_int8_policy_value_and_roundtrip():
    assert INT8_INFERENCE.quant == "int8"
    assert not INT8_INFERENCE.mixed  # compute == params == float32
    assert resolve_policy("int8_inference") is INT8_INFERENCE
    rt = PrecisionPolicy.from_json_dict(INT8_INFERENCE.to_json_dict())
    assert rt == INT8_INFERENCE
    # quant is hashable key material: the tier can never alias FULL.
    assert hash(INT8_INFERENCE) != hash(FULL)
    assert "quant" not in FULL.to_json_dict()  # legacy files unchanged
    with pytest.raises(ValueError, match="unknown quantization"):
        PrecisionPolicy(quant="int4")


def test_quantize_absmax_per_column_properties():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(24, 6)) * np.array([1, 10, 0.1, 5, 1, 1])
    q, s = quantize_absmax(w)
    assert q.dtype == np.int8 and s.dtype == np.float32
    assert s.shape == (6,)  # per LAST-axis column
    assert np.abs(q).max() <= 127
    # Error bound: half an LSB of each column's scale.
    err = np.abs(dequantize_absmax(q, s, np.float64) - w)
    assert np.all(err <= s.astype(np.float64) * 0.5 + 1e-12)
    # 1-D vectors get one per-tensor scale; zeros stay exact.
    v = np.array([0.5, -2.0, 0.0, 1.0])
    qv, sv = quantize_absmax(v)
    assert np.ndim(sv) == 0
    assert qv[2] == 0
    qz, sz = quantize_absmax(np.zeros((8, 3)))
    assert np.all(qz == 0) and np.all(sz == 1.0)


def test_int8_fused_chain_quality_tolerance_pinned(quantize_small_consts):
    """The tier's quality contract: quantization is ACTIVE (outputs
    differ from f32) yet decisions are identical and probabilities sit
    within the pinned tolerance — the absmax scheme's documented error
    envelope for this chain."""
    pm, t = _wide_scaler_lr_pipeline()
    (o32,) = pm.transform(t)
    p32 = np.asarray(o32.column("prediction"))
    r32 = np.asarray(o32.column("rawPrediction"))
    with pipeline_fusion.precision_scope("int8_inference"):
        (oq,) = pm.transform(t)
        pq = np.asarray(oq.column("prediction"))
        rq = np.asarray(oq.column("rawPrediction"))
    dev = float(np.max(np.abs(rq.astype(np.float64) - r32.astype(np.float64))))
    assert dev > 0.0, "int8 tier quantized nothing (vacuous test)"
    assert dev < 5e-3, f"int8 deviation {dev} outside the pinned tolerance"
    # Only points within dev of the decision boundary may flip.
    assert float(np.mean(p32 == pq)) >= 0.99
    # Outputs run at the tier's declared compute width (f32 — the
    # boundary casts f64 activations down, like the mixed tiers), never
    # anything narrower: dequant-fused compute, not integer math.
    assert rq.dtype == np.dtype(INT8_INFERENCE.compute)


def test_int8_program_never_aliases_f32_program(quantize_small_consts):
    pm, t = _wide_scaler_lr_pipeline(seed=12)
    pipeline_fusion.reset_cache()
    (o32,) = pm.transform(t)
    np.asarray(o32.column("prediction"))
    n_f32 = pipeline_fusion.compiled_program_count()
    with pipeline_fusion.precision_scope(INT8_INFERENCE):
        (oq,) = pm.transform(t)
        np.asarray(oq.column("prediction"))
    assert pipeline_fusion.compiled_program_count() > n_f32
    # And the f32 program still serves f32 traffic bitwise-unchanged.
    (o32b,) = pm.transform(t)
    np.testing.assert_array_equal(
        np.asarray(o32.column("rawPrediction")),
        np.asarray(o32b.column("rawPrediction")),
    )


def test_fml606_unscaled_int8_accumulation_flagged():
    def unscaled(q, x):
        return jnp.dot(x, q)  # int8 @ int8 -> int8: wraps at ±127

    q = jax.ShapeDtypeStruct((8, 8), np.int8)
    x = jax.ShapeDtypeStruct((4, 8), np.int8)
    findings = check_precision_fn(
        unscaled, q, x, policy=INT8_INFERENCE, param_argnums=(0,),
    )
    assert "FML606" in {f.rule for f in findings}

    def dequant_first(q, scale, x):
        w = q.astype(jnp.float32) * scale  # the sanctioned shape
        return jnp.dot(x, w)

    clean = check_precision_fn(
        dequant_first, q, jax.ShapeDtypeStruct((8,), np.float32),
        jax.ShapeDtypeStruct((4, 8), np.float32),
        policy=INT8_INFERENCE, param_argnums=(0, 1),
    )
    assert "FML606" not in {f.rule for f in clean}


def test_fml607_int8_params_under_full_width_policy_flagged():
    def ident(state):
        return state

    state = {"coef_q": jax.ShapeDtypeStruct((16, 16), np.int8)}
    findings = check_precision_fn(
        ident, state, policy=FULL, param_argnums=(0,),
    )
    assert "FML607" in {f.rule for f in findings}
    # Sanctioned under the quantized tier itself.
    clean = check_precision_fn(
        ident, state, policy=INT8_INFERENCE, param_argnums=(0,),
    )
    assert "FML607" not in {f.rule for f in clean}
    # Ordinary integer metadata constants (int32/int64 sizes) are NOT
    # the quantized-params shape.
    meta = {"n_categories": jax.ShapeDtypeStruct((16,), np.int64)}
    clean = check_precision_fn(
        ident, meta, policy=FULL, param_argnums=(0,),
    )
    assert clean == []


def test_serving_engine_int8_tier_end_to_end(quantize_small_consts):
    """ServingConfig(precision='int8_inference'): the engine serves the
    quantized tier within the pinned tolerance of an f32 engine, through
    the same load/warmup/FML6xx gate path as every other policy."""
    pm, t = _wide_scaler_lr_pipeline(seed=13)
    x = np.asarray(t.column("features"))
    example = Table({"features": x[:4]})
    e32 = ServingEngine(
        pm, example, ServingConfig(max_batch_rows=64, max_wait_ms=1.0),
        output_cols=("prediction", "rawPrediction"), name="p_f32",
    ).start()
    eq8 = ServingEngine(
        pm, example,
        ServingConfig(max_batch_rows=64, max_wait_ms=1.0,
                      precision="int8_inference"),
        output_cols=("prediction", "rawPrediction"), name="p_int8",
    ).start()
    try:
        r32 = e32.predict({"features": x[:32]})
        rq8 = eq8.predict({"features": x[:32]})
        np.testing.assert_array_equal(
            r32.column("prediction"), rq8.column("prediction")
        )
        dev = np.max(np.abs(
            r32.column("rawPrediction").astype(np.float64)
            - rq8.column("rawPrediction").astype(np.float64)
        ))
        assert 0.0 < dev < 5e-3, dev
    finally:
        e32.stop()
        eq8.stop()


def test_int8_tier_refuses_explicit_pallas_backend():
    """An EXPLICIT pallas request composed with the int8 tier refuses
    loudly (the gate contract) — the Pallas chain body has no dequant
    path; a table-chosen backend would warn-and-fall-back instead."""
    from flinkml_tpu.kernels._gate import KernelUnsupportedError

    pm, t = _wide_scaler_lr_pipeline(seed=14)
    old = os.environ.get("FLINKML_TPU_KERNELS")
    os.environ["FLINKML_TPU_KERNELS"] = "pallas"
    try:
        with pipeline_fusion.precision_scope("int8_inference"):
            with pytest.raises(KernelUnsupportedError, match="quantized"):
                (out,) = pm.transform(t)
                np.asarray(out.column("prediction"))
    finally:
        if old is None:
            os.environ.pop("FLINKML_TPU_KERNELS", None)
        else:
            os.environ["FLINKML_TPU_KERNELS"] = old
