"""Sorted-by-design sparse hot loops (ISSUE 16): the multi-block
segment-sum grid above the retired one-block input ceiling, the CSR
SpMV chain kernel (parity contract: the JITTED XLA twin), the
SortedSparseColumn pack/prefetch format with zero retraces across
buckets, the sorted-column stream fit's bitwise parity with the CSR
stream, and the FML404 sorted-scatter provenance gate."""

import importlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flinkml_tpu import kernels
from flinkml_tpu.kernels import ENV_VAR, KernelUnsupportedError
from flinkml_tpu.kernels import segsum as _segsum

# The package re-exports the spmv DISPATCHER under the submodule's
# name; import the module itself for ROW_TILE / MAX_COMPILED_DIM.
_spmv = importlib.import_module("flinkml_tpu.kernels.spmv")
from flinkml_tpu.linalg import SparseVector
from flinkml_tpu.table import SortedSparseColumn, Table


def _sparse_table(rng, rows, dim, nnz, weight=True):
    vecs = np.empty(rows, object)
    for i in range(rows):
        idx = np.sort(rng.choice(dim, size=nnz, replace=False))
        vecs[i] = SparseVector(
            dim, idx, rng.normal(size=nnz).astype(np.float32)
        )
    cols = {"features": vecs,
            "y": (rng.random(rows) > 0.5).astype(np.float32)}
    if weight:
        cols["w"] = rng.uniform(0.5, 1.5, rows).astype(np.float32)
    return Table(cols)


# -- multi-block segment-sum -------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("sorted_", [False, True])
def test_segsum_multiblock_above_old_input_ceiling(dtype, sorted_):
    """cells just ABOVE the retired one-block input ceiling
    (MAX_COMPILED_CELLS used to refuse this shape outright): the grid
    streams ceil(cells / BLOCK_CELLS) blocks and stays bitwise with
    ``jax.ops.segment_sum`` — the carry between blocks adds in the same
    left-to-right element order XLA's CPU scatter uses."""
    rng = np.random.default_rng(0)
    cells = _segsum.MAX_COMPILED_CELLS + 1000
    nseg = 1 << 10
    ids = rng.integers(0, nseg, cells)
    if sorted_:
        ids = np.sort(ids)
    ids = jnp.asarray(ids, jnp.int32)
    vals = jnp.asarray(rng.normal(size=cells)).astype(dtype)
    ref = jax.ops.segment_sum(vals, ids, num_segments=nseg,
                              indices_are_sorted=sorted_)
    out = kernels.segment_sum(vals, ids, nseg, indices_are_sorted=sorted_,
                              backend="pallas")
    assert out.dtype == ref.dtype
    assert np.asarray(ref).tobytes() == np.asarray(out).tobytes()


def test_segsum_multiblock_just_below_old_ceiling_row_payload():
    """The [cells, k] embedding-exchange shape with cells*k straddling
    the old ceiling: one flat-size below, one above — both bitwise (the
    ceiling no longer depends on the INPUT size at all)."""
    rng = np.random.default_rng(1)
    k, nseg = 8, 512
    for cells in (_segsum.MAX_COMPILED_CELLS // k - 16,
                  _segsum.MAX_COMPILED_CELLS // k + 16):
        ids = jnp.asarray(rng.integers(0, nseg, cells), jnp.int32)
        rows = jnp.asarray(rng.normal(size=(cells, k)).astype(np.float32))
        ref = jax.ops.segment_sum(rows, ids, num_segments=nseg)
        out = kernels.segment_sum(rows, ids, nseg, backend="pallas")
        assert np.asarray(ref).tobytes() == np.asarray(out).tobytes()


def test_segsum_multiblock_ragged_tail_parity():
    """cells one past a block boundary — the final grid step is almost
    entirely zero-padding; padding cells must be exact no-op adds."""
    rng = np.random.default_rng(2)
    cells = _segsum.BLOCK_CELLS + 1
    ids = jnp.asarray(np.sort(rng.integers(0, 100, cells)), jnp.int32)
    vals = jnp.asarray(rng.normal(size=cells).astype(np.float32))
    ref = jax.ops.segment_sum(vals, ids, num_segments=100,
                              indices_are_sorted=True)
    out = kernels.segment_sum(vals, ids, 100, indices_are_sorted=True,
                              backend="pallas")
    assert np.asarray(ref).tobytes() == np.asarray(out).tobytes()


def test_segsum_output_ceiling_refusal_names_constant(monkeypatch):
    """The ONLY remaining compiled-path ceiling is the OUTPUT block
    (num_segments * k): an explicit pallas request above it refuses
    typed, naming MAX_COMPILED_CELLS — through the dispatcher AND the
    direct kernel entry point."""
    monkeypatch.setenv(kernels.ENV_INTERPRET_VAR, "0")
    vals = jnp.ones(8, jnp.float32)
    ids = jnp.zeros(8, jnp.int32)
    over = _segsum.MAX_COMPILED_CELLS + 1
    with pytest.raises(KernelUnsupportedError, match="MAX_COMPILED_CELLS"):
        kernels.segment_sum(vals, ids, over, backend="pallas")
    with pytest.raises(KernelUnsupportedError, match="MAX_COMPILED_CELLS"):
        _segsum.pallas_segment_sum(vals, ids, over, interpret=False)
    # ... while the interpreter (no VMEM) accepts any num_segments.
    assert _segsum.unsupported_reason(vals, ids, over, interpret=True) is None


def test_segsum_exchange_shape_above_old_ceiling_accepted_compiled():
    """The embedding-exchange scatter at production shard sizes: an
    input block far above the old input ceiling with a modest output
    block is now COMPILED-path eligible (unsupported_reason is None) —
    checked abstractly via ShapeDtypeStruct, no 128 MB allocation."""
    cells, k, shard_rows = 1 << 21, 16, 1 << 14   # cells*k = 8x old cap
    vals = jax.ShapeDtypeStruct((cells, k), jnp.float32)
    ids = jax.ShapeDtypeStruct((cells,), jnp.int32)
    assert cells * k > _segsum.MAX_COMPILED_CELLS
    assert _segsum.unsupported_reason(
        vals, ids, shard_rows, interpret=False) is None
    # the output ceiling still applies to the same shape:
    assert "MAX_COMPILED_CELLS" in _segsum.unsupported_reason(
        vals, ids, (_segsum.MAX_COMPILED_CELLS // k) + 1, interpret=False)


# -- CSR SpMV ---------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "float64", "bfloat16"])
def test_spmv_parity_vs_jitted_twin(dtype):
    """Bitwise vs the JITTED XLA reference (the parity contract — an
    eager reference can differ in the last f32 bit because XLA's
    unfused reduce uses a different association tree), including a row
    count that is not a multiple of ROW_TILE."""
    rng = np.random.default_rng(3)
    rows, width, dim = _spmv.ROW_TILE * 4 + 3, 16, 512
    ib = jnp.asarray(rng.integers(0, dim, (rows, width)), jnp.int32)
    vb = jnp.asarray(rng.normal(size=(rows, width))).astype(dtype)
    w = jnp.asarray(rng.normal(size=dim)).astype(dtype)
    twin = jax.jit(
        lambda i, v, ww: jnp.sum(v * jnp.take(ww, i, axis=0), axis=1)
    )
    ref = twin(ib, vb, w)
    out = kernels.spmv(ib, vb, w, backend="pallas")
    assert out.dtype == ref.dtype
    assert np.asarray(ref).tobytes() == np.asarray(out).tobytes()


def test_spmv_refusals(monkeypatch):
    ib = jnp.zeros((4, 2), jnp.int32)
    vb = jnp.ones((4, 2), jnp.float32)
    with pytest.raises(KernelUnsupportedError, match="not floating"):
        kernels.spmv(ib, jnp.ones((4, 2), jnp.int32),
                     jnp.ones(8, jnp.int32), backend="pallas")
    with pytest.raises(KernelUnsupportedError, match="!= w dtype"):
        kernels.spmv(ib, vb, jnp.ones(8, jnp.float64), backend="pallas")
    # the one-block weight ceiling holds on the compiled path only,
    # named after its constant (checked abstractly — no 32 MB alloc).
    big_w = jax.ShapeDtypeStruct((_spmv.MAX_COMPILED_DIM + 1,), jnp.float32)
    reason = _spmv.unsupported_reason(ib, vb, big_w, interpret=False)
    assert reason is not None and "MAX_COMPILED_DIM" in reason
    assert _spmv.unsupported_reason(ib, vb, big_w, interpret=True) is None


def test_spmv_gate_threaded_vs_explicit(tmp_path, monkeypatch):
    """The lru-key idiom for the 4th site: a TABLE-chosen pallas
    threaded through ``backend=`` keeps warn-and-fallback on
    unsupported operands; a backend DISAGREEING with the gate is an
    explicit request and refuses loudly."""
    from flinkml_tpu.autotune import TuningTable, mesh_key
    from flinkml_tpu.autotune.table import ENV_TABLE_VAR

    table = TuningTable()
    table.set_knob(mesh_key(), "kernel_backend_spmv", "pallas",
                   candidates={"xla": 1.0, "pallas": 2.0}, source="test")
    path = str(tmp_path / "table.json")
    table.save(path)
    monkeypatch.setenv(ENV_TABLE_VAR, path)
    monkeypatch.setenv(kernels.ENV_INTERPRET_VAR, "0")  # f64 unsupported
    rng = np.random.default_rng(4)
    ib = jnp.asarray(rng.integers(0, 32, (4, 3)), jnp.int32)
    vb = jnp.asarray(rng.normal(size=(4, 3)))            # float64
    w = jnp.asarray(rng.normal(size=32))
    assert vb.dtype == jnp.float64
    threaded = kernels.spmv_backend()
    assert threaded == "pallas"
    ref = jax.jit(
        lambda i, v, ww: jnp.sum(v * jnp.take(ww, i, axis=0), axis=1)
    )(ib, vb, w)
    out = kernels.spmv(ib, vb, w, backend=threaded)      # degrades
    assert np.asarray(ref).tobytes() == np.asarray(out).tobytes()
    monkeypatch.setenv(ENV_VAR, "spmv=xla")              # gate says xla
    with pytest.raises(KernelUnsupportedError):
        kernels.spmv(ib, vb, w, backend="pallas")        # arg disagrees


def test_spmv_in_gate_sites_and_factory():
    assert "spmv" in kernels.SITES
    assert kernels.spmv_backend() == "xla"   # opt-in by measurement


# -- SortedSparseColumn pack + prefetch --------------------------------------


def test_pad_place_table_emits_sorted_columns_round_trip():
    """The prefetcher's pack step: all-SparseVector object columns
    become SortedSparseColumns — power-of-two bucket/width, recorded
    ``indices_are_sorted``, pack-time sort tables covering the FULL
    padded block, and a to_host() that reconstructs the vectors."""
    from flinkml_tpu.data.prefetch import pad_place_table

    rng = np.random.default_rng(5)
    t = _sparse_table(rng, rows=11, dim=256, nnz=6)
    dev = pad_place_table(t)
    col = dev._raw_column("features")
    assert isinstance(col, SortedSparseColumn)
    assert col.indices_are_sorted is True
    assert col.dim == 256 and col.rows == 11
    bucket, width = col.buf.shape
    assert bucket & (bucket - 1) == 0 and width & (width - 1) == 0
    assert col.indptr.shape == (bucket + 1,)
    assert col.perm.shape == col.segment_ids.shape == (bucket * width,)
    # the sort tables really are sorted — the scatter's entitlement.
    seg = np.asarray(col.segment_ids)
    assert np.all(np.diff(seg) >= 0)
    # round trip: host view reconstructs every vector exactly.
    for vec, orig in zip(dev.column("features"), t.column("features")):
        np.testing.assert_array_equal(vec.indices, orig.indices)
        np.testing.assert_array_equal(vec.values, orig.values)
    # dense siblings keep the plain padded contract.
    assert dev._raw_column("y").rows == 11


@pytest.mark.no_retrace(allow_compiles=3)
def test_prefetcher_sorted_columns_zero_retraces_across_buckets():
    """ISSUE 16 acceptance: the prefetch feed emits SortedSparseColumns
    across three row buckets and the sorted-column step compiles once
    per bucket and NEVER again — batch-size jitter inside a bucket is
    neutralized by the traced n_valid mask, and the pack-time tables
    are bucket-shaped, not batch-shaped. The budget of 3 is exactly the
    per-bucket warmup (8, 16, 32); the guarded replay must add zero."""
    from flinkml_tpu.data.prefetch import DevicePrefetcher
    from flinkml_tpu.models._linear_sgd import _sorted_column_stepper

    rng = np.random.default_rng(6)
    dim, nnz = 128, 4
    # rows hitting buckets 8, 16, 32; two row counts per bucket.
    tables = [_sparse_table(rng, rows, dim, nnz)
              for rows in (5, 8, 12, 16, 20, 31)]
    step = _sorted_column_stepper("logistic", dim)
    hy = (jnp.float32(0.5), jnp.float32(1e-4), jnp.float32(0.0))
    coef = jnp.zeros(dim, jnp.float32)

    def drive(coef):
        batches = list(DevicePrefetcher(iter(tables), depth=2))
        assert len(batches) == 6
        for t in batches:
            col = t._raw_column("features")
            assert isinstance(col, SortedSparseColumn)
            coef, _, _ = step(
                coef, col.indices, col.buf, col.perm, col.segment_ids,
                t._raw_column("y").buf, t._raw_column("w").buf,
                jnp.asarray(col.rows, jnp.int32), *hy,
            )
        return coef.block_until_ready()

    coef = drive(coef)       # warmup: one compile per bucket (3 total)
    drive(coef)              # guarded replay: zero new compiles


def test_sorted_stream_fit_bitwise_matches_csr_stream():
    """End-to-end acceptance: the sorted-column stream (device Tables
    from pad_place_table, zero densify / zero step-time sort) produces
    the BIT-IDENTICAL model to the CSR stream reference over a
    multi-epoch weighted elastic-net logistic fit."""
    from flinkml_tpu.data.prefetch import pad_place_table
    from flinkml_tpu.models._linear_sgd import (
        streamed_linear_fit,
        train_linear_model_sorted_stream,
    )
    from flinkml_tpu.parallel import DeviceMesh

    rng = np.random.default_rng(7)
    dim, nnz = 512, 8
    tabs = [_sparse_table(rng, rows, dim, nnz) for rows in (24, 48, 33)]
    hyper = dict(loss="logistic", max_iter=4, learning_rate=0.5, reg=1e-3,
                 elastic_net=0.3, tol=0.0)
    # The contract is at the pipeline's f32 dtype on a single-device
    # reference mesh: the conftest's global x64 flag and 8-device psum
    # order would each perturb the CSR reference in the last bit.
    mesh1 = DeviceMesh(devices=jax.devices()[:1])
    with jax.experimental.disable_x64():
        ref = streamed_linear_fit(
            list(tabs), features_col="features", label_col="y",
            weight_col="w", mesh=mesh1, **hyper,
        )
        dev = [pad_place_table(t) for t in tabs]
        got = train_linear_model_sorted_stream(dev, "features", "y", "w",
                                               **hyper)
        assert np.asarray(ref, np.float32).tobytes() == \
            np.asarray(got, np.float32).tobytes()
        # routing: streamed_linear_fit recognizes the device tables too.
        routed = streamed_linear_fit(
            [t for t in dev], features_col="features", label_col="y",
            weight_col="w", mesh=mesh1, **hyper,
        )
        assert np.asarray(routed, np.float32).tobytes() == \
            np.asarray(got, np.float32).tobytes()


def test_sorted_stream_refuses_checkpointing():
    from flinkml_tpu.models._linear_sgd import (
        train_linear_model_sorted_stream,
    )

    with pytest.raises(ValueError, match="checkpoint"):
        train_linear_model_sorted_stream(
            [], "features", "y", loss="logistic", max_iter=1,
            learning_rate=0.1, reg=0.0, elastic_net=0.0, tol=0.0,
            checkpoint_interval=2,
        )


# -- FML404: sorted-scatter provenance ---------------------------------------


def test_fml404_fires_on_unsorted_flag_over_sorted_input():
    from flinkml_tpu.analysis import check_sorted_scatter_fn

    def bad(v, i):
        return jax.ops.segment_sum(v, i, num_segments=16,
                                   indices_are_sorted=False)

    args = (jnp.zeros(64, jnp.float32), jnp.zeros(64, jnp.int32))
    findings = check_sorted_scatter_fn(bad, args, sorted_argnums=(1,))
    assert [f.rule for f in findings] == ["FML404"]
    assert "sorted" in findings[0].message


def test_fml404_clean_when_flag_asserted_or_no_provenance():
    from flinkml_tpu.analysis import check_sorted_scatter_fn

    def good(v, i):
        return jax.ops.segment_sum(v, i, num_segments=16,
                                   indices_are_sorted=True)

    def bad(v, i):
        return jax.ops.segment_sum(v, i, num_segments=16,
                                   indices_are_sorted=False)

    args = (jnp.zeros(64, jnp.float32), jnp.zeros(64, jnp.int32))
    assert check_sorted_scatter_fn(good, args, sorted_argnums=(1,)) == []
    # unsorted flag over ids WITHOUT provenance is legitimate.
    assert check_sorted_scatter_fn(bad, args, sorted_argnums=()) == []


def test_fml404_walks_through_pjit():
    """The trainers wrap their scatters in jit — the walk must recurse
    one call level or every real consumer would be false-clean."""
    from flinkml_tpu.analysis import check_sorted_scatter_fn

    @jax.jit
    def bad(v, i):
        return jax.ops.segment_sum(v, i, num_segments=16,
                                   indices_are_sorted=False)

    args = (jnp.zeros(64, jnp.float32), jnp.zeros(64, jnp.int32))
    findings = check_sorted_scatter_fn(bad, args, sorted_argnums=(1,))
    assert [f.rule for f in findings] == ["FML404"]


def test_fml404_sorted_column_stepper_traces_clean():
    """The acceptance trace: the production sorted-column SGD step,
    with the column's perm/segment_ids declared sorted-provenance, has
    ZERO FML404 findings — the pipeline never re-pays the sort."""
    from flinkml_tpu.analysis import check_sorted_scatter_fn
    from flinkml_tpu.models._linear_sgd import _sorted_column_stepper

    dim, bucket, width = 64, 16, 8
    step = _sorted_column_stepper("logistic", dim)
    args = (
        jnp.zeros(dim, jnp.float32),                 # coef
        jnp.zeros((bucket, width), jnp.int32),       # ib
        jnp.zeros((bucket, width), jnp.float32),     # vb
        jnp.zeros(bucket * width, jnp.int32),        # perm
        jnp.zeros(bucket * width, jnp.int32),        # segment_ids
        jnp.zeros(bucket, jnp.float32),              # yb
        jnp.ones(bucket, jnp.float32),               # wb
        jnp.asarray(12, jnp.int32),                  # n_valid
        jnp.float32(0.5), jnp.float32(1e-4), jnp.float32(0.0),
    )
    assert check_sorted_scatter_fn(step, args, sorted_argnums=(3, 4)) == []


def test_fml404_scatter_fixture_files():
    from flinkml_tpu.analysis import check_scatter_file

    bad = check_scatter_file(
        "tests/analysis_fixtures/"
        "bad_scatter_fml404_unsorted_flag_on_sorted_input.scatter.json"
    )
    assert [f.rule for f in bad] == ["FML404"]
    good = check_scatter_file(
        "tests/analysis_fixtures/"
        "good_scatter_sorted_flag_on_sorted_input.scatter.json"
    )
    assert good == []
    malformed = check_scatter_file("tests/analysis_fixtures/nope.json")
    assert [f.rule for f in malformed] == ["FML404"]
    assert "unreadable or malformed" in malformed[0].message
