"""Unit tests for the bounded in-flight dispatch policy (single-process
half; the multi-process sustained-dispatch ITs live in test_distributed.py).
"""

import jax.numpy as jnp
import pytest

from flinkml_tpu.parallel import (
    DispatchGuard,
    default_sync_interval,
    synced_loop,
)


def test_default_interval_single_process_unbounded(monkeypatch):
    monkeypatch.delenv("FLINKML_SYNC_INTERVAL", raising=False)
    assert default_sync_interval() == 0


def test_env_override(monkeypatch):
    monkeypatch.setenv("FLINKML_SYNC_INTERVAL", "4")
    assert default_sync_interval() == 4
    monkeypatch.setenv("FLINKML_SYNC_INTERVAL", "0")
    assert default_sync_interval() == 0


def test_guard_blocks_every_interval(monkeypatch):
    syncs = []
    guard = DispatchGuard(interval=3)
    monkeypatch.setattr(
        "flinkml_tpu.parallel.dispatch.jax.block_until_ready",
        lambda c: syncs.append(c) or c,
    )
    for i in range(7):
        guard.after_dispatch(i)
    assert syncs == [2, 5]  # after dispatches 3 and 6
    guard.flush(99)
    assert syncs == [2, 5, 99]  # one pending dispatch forced out
    guard.flush(100)
    assert syncs == [2, 5, 99]  # nothing pending: no extra sync


def test_synced_loop_runs_all_steps_and_returns_carry():
    out = synced_loop(10, lambda c, i: c + jnp.float32(i), jnp.float32(0),
                      interval=4)
    assert float(out) == sum(range(10))


def test_synced_loop_zero_steps():
    init = jnp.arange(3.0)
    out = synced_loop(0, lambda c, i: pytest.fail("must not run"), init)
    assert out is init
