"""AgglomerativeClustering vs sklearn (all linkages) + Swing semantics."""

import numpy as np
import pytest
from sklearn.cluster import AgglomerativeClustering as SkAgg
from sklearn.metrics import adjusted_rand_score

from flinkml_tpu.models import AgglomerativeClustering, Swing
from flinkml_tpu.models.agglomerative import agglomerate
from flinkml_tpu.table import Table


def _blobs(n_per=30, seed=0):
    rng = np.random.default_rng(seed)
    return np.concatenate([
        rng.normal(size=(n_per, 2)) * 0.5 + c
        for c in ([0, 0], [6, 0], [0, 6])
    ])


@pytest.mark.parametrize("linkage", ["ward", "complete", "average", "single"])
def test_agglomerative_matches_sklearn(linkage):
    x = _blobs(seed=1)
    ours = agglomerate(x, linkage=linkage, num_clusters=3)
    ref = SkAgg(n_clusters=3, linkage=linkage).fit_predict(x)
    assert adjusted_rand_score(ours, ref) == 1.0


def test_agglomerative_distance_threshold_matches_sklearn():
    x = _blobs(seed=2)
    for thr in (2.0, 8.0):
        ours = agglomerate(x, linkage="average", num_clusters=None,
                           distance_threshold=thr)
        ref = SkAgg(
            n_clusters=None, distance_threshold=thr, linkage="average"
        ).fit_predict(x)
        assert len(np.unique(ours)) == len(np.unique(ref))
        assert adjusted_rand_score(ours, ref) == 1.0


def test_agglomerative_operator_labels_first_appearance():
    x = _blobs(n_per=10, seed=3)
    t = Table({"features": x})
    (out,) = AgglomerativeClustering().set_num_clusters(3).transform(t)
    labels = out["prediction"]
    assert labels[0] == 0.0  # first row defines cluster 0
    assert set(np.unique(labels)) == {0.0, 1.0, 2.0}
    with pytest.raises(ValueError, match="numClusters"):
        AgglomerativeClustering().set_num_clusters(99).transform(
            Table({"features": x[:5]})
        )


def test_agglomerative_ward_threshold_scale():
    # Ward reports sqrt of the Ward objective (sklearn convention):
    # two far blobs at distance ~12 merge only above that threshold.
    x = _blobs(seed=4)[:60]  # two blobs
    low = agglomerate(x, "ward", None, distance_threshold=3.0)
    high = agglomerate(x, "ward", None, distance_threshold=1000.0)
    assert len(np.unique(low)) >= 2
    assert len(np.unique(high)) == 1


# -- Swing -------------------------------------------------------------------

def _swing(**kw):
    s = (
        Swing().set_k(5).set_min_user_behavior(2).set_max_user_behavior(100)
    )
    for name, v in kw.items():
        getattr(s, f"set_{name}")(v)
    return s


def test_swing_finds_co_consumed_items():
    # Items 0,1 always consumed together; item 2 by disjoint users.
    users = np.asarray([0, 0, 1, 1, 2, 2, 3, 3, 4, 4])
    items = np.asarray([0, 1, 0, 1, 0, 1, 2, 3, 2, 3])
    t = Table({"user": users, "item": items})
    (out,) = _swing().transform(t)
    row0 = {it: s for it, s in zip(out["similarItems"][0], out["scores"][0])}
    assert 1 in row0 and row0[1] > 0
    assert 2 not in row0 and 3 not in row0   # no shared users
    # Symmetry.
    row1 = {it: s for it, s in zip(out["similarItems"][1], out["scores"][1])}
    assert row1[0] == pytest.approx(row0[1])


def test_swing_overlap_damping():
    # Pair (0,1) supported by users with ONLY those two items; pair (2,3)
    # supported by users sharing many items -> weaker per-pair evidence.
    users, items = [], []
    for u in range(4):  # users 0-3: exactly items {0, 1}
        users += [u, u]
        items += [0, 1]
    for u in range(4, 8):  # users 4-7: items {2, 3, 4, 5, 6}
        users += [u] * 5
        items += [2, 3, 4, 5, 6]
    t = Table({"user": np.asarray(users), "item": np.asarray(items)})
    (out,) = _swing(alpha1=1.0, beta=0.0).transform(t)
    by_item = {
        it: dict(zip(sim, sc))
        for it, sim, sc in zip(out["item"], out["similarItems"], out["scores"])
    }
    assert by_item[0][1] > by_item[2][3]


def test_swing_behavior_bounds_filter_users():
    users = np.asarray([0, 1, 1, 2, 2, 2, 2, 2])
    items = np.asarray([0, 0, 1, 0, 1, 2, 3, 4])
    t = Table({"user": users, "item": items})
    # minUserBehavior=2 drops user 0; maxUserBehavior=4 drops user 2.
    (out,) = (
        Swing().set_k(5).set_min_user_behavior(2).set_max_user_behavior(4)
        .transform(t)
    )
    # Only user 1 remains -> no user PAIRS -> no similarities anywhere.
    assert all(len(s) == 0 for s in out["similarItems"])
    with pytest.raises(ValueError, match="minUserBehavior"):
        Swing().set_min_user_behavior(5).set_max_user_behavior(2).transform(t)


def test_swing_k_truncates_and_sorts():
    rng = np.random.default_rng(5)
    users = np.repeat(np.arange(12), 6)
    items = np.concatenate([
        rng.choice(8, size=6, replace=False) for _ in range(12)
    ])
    t = Table({"user": users, "item": items})
    (out,) = _swing(k=3).transform(t)
    for sc in out["scores"]:
        assert len(sc) <= 3
        assert np.all(np.diff(sc) <= 1e-12)


def test_swing_cap_gates_contributions():
    # Items 0,1 shared by users 0,1,2. With maxUserNumPerItem=2, user 2
    # is evicted from both items' lists, so only the (0,1) user pair may
    # contribute anywhere.
    users = np.asarray([0, 0, 1, 1, 2, 2])
    items = np.asarray([0, 1, 0, 1, 0, 1])
    t = Table({"user": users, "item": items})
    (capped,) = (
        Swing().set_k(5).set_min_user_behavior(2).set_max_user_behavior(10)
        .set_max_user_num_per_item(2).set_alpha1(1.0).set_beta(0.0)
        .transform(t)
    )
    (full,) = (
        Swing().set_k(5).set_min_user_behavior(2).set_max_user_behavior(10)
        .set_alpha1(1.0).set_beta(0.0)
        .transform(t)
    )
    # Full: 3 user pairs x 1/(1+2); capped: 1 user pair.
    assert full["scores"][0][0] == pytest.approx(3 / 3)
    assert capped["scores"][0][0] == pytest.approx(1 / 3)


def test_swing_output_uses_item_col_name():
    t = Table({"u": np.asarray([0, 0, 1, 1]),
               "movie": np.asarray([0, 1, 0, 1])})
    (out,) = (
        Swing().set_user_col("u").set_item_col("movie")
        .set_min_user_behavior(2).set_max_user_behavior(10)
        .transform(t)
    )
    assert "movie" in out.column_names


def test_agglomerative_distance_threshold_resettable():
    op = AgglomerativeClustering().set_distance_threshold(2.0)
    op.set_distance_threshold(None)
    x = _blobs(n_per=5, seed=9)
    (out,) = op.set_num_clusters(3).transform(Table({"features": x}))
    assert len(np.unique(out["prediction"])) == 3


def test_agglomerative_matches_sklearn_fuzz():
    # Random (unseparated) gaussians: merge order is precision-sensitive;
    # the f64 distance matrix must track sklearn exactly.
    from itertools import product

    for seed, linkage in product(range(6), ["ward", "average", "single"]):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(8, 40))
        x = rng.normal(size=(n, 3))
        k = int(rng.integers(2, min(6, n)))
        ours = agglomerate(x, linkage=linkage, num_clusters=k)
        ref = SkAgg(n_clusters=k, linkage=linkage).fit_predict(x)
        assert adjusted_rand_score(ours, ref) == 1.0, (seed, linkage, k)
