"""Device-client mutex: exclusivity, timeout, held-marker inheritance.

The mutex is the framework's admission control for the single-tenant
tunneled device (BASELINE.md round-2 "Tunnel wedge observed"): the analog
of Flink's slot pool serializing access to TaskManager slots.
"""

import os
import subprocess
import sys

from flinkml_tpu.utils.device_lock import (
    _HELD_ENV,
    LOCK_PATH_ENV,
    device_client_lock,
)


def test_cpu_process_skips_lock(tmp_path, monkeypatch):
    # tests/conftest.py sets JAX_PLATFORMS=cpu; a CPU-only process must
    # not serialize on (or create) the device lock.
    monkeypatch.setenv(LOCK_PATH_ENV, str(tmp_path / "lock"))
    with device_client_lock() as acquired:
        assert acquired is False
    assert not (tmp_path / "lock").exists()


def test_exclusive_across_processes(tmp_path, monkeypatch):
    path = str(tmp_path / "lock")
    monkeypatch.setenv(LOCK_PATH_ENV, path)
    with device_client_lock(force=True) as acquired:
        assert acquired is True
        # A second CLIENT process must time out rather than proceed.
        code = (
            "import os\n"
            "os.environ.pop('_FLINKML_TPU_DEVICE_LOCK_HELD', None)\n"
            "from flinkml_tpu.utils.device_lock import device_client_lock\n"
            "try:\n"
            "    with device_client_lock(timeout_s=0.5, poll_s=0.1,"
            " force=True):\n"
            "        print('ACQUIRED')\n"
            "except TimeoutError:\n"
            "    print('TIMEOUT')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, LOCK_PATH_ENV: path},
            capture_output=True, text=True, timeout=60,
        )
        assert out.stdout.strip() == "TIMEOUT", (out.stdout, out.stderr)
    # Released: the same child program now acquires immediately.
    out = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, LOCK_PATH_ENV: path},
        capture_output=True, text=True, timeout=60,
    )
    assert out.stdout.strip() == "ACQUIRED", (out.stdout, out.stderr)


def test_child_of_holder_skips(tmp_path, monkeypatch):
    # bench.py stage children inherit os.environ from the lock-holding
    # parent; they must skip re-acquiring instead of deadlocking.
    monkeypatch.setenv(LOCK_PATH_ENV, str(tmp_path / "lock"))
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    with device_client_lock(force=True) as acquired:
        assert acquired is True
        assert os.environ.get(_HELD_ENV) == "1"
        with device_client_lock() as nested:
            assert nested is False
    assert _HELD_ENV not in os.environ
