"""Gray-failure defense: abandonment, hedging, quarantine, brownout.

The acceptance contract of the gray-failure subsystem (ISSUE 19):

  1. Per-dispatch deadlines with TRUE abandonment — the router stops
     waiting past the per-attempt budget and fails over; the abandoned
     straggler's late result is discarded by the request's terminal CAS,
     so it can never surface as a duplicate or (across a hot swap)
     mis-versioned response.
  2. Hedged requests are exactly-once at the client: first completion
     wins, the loser is cancelled at the queue, admission is charged per
     request (never per attempt).
  3. Latency-outlier quarantine: the MAD test trips a slow-but-alive
     replica into SLOW (out of routing, NOT killed), canary probes
     drive SLOW -> HEALTHY on sustained recovery, and a quarantine that
     never recovers escalates to retirement. SLOW counts against the
     autoscaler's ``min_replicas``, so quarantine triggers replacement.
  4. The brownout ladder sheds SLO classes in declared order (batch
     before interactive) under pool-WIDE degradation, via the typed
     ``SLOAdmissionError``.
  5. Chaos acceptance: 1 of 4 replicas stalled ~100x mid-traffic is
     autonomously quarantined, zero requests are lost, zero responses
     are duplicated or mis-versioned, closed-loop p99 recovers, and the
     replica rejoins after the stall clears with no operator action.
"""

import threading
import time

import numpy as np
import pytest

from flinkml_tpu import faults
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.serving import (
    AutoscaleConfig,
    BATCH,
    GrayFailPolicy,
    INTERACTIVE,
    ModelRegistry,
    MultiModelPool,
    PoolAutoscaler,
    ReplicaPool,
    ReplicaState,
    ServingConfig,
    ServingRequest,
    ServingTimeoutError,
    SLOAdmissionError,
)
from flinkml_tpu.table import Table
from flinkml_tpu.utils.metrics import metrics


def _data(n=256, d=4, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, d))


def _scaler(x):
    return (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "scaled")
        .fit(Table({"features": x}))
    )


def _pool(source, x, n_replicas=4, name="gf_pool", grayfail=None, **cfg):
    config = ServingConfig(**{
        "max_batch_rows": 64,
        "max_queue_rows": 512,
        "max_wait_ms": 1.0,
        **cfg,
    })
    return ReplicaPool(
        source, Table({"features": x[:4]}), config=config,
        n_replicas=n_replicas, output_cols=("scaled",), name=name,
        grayfail=grayfail,
    )


def _seed_rings(pool, ms=5.0, n=10, only=None):
    """Deterministically seed per-replica attempt rings (sequential
    warm traffic all lands on one replica under least-outstanding
    ties, so tests seed the sibling evidence directly)."""
    for r in pool.replicas:
        if only is not None and r.name not in only:
            continue
        for _ in range(n):
            r.health.record_attempt(ms)


def _expected(model, x):
    (ref,) = model.transform(Table({"features": x}))
    return np.asarray(ref.column("scaled"))


# ---------------------------------------------------------------------------
# 1. Terminal-transition CAS on ServingRequest (the safety primitive)
# ---------------------------------------------------------------------------

def test_request_terminal_cas_first_transition_wins():
    """Exactly one of complete/fail/abandon takes effect; every later
    transition is refused — the mechanism that makes a late straggler
    incapable of producing a duplicate or mis-versioned response."""
    def req():
        return ServingRequest(
            columns={"x": np.zeros((2, 2))}, rows=2,
            enqueued_at=time.monotonic(), deadline=None,
        )

    r = req()
    race = threading.Event()
    r.race = race
    assert r.complete({"x": np.ones((2, 2))}, version=1)
    assert race.is_set()  # terminal transition wakes the racing router
    assert not r.complete({"x": np.zeros((2, 2))}, version=2)
    assert not r.abandon()
    assert not r.fail(RuntimeError("late"))
    assert r.version == 1 and r.error is None and not r.abandoned

    r = req()
    assert r.abandon()
    assert r.abandoned
    assert not r.complete({"x": np.ones((2, 2))}, version=9)
    assert r.result is None and r.version is None

    r = req()
    assert r.fail(RuntimeError("boom"))
    assert not r.abandon()


# ---------------------------------------------------------------------------
# 2. Pool-level default timeout (an untimed request can never hang)
# ---------------------------------------------------------------------------

def test_untimed_request_inherits_pool_default_timeout():
    x = _data()
    model = _scaler(x)
    pool = _pool(model, x, n_replicas=2, name="deft_pool",
                 default_timeout_ms=200.0).start()
    try:
        assert pool._router._default_timeout_ms == 200.0
        with faults.armed(faults.FaultPlan(
            faults.StallDispatch("r0", delay_s=1.0),
            faults.StallDispatch("r1", delay_s=1.0),
        )):
            t0 = time.monotonic()
            with pytest.raises(ServingTimeoutError):
                pool.predict({"features": x[:2]})  # NO explicit timeout
            # Bounded by default deadline + in-flight grace, not by the
            # 1s stall (and certainly not forever).
            assert time.monotonic() - t0 < 2.0
    finally:
        pool.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 3. Abandonment: stop waiting, fail over, censored evidence
# ---------------------------------------------------------------------------

def test_abandonment_fails_over_and_records_censored():
    x = _data()
    model = _scaler(x)
    policy = GrayFailPolicy(
        attempt_floor_ms=40.0, min_attempt_samples=5, hedge=False,
        deadline_multiplier=4.0, brownout=False,
    )
    pool = _pool(model, x, n_replicas=3, name="aband_pool",
                 grayfail=policy).start()
    expected = _expected(model, x)
    try:
        _seed_rings(pool, ms=5.0, n=10)
        with faults.armed(faults.FaultPlan(
            faults.StallDispatch("r0", delay_s=0.6)
        )):
            for i in range(4):
                sl = slice(i * 4, i * 4 + 4)
                t0 = time.monotonic()
                resp = pool.predict({"features": x[sl]}, timeout_ms=5000.0)
                # Served well inside the 0.6s stall: the router stopped
                # waiting at the ~40ms attempt budget and failed over.
                assert time.monotonic() - t0 < 0.5
                np.testing.assert_array_equal(
                    np.asarray(resp.columns["scaled"]), expected[sl]
                )
        st = pool.stats()
        assert st["router"].get("abandoned_attempts", 0) >= 1
        r0 = pool.replicas[0].health.snapshot()
        assert r0["abandoned_attempts"] >= 1  # censored ring evidence
        assert r0["state"] == "healthy"  # abandonment alone never kills
    finally:
        pool.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 4. Hedging: exactly-once, loser cancelled, straggler discarded
# ---------------------------------------------------------------------------

def test_hedge_exactly_once_straggler_discarded():
    x = _data()
    model = _scaler(x)
    policy = GrayFailPolicy(
        abandon=False, hedge=True, hedge_floor_ms=40.0,
        hedge_multiplier=1.0, min_attempt_samples=5, brownout=False,
    )
    pool = _pool(model, x, n_replicas=2, name="hedge_pool",
                 grayfail=policy).start()
    expected = _expected(model, x)
    try:
        _seed_rings(pool, ms=5.0, n=10)
        with faults.armed(faults.FaultPlan(
            faults.StallDispatch("r0", delay_s=0.4, for_batches=1)
        )):
            resp = pool.predict({"features": x[:4]}, timeout_ms=5000.0)
            np.testing.assert_array_equal(
                np.asarray(resp.columns["scaled"]), expected[:4]
            )
            # The stalled primary finishes ~0.4s in; its result must be
            # discarded by the terminal CAS, never double-surfaced.
            deadline = time.monotonic() + 5.0
            r0 = pool.replicas[0].engine
            while time.monotonic() < deadline:
                if r0._metrics.snapshot()["counters"].get(
                        "discarded_results", 0) >= 1:
                    break
                time.sleep(0.02)
        st = pool.stats()["router"]
        assert st.get("hedges_dispatched", 0) >= 1
        assert st.get("hedges_won", 0) >= 1
        assert r0._metrics.snapshot()["counters"].get(
            "discarded_results", 0) >= 1
        # The labeled hedge-outcome metric family is live.
        won = metrics.group("serving.hedge_pool.hedges",
                            labels={"outcome": "won"})
        assert won.snapshot()["counters"].get("total", 0) >= 1
    finally:
        pool.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 5. Abandoned straggler across a hot swap: version safety
# ---------------------------------------------------------------------------

def test_abandoned_straggler_version_safety_across_hot_swap(tmp_path):
    x = _data()
    model = _scaler(x)
    policy = GrayFailPolicy(
        attempt_floor_ms=40.0, min_attempt_samples=5, hedge=False,
        deadline_multiplier=4.0, brownout=False,
    )
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(model)
    pool = _pool(reg, x, n_replicas=2, name="swap_pool",
                 grayfail=policy).start()
    pool.follow_registry()
    try:
        _seed_rings(pool, ms=5.0, n=10)
        with faults.armed(faults.FaultPlan(
            faults.StallDispatch("r0", delay_s=0.5, for_batches=1)
        )):
            # Lands on r0 (stalled), is abandoned at ~40ms, serves on r1.
            resp = pool.predict({"features": x[:4]}, timeout_ms=5000.0)
            assert resp.version == 1
            # Roll the pool to v2 while r0's straggler batch is still
            # sleeping on the v1-era request.
            reg.publish(_scaler(x * 2.0))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if set(pool.versions().values()) == {2}:
                break
            time.sleep(0.05)
        assert set(pool.versions().values()) == {2}
        # The straggler completed under SOME version — but its request
        # was already terminal, so the result was discarded, not served.
        r0 = pool.replicas[0].engine
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if r0._metrics.snapshot()["counters"].get(
                    "discarded_results", 0) >= 1:
                break
            time.sleep(0.02)
        assert r0._metrics.snapshot()["counters"].get(
            "discarded_results", 0) >= 1
    finally:
        pool.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 6. Quarantine -> canary -> rejoin lifecycle (deterministic, step-driven)
# ---------------------------------------------------------------------------

def _lifecycle_policy(**over):
    kw = dict(
        abandon=False, hedge=False, brownout=False,
        min_slow_samples=5, slow_trip=2, slow_clear=2,
        slow_abs_floor_ms=1.0, canary_interval_s=0.0,
        canary_timeout_ms=1000.0, canary_min_samples=2,
        quarantine_retire_s=None,
    )
    kw.update(over)
    return GrayFailPolicy(**kw)


def test_quarantine_canary_rejoin_lifecycle():
    x = _data()
    model = _scaler(x)
    pool = _pool(model, x, n_replicas=4, name="quar_pool").start()
    guard = pool.grayfail_guard(policy=_lifecycle_policy())
    try:
        _seed_rings(pool, ms=5.0, n=10, only={"r1", "r2", "r3"})
        _seed_rings(pool, ms=500.0, n=10, only={"r0"})
        assert guard.step() == []  # hysteresis: one trip is not enough
        actions = guard.step()
        assert "quarantine:r0" in actions
        assert pool.replicas[0].health.state is ReplicaState.SLOW
        assert pool.stats()["healthy"] == 3  # out of routing, NOT killed
        # The outlier score gauge is published per replica.
        score = metrics.group("serving.quar_pool",
                              labels={"replica": "r0"})
        assert score.snapshot()["gauges"]["slow_score"] > 6.0
        # Canary probes (the engine is actually fast — the seeded ring
        # was the lie) accumulate post-quarantine evidence and rejoin.
        seen = []
        for _ in range(10):
            seen += guard.step()
            if "rejoin:r0" in seen:
                break
        assert "rejoin:r0" in seen
        assert pool.replicas[0].health.state is ReplicaState.HEALTHY
        assert pool.stats()["healthy"] == 4
        counters = guard._metrics.snapshot()["counters"]
        assert counters.get("quarantines_total", 0) >= 1
        assert counters.get("rejoins_total", 0) >= 1
        assert counters.get("canary_probes", 0) >= 2
    finally:
        pool.stop(drain=False, timeout=5.0)


def test_quarantine_refused_when_it_would_empty_the_pool():
    x = _data()
    model = _scaler(x)
    pool = _pool(model, x, n_replicas=2, name="floor_pool").start()
    guard = pool.grayfail_guard(
        policy=_lifecycle_policy(min_healthy_after_quarantine=2)
    )
    try:
        _seed_rings(pool, ms=5.0, n=10, only={"r1"})
        _seed_rings(pool, ms=500.0, n=10, only={"r0"})
        for _ in range(4):
            assert guard.step() == []
        assert pool.replicas[0].health.state is ReplicaState.HEALTHY
    finally:
        pool.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 7. Composition with the autoscaler: replacement and escalation
# ---------------------------------------------------------------------------

def test_quarantine_counts_against_min_replicas_and_is_replaced():
    x = _data()
    model = _scaler(x)
    pool = _pool(model, x, n_replicas=4, name="scale_pool").start()
    scaler = PoolAutoscaler(pool, AutoscaleConfig(
        min_replicas=4, max_replicas=6, cooldown_s=0.0,
    ))
    try:
        assert pool.replicas[0].health.mark_slow()
        sig = scaler.signals()
        assert sig["healthy"] == 3  # SLOW is not healthy
        assert scaler.step() == "replace"
        assert len(pool.replicas) == 5
        # The quarantined replica is still there, still SLOW — replaced,
        # not killed: it may yet recover and rejoin.
        assert pool.replicas[0].health.state is ReplicaState.SLOW
        assert scaler.signals()["healthy"] == 4
    finally:
        pool.stop(drain=False, timeout=5.0)


def test_quarantine_that_never_recovers_escalates_to_retirement():
    x = _data()
    model = _scaler(x)
    pool = _pool(model, x, n_replicas=4, name="retire_pool").start()
    guard = pool.grayfail_guard(
        policy=_lifecycle_policy(quarantine_retire_s=0.0)
    )
    try:
        assert pool.replicas[0].health.mark_slow()
        time.sleep(0.01)  # any positive state age beats the 0.0s budget
        actions = guard.step()
        assert "retire:r0" in actions
        assert pool.replicas[0].health.state is ReplicaState.UNHEALTHY
        counters = guard._metrics.snapshot()["counters"]
        assert counters.get("slow_retired_total", 0) >= 1
    finally:
        pool.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 8. Brownout ladder: shed batch before interactive, recover one rung
# ---------------------------------------------------------------------------

def test_brownout_sheds_batch_before_interactive():
    x = _data()
    policy = GrayFailPolicy(
        abandon=False, hedge=False,
        slow_mad_k=1e9,  # isolate the brownout path from quarantine
        min_slow_samples=2,
        brownout=True, brownout_trip=2, brownout_clear=2,
        brownout_multiplier=2.0, brownout_abs_floor_ms=1.0,
    )
    mm = MultiModelPool(Table({"features": x[:4]}),
                        config=ServingConfig(max_batch_rows=64,
                                             max_queue_rows=512,
                                             max_wait_ms=1.0),
                        name="bo_pool", grayfail=policy)
    mm.add_model("m_int", _scaler(x), slo=INTERACTIVE, n_replicas=1)
    mm.add_model("m_batch", _scaler(x), slo=BATCH, n_replicas=1)
    mm.start()
    guard = mm.grayfail_guard(policy=policy)
    feats = {"features": x[:2]}
    try:
        _seed_rings(mm, ms=5.0, n=10)
        guard.step()  # establishes the ~5ms baseline
        # Pool-WIDE degradation: every replica slow — the MAD test is
        # blind to this (the median moves with the failure).
        for r in mm.replicas:
            r.health._attempt_ms.clear()
        _seed_rings(mm, ms=100.0, n=10)
        actions = []
        for _ in range(3):
            actions += guard.step()
        assert "brownout:1" in actions
        assert mm.brownout_shed_classes == frozenset({"batch"})
        # Batch is refused with the typed error; interactive still serves.
        with pytest.raises(SLOAdmissionError):
            mm.predict("m_batch", feats)
        resp = mm.predict("m_int", feats, timeout_ms=5000.0)
        assert resp.columns["scaled"].shape == (2, x.shape[1])
        assert mm._ledgers["batch"].metrics.snapshot()["counters"].get(
            "brownout_rejections", 0) >= 1
        # Recovery de-escalates one rung and batch is admitted again.
        for r in mm.replicas:
            r.health._attempt_ms.clear()
        _seed_rings(mm, ms=5.0, n=10)
        for _ in range(3):
            actions += guard.step()
        assert "brownout:0" in actions
        assert mm.brownout_shed_classes == frozenset()
        mm.predict("m_batch", feats, timeout_ms=5000.0)
    finally:
        mm.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 9. SLO admission releases at abandonment, not straggler completion
# ---------------------------------------------------------------------------

def test_slo_admission_released_at_abandonment():
    x = _data()
    policy = GrayFailPolicy(
        attempt_floor_ms=40.0, min_attempt_samples=5, hedge=False,
        deadline_multiplier=4.0, brownout=False,
    )
    mm = MultiModelPool(Table({"features": x[:4]}),
                        config=ServingConfig(max_batch_rows=64,
                                             max_queue_rows=512,
                                             max_wait_ms=1.0),
                        name="slo_pool", grayfail=policy)
    mm.add_model("m", _scaler(x), slo=BATCH, n_replicas=2)
    mm.start()
    try:
        _seed_rings(mm, ms=5.0, n=10)
        ledger = mm._ledgers["batch"]
        with faults.armed(faults.FaultPlan(
            faults.StallDispatch("r0", delay_s=0.6, for_batches=1)
        )):
            t0 = time.monotonic()
            resp = mm.predict("m", {"features": x[:4]}, timeout_ms=5000.0)
            elapsed = time.monotonic() - t0
            # Served by failover while r0's straggler is still sleeping…
            assert elapsed < 0.5
            assert resp.columns["scaled"].shape[0] == 4
            # …and the admission rows are ALREADY released — a stalled
            # replica must not hold a class's share hostage for the
            # straggler's lifetime.
            assert ledger.outstanding_rows == 0
        time.sleep(0.7)  # let the straggler finish + be discarded
        assert ledger.outstanding_rows == 0  # no double-settle underflow
    finally:
        mm.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# 10. Fault specs: round-trip, determinism, fuzz sampler
# ---------------------------------------------------------------------------

def test_grayfail_fault_specs_roundtrip_and_determinism():
    for name in ("StallDispatch", "JitterDispatch", "SlowRamp"):
        assert name in faults.fault_types()
    plan = faults.FaultPlan(
        faults.StallDispatch("r1", at_batch=2, delay_s=0.05, for_batches=3),
        faults.JitterDispatch("r0", p=0.5, delay_s=0.0, seed=7),
        faults.SlowRamp("r2", at_batch=1, step_s=0.01, max_s=0.1),
    )
    clone = faults.plan_from_json(faults.plan_to_json(plan))
    assert [faults.fault_to_spec(f) for f in clone.faults] == \
        [faults.fault_to_spec(f) for f in plan.faults]
    # Jitter draws are deterministic in the committed seed: a JSON repro
    # replays the exact stall pattern.
    j1, j2 = plan.faults[1], clone.faults[1]
    ctx = {"engine": "pool/r0"}
    assert [j1.should_fire(ctx) for _ in range(32)] == \
        [j2.should_fire(ctx) for _ in range(32)]
    # A finite stall window opens at at_batch and closes after
    # for_batches — the rejoin fixture.
    st = faults.StallDispatch("r0", at_batch=2, delay_s=0.0, for_batches=2)
    fired = []
    for _ in range(5):
        hit = st.should_fire({"engine": "p/r0"})
        if hit:
            st.apply({})
        fired.append(hit)
    assert fired == [False, True, True, False, False]


def test_fuzzplan_serving_seam_sampler_is_deterministic():
    plan = faults.FuzzPlan(seed=3, seams=("serving.replica",),
                          budget=4, horizon=8, replicas=4)
    for i in range(4):
        a, b = plan.sample(i), plan.sample(i)
        assert [faults.fault_to_spec(f) for f in a.faults] == \
            [faults.fault_to_spec(f) for f in b.faults]
        for f in a.faults:
            assert f.site == "serving.replica"
            assert f.engine in {"r0", "r1", "r2", "r3"}


# ---------------------------------------------------------------------------
# 11. Chaos acceptance: stall 1 of 4 replicas ~100x mid-traffic
# ---------------------------------------------------------------------------

def test_grayfail_chaos_acceptance():
    """The pinned end-to-end contract: one replica stalls ~100x under
    closed-loop load -> the guard quarantines it autonomously, zero
    requests are lost, zero responses are duplicated/mis-versioned,
    p99 recovers, and the replica rejoins once the stall clears."""
    from flinkml_tpu.recovery.fuzz import serving_grayfail_policy

    x = _data()
    model = _scaler(x)
    expected = _expected(model, x)
    pool = _pool(model, x, n_replicas=4, name="chaos_gf_pool",
                 grayfail=serving_grayfail_policy()).start()
    guard = pool.grayfail_guard(interval_s=0.05).start()
    errors = []
    served = [0]
    stop = threading.Event()

    def probe_p99(n=60):
        lat = []
        for i in range(n):
            sl = slice((i % 50) * 4, (i % 50) * 4 + 4)
            t0 = time.perf_counter()
            resp = pool.predict({"features": x[sl]}, timeout_ms=5000.0)
            lat.append((time.perf_counter() - t0) * 1e3)
            np.testing.assert_array_equal(
                np.asarray(resp.columns["scaled"]), expected[sl]
            )
        lat.sort()
        return lat[min(len(lat) - 1, int(np.ceil(0.99 * len(lat))) - 1)]

    def client(tid):
        rng = np.random.default_rng(tid)
        try:
            while not stop.is_set():
                lo = int(rng.integers(0, x.shape[0] - 4))
                sl = slice(lo, lo + 4)
                resp = pool.predict({"features": x[sl]},
                                    timeout_ms=5000.0)
                np.testing.assert_array_equal(
                    np.asarray(resp.columns["scaled"]), expected[sl]
                )
                served[0] += 1
                time.sleep(0.002)
        except BaseException as e:  # noqa: BLE001 — any client error fails
            errors.append(e)

    try:
        p99_base = probe_p99()
        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(4)]
        for t in threads:
            t.start()
        with faults.armed(faults.FaultPlan(
            faults.StallDispatch("r1", delay_s=0.2)  # ~100x a CPU batch
        )):
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if pool.replicas[1].health.state is ReplicaState.SLOW:
                    break
                time.sleep(0.05)
            assert pool.replicas[1].health.state is ReplicaState.SLOW, \
                "guard never quarantined the stalled replica"
            served_at_quarantine = served[0]
            time.sleep(0.3)  # pool must keep serving around the stall
            assert served[0] > served_at_quarantine
        # Stall cleared (faults disarmed): canaries must rejoin r1 with
        # no operator intervention.
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if pool.replicas[1].health.state is ReplicaState.HEALTHY:
                break
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
        assert not errors, errors[:3]  # zero lost, zero mis-served
        assert pool.replicas[1].health.state is ReplicaState.HEALTHY, \
            "replica never rejoined after the stall cleared"
        counters = guard._metrics.snapshot()["counters"]
        assert counters.get("quarantines_total", 0) >= 1
        assert counters.get("rejoins_total", 0) >= 1
        p99_after = probe_p99()
        assert p99_after <= max(2.0 * p99_base, p99_base + 50.0), (
            f"p99 did not recover: {p99_after:.1f}ms vs baseline "
            f"{p99_base:.1f}ms"
        )
    finally:
        stop.set()
        guard.stop()
        pool.stop(drain=False, timeout=5.0)
