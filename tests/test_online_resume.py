"""Crash-safe online training (ISSUE 4): kill/resume bit-parity for the
online trio — OnlineLogisticRegression, OnlineKMeans,
OnlineStandardScaler.

Acceptance contract: a ``fit_stream`` killed by an injected fault at
epoch k, with its NEWEST checkpoint deliberately corrupted, resumes from
the prior valid snapshot and produces a final model bit-identical to the
uninterrupted run. Also covered: replay-vs-continue stream cursor
semantics, resume-as-noop after completion, and the SIGTERM watchdog
(final checkpoint + serving drain + resume-to-parity).
"""

import numpy as np
import pytest

from flinkml_tpu import faults
from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.models import (
    OnlineKMeans,
    OnlineLogisticRegression,
)
from flinkml_tpu.models.online_scaler import OnlineStandardScaler
from flinkml_tpu.table import Table
from flinkml_tpu.utils.preemption import PreemptionWatchdog

N_BATCHES = 12
CRASH_EPOCH = 7
INTERVAL = 2


def lr_batches(seed=0, n=N_BATCHES, rows=48, dim=5):
    rng = np.random.default_rng(seed)
    true = rng.normal(size=dim) * 2
    out = []
    for _ in range(n):
        x = rng.normal(size=(rows, dim))
        out.append(Table({"features": x,
                          "label": (x @ true > 0).astype(np.float64)}))
    return out


def km_batches(seed=1, n=N_BATCHES, rows=40, dim=4):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-8, 8, size=(3, dim))
    out = []
    for _ in range(n):
        assign = rng.integers(0, 3, size=rows)
        x = centers[assign] + rng.normal(scale=0.4, size=(rows, dim))
        out.append(Table({"features": x}))
    return out


def sc_batches(seed=2, n=N_BATCHES, rows=32, dim=6):
    rng = np.random.default_rng(seed)
    return [Table({"input": rng.normal(size=(rows, dim)) * (1 + i)})
            for i in range(n)]


def _lr():
    return OnlineLogisticRegression().set_alpha(0.5).set_reg(0.01)


def _km():
    return OnlineKMeans().set_k(3).set_seed(11).set_decay_factor(0.9)


def _sc():
    return OnlineStandardScaler()


def _crash_and_corrupt(est_factory, batches, mgr, corrupt="arrays"):
    """Run the acceptance scenario's failure half: injected crash at
    CRASH_EPOCH, then damage the newest committed snapshot."""
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(CRASH_EPOCH))):
        with pytest.raises(faults.FaultInjected):
            est_factory().fit_stream(batches, checkpoint_manager=mgr,
                                     checkpoint_interval=INTERVAL)
    assert mgr.latest_epoch() == CRASH_EPOCH - 1  # 6, the interval commit
    corrupted = faults.corrupt_latest(mgr, target=corrupt)
    return corrupted


# ---------------------------------------------------------------------------
# The acceptance criterion, per trainer
# ---------------------------------------------------------------------------

def test_online_lr_kill_corrupt_resume_bit_exact(tmp_path):
    batches = lr_batches()
    golden = _lr().fit_stream(batches)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    corrupted = _crash_and_corrupt(_lr, batches, mgr)
    assert corrupted == 6

    recovered = _lr().fit_stream(batches, checkpoint_manager=mgr,
                                 checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(recovered.coefficient, golden.coefficient)
    assert recovered.model_version == golden.model_version == N_BATCHES


def test_online_kmeans_kill_corrupt_resume_bit_exact(tmp_path):
    batches = km_batches()
    golden = _km().fit_stream(batches)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    _crash_and_corrupt(_km, batches, mgr, corrupt="manifest")

    recovered = _km().fit_stream(batches, checkpoint_manager=mgr,
                                 checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(recovered.centroids, golden.centroids)
    assert recovered.model_version == golden.model_version == N_BATCHES


def test_online_scaler_kill_corrupt_resume_bit_exact(tmp_path):
    batches = sc_batches()
    golden = _sc().fit_stream(batches)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    _crash_and_corrupt(_sc, batches, mgr, corrupt="truncate")

    recovered = _sc().fit_stream(batches, checkpoint_manager=mgr,
                                 checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(recovered._mean, golden._mean)
    np.testing.assert_array_equal(recovered._std, golden._std)
    assert recovered.model_version == golden.model_version == N_BATCHES


# ---------------------------------------------------------------------------
# Stream cursor semantics
# ---------------------------------------------------------------------------

def test_replay_vs_continue_cursor(tmp_path):
    """'replay' re-presents the stream from the start (the trainer skips
    the consumed prefix); 'continue' consumes a live stream positioned at
    'now' — the caller hands over only the unconsumed tail."""
    batches = lr_batches(seed=3)
    golden = _lr().fit_stream(batches)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(CRASH_EPOCH))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(iter(batches), checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL)
    ckpt_epoch = mgr.latest_epoch()
    assert ckpt_epoch == 6

    # continue: the live stream's unconsumed tail starts at the restored
    # epoch (batches 0..5 are in the snapshot's state already).
    recovered = _lr().fit_stream(
        iter(batches[ckpt_epoch:]), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL, resume=True, stream_resume="continue",
    )
    np.testing.assert_array_equal(recovered.coefficient, golden.coefficient)
    assert recovered.model_version == N_BATCHES

    # replay on a restartable source reaches the same model.
    mgr2 = CheckpointManager(str(tmp_path / "ckpt2"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(CRASH_EPOCH))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(batches, checkpoint_manager=mgr2,
                             checkpoint_interval=INTERVAL)
    replayed = _lr().fit_stream(batches, checkpoint_manager=mgr2,
                                checkpoint_interval=INTERVAL, resume=True,
                                stream_resume="replay")
    np.testing.assert_array_equal(replayed.coefficient, golden.coefficient)


def test_resume_after_completion_is_noop(tmp_path):
    """A finished run leaves a terminal snapshot; resuming re-runs zero
    epochs and returns the identical model."""
    batches = km_batches(seed=9)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    done = _km().fit_stream(batches, checkpoint_manager=mgr,
                            checkpoint_interval=INTERVAL)
    assert mgr.latest_epoch() == N_BATCHES  # terminal snapshot
    again = _km().fit_stream(batches, checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(again.centroids, done.centroids)
    assert again.model_version == done.model_version


def test_kmeans_resume_skips_initial_draw_validation(tmp_path):
    """A resumed run's first batch is NOT the centroid-draw batch: a
    small-first-batch live tail must resume fine (the rows >= k check
    applies only to a genuine fresh start)."""
    batches = km_batches(seed=21)  # 40 rows per batch
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    golden = _km().fit_stream(batches, checkpoint_manager=mgr,
                              checkpoint_interval=4)
    # Tail whose first batch has 2 rows < k=3; with stream_resume=
    # 'continue' the restored centroids make the draw irrelevant.
    small_tail = [Table({"features": np.asarray(
        batches[-1].column("features"))[:2]})]
    resumed = _km().fit_stream(small_tail, checkpoint_manager=mgr,
                               checkpoint_interval=4, resume=True,
                               stream_resume="continue")
    assert resumed.model_version == golden.model_version + 1


def test_resume_with_exhausted_stream_returns_checkpointed_model(tmp_path):
    """'continue' resume where the live tail is already empty (crash at
    stream end): the checkpointed model comes back, no error."""
    batches = lr_batches(seed=23)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    done = _lr().fit_stream(batches, checkpoint_manager=mgr,
                            checkpoint_interval=2)
    again = _lr().fit_stream(iter([]), checkpoint_manager=mgr,
                             checkpoint_interval=2, resume=True,
                             stream_resume="continue")
    np.testing.assert_array_equal(again.coefficient, done.coefficient)
    assert again.model_version == done.model_version

    sc_mgr = CheckpointManager(str(tmp_path / "sc"), max_to_keep=10)
    sc_done = _sc().fit_stream(sc_batches(seed=24),
                               checkpoint_manager=sc_mgr,
                               checkpoint_interval=2)
    sc_again = _sc().fit_stream(iter([]), checkpoint_manager=sc_mgr,
                                resume=True, stream_resume="continue")
    np.testing.assert_array_equal(sc_again._mean, sc_done._mean)
    assert sc_again.model_version == sc_done.model_version


def test_empty_stream_with_warm_start_returns_initial_model():
    """Pre-ISSUE-4 contract preserved: a warm-started trainer fed an
    empty stream returns the initial model data at version 0."""
    init = np.array([1.0, -2.0, 3.0])
    est = OnlineLogisticRegression()
    est._initial_coefficient = init
    model = est.fit_stream(iter([]))
    np.testing.assert_array_equal(model.coefficient, init)
    assert model.model_version == 0

    centroids = np.arange(6.0).reshape(3, 2)
    km = OnlineKMeans().set_k(3)
    km._initial_centroids = centroids
    kmodel = km.fit_stream(iter([]))
    np.testing.assert_array_equal(kmodel.centroids, centroids)
    assert kmodel.model_version == 0


def test_resume_without_manager_rejected():
    with pytest.raises(ValueError, match="requires a checkpoint_manager"):
        _lr().fit_stream(lr_batches(n=2), resume=True)


def test_double_failure_recovery(tmp_path):
    """Two crashes at different epochs, resume each time — still
    bit-exact (the reference's failoverCount-parameterized ITCases)."""
    batches = lr_batches(seed=5)
    golden = _lr().fit_stream(batches)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    for crash_at in (4, 9):
        with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(crash_at))):
            with pytest.raises(faults.FaultInjected):
                _lr().fit_stream(batches, checkpoint_manager=mgr,
                                 checkpoint_interval=1, resume=True)
        assert mgr.latest_epoch() == crash_at
    final = _lr().fit_stream(batches, checkpoint_manager=mgr,
                             checkpoint_interval=1, resume=True)
    np.testing.assert_array_equal(final.coefficient, golden.coefficient)


# ---------------------------------------------------------------------------
# Dataset-fed trainers (ISSUE 5): cursor checkpoint/resume through the
# input-pipeline subsystem, shuffle order preserved across the kill
# ---------------------------------------------------------------------------

def _lr_dataset(seed=0, shuffled=True):
    """The lr_batches feed as a flinkml_tpu.data pipeline: one source
    table, rebatched, with a seeded shuffle — the shape whose resume
    parity only holds if the cursor machinery replays the exact
    shuffled sequence."""
    from flinkml_tpu.data import Dataset
    from flinkml_tpu.table import Table as T

    rows = np.concatenate([np.asarray(b.column("features"))
                           for b in lr_batches(seed=seed)])
    labels = np.concatenate([np.asarray(b.column("label"))
                             for b in lr_batches(seed=seed)])
    ds = Dataset.from_arrays(
        T({"features": rows, "label": labels}), batch_size=48
    )
    return ds.shuffle(4, seed=13) if shuffled else ds


def test_dataset_shuffled_kill_corrupt_resume_bit_exact(tmp_path):
    """The ISSUE 5 acceptance criterion: a Dataset-fed
    OnlineLogisticRegression.fit_stream with a SHUFFLED pipeline, killed
    mid-stream (RaiseAtEpoch through the iteration seam), newest cursor
    snapshot corrupted, resumed from the prior valid one — bit-identical
    to the uninterrupted run, shuffle order preserved across the kill."""
    golden = _lr().fit_stream(_lr_dataset())

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(CRASH_EPOCH))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(_lr_dataset(), checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL)
    assert mgr.latest_epoch() == CRASH_EPOCH - 1
    faults.corrupt_latest(mgr, target="arrays")

    recovered = _lr().fit_stream(_lr_dataset(), checkpoint_manager=mgr,
                                 checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(recovered.coefficient, golden.coefficient)
    assert recovered.model_version == golden.model_version == N_BATCHES
    # The restored snapshot carried the pipeline cursor (epoch 4's
    # commit — the newest valid one after corrupting epoch 6's).
    cursor = mgr.last_restored_extra["data_cursor"]
    assert cursor["emitted"] == 4
    assert cursor["shuffle"] is not None


def test_dataset_kill_at_read_seam_resume_bit_exact(tmp_path):
    """Same parity with the crash at the NEW data.read seam — the
    source itself dies mid-stream rather than the training loop.
    (Unshuffled feed so the read count maps 1:1 to emitted batches:
    the trainer's peek costs read #1, the fit re-reads from the start,
    so read #10 kills after epoch 8 completed — past the epoch-8
    interval commit.)"""
    golden = _lr().fit_stream(_lr_dataset(seed=31, shuffled=False))

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.RaiseAtRead(at_read=10))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(_lr_dataset(seed=31, shuffled=False),
                             checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL)
    assert mgr.latest_epoch() == 8
    recovered = _lr().fit_stream(_lr_dataset(seed=31, shuffled=False),
                                 checkpoint_manager=mgr,
                                 checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(recovered.coefficient, golden.coefficient)
    assert recovered.model_version == golden.model_version


def test_dataset_fed_kmeans_and_scaler_resume_bit_exact(tmp_path):
    """The other two online trainers accept a Dataset anywhere an
    iterator is accepted, with the same kill+resume parity."""
    from flinkml_tpu.data import Dataset
    from flinkml_tpu.table import Table as T

    km_rows = np.concatenate([np.asarray(b.column("features"))
                              for b in km_batches()])

    def km_ds():
        return Dataset.from_arrays(T({"features": km_rows}), batch_size=40)

    golden = _km().fit_stream(km_ds())
    mgr = CheckpointManager(str(tmp_path / "km"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(CRASH_EPOCH))):
        with pytest.raises(faults.FaultInjected):
            _km().fit_stream(km_ds(), checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL)
    faults.corrupt_latest(mgr, target="manifest")
    recovered = _km().fit_stream(km_ds(), checkpoint_manager=mgr,
                                 checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(recovered.centroids, golden.centroids)

    sc_rows = np.concatenate([np.asarray(b.column("input"))
                              for b in sc_batches()])

    def sc_ds():
        return Dataset.from_arrays(T({"input": sc_rows}), batch_size=32)

    sc_golden = _sc().fit_stream(sc_ds())
    sc_mgr = CheckpointManager(str(tmp_path / "sc"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(CRASH_EPOCH))):
        with pytest.raises(faults.FaultInjected):
            _sc().fit_stream(sc_ds(), checkpoint_manager=sc_mgr,
                             checkpoint_interval=INTERVAL)
    sc_rec = _sc().fit_stream(sc_ds(), checkpoint_manager=sc_mgr,
                              checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(sc_rec._mean, sc_golden._mean)
    np.testing.assert_array_equal(sc_rec._std, sc_golden._std)


def test_dataset_fed_prefetched_fit_matches_plain(tmp_path):
    """A prefetch tail (device-resident bucket-padded batches) changes
    nothing about the fitted model — and the fit closes the worker."""
    import threading

    golden = _lr().fit_stream(_lr_dataset(seed=41, shuffled=False))
    fed = _lr().fit_stream(
        _lr_dataset(seed=41, shuffled=False).prefetch(depth=2)
    )
    np.testing.assert_array_equal(fed.coefficient, golden.coefficient)
    assert not any(
        t.name.startswith("data-prefetch") and t.is_alive()
        for t in threading.enumerate()
    )


# ---------------------------------------------------------------------------
# SIGTERM watchdog
# ---------------------------------------------------------------------------

class _DrainRecorder:
    def __init__(self):
        self.stopped = []

    def stop(self, drain=True, timeout=None):
        self.stopped.append(drain)


def test_watchdog_preempts_online_fit_and_resumes(tmp_path):
    """Preemption mid-fit_stream: the ambient watchdog stops the loop at
    an epoch boundary, a final checkpoint commits, registered engines
    drain, and a later resume converges to the uninterrupted model."""
    batches = lr_batches(seed=7)
    golden = _lr().fit_stream(batches)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    engine = _DrainRecorder()
    wd = PreemptionWatchdog(signals=())
    wd.register_engine(engine)

    # Deterministic trigger: request preemption when epoch 5's transfer
    # seam fires (the fit is mid-stream).
    class _RequestAt(faults.Fault):
        site = "iteration.epoch"

        def should_fire(self, ctx):
            return ctx.get("epoch") == 5

        def apply(self, ctx):
            wd.request("scripted preemption")

    with wd:
        with faults.armed(faults.FaultPlan(_RequestAt())):
            preempted_model = _lr().fit_stream(
                batches, checkpoint_manager=mgr, checkpoint_interval=INTERVAL,
            )
    # The loop stopped at the epoch-5 boundary with a terminal snapshot
    # and drained the engine; the partial model is the epoch-5 state.
    assert mgr.latest_epoch() == 5
    assert engine.stopped == [True]
    assert preempted_model.model_version == 5

    resumed = _lr().fit_stream(batches, checkpoint_manager=mgr,
                               checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(resumed.coefficient, golden.coefficient)
    assert resumed.model_version == N_BATCHES


def test_multiprocess_checkpoint_rejected_cleanly(tmp_path, monkeypatch):
    """The multi-process online path declares checkpoint support not
    wired rather than failing deep inside the synced stream."""
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(NotImplementedError, match="multi-process"):
        _lr().fit_stream(lr_batches(n=2),
                         checkpoint_manager=CheckpointManager(
                             str(tmp_path / "c")))
