"""OneHotEncoder tests — mirrors the reference's OneHotEncoderTest."""

import numpy as np
import pytest

from flinkml_tpu.models import OneHotEncoder, OneHotEncoderModel
from flinkml_tpu.table import Table


@pytest.fixture
def train_table():
    return Table({"c1": np.array([0.0, 1.0, 2.0, 2.0]), "c2": np.array([0.0, 1.0, 0.0, 1.0])})


def make_encoder():
    return OneHotEncoder().set_input_cols(["c1", "c2"]).set_output_cols(["o1", "o2"])


def test_drop_last_default(train_table):
    model = make_encoder().fit(train_table)
    (out,) = model.transform(train_table)
    # c1 has max index 2 -> size 2 with dropLast; value 2 -> all zeros.
    np.testing.assert_array_equal(
        out["o1"], [[1, 0], [0, 1], [0, 0], [0, 0]]
    )
    # c2 max index 1 -> size 1; value 1 -> empty.
    np.testing.assert_array_equal(out["o2"], [[1], [0], [1], [0]])


def test_without_drop_last(train_table):
    model = make_encoder().set_drop_last(False).fit(train_table)
    (out,) = model.transform(train_table)
    np.testing.assert_array_equal(
        out["o1"], [[1, 0, 0], [0, 1, 0], [0, 0, 1], [0, 0, 1]]
    )


def test_error_on_out_of_range(train_table):
    model = make_encoder().fit(train_table)
    bad = Table({"c1": np.array([5.0]), "c2": np.array([0.0])})
    with pytest.raises(ValueError, match="categories outside"):
        model.transform(bad)


def test_error_on_unseen_category_without_drop_last(train_table):
    """Without dropLast there is no all-zero encoding: maxIndex+1 is an
    unseen category and must error (not silently encode as zeros)."""
    model = make_encoder().set_drop_last(False).fit(train_table)
    bad = Table({"c1": np.array([3.0]), "c2": np.array([0.0])})
    with pytest.raises(ValueError, match="categories outside"):
        model.transform(bad)
    # And under 'keep' it goes to the catch-all slot, not the zero vector.
    keep_model = (
        make_encoder().set_drop_last(False).set_handle_invalid("keep")
        .fit(train_table)
    )
    (out,) = keep_model.transform(bad)
    np.testing.assert_array_equal(out["o1"][0], [0, 0, 0, 1])


def test_error_on_non_integer(train_table):
    model = make_encoder().fit(train_table)
    bad = Table({"c1": np.array([0.5]), "c2": np.array([0.0])})
    with pytest.raises(ValueError, match="indexed integer"):
        model.transform(bad)


def test_keep_invalid(train_table):
    model = make_encoder().set_handle_invalid("keep").fit(train_table)
    bad = Table({"c1": np.array([0.0, 7.0, 2.0]), "c2": np.array([0.0, 0.0, 0.0])})
    (out,) = model.transform(bad)
    # keep: extra catch-all category at the end.
    assert out["o1"].shape == (3, 3)
    np.testing.assert_array_equal(out["o1"][1], [0, 0, 1])
    # The VALID dropped-last category (2) keeps its all-zero encoding and
    # stays distinguishable from invalid values.
    np.testing.assert_array_equal(out["o1"][2], [0, 0, 0])


def test_skip_invalid_rejected(train_table):
    model = make_encoder().set_handle_invalid("skip").fit(train_table)
    with pytest.raises(ValueError, match="skip"):
        model.transform(train_table)


def test_negative_category_rejected():
    t = Table({"c1": np.array([-1.0, 0.0])})
    with pytest.raises(ValueError, match="negative"):
        OneHotEncoder().set_input_cols(["c1"]).set_output_cols(["o1"]).fit(t)


def test_missing_input_cols():
    with pytest.raises(ValueError, match="inputCols"):
        OneHotEncoder().fit(Table({"c1": np.array([0.0])}))


def test_save_load(tmp_path, train_table):
    model = make_encoder().fit(train_table)
    p = str(tmp_path / "ohe")
    model.save(p)
    loaded = OneHotEncoderModel.load(p)
    assert loaded.get_input_cols() == ["c1", "c2"]
    (a,) = model.transform(train_table)
    (b,) = loaded.transform(train_table)
    np.testing.assert_array_equal(a["o1"], b["o1"])


def test_model_data_round_trip(train_table):
    model = make_encoder().fit(train_table)
    other = (
        OneHotEncoderModel()
        .set_input_cols(["c1", "c2"])
        .set_output_cols(["o1", "o2"])
        .set_model_data(*model.get_model_data())
    )
    (a,) = model.transform(train_table)
    (b,) = other.transform(train_table)
    np.testing.assert_array_equal(a["o2"], b["o2"])


def test_sparse_output_format(train_table):
    """outputFormat='sparse': the reference's exact encoding
    (OneHotEncoderModel.java:160-183) — SparseVector(size, [v], [1.0]),
    empty vector for the dropped-last value."""
    from flinkml_tpu.linalg import SparseVector

    model = make_encoder().set_output_format("sparse").fit(train_table)
    (out,) = model.transform(train_table)
    o1 = out["o1"]
    assert o1.dtype == object and isinstance(o1[0], SparseVector)
    # c1 = [0, 1, 2, 2], max 2 -> size 2 with dropLast; 2 -> empty vector.
    assert o1[0].size() == 2
    np.testing.assert_array_equal(o1[0].indices, [0])
    np.testing.assert_array_equal(o1[0].values, [1.0])
    np.testing.assert_array_equal(o1[1].indices, [1])
    assert o1[2].indices.size == 0 and o1[3].indices.size == 0
    # Sparse and dense encodings agree elementwise.
    (dense_out,) = make_encoder().fit(train_table).transform(train_table)
    for sv, row in zip(o1, dense_out["o1"]):
        np.testing.assert_array_equal(sv.to_array(), row)


def test_sparse_output_keep_invalid(train_table):
    model = (
        make_encoder().set_output_format("sparse")
        .set_handle_invalid("keep").fit(train_table)
    )
    bad = Table({"c1": np.array([7.0]), "c2": np.array([0.0])})
    (out,) = model.transform(bad)
    sv = out["o1"][0]
    assert sv.size() == 3  # catch-all slot appended
    np.testing.assert_array_equal(sv.indices, [2])


def test_invalid_output_format_rejected(train_table):
    with pytest.raises(ValueError):
        make_encoder().set_output_format("coo")


def test_high_cardinality_sparse_to_sparse_lr():
    """Cardinality 2e6: dense output would need n·cardinality·8 bytes
    (8 GB at n=500 — guaranteed OOM); the sparse encoding is O(n) and
    feeds the sparse LogisticRegression path end-to-end (round-1 VERDICT
    "missing" #4/#5)."""
    from flinkml_tpu.models import LogisticRegression
    from flinkml_tpu.pipeline import Pipeline

    card = 2_000_000
    n = 500
    rng = np.random.default_rng(3)
    # Categories drawn from the full range; a planted subset is positive.
    cats = rng.integers(0, card, size=n).astype(np.float64)
    cats[-1] = card - 1  # pin the max so the fitted size is the cardinality
    positive = cats >= card // 2
    t = Table({"c1": cats, "label": positive.astype(np.float64)})

    dense_bytes = n * card * 8
    assert dense_bytes > 4 * 2**30  # the dense layout would be absurd

    encoder = (
        OneHotEncoder().set_input_cols(["c1"]).set_output_cols(["features"])
        .set_drop_last(False).set_output_format("sparse")
    )
    model = encoder.fit(t)
    (enc,) = model.transform(t)
    assert enc["features"][0].size() == card

    # One-hot features are memorization features: LR must fit the train
    # labels (each category has its own weight).
    pipeline = Pipeline([
        encoder,
        LogisticRegression().set_seed(0).set_max_iter(150)
        .set_learning_rate(5.0).set_global_batch_size(n),
    ])
    pm = pipeline.fit(t)
    (out,) = pm.transform(t)
    assert np.mean(out["prediction"] == t["label"]) > 0.95


def test_in_pipeline_with_lr(train_table):
    """OneHotEncoder -> LogisticRegression chained in a Pipeline (the
    reference's canonical pipeline composition)."""
    from flinkml_tpu.models import LogisticRegression
    from flinkml_tpu.pipeline import Pipeline

    rng = np.random.default_rng(0)
    c = rng.integers(0, 3, size=80).astype(np.float64)
    y = (c == 2).astype(np.float64)
    t = Table({"c1": c, "label": y})
    pipeline = Pipeline(
        [
            OneHotEncoder().set_input_cols(["c1"]).set_output_cols(["features"]).set_drop_last(False),
            LogisticRegression().set_seed(0).set_max_iter(200).set_learning_rate(1.0),
        ]
    )
    pm = pipeline.fit(t)
    (out,) = pm.transform(t)
    assert np.mean(out["prediction"] == y) == 1.0
