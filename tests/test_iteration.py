"""Iteration runtime tests — the analog of the reference's iteration ITCases
(``BoundedAllRoundStreamIterationITCase``, ``UnboundedStreamIterationITCase``,
``BoundedAllRoundCheckpointITCase`` fault injection; SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flinkml_tpu.iteration import (
    CheckpointManager,
    IterationConfig,
    IterationListener,
    Iterations,
    TerminateOnMaxIter,
    TerminateOnMaxIterOrTol,
    device_iterate,
    iterate,
)


def test_bounded_replay_sum():
    # Analog of BoundedAllRoundStreamIterationITCase: 4 "sources" x 1000
    # records, replayed 5 rounds; the state accumulates the global sum.
    records = np.arange(4000, dtype=np.float64)

    def step(state, data, epoch):
        return state + data.sum(), None

    result = Iterations.iterate_bounded_streams_until_termination(
        step, 0.0, records, IterationConfig(TerminateOnMaxIter(5))
    )
    assert result.epochs == 5
    assert result.state == pytest.approx(5 * records.sum())


def test_terminate_on_tol():
    # criteria halves each epoch; tol hits before max_iter.
    def step(state, epoch):
        new = state / 2.0
        return new, new

    result = iterate(step, 1.0, config=IterationConfig(TerminateOnMaxIterOrTol(100, 0.01)))
    assert result.state <= 0.01
    assert result.epochs == 7  # 1/2^7 ≈ 0.0078 <= 0.01
    assert result.criteria_history[-1] <= 0.01


def test_max_iter_validation():
    with pytest.raises(ValueError):
        TerminateOnMaxIter(0)
    with pytest.raises(ValueError):
        TerminateOnMaxIterOrTol(0, 0.1)


def test_listeners_called_per_epoch():
    events = []

    class Recorder(IterationListener):
        def on_epoch_watermark_incremented(self, epoch, state):
            events.append(("epoch", epoch, state))

        def on_iteration_terminated(self, state):
            events.append(("terminated", state))

    def step(state, epoch):
        return state + 1, None

    iterate(step, 0, config=IterationConfig(TerminateOnMaxIter(3)), listeners=[Recorder()])
    assert events == [
        ("epoch", 0, 1),
        ("epoch", 1, 2),
        ("epoch", 2, 3),
        ("terminated", 3),
    ]


def test_unbounded_stream_consumes_once_each():
    # Analog of UnboundedStreamIterationITCase: one batch per epoch,
    # terminates when the stream ends.
    batches = [np.full(10, i, dtype=np.float64) for i in range(4)]

    def step(state, batch, epoch):
        return state + batch.sum(), None

    result = Iterations.iterate_unbounded_streams(
        step, 0.0, batches, IterationConfig(TerminateOnMaxIter(100))
    )
    assert result.epochs == 4
    assert result.state == pytest.approx(sum(b.sum() for b in batches))


def test_callable_data_provider_stops_on_none():
    def provider(epoch):
        return np.ones(3) if epoch < 6 else None

    def step(state, batch, epoch):
        return state + batch.sum(), None

    result = iterate(step, 0.0, provider, IterationConfig(TerminateOnMaxIter(100)))
    assert result.epochs == 6
    assert result.state == 18.0


def test_outputs_collected():
    def step(state, epoch):
        return state + 1, None, state * 10

    result = iterate(step, 0, config=IterationConfig(TerminateOnMaxIter(3)))
    assert result.outputs == [0, 10, 20]


def test_jitted_step():
    @jax.jit
    def step(state, data, epoch):
        new = state + jnp.sum(data)
        return new, jnp.abs(new)

    result = iterate(
        step,
        jnp.asarray(0.0),
        jnp.ones(8),
        IterationConfig(TerminateOnMaxIter(4)),
    )
    assert float(result.state) == 32.0


def test_device_iterate_max_iter():
    def step(state, epoch):
        return state + 1.0, jnp.asarray(1e9)

    state, epochs, _ = device_iterate(step, jnp.asarray(0.0), max_iter=10)
    assert float(state) == 10.0 and int(epochs) == 10


def test_device_iterate_tol():
    def step(state, epoch):
        new = state / 2.0
        return new, new

    state, epochs, crit = device_iterate(step, jnp.asarray(1.0), max_iter=100, tol=0.01)
    assert int(epochs) == 7
    assert float(crit) <= 0.01


# ---------------------------------------------------------------------------
# Checkpoint / resume / fault injection
# ---------------------------------------------------------------------------

def test_checkpoint_save_restore(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    state = {"w": np.arange(5.0), "rng": jax.random.key_data(jax.random.key(0))}
    mgr.save(state, epoch=3)
    restored, epoch = mgr.restore_latest(like=state)
    assert epoch == 3
    np.testing.assert_array_equal(restored["w"], state["w"])
    np.testing.assert_array_equal(restored["rng"], state["rng"])


def test_async_checkpoint_matches_sync(tmp_path):
    """async_write=True commits identically to the synchronous manager;
    readers drain the in-flight write."""
    state = {"w": np.arange(6.0), "e": np.float64(1.5)}
    sync = CheckpointManager(str(tmp_path / "s"))
    anc = CheckpointManager(str(tmp_path / "a"), async_write=True)
    for epoch in (1, 2, 3):
        sync.save(state, epoch)
        anc.save(state, epoch)
    assert anc.all_epochs() == sync.all_epochs() == [1, 2, 3]
    ra, ea = anc.restore_latest(like=state)
    rs, es = sync.restore_latest(like=state)
    assert ea == es == 3
    np.testing.assert_array_equal(ra["w"], rs["w"])


def test_async_checkpoint_failover_exact(tmp_path):
    """The chunked-failover contract holds with async writes: crash,
    resume, bit-exact result."""
    from flinkml_tpu.models.logistic_regression import train_logistic_regression
    from flinkml_tpu.parallel import DeviceMesh

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 3))
    y = (x[:, 0] > 0).astype(np.float64)
    w = np.ones(64)
    kw = dict(mesh=DeviceMesh(), max_iter=30, learning_rate=0.5,
              global_batch_size=64, reg=0.0, tol=0.0, seed=5)
    golden = train_logistic_regression(x, y, w, **kw)
    mgr = CheckpointManager(str(tmp_path), async_write=True)
    train_logistic_regression(
        x, y, w, **{**kw, "max_iter": 12},
        checkpoint_manager=mgr, checkpoint_interval=6,
    )
    assert mgr.latest_epoch() == 12
    resumed = train_logistic_regression(
        x, y, w, **kw, checkpoint_manager=mgr, checkpoint_interval=6,
        resume=True,
    )
    np.testing.assert_allclose(resumed, golden, atol=0)


def test_async_checkpoint_snapshots_before_mutation(tmp_path):
    """The async snapshot must own its memory: mutating the saved arrays
    after save() returns cannot leak into the checkpoint."""
    mgr = CheckpointManager(str(tmp_path), async_write=True)
    state = {"w": np.arange(5.0)}
    mgr.save(state, epoch=1)
    state["w"] += 100.0  # caller mutates immediately (in-place training)
    restored, _ = mgr.restore(1, like=state)
    np.testing.assert_array_equal(restored["w"], np.arange(5.0))
    mgr.close()


def test_async_checkpoint_close_idempotent(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_write=True)
    mgr.save({"w": np.ones(2)}, epoch=1)
    mgr.close()
    mgr.close()
    # Still usable after close: a later save re-creates the writer.
    mgr.save({"w": np.ones(2)}, epoch=2)
    assert mgr.all_epochs() == [1, 2]
    mgr.close()


def test_async_checkpoint_write_error_surfaces(tmp_path):
    import shutil

    target = tmp_path / "ckpts"
    mgr = CheckpointManager(str(target), async_write=True)
    mgr.save({"w": np.ones(2)}, epoch=1)
    mgr.wait()
    # Remove the directory out from under the manager so the background
    # write fails; the error must surface on the next wait()/save().
    shutil.rmtree(target)
    mgr.save({"w": np.ones(2)}, epoch=2)  # submitted; fails in background
    with pytest.raises(OSError):
        mgr.wait()


def test_checkpoint_prune(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    for e in range(5):
        mgr.save({"x": np.array([e])}, epoch=e)
    assert mgr.all_epochs() == [3, 4]


def test_checkpoint_structure_mismatch(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save({"a": np.ones(2), "b": np.ones(3)}, epoch=0)
    with pytest.raises(ValueError):
        mgr.restore(0, like={"a": np.ones(2)})


def test_periodic_checkpoint_during_iterate(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=100)

    def step(state, epoch):
        return state + 1, None

    iterate(
        step,
        0,
        config=IterationConfig(
            TerminateOnMaxIter(10), checkpoint_interval=3, checkpoint_manager=mgr
        ),
    )
    # epochs 3, 6, 9 plus the terminal epoch 10.
    assert mgr.all_epochs() == [3, 6, 9, 10]


def test_failover_resume_exact(tmp_path):
    """The BoundedAllRoundCheckpointITCase analog: fail mid-iteration on the
    first attempt, resume from checkpoint, final result must be EXACTLY the
    no-failure result."""
    records = np.arange(100, dtype=np.float64)

    def make_step(fail_at_epoch):
        calls = {"n": 0}

        def step(state, data, epoch):
            if fail_at_epoch is not None and epoch == fail_at_epoch:
                raise RuntimeError("injected failure")
            return state + data.sum() * (epoch + 1), None

        return step

    config = lambda mgr: IterationConfig(
        TerminateOnMaxIter(8), checkpoint_interval=2, checkpoint_manager=mgr
    )

    # Golden: no failure.
    golden = iterate(
        make_step(None), 0.0, records, config(CheckpointManager(str(tmp_path / "g")))
    )

    # Attempt 0: fails at epoch 5 (after the epoch-4 checkpoint).
    mgr = CheckpointManager(str(tmp_path / "f"))
    with pytest.raises(RuntimeError):
        iterate(make_step(5), 0.0, records, config(mgr))
    assert mgr.latest_epoch() == 4

    # Attempt 1: resume; must converge to the exact same state.
    result = iterate(make_step(None), 0.0, records, config(mgr), resume=True)
    assert result.state == golden.state
    assert mgr.latest_epoch() == 8


def test_stream_resume_replay_vs_continue(tmp_path):
    """Resumed iterable streams: 'replay' (default) skips the consumed
    epochs of a restartable source; 'continue' consumes a live one-shot
    stream from the front instead of silently dropping its batches."""
    step = lambda s, data, epoch: (s + float(data), None)

    def run(mode, stream):
        mgr = CheckpointManager(str(tmp_path / mode))
        # Pretend epochs 0-1 already consumed batches 10, 20 (sum 30).
        mgr.save(30.0, epoch=2)
        return iterate(
            step, 0.0, stream,
            IterationConfig(TerminateOnMaxIter(4), checkpoint_manager=mgr,
                            stream_resume=mode),
            resume=True,
        ).state

    # Replayable source restarts from the beginning: epochs 2..3 must see
    # batches 2..3 (30, 40), not re-consume 10, 20.
    assert run("replay", [10.0, 20.0, 30.0, 40.0]) == 100.0
    # A live one-shot stream is already positioned at "now": consume from
    # the front — 'replay' would have skipped (dropped) 30 and 40 and
    # ended at 30.0.
    assert run("continue", iter([30.0, 40.0])) == 100.0


def test_stream_resume_invalid_mode():
    with pytest.raises(ValueError, match="stream_resume"):
        iterate(
            lambda s, d, e: (s, None), 0, [1.0],
            IterationConfig(TerminateOnMaxIter(1), stream_resume="bogus"),
        )


def test_resume_without_manager_raises():
    with pytest.raises(ValueError):
        iterate(lambda s, e: (s, None), 0, resume=True)


def test_resume_with_empty_dir_starts_fresh(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    result = iterate(
        lambda s, e: (s + 1, None),
        0,
        config=IterationConfig(TerminateOnMaxIter(3), checkpoint_manager=mgr),
        resume=True,
    )
    assert result.state == 3


def test_rescale_guard_on_restore(tmp_path, monkeypatch):
    """Reference parity: restoring under a different device count is
    rejected (HeadOperator.java:130-146) unless explicitly allowed."""
    import json
    import os

    from flinkml_tpu.iteration.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    state = {"coef": np.arange(4.0)}
    mgr.save(state, epoch=3)
    # Tamper the recorded world size to simulate a different pod shape.
    meta_path = os.path.join(str(tmp_path), "ckpt-3", "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["world_size"] = meta["world_size"] + 1
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    with pytest.raises(ValueError, match="rescal"):
        mgr.restore(3, like=state)
    relaxed = CheckpointManager(str(tmp_path), allow_rescale=True)
    restored, epoch = relaxed.restore(3, like=state)
    assert epoch == 3
    np.testing.assert_array_equal(restored["coef"], state["coef"])


def test_rescale_guard_uses_mesh_world_size(tmp_path):
    """A manager pinned to its mesh size ignores the process device count."""
    from flinkml_tpu.iteration.checkpoint import CheckpointManager

    state = {"w": np.ones(2)}
    writer = CheckpointManager(str(tmp_path), world_size=4)
    writer.save(state, epoch=1)
    # Same mesh size on restore -> fine, regardless of jax.device_count().
    ok = CheckpointManager(str(tmp_path), world_size=4)
    _, epoch = ok.restore(1, like=state)
    assert epoch == 1
    # Different mesh size -> rejected.
    bad = CheckpointManager(str(tmp_path), world_size=2)
    with pytest.raises(ValueError, match="rescal"):
        bad.restore(1, like=state)
