"""TP/PP primitives vs single-device references, on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.parallel.tensor import (
    pipeline_parallel_apply,
    register_pipeline_stage,
    tensor_parallel_mlp,
)


def test_tp_mlp_matches_dense():
    rng = np.random.default_rng(0)
    d_in, d_ff, d_out, n = 16, 64, 16, 32
    x = rng.normal(size=(n, d_in)).astype(np.float32)
    w1 = rng.normal(size=(d_in, d_ff)).astype(np.float32)
    b1 = rng.normal(size=(d_ff,)).astype(np.float32)
    w2 = rng.normal(size=(d_ff, d_out)).astype(np.float32)
    b2 = rng.normal(size=(d_out,)).astype(np.float32)

    out = tensor_parallel_mlp(
        x, w1, b1, w2, b2, DeviceMesh({"model": 8}), axis="model"
    )
    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_tp_mlp_on_2d_mesh_model_axis():
    """TP must address its named axis on a multi-axis mesh."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    w1 = rng.normal(size=(4, 16)).astype(np.float32)
    b1 = np.zeros(16, np.float32)
    w2 = rng.normal(size=(16, 4)).astype(np.float32)
    b2 = np.zeros(4, np.float32)
    mesh = DeviceMesh({"data": 2, "model": 4})
    out = tensor_parallel_mlp(x, w1, b1, w2, b2, mesh, axis="model")
    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_tp_mlp_validates_d_ff():
    x = np.zeros((2, 4), np.float32)
    w1 = np.zeros((4, 10), np.float32)  # 10 not divisible by 8
    with pytest.raises(ValueError, match="divide"):
        tensor_parallel_mlp(x, w1, np.zeros(10, np.float32),
                            np.zeros((10, 4), np.float32),
                            np.zeros(4, np.float32), DeviceMesh({"model": 8}))


def test_tp_mlp_validates_axis_name():
    x = np.zeros((2, 4), np.float32)
    with pytest.raises(ValueError, match="no axis named"):
        tensor_parallel_mlp(x, np.zeros((4, 8), np.float32),
                            np.zeros(8, np.float32),
                            np.zeros((8, 4), np.float32),
                            np.zeros(4, np.float32),
                            DeviceMesh({"data": 8}), axis="model")


def test_pipeline_matches_sequential():
    rng = np.random.default_rng(2)
    n_stages, n_mb, b, d = 8, 6, 4, 8
    params = rng.normal(size=(n_stages, d, d)).astype(np.float32) * 0.3
    x = rng.normal(size=(n_mb, b, d)).astype(np.float32)

    out = pipeline_parallel_apply(
        x, params, stage="linear_tanh", mesh=DeviceMesh({"pipe": 8})
    )
    ref = x
    for s in range(n_stages):
        ref = np.tanh(ref @ params[s])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_pipeline_validates_stage_count():
    x = np.zeros((2, 2, 4), np.float32)
    params = np.zeros((3, 4, 4), np.float32)  # 3 stages on an 8-wide axis
    with pytest.raises(ValueError, match="stages"):
        pipeline_parallel_apply(x, params, stage="linear_tanh",
                                mesh=DeviceMesh({"pipe": 8}))


def test_pipeline_unknown_stage():
    x = np.zeros((2, 2, 4), np.float32)
    params = np.zeros((8, 4, 4), np.float32)
    with pytest.raises(ValueError, match="unknown"):
        pipeline_parallel_apply(x, params, stage="nope",
                                mesh=DeviceMesh({"pipe": 8}))


def test_custom_registered_stage():
    register_pipeline_stage("affine_relu", lambda a, p: jnp.maximum(a @ p, 0))
    rng = np.random.default_rng(3)
    params = rng.normal(size=(8, 4, 4)).astype(np.float32) * 0.4
    x = rng.normal(size=(3, 2, 4)).astype(np.float32)
    out = pipeline_parallel_apply(x, params, stage="affine_relu",
                                  mesh=DeviceMesh({"pipe": 8}))
    ref = x
    for s in range(8):
        ref = np.maximum(ref @ params[s], 0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_expert_parallel_matches_dense_moe():
    from flinkml_tpu.parallel.tensor import expert_parallel_ffn

    rng = np.random.default_rng(4)
    n, d_in, d_ff, d_out, E = 16, 8, 32, 8, 8
    x = rng.normal(size=(n, d_in)).astype(np.float32)
    w1 = rng.normal(size=(E, d_in, d_ff)).astype(np.float32) * 0.3
    w2 = rng.normal(size=(E, d_ff, d_out)).astype(np.float32) * 0.3
    logits = rng.normal(size=(n, E)).astype(np.float32)
    gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))

    out = expert_parallel_ffn(x, gates, w1, w2, DeviceMesh({"expert": 8}))
    ref = np.zeros((n, d_out), np.float32)
    for e in range(E):
        ref += gates[:, e:e + 1] * np.asarray(
            jax.nn.gelu(x @ w1[e]) @ w2[e]
        )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_expert_parallel_top1_routing():
    from flinkml_tpu.parallel.tensor import expert_parallel_ffn

    rng = np.random.default_rng(5)
    n, E = 8, 8
    x = rng.normal(size=(n, 4)).astype(np.float32)
    w1 = rng.normal(size=(E, 4, 8)).astype(np.float32)
    w2 = rng.normal(size=(E, 8, 4)).astype(np.float32)
    assign = rng.integers(0, E, size=n)
    gates = np.eye(E, dtype=np.float32)[assign]  # hard top-1
    out = np.asarray(
        expert_parallel_ffn(x, gates, w1, w2, DeviceMesh({"expert": 8}))
    )
    for i in range(n):
        e = assign[i]
        ref = np.asarray(jax.nn.gelu(x[i] @ w1[e]) @ w2[e])
        np.testing.assert_allclose(out[i], ref, rtol=2e-4, atol=2e-5)


def test_expert_parallel_validates_expert_count():
    from flinkml_tpu.parallel.tensor import expert_parallel_ffn

    with pytest.raises(ValueError, match="expert count"):
        expert_parallel_ffn(
            np.zeros((2, 4), np.float32), np.zeros((2, 3), np.float32),
            np.zeros((3, 4, 8), np.float32), np.zeros((3, 8, 4), np.float32),
            DeviceMesh({"expert": 8}),
        )


def test_pipeline_on_multi_axis_mesh():
    rng = np.random.default_rng(6)
    params = (rng.normal(size=(4, 5, 5)) * 0.3).astype(np.float32)
    x = rng.normal(size=(3, 2, 5)).astype(np.float32)
    out = pipeline_parallel_apply(
        x, params, stage="linear_tanh",
        mesh=DeviceMesh({"data": 2, "pipe": 4}), axis="pipe",
    )
    ref = x
    for s in range(4):
        ref = np.tanh(ref @ params[s])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_expert_parallel_on_multi_axis_mesh():
    from flinkml_tpu.parallel.tensor import expert_parallel_ffn

    rng = np.random.default_rng(7)
    n, E = 6, 4
    x = rng.normal(size=(n, 4)).astype(np.float32)
    w1 = (rng.normal(size=(E, 4, 8)) * 0.3).astype(np.float32)
    w2 = (rng.normal(size=(E, 8, 4)) * 0.3).astype(np.float32)
    gates = np.asarray(
        jax.nn.softmax(jnp.asarray(rng.normal(size=(n, E)).astype(np.float32)), -1)
    )
    out = expert_parallel_ffn(
        x, gates, w1, w2, DeviceMesh({"data": 2, "expert": 4}), axis="expert"
    )
    ref = sum(
        gates[:, e:e + 1] * np.asarray(jax.nn.gelu(x @ w1[e]) @ w2[e])
        for e in range(E)
    )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_stage_reregistration_takes_effect():
    """Regression: re-registering a stage name must recompile, not reuse
    the old function from the jit cache."""
    register_pipeline_stage("mutable_stage", lambda a, p: a @ p)
    params = np.stack([np.eye(4, dtype=np.float32)] * 8)
    x = np.ones((2, 2, 4), np.float32)
    out1 = np.asarray(pipeline_parallel_apply(
        x, params, "mutable_stage", DeviceMesh({"pipe": 8})))
    register_pipeline_stage("mutable_stage", lambda a, p: (a @ p) * 2.0)
    out2 = np.asarray(pipeline_parallel_apply(
        x, params, "mutable_stage", DeviceMesh({"pipe": 8})))
    np.testing.assert_allclose(out2, out1 * 256.0)  # 2^8 over 8 stages


def _moe_ref(x, logits, w1, w2, n_local, p_size, capacity):
    """Per-device top-1 routed reference with capacity dropping."""
    import scipy.special as sp

    probs = sp.softmax(logits, axis=-1)
    expert = probs.argmax(-1)
    gate = probs.max(-1)
    out = np.zeros((x.shape[0], w2.shape[2]), np.float32)
    for dev in range(p_size):
        lo, hi = dev * n_local, (dev + 1) * n_local
        counts = np.zeros(logits.shape[1], np.int64)
        for i in range(lo, hi):
            e = expert[i]
            if counts[e] < capacity:
                h = np.asarray(jax.nn.gelu(x[i] @ w1[e]))
                out[i] = gate[i] * (h @ w2[e])
            counts[e] += 1
    return out


def test_routed_expert_matches_reference_with_drops():
    from flinkml_tpu.parallel.tensor import routed_expert_ffn

    rng = np.random.default_rng(8)
    P_SIZE, n, d, ff = 8, 64, 4, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = (rng.normal(size=(n, P_SIZE)) * 2).astype(np.float32)
    w1 = (rng.normal(size=(P_SIZE, d, ff)) * 0.4).astype(np.float32)
    w2 = (rng.normal(size=(P_SIZE, ff, d)) * 0.4).astype(np.float32)
    cf = 0.5  # deliberately tight: forces drops
    out = np.asarray(routed_expert_ffn(
        x, logits, w1, w2, DeviceMesh({"expert": P_SIZE}),
        capacity_factor=cf,
    ))
    n_local = n // P_SIZE
    capacity = max(1, int(np.ceil(n_local * cf / P_SIZE)))
    ref = _moe_ref(x, logits, w1, w2, n_local, P_SIZE, capacity)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_routed_expert_no_drops_matches_dense_top1():
    """With generous capacity, routed == dense dispatch with hard top-1."""
    from flinkml_tpu.parallel.tensor import expert_parallel_ffn, routed_expert_ffn
    import scipy.special as sp

    rng = np.random.default_rng(9)
    P_SIZE, n, d, ff = 8, 64, 4, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = (rng.normal(size=(n, P_SIZE)) * 2).astype(np.float32)
    w1 = (rng.normal(size=(P_SIZE, d, ff)) * 0.4).astype(np.float32)
    w2 = (rng.normal(size=(P_SIZE, ff, d)) * 0.4).astype(np.float32)
    out = np.asarray(routed_expert_ffn(
        x, logits, w1, w2, DeviceMesh({"expert": P_SIZE}),
        capacity_factor=100.0,  # no drops
    ))
    probs = sp.softmax(logits, -1)
    gates = np.eye(P_SIZE, dtype=np.float32)[probs.argmax(-1)] * probs.max(-1)[:, None]
    ref = np.asarray(expert_parallel_ffn(
        x, gates, w1, w2, DeviceMesh({"expert": P_SIZE})
    ))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_routed_expert_validates():
    from flinkml_tpu.parallel.tensor import routed_expert_ffn

    with pytest.raises(ValueError, match="expert count"):
        routed_expert_ffn(
            np.zeros((8, 4), np.float32), np.zeros((8, 3), np.float32),
            np.zeros((3, 4, 8), np.float32), np.zeros((3, 8, 4), np.float32),
            DeviceMesh({"expert": 8}),
        )


def test_routed_expert_bf16_many_tokens_unique_slots():
    """Regression: rank bookkeeping must count in int32 — a bf16 cumsum
    cannot count past 256, colliding buffer slots for hot experts."""
    from flinkml_tpu.parallel.tensor import routed_expert_ffn

    rng = np.random.default_rng(10)
    P_SIZE, d = 8, 4
    n = P_SIZE * 320  # 320 tokens per device, all to expert 0
    x = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.bfloat16)
    logits = np.full((n, P_SIZE), -10.0, np.float32)
    logits[:, 0] = 10.0
    w1 = jnp.asarray(rng.normal(size=(P_SIZE, d, 8)) * 0.3, dtype=jnp.bfloat16)
    w2 = jnp.asarray(rng.normal(size=(P_SIZE, 8, d)) * 0.3, dtype=jnp.bfloat16)
    out = np.asarray(routed_expert_ffn(
        x, jnp.asarray(logits, jnp.bfloat16), w1, w2,
        DeviceMesh({"expert": P_SIZE}), capacity_factor=float(P_SIZE),
    ), dtype=np.float32)
    # All tokens kept (capacity = 320); every output must match its own
    # token's expert-0 result, not a sum of colliding tokens.
    xf = np.asarray(x, np.float32)
    h = np.asarray(jax.nn.gelu(jnp.asarray(xf) @ jnp.asarray(w1[0], jnp.float32).astype(jnp.float32)))
    ref = h @ np.asarray(w2[0], np.float32)
    # bf16 compute: loose tolerance, but collisions produce O(1) errors.
    assert np.abs(out - ref).max() < 0.15, np.abs(out - ref).max()
