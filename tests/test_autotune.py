"""Autotune: tuning-table semantics, lookup precedence, hysteresis,
consumers at every key-construction site, and the committed table's
measured-values contract (ISSUE 11)."""

import json
import os

import numpy as np
import pytest

from flinkml_tpu.autotune import (
    KNOWN_KNOBS,
    TuningTable,
    load_table,
    mesh_key,
    tuned_default,
)
from flinkml_tpu.autotune.search import (
    RATIO_FLOOR,
    STATIC_DEFAULTS,
    order_presets,
    settle,
)
from flinkml_tpu.autotune.table import ENV_DISABLE_VAR, ENV_TABLE_VAR


def _write_table(tmp_path, knobs, mesh=None):
    table = TuningTable()
    mesh = mesh or mesh_key()
    for knob, value in knobs.items():
        table.set_knob(mesh, knob, value,
                       candidates={"a": 1.0, "b": 2.0},
                       source="test")
    path = str(tmp_path / "table.json")
    table.save(path)
    return path


@pytest.fixture
def tuned(tmp_path, monkeypatch):
    """Point the process at a throwaway tuning table."""
    def point_at(knobs, mesh=None):
        monkeypatch.setenv(ENV_TABLE_VAR, _write_table(tmp_path, knobs, mesh))
    return point_at


# -- table semantics ---------------------------------------------------------


def test_table_roundtrip_and_check(tmp_path):
    table = TuningTable()
    table.set_knob("cpu/cpu/8", "sparse_layout", "cumsum",
                   candidates={"unsorted": 1.0, "cumsum": 2.0},
                   source="test")
    path = str(tmp_path / "t.json")
    table.save(path)
    loaded = load_table(path)
    assert loaded.value("cpu/cpu/8", "sparse_layout") == "cumsum"
    assert loaded.check() == []
    rec = loaded.record("cpu/cpu/8", "sparse_layout")
    assert rec["candidates"] == {"unsorted": 1.0, "cumsum": 2.0}
    assert rec["source"] == "test"


def test_table_check_flags_problems(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as fh:
        json.dump({
            "version": 1,
            "entries": {
                "cpu/cpu/8": {
                    "not_a_knob": {"value": 1, "candidates": {"x": 1.0},
                                   "measured_at": "", "source": "",
                                   "unit": ""},
                    "sparse_layout": {"value": "cumsum", "candidates": {},
                                      "measured_at": "", "source": "",
                                      "unit": ""},
                },
                "not-a-mesh-key": {},
            },
        }, fh)
    problems = load_table(path).check()
    assert any("unknown knob" in p for p in problems)
    assert any("measured, not guessed" in p for p in problems)
    assert any("bad mesh key" in p for p in problems)


def test_set_knob_refuses_unknown_knob():
    with pytest.raises(ValueError, match="unknown tuning knob"):
        TuningTable().set_knob("cpu/cpu/8", "typo_knob", 1)


def test_unreadable_table_degrades_to_empty(tmp_path, monkeypatch):
    path = tmp_path / "garbage.json"
    path.write_text("{not json")
    monkeypatch.setenv(ENV_TABLE_VAR, str(path))
    assert tuned_default("sparse_layout", "unsorted") == "unsorted"


# -- lookup precedence -------------------------------------------------------


def test_tuned_default_precedence(tuned, monkeypatch):
    tuned({"sparse_layout": "cumsum"})
    assert tuned_default("sparse_layout", "unsorted") == "cumsum"
    # FLINKML_TPU_AUTOTUNE=0 turns the table layer off.
    monkeypatch.setenv(ENV_DISABLE_VAR, "0")
    assert tuned_default("sparse_layout", "unsorted") == "unsorted"
    monkeypatch.delenv(ENV_DISABLE_VAR)
    # a value outside `allowed` degrades to the fallback, loudly-once.
    assert tuned_default("sparse_layout", "unsorted",
                         allowed=("unsorted", "sorted")) == "unsorted"
    # another mesh's entry is invisible here.
    tuned({"sparse_layout": "cumsum"}, mesh="tpu/TPU_v4/8")
    assert tuned_default("sparse_layout", "unsorted") == "unsorted"


def test_gates_consult_table_env_wins(tuned, monkeypatch):
    from flinkml_tpu.models._linear_sgd import _sparse_layout
    from flinkml_tpu.models.als import _als_layout
    from flinkml_tpu.models.gbt import _hist_layout
    from flinkml_tpu.models.word2vec import _w2v_accum

    tuned({
        "sparse_layout": "cumsum",
        "gbt_histogram": "cumsum",
        "als_reduction": "cumsum",
        "w2v_accum": "onehot",
    })
    assert _sparse_layout() == "cumsum"
    assert _hist_layout() == "cumsum"
    assert _als_layout() == "cumsum"
    assert _w2v_accum() == "onehot"
    # the explicit env gate beats the table everywhere.
    monkeypatch.setenv("FLINKML_TPU_SPARSE_LAYOUT", "sorted")
    monkeypatch.setenv("FLINKML_TPU_GBT_HISTOGRAM", "segment")
    monkeypatch.setenv("FLINKML_TPU_ALS_REDUCTION", "segment")
    monkeypatch.setenv("FLINKML_TPU_W2V_ACCUM", "scatter")
    assert _sparse_layout() == "sorted"
    assert _hist_layout() == "segment"
    assert _als_layout() == "segment"
    assert _w2v_accum() == "scatter"


def test_infer_plan_consults_measured_order(tuned):
    from flinkml_tpu.sharding.plan import (
        BATCH_PARALLEL,
        FSDP,
        infer_plan,
    )

    shapes = {"coef": (64,)}
    mesh = {"data": 2, "fsdp": 4}
    # Static order: batch_parallel fits -> wins.
    assert infer_plan(mesh, shapes, hbm_budget_bytes=1 << 20).name == \
        "batch_parallel"
    # A measured order promoting fsdp flips the default choice...
    tuned({"infer_plan_order": ["fsdp", "batch_parallel", "fsdp_tp"]})
    assert infer_plan(mesh, shapes, hbm_budget_bytes=1 << 20).name == "fsdp"
    # ...while explicit candidates are untouched by the table.
    assert infer_plan(
        mesh, shapes, hbm_budget_bytes=1 << 20,
        candidates=(BATCH_PARALLEL, FSDP),
    ).name == "batch_parallel"


def test_serving_config_consults_table(tuned):
    from flinkml_tpu.serving.engine import ServingConfig, ServingEngine
    from flinkml_tpu.table import Table

    tuned({"serving_max_batch_rows": 512, "serving_window_ms": 1.5})

    class _Identity:
        def transform(self, table):
            return (table.with_column(
                "out", np.asarray(table.column("features")) * 2.0
            ),)

    example = Table({"features": np.ones((4, 2))})
    engine = ServingEngine(_Identity(), example, name="tuned-cfg")
    assert engine.config.max_batch_rows == 512
    assert engine.config.max_wait_ms == 1.5
    # explicit values always win over the table.
    engine2 = ServingEngine(
        _Identity(), example,
        ServingConfig(max_batch_rows=64, max_wait_ms=3.0),
        name="explicit-cfg",
    )
    assert engine2.config.max_batch_rows == 64
    assert engine2.config.max_wait_ms == 3.0


# -- hysteresis --------------------------------------------------------------


def test_settle_hysteresis():
    # within the floor: incumbent keeps the seat (noise cannot flip).
    assert settle("sparse_layout",
                  {"unsorted": 100.0, "cumsum": 105.0}) == "unsorted"
    # decisive win: challenger takes it.
    assert settle("sparse_layout",
                  {"unsorted": 100.0, "cumsum": 100.0 * RATIO_FLOOR * 1.05}
                  ) == "cumsum"
    # numeric knobs keep their type.
    assert settle("serving_max_batch_rows",
                  {"1024": 100.0, "2048": 200.0}) == 2048
    assert settle("serving_window_ms",
                  {"2.0": 100.0, "1.0": 101.0}) == 2.0
    # a COMMITTED winner defends the seat, not the static default: a
    # near-floor measurement cannot flip-flop it back (reverting needs
    # its own decisive win).
    assert settle("sparse_layout",
                  {"unsorted": 105.0, "cumsum": 100.0},
                  incumbent="cumsum") == "cumsum"
    assert settle("sparse_layout",
                  {"unsorted": 100.0 * RATIO_FLOOR * 1.05, "cumsum": 100.0},
                  incumbent="cumsum") == "unsorted"


def test_order_presets_promotion():
    static = STATIC_DEFAULTS["infer_plan_order"]
    # ties / within-floor keep the static (cheapest-communication) order
    assert order_presets(
        {"batch_parallel": 100.0, "fsdp": 105.0, "fsdp_tp": 50.0}
    ) == static
    # a decisive fsdp win promotes it past batch_parallel only
    assert order_presets(
        {"batch_parallel": 100.0, "fsdp": 150.0, "fsdp_tp": 50.0}
    ) == ["fsdp", "batch_parallel", "fsdp_tp"]


# -- the committed table -----------------------------------------------------


def test_committed_table_has_measured_values_for_this_mesh():
    """The acceptance pin: the committed table carries MEASURED (not
    guessed) values — winner + candidate measurements — for the four
    sort-class cumsum defaults, the serving bucket/window, and the
    infer_plan order, on the CI mesh (the 8-virtual-device CPU host the
    whole suite runs on)."""
    table = load_table()
    assert table.check() == []
    mesh = mesh_key()
    for knob in KNOWN_KNOBS:
        rec = table.record(mesh, knob)
        assert rec is not None, (
            f"committed tuning table has no {knob!r} entry for mesh "
            f"{mesh!r} — run `python -m flinkml_tpu.autotune --commit`"
        )
        assert rec["candidates"], f"{knob}: no measured candidates"
        assert rec["measured_at"], knob
    # The four sort-class knobs each measured every landed layout.
    assert set(table.record(mesh, "sparse_layout")["candidates"]) == \
        {"unsorted", "sorted", "cumsum"}
    assert set(table.record(mesh, "gbt_histogram")["candidates"]) == \
        {"segment", "cumsum"}
    assert set(table.record(mesh, "als_reduction")["candidates"]) == \
        {"segment", "cumsum"}
    assert set(table.record(mesh, "w2v_accum")["candidates"]) == \
        {"scatter", "onehot"}


def test_quick_search_smoke(tmp_path):
    """The search harness itself, smoke-size, on two cheap knobs — the
    full run is `python -m flinkml_tpu.autotune --commit` (and bench's
    autotune stage on-device)."""
    from flinkml_tpu.autotune.search import apply_results, search_knobs

    results = search_knobs(["infer_plan_order"], quick=True)
    assert set(results) == {"infer_plan_order"}
    rec = results["infer_plan_order"]
    assert set(rec["candidates"]) == set(STATIC_DEFAULTS["infer_plan_order"])
    assert all(v > 0 for v in rec["candidates"].values())
    table = apply_results(TuningTable(), results, mesh="cpu/cpu/8")
    path = table.save(str(tmp_path / "out.json"))
    assert load_table(path).check() == []
