"""OnlineLogisticRegression (FTRL) tests — unbounded-mode coverage
(BASELINE.json config #4)."""

import numpy as np
import pytest

from flinkml_tpu.models import (
    LogisticRegression,
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
)
from flinkml_tpu.table import Table


def make_stream(rng, n_batches=20, batch=64, dim=5):
    true = rng.normal(size=dim) * 2
    batches, full_x, full_y = [], [], []
    for _ in range(n_batches):
        x = rng.normal(size=(batch, dim))
        y = (x @ true > 0).astype(np.float64)
        batches.append(Table({"features": x, "label": y}))
        full_x.append(x)
        full_y.append(y)
    return batches, np.concatenate(full_x), np.concatenate(full_y), true


def test_param_defaults():
    olr = OnlineLogisticRegression()
    assert olr.get_alpha() == 0.1
    assert olr.get_beta() == 0.1
    assert olr.get_batch_strategy() == "count"
    assert olr.get_global_batch_size() == 32


def test_fit_stream_learns(rng):
    batches, x, y, _ = make_stream(rng)
    model = OnlineLogisticRegression().set_alpha(0.5).fit_stream(batches)
    assert model.model_version == 20
    (out,) = model.transform(Table({"features": x, "label": y}))
    assert np.mean(out["prediction"] == y) > 0.9
    # Every output row carries the model version.
    assert (out["modelVersion"] == 20).all()


def test_fit_single_table_batches(rng):
    batches, x, y, _ = make_stream(rng, n_batches=4, batch=32)
    table = Table({"features": x, "label": y})
    model = OnlineLogisticRegression().set_global_batch_size(32).fit(table)
    assert model.model_version == 4


def test_warm_start_from_offline_model(rng):
    batches, x, y, _ = make_stream(rng, n_batches=3)
    offline = (
        LogisticRegression().set_seed(0).set_max_iter(100)
        .set_global_batch_size(512).fit(Table({"features": x, "label": y}))
    )
    olr = OnlineLogisticRegression().set_initial_model_data(
        *offline.get_model_data()
    )
    model = olr.fit_stream(batches[:1])
    # Warm start means predictions stay good after one tiny batch.
    (out,) = model.transform(Table({"features": x, "label": y}))
    assert np.mean(out["prediction"] == y) > 0.95


def test_l1_sparsifies(rng):
    dim = 10
    batches = []
    for _ in range(30):
        x = rng.normal(size=(64, dim))
        y = (x[:, 0] > 0).astype(np.float64)  # only feature 0 matters
        batches.append(Table({"features": x, "label": y}))
    model = (
        OnlineLogisticRegression().set_alpha(0.5)
        .set_reg(0.1).set_elastic_net(1.0).fit_stream(batches)
    )
    coef = model.coefficient
    assert abs(coef[0]) > 0.5
    assert np.sum(np.abs(coef[1:]) < 1e-9) >= dim // 2  # FTRL exact zeros


def test_empty_stream_raises():
    with pytest.raises(ValueError, match="empty"):
        OnlineLogisticRegression().fit_stream([])


def test_save_load(tmp_path, rng):
    batches, x, y, _ = make_stream(rng, n_batches=5)
    model = OnlineLogisticRegression().set_alpha(0.5).fit_stream(batches)
    p = str(tmp_path / "olr")
    model.save(p)
    loaded = OnlineLogisticRegressionModel.load(p)
    assert loaded.model_version == 5
    np.testing.assert_array_equal(loaded.coefficient, model.coefficient)


def test_model_data_round_trip(rng):
    batches, *_ = make_stream(rng, n_batches=2)
    model = OnlineLogisticRegression().fit_stream(batches)
    other = OnlineLogisticRegressionModel().set_model_data(*model.get_model_data())
    assert other.model_version == 2
    np.testing.assert_array_equal(other.coefficient, model.coefficient)
