"""Criteo-scale sparse path: dim ≥ 1e6, skewed nnz, bounded memory.

Round-1 VERDICT "missing" #4 / "weak" #3: the ELL layout padded every row
to the dataset-max nnz (pathological under skew) and nothing exercised
dim ≥ 1e5. These tests pin the nnz-bucketed layout
(``ops.sparse.pack_ell_buckets`` + ``train_linear_model_sparse_csr``):
packing is exact, the padded footprint is within a stated budget that the
uniform layout would exceed by orders of magnitude, training at dim=1e6
recovers a planted signal, and chunked checkpoint/resume is bit-exact.

Reference scale anchor: BASELINE.json config #5 (Criteo) — fixed nnz=39
per row there; the skewed distributions here are strictly harder.
"""

import numpy as np
import pytest

from flinkml_tpu.models import LogisticRegression
from flinkml_tpu.models._linear_sgd import (
    train_linear_model_sparse,
    train_linear_model_sparse_csr,
)
from flinkml_tpu.ops.sparse import choose_ell_widths, pack_ell_buckets
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


def _skewed_csr(rng, n, dim, head_nnz=(1, 9), tail_frac=0.005, tail_nnz=16384):
    """CSR with a power-law-ish nnz profile: almost all rows tiny, a few
    huge — the worst case for uniform ELL padding."""
    nnz = rng.integers(*head_nnz, size=n)
    tail = rng.choice(n, size=max(1, int(n * tail_frac)), replace=False)
    nnz[tail] = tail_nnz
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(nnz, out=indptr[1:])
    total = int(indptr[-1])
    indices = rng.integers(0, dim, size=total).astype(np.int32)
    values = rng.normal(size=total).astype(np.float64)
    return indptr, indices, values, nnz


def _densify(indptr, indices, values, n, dim):
    out = np.zeros((n, dim))
    for r in range(n):
        np.add.at(out[r], indices[indptr[r]:indptr[r + 1]],
                  values[indptr[r]:indptr[r + 1]])
    return out


def test_bucketed_packing_exact(rng):
    n, dim = 512, 1000
    indptr, indices, values, _ = _skewed_csr(
        rng, n, dim, head_nnz=(1, 6), tail_frac=0.02, tail_nnz=300
    )
    buckets, row_ids = pack_ell_buckets(
        indptr, indices, values, dim, max_buckets=4, dtype=np.float64
    )
    assert sorted(np.concatenate(row_ids).tolist()) == list(range(n))
    got = np.zeros((n, dim))
    for b, rows in zip(buckets, row_ids):
        for k, r in enumerate(rows):
            np.add.at(got[r], b["indices"][k], b["values"][k])
    np.testing.assert_allclose(
        got, _densify(indptr, indices, values, n, dim), atol=1e-12
    )


def test_choose_ell_widths_beats_uniform(rng):
    nnz = np.concatenate(
        [rng.integers(1, 8, 10_000), rng.integers(1000, 2049, 50)]
    )
    widths = choose_ell_widths(nnz, max_buckets=4)
    assert widths[-1] >= nnz.max()
    # Padded cells at the DP widths vs uniform padding to the max.
    edges = np.asarray(widths)
    cells = sum(
        int((np.searchsorted(edges, np.maximum(nnz, 1)) == b).sum()) * w
        for b, w in enumerate(widths)
    )
    assert cells <= 2 * nnz.sum()  # near-ideal
    assert nnz.size * nnz.max() >= 50 * cells  # uniform is catastrophic


def test_bucketed_matches_uniform_ell_full_batch(rng, mesh):
    """Full batch ⇒ every step uses the whole dataset in both layouts ⇒
    identical GD trajectories up to float summation order."""
    n, dim = 96, 40
    indptr, indices, values, nnz = _skewed_csr(
        rng, n, dim, head_nnz=(1, 5), tail_frac=0.05, tail_nnz=20
    )
    y = rng.integers(0, 2, n).astype(np.float64)
    w = np.ones(n)
    # Uniform ELL pack of the same rows.
    width = int(nnz.max())
    ell_i = np.zeros((n, width), dtype=np.int32)
    ell_v = np.zeros((n, width), dtype=np.float64)
    for r in range(n):
        k = int(indptr[r + 1] - indptr[r])
        ell_i[r, :k] = indices[indptr[r]:indptr[r + 1]]
        ell_v[r, :k] = values[indptr[r]:indptr[r + 1]]
    hyper = dict(
        loss="logistic", mesh=mesh, max_iter=40, learning_rate=0.5,
        global_batch_size=n, reg=0.01, elastic_net=0.25, tol=0.0, seed=3,
    )
    uniform = train_linear_model_sparse(ell_i, ell_v, dim, y, w, **hyper)
    bucketed = train_linear_model_sparse_csr(
        indptr, indices, values, dim, y, w, dtype=np.float64, **hyper
    )
    # Under the suite's x64 conftest both paths run f64 and agree to
    # 1e-10; without x64 (production default) f64 truncates to f32 and
    # only f32 summation-order noise remains.
    import jax

    atol = 1e-10 if jax.config.jax_enable_x64 else 1e-6
    np.testing.assert_allclose(bucketed, uniform, atol=atol)


def test_criteo_scale_dim_1e6_within_memory_budget(rng, mesh):
    """dim = 1e6, skewed nnz. The packed footprint must fit a budget the
    uniform layout exceeds ~100×, and training must recover a planted
    sparse signal."""
    n, dim = 4096, 1_000_000
    indptr, indices, values, nnz = _skewed_csr(rng, n, dim)
    # Plant signal on a small active set; labels from the true margin.
    active = rng.choice(dim, size=64, replace=False)
    beta = np.zeros(dim)
    beta[active] = rng.normal(size=64) * 2
    margins = np.zeros(n)
    for r in range(n):
        sl = slice(indptr[r], indptr[r + 1])
        margins[r] = values[sl] @ beta[indices[sl]]
    y = (margins > 0).astype(np.float64)
    w = np.ones(n)

    buckets, _ = pack_ell_buckets(
        indptr, indices, values, dim, max_buckets=4, dtype=np.float32
    )
    packed_bytes = sum(
        b["indices"].nbytes + b["values"].nbytes for b in buckets
    )
    uniform_bytes = n * int(nnz.max()) * 8  # int32 + float32 per cell
    total_nnz = int(indptr[-1])
    # Budget: within 2× of the information content, and ≥ 50× better
    # than uniform ELL on this skew.
    assert packed_bytes <= 2 * total_nnz * 8, (packed_bytes, total_nnz)
    assert uniform_bytes >= 50 * packed_bytes, (uniform_bytes, packed_bytes)

    coef = train_linear_model_sparse_csr(
        indptr, indices, values, dim, y, w,
        loss="logistic", mesh=mesh, max_iter=60, learning_rate=1.0,
        global_batch_size=n, reg=0.0, elastic_net=0.0, tol=0.0, seed=0,
    )
    assert coef.shape == (dim,)
    pred = np.zeros(n)
    for r in range(n):
        sl = slice(indptr[r], indptr[r + 1])
        pred[r] = values[sl] @ coef[indices[sl]]
    acc = np.mean((pred > 0) == (y > 0.5))
    assert acc > 0.9, acc


def test_minibatch_stratified_convergence(rng, mesh):
    """global_batch < n: each step draws a proportional window from every
    nnz bucket; the model must still learn."""
    n, dim = 2048, 5000
    indptr, indices, values, _ = _skewed_csr(
        rng, n, dim, head_nnz=(2, 10), tail_frac=0.01, tail_nnz=256
    )
    active = rng.choice(dim, size=32, replace=False)
    beta = np.zeros(dim)
    beta[active] = rng.normal(size=32) * 3
    margins = np.array([
        values[indptr[r]:indptr[r + 1]]
        @ beta[indices[indptr[r]:indptr[r + 1]]]
        for r in range(n)
    ])
    y = (margins > 0).astype(np.float64)
    coef = train_linear_model_sparse_csr(
        indptr, indices, values, dim, y, np.ones(n),
        loss="logistic", mesh=mesh, max_iter=300, learning_rate=0.5,
        global_batch_size=256, reg=0.0, elastic_net=0.0, tol=0.0, seed=1,
    )
    pred = np.array([
        values[indptr[r]:indptr[r + 1]]
        @ coef[indices[indptr[r]:indptr[r + 1]]]
        for r in range(n)
    ])
    assert np.mean((pred > 0) == (y > 0.5)) > 0.85


def test_sparse_csr_checkpoint_resume_exact(rng, mesh, tmp_path):
    from flinkml_tpu.iteration import CheckpointManager

    n, dim = 128, 300
    indptr, indices, values, _ = _skewed_csr(
        rng, n, dim, head_nnz=(1, 5), tail_frac=0.05, tail_nnz=40
    )
    y = rng.integers(0, 2, n).astype(np.float64)
    w = np.ones(n)
    hyper = dict(
        loss="logistic", mesh=mesh, max_iter=30, learning_rate=0.5,
        global_batch_size=64, reg=0.0, elastic_net=0.0, tol=0.0, seed=2,
        dtype=np.float64,
    )
    golden = train_linear_model_sparse_csr(
        indptr, indices, values, dim, y, w, **hyper
    )
    mgr = CheckpointManager(str(tmp_path))
    train_linear_model_sparse_csr(
        indptr, indices, values, dim, y, w,
        **{**hyper, "max_iter": 12},
        checkpoint_manager=mgr, checkpoint_interval=6,
    )
    assert mgr.latest_epoch() == 12
    resumed = train_linear_model_sparse_csr(
        indptr, indices, values, dim, y, w, **hyper,
        checkpoint_manager=mgr, checkpoint_interval=6, resume=True,
    )
    np.testing.assert_allclose(resumed, golden, atol=0)


def test_sparse_margins_bucketed_inference(rng):
    """Inference-side bucketed dots: exact vs dense, O(nnz) under skew."""
    from flinkml_tpu.linalg import Vectors
    from flinkml_tpu.ops.sparse import sparse_margins

    dim = 5000
    vecs, dense = [], []
    for i in range(300):
        k = 200 if i % 25 == 0 else 3
        idx = np.sort(rng.choice(dim, size=k, replace=False))
        val = rng.normal(size=k)
        vecs.append(Vectors.sparse(dim, idx, val))
        row = np.zeros(dim)
        row[idx] = val
        dense.append(row)
    coef = rng.normal(size=dim)
    got = sparse_margins(vecs, coef)
    want = np.stack(dense) @ coef
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_sparse_margins_multichunk_both_shapes(rng, monkeypatch):
    """Force the chunk loop to run many times (budget of 64 elements) and
    check both coefficient shapes stay exact — the path production hits
    at million-row scoring batches."""
    from flinkml_tpu.linalg import Vectors
    from flinkml_tpu.ops import sparse as sparse_mod

    monkeypatch.setattr(sparse_mod, "_SCORING_CHUNK_ELEMS", 64)
    dim, n, k = 300, 120, 3
    vecs, dense = [], []
    for i in range(n):
        nnz = 20 if i % 7 == 0 else 4   # two buckets
        idx = np.sort(rng.choice(dim, size=nnz, replace=False))
        val = rng.normal(size=nnz)
        vecs.append(Vectors.sparse(dim, idx, val))
        row = np.zeros(dim)
        row[idx] = val
        dense.append(row)
    X = np.stack(dense)
    coef1 = rng.normal(size=dim)
    coef2 = rng.normal(size=(k, dim))
    got1 = sparse_mod.sparse_margins(vecs, coef1)
    got2 = sparse_mod.sparse_margins(vecs, coef2)
    np.testing.assert_allclose(got1, X @ coef1, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(got2, X @ coef2.T, rtol=2e-4, atol=2e-4)


def test_estimator_sparse_vectors_use_bucketed_path(rng):
    """End-to-end through the public API with SparseVector rows of very
    different nnz — exercises csr_from_sparse_vectors + bucketing."""
    from flinkml_tpu.linalg import Vectors

    n, dim = 200, 400
    vecs, labels = [], []
    for i in range(n):
        k = 2 if i % 10 else 60
        idx = np.sort(rng.choice(dim, size=k, replace=False))
        val = rng.normal(size=k)
        vecs.append(Vectors.sparse(dim, idx, val))
        labels.append(float(val.sum() > 0))
    table = Table({
        "features": np.array(vecs, dtype=object),
        "label": np.array(labels),
    })
    model = (
        LogisticRegression().set_seed(0).set_max_iter(150)
        .set_global_batch_size(n).set_learning_rate(1.0).fit(table)
    )
    (out,) = model.transform(table)
    assert np.mean(out["prediction"] == np.array(labels)) > 0.9


def test_sorted_scatter_layout_matches_unsorted(mesh, monkeypatch):
    """Round-3 sort-elimination layout: pre-sorted per-window scatter with
    indices_are_sorted=True must train to the same model as the per-step
    sort layout (identical up to f32 summation order)."""
    from flinkml_tpu.models import _linear_sgd

    rng = np.random.default_rng(5)
    n, dim, nnz = 512, 2000, 7
    indptr = np.arange(n + 1, dtype=np.int64) * nnz
    indices = rng.integers(0, dim, size=n * nnz).astype(np.int32)
    values = rng.normal(size=n * nnz).astype(np.float32)
    beta = np.zeros(dim, np.float32)
    beta[rng.choice(dim, 50, replace=False)] = rng.normal(size=50)
    margins = (values.reshape(n, nnz) * beta[indices.reshape(n, nnz)]).sum(1)
    y = (margins > 0).astype(np.float32)
    w = np.ones(n, np.float32)

    def train(flag):
        monkeypatch.setenv("FLINKML_TPU_SORTED_SCATTER", flag)
        return _linear_sgd.train_linear_model_sparse_csr(
            indptr, indices, values, dim, y, w, loss="logistic",
            mesh=mesh, max_iter=30, learning_rate=0.5,
            global_batch_size=256, reg=0.01, elastic_net=0.0, tol=0.0,
            seed=3,
        )

    unsorted_coef = train("0")
    sorted_coef = train("1")
    np.testing.assert_allclose(sorted_coef, unsorted_coef, atol=1e-5)
    # And the sorted run actually learns.
    acc = np.mean(
        ((values.reshape(n, nnz)
          * sorted_coef[indices.reshape(n, nnz)]).sum(1) > 0) == y
    )
    assert acc > 0.9, acc


def test_cumsum_layout_matches_unsorted(mesh, monkeypatch):
    """Round-5 sort-free layout: pack-time column-sorted cells + running-
    sum boundary differences must train to the same model (allclose —
    the running-sum difference changes f32 summation order)."""
    from flinkml_tpu.models import _linear_sgd

    rng = np.random.default_rng(7)
    n, dim = 640, 3000
    # Skewed nnz so multiple ELL buckets exist, plus a Zipfian column
    # distribution (the Criteo profile the layout exists for).
    nnz = np.clip(rng.geometric(0.2, size=n), 1, 40)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(nnz, out=indptr[1:])
    total = int(indptr[-1])
    indices = np.minimum(
        rng.zipf(1.3, size=total) - 1, dim - 1
    ).astype(np.int32)
    values = rng.normal(size=total).astype(np.float32)
    beta = np.zeros(dim, np.float32)
    beta[rng.choice(dim, 50, replace=False)] = rng.normal(size=50)
    y = np.zeros(n, np.float32)
    for r in range(n):
        sl = slice(indptr[r], indptr[r + 1])
        y[r] = float((values[sl] * beta[indices[sl]]).sum() > 0)
    w = np.ones(n, np.float32)

    def train(layout):
        monkeypatch.setenv("FLINKML_TPU_SPARSE_LAYOUT", layout)
        return _linear_sgd.train_linear_model_sparse_csr(
            indptr, indices, values, dim, y, w, loss="logistic",
            mesh=mesh, max_iter=30, learning_rate=0.5,
            global_batch_size=256, reg=0.01, elastic_net=0.1, tol=0.0,
            seed=3,
        )

    base = train("unsorted")
    cum = train("cumsum")
    np.testing.assert_allclose(cum, base, atol=2e-4, rtol=2e-4)


def test_sparse_layout_env_validation(monkeypatch):
    from flinkml_tpu.models._linear_sgd import _sparse_layout

    monkeypatch.setenv("FLINKML_TPU_SPARSE_LAYOUT", "bogus")
    with pytest.raises(ValueError, match="FLINKML_TPU_SPARSE_LAYOUT"):
        _sparse_layout()
    monkeypatch.delenv("FLINKML_TPU_SPARSE_LAYOUT")
    monkeypatch.setenv("FLINKML_TPU_SORTED_SCATTER", "1")
    assert _sparse_layout() == "sorted"


def test_chunked_segment_totals_precision_at_bench_scale():
    """The two-level running sum must hold f32 precision at the REAL
    Criteo cell count: a single global f32 prefix sum random-walks to
    ~3e3x a cell magnitude by 1e7 cells, putting a fixed ~1e-3-relative
    bias on rare-column (small-run) segment totals; the chunked
    decomposition bounds the error by the chunk scale instead. Checked
    against a float64 reference with a Zipf run-length profile."""
    import jax.numpy as jnp

    from flinkml_tpu.ops.sparse import chunked_run_totals

    rng = np.random.default_rng(0)
    cells = 10_000_000
    contrib = rng.normal(size=cells).astype(np.float32)
    # Zipfian run lengths: many 1-cell runs (rare columns) plus hot runs.
    lens = np.minimum(rng.zipf(1.5, size=cells), 200_000)
    lens = lens[np.cumsum(lens) <= cells]
    total = int(lens.sum())
    lens = np.concatenate([lens, [cells - total]]) if total < cells else lens
    ends = np.cumsum(lens).astype(np.int32) - 1
    seg32 = np.asarray(chunked_run_totals(
        jnp.asarray(contrib), jnp.asarray(ends)
    ))
    c64 = np.cumsum(contrib.astype(np.float64))
    t = c64[ends]
    seg64 = t - np.concatenate([[0.0], t[:-1]])
    # Absolute error relative to each segment's own scale (>= 1 cell's
    # typical magnitude). Chunked error is bounded by the CHUNK's
    # running-sum magnitude (measured ~8e-5 here); the single global f32
    # prefix sum it replaces carries the full window's magnitude into
    # every small segment — an order worse, checked below.
    denom = np.maximum(np.abs(seg64), 1.0)
    rel = np.abs(seg32 - seg64) / denom
    assert rel.max() < 3e-4, rel.max()
    c32 = np.cumsum(contrib)  # the naive scheme
    t32 = c32[ends]
    naive = t32 - np.concatenate([[np.float32(0)], t32[:-1]])
    naive_rel = np.abs(naive - seg64) / denom
    assert rel.max() < naive_rel.max() / 5, (rel.max(), naive_rel.max())


def test_window_cumsum_tables_reconstruct_segment_sums():
    """The pack-time tables must reproduce an exact per-window histogram:
    sum(svals[run] * mult[srows[run]]) grouped by cols == dense reference."""
    from flinkml_tpu.models._linear_sgd import _window_cumsum_tables

    rng = np.random.default_rng(0)
    p, n_local, width, local_bs, dim = 2, 12, 3, 5, 17
    idx_pad = rng.integers(0, dim, size=(p * n_local, width)).astype(np.int32)
    val_pad = rng.normal(size=(p * n_local, width)).astype(np.float64)
    srows, svals, ends, cols = _window_cumsum_tables(
        idx_pad, val_pad, p, local_bs
    )
    n_windows = -(-n_local // local_bs)
    assert srows.shape == (p * n_windows, local_bs * width)
    for d in range(p):
        mult = rng.normal(size=local_bs)
        for wnum in range(n_windows):
            row = d * n_windows + wnum
            start = min(wnum * local_bs, n_local - local_bs)
            ib = idx_pad[d * n_local + start:d * n_local + start + local_bs]
            vb = val_pad[d * n_local + start:d * n_local + start + local_bs]
            expect = np.zeros(dim)
            np.add.at(expect, ib.reshape(-1),
                      (vb * mult[:, None]).reshape(-1))
            contrib = svals[row] * mult[srows[row]]
            csum = np.cumsum(contrib)
            t = csum[ends[row]]
            seg = t - np.concatenate([[0.0], t[:-1]])
            got = np.zeros(dim)
            np.add.at(got, cols[row], seg)
            np.testing.assert_allclose(got, expect, atol=1e-12)
            assert (np.diff(cols[row]) >= 0).all()


def test_window_sort_tables_are_sorted_and_permute_back():
    from flinkml_tpu.models._linear_sgd import _window_sort_tables

    rng = np.random.default_rng(0)
    p, n_local, width, local_bs = 2, 12, 3, 5
    idx_pad = rng.integers(0, 100, size=(p * n_local, width)).astype(np.int32)
    perm, sids = _window_sort_tables(idx_pad, p, local_bs)
    n_windows = -(-n_local // local_bs)
    assert perm.shape == (p * n_windows, local_bs * width)
    for d in range(p):
        shard = idx_pad[d * n_local:(d + 1) * n_local]
        for wnum in range(n_windows):
            row = d * n_windows + wnum
            start = min(wnum * local_bs, n_local - local_bs)
            flat = shard[start:start + local_bs].reshape(-1)
            # sids is flat permuted by perm, and non-decreasing.
            np.testing.assert_array_equal(flat[perm[row]], sids[row])
            assert (np.diff(sids[row]) >= 0).all()


def test_chunked_run_totals_small_input_avoids_full_chunk_pad():
    """ADVICE r5 (low): inputs smaller than one CUMSUM_CHUNK must not pad
    their cumsum transient up to 65536 rows — at the ALS cumsum layout
    ([chunk, k*k+k+1] payload, rank ~100) that is a multi-GB intermediate
    for a few-MB input. The trace for a 4k-cell input must contain no
    array whose leading dim reaches CUMSUM_CHUNK, and results must stay
    correct at every small size (a sub-chunk input is a single chunk
    either way, so the error-bound rationale is untouched)."""
    import jax

    from flinkml_tpu.ops.sparse import CUMSUM_CHUNK, chunked_run_totals

    rng = np.random.default_rng(1)
    cells, k = 4_000, 7
    contrib = rng.normal(size=(cells, k)).astype(np.float32)
    ends = np.sort(
        rng.choice(cells - 1, size=36, replace=False)
    ).astype(np.int32)
    ends = np.concatenate([ends, [cells - 1]]).astype(np.int32)

    jaxpr = jax.make_jaxpr(chunked_run_totals)(contrib, ends)
    dims = [
        d
        for eqn in jaxpr.jaxpr.eqns
        for v in eqn.outvars
        for d in getattr(v.aval, "shape", ())
    ]
    assert max(dims) < CUMSUM_CHUNK, (
        f"4k-cell input materialized a {max(dims)}-row transient"
    )

    # Correctness across small sizes, against a float64 prefix-sum ref.
    import jax.numpy as jnp

    for cells2 in (1, 3, 100, 4_000):
        c2 = rng.normal(size=cells2)
        e2 = np.unique(
            rng.integers(0, cells2, size=min(cells2, 11))
        ).astype(np.int32)
        e2[-1] = cells2 - 1
        got = np.asarray(
            chunked_run_totals(jnp.asarray(c2), jnp.asarray(e2))
        )
        pref = np.cumsum(c2)[e2]
        ref = pref - np.concatenate([[0.0], pref[:-1]])
        np.testing.assert_allclose(got, ref, rtol=1e-12, atol=1e-12)
