"""StringIndexer / IndexToString: ordering, handleInvalid, persistence."""

import numpy as np
import pytest

from flinkml_tpu.models import (
    IndexToStringModel,
    StringIndexer,
    StringIndexerModel,
)
from flinkml_tpu.table import Table


def _table():
    return Table({
        "color": np.asarray(["b", "a", "b", "c", "b", "a"]),
        "size": np.asarray([2.0, 1.0, 2.0, 2.0, 3.0, 1.0]),
    })


def _indexer(order="arbitrary", handle="error"):
    return (
        StringIndexer()
        .set_input_cols(["color", "size"])
        .set_output_cols(["colorIdx", "sizeIdx"])
        .set_string_order_type(order)
        .set_handle_invalid(handle)
    )


def test_frequency_desc_ordering():
    model = _indexer("frequencyDesc").fit(_table())
    (out,) = model.transform(_table())
    # color counts: b=3, a=2, c=1 -> b:0, a:1, c:2
    np.testing.assert_array_equal(
        out.column("colorIdx"), [0, 1, 0, 2, 0, 1]
    )
    # size counts: 2.0=3, 1.0=2, 3.0=1 -> 2.0:0, 1.0:1, 3.0:2
    np.testing.assert_array_equal(out.column("sizeIdx"), [0, 1, 0, 0, 2, 1])


def test_frequency_asc_and_tie_break():
    t = Table({"c": np.asarray(["y", "x", "y", "x", "z"])})
    model = (
        StringIndexer()
        .set_input_cols(["c"]).set_output_cols(["i"])
        .set_string_order_type("frequencyAsc")
        .fit(t)
    )
    (out,) = model.transform(t)
    # counts: x=2, y=2, z=1 -> z:0, then tie x before y (value ascending)
    np.testing.assert_array_equal(out.column("i"), [2, 1, 2, 1, 0])


def test_alphabet_orders():
    t = _table()
    asc = _indexer("alphabetAsc").fit(t).transform(t)[0]
    np.testing.assert_array_equal(asc.column("colorIdx"), [1, 0, 1, 2, 1, 0])
    desc = _indexer("alphabetDesc").fit(t).transform(t)[0]
    np.testing.assert_array_equal(desc.column("colorIdx"), [1, 2, 1, 0, 1, 2])
    # Numeric columns order by value, not by string representation.
    t2 = Table({"v": np.asarray([10.0, 2.0, 10.0])})
    m = (
        StringIndexer().set_input_cols(["v"]).set_output_cols(["i"])
        .set_string_order_type("alphabetAsc").fit(t2)
    )
    np.testing.assert_array_equal(m.transform(t2)[0].column("i"), [1, 0, 1])


def test_handle_invalid_error():
    model = _indexer().fit(_table())
    bad = Table({
        "color": np.asarray(["a", "UNSEEN"]),
        "size": np.asarray([1.0, 2.0]),
    })
    with pytest.raises(ValueError, match="UNSEEN"):
        model.transform(bad)


def test_handle_invalid_skip_drops_whole_row():
    model = _indexer(handle="skip").fit(_table())
    bad = Table({
        "color": np.asarray(["a", "UNSEEN", "c"]),
        "size": np.asarray([1.0, 2.0, 99.0]),
    })
    (out,) = model.transform(bad)
    # row 1 (unseen color) and row 2 (unseen size) both dropped
    assert out.num_rows == 1
    np.testing.assert_array_equal(out.column("color"), ["a"])


def test_handle_invalid_keep_maps_to_catch_all():
    model = _indexer(handle="keep", order="alphabetAsc").fit(_table())
    bad = Table({
        "color": np.asarray(["a", "UNSEEN"]),
        "size": np.asarray([99.0, 2.0]),
    })
    (out,) = model.transform(bad)
    np.testing.assert_array_equal(out.column("colorIdx"), [0.0, 3.0])
    np.testing.assert_array_equal(out.column("sizeIdx"), [3.0, 1.0])


def test_save_load_roundtrip(tmp_path):
    model = _indexer("frequencyDesc").fit(_table())
    model.save(str(tmp_path / "si"))
    loaded = StringIndexerModel.load(str(tmp_path / "si"))
    t = _table()
    np.testing.assert_array_equal(
        loaded.transform(t)[0].column("colorIdx"),
        model.transform(t)[0].column("colorIdx"),
    )
    assert loaded.get(StringIndexerModel.STRING_ORDER_TYPE) == "frequencyDesc"


def test_model_data_roundtrip():
    model = _indexer("frequencyDesc").fit(_table())
    clone = StringIndexerModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    t = _table()
    np.testing.assert_array_equal(
        clone.transform(t)[0].column("sizeIdx"),
        model.transform(t)[0].column("sizeIdx"),
    )


def test_index_to_string_inverts(tmp_path):
    indexer = _indexer("frequencyDesc").fit(_table())
    (indexed,) = indexer.transform(_table())
    inv = IndexToStringModel.from_indexer(indexer)
    inv.set_input_cols(["colorIdx", "sizeIdx"]).set_output_cols(["color2", "size2"])
    (out,) = inv.transform(indexed)
    np.testing.assert_array_equal(out.column("color2"), _table().column("color"))
    np.testing.assert_array_equal(
        out.column("size2").astype(float), _table().column("size")
    )
    # persistence of the inverse model
    inv.save(str(tmp_path / "i2s"))
    loaded = IndexToStringModel.load(str(tmp_path / "i2s"))
    np.testing.assert_array_equal(
        loaded.transform(indexed)[0].column("color2"), out.column("color2")
    )


def test_index_to_string_rejects_bad_indices():
    indexer = _indexer().fit(_table())
    inv = IndexToStringModel.from_indexer(indexer)
    inv.set_input_cols(["i", "j"]).set_output_cols(["o1", "o2"])
    bad = Table({"i": np.asarray([5.0]), "j": np.asarray([0.0])})
    with pytest.raises(ValueError, match="outside"):
        inv.transform(bad)
    frac = Table({"i": np.asarray([0.5]), "j": np.asarray([0.0])})
    with pytest.raises(ValueError, match="non-integral"):
        inv.transform(frac)


def test_chains_into_one_hot():
    from flinkml_tpu.models import OneHotEncoder

    t = _table()
    indexer = _indexer("frequencyDesc").fit(t)
    (indexed,) = indexer.transform(t)
    enc = (
        OneHotEncoder()
        .set_input_cols(["colorIdx"]).set_output_cols(["colorVec"])
        .fit(indexed)
    )
    (out,) = enc.transform(indexed)
    vec = out.column("colorVec")
    assert vec.shape == (6, 2)  # 3 categories, dropLast
    np.testing.assert_array_equal(vec[0], [1.0, 0.0])  # "b" -> idx 0


def test_nan_excluded_from_vocab_and_handled_as_invalid():
    t = Table({"v": np.asarray([1.0, np.nan, 1.0, 2.0, np.nan])})
    model = (
        StringIndexer().set_input_cols(["v"]).set_output_cols(["i"])
        .set_string_order_type("frequencyDesc").fit(t)
    )
    # vocab is NaN-free: {1.0: 0, 2.0: 1}
    with pytest.raises(ValueError, match="not seen"):
        model.transform(t)
    (kept,) = model.set_handle_invalid("keep").transform(t)
    np.testing.assert_array_equal(kept.column("i"), [0.0, 2.0, 0.0, 1.0, 2.0])
    (skipped,) = model.set_handle_invalid("skip").transform(t)
    np.testing.assert_array_equal(skipped.column("i"), [0.0, 0.0, 1.0])


def test_all_nan_column_rejected_at_fit():
    t = Table({"v": np.asarray([np.nan, np.nan])})
    with pytest.raises(ValueError, match="non-NaN"):
        StringIndexer().set_input_cols(["v"]).set_output_cols(["i"]).fit(t)


def test_max_index_num_caps_vocabulary():
    t = _table()
    model = (
        _indexer("frequencyDesc", handle="keep")
        .set_max_index_num(2).fit(t)
    )
    (out,) = model.transform(t)
    # color vocab capped at {b, a}; "c" becomes the catch-all index 2.
    np.testing.assert_array_equal(out.column("colorIdx"), [0, 1, 0, 2, 0, 1])
    with pytest.raises(ValueError, match="not seen"):
        model.set_handle_invalid("error").transform(t)


def test_numeric_vocab_queried_with_strings():
    # ADVICE r2: numeric-sorted vocab [2, 10] stringifies to ['2', '10'],
    # which is NOT lexicographically sorted; the lookup must re-sort on
    # dtype coercion or it silently treats present values as unseen.
    t = Table({"c": np.asarray([2.0, 10.0, 2.0])})
    model = (
        StringIndexer().set_input_cols(["c"]).set_output_cols(["i"])
        .set_handle_invalid("keep").fit(t)
    )
    ts = Table({"c": np.asarray(["2.0", "10.0", "nope"], dtype=object)})
    (out,) = model.transform(ts)
    # '2.0' and '10.0' must be FOUND (same indices as the numeric query);
    # only 'nope' is the catch-all.
    (num_out,) = model.transform(t)
    np.testing.assert_array_equal(out.column("i")[:2], num_out.column("i")[:2])
    assert out.column("i")[2] == 2.0  # len(vocab) catch-all


def test_keep_catch_all_round_trips_through_index_to_string():
    # ADVICE r2: handleInvalid='keep' emits index len(vocab); the inverse
    # transform maps it to a sentinel instead of raising.
    t = _table()
    indexer = _indexer(handle="keep").fit(t)
    unseen = Table({
        "color": np.asarray(["a", "zzz"]),
        "size": np.asarray([1.0, 99.0]),
    })
    (indexed,) = indexer.transform(unseen)
    inv = IndexToStringModel.from_indexer(indexer)
    inv.set_input_cols(["colorIdx", "sizeIdx"]).set_output_cols(["c2", "s2"])
    (out,) = inv.transform(indexed)
    assert out.column("c2")[0] == "a"
    assert out.column("c2")[1] == IndexToStringModel.UNKNOWN_SENTINEL
    assert out.column("s2")[0] == 1.0
    assert np.isnan(out.column("s2")[1])
