"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the MiniCluster analog from SURVEY.md §4: the reference runs its
system tests on a 2-TM × 2-slot MiniCluster; we run ours on
``--xla_force_host_platform_device_count=8`` CPU devices so every collective
and sharding path is exercised multi-device without TPU hardware.

Must set env vars before the first jax import anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax

# The axon TPU plugin prepends itself to jax_platforms at import time,
# overriding the JAX_PLATFORMS env var — force CPU via config as well.
jax.config.update("jax_platforms", "cpu")

# Golden-value tests compare against numpy float64; the env var form of this
# flag is not honored by this jax build, so set it via config.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: repeated pytest runs skip recompiles.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np
import pytest


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    """``@pytest.mark.no_retrace`` wraps the test body in the analysis
    transfer/retrace guard: compile-cache misses beyond the bucket policy
    (and any declared transfer budgets) fail the test. Marker kwargs pass
    through to ``TransferRetraceGuard`` — e.g.
    ``@pytest.mark.no_retrace(allow_compiles=1)`` to budget the warmup
    compile inside the test itself."""
    marker = item.get_closest_marker("no_retrace")
    if marker is None:
        yield
        return
    from flinkml_tpu.analysis.guard import TransferRetraceGuard

    kwargs = dict(marker.kwargs)
    kwargs.setdefault("location", item.nodeid)
    guard = TransferRetraceGuard(**kwargs)
    guard.__enter__()
    outcome = yield
    # Only enforce the budget when the test body itself passed (a failing
    # test's own error is the more useful signal).
    guard.__exit__(
        None if outcome.excinfo is None else outcome.excinfo[0], None, None
    )


@pytest.fixture
def rng():
    return np.random.default_rng(2024)


@pytest.fixture(scope="session")
def mesh():
    from flinkml_tpu.parallel import DeviceMesh

    return DeviceMesh()
