"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the MiniCluster analog from SURVEY.md §4: the reference runs its
system tests on a 2-TM × 2-slot MiniCluster; we run ours on
``--xla_force_host_platform_device_count=8`` CPU devices so every collective
and sharding path is exercised multi-device without TPU hardware.

Must set env vars before the first jax import anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(2024)
