"""Multiclass / regression / clustering evaluators vs sklearn."""

import numpy as np
import pytest
from sklearn.metrics import (
    accuracy_score,
    explained_variance_score,
    f1_score,
    mean_absolute_error,
    mean_squared_error,
    precision_score,
    r2_score,
    recall_score,
    silhouette_score,
)

from flinkml_tpu.models import (
    ClusteringEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from flinkml_tpu.models.evaluation_multi import (
    multiclass_metrics,
    regression_metrics,
    simplified_silhouette,
)
from flinkml_tpu.table import Table


def test_multiclass_matches_sklearn_weighted():
    rng = np.random.default_rng(0)
    y = rng.integers(0, 4, 500).astype(float)
    p = np.where(rng.uniform(size=500) < 0.7, y, rng.integers(0, 4, 500)).astype(float)
    m = multiclass_metrics(y, p)
    assert m["accuracy"] == pytest.approx(accuracy_score(y, p))
    assert m["weightedPrecision"] == pytest.approx(
        precision_score(y, p, average="weighted", zero_division=0)
    )
    assert m["weightedRecall"] == pytest.approx(
        recall_score(y, p, average="weighted", zero_division=0)
    )
    assert m["weightedF1"] == pytest.approx(
        f1_score(y, p, average="weighted", zero_division=0)
    )


def test_multiclass_with_sample_weights():
    y = np.asarray([0.0, 0.0, 1.0, 1.0])
    p = np.asarray([0.0, 1.0, 1.0, 1.0])
    w = np.asarray([10.0, 1.0, 1.0, 1.0])
    m = multiclass_metrics(y, p, w)
    assert m["accuracy"] == pytest.approx(
        accuracy_score(y, p, sample_weight=w)
    )
    assert m["weightedF1"] == pytest.approx(
        f1_score(y, p, average="weighted", sample_weight=w)
    )


def test_multiclass_operator_and_validation():
    t = Table({
        "label": np.asarray([0.0, 1.0, 2.0, 1.0]),
        "prediction": np.asarray([0.0, 1.0, 1.0, 1.0]),
    })
    (out,) = (
        MulticlassClassificationEvaluator()
        .set_metrics_names(["accuracy", "weightedPrecision"])
        .transform(t)
    )
    assert out["accuracy"][0] == pytest.approx(0.75)
    with pytest.raises(ValueError, match="unsupported"):
        MulticlassClassificationEvaluator().set_metrics_names(["auc"]).transform(t)


def test_regression_matches_sklearn():
    rng = np.random.default_rng(1)
    y = rng.normal(size=300) * 3 + 5
    p = y + rng.normal(size=300) * 0.7 + 0.2
    m = regression_metrics(y, p)
    assert m["mse"] == pytest.approx(mean_squared_error(y, p))
    assert m["rmse"] == pytest.approx(np.sqrt(mean_squared_error(y, p)))
    assert m["mae"] == pytest.approx(mean_absolute_error(y, p))
    assert m["r2"] == pytest.approx(r2_score(y, p))
    assert m["explainedVariance"] == pytest.approx(
        explained_variance_score(y, p)
    )


def test_regression_weighted_and_operator():
    y = np.asarray([1.0, 2.0, 3.0])
    p = np.asarray([1.5, 2.0, 2.0])
    w = np.asarray([1.0, 2.0, 3.0])
    m = regression_metrics(y, p, w)
    assert m["r2"] == pytest.approx(r2_score(y, p, sample_weight=w))
    t = Table({"label": y, "prediction": p, "w": w})
    (out,) = (
        RegressionEvaluator().set_metrics_names(["rmse", "mae"])
        .set_weight_col("w").transform(t)
    )
    assert out["rmse"][0] == pytest.approx(
        np.sqrt(mean_squared_error(y, p, sample_weight=w))
    )


def test_silhouette_reasonable_vs_sklearn():
    rng = np.random.default_rng(2)
    # Well-separated blobs: simplified (centroid) silhouette tracks the
    # exact pairwise one closely.
    x = np.concatenate([
        rng.normal(size=(60, 3)) + np.asarray([5.0, 0, 0]),
        rng.normal(size=(60, 3)) - np.asarray([5.0, 0, 0]),
    ])
    a = np.concatenate([np.zeros(60), np.ones(60)])
    ours = simplified_silhouette(x, a)
    exact = silhouette_score(x, a)
    assert abs(ours - exact) < 0.1
    assert ours > 0.7
    # Random assignment scores near zero.
    bad = simplified_silhouette(x, rng.integers(0, 2, 120))
    assert bad < 0.1


def test_clustering_evaluator_end_to_end():
    from flinkml_tpu.models import KMeans

    rng = np.random.default_rng(3)
    x = np.concatenate([
        rng.normal(size=(50, 2)) + 6, rng.normal(size=(50, 2)) - 6,
    ]).astype(np.float64)
    t = Table({"features": x})
    model = KMeans().set_k(2).set_seed(5).fit(t)
    (assigned,) = model.transform(t)
    (out,) = ClusteringEvaluator().transform(assigned)
    assert out["silhouette"][0] > 0.7
    with pytest.raises(ValueError, match="2 clusters"):
        ClusteringEvaluator().transform(
            Table({"features": x, "prediction": np.zeros(100)})
        )


def test_multiclass_rejects_nan_predictions():
    t = Table({
        "label": np.asarray([0.0, 1.0]),
        "prediction": np.asarray([0.0, np.nan]),
    })
    with pytest.raises(ValueError, match="NaN"):
        MulticlassClassificationEvaluator().transform(t)
