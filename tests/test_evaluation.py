"""BinaryClassificationEvaluator vs sklearn golden values."""

import numpy as np
import pytest
from sklearn.metrics import average_precision_score, roc_auc_score

from flinkml_tpu.models.evaluation import (
    BinaryClassificationEvaluator,
    binary_metrics,
)
from flinkml_tpu.table import Table


def _data(n=500, seed=0, ties=False):
    rng = np.random.default_rng(seed)
    y = (rng.uniform(size=n) > 0.4).astype(np.float64)
    scores = np.clip(y * 0.3 + rng.normal(0.35, 0.25, size=n), 0, 1)
    if ties:
        scores = np.round(scores, 1)  # heavy ties
    return scores, y


@pytest.mark.parametrize("ties", [False, True])
def test_auc_roc_matches_sklearn(ties):
    s, y = _data(ties=ties)
    m = binary_metrics(s, y)
    assert m["areaUnderROC"] == pytest.approx(roc_auc_score(y, s), abs=1e-12)


def test_weighted_auc_matches_sklearn():
    s, y = _data(seed=1)
    w = np.random.default_rng(2).uniform(0.1, 3.0, size=s.shape)
    m = binary_metrics(s, y, w)
    assert m["areaUnderROC"] == pytest.approx(
        roc_auc_score(y, s, sample_weight=w), abs=1e-12
    )


def test_auc_pr_close_to_sklearn_ap():
    # Trapezoidal PR-AUC vs sklearn's step-interpolated AP: close, not equal.
    s, y = _data(seed=3)
    m = binary_metrics(s, y)
    assert m["areaUnderPR"] == pytest.approx(
        average_precision_score(y, s), abs=0.02
    )


def test_ks_and_accuracy():
    # Perfect separation: KS = 1, accuracy = 1 at the 0.5 threshold.
    y = np.asarray([0, 0, 1, 1], dtype=float)
    s = np.asarray([0.1, 0.2, 0.8, 0.9])
    m = binary_metrics(s, y)
    assert m["ks"] == pytest.approx(1.0)
    assert m["accuracy"] == pytest.approx(1.0)
    assert m["areaUnderROC"] == pytest.approx(1.0)


def test_evaluator_operator_table_io():
    s, y = _data(seed=4)
    t = Table({"label": y, "rawPrediction": np.stack([1 - s, s], axis=1)})
    ev = BinaryClassificationEvaluator().set(
        BinaryClassificationEvaluator.METRICS_NAMES,
        ["areaUnderROC", "ks", "accuracy"],
    )
    (out,) = ev.transform(t)
    assert set(out.column_names) == {"areaUnderROC", "ks", "accuracy"}
    assert out.column("areaUnderROC")[0] == pytest.approx(roc_auc_score(y, s))


def test_evaluator_rejects_unknown_metric():
    ev = BinaryClassificationEvaluator().set(
        BinaryClassificationEvaluator.METRICS_NAMES, ["areaUnderLorenz"]
    )
    with pytest.raises(ValueError, match="unsupported"):
        ev.transform(Table({"label": np.zeros(2), "rawPrediction": np.zeros(2)}))


def test_single_class_rejected():
    with pytest.raises(ValueError, match="both classes"):
        binary_metrics(np.asarray([0.1, 0.9]), np.asarray([1.0, 1.0]))


def test_end_to_end_with_logistic_regression():
    from flinkml_tpu.models import LogisticRegression

    rng = np.random.default_rng(5)
    x = rng.normal(size=(600, 10)).astype(np.float32)
    y = (x @ rng.normal(size=10) + 0.3 * rng.normal(size=600) > 0).astype(
        np.float32
    )
    train = Table({"features": x, "label": y})
    model = (LogisticRegression().set_max_iter(80).set_learning_rate(0.5)
             .set_global_batch_size(600).set_seed(0).fit(train))
    (scored,) = model.transform(train)
    (metrics,) = BinaryClassificationEvaluator().transform(scored)
    assert metrics.column("areaUnderROC")[0] > 0.95


def test_accuracy_uses_prediction_column_for_margins():
    """LinearSVC-style margins: thresholding raw scores at 0.5 is wrong;
    the prediction column must drive accuracy."""
    y = np.asarray([0.0, 0.0, 1.0, 1.0])
    margins = np.asarray([-0.3, -0.1, 0.1, 0.3])  # perfect at threshold 0
    t = Table({
        "label": y, "rawPrediction": margins,
        "prediction": (margins > 0).astype(np.float64),
    })
    ev = BinaryClassificationEvaluator().set(
        BinaryClassificationEvaluator.METRICS_NAMES, ["accuracy"]
    )
    (out,) = ev.transform(t)
    assert out.column("accuracy")[0] == pytest.approx(1.0)
    # Without a prediction column the 0.5 threshold is (documentedly) off.
    t2 = Table({"label": y, "rawPrediction": margins})
    (out2,) = ev.transform(t2)
    assert out2.column("accuracy")[0] == pytest.approx(0.5)


def test_nan_scores_rejected():
    with pytest.raises(ValueError, match="NaN"):
        binary_metrics(np.asarray([0.1, np.nan]), np.asarray([0.0, 1.0]))


def test_log_loss_matches_sklearn():
    from sklearn.metrics import log_loss as sk_log_loss

    rng = np.random.default_rng(11)
    y = rng.integers(0, 2, 300).astype(float)
    p = np.clip(rng.beta(2, 2, 300) * 0.6 + y * 0.3, 0, 1)
    m = binary_metrics(p, y)
    assert m["logLoss"] == pytest.approx(sk_log_loss(y, p))
    w = rng.uniform(0.5, 2.0, 300)
    mw = binary_metrics(p, y, w)
    assert mw["logLoss"] == pytest.approx(
        sk_log_loss(y, p, sample_weight=w)
    )
    # Hard 0/1 scores stay finite (clipped).
    hard = binary_metrics(y, y)
    assert np.isfinite(hard["logLoss"])
