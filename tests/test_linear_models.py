"""LinearSVC / LinearRegression / sparse LR / elastic-net tests
(BASELINE.json config #3 and #5 coverage)."""

import numpy as np
import pytest
import scipy.sparse as sp

from flinkml_tpu.linalg import Vectors
from flinkml_tpu.models import (
    LinearRegression,
    LinearRegressionModel,
    LinearSVC,
    LinearSVCModel,
    LogisticRegression,
)
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


@pytest.fixture
def class_table(rng):
    x = rng.normal(size=(300, 5))
    true = rng.normal(size=5) * 2
    y = (x @ true > 0).astype(np.float64)
    return Table({"features": x, "label": y}), true


def test_linear_svc_fit_predict(class_table):
    table, _ = class_table
    model = (
        LinearSVC().set_seed(0).set_max_iter(300).set_learning_rate(0.5)
        .set_global_batch_size(300).fit(table)
    )
    (out,) = model.transform(table)
    acc = np.mean(out["prediction"] == table["label"])
    assert acc > 0.97
    # Raw prediction column = margin (dot product).
    assert out["rawPrediction"].shape == (300,)


def test_linear_svc_against_sklearn(class_table):
    from sklearn.svm import LinearSVC as SkSVC

    table, _ = class_table
    x, y = table["features"], table["label"]
    model = (
        LinearSVC().set_seed(0).set_max_iter(500).set_learning_rate(0.5)
        .set_global_batch_size(300).set_reg(0.001).fit(table)
    )
    sk = SkSVC(fit_intercept=False, max_iter=5000).fit(x, y)
    cos = np.dot(model.coefficient, sk.coef_[0]) / (
        np.linalg.norm(model.coefficient) * np.linalg.norm(sk.coef_[0])
    )
    assert cos > 0.98


def test_linear_svc_save_load(tmp_path, class_table):
    table, _ = class_table
    model = LinearSVC().set_seed(0).set_max_iter(50).fit(table)
    p = str(tmp_path / "svc")
    model.save(p)
    loaded = LinearSVCModel.load(p)
    np.testing.assert_array_equal(loaded.coefficient, model.coefficient)


def test_linear_regression_recovers_coefficients(rng):
    x = rng.normal(size=(500, 4))
    true = np.array([1.5, -2.0, 0.5, 3.0])
    y = x @ true + 0.01 * rng.normal(size=500)
    table = Table({"features": x, "label": y})
    model = (
        LinearRegression().set_seed(0).set_max_iter(2000)
        .set_learning_rate(0.5).set_global_batch_size(500).fit(table)
    )
    np.testing.assert_allclose(model.coefficient, true, atol=0.05)
    (out,) = model.transform(table)
    assert np.corrcoef(out["prediction"], y)[0, 1] > 0.999


def test_lasso_sparsifies(rng):
    # 2 informative + 6 dead features; L1 must zero the dead ones.
    x = rng.normal(size=(400, 8))
    y = 2.0 * x[:, 0] - 1.0 * x[:, 1] + 0.01 * rng.normal(size=400)
    table = Table({"features": x, "label": y})
    model = (
        LinearRegression().set_seed(0).set_max_iter(1500)
        .set_learning_rate(0.5).set_global_batch_size(400)
        .set_reg(0.5).set_elastic_net(1.0).fit(table)
    )
    coef = model.coefficient
    assert abs(coef[0]) > 1.0 and abs(coef[1]) > 0.4
    assert np.all(np.abs(coef[2:]) < 0.02)


def test_weighted_linear_regression(rng):
    x = rng.normal(size=(200, 2))
    y = x @ np.array([1.0, 1.0])
    w = np.ones(200)
    table_w = Table({"features": x, "label": y, "w": w})
    m1 = (
        LinearRegression().set_seed(1).set_max_iter(500).set_learning_rate(0.5)
        .set_global_batch_size(200).set_weight_col("w").fit(table_w)
    )
    np.testing.assert_allclose(m1.coefficient, [1.0, 1.0], atol=0.02)


def test_sparse_logistic_regression(rng):
    # Sparse features via SparseVector column (the Criteo-style path).
    mat = sp.random(400, 50, density=0.1, random_state=0, format="csr")
    true = rng.normal(size=50)
    y = (mat @ true > 0).astype(np.float64)
    vecs = [
        Vectors.sparse(
            50,
            mat.indices[mat.indptr[i] : mat.indptr[i + 1]],
            mat.data[mat.indptr[i] : mat.indptr[i + 1]],
        )
        for i in range(400)
    ]
    table = Table({"features": vecs, "label": y})
    model = (
        LogisticRegression().set_seed(0).set_max_iter(400)
        .set_learning_rate(1.0).set_global_batch_size(400).fit(table)
    )
    (out,) = model.transform(table)
    acc = np.mean(out["prediction"] == y)
    assert acc > 0.93, acc


def _sparse_and_dense_tables(rng, n=200, d=6, label_fn=None):
    x = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.4)
    y = (
        label_fn(x) if label_fn is not None
        else (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    )
    vecs = [
        Vectors.sparse(d, np.nonzero(row)[0], row[np.nonzero(row)[0]])
        for row in x
    ]
    return (
        Table({"features": x, "label": y}),
        Table({"features": np.array(vecs, dtype=object), "label": y}),
        y,
    )


def test_sparse_linear_svc_matches_dense(rng):
    """LinearSVC accepts SparseVector columns (bucketed path) and agrees
    with its dense fit on the same data."""
    dense_t, sparse_t, y = _sparse_and_dense_tables(rng)
    kw = lambda: (LinearSVC().set_seed(3).set_max_iter(200)
                  .set_global_batch_size(200).set_learning_rate(0.5))
    dense_m = kw().fit(dense_t)
    sparse_m = kw().fit(sparse_t)
    cos = np.dot(dense_m.coefficient, sparse_m.coefficient) / (
        np.linalg.norm(dense_m.coefficient)
        * np.linalg.norm(sparse_m.coefficient)
    )
    assert cos > 0.999, cos
    (a,) = sparse_m.transform(sparse_t)   # sparse inference path
    (b,) = dense_m.transform(dense_t)
    assert np.mean(a["prediction"] == b["prediction"]) > 0.98


def test_sparse_linear_regression_matches_dense(rng):
    dense_t, sparse_t, y = _sparse_and_dense_tables(
        rng, label_fn=lambda x: x[:, 0] * 2.0 - x[:, 2]
    )
    kw = lambda: (LinearRegression().set_seed(3).set_max_iter(400)
                  .set_global_batch_size(200).set_learning_rate(0.5)
                  .set_tol(0.0))
    dense_m = kw().fit(dense_t)
    sparse_m = kw().fit(sparse_t)
    np.testing.assert_allclose(
        sparse_m.coefficient, dense_m.coefficient, atol=5e-3
    )
    (a,) = sparse_m.transform(sparse_t)
    (b,) = dense_m.transform(dense_t)
    np.testing.assert_allclose(a["prediction"], b["prediction"], atol=2e-2)


def test_sparse_inference_dim_mismatch_raises(rng):
    """A dim mismatch must raise like the dense matmul would — JAX's
    gather would otherwise silently clamp out-of-range indices."""
    _, sparse_t, y = _sparse_and_dense_tables(rng)
    model = (
        LinearSVC().set_seed(0).set_max_iter(20)
        .set_global_batch_size(200).fit(sparse_t)
    )
    wrong = Table({
        "features": np.array(
            [Vectors.sparse(12, [0, 7], [1.0, 2.0])], dtype=object
        ),
    })
    with pytest.raises(ValueError, match="dim"):
        model.transform(wrong)


def test_mixed_vector_column_densifies(rng):
    """A column mixing Sparse and Dense vectors takes the densifying
    path (any-Vector support), not the CSR path."""
    from flinkml_tpu.linalg import DenseVector

    x = rng.normal(size=(64, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    vecs = [
        Vectors.sparse(4, np.arange(4), row) if i % 2 else DenseVector(row)
        for i, row in enumerate(x)
    ]
    t = Table({"features": np.array(vecs, dtype=object), "label": y})
    model = (
        LinearSVC().set_seed(0).set_max_iter(100)
        .set_global_batch_size(64).set_learning_rate(0.5).fit(t)
    )
    (out,) = model.transform(t)
    assert np.mean(out["prediction"] == y) > 0.9


def test_sparse_dense_agreement(rng):
    # Same data sparse vs dense must converge to similar coefficients.
    x = rng.normal(size=(200, 6)) * (rng.random((200, 6)) < 0.4)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    dense_table = Table({"features": x, "label": y})
    vecs = [
        Vectors.sparse(6, np.nonzero(row)[0], row[np.nonzero(row)[0]])
        for row in x
    ]
    sparse_table = Table({"features": vecs, "label": y})
    kw = dict()
    dense_m = (
        LogisticRegression().set_seed(3).set_max_iter(200)
        .set_global_batch_size(200).fit(dense_table)
    )
    sparse_m = (
        LogisticRegression().set_seed(3).set_max_iter(200)
        .set_global_batch_size(200).fit(sparse_table)
    )
    cos = np.dot(dense_m.coefficient, sparse_m.coefficient) / (
        np.linalg.norm(dense_m.coefficient) * np.linalg.norm(sparse_m.coefficient)
    )
    assert cos > 0.999
    (a,) = sparse_m.transform(sparse_table)
    (b,) = dense_m.transform(dense_table)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])


def test_multi_device_sparse(rng):
    mat = sp.random(333, 20, density=0.2, random_state=1, format="csr")
    y = (np.asarray(mat.sum(axis=1)).ravel() > mat.sum() / 333).astype(np.float64)
    vecs = [
        Vectors.sparse(
            20,
            mat.indices[mat.indptr[i] : mat.indptr[i + 1]],
            mat.data[mat.indptr[i] : mat.indptr[i + 1]],
        )
        for i in range(333)
    ]
    table = Table({"features": vecs, "label": y})
    model = (
        LogisticRegression(mesh=DeviceMesh()).set_seed(0).set_max_iter(100)
        .set_global_batch_size(333).fit(table)
    )
    assert np.isfinite(model.coefficient).all()


def test_linear_regression_normal_solver_exact():
    from sklearn.linear_model import LinearRegression as SkOLS, Ridge

    rng = np.random.default_rng(11)
    x = rng.normal(size=(300, 6))
    true = rng.normal(size=6)
    y = x @ true + 0.1 * rng.normal(size=300)
    t = Table({"features": x, "label": y})
    model = LinearRegression().set_solver("normal").fit(t)
    ref = SkOLS(fit_intercept=False).fit(x, y)
    np.testing.assert_allclose(
        model.coefficient, ref.coef_, rtol=1e-4, atol=1e-5
    )
    # Ridge consistency: the SGD fixed point uses 2*reg unscaled by
    # sum(w), so sklearn alpha = 2 * reg.
    reg = 5.0
    ridged = LinearRegression().set_solver("normal").set_reg(reg).fit(t)
    ref_r = Ridge(alpha=2 * reg, fit_intercept=False).fit(x, y)
    np.testing.assert_allclose(
        ridged.coefficient, ref_r.coef_, rtol=1e-4, atol=1e-5
    )


def test_linear_regression_normal_solver_weighted():
    from sklearn.linear_model import LinearRegression as SkOLS

    rng = np.random.default_rng(12)
    x = rng.normal(size=(200, 3))
    y = x @ np.asarray([1.0, -2.0, 0.5]) + rng.normal(size=200)
    w = rng.uniform(0.1, 5.0, size=200)
    t = Table({"features": x, "label": y, "w": w})
    model = (
        LinearRegression().set_solver("normal").set_weight_col("w").fit(t)
    )
    ref = SkOLS(fit_intercept=False).fit(x, y, sample_weight=w)
    np.testing.assert_allclose(
        model.coefficient, ref.coef_, rtol=1e-4, atol=1e-5
    )


def test_linear_regression_normal_solver_validation():
    t = Table({"features": np.zeros((4, 2)), "label": np.zeros(4)})
    with pytest.raises(ValueError, match="elasticNet"):
        (
            LinearRegression().set_solver("normal").set_elastic_net(0.5)
            .set_reg(0.1).fit(t)
        )


def test_normal_solver_matches_sgd_fixed_point():
    # Same reg in both solvers must land on (nearly) the same optimum.
    rng = np.random.default_rng(13)
    x = rng.normal(size=(400, 4))
    y = x @ np.asarray([2.0, -1.0, 0.5, 0.0]) + 0.05 * rng.normal(size=400)
    t = Table({"features": x, "label": y})
    reg = 2.0
    exact = LinearRegression().set_solver("normal").set_reg(reg).fit(t)
    sgd = (
        LinearRegression().set_reg(reg).set_max_iter(800)
        .set_global_batch_size(400).set_learning_rate(0.5).set_tol(0.0)
        .set_seed(0).fit(t)
    )
    np.testing.assert_allclose(
        sgd.coefficient, exact.coefficient, rtol=2e-3, atol=2e-4
    )


def test_normal_solver_tiny_scale_features():
    # 1e-6-scale features: an absolute jitter would distort the solve.
    from sklearn.linear_model import LinearRegression as SkOLS

    rng = np.random.default_rng(14)
    x = rng.normal(size=(200, 3)) * 1e-6
    y = x @ np.asarray([1e6, -2e6, 5e5]) + 0.01 * rng.normal(size=200)
    t = Table({"features": x, "label": y})
    model = LinearRegression().set_solver("normal").fit(t)
    ref = SkOLS(fit_intercept=False).fit(x, y)
    np.testing.assert_allclose(
        model.coefficient, ref.coef_, rtol=1e-3
    )


def test_normal_solver_collinear_min_norm():
    # Duplicated column, reg=0: must match sklearn's min-norm solution,
    # not an arbitrary split from a jittered near-singular solve.
    from sklearn.linear_model import LinearRegression as SkOLS

    rng = np.random.default_rng(15)
    base = rng.normal(size=(150, 2))
    x = np.concatenate([base, base[:, :1]], axis=1)  # col 2 == col 0
    y = base @ np.asarray([1.0, -1.0]) + 0.01 * rng.normal(size=150)
    t = Table({"features": x, "label": y})
    model = LinearRegression().set_solver("normal").fit(t)
    ref = SkOLS(fit_intercept=False).fit(x, y)
    np.testing.assert_allclose(model.coefficient, ref.coef_, atol=1e-3)
    # Min-norm: the duplicated columns share the weight equally.
    np.testing.assert_allclose(
        model.coefficient[0], model.coefficient[2], atol=1e-3
    )
