"""Replica pool, router, continuous batching, per-replica degradation.

The acceptance contract of the serving scale-out subsystem (ISSUE 8):

  1. Continuous batching splits requests at bucket boundaries — a late
     arrival joins the currently forming power-of-two bucket, tails ride
     the next dispatch, and per-request reassembly keeps responses
     bitwise-equal to a direct transform and single-version.
  2. Deadlines are swept promptly: an overdue request fails with the
     typed timeout as soon as its deadline passes, not at the window.
  3. A ReplicaPool routes by least-outstanding-rows over healthy
     replicas; killing one replica mid-traffic (the ``serving.replica``
     fault seam) loses zero requests routed to healthy replicas — the
     dead replica's traffic is retried elsewhere and the replica is
     retired while the pool keeps serving.
  4. ``follow_registry`` rolls hot-swaps across the pool one replica at
     a time; a rollback racing a publish converges every replica to the
     registry's final CURRENT pointer with zero mis-versioned responses.
  5. Overload degrades by replica: one replica tripping its queue bound
     drains and rejoins; the pool never browns out globally.
"""

import threading
import time

import numpy as np
import pytest

from flinkml_tpu import faults
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import MinMaxScaler, StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.serving import (
    ContinuousBatcher,
    HealthPolicy,
    ModelRegistry,
    PoolUnavailableError,
    ReplicaPool,
    ReplicaState,
    ServingConfig,
    ServingRequest,
    ServingTimeoutError,
    slice_meshes,
)
from flinkml_tpu.table import Table


def _data(n=200, d=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return x, y


def _two_stage_chain(x, y):
    train = Table({"features": x, "label": y})
    sc = (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "scaled")
        .fit(train)
    )
    (t2,) = sc.transform(train)
    lr = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, "scaled")
        .set(LogisticRegression.LABEL_COL, "label")
        .set_max_iter(3)
        .fit(t2)
    )
    return PipelineModel([sc, lr])


def _pool(source, x, n_replicas=4, name="pool", **cfg):
    config = ServingConfig(**{
        "max_batch_rows": 64,
        "max_queue_rows": 512,
        "max_wait_ms": 1.0,
        **cfg,
    })
    return ReplicaPool(
        source, Table({"features": x[:4]}), config=config,
        n_replicas=n_replicas, output_cols=("prediction",), name=name,
    )


def _req(rows, deadline=None):
    return ServingRequest(
        columns={"x": np.zeros((rows, 2))},
        rows=rows,
        enqueued_at=time.monotonic(),
        deadline=deadline,
    )


# ---------------------------------------------------------------------------
# 1. ContinuousBatcher
# ---------------------------------------------------------------------------

def test_continuous_batcher_splits_at_cap():
    """Saturated queue: every dispatch is an exactly-full cap bucket —
    the straddling request contributes its head rows, the tail rides the
    next dispatch (no head-of-line blocking)."""
    b = ContinuousBatcher(max_batch_rows=8, max_wait_s=0.0,
                          max_queue_rows=64)
    b.offer(_req(5))
    b.offer(_req(5))
    batch, _ = b.next_batch(poll_s=0.01)
    assert [(s.rows, s.start) for s in batch] == [(5, 0), (3, 0)]
    assert sum(s.rows for s in batch) == 8  # exactly the cap bucket
    batch2, _ = b.next_batch(poll_s=0.01)
    assert [(s.rows, s.start) for s in batch2] == [(2, 3)]
    assert batch[1].request is batch2[0].request
    # Segment views are the right row ranges of the request's columns.
    np.testing.assert_array_equal(
        batch2[0].columns["x"], batch2[0].request.columns["x"][3:5]
    )


def test_continuous_batcher_late_arrival_fills_forming_bucket():
    """6 rows are waiting out a long window (bucket 8); a late 4-row
    arrival fills the forming bucket, so the window closes immediately
    with an exactly-full 8-row batch (6 + 2 split) — occupancy 1.0
    without waiting, the Orca-style admission."""
    b = ContinuousBatcher(max_batch_rows=64, max_wait_s=30.0,
                          max_queue_rows=256)
    b.offer(_req(6))
    result = {}

    def consume():
        result["batch"], result["expired"] = b.next_batch(poll_s=0.01)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)
    b.offer(_req(4))
    t.join(timeout=5)
    assert not t.is_alive(), "window did not close on the late arrival"
    batch = result["batch"]
    assert [s.rows for s in batch] == [6, 2]
    assert sum(s.rows for s in batch) == 8
    # The tail is at the queue front and dispatches next.
    tail, _ = b.next_batch(poll_s=0.01)
    assert [(s.start, s.rows) for s in tail] == [(2, 2)]


def test_continuous_batcher_window_expiry_flushes_whole_queue():
    b = ContinuousBatcher(max_batch_rows=64, max_wait_s=0.0,
                          max_queue_rows=256)
    for _ in range(3):
        b.offer(_req(2))
    batch, expired = b.next_batch(poll_s=0.01)
    assert [s.rows for s in batch] == [2, 2, 2]
    assert expired == []


def test_batcher_prompt_deadline_sweep():
    """An overdue request is failed the moment the consumer observes its
    deadline — it must neither ride a batch nor wait out a long window
    (the PR 3 behavior this bugfix replaces)."""
    b = ContinuousBatcher(max_batch_rows=64, max_wait_s=30.0,
                          max_queue_rows=256)
    b.offer(_req(2))  # fresh, keeps the window open
    result = {}

    def consume():
        result["batch"], result["expired"] = b.next_batch(poll_s=0.01)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)
    overdue = _req(3, deadline=time.monotonic() - 0.001)
    b.offer(overdue)
    t.join(timeout=5)
    assert not t.is_alive(), "sweep did not wake promptly"
    assert result["batch"] == []
    assert result["expired"] == [overdue]
    assert b.queued_rows == 2  # the fresh request still queued


def test_continuous_request_reassembly_single_version():
    req = _req(5)
    assert req.add_segment(0, {"p": np.arange(3.0)}, 7, 3) is None
    out = req.add_segment(3, {"p": np.arange(3.0, 5.0)}, 7, 2)
    cols, version = out
    np.testing.assert_array_equal(cols["p"], np.arange(5.0))
    assert version == 7


def test_continuous_request_reassembly_flags_mixed_versions():
    req = _req(5)
    assert req.add_segment(0, {"p": np.arange(3.0)}, 7, 3) is None
    assert req.add_segment(3, {"p": np.arange(2.0)}, 8, 2) == "mixed"
    req.reset_segments()
    assert req.segments == []
    assert not req.done.is_set()


def test_continuous_batcher_discards_dead_tails():
    """A split request whose head batch FAILED must not dispatch its
    queued tail as dead device work (and must release its admission
    rows)."""
    b = ContinuousBatcher(max_batch_rows=8, max_wait_s=0.0,
                          max_queue_rows=64)
    r1, r2 = _req(12), _req(4)
    b.offer(r1)
    b.offer(r2)
    batch, _ = b.next_batch(poll_s=0.01)  # head 8 rows of r1
    assert [(s.request, s.rows) for s in batch] == [(r1, 8)]
    r1.fail(RuntimeError("head batch died"))  # the engine's error path
    batch, _ = b.next_batch(poll_s=0.01)
    assert [(s.request, s.rows) for s in batch] == [(r2, 4)]
    assert b.queued_rows == 0


def test_slice_meshes_rejects_indivisible():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    with pytest.raises(ValueError, match="equal slices"):
        slice_meshes(3, devices=jax.devices()[:8])


def test_continuous_batcher_requeue_front():
    b = ContinuousBatcher(max_batch_rows=8, max_wait_s=0.0,
                          max_queue_rows=64)
    r1, r2 = _req(3), _req(2)
    b.offer(r1)
    batch, _ = b.next_batch(poll_s=0.01)
    assert batch[0].request is r1
    b.offer(r2)
    r1.dispatched_rows = 3
    assert b.requeue(r1)
    batch, _ = b.next_batch(poll_s=0.01)
    # r1 re-dispatches whole, from the front, before r2.
    assert [(s.request, s.start, s.rows) for s in batch] == [
        (r1, 0, 3), (r2, 0, 2)
    ]
    b.stop()
    assert not b.requeue(r2)


# ---------------------------------------------------------------------------
# 2. ReplicaPool routing
# ---------------------------------------------------------------------------

def test_pool_parity_and_balance():
    """Concurrent clients through a 4-replica pool: every response
    bitwise-equal to direct transform, and every replica served some."""
    x, y = _data()
    pm = _two_stage_chain(x, y)
    pool = _pool(pm, x, name="parity_pool").start()
    errors = []

    def client(tid):
        rng = np.random.default_rng(tid)
        try:
            for _ in range(20):
                rows = int(rng.integers(1, 9))
                lo = int(rng.integers(0, x.shape[0] - rows))
                sl = x[lo:lo + rows]
                resp = pool.predict({"features": sl})
                (ref,) = pm.transform(Table({"features": sl}))
                np.testing.assert_array_equal(
                    np.asarray(ref.column("prediction")),
                    resp.column("prediction"),
                )
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    try:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
        assert not errors, errors[:3]
        st = pool.stats()
        assert st["router"]["routed_requests"] == 160
        per = st["per_replica"]
        requests = {r: per[r]["counters"].get("requests", 0) for r in per}
        assert sum(requests.values()) >= 160
        assert all(v > 0 for v in requests.values()), (
            f"router starved a replica: {requests}"
        )
    finally:
        pool.stop()


def test_pool_replica_kill_mid_traffic_loses_nothing():
    """Chaos contract: kill 1 of 4 replicas via the serving.replica seam
    while clients run. Zero client errors (requests on the dead replica
    are retried on healthy ones), correct parity and version tags, the
    replica is retired, the pool keeps serving."""
    x, y = _data()
    pm = _two_stage_chain(x, y)
    pool = _pool(pm, x, name="chaos_pool").start()
    errors = []
    served = [0]
    stop = threading.Event()

    def client(tid):
        rng = np.random.default_rng(tid)
        try:
            while not stop.is_set():
                rows = int(rng.integers(1, 7))
                lo = int(rng.integers(0, x.shape[0] - rows))
                sl = x[lo:lo + rows]
                resp = pool.predict({"features": sl})
                (ref,) = pm.transform(Table({"features": sl}))
                np.testing.assert_array_equal(
                    np.asarray(ref.column("prediction")),
                    resp.column("prediction"),
                )
                served[0] += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    try:
        with faults.armed(faults.FaultPlan(
            faults.ReplicaDown("r2", at_batch=2)
        )) as plan:
            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(6)
            ]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                st = pool.stats()
                if st["per_replica"]["r2"]["state"] == "unhealthy":
                    break
                time.sleep(0.05)
            served_at_kill = served[0]
            time.sleep(0.5)  # pool must keep serving after the kill
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        assert not errors, errors[:3]
        st = pool.stats()
        assert st["per_replica"]["r2"]["state"] == "unhealthy"
        assert st["healthy"] == 3
        assert st["router"].get("failovers", 0) >= 1
        assert served[0] > served_at_kill, "pool stopped serving after kill"
        assert any(site == "serving.replica" for site, _, _ in plan.log)
    finally:
        pool.stop()


def test_pool_deadline_expired_at_admission():
    x, y = _data()
    pm = _two_stage_chain(x, y)
    pool = _pool(pm, x, n_replicas=2, name="deadline_pool").start()
    try:
        with pytest.raises(ServingTimeoutError):
            pool.predict({"features": x[:2]}, timeout_ms=0.0)
        assert pool.stats()["router"].get("admission_timeouts", 0) >= 1
    finally:
        pool.stop()


def test_pool_unavailable_when_every_replica_dead():
    x, y = _data()
    pm = _two_stage_chain(x, y)
    pool = _pool(pm, x, n_replicas=2, name="dead_pool").start()
    try:
        with faults.armed(faults.FaultPlan(
            faults.ReplicaDown("r0"), faults.ReplicaDown("r1")
        )):
            with pytest.raises(PoolUnavailableError):
                for _ in range(8):  # a few: retire both, then refuse
                    pool.predict({"features": x[:2]})
        assert pool.stats()["healthy"] == 0
    finally:
        pool.stop()


def test_pool_revive_rejoins_rotation(tmp_path):
    x, y = _data()
    pm = _two_stage_chain(x, y)
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(pm)
    pool = _pool(reg, x, n_replicas=2, name="revive_pool").start()
    pool.follow_registry()
    try:
        with faults.armed(faults.FaultPlan(faults.ReplicaDown("r0"))):
            pool.predict({"features": x[:2]})  # retires r0, serves on r1
        assert pool.stats()["per_replica"]["r0"]["state"] == "unhealthy"
        reg.publish(_two_stage_chain(x, -y + 1))  # rolls only r1
        assert pool.replicas[1].engine.active_version == 2
        pool.revive("r0")
        st = pool.stats()
        assert st["per_replica"]["r0"]["state"] == "healthy"
        # Revive re-synced the replica to the registry's current version.
        assert pool.versions() == {"r0": 2, "r1": 2}
        resp = pool.predict({"features": x[:2]})
        assert resp.version == 2
    finally:
        pool.stop()


def test_pool_overload_degrades_by_replica():
    """One replica saturating its bounded queue trips into DRAINING and
    out of rotation; traffic keeps flowing through the other replica;
    the drained replica rejoins once its backlog falls under the
    low-water mark."""
    x, y = _data()
    pm = _two_stage_chain(x, y)
    pool = _pool(
        pm, x, n_replicas=2, name="shed_pool",
        max_batch_rows=8, max_queue_rows=8, shed_on_overload=False,
    )
    pool.start()
    try:
        r0, r1 = pool.replicas
        # Pool replicas never shed to the caller's thread — failover IS
        # the pool's shed path, and shedding would hide the queue-full
        # signal the degradation ladder is built on.
        assert not r0.engine.config.shed_on_overload
        # Ledger: consecutive queue-full refusals trip DRAINING at the
        # policy threshold (the router reports each refusal it reroutes).
        for _ in range(HealthPolicy().overload_trip - 1):
            assert not r0.health.on_overload()
        assert r0.health.on_overload()
        assert r0.health.state is ReplicaState.DRAINING
        # Backlog still above low water (simulated stuck queue): the
        # replica stays out of rotation — requests flow through r1 only.
        r0.engine._batcher._queued_rows = 6
        resp = pool.predict({"features": x[:3]})
        assert resp.columns["prediction"].shape == (3,)
        assert r0.health.state is ReplicaState.DRAINING
        assert r1.engine.stats()["counters"]["requests"] >= 1
        assert r0.engine.stats()["counters"].get("requests", 0) == 0
        # Backlog cleared -> the next routing pass rejoins it.
        r0.engine._batcher._queued_rows = 0
        pool.predict({"features": x[:3]})
        assert r0.health.state is ReplicaState.HEALTHY
        # A success resets the overload streak.
        assert r0.health.snapshot()["consecutive_overloads"] == 0
    finally:
        pool.stop()


def test_pool_mesh_slices_hold_slice_locks():
    """Mesh-slice placement: every replica batch dispatch records the
    slice's devices and holds the slice's local_execution_lock — the
    trace is FML303-clean against a concurrently locked trainer shape."""
    import jax

    from flinkml_tpu.analysis.collectives import (
        DispatchEvent,
        check_dispatch_trace,
    )
    from flinkml_tpu.parallel import dispatch as _dispatch

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    x, y = _data()
    pm = _two_stage_chain(x, y)
    meshes = slice_meshes(2, devices=jax.devices()[:4])
    # The slice locks this test registers overlap the full-device mesh:
    # leaving them registered would silently upgrade every later
    # full-mesh lock in the process to a composite (test cross-talk).
    locks_before = set(_dispatch._MESH_LOCKS)
    pool = ReplicaPool(
        pm, Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=32, max_queue_rows=256,
                             max_wait_ms=1.0),
        meshes=meshes, output_cols=("prediction",), name="slice_pool",
    ).start()
    events = []
    _dispatch.add_dispatch_observer(events.append)
    try:
        for i in range(6):
            pool.predict({"features": x[i:i + 2]})
        pool_events = [
            e for e in events if e["program"].startswith("serving.pool/")
        ]
        assert pool_events, "no replica dispatch was recorded"
        for e in pool_events:
            assert len(e["devices"]) == 2  # the slice, not one device
            assert any(t.startswith("lock:mesh:") for t in e["locks"]), e
        trace = [
            DispatchEvent(
                thread=e["thread"], program=e["program"],
                devices=tuple(e["devices"]), locks=tuple(e["locks"]),
            )
            for e in events
        ]
        assert check_dispatch_trace(trace) == []
    finally:
        _dispatch.remove_dispatch_observer(events.append)
        pool.stop()
        with _dispatch._MESH_LOCKS_GUARD:
            for key in set(_dispatch._MESH_LOCKS) - locks_before:
                del _dispatch._MESH_LOCKS[key]


# ---------------------------------------------------------------------------
# 3. Rolling hot-swap
# ---------------------------------------------------------------------------

def test_pool_follow_registry_rolls_all_replicas(tmp_path):
    x, y = _data()
    pm1 = _two_stage_chain(x, y)
    pm2 = _two_stage_chain(x, -y + 1)
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(pm1)
    pool = _pool(reg, x, n_replicas=3, name="roll_pool").start()
    pool.follow_registry()
    try:
        assert pool.versions() == {"r0": 1, "r1": 1, "r2": 1}
        reg.publish(pm2)  # the pool listener rolls replicas one by one
        assert pool.versions() == {"r0": 2, "r1": 2, "r2": 2}
        resp = pool.predict({"features": x[:3]})
        assert resp.version == 2
        (ref,) = pm2.transform(Table({"features": x[:3]}))
        np.testing.assert_array_equal(
            np.asarray(ref.column("prediction")), resp.column("prediction")
        )
        reg.rollback(1)
        assert pool.versions() == {"r0": 1, "r1": 1, "r2": 1}
        assert pool.predict({"features": x[:3]}).version == 1
    finally:
        pool.stop()
