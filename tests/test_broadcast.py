"""BroadcastUtils-analog tests — mirrors the reference's
``BroadcastUtilsTest`` (SURVEY.md §4 tier 2) plus ``ForwardInputsOfLastRound``
semantics."""

import numpy as np
import pytest

from flinkml_tpu.iteration import (
    ForwardInputsOfLastRound,
    IterationConfig,
    TerminateOnMaxIter,
    iterate,
)
from flinkml_tpu.parallel import (
    DeviceMesh,
    get_broadcast_variable,
    with_broadcast,
)


def test_with_broadcast_basic():
    coef = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    x = np.ones((4, 3), dtype=np.float32)

    def predict(batch):
        c = get_broadcast_variable("model")
        return np.asarray(batch @ np.asarray(c))

    out = with_broadcast(predict, inputs=[x], broadcast_variables={"model": coef})
    np.testing.assert_allclose(out, np.full(4, 6.0), rtol=1e-6)


def test_with_broadcast_over_mesh(mesh):
    coef = np.arange(8, dtype=np.float32)

    def fn():
        c = get_broadcast_variable("coef")
        # Replicated over the mesh: addressable on every device.
        assert len(c.sharding.device_set) == mesh.num_devices
        return np.asarray(c)

    out = with_broadcast(fn, broadcast_variables={"coef": coef}, mesh=mesh)
    np.testing.assert_array_equal(out, coef)


def test_broadcast_scope_cleanup():
    with_broadcast(lambda: None, broadcast_variables={"v": np.zeros(2)})
    with pytest.raises(KeyError):
        get_broadcast_variable("v")


def test_nested_scopes_shadow():
    def outer():
        def inner():
            assert float(np.asarray(get_broadcast_variable("v"))[0]) == 2.0
            assert float(np.asarray(get_broadcast_variable("w"))[0]) == 9.0
            return True

        assert with_broadcast(
            inner, broadcast_variables={"v": np.full(1, 2.0)}
        )
        # Outer value restored after the inner scope pops.
        return float(np.asarray(get_broadcast_variable("v"))[0])

    assert (
        with_broadcast(
            outer, broadcast_variables={"v": np.full(1, 1.0), "w": np.full(1, 9.0)}
        )
        == 1.0
    )


def test_missing_variable_raises():
    with pytest.raises(KeyError, match="no broadcast variable"):
        with_broadcast(lambda: get_broadcast_variable("nope"), broadcast_variables={})


def test_forward_inputs_of_last_round():
    fwd = ForwardInputsOfLastRound(extract=lambda s: s * 10)
    res = iterate(
        lambda s, e: (s + 1, None),
        0,
        config=IterationConfig(termination=TerminateOnMaxIter(5)),
        listeners=[fwd],
    )
    assert fwd.terminated
    # Only the final round's value survives (state after epoch 4 is 5).
    assert fwd.value == 50
    assert res.epochs == 5
