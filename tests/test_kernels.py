"""Pallas kernel backend (ISSUE 13): interpret-mode parity vs the XLA
path for each kernel, unsupported-dtype refusal, gate precedence
(env var > autotune table > static default), and the compile-cache
round-trip proving the backend is part of the program key (the
would-have-aliased regression)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flinkml_tpu import compile_cache, kernels, pipeline_fusion
from flinkml_tpu.autotune import TuningTable, mesh_key
from flinkml_tpu.autotune.table import ENV_DISABLE_VAR, ENV_TABLE_VAR
from flinkml_tpu.kernels import ENV_VAR, KernelUnsupportedError
from flinkml_tpu.kernels import chain as kchain
from flinkml_tpu.table import Table


@pytest.fixture
def tuned_kernels(tmp_path, monkeypatch):
    """Point the process at a throwaway tuning table carrying kernel
    backend knobs (the test_autotune fixture, scoped to this family)."""
    def point_at(knobs, mesh=None):
        table = TuningTable()
        m = mesh or mesh_key()
        for knob, value in knobs.items():
            table.set_knob(m, knob, value,
                           candidates={"xla": 1.0, "pallas": 2.0},
                           source="test")
        path = str(tmp_path / "table.json")
        table.save(path)
        monkeypatch.setenv(ENV_TABLE_VAR, path)
    return point_at


@pytest.fixture
def fusion_cache():
    pipeline_fusion.reset_cache()
    saved = list(pipeline_fusion.on_compile)
    yield
    pipeline_fusion.on_compile[:] = saved
    pipeline_fusion.reset_cache()


def _chain_model(rows=200, d=5, seed=0):
    """The canonical all-kernel chain (4 scalers + logistic) and its
    input table — the fused executor's richest program."""
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import (
        MaxAbsScaler, MinMaxScaler, RobustScaler, StandardScaler,
    )
    from flinkml_tpu.pipeline import PipelineModel

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, d))
    y = (x @ np.arange(1.0, d + 1) > 0).astype(np.float64)
    t = Table({"features": x, "label": y})
    stages, cur, prev = [], t, "features"
    for i, cls in enumerate(
        (StandardScaler, MinMaxScaler, MaxAbsScaler, RobustScaler), 1
    ):
        m = cls().set(cls.INPUT_COL, prev).set(cls.OUTPUT_COL, f"s{i}") \
            .fit(cur)
        (cur,) = m.transform(cur)
        prev = f"s{i}"
        stages.append(m)
    stages.append(
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, prev)
        .set(LogisticRegression.LABEL_COL, "label")
        .set_max_iter(2).fit(cur)
    )
    return PipelineModel(stages), t


def _outputs(model, table):
    (out,) = model.transform(table)
    return {c: np.asarray(out.column(c)) for c in out.column_names
            if c not in ("features", "label")}


# -- segment-sum parity ------------------------------------------------------


@pytest.mark.parametrize("sorted_", [False, True])
@pytest.mark.parametrize("dtype", ["float32", "float64", "bfloat16"])
def test_segment_sum_parity(sorted_, dtype):
    """Bitwise vs ``jax.ops.segment_sum`` for flat payloads: the
    unsorted kernel accumulates in element order (XLA's CPU scatter
    order) and the sorted run-flush adds left-to-right within each run
    — both reproduce the XLA result exactly at every dtype."""
    rng = np.random.default_rng(1)
    cells, dim = 700, 97
    ids = jnp.asarray(rng.integers(0, dim, cells), jnp.int32)
    if sorted_:
        ids = jnp.sort(ids)
    vals = jnp.asarray(rng.normal(size=cells)).astype(dtype)
    ref = jax.ops.segment_sum(vals, ids, num_segments=dim,
                              indices_are_sorted=sorted_)
    out = kernels.segment_sum(vals, ids, dim, indices_are_sorted=sorted_,
                              backend="pallas")
    assert out.dtype == ref.dtype
    assert np.asarray(ref).tobytes() == np.asarray(out).tobytes()


def test_segment_sum_row_payload_parity():
    """The W2V accumulator shape: [cells, k] rows scattered by id."""
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 40, 300), jnp.int32)
    rows = jnp.asarray(rng.normal(size=(300, 16)).astype(np.float32))
    ref = jax.ops.segment_sum(rows, ids, num_segments=40)
    out = kernels.segment_sum(rows, ids, 40, backend="pallas")
    assert np.asarray(ref).tobytes() == np.asarray(out).tobytes()


def test_sparse_step_backend_bitwise():
    """The real consumer: one padded-ELL SGD step, Pallas scatter vs
    XLA scatter, bit-identical new coefficients."""
    from jax.sharding import Mesh, PartitionSpec as P

    from flinkml_tpu.models import _linear_sgd

    rng = np.random.default_rng(3)
    dim, bs, w = 256, 32, 5
    idx = jnp.asarray(rng.integers(0, dim, (bs, w)), jnp.int32)
    val = jnp.asarray(rng.normal(size=(bs, w)).astype(np.float32))
    y = jnp.asarray((rng.random(bs) > 0.5).astype(np.float32))
    wt = jnp.ones(bs, jnp.float32)
    coef = jnp.asarray(rng.normal(size=dim).astype(np.float32))
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    outs = {}
    for backend in ("xla", "pallas"):
        step = _linear_sgd.make_sparse_step("logistic", bs, "data", dim,
                                            backend)
        f = jax.jit(jax.shard_map(
            lambda c, e, i, v, yy, ww, _s=step: _s(
                c, e, i, v, yy, ww, jnp.float32(0.1), jnp.float32(0.0),
                jnp.float32(0.0),
            ),
            mesh=mesh, in_specs=(P(),) * 6, out_specs=(P(), P()),
        ))
        outs[backend] = np.asarray(
            f(coef, jnp.asarray(0, jnp.int32), idx, val, y, wt)[0]
        )
    assert outs["xla"].tobytes() == outs["pallas"].tobytes()


# -- top-k parity ------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "float64", "bfloat16"])
def test_top_k_parity(dtype):
    """Values AND indices bitwise vs ``lax.top_k``, including ties
    (both break toward the lower index) and a row count that is not a
    multiple of the kernel's row tile."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(13, 57))).astype(dtype)
    x = x.at[0, 9].set(x[0, 3])   # tie inside one row
    x = x.at[5, :].set(x[5, 0])   # fully tied row
    rv, ri = jax.lax.top_k(x, 6)
    pv, pi = kernels.top_k(x, 6, backend="pallas")
    assert np.asarray(rv).tobytes() == np.asarray(pv).tobytes()
    assert np.asarray(ri).tobytes() == np.asarray(pi).tobytes()


def test_top_k_neg_inf_rows_parity():
    """A row whose tail is -inf must walk the untaken -inf entries in
    ascending index order exactly like ``lax.top_k`` — masking the
    selected column cannot alias the remaining -inf entries (the
    duplicate-index regression)."""
    x = jnp.asarray([
        [-np.inf, 5.0, -np.inf],
        [-np.inf, -np.inf, -np.inf],
        [1.0, -np.inf, 2.0],
    ], dtype=jnp.float32)
    rv, ri = jax.lax.top_k(x, 3)
    pv, pi = kernels.top_k(x, 3, backend="pallas")
    assert np.asarray(rv).tobytes() == np.asarray(pv).tobytes()
    assert np.asarray(ri).tobytes() == np.asarray(pi).tobytes()


def test_top_k_1d_parity():
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=41).astype(np.float32))
    rv, ri = jax.lax.top_k(x, 7)
    pv, pi = kernels.top_k(x, 7, backend="pallas")
    assert np.asarray(rv).tobytes() == np.asarray(pv).tobytes()
    assert np.asarray(ri).tobytes() == np.asarray(pi).tobytes()


def test_knn_backends_agree(fusion_cache, monkeypatch):
    """KNN predictions are backend-invariant (the vote consumes only
    the top-k indices, which are bitwise-equal)."""
    from flinkml_tpu.models.knn import Knn

    rng = np.random.default_rng(6)
    x = rng.normal(size=(80, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    t = Table({"features": x, "label": y})
    model = Knn().set(Knn.FEATURES_COL, "features") \
        .set(Knn.LABEL_COL, "label").set(Knn.K, 5).fit(t)
    q = Table({"features": rng.normal(size=(30, 4))})
    (ref,) = model.transform(q)
    monkeypatch.setenv(ENV_VAR, "topk=pallas")
    (got,) = model.transform(q)
    assert np.array_equal(np.asarray(ref.column("prediction")),
                          np.asarray(got.column("prediction")))


def test_lsh_ranking_pinned_order(monkeypatch):
    """The satellite fix (lsh.py host argsort → device top_k): ranking
    order equals the stable host argsort EXACTLY — ascending distance,
    ties toward the lower candidate index — on both backends."""
    from flinkml_tpu.models.lsh import MinHashLSH

    rng = np.random.default_rng(7)
    # Low-cardinality 0/1 rows manufacture many EQUAL Jaccard distances,
    # so a tie-break regression cannot hide.
    x = (rng.random((60, 12)) > 0.5).astype(np.float64)
    t = Table({"f": x})
    model = MinHashLSH().set(MinHashLSH.INPUT_COL, "f") \
        .set(MinHashLSH.OUTPUT_COL, "h") \
        .set(MinHashLSH.NUM_HASH_TABLES, 3).set_seed(11).fit(t)

    def golden(key, k):
        """The pre-fix host ranking, reproduced inline."""
        from flinkml_tpu.models.lsh import (
            _active_indices, _jaccard_distance,
        )
        rows = _active_indices(t.column("f"))
        hashes = model._hash_rows(rows)
        key_idx = np.nonzero(np.asarray(key, dtype=np.float64))[0]
        key_hash = model._hash_rows([key_idx])[0]
        cand = np.nonzero((hashes == key_hash[None, :]).any(axis=1))[0]
        dists = np.asarray([
            _jaccard_distance(rows[i], key_idx) for i in cand
        ])
        order = np.argsort(dists, kind="stable")[:k]
        return cand[order], dists[order]

    for k in (3, 7, 1000):   # 1000 > candidate count: clamp path
        want_rows, want_dists = golden(x[0], k)
        for env in (None, "topk=pallas"):
            if env is None:
                monkeypatch.delenv(ENV_VAR, raising=False)
            else:
                monkeypatch.setenv(ENV_VAR, env)
            got = model.approx_nearest_neighbors(t, x[0], k)
            assert np.array_equal(np.asarray(got.column("distCol")),
                                  want_dists), (k, env)
            assert np.array_equal(np.asarray(got.column("f")),
                                  x[want_rows]), (k, env)
        # duplicate distances must actually occur for the tie pin to
        # mean anything
    assert len(np.unique(golden(x[0], 1000)[1])) < \
        len(golden(x[0], 1000)[1])


# -- fused chain parity ------------------------------------------------------


@pytest.mark.parametrize("rows", [6, 50, 200])
def test_fused_chain_parity(rows, fusion_cache, monkeypatch):
    """The whole 5-stage chain through the real fused executor, Pallas
    vs XLA, bitwise at every row bucket (8 / 64 / 256 — one-tile and
    multi-tile grids)."""
    model, t = _chain_model(rows=200)
    sub = Table({c: np.asarray(t.column(c))[:rows] for c in t.column_names})
    ref = _outputs(model, sub)
    monkeypatch.setenv(ENV_VAR, "fused_chain=pallas")
    got = _outputs(model, sub)
    assert set(ref) == set(got)
    for c in ref:
        assert ref[c].dtype == got[c].dtype, c
        assert ref[c].tobytes() == got[c].tobytes(), c


def test_fused_chain_parity_bf16_policy(fusion_cache, monkeypatch):
    """Under the mixed-inference policy both backends compute at bf16;
    outputs agree within policy tolerance and decisions match away from
    the boundary (the precision-smoke contract, backend-invariant)."""
    model, t = _chain_model(rows=128)
    with pipeline_fusion.precision_scope("mixed_inference"):
        ref = _outputs(model, t)
    monkeypatch.setenv(ENV_VAR, "fused_chain=pallas")
    with pipeline_fusion.precision_scope("mixed_inference"):
        got = _outputs(model, t)
    raw_r = ref["rawPrediction"].astype(np.float64)
    raw_g = got["rawPrediction"].astype(np.float64)
    np.testing.assert_allclose(raw_r, raw_g, atol=2e-2)
    decisive = np.abs(raw_r[:, 1] - 0.5) > 2e-2
    assert decisive.any()
    assert np.array_equal(ref["prediction"][decisive],
                          got["prediction"][decisive])


def test_pallas_compile_counter(fusion_cache, monkeypatch):
    """A Pallas chain compile is visible in the executor's metrics."""
    from flinkml_tpu.utils.metrics import metrics

    model, t = _chain_model(rows=32)
    group = metrics.group("pipeline.fusion")
    before = group.snapshot()["counters"].get("pallas_compiles", 0)
    monkeypatch.setenv(ENV_VAR, "fused_chain=pallas")
    _outputs(model, t)
    after = group.snapshot()["counters"].get("pallas_compiles", 0)
    assert after > before


# -- refusal -----------------------------------------------------------------


def test_top_k_refuses_integer_dtype():
    with pytest.raises(KernelUnsupportedError, match="not floating"):
        kernels.top_k(jnp.arange(10), 3, backend="pallas")


def test_top_k_refuses_bad_k():
    x = jnp.ones((4, 8), jnp.float32)
    with pytest.raises(KernelUnsupportedError, match="outside"):
        kernels.top_k(x, 9, backend="pallas")


def test_segment_sum_refuses_integer_values():
    with pytest.raises(KernelUnsupportedError, match="not floating"):
        kernels.segment_sum(jnp.arange(8), jnp.zeros(8, jnp.int32), 4,
                            backend="pallas")


def test_chain_refuses_cross_row_kernel(monkeypatch):
    """A kernel whose output is not row-leading (a cross-row reduction)
    has no Pallas chain path: explicit request refuses loudly through
    the executor's gate."""
    from flinkml_tpu.api import ColumnKernel

    cross = ColumnKernel(
        input_cols=("x",), output_cols=("y",),
        fn=lambda cols, c, valid: {"y": jnp.sum(cols["x"], axis=0)},
        fingerprint=("crossrow",),
    )
    ext = (jnp.ones((8, 4), jnp.float32),)
    reason = kchain.unsupported_reason(
        (cross,), ("x",), ("y",), 8, None, ext, ((),), True,
    )
    assert reason is not None and "row-leading" in reason
    monkeypatch.setenv(ENV_VAR, "fused_chain=pallas")
    with pytest.raises(KernelUnsupportedError, match="row-leading"):
        pipeline_fusion._chain_backend(
            (cross,), ("x",), ("y",), 8, None, ext, ((),),
        )


def test_chain_refuses_weak_typed_constant():
    """A python-scalar (weak-typed) constant would promote differently
    through strong-typed Pallas refs — refused, never silently wrong."""
    from flinkml_tpu.api import ColumnKernel

    with jax.experimental.enable_x64(True):
        weak = jnp.asarray(2.0)   # weak float
        assert weak.weak_type
        k = ColumnKernel(
            input_cols=("x",), output_cols=("y",),
            fn=lambda cols, c, valid: {"y": cols["x"] * c["s"]},
            constants={"s": 2.0}, fingerprint=("weak",),
        )
        reason = kchain.unsupported_reason(
            (k,), ("x",), ("y",), 8, None,
            (jnp.ones((8, 4), jnp.float32),), ((weak,),), True,
        )
    assert reason is not None and "weak-typed" in reason


def test_env_var_validation(monkeypatch):
    monkeypatch.setenv(ENV_VAR, "bogus")
    with pytest.raises(ValueError, match="FLINKML_TPU_KERNELS"):
        kernels.backend_for("topk")
    monkeypatch.setenv(ENV_VAR, "topk=metal")
    with pytest.raises(ValueError, match="bad pair"):
        kernels.backend_for("topk")
    monkeypatch.setenv(ENV_VAR, "notasite=pallas")
    with pytest.raises(ValueError, match="bad pair"):
        kernels.backend_for("topk")


def test_threaded_table_choice_keeps_fallback_semantics(
    tuned_kernels, monkeypatch
):
    """Consumers resolve the gate once and re-pass the result as
    ``backend=`` (the lru-key idiom). A TABLE-chosen pallas threaded
    through that way must keep warn-and-fallback on unsupported
    operands — only a backend DISAGREEING with the gate is an explicit
    per-call request that refuses loudly."""
    tuned_kernels({"kernel_backend_topk": "pallas"})
    # Simulate a compiled (non-interpret) target: float64 unsupported.
    monkeypatch.setenv(kernels.ENV_INTERPRET_VAR, "0")
    x = jnp.asarray(np.random.default_rng(8).normal(size=(4, 16)))
    assert x.dtype == jnp.float64
    threaded = kernels.topk_backend()
    assert threaded == "pallas"
    # table choice threaded through: degrades to the XLA result.
    rv, ri = kernels.top_k(x, 3, backend=threaded)
    ev, ei = jax.lax.top_k(x, 3)
    assert np.asarray(rv).tobytes() == np.asarray(ev).tobytes()
    assert np.asarray(ri).tobytes() == np.asarray(ei).tobytes()
    # the same operands under a genuinely explicit request refuse.
    monkeypatch.setenv(ENV_VAR, "topk=xla")   # gate now says xla ...
    with pytest.raises(KernelUnsupportedError):
        kernels.top_k(x, 3, backend="pallas")  # ... arg disagrees


def test_table_chosen_backend_falls_back_warn_once(tuned_kernels):
    """A TABLE-chosen pallas backend degrades to XLA on unsupported
    operands (never crashes a consumer the user didn't gate) — the
    same never-crash discipline as a stale autotune entry."""
    tuned_kernels({"kernel_backend_segment_sum": "pallas"})
    assert kernels.backend_for("segment_sum") == "pallas"
    # integer values are unsupported — table choice falls back, loudly
    # in the log but without raising, and still computes correctly.
    out = kernels.segment_sum(
        jnp.arange(6), jnp.asarray([0, 0, 1, 1, 2, 2], jnp.int32), 3,
    )
    assert np.array_equal(np.asarray(out), [1, 5, 9])


# -- gate precedence ---------------------------------------------------------


def test_gate_defaults_off():
    """No env, no table entry (or the committed xla entries): every
    site resolves to XLA — Pallas is strictly opt-in-by-measurement."""
    for site in kernels.SITES:
        assert kernels.backend_for(site) == "xla"


def test_gate_precedence_env_over_table_over_default(
    tuned_kernels, monkeypatch
):
    tuned_kernels({"kernel_backend_topk": "pallas"})
    # table layer supplies the default ...
    assert kernels.backend_for("topk") == "pallas"
    # ... other sites keep the static default ...
    assert kernels.backend_for("segment_sum") == "xla"
    # ... the env var beats the table ...
    monkeypatch.setenv(ENV_VAR, "topk=xla")
    assert kernels.backend_for("topk") == "xla"
    # ... a global env value covers every site ...
    monkeypatch.setenv(ENV_VAR, "pallas")
    for site in kernels.SITES:
        assert kernels.backend_for(site) == "pallas"
    # ... and FLINKML_TPU_AUTOTUNE=0 turns the table layer off.
    monkeypatch.delenv(ENV_VAR)
    monkeypatch.setenv(ENV_DISABLE_VAR, "0")
    assert kernels.backend_for("topk") == "xla"


def test_factory_backends_follow_gate(monkeypatch):
    from flinkml_tpu.models._linear_sgd import _segsum_backend

    assert _segsum_backend() == "xla"
    assert kernels.topk_backend() == "xla"
    monkeypatch.setenv(ENV_VAR, "pallas")
    assert _segsum_backend() == "pallas"
    assert kernels.topk_backend() == "pallas"


# -- compile cache: backend is key material ----------------------------------


def test_backend_joins_program_and_aot_cache_key(
    tmp_path, fusion_cache, monkeypatch
):
    """The would-have-aliased regression: flipping the gate must
    compile a NEW program under a key differing exactly in the backend
    element — against the in-memory cache AND the persistent AOT store
    — and flipping back must hit the original entry, not recompile."""
    keys = []
    pipeline_fusion.on_compile.append(keys.append)
    compile_cache.configure(str(tmp_path / "aot"))
    try:
        model, t = _chain_model(rows=48)
        ref = _outputs(model, t)
        n_xla = len(keys)
        assert n_xla > 0 and all(k[-1] == "xla" for k in keys)

        monkeypatch.setenv(ENV_VAR, "fused_chain=pallas")
        got = _outputs(model, t)
        pallas_keys = keys[n_xla:]
        assert pallas_keys, "gate flip did not compile a new program"
        assert all(k[-1] == "pallas" for k in pallas_keys)
        # identical but for the backend element — the would-have-aliased
        # pair.
        assert pallas_keys[0][:-1] == keys[0][:-1]
        for c in ref:
            assert ref[c].tobytes() == got[c].tobytes(), c

        # the persistent store addresses the two programs as DISTINCT
        # artifacts — and stripped of the backend element they would
        # have aliased one on-disk entry (the exact bug this guards).
        store = compile_cache.active_store()
        path_xla = store.entry_path(("pipeline_fusion", keys[0]))
        path_pallas = store.entry_path(("pipeline_fusion", pallas_keys[0]))
        assert path_xla != path_pallas
        assert store.entry_path(("pipeline_fusion", keys[0][:-1])) == \
            store.entry_path(("pipeline_fusion", pallas_keys[0][:-1]))

        # flipping back hits the original executable: zero new compiles.
        monkeypatch.delenv(ENV_VAR)
        n_before = len(keys)
        again = _outputs(model, t)
        assert len(keys) == n_before
        for c in ref:
            assert ref[c].tobytes() == again[c].tobytes(), c
    finally:
        compile_cache.reset()


def test_aot_round_trip_with_pallas_program(tmp_path, fusion_cache,
                                            monkeypatch):
    """The Pallas backend rides the AOT store's never-crash ladder:
    after dropping the in-memory layer, a re-transform either LOADS the
    serialized executable (zero compiles) or — where this jax build's
    CPU export cannot serialize the program — recompiles through the
    store's loud ``fallbacks`` path. Both legs must serve bitwise-equal
    outputs; a crash or silent wrong answer fails either way."""
    from flinkml_tpu.utils.metrics import metrics

    compile_cache.configure(str(tmp_path / "aot"))
    try:
        monkeypatch.setenv(ENV_VAR, "fused_chain=pallas")
        model, t = _chain_model(rows=48)
        ref = _outputs(model, t)
        group = metrics.group("pipeline.fusion")
        store_group = metrics.group("compile_cache")
        compiles = []
        pipeline_fusion.on_compile.append(compiles.append)
        pipeline_fusion.reset_cache()   # drop memory, keep disk
        loads_before = group.snapshot()["counters"].get("aot_loads", 0)
        got = _outputs(model, t)
        loads_after = group.snapshot()["counters"].get("aot_loads", 0)
        if compiles:
            # the store must have refused serialization LOUDLY, never
            # silently recompiled a persistable program.
            counters = store_group.snapshot()["counters"]
            assert counters.get("fallbacks", 0) > 0, counters
        else:
            assert loads_after > loads_before
        for c in ref:
            assert ref[c].tobytes() == got[c].tobytes(), c
    finally:
        compile_cache.reset()
