"""Multi-process worker: sustained cross-process collective dispatch.

Regression for the multi-process in-flight-dispatch deadlock: a bare host
loop enqueueing 60 ``psum`` steps with no synchronization wedges a
2-process Gloo mesh permanently (threshold between 20 and 60 in-flight).
``synced_loop`` is the framework's backpressure policy (the role Flink's
credit-based flow control plays under ``AllReduceImpl.java:52-299``);
this worker (launched as an N-process pod) drives 80 sustained steps through it — more than the wedge
trigger — and checks the numeric result.

Usage: python _sync_cadence_worker.py <port> <process_id> <num_processes>
Prints ``CADENCE_OK <pid>`` on success.
"""

import os
import sys

port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from flinkml_tpu.parallel import (  # noqa: E402
    DeviceMesh,
    default_sync_interval,
    init_distributed,
    synced_loop,
)

init_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc,
    process_id=pid,
)
assert default_sync_interval() > 0, (
    "multi-process mesh must default to a bounded dispatch interval"
)

dm = DeviceMesh()
axis = DeviceMesh.DATA_AXIS

def body(acc, contrib):
    return acc + jax.lax.psum(jnp.sum(contrib, 0), axis)

stepper = jax.jit(jax.shard_map(
    body, mesh=dm.mesh, in_specs=(P(), P(axis)), out_specs=P(),
))

n_dev = dm.num_devices
contrib_local = np.ones((jax.local_device_count(), 2), dtype=np.float32)
contrib = jax.make_array_from_process_local_data(
    dm.data_sharding(), contrib_local
)

N_STEPS = 80  # > the 60-step trigger that wedges an unsynchronized loop
acc = synced_loop(N_STEPS, lambda c, i: stepper(c, contrib),
                  jnp.zeros(2, jnp.float32))
got = np.asarray(acc.addressable_shards[0].data)
assert np.allclose(got, N_STEPS * n_dev), (got, N_STEPS * n_dev)

print(f"CADENCE_OK {pid}", flush=True)
