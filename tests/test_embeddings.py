"""flinkml_tpu.embeddings — the sharded-embedding-table subsystem.

The acceptance ladder (ISSUE 14), all on the conftest 8-virtual-device
CPU mesh: exchange parity vs dense references, strategy gating, the
over-budget refuse/route contract, world-8 -> world-2 elastic resume,
mixed-precision serving, and the three consumers (W2V re-expressed on
the primitive, FM's sharded factor matrix, ALS's loud refusal +
factor-table export).
"""

import json
import os

import numpy as np
import pytest

import jax

from flinkml_tpu.embeddings import (
    EmbeddingTable,
    dense_vocab_threshold,
    resolve_exchange,
    shard_rows_for,
)
from flinkml_tpu.embeddings import exchange
from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.sharding import (
    EMBEDDING,
    FSDP,
    FSDP_TP,
    REPLICATED,
    NoFeasiblePlanError,
    infer_plan,
    is_embedding_param,
)
from flinkml_tpu.table import Table


def _table(vocab=1000, dim=16, seed=0, **kw):
    rng = np.random.default_rng(seed)
    rows = rng.normal(size=(vocab, dim)).astype(np.float32)
    mesh = kw.pop("mesh", None) or DeviceMesh.for_plan(EMBEDDING)
    return rows, EmbeddingTable("t", vocab, dim, mesh=mesh,
                                plan=kw.pop("plan", EMBEDDING),
                                rows=rows, **kw)


# ---------------------------------------------------------------------------
# exchange primitives
# ---------------------------------------------------------------------------

def test_family_naming_convention():
    assert is_embedding_param("w2v/center_embedding")
    assert is_embedding_param("t/embedding_slot0")
    assert not is_embedding_param("coef")
    assert shard_rows_for(1000, 8) == 125
    assert shard_rows_for(1001, 8) == 126


def test_lookup_bitwise_vs_dense_and_across_strategies():
    """Lookups are exact (one owning shard per id), so they match the
    dense gather BITWISE — the property serving stability rests on."""
    rows, t = _table()
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 1000, 512).astype(np.int32)
    assert t.n_shards == 8 and t.sharded
    got = np.asarray(t.lookup(ids))
    assert got.tobytes() == rows[ids].tobytes()


@pytest.mark.parametrize("strategy", ["ring", "all_to_all"])
def test_scatter_add_matches_dense_reference(strategy):
    """Both exchange strategies reproduce the dense np.add.at scatter
    (duplicate ids included) up to f32 summation order."""
    rows, t = _table()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 1000, 777).astype(np.int32)  # odd count: pads
    delta = rng.normal(size=(777, 16)).astype(np.float32)
    t.scatter_add(ids, delta, strategy=strategy)
    ref = rows.copy()
    np.add.at(ref, ids, delta)
    np.testing.assert_allclose(t.to_host(), ref, rtol=1e-5, atol=1e-5)


def test_exchange_strategy_resolution():
    """env > autotune > static; dense_psum is the below-threshold
    placement (subsuming W2V's old static threshold) and never the
    sharded algorithm."""
    assert resolve_exchange(10, 1) == "dense_psum"
    assert resolve_exchange(dense_vocab_threshold(), 8) == "dense_psum"
    over = dense_vocab_threshold() + 1
    assert resolve_exchange(over, 8) in ("ring", "all_to_all")
    env = dict(os.environ)
    try:
        os.environ["FLINKML_TPU_EMBEDDING_EXCHANGE"] = "ring"
        assert resolve_exchange(over, 8) == "ring"
        os.environ["FLINKML_TPU_EMBEDDING_EXCHANGE"] = "bogus"
        with pytest.raises(ValueError, match="bogus"):
            resolve_exchange(over, 8)
        # An EXPLICIT dense_psum request on a sharded table is refused
        # loudly (it is a placement, not an exchange) with the
        # threshold-var remedy in the message — never silently
        # rewritten to ring.
        os.environ["FLINKML_TPU_EMBEDDING_EXCHANGE"] = "dense_psum"
        with pytest.raises(ValueError, match="vocab threshold"):
            resolve_exchange(over, 8)
        # the back-compat W2V threshold alias still works
        os.environ.pop("FLINKML_TPU_EMBEDDING_EXCHANGE")
        os.environ["FLINKML_W2V_SHARD_VOCAB"] = "0"
        assert resolve_exchange(10, 8) in ("ring", "all_to_all")
    finally:
        os.environ.clear()
        os.environ.update(env)


def test_scatter_add_validates_strategy_even_unsharded():
    """A typo'd strategy must fail on a small (unsharded) table too —
    not first in production sharded use."""
    t = EmbeddingTable("small", 16, 4, plan=REPLICATED,
                       mesh=DeviceMesh.for_plan(REPLICATED))
    assert not t.sharded
    with pytest.raises(ValueError, match="unknown exchange strategy"):
        t.scatter_add(np.zeros(2, np.int32), np.zeros((2, 4)),
                      strategy="all_to_al")


def test_footprint_model_agrees_with_padded_placement():
    """infer_plan's footprint is the LARGEST slice (per-dim ceil), so a
    plan it accepts can never fail the table's padded FML503 check at
    the same budget — the indivisible-vocab boundary case."""
    from flinkml_tpu.sharding import per_device_state_bytes

    mesh = {"data": 1, "fsdp": 4, "tp": 2}
    vocab, dim = 8193, 64  # 8193 % 8 != 0: padded rows = 1025 per shard
    shapes = {"edge/embedding": (vocab, dim)}
    padded = 1025 * dim * 4 * 2
    assert per_device_state_bytes(EMBEDDING, mesh, shapes,
                                  optimizer_slots=1) == padded
    # Exactly at the padded footprint: infer_plan routes AND the table
    # constructs (its padded validation sees the same number).
    t = EmbeddingTable("edge", vocab, dim,
                       mesh=DeviceMesh.for_plan(EMBEDDING),
                       hbm_budget_bytes=padded, optimizer_slots=1)
    assert t.plan.name == "embedding" and t.shard_rows == 1025
    # One byte under: refused consistently (NoFeasiblePlanError from
    # the route, never a post-route PlanValidationError surprise).
    with pytest.raises(NoFeasiblePlanError):
        EmbeddingTable("edge", vocab, dim,
                       mesh=DeviceMesh.for_plan(EMBEDDING),
                       hbm_budget_bytes=padded - 1, optimizer_slots=1)


def test_unknown_strategy_refused_in_exchange():
    with pytest.raises(ValueError, match="dense_psum is a placement"):
        exchange.gather((), axes="data", n_shards=8, shard_rows=1,
                        strategy="dense_psum")
    with pytest.raises(ValueError, match="dense_psum is a placement"):
        exchange.scatter_add((), (), axes="data", n_shards=8,
                             shard_rows=1, strategy="dense_psum")


# ---------------------------------------------------------------------------
# refuse / route: the over-budget contract
# ---------------------------------------------------------------------------

def test_over_budget_vocab_refused_replicated_and_routed_sharded():
    """THE acceptance gate: a vocab whose table + optimizer state
    provably exceeds the per-device budget is (a) refused replicated by
    FML503 and (b) routed to the embedding plan by infer_plan."""
    from flinkml_tpu.sharding.apply import PlanValidationError

    mesh = DeviceMesh.for_plan(EMBEDDING)
    vocab, dim = 1 << 16, 16
    rep_bytes = vocab * dim * 4 * 2          # table + 1 slot
    budget = rep_bytes // 6                  # /4 over, /8 fits
    with pytest.raises(PlanValidationError, match="FML503"):
        EmbeddingTable("big", vocab, dim, mesh=mesh, plan=REPLICATED,
                       hbm_budget_bytes=budget, optimizer_slots=1)
    t = EmbeddingTable("big/embedding_probe", vocab, dim, mesh=mesh,
                       hbm_budget_bytes=budget, optimizer_slots=1)
    assert t.plan.name == "embedding" and t.n_shards == 8
    assert t.per_device_bytes() <= budget
    with pytest.raises(NoFeasiblePlanError):
        EmbeddingTable("huge/embedding_probe", vocab, dim, mesh=mesh,
                       hbm_budget_bytes=rep_bytes // 32,
                       optimizer_slots=1)


def test_row_splitting_plan_refused():
    """FSDP_TP splits dim 1 of a [vocab, dim] table — the layout the
    exchange primitives cannot host; refused loudly at construction."""
    with pytest.raises(ValueError, match="WHOLE rows"):
        EmbeddingTable("t", 64, 8, mesh=DeviceMesh.for_plan(FSDP_TP),
                       plan=FSDP_TP)


def test_fsdp_plan_is_a_legal_row_layout():
    """FSDP shards rows over fsdp only (dim intact) — a legal embedding
    layout with 4 shards on the 8-device EMBEDDING-shaped mesh."""
    rows, t = _table(plan=FSDP, mesh=DeviceMesh.for_plan(EMBEDDING))
    assert t.n_shards == 4
    ids = np.arange(100, dtype=np.int32)
    assert np.asarray(t.lookup(ids)).tobytes() == rows[:100].tobytes()


# ---------------------------------------------------------------------------
# checkpoint: world-8 -> world-2 elastic resume
# ---------------------------------------------------------------------------

def test_world8_to_world2_resume_bit_equal(tmp_path):
    rows, t = _table(vocab=1001, optimizer_slots=2)  # odd vocab: pads
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 1001, 256).astype(np.int32)
    delta = rng.normal(size=(256, 16)).astype(np.float32)
    t.scatter_add(ids, delta)
    mgr = CheckpointManager(str(tmp_path), rescale="reshard")
    t.save(mgr, 7)
    with open(tmp_path / "ckpt-7" / "meta.json") as fh:
        meta = json.load(fh)
    # plan-derived tags: the table AND both optimizer slots are
    # sharded:0 (slots land in the same *embedding* family).
    assert meta["layouts"] == ["sharded:0"] * 3
    mesh2 = DeviceMesh.for_plan(EMBEDDING, devices=jax.devices()[:2])
    t2, epoch = EmbeddingTable.restore(
        mgr, "t", 1001, 16, mesh=mesh2, plan=EMBEDDING, optimizer_slots=2
    )
    assert epoch == 7 and t2.n_shards == 2
    assert t2.to_host().tobytes() == t.to_host().tobytes()
    # lookups after the reshard serve identical bytes (the serving
    # stability contract across world sizes).
    q = rng.integers(0, 1001, 64).astype(np.int32)
    assert np.asarray(t2.lookup(q)).tobytes() == \
        np.asarray(t.lookup(q)).tobytes()


def test_restore_without_snapshot_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    with pytest.raises(ValueError, match="no checkpoint"):
        EmbeddingTable.restore(mgr, "t", 10, 4)


# ---------------------------------------------------------------------------
# serving: slice-mesh pool, mixed precision
# ---------------------------------------------------------------------------

def test_pool_serving_bitwise_stable_and_bf16_tolerance():
    from flinkml_tpu.embeddings.serving import EmbeddingLookupModel
    from flinkml_tpu.serving.engine import ServingConfig
    from flinkml_tpu.serving.pool import ReplicaPool, slice_meshes

    rng = np.random.default_rng(4)
    vocab, dim = 2048, 16
    rows = rng.normal(size=(vocab, dim)).astype(np.float32)
    ids = rng.integers(0, vocab, size=(48, 5)).astype(np.int32)
    ids[ids % 11 == 0] = -1
    model = EmbeddingLookupModel(rows, plan=EMBEDDING,
                                 precision="mixed_inference")
    (unbound,) = EmbeddingLookupModel(
        rows, precision="mixed_inference").transform(Table({"ids": ids}))
    pool = ReplicaPool(
        model, Table({"ids": ids[:8]}),
        config=ServingConfig(max_batch_rows=64, max_wait_ms=1.0),
        meshes=slice_meshes(2, plan=EMBEDDING), output_cols=("vector",),
        name="emb_test_pool",
    ).start()
    try:
        v1 = pool.predict({"ids": ids}).columns["vector"]
        v2 = pool.predict({"ids": ids}).columns["vector"]
    finally:
        pool.stop()
    # bitwise-stable across requests AND vs the single-device reference.
    assert v1.tobytes() == v2.tobytes()
    assert v1.tobytes() == np.asarray(unbound.column("vector")).tobytes()
    # mixed-precision tolerance pin: bf16 compute within bf16 epsilon
    # of the f32 pooling (values here are O(1)).
    (f32,) = EmbeddingLookupModel(rows, precision=None).transform(
        Table({"ids": ids}))
    diff = np.abs(v1 - np.asarray(f32.column("vector"))).max()
    assert 0 < diff < 0.05, diff  # bf16 really engaged, and bounded


def test_slice_meshes_plan_shaping():
    from flinkml_tpu.serving.pool import slice_meshes

    meshes = slice_meshes(2, devices=jax.devices()[:8], plan=EMBEDDING)
    assert [dict(m.mesh.shape) for m in meshes] == \
        [{"data": 1, "fsdp": 2, "tp": 2}] * 2
    flat = slice_meshes(4, devices=jax.devices()[:8])
    assert [dict(m.mesh.shape) for m in flat] == [{"data": 2}] * 4


# ---------------------------------------------------------------------------
# consumer: Word2Vec re-expressed on the primitive
# ---------------------------------------------------------------------------

def _w2v_corpus(seed=3):
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow", "sheep", "goat"]
    tools = ["hammer", "saw", "drill", "wrench", "screw", "nail"]
    docs = []
    for _ in range(120):
        pool = animals if rng.random() < 0.5 else tools
        docs.append(list(rng.choice(pool, size=8)))
    return docs


@pytest.mark.parametrize("strategy", ["ring", "all_to_all"])
def test_w2v_sharded_strategies_match_dense(monkeypatch, strategy):
    """W2V's sharded SGNS trainer, re-expressed on the exchange
    primitives, reproduces the dense trainer's vectors under BOTH
    strategies (identical sampling sequence; f32 order differs only
    through the exchange's partial adds) — the W2V-primitive-vs-ring
    pinned parity."""
    from flinkml_tpu.models.word2vec import Word2Vec

    docs = _w2v_corpus()
    t = Table({"doc": np.asarray(docs, dtype=object)})

    def fit():
        return Word2Vec().set_input_col("doc").set_vector_size(12) \
            .set_max_iter(2).set_min_count(1).set_seed(0).fit(t)

    dense = fit()
    monkeypatch.setenv("FLINKML_W2V_SHARD_VOCAB", "0")
    monkeypatch.setenv("FLINKML_TPU_EMBEDDING_EXCHANGE", strategy)
    sharded = fit()
    np.testing.assert_array_equal(sharded.vocabulary, dense.vocabulary)
    np.testing.assert_allclose(sharded.vectors, dense.vectors,
                               rtol=2e-3, atol=2e-4)


def test_w2v_ring_and_a2a_gathers_agree_bitwise(monkeypatch):
    """The two strategies' GATHER halves are exactly equal (one owning
    shard per id); end-to-end the fits differ only by scatter summation
    order — pinned tight."""
    from flinkml_tpu.models.word2vec import Word2Vec

    docs = _w2v_corpus(seed=5)
    t = Table({"doc": np.asarray(docs, dtype=object)})
    monkeypatch.setenv("FLINKML_W2V_SHARD_VOCAB", "0")

    out = {}
    for strategy in ("ring", "all_to_all"):
        monkeypatch.setenv("FLINKML_TPU_EMBEDDING_EXCHANGE", strategy)
        out[strategy] = Word2Vec().set_input_col("doc") \
            .set_vector_size(8).set_max_iter(1).set_min_count(1) \
            .set_seed(0).fit(t).vectors
    np.testing.assert_allclose(out["ring"], out["all_to_all"],
                               rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# consumer: FM's sharded factor matrix
# ---------------------------------------------------------------------------

def _fm_data(n=512, d=24, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    true = rng.normal(size=d)
    y = (x @ true > 0).astype(np.float64)
    return Table({"features": x, "label": y}), x, y


def test_fm_sharded_factors_quality_parity():
    """FMClassifier under the EMBEDDING plan shards V/w + Adam slots and
    follows the dense trainer's sampling trajectory; the end-model pin
    is quality parity (Adam's sign normalization amplifies f32
    summation-order noise, so per-coordinate parity is not a valid
    contract — see the trainer docstring)."""
    from flinkml_tpu.models.fm import FMClassifier
    from flinkml_tpu.sharding import EMBEDDING

    t, x, y = _fm_data()
    dense = FMClassifier().set_max_iter(40).set_global_batch_size(256)\
        .fit(t)
    shard = FMClassifier(sharding_plan=EMBEDDING).set_max_iter(40)\
        .set_global_batch_size(256).fit(t)
    assert shard._v.shape == dense._v.shape
    (pd,) = dense.transform(t)
    (ps,) = shard.transform(t)
    yd = np.asarray(pd.column("prediction"))
    ys = np.asarray(ps.column("prediction"))
    acc_d = (yd == y).mean()
    acc_s = (ys == y).mean()
    assert acc_s >= acc_d - 0.05, (acc_s, acc_d)
    assert (yd == ys).mean() >= 0.9, (yd != ys).sum()


def test_fm_sharded_first_step_margins_match_dense():
    """One-step pin at the gradient level: the sharded trainer's
    column-psum'd forward margins equal the dense FM margins to f32
    tolerance — the numerics contract underneath the quality pin."""
    from flinkml_tpu.models.fm import FMRegressor
    from flinkml_tpu.sharding import FSDP

    t, x, y = _fm_data(seed=2)
    # tol=inf-ish via 1 step: compare the one-step w0 (a pure function
    # of the first batch's margins) between the layouts. The label is
    # SHIFTED so the mean margin is decisively nonzero: Adam's first
    # step is ±lr·g/(|g|+eps) — with a near-zero g (the unshifted
    # x[:, 0] label for this seed) the w0 SIGN becomes a coin flip on
    # the two layouts' reduction order, a full-suite flake observed
    # once (shard -0.0999992 vs dense +0.0999993); the margins
    # themselves (the contract under test) match either way.
    label = x[:, 0] + 1.0
    dense = FMRegressor().set_max_iter(1).set_global_batch_size(256)\
        .fit(Table({"features": x, "label": label}))
    shard = FMRegressor(sharding_plan=FSDP).set_max_iter(1)\
        .set_global_batch_size(256)\
        .fit(Table({"features": x, "label": label}))
    np.testing.assert_allclose(shard._w0, dense._w0, rtol=1e-4,
                               atol=1e-6)


def test_fm_streamed_fit_refuses_plan():
    from flinkml_tpu.models.fm import FMClassifier
    from flinkml_tpu.sharding import EMBEDDING

    t, _, _ = _fm_data(n=64)
    est = FMClassifier(sharding_plan=EMBEDDING)
    with pytest.raises(ValueError, match="streamed fit does not thread"):
        est.fit([t, t])


def test_fm_replicated_plan_refused():
    from flinkml_tpu.models.fm import FMClassifier
    from flinkml_tpu.sharding import BATCH_PARALLEL

    t, _, _ = _fm_data(n=64)
    with pytest.raises(ValueError, match="leaves the FM factor family"):
        FMClassifier(sharding_plan=BATCH_PARALLEL).fit(t)


def test_fm_row_splitting_plan_refused():
    from flinkml_tpu.models.fm import FMClassifier
    from flinkml_tpu.sharding import FSDP_TP

    t, _, _ = _fm_data(n=64)
    with pytest.raises(ValueError, match="factor rows whole"):
        FMClassifier(sharding_plan=FSDP_TP).fit(t)


# ---------------------------------------------------------------------------
# consumer: ALS — loud refusal + factor-table export
# ---------------------------------------------------------------------------

def _als_model():
    from flinkml_tpu.models.als import ALS

    rng = np.random.default_rng(0)
    n = 400
    t = Table({
        "user": rng.integers(0, 24, n),
        "item": rng.integers(0, 16, n),
        "rating": rng.random(n) * 5,
    })
    return ALS().set_max_iter(2).fit(t)


def test_als_fit_refuses_sharding_plan():
    from flinkml_tpu.models.als import ALS

    with pytest.raises(ValueError, match="normal-equation buffers"):
        ALS(sharding_plan=EMBEDDING).fit(Table({
            "user": np.zeros(4, np.int64),
            "item": np.zeros(4, np.int64),
            "rating": np.ones(4),
        }))


def test_als_factor_tables_export_sharded():
    model = _als_model()
    user_t, item_t = model.factor_tables(plan=EMBEDDING)
    assert user_t.sharded and item_t.sharded
    np.testing.assert_allclose(
        user_t.to_host(), model.user_factors.astype(np.float32),
        rtol=1e-6, atol=1e-7,
    )
    ids = np.arange(len(model.user_factors), dtype=np.int32)
    got = np.asarray(user_t.lookup(ids))
    assert got.tobytes() == user_t.to_host()[ids].tobytes()
