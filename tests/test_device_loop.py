"""device_iterate: the whole-loop-on-device mode (lax.while_loop).

The highest-performance iteration mode (zero host round-trips per epoch);
its termination semantics must match the host runtime's
TerminateOnMaxIterOrTol exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.iteration.device_loop import device_iterate
from flinkml_tpu.iteration.runtime import (
    IterationConfig,
    TerminateOnMaxIterOrTol,
    iterate,
)


def test_runs_exactly_max_iter_without_tol():
    state, epochs, criteria = device_iterate(
        lambda s, e: (s + 1.0, s), jnp.asarray(0.0), max_iter=7
    )
    assert int(epochs) == 7
    assert float(state) == 7.0


def test_tol_stops_early():
    # criteria = 10 - epoch; tol 5.5 -> stops when 10 - e <= 5.5 (e = 5),
    # i.e. after epoch index 5 has run -> 6 epochs.
    state, epochs, criteria = device_iterate(
        lambda s, e: (s, 10.0 - e.astype(jnp.float32)),
        jnp.asarray(0.0), max_iter=100, tol=5.5,
    )
    assert int(epochs) == 6
    assert float(criteria) <= 5.5


def test_matches_host_runtime_trajectory():
    """Same step, same termination: device loop == host iterate."""

    def step(s, e):
        s = s * 0.5 + 1.0
        return s, jnp.abs(s - 2.0)

    d_state, d_epochs, _ = device_iterate(
        step, jnp.asarray(0.0), max_iter=50, tol=1e-3
    )
    h = iterate(
        lambda s, e: step(s, jnp.asarray(e)),
        jnp.asarray(0.0),
        config=IterationConfig(TerminateOnMaxIterOrTol(50, 1e-3)),
    )
    assert int(d_epochs) == h.epochs
    np.testing.assert_allclose(float(d_state), float(h.state), rtol=1e-6)


def test_pytree_state_and_single_compile():
    traces = {"n": 0}

    def step(s, e):
        traces["n"] += 1
        return {"a": s["a"] + s["b"], "b": s["b"]}, jnp.asarray(1.0)

    init = {"a": jnp.zeros(3), "b": jnp.ones(3)}
    state, epochs, _ = device_iterate(step, init, max_iter=10)
    np.testing.assert_array_equal(np.asarray(state["a"]), np.full(3, 10.0))
    # Traced once (whole loop is one XLA program), not once per epoch.
    assert traces["n"] == 1


def test_nan_criteria_terminates():
    """NaN <= tol is False — the loop must still stop at max_iter, not
    spin forever."""
    state, epochs, criteria = device_iterate(
        lambda s, e: (s, jnp.asarray(float("nan"))),
        jnp.asarray(0.0), max_iter=5, tol=1e-6,
    )
    assert int(epochs) == 5
