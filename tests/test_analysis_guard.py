"""Pass 3 (transfer/retrace guard) tests + the satellite regressions that
ride on the fused executor: the constant weak_type cache-key fix and the
LazyDeviceColumn donated-buffer error paths.
"""

import numpy as np
import pytest

from flinkml_tpu import pipeline_fusion
from flinkml_tpu.analysis import GuardViolation, TransferRetraceGuard
from flinkml_tpu.api import ColumnKernel
from flinkml_tpu.models.scalers import MaxAbsScaler, StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.table import LazyDeviceColumn, Table


@pytest.fixture(autouse=True)
def _fusion_state():
    pipeline_fusion.set_enabled(True)
    pipeline_fusion.reset_cache()
    saved = list(pipeline_fusion.on_compile)
    yield
    pipeline_fusion.on_compile[:] = saved
    pipeline_fusion.set_enabled(True)
    pipeline_fusion.reset_cache()


def _data(n=60, d=4, seed=0):
    rng = np.random.default_rng(seed)
    return Table({"features": rng.normal(size=(n, d))})


def _two_stage_chain(t):
    a = StandardScaler().set(StandardScaler.INPUT_COL, "features").set(
        StandardScaler.OUTPUT_COL, "a"
    ).fit(t)
    b = MaxAbsScaler().set(MaxAbsScaler.INPUT_COL, "a").set(
        MaxAbsScaler.OUTPUT_COL, "b"
    ).fit(a.transform(t)[0])
    return PipelineModel([a, b])


# ---------------------------------------------------------------------------
# guard semantics
# ---------------------------------------------------------------------------

def test_warm_hot_loop_passes_with_zero_budget():
    t = _data()
    pm = _two_stage_chain(t)
    pm.transform(t)  # warmup compile outside the guard
    with TransferRetraceGuard(allow_compiles=0):
        for rows in (60, 33, 47, 64):  # one 64-row bucket
            pm.transform(t.slice(0, rows))


def test_new_chain_compile_inside_guard_violates():
    t = _data()
    pm = _two_stage_chain(t)
    with pytest.raises(GuardViolation) as err:
        with TransferRetraceGuard(allow_compiles=0):
            pm.transform(t)  # cold chain: compiles in-region
    assert any(f.rule == "FML402" for f in err.value.findings)
    # The same loop with a declared budget passes.
    pipeline_fusion.reset_cache()
    with TransferRetraceGuard(allow_compiles=1):
        pm.transform(t)


def test_new_bucket_compile_is_policy_allowed():
    t = _data(n=200)
    pm = _two_stage_chain(t)
    pm.transform(t.slice(0, 60))  # warm the 64 bucket
    with TransferRetraceGuard(allow_compiles=0, allow_new_buckets=True):
        pm.transform(t.slice(0, 129))  # 256 bucket: allowed
    pipeline_fusion.reset_cache()
    pm.transform(t.slice(0, 60))
    with pytest.raises(GuardViolation):
        with TransferRetraceGuard(allow_compiles=0,
                                  allow_new_buckets=False):
            pm.transform(t.slice(0, 129))


def test_transfer_budgets_fml401():
    t = _data()
    pm = _two_stage_chain(t)
    pm.transform(t)
    guard = TransferRetraceGuard(
        allow_compiles=0, allow_host_to_device=0,
        raise_on_violation=False,
    )
    with guard:
        fresh = _data(seed=1)  # a NEW table: its upload is "implicit"
        pm.transform(fresh)
    assert [f.rule for f in guard.findings] == ["FML401"]

    # Device->host reads inside the region are caught too.
    (out,) = pm.transform(t)
    guard2 = TransferRetraceGuard(
        allow_compiles=0, allow_device_to_host=0, raise_on_violation=False,
    )
    with guard2:
        out.column("b")
    assert [f.rule for f in guard2.findings] == ["FML401"]


def test_guard_reports_not_raises_when_asked():
    t = _data()
    pm = _two_stage_chain(t)
    guard = TransferRetraceGuard(allow_compiles=0, raise_on_violation=False)
    with guard:
        pm.transform(t)
    assert guard.findings and guard.findings[0].rule == "FML402"


@pytest.mark.no_retrace(allow_compiles=1)
def test_no_retrace_marker_budgets_warmup():
    """The pytest marker wraps the test in the guard: one compile for the
    cold chain is budgeted, the following varied-size calls must all hit
    the cache (a retrace here fails this test via GuardViolation)."""
    t = _data()
    pm = _two_stage_chain(t)
    for rows in (60, 33, 47):
        pm.transform(t.slice(0, rows))


def _fp_chain(fp_suffix):
    """A chain identical in everything but its fingerprint — the shape an
    unstable fingerprint produces on every call."""
    def f1(cols, c, valid):
        return {"y": cols["x"] * 2.0}

    def f2(cols, c, valid):
        return {"z": cols["y"] + 0}

    return [
        ColumnKernel(("x",), ("y",), f1, fingerprint=("mul", fp_suffix)),
        ColumnKernel(("y",), ("z",), f2, fingerprint=("id",)),
    ]


def test_fingerprint_churn_flagged_fml403_but_pair_is_not():
    t = Table({"x": np.ones(10)})
    # Two distinct chains (an A/B pair) with the same shapes: budgeted,
    # NOT churn.
    guard = TransferRetraceGuard(allow_compiles=2, raise_on_violation=False)
    with guard:
        pipeline_fusion.execute_kernel_chain(t, _fp_chain(0))
        pipeline_fusion.execute_kernel_chain(t, _fp_chain(1))
    assert not guard.findings, [f.rule for f in guard.findings]
    # Three+ fingerprints over identical specs = churn.
    pipeline_fusion.reset_cache()
    guard = TransferRetraceGuard(allow_compiles=3, raise_on_violation=False)
    with guard:
        for i in range(3):
            pipeline_fusion.execute_kernel_chain(t, _fp_chain(i))
    assert "FML403" in [f.rule for f in guard.findings]


# ---------------------------------------------------------------------------
# satellite: constant weak_type in the compile-cache key
# ---------------------------------------------------------------------------

def _mul_chain(const):
    """Two-kernel chain whose first kernel multiplies by a constant; a
    python-float constant is weak float64, an np scalar is strong."""
    def mul(cols, c, valid):
        return {"y": cols["x"] * c["k"]}

    def ident(cols, c, valid):
        return {"z": cols["y"] + 0}

    return [
        ColumnKernel(("x",), ("y",), mul, {"k": const}, ("mul",)),
        ColumnKernel(("y",), ("z",), ident, fingerprint=("ident",)),
    ]


def test_constant_weak_type_does_not_alias_cached_program():
    """Regression: the cache key once recorded only (dtype, shape) of each
    constant. A weak-float64 constant (python scalar) and a strong-float64
    constant then aliased one executable even though they trace to
    DIFFERENT programs over float32 columns (weak * f32 -> f32,
    strong * f32 -> f64) — the second caller silently got the first
    caller's dtypes. The key now includes weak_type."""
    t = Table({"x": np.ones(10, dtype=np.float32)})
    weak = pipeline_fusion.execute_kernel_chain(t, _mul_chain(2.0))
    strong = pipeline_fusion.execute_kernel_chain(
        t, _mul_chain(np.float64(2.0))
    )
    assert weak.column("z").dtype == np.float32
    assert strong.column("z").dtype == np.float64
    assert pipeline_fusion.compiled_program_count() == 2
    np.testing.assert_array_equal(weak.column("z"), 2.0 * np.ones(10))
    np.testing.assert_array_equal(strong.column("z"), 2.0 * np.ones(10))


# ---------------------------------------------------------------------------
# satellite: LazyDeviceColumn error paths
# ---------------------------------------------------------------------------

def test_lazy_column_clear_error_after_source_buffer_freed():
    """Reading a lazy intermediate after its captured source buffers were
    donated/freed raises a clear, named error — not a jax internal error
    or stale data — and stays a clear error on repeated reads."""
    t = _data(n=20)
    pm = _two_stage_chain(t)
    (out,) = pm.transform(t)
    assert isinstance(out._columns["a"], LazyDeviceColumn)
    for buf in list(t._device_cache.values()):
        buf.delete()
    with pytest.raises(RuntimeError, match="donated or freed"):
        out.column("a")
    with pytest.raises(RuntimeError, match="lazy intermediate column 'a'"):
        out.column("a")


def test_lazy_column_clear_error_when_own_buffer_freed():
    """A lazy column materialized once and then freed must also fail
    loudly on the next device-side use, not crash or serve stale bits."""
    t = _data(n=20)
    pm = _two_stage_chain(t)
    (out,) = pm.transform(t)
    col = out._columns["a"]
    _ = col.buf  # materialize the device buffer
    col.buf.delete()
    with pytest.raises(RuntimeError, match="donated or freed"):
        _ = col.buf


def test_lazy_column_reads_before_free_still_work():
    t = _data(n=20)
    pm = _two_stage_chain(t)
    pipeline_fusion.set_enabled(False)
    (expected,) = pm.transform(t)
    pipeline_fusion.set_enabled(True)
    (out,) = pm.transform(t)
    np.testing.assert_array_equal(out.column("a"), expected.column("a"))
    # Host cache survives a later free: the column was already fetched.
    for buf in list(t._device_cache.values()):
        buf.delete()
    np.testing.assert_array_equal(out.column("a"), expected.column("a"))
