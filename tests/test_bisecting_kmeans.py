"""BisectingKMeans: recovery, degenerate splits, persistence."""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score

from flinkml_tpu.models import BisectingKMeans, BisectingKMeansModel
from flinkml_tpu.table import Table


def _blobs(seed=0, n_per=80, k=4, spread=8.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, 3)) * spread
    xs, ys = [], []
    for i, c in enumerate(centers):
        xs.append(rng.normal(size=(n_per, 3)) + c)
        ys.append(np.full(n_per, i))
    return np.concatenate(xs), np.concatenate(ys)


def test_recovers_well_separated_blobs():
    x, y = _blobs()
    t = Table({"features": x})
    model = BisectingKMeans().set_k(4).set_max_iter(20).set_seed(0).fit(t)
    assert model.centroids.shape == (4, 3)
    (out,) = model.transform(t)
    assert adjusted_rand_score(y, out["prediction"]) > 0.95


def test_degenerate_duplicates_stop_early():
    x = np.ones((30, 2))
    x[15:] = 5.0  # only two distinct points: at most 2 real clusters
    t = Table({"features": x})
    model = BisectingKMeans().set_k(4).set_max_iter(5).set_seed(1).fit(t)
    # Can't split identical-point leaves: fewer than k centroids is fine.
    assert 2 <= model.centroids.shape[0] <= 4
    (out,) = model.transform(t)
    assert len(np.unique(out["prediction"])) == 2


def test_validation_and_persistence(tmp_path):
    x, _ = _blobs(seed=2, n_per=30)
    t = Table({"features": x})
    with pytest.raises(ValueError, match="n_rows"):
        BisectingKMeans().set_k(10_000).fit(t)
    model = BisectingKMeans().set_k(3).set_max_iter(10).set_seed(3).fit(t)
    model.save(str(tmp_path / "bkm"))
    loaded = BisectingKMeansModel.load(str(tmp_path / "bkm"))
    np.testing.assert_array_equal(loaded.centroids, model.centroids)
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_array_equal(p1["prediction"], p2["prediction"])


def test_deterministic():
    x, _ = _blobs(seed=4, n_per=40)
    t = Table({"features": x})
    m1 = BisectingKMeans().set_k(3).set_seed(5).fit(t)
    m2 = BisectingKMeans().set_k(3).set_seed(5).fit(t)
    np.testing.assert_array_equal(m1.centroids, m2.centroids)


def test_rejects_non_euclidean_and_honors_init_mode():
    x, _ = _blobs(seed=6, n_per=30)
    t = Table({"features": x})
    with pytest.raises(ValueError, match="euclidean"):
        BisectingKMeans().set_distance_measure("cosine").set_k(2).fit(t)
    m_pp = (
        BisectingKMeans().set_k(3).set_init_mode("k-means++")
        .set_seed(7).fit(t)
    )
    m_rand = (
        BisectingKMeans().set_k(3).set_init_mode("random")
        .set_seed(7).fit(t)
    )
    assert m_pp.centroids.shape == m_rand.centroids.shape == (3, 3)
