"""Linalg tests — mirror the reference's BLASTest / SparseVectorTest
(``flink-ml-core/src/test/java/.../linalg/``) plus the Python test_linalg.py,
with golden values computed by numpy."""

import numpy as np
import pytest

from flinkml_tpu.linalg import (
    DenseMatrix,
    DenseVector,
    SparseVector,
    Vectors,
    stack_vectors,
)


def test_dense_factory():
    v = Vectors.dense(1.0, 2.0, 3.0)
    assert v.size() == 3
    assert v.get(1) == 2.0
    assert np.array_equal(v.to_array(), [1, 2, 3])
    v2 = Vectors.dense([4.0, 5.0])
    assert v2.size() == 2


def test_dense_ops():
    a = Vectors.dense(1.0, 2.0)
    b = Vectors.dense(3.0, 4.0)
    assert a.dot(b) == 11.0
    assert a.norm2() == pytest.approx(np.sqrt(5))
    assert a == Vectors.dense(1.0, 2.0)
    assert a != b


def test_dense_rejects_2d():
    with pytest.raises(ValueError):
        DenseVector(np.ones((2, 2)))


def test_sparse_basic():
    v = Vectors.sparse(5, [0, 3], [1.0, 2.0])
    assert v.size() == 5
    assert v.get(0) == 1.0
    assert v.get(1) == 0.0
    assert v.get(3) == 2.0
    assert np.array_equal(v.to_array(), [1, 0, 0, 2, 0])


def test_sparse_sorts_indices():
    v = Vectors.sparse(5, [3, 0], [2.0, 1.0])
    assert list(v.indices) == [0, 3]
    assert list(v.values) == [1.0, 2.0]


def test_sparse_rejects_bad_indices():
    with pytest.raises(ValueError):
        Vectors.sparse(3, [0, 3], [1.0, 1.0])
    with pytest.raises(ValueError):
        Vectors.sparse(3, [-1], [1.0])
    with pytest.raises(ValueError):
        Vectors.sparse(3, [1, 1], [1.0, 2.0])


def test_sparse_get_bounds():
    v = Vectors.sparse(3, [1], [1.0])
    with pytest.raises(IndexError):
        v.get(3)


def test_sparse_dot():
    s = Vectors.sparse(4, [1, 2], [2.0, 3.0])
    d = Vectors.dense(1.0, 1.0, 1.0, 1.0)
    assert s.dot(d) == 5.0
    s2 = Vectors.sparse(4, [2, 3], [1.0, 1.0])
    assert s.dot(s2) == 3.0


def test_to_dense():
    s = Vectors.sparse(3, [1], [7.0])
    d = s.to_dense()
    assert isinstance(d, DenseVector)
    assert np.array_equal(d.to_array(), [0, 7, 0])


def test_dense_matrix():
    m = DenseMatrix(2, 3)
    assert m.num_rows == 2 and m.num_cols == 3
    m2 = DenseMatrix(2, 2, np.array([[1.0, 2.0], [3.0, 4.0]]))
    assert m2.get(0, 1) == 2.0
    # Flat column-major payload like the reference ctor.
    m3 = DenseMatrix(2, 2, np.array([1.0, 3.0, 2.0, 4.0]))
    assert m3 == m2


def test_stack_vectors():
    batch = stack_vectors([Vectors.dense(1.0, 2.0), Vectors.sparse(2, [1], [5.0])])
    assert batch.shape == (2, 2)
    assert np.array_equal(batch, [[1, 2], [0, 5]])
