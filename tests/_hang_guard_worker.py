"""Multi-process hang-guard worker, launched by test_distributed.py.

Regression for the rank-local-failure hang class: a failure that occurs
on ONE rank only (a bad batch, a raising source iterator, a missing or
corrupt checkpoint shard) must abort EVERY rank together through the
agreement layer (``iteration/stream_sync.py``) — the alternative is the
failing rank exiting while its peers block forever in their next
collective (the Gloo backend wedges permanently). Each case constructs
the failure on rank 0 only and asserts BOTH ranks raise; a hang fails
the parent test's timeout instead.

Also covers the straddled-checkpoint resume protocol for rank-scoped GBT
snapshots: ranks whose checkpoint sets differ (a crash between one
rank's save and the agreed commit, plus pruning) must converge on the
newest COMMON tree — or all restart together when the intersection is
empty — and still reproduce the uninterrupted forest exactly.

Usage: python _hang_guard_worker.py <port> <process_id> <num_processes> <workdir>
Prints ``GUARD_OK <pid>`` on success.
"""

import os
import shutil
import sys

port, pid, nproc, workdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from flinkml_tpu.iteration.checkpoint import CheckpointManager  # noqa: E402
from flinkml_tpu.iteration.datacache import cache_stream  # noqa: E402
from flinkml_tpu.iteration.stream_sync import synced_stream  # noqa: E402
from flinkml_tpu.models._gbt_stream import train_gbt_stream  # noqa: E402
from flinkml_tpu.models._linear_sgd import (  # noqa: E402
    train_linear_model_stream,
)
from flinkml_tpu.models.kmeans import train_kmeans_stream  # noqa: E402
from flinkml_tpu.parallel import DeviceMesh, init_distributed  # noqa: E402

idx, count = init_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc,
    process_id=pid,
)
assert (idx, count) == (pid, nproc), (idx, count)

mesh = DeviceMesh()
rng = np.random.default_rng(100 + pid)


def expect_all_ranks_raise(label, fn):
    """Run a case whose failure lives on rank 0 only; EVERY rank must
    raise (rank 0 the original error, peers the agreement error). A hang
    here trips the parent's subprocess timeout."""
    try:
        fn()
    except Exception as e:  # noqa: BLE001 — the expected agreed abort
        print(f"{label}: rank {pid} raised {type(e).__name__}", flush=True)
        return
    raise SystemExit(f"{label}: rank {pid} did NOT raise")


def good_batch(n=16, d=4):
    x = rng.normal(size=(n, d)).astype(np.float32)
    return {"x": x, "y": (x[:, 0] > 0).astype(np.float32)}


# --- 1. synced_stream: the SOURCE ITERATOR raises on rank 0 mid-stream.
def case_iterator_raise():
    def source():
        yield np.ones((4, 3), np.float32)
        if pid == 0:
            raise IOError("injected shard read failure")
        yield np.ones((4, 3), np.float32)

    for _ in synced_stream(source(), mesh):
        pass


expect_all_ranks_raise("case1-iterator", case_iterator_raise)


# --- 2. GBT streamed pass A: ragged SECOND batch on rank 0 (the ingest
# accumulation — fixed-width reservoir add — must be skipped, not raise).
def case_gbt_ragged():
    batches = [good_batch()]
    bad_d = 6 if pid == 0 else 4
    x = rng.normal(size=(16, bad_d)).astype(np.float32)
    batches.append({"x": x, "y": (x[:, 0] > 0).astype(np.float32)})
    train_gbt_stream(
        cache_stream(iter(batches)), mesh=mesh, logistic=True,
        num_trees=2, depth=2, max_bins=8, learning_rate=0.3,
        reg_lambda=1.0, subsample=1.0, seed=0,
    )


expect_all_ranks_raise("case2-gbt-ragged", case_gbt_ragged)


# --- 3. KMeans streamed pass 0: ragged second batch on rank 0 (iterable
# source; checked extraction must gate the reservoir add + cache append).
def case_kmeans_ragged():
    batches = [good_batch(), good_batch()]
    if pid == 0:
        batches[1] = {"x": rng.normal(size=(16, 6)).astype(np.float32)}
    train_kmeans_stream(
        iter({"x": b["x"]} for b in batches), k=2, mesh=mesh,
        max_iter=2, seed=0,
    )


expect_all_ranks_raise("case3-kmeans-ragged", case_kmeans_ragged)


# --- 3b. KMeans streamed pass 0: the source ITERATOR raises on rank 0
# (guarded_iter must fold it into the rendezvous, not propagate before
# the plan's collectives).
def case_kmeans_iter_raise():
    def source():
        yield {"x": good_batch()["x"]}
        if pid == 0:
            raise IOError("injected stream failure")
        yield {"x": good_batch()["x"]}

    train_kmeans_stream(source(), k=2, mesh=mesh, max_iter=2, seed=0)


expect_all_ranks_raise("case3b-kmeans-iter", case_kmeans_iter_raise)


# --- 3c. Uniformly bad stream (every batch fails validation on EVERY
# rank): skip-on-failure leaves all local caches empty, but the held
# validation error must surface as ITSELF — rendezvous runs before the
# plan, so the user never debugs a phantom "stream is empty" instead.
def case_all_bad_surfaces_real_error():
    bad = {"x": np.ones(8, np.float32)}  # 1-D: fails the [n, d] check
    try:
        train_kmeans_stream(iter([bad]), k=2, mesh=mesh, max_iter=2, seed=0)
    except ValueError as e:
        assert "must be [n, d]" in str(e), e
        print(f"case3c-real-error: rank {pid} got the validation error",
              flush=True)
        return
    raise SystemExit(f"case3c: rank {pid} did NOT raise")


case_all_bad_surfaces_real_error()


# --- 4. Linear streamed ingest: a ragged VALUE (np.array raises) on
# rank 0 — the checked copy holds it; the append must be skipped.
def case_linear_ragged_value():
    batches = [good_batch(), good_batch()]
    if pid == 0:
        bad = dict(batches[1])
        bad["x"] = [[1.0, 2.0], [3.0]]  # ragged: np.array raises
        batches[1] = bad
    train_linear_model_stream(
        iter(batches), mesh=mesh, loss="logistic", max_iter=2,
        learning_rate=0.5, reg=0.0, elastic_net=0.0, tol=0.0,
    )


expect_all_ranks_raise("case4-linear-ragged", case_linear_ragged_value)


# --- 4b. LDA from a sealed DataCache whose SECOND batch is invalid on
# rank 0 only (negative count): the full-cache pre-validation must hold
# it for the rendezvous, not raise rank-locally at replay time.
def case_lda_bad_cached_batch():
    from flinkml_tpu.models.lda import LDA

    good = np.abs(rng.normal(size=(8, 6))).astype(np.float32)
    bad = good.copy()
    if pid == 0:
        bad[0, 0] = -1.0
    cache = cache_stream(iter({"features": b} for b in [good, bad]))
    LDA(mesh=mesh).set_k(2).set_max_iter(2).fit(cache)


expect_all_ranks_raise("case4b-lda-bad-cache", case_lda_bad_cached_batch)


# --- 4c. Online FTRL: the source stream raises on rank 0 mid-lockstep
# (agree_first_item_dim + synced_padded_stream failure paths).
def case_online_ftrl_iter_raise():
    from flinkml_tpu.models.online_logistic_regression import (
        OnlineLogisticRegression,
    )
    from flinkml_tpu.table import Table

    def source():
        b = good_batch()
        yield Table({"features": b["x"], "label": b["y"]})
        if pid == 0:
            raise IOError("injected stream failure")
        b = good_batch()
        yield Table({"features": b["x"], "label": b["y"]})

    OnlineLogisticRegression(mesh=mesh).fit_stream(source())


expect_all_ranks_raise("case4c-ftrl-iter", case_online_ftrl_iter_raise)


# --- 4d. Word2Vec: a bad document batch on rank 0 (missing token
# column) must ride the ingest rendezvous, not raise rank-locally
# before the vocabulary-union collective.
def case_w2v_bad_batch():
    from flinkml_tpu.models.word2vec import Word2Vec
    from flinkml_tpu.table import Table

    docs = np.asarray([["a", "b", "a", "c"]] * 4, dtype=object)
    batches = [Table({"tok": docs})]
    if pid == 0:
        batches.append(Table({"wrong_col": docs}))
    else:
        batches.append(Table({"tok": docs}))
    (
        Word2Vec(mesh=mesh).set_input_col("tok").set_vector_size(4)
        .set_min_count(1).set_max_iter(1).set_seed(0)
        .fit(iter(batches))
    )


expect_all_ranks_raise("case4d-w2v-bad-batch", case_w2v_bad_batch)


# --- 5. GBT straddled-checkpoint resume (rank-scoped snapshots).
gbt_args = dict(
    mesh=mesh, logistic=True, num_trees=3, depth=2, max_bins=8,
    learning_rate=0.3, reg_lambda=1.0, subsample=1.0, seed=0,
)
gbt_cache = cache_stream(iter([good_batch(48), good_batch(48)]))
golden = train_gbt_stream(gbt_cache, **gbt_args)


def checkpointed_fit(tag):
    ckpt = os.path.join(workdir, tag)
    os.makedirs(ckpt, exist_ok=True)
    mgr = CheckpointManager(ckpt, max_to_keep=3)
    out = train_gbt_stream(
        gbt_cache, checkpoint_manager=mgr, checkpoint_interval=1,
        **gbt_args,
    )
    for a, b in zip(golden, out):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    return ckpt


def drop(ckpt, trees):
    for t in trees:
        shutil.rmtree(
            os.path.join(ckpt, f"rank-{pid}", f"ckpt-{t}"),
            ignore_errors=False,
        )


def resume_fit(ckpt):
    mgr = CheckpointManager(ckpt, max_to_keep=3)
    return train_gbt_stream(
        gbt_cache, checkpoint_manager=mgr, checkpoint_interval=1,
        resume=True, **gbt_args,
    )


# 5a. Straddle: rank 0 holds {2,3} (pruned 1), rank 1 holds {1,2}
# (crashed before saving 3) — the newest COMMON tree is 2; the resumed
# run must rebuild tree 3 and match the uninterrupted forest exactly.
ckpt = checkpointed_fit("ckpt_straddle")
drop(ckpt, [1] if pid == 0 else [3])
resumed = resume_fit(ckpt)
for a, b in zip(golden, resumed):
    assert np.array_equal(np.asarray(a), np.asarray(b)), "straddle resume"
print(f"case5a-straddle: rank {pid} resumed from common tree", flush=True)

# 5b. Disjoint: rank 0 holds only {3}, rank 1 only {1} — no common tree;
# every rank must restart from scratch together and still match.
ckpt = checkpointed_fit("ckpt_disjoint")
drop(ckpt, [1, 2] if pid == 0 else [2, 3])
resumed = resume_fit(ckpt)
for a, b in zip(golden, resumed):
    assert np.array_equal(np.asarray(a), np.asarray(b)), "disjoint resume"
print(f"case5b-disjoint: rank {pid} restarted together", flush=True)


# 5c. Corrupt shard: every rank agrees on tree 3, but rank 0's shard of
# it is unreadable — the agreed restore must abort EVERY rank (not
# strand rank 1 in the training collectives).
def case_corrupt_restore():
    ckpt = checkpointed_fit("ckpt_corrupt")
    if pid == 0:
        os.remove(
            os.path.join(ckpt, f"rank-{pid}", "ckpt-3", "arrays.npz")
        )
    resume_fit(ckpt)


expect_all_ranks_raise("case5c-corrupt", case_corrupt_restore)


# 5d. REAL crash-injection resume on the 2-rank mesh: the rank-scoped
# manager raises after durably writing tree 2's snapshot, crashing the
# fit as an AGREED abort (save_agreed holds the save failure and every
# rank raises together); the resumed fit rebuilds tree 3 to reproduce
# the uninterrupted forest exactly. The injection wraps rank_scoped
# because the GBT path constructs its per-rank manager through it.
def case5d_crash_resume():
    import flinkml_tpu.iteration.checkpoint as ckpt_mod

    ckpt = os.path.join(workdir, "ckpt_crashinject")
    os.makedirs(ckpt, exist_ok=True)

    class Crash(CheckpointManager):
        fired = False

        def save(self, state, epoch, extra=None, **kw):
            p = super().save(state, epoch, extra, **kw)
            if not Crash.fired and epoch >= 2:
                Crash.fired = True
                raise RuntimeError("injected crash")
            return p

    orig_rank_scoped = ckpt_mod.rank_scoped

    def crashing_rank_scoped(manager):
        inner = orig_rank_scoped(manager)
        return Crash(
            inner.directory, max_to_keep=inner.max_to_keep,
            allow_rescale=inner.allow_rescale,
            world_size=inner.world_size, async_write=inner.async_write,
        )

    ckpt_mod.rank_scoped = crashing_rank_scoped
    try:
        train_gbt_stream(
            gbt_cache,
            checkpoint_manager=CheckpointManager(ckpt, max_to_keep=3),
            checkpoint_interval=1, **gbt_args,
        )
        raise SystemExit(f"case5d: rank {pid} did NOT crash")
    except RuntimeError as e:
        assert "injected crash" in str(e), e
    finally:
        ckpt_mod.rank_scoped = orig_rank_scoped
    recovered = resume_fit(ckpt)
    for a, b in zip(golden, recovered):
        assert np.array_equal(np.asarray(a), np.asarray(b)), "crash resume"
    print(f"case5d-crash-resume: rank {pid} resumed exactly", flush=True)


case5d_crash_resume()


# --- 6. Agreed restore for REPLICATED snapshots (ALS; LDA/Word2Vec share
# the identical DeferredValidation-wrapped restore). A rank-local restore
# failure (unreadable checkpoint on the shared FS) must abort every rank,
# not strand the peer in the normal-equation collectives.
def case6_als_restore_ioerror():
    from flinkml_tpu.models.als import ALS

    ckpt = os.path.join(workdir, "ckpt_als_restore")
    os.makedirs(ckpt, exist_ok=True)
    r = np.random.default_rng(40 + pid)
    cache = cache_stream(iter([{
        "user": r.integers(0, 8, size=32).astype(np.int32),
        "item": r.integers(0, 8, size=32).astype(np.int32),
        "rating": r.uniform(1, 5, size=32).astype(np.float32),
    }]))
    ALS(
        mesh=mesh, checkpoint_manager=CheckpointManager(ckpt),
        checkpoint_interval=1,
    ).set_rank(2).set_max_iter(2).set_seed(0).fit(cache)

    class BadRestore(CheckpointManager):
        def restore(self, epoch, like):
            raise IOError("injected unreadable checkpoint")

    mgr = (BadRestore if pid == 0 else CheckpointManager)(ckpt)
    ALS(
        mesh=mesh, checkpoint_manager=mgr, checkpoint_interval=1,
        resume=True,
    ).set_rank(2).set_max_iter(3).set_seed(0).fit(cache)


expect_all_ranks_raise("case6-als-restore", case6_als_restore_ioerror)


# --- 7. Cached-source KMeans with need_init=False (initial_centroids):
# pre-validation must still run — a bad cached batch on rank 0 would
# otherwise first raise rank-locally in place_multi's check_dims on the
# prefetch thread at replay, stranding the peer mid-collective.
def case7_kmeans_cached_bad_batch_no_init():
    blobs = [{"x": good_batch(16)["x"]}]
    if pid == 0:
        blobs.append({"x": np.zeros((4, 7), np.float32)})  # ragged dim
    train_kmeans_stream(
        cache_stream(iter(blobs)), k=2, mesh=mesh, max_iter=2, seed=0,
        initial_centroids=np.zeros((2, 4), np.float32),
    )


expect_all_ranks_raise("case7-kmeans-cached", case7_kmeans_cached_bad_batch_no_init)


# --- 8. Sparse-native CSR streaming (round 5): a ragged CSR batch on
# rank 0 (indices/indptr disagree) must abort every rank at the ingest
# rendezvous, not raise rank-locally before the agreed schedule.
def case8_sparse_stream_ragged_csr():
    from flinkml_tpu.models._linear_sgd import train_linear_model_stream

    def csr(n=8, dim=50, nnz=3, broken=False):
        r = np.random.default_rng(60 + pid)
        indptr = np.arange(n + 1, dtype=np.int64) * nnz
        k = n * nnz - (1 if broken else 0)  # broken: indices too short
        return {
            "indptr": indptr[None, :],
            "indices": r.integers(0, dim, k).astype(np.int32)[None, :],
            "values": r.normal(size=k).astype(np.float32)[None, :],
            "y": (r.random(n) > 0.5).astype(np.float32)[None, :],
            "w": np.ones(n, np.float32)[None, :],
            "dim": np.asarray([[dim]], np.int64),
        }

    train_linear_model_stream(
        iter([csr(), csr(broken=(pid == 0))]),
        loss="logistic", mesh=mesh, max_iter=2, learning_rate=0.5,
        reg=0.0, elastic_net=0.0, tol=0.0, sparse_dim=50,
    )


expect_all_ranks_raise("case8-sparse-ragged", case8_sparse_stream_ragged_csr)

print(f"GUARD_OK {pid}", flush=True)
