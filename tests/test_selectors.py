"""ChiSqTest / VarianceThresholdSelector / UnivariateFeatureSelector vs
scipy + sklearn."""

import numpy as np
import pytest
from scipy.stats import chi2_contingency, f_oneway
from sklearn.feature_selection import (
    SelectKBest,
    VarianceThreshold,
    chi2 as sk_chi2,
    f_classif as sk_f_classif,
)

from flinkml_tpu.models import (
    ChiSqTest,
    UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
)
from flinkml_tpu.models.selectors import chi_square_test, f_classif_test
from flinkml_tpu.table import Table


def _cat_data(n=400, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 3, n)
    # feature 0: depends on label; features 1, 2: independent noise.
    x0 = (y + rng.integers(0, 2, n)) % 4
    x1 = rng.integers(0, 5, n)
    x2 = rng.integers(0, 2, n)
    return np.stack([x0, x1, x2], axis=1).astype(float), y.astype(float)


def test_chi_square_matches_scipy():
    x, y = _cat_data()
    stats, pvals, dofs = chi_square_test(x, y)
    for j in range(x.shape[1]):
        observed = np.zeros((len(np.unique(x[:, j])), len(np.unique(y))))
        cats = {v: i for i, v in enumerate(np.unique(x[:, j]))}
        labs = {v: i for i, v in enumerate(np.unique(y))}
        for xi, yi in zip(x[:, j], y):
            observed[cats[xi], labs[yi]] += 1
        ref = chi2_contingency(observed, correction=False)
        assert stats[j] == pytest.approx(ref.statistic, rel=1e-10)
        assert pvals[j] == pytest.approx(ref.pvalue, rel=1e-8, abs=1e-12)
        assert dofs[j] == ref.dof
    # Dependent feature is far more significant than the noise ones.
    assert pvals[0] < 1e-6 < pvals[1]


def test_chi_sq_test_operator_layout():
    x, y = _cat_data(seed=1)
    t = Table({"features": x, "label": y})
    (out,) = ChiSqTest().transform(t)
    assert out.column_names == [
        "featureIndex", "pValue", "statistic", "degreesOfFreedom",
    ]
    assert out.num_rows == 3


def test_f_classif_matches_sklearn():
    rng = np.random.default_rng(2)
    y = rng.integers(0, 3, 200).astype(float)
    x = rng.normal(size=(200, 4))
    x[:, 0] += y  # informative
    f, p = f_classif_test(x, y)
    f_ref, p_ref = sk_f_classif(x, y)
    np.testing.assert_allclose(f, f_ref, rtol=1e-10)
    np.testing.assert_allclose(p, p_ref, rtol=1e-8, atol=1e-14)
    # Cross-check one feature against scipy's one-way ANOVA too.
    groups = [x[y == c, 0] for c in np.unique(y)]
    assert f[0] == pytest.approx(f_oneway(*groups).statistic, rel=1e-10)


def test_variance_threshold_matches_sklearn(tmp_path):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(150, 5)) * np.asarray([2.0, 0.0, 0.5, 3.0, 0.01])
    x[:, 1] = 7.0  # constant
    t = Table({"features": x})
    model = VarianceThresholdSelector().set_variance_threshold(0.1).fit(t)
    ref = VarianceThreshold(threshold=0.1).fit(x)
    np.testing.assert_array_equal(
        model.selected_indices, np.nonzero(ref.get_support())[0]
    )
    (out,) = model.transform(t)
    np.testing.assert_allclose(
        out["output"], ref.transform(x), rtol=1e-6, atol=1e-6
    )
    model.save(str(tmp_path / "vts"))
    loaded = VarianceThresholdSelectorModel.load(str(tmp_path / "vts"))
    np.testing.assert_array_equal(
        loaded.selected_indices, model.selected_indices
    )


def test_univariate_chi2_top_k_matches_sklearn():
    x, y = _cat_data(seed=4)
    t = Table({"features": x, "label": y})
    model = (
        UnivariateFeatureSelector()
        .set_score_function("chi2")
        .set_selection_mode("numTopFeatures")
        .set_selection_threshold(1.0)
        .fit(t)
    )
    # NOTE: sklearn's chi2 is a different statistic (nonnegative-feature
    # form), but both must agree the label-dependent feature wins.
    sk = SelectKBest(sk_chi2, k=1).fit(x, y)
    np.testing.assert_array_equal(
        model.selected_indices, np.nonzero(sk.get_support())[0]
    )
    (out,) = model.transform(t)
    assert out["output"].shape == (x.shape[0], 1)


def test_univariate_fclassif_modes(tmp_path):
    rng = np.random.default_rng(5)
    y = rng.integers(0, 2, 300).astype(float)
    x = rng.normal(size=(300, 10))
    x[:, 2] += 2 * y
    x[:, 7] += y
    t = Table({"features": x, "label": y})
    top2 = (
        UnivariateFeatureSelector().set_score_function("fClassif")
        .set_selection_mode("numTopFeatures").set_selection_threshold(2.0)
        .fit(t)
    )
    np.testing.assert_array_equal(top2.selected_indices, [2, 7])
    pct = (
        UnivariateFeatureSelector().set_score_function("fClassif")
        .set_selection_mode("percentile").set_selection_threshold(0.2)
        .fit(t)
    )
    np.testing.assert_array_equal(pct.selected_indices, [2, 7])
    fpr = (
        UnivariateFeatureSelector().set_score_function("fClassif")
        .set_selection_mode("fpr").set_selection_threshold(1e-6)
        .fit(t)
    )
    assert 2 in fpr.selected_indices and len(fpr.selected_indices) <= 2
    fpr.save(str(tmp_path / "ufs"))
    loaded = UnivariateFeatureSelectorModel.load(str(tmp_path / "ufs"))
    np.testing.assert_array_equal(loaded.selected_indices, fpr.selected_indices)


def test_selector_dim_mismatch_rejected():
    x, y = _cat_data(seed=6)
    t = Table({"features": x, "label": y})
    model = (
        UnivariateFeatureSelector().set_selection_mode("numTopFeatures")
        .set_selection_threshold(2.0).fit(t)
    )
    small = Table({"features": x[:, :1]})
    with pytest.raises(ValueError, match="dim"):
        model.transform(small)


def test_f_classif_perfectly_discriminative_feature_wins():
    rng = np.random.default_rng(7)
    y = rng.integers(0, 2, 100).astype(float)
    x = rng.normal(size=(100, 3))
    x[:, 0] = y          # zero within-class variance: F = inf, p = 0
    x[:, 2] = 5.0        # constant: F = 0
    f, p = f_classif_test(x, y)
    assert np.isinf(f[0]) and p[0] == 0.0
    assert f[2] == 0.0
    model = (
        UnivariateFeatureSelector().set_score_function("fClassif")
        .set_selection_mode("numTopFeatures").set_selection_threshold(1.0)
        .fit(Table({"features": x, "label": y}))
    )
    np.testing.assert_array_equal(model.selected_indices, [0])


def test_selection_threshold_validation():
    x, y = _cat_data(seed=8)
    t = Table({"features": x, "label": y})
    with pytest.raises(ValueError, match=">= 1"):
        (
            UnivariateFeatureSelector().set_selection_mode("numTopFeatures")
            .set_selection_threshold(-1.0).fit(t)
        )
    with pytest.raises(ValueError, match=r"\(0, 1\]"):
        (
            UnivariateFeatureSelector().set_selection_mode("percentile")
            .set_selection_threshold(1.5).fit(t)
        )
