"""Serving subsystem units: registry, batcher, engine, publisher.

The contracts under test:

  1. ModelRegistry: monotone versions, atomic CURRENT pointer,
     publish/get/rollback, listener notification, and fingerprint-verified
     loads (save → tamper → load raises ModelIntegrityError).
  2. AdaptiveMicroBatcher: coalescing up to the bucket / max-wait window,
     FIFO whole-request batches, bounded admission, deadline expiry.
  3. ServingEngine: responses bitwise-equal to direct transform, version
     tagging, schema validation, hot swap (old in-flight batches finish on
     the old version), warmup precompilation, stats exposition.
  4. SnapshotPublisher: mid-stream publication cadence from iterate()'s
     unbounded mode and from train_kmeans_stream's listener hook.
"""

import threading
import time

import numpy as np
import pytest

from flinkml_tpu import pipeline_fusion
from flinkml_tpu.io import read_write
from flinkml_tpu.models.kmeans import KMeansModel
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.serving import (
    AdaptiveMicroBatcher,
    EngineStoppedError,
    ModelIntegrityError,
    ModelRegistry,
    ModelVersionNotFoundError,
    RegistryError,
    ServingConfig,
    ServingEngine,
    ServingRequest,
    ServingSchemaError,
    SnapshotPublisher,
)
from flinkml_tpu.table import Table


def _data(n=120, d=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return x, y


def _fitted_pipeline(x, y):
    train = Table({"features": x, "label": y})
    sc = (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "scaled")
        .fit(train)
    )
    (t2,) = sc.transform(train)
    lr = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, "scaled")
        .set(LogisticRegression.LABEL_COL, "label")
        .set_max_iter(3)
        .fit(t2)
    )
    return PipelineModel([sc, lr])


@pytest.fixture
def pipeline_and_data():
    x, y = _data()
    return _fitted_pipeline(x, y), x


def _engine(source, x, **cfg):
    config = ServingConfig(**{
        "max_batch_rows": 64,
        "max_queue_rows": 256,
        "warmup_row_counts": (1, 64),
        **cfg,
    })
    return ServingEngine(
        source, Table({"features": x[:4]}), config,
        output_cols=("prediction", "rawPrediction"),
    )


# ---------------------------------------------------------------------------
# 1. ModelRegistry
# ---------------------------------------------------------------------------

def test_registry_publish_get_rollback(tmp_path, pipeline_and_data):
    pm, x = pipeline_and_data
    reg = ModelRegistry(str(tmp_path / "reg"))
    assert reg.current_version() is None
    assert reg.versions() == []
    with pytest.raises(ModelVersionNotFoundError):
        reg.get()

    v1 = reg.publish(pm)
    assert (v1, reg.current_version(), reg.versions()) == (1, 1, [1])
    v2 = reg.publish(pm)
    assert (v2, reg.current_version(), reg.versions()) == (2, 2, [1, 2])

    got_v, loaded = reg.get()
    assert got_v == 2
    t = Table({"features": x[:7]})
    np.testing.assert_array_equal(
        pm.transform(t)[0].column("prediction"),
        loaded.transform(t)[0].column("prediction"),
    )

    assert reg.rollback(1) == 1
    assert reg.current_version() == 1
    assert reg.versions() == [1, 2]  # rollback deletes nothing
    with pytest.raises(ModelVersionNotFoundError):
        reg.rollback(99)
    with pytest.raises(RegistryError):
        reg.publish(pm, version=2)  # explicit collision


def test_registry_notifies_listeners(tmp_path, pipeline_and_data):
    pm, _ = pipeline_and_data
    reg = ModelRegistry(str(tmp_path / "reg"))
    seen = []
    reg.add_listener(seen.append)
    reg.publish(pm)
    reg.publish(pm)
    reg.rollback(1)
    assert seen == [1, 2, 1]
    reg.remove_listener(seen.append)
    reg.publish(pm)
    assert seen == [1, 2, 1]


def test_registry_listener_exception_does_not_break_publish(
    tmp_path, pipeline_and_data
):
    """A failing follower (e.g. an engine whose swap raises) must not
    unwind into the publishing/training thread: the publish is already
    committed; the failure surfaces as a warning + counter, and every
    other listener still fires."""
    pm, _ = pipeline_and_data
    reg = ModelRegistry(str(tmp_path / "reg"))
    seen = []

    def bad(version):
        raise RuntimeError("boom")

    reg.add_listener(bad)
    reg.add_listener(seen.append)
    with pytest.warns(RuntimeWarning, match="boom"):
        assert reg.publish(pm) == 1
    assert seen == [1]
    assert reg.current_version() == 1


def test_registry_tampered_model_fails_load(tmp_path, pipeline_and_data):
    """save → tamper → load: a bit flip in any stage's persisted model
    arrays must surface as ModelIntegrityError, not silent corruption."""
    pm, _ = pipeline_and_data
    reg = ModelRegistry(str(tmp_path / "reg"))
    v = reg.publish(pm)
    # Rewrite stage 0's (the scaler's) model data with altered values.
    stage_dir = read_write.stage_path(reg.path_of(v), 0)
    arrays = read_write.load_model_arrays(stage_dir)
    arrays["mean"] = arrays["mean"] + 1.0
    import os
    os.remove(os.path.join(stage_dir, read_write.MODEL_DATA_DIR, "model.npz"))
    read_write.save_model_arrays(stage_dir, arrays)
    with pytest.raises(ModelIntegrityError):
        reg.get(v)


# ---------------------------------------------------------------------------
# 2. AdaptiveMicroBatcher
# ---------------------------------------------------------------------------

def _req(rows, deadline=None):
    return ServingRequest(
        columns={"x": np.zeros((rows, 2))},
        rows=rows,
        enqueued_at=time.monotonic(),
        deadline=deadline,
    )


def test_batcher_coalesces_within_window():
    b = AdaptiveMicroBatcher(max_batch_rows=64, max_wait_s=0.2,
                             max_queue_rows=256)
    for _ in range(3):
        assert b.offer(_req(2))
    batch, expired = b.next_batch(poll_s=0.01)
    # 6 rows < bucket 8: the window waits max_wait for company, then
    # dispatches all three together.
    assert [r.rows for r in batch] == [2, 2, 2]
    assert expired == []


def test_batcher_dispatches_early_when_bucket_fills():
    b = AdaptiveMicroBatcher(max_batch_rows=64, max_wait_s=30.0,
                             max_queue_rows=256)
    b.offer(_req(5))
    b.offer(_req(3))  # 8 rows == bucket(8): occupancy 1.0
    t0 = time.monotonic()
    batch, _ = b.next_batch(poll_s=0.01)
    assert [r.rows for r in batch] == [5, 3]
    assert time.monotonic() - t0 < 5.0  # did NOT wait the 30s window


def test_batcher_never_splits_and_respects_max_rows():
    b = AdaptiveMicroBatcher(max_batch_rows=8, max_wait_s=0.0,
                             max_queue_rows=64)
    b.offer(_req(5))
    b.offer(_req(5))  # would overflow max_batch_rows together
    batch, _ = b.next_batch()
    assert [r.rows for r in batch] == [5]
    batch, _ = b.next_batch()
    assert [r.rows for r in batch] == [5]


def test_batcher_bounded_admission_and_stop():
    b = AdaptiveMicroBatcher(max_batch_rows=8, max_wait_s=0.0,
                             max_queue_rows=8)
    assert b.offer(_req(8))
    assert not b.offer(_req(1))  # full
    b.stop()
    with pytest.raises(EngineStoppedError):
        b.offer(_req(1))
    assert [r.rows for r in b.drain_pending()] == [8]
    assert b.queue_depth == 0


def test_batcher_window_closes_before_queued_deadline():
    """A lone request whose deadline falls INSIDE the max-wait window must
    be dispatched in time, not expired by the very wait that was supposed
    to batch it."""
    b = AdaptiveMicroBatcher(max_batch_rows=64, max_wait_s=5.0,
                             max_queue_rows=256)
    b.offer(_req(2, deadline=time.monotonic() + 0.05))
    t0 = time.monotonic()
    batch, expired = b.next_batch(poll_s=0.01)
    assert [r.rows for r in batch] == [2]
    assert expired == []
    assert time.monotonic() - t0 < 2.0  # closed at the deadline, not 5s


def test_batcher_expires_overdue_requests():
    b = AdaptiveMicroBatcher(max_batch_rows=8, max_wait_s=0.0,
                             max_queue_rows=64)
    b.offer(_req(2, deadline=time.monotonic() - 1.0))  # already expired
    b.offer(_req(3))
    batch, expired = b.next_batch(poll_s=0.01)
    assert [r.rows for r in expired] == [2]
    assert [r.rows for r in batch] == [3]


# ---------------------------------------------------------------------------
# 3. ServingEngine
# ---------------------------------------------------------------------------

def test_engine_parity_and_response_shape(pipeline_and_data):
    pm, x = pipeline_and_data
    eng = _engine(pm, x).start()
    try:
        (ref,) = pm.transform(Table({"features": x[:9]}))
        resp = eng.predict({"features": x[:9]})
        assert resp.version is None  # fixed-model engine: unversioned
        for c in ("prediction", "rawPrediction"):
            np.testing.assert_array_equal(ref.column(c), resp.column(c))
        # Single row with the leading axis omitted.
        one = eng.predict({"features": x[0]})
        np.testing.assert_array_equal(
            ref.column("prediction")[:1], one.column("prediction")
        )
        assert one.latency_ms >= 0.0
    finally:
        eng.stop()


def test_engine_schema_validation(pipeline_and_data):
    pm, x = pipeline_and_data
    eng = _engine(pm, x).start()
    try:
        with pytest.raises(ServingSchemaError):
            eng.predict({"wrong": x[:2]})
        with pytest.raises(ServingSchemaError):
            eng.predict({"features": x[:2, :3]})  # wrong trailing dim
        with pytest.raises(ServingSchemaError):
            eng.predict({"features": x[:0]})  # empty
        with pytest.raises(ServingSchemaError):
            eng.predict({"features": np.zeros((65, x.shape[1]))})  # > max
    finally:
        eng.stop()


def test_engine_serves_deadline_inside_batch_window(pipeline_and_data):
    """Idle server, long batching window, short request deadline: the
    window must close early and serve the request before it expires."""
    pm, x = pipeline_and_data
    eng = _engine(pm, x, max_wait_ms=5000.0).start()
    try:
        resp = eng.predict({"features": x[:2]}, timeout_ms=500)
        assert resp.columns["prediction"].shape == (2,)
    finally:
        eng.stop()


def test_engine_rejects_undiscoverable_output_cols():
    """In-place overwrite (OUTPUT_COL == INPUT_COL) defeats added-column
    discovery; the engine must fail the load, not serve empty responses."""
    x, y = _data()
    train = Table({"features": x})
    sc = (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "features")
        .fit(train)
    )
    eng = ServingEngine(
        sc, Table({"features": x[:4]}),
        ServingConfig(max_batch_rows=64, warmup_row_counts=(1,)),
    )
    with pytest.raises(ServingSchemaError, match="output columns"):
        eng.start()


def test_engine_follow_registry_catches_up(tmp_path):
    """A publish landing before follow_registry() is delivered by the
    registration-time catch-up swap, not lost."""
    x, y = _data()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(_fitted_pipeline(x, y))
    eng = _engine(reg, x).start()          # loads v1
    try:
        reg.publish(_fitted_pipeline(x, -y + 1))  # lands unobserved
        assert eng.active_version == 1
        eng.follow_registry()              # catch-up swap to v2
        assert eng.active_version == 2
    finally:
        eng.stop()


def test_engine_requires_start(pipeline_and_data):
    pm, x = pipeline_and_data
    eng = _engine(pm, x)
    with pytest.raises(EngineStoppedError):
        eng.predict({"features": x[:2]})


def test_engine_warmup_precompiles_buckets(pipeline_and_data):
    """After start(), serving row counts within warmed buckets compiles
    nothing: the engine paid every compile at load."""
    pm, x = pipeline_and_data
    pipeline_fusion.reset_cache()
    eng = _engine(pm, x, warmup_row_counts=None).start()  # all buckets
    try:
        compiled = []
        pipeline_fusion.on_compile.append(compiled.append)
        try:
            for rows in (1, 3, 8, 9, 17, 33, 64):
                eng.predict({"features": np.resize(x, (rows, x.shape[1]))})
        finally:
            pipeline_fusion.on_compile.remove(compiled.append)
        assert compiled == []
    finally:
        eng.stop()


def test_engine_hot_swap_routes_new_requests(tmp_path):
    x, y = _data()
    pm1 = _fitted_pipeline(x, y)
    pm2 = _fitted_pipeline(x, -y + 1)  # different fit, same shapes
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(pm1)
    eng = _engine(reg, x).start()
    try:
        r1 = eng.predict({"features": x[:5]})
        assert r1.version == 1
        v2 = reg.publish(pm2)
        assert eng.active_version == 1  # not following: explicit swap
        assert eng.swap_to() == v2
        r2 = eng.predict({"features": x[:5]})
        assert r2.version == 2
        np.testing.assert_array_equal(
            pm2.transform(Table({"features": x[:5]}))[0].column("prediction"),
            r2.column("prediction"),
        )
    finally:
        eng.stop()


def test_engine_follow_registry_auto_swaps(tmp_path):
    x, y = _data()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(_fitted_pipeline(x, y))
    eng = _engine(reg, x).start().follow_registry()
    try:
        reg.publish(_fitted_pipeline(x, -y + 1))
        assert eng.active_version == 2
        assert eng.predict({"features": x[:3]}).version == 2
        reg.rollback(1)
        assert eng.active_version == 1
        # Following survives a stop()/start() cycle.
        eng.stop()
        eng.start()
        reg.rollback(2)
        assert eng.active_version == 2
    finally:
        eng.stop()


def test_engine_stop_drains_and_rejects(pipeline_and_data):
    pm, x = pipeline_and_data
    eng = _engine(pm, x).start()
    eng.stop()
    with pytest.raises(EngineStoppedError):
        eng.predict({"features": x[:2]})
    # Restartable: a stopped engine can come back with a fresh queue.
    eng.start()
    try:
        assert eng.predict({"features": x[:2]}).columns
    finally:
        eng.stop()


def test_engine_stats_and_exposition(pipeline_and_data):
    pm, x = pipeline_and_data
    eng = ServingEngine(
        pm, Table({"features": x[:4]}),
        ServingConfig(max_batch_rows=64, warmup_row_counts=(1,)),
        output_cols=("prediction",), name="statstest",
    ).start()
    try:
        eng.predict({"features": x[:6]})
        stats = eng.stats()
        assert stats["counters"]["requests"] >= 1
        assert stats["counters"]["batches"] >= 1
        assert "p50_ms" in stats["gauges"]
        text = eng.stats_text()
        assert "# TYPE flinkml_requests counter" in text
        assert 'flinkml_requests{group="serving.statstest"}' in text
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# 4. SnapshotPublisher
# ---------------------------------------------------------------------------

def _kmeans_model(centroids):
    m = KMeansModel().set(KMeansModel.FEATURES_COL, "features")
    m.set_model_data(
        Table({"centroids": np.asarray(centroids, np.float64)[None]})
    )
    return m


def test_publisher_cadence_in_unbounded_iterate(tmp_path):
    from flinkml_tpu.iteration import Iterations

    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = SnapshotPublisher(
        reg, _kmeans_model, every_n_epochs=2, publish_on_terminate=True
    )

    def step(state, batch, epoch):
        return state + batch, None

    stream = [np.ones((3, 2)) * i for i in range(5)]  # 5 epochs
    Iterations.iterate_unbounded_streams(
        step, np.zeros((3, 2)), stream, listeners=[pub]
    )
    # Epochs 1 and 3 publish on cadence; epoch 4 (final) on terminate.
    assert [e for e, _ in pub.published] == [1, 3, 4]
    assert reg.versions() == [1, 2, 3]
    assert reg.current_version() == 3


def test_publisher_skips_duplicate_terminal_snapshot(tmp_path):
    from flinkml_tpu.iteration import Iterations

    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = SnapshotPublisher(reg, _kmeans_model, every_n_epochs=2)

    def step(state, batch, epoch):
        return state + batch, None

    stream = [np.ones((2, 2))] * 4  # 4 epochs: epoch 3 publishes on cadence
    Iterations.iterate_unbounded_streams(
        step, np.zeros((2, 2)), stream, listeners=[pub]
    )
    assert [e for e, _ in pub.published] == [1, 3]  # no duplicate terminal


def test_publisher_restart_then_republish_is_idempotent(tmp_path):
    """ISSUE 4 satellite: a trainer that crashes after publishing epoch E
    and resumes from the epoch-E checkpoint re-reaches the same publish
    point — the registry must NOT grow a duplicate version (dedupe keyed
    on epoch + state fingerprint, committed atomically with the
    version)."""
    from flinkml_tpu.iteration import Iterations

    reg = ModelRegistry(str(tmp_path / "reg"))

    def step(state, batch, epoch):
        return state + batch, None

    stream = [np.ones((3, 2)) * i for i in range(5)]
    pub = SnapshotPublisher(reg, _kmeans_model, every_n_epochs=2,
                            publish_on_terminate=False)
    Iterations.iterate_unbounded_streams(
        step, np.zeros((3, 2)), stream, listeners=[pub]
    )
    assert [e for e, _ in pub.published] == [1, 3]
    assert reg.versions() == [1, 2]

    # "Restart": a FRESH publisher (and fresh registry handle, as a new
    # process would construct) replays the run from the start — every
    # publish re-reaches an (epoch, state) the registry already holds.
    reg2 = ModelRegistry(str(tmp_path / "reg"))
    pub2 = SnapshotPublisher(reg2, _kmeans_model, every_n_epochs=2,
                             publish_on_terminate=False)
    Iterations.iterate_unbounded_streams(
        step, np.zeros((3, 2)), stream, listeners=[pub2]
    )
    # The replayed publishes resolved to the EXISTING versions.
    assert [v for _, v in pub2.published] == [1, 2]
    assert reg2.versions() == [1, 2]  # no growth
    assert reg2.current_version() == 2

    # A genuinely new state still publishes a new version.
    pub3 = SnapshotPublisher(reg2, _kmeans_model, every_n_epochs=2,
                             publish_on_terminate=False)
    Iterations.iterate_unbounded_streams(
        step, np.ones((3, 2)) * 100, stream, listeners=[pub3]
    )
    assert reg2.versions() == [1, 2, 3, 4]


def test_publisher_dedupe_hit_still_swaps_engine(tmp_path):
    """An attached engine may be serving a pre-restart version: a publish
    that resolves via dedupe must still hot-swap the engine to the
    resolved version."""
    from flinkml_tpu.iteration import Iterations

    class SwapRecorder:
        def __init__(self):
            self.swaps = []

        def swap_to(self, version):
            self.swaps.append(version)

    reg = ModelRegistry(str(tmp_path / "reg"))

    def step(state, batch, epoch):
        return state + batch, None

    stream = [np.ones((3, 2))] * 4  # publishes at epochs 1 and 3
    pub = SnapshotPublisher(reg, _kmeans_model, every_n_epochs=2,
                            publish_on_terminate=False)
    Iterations.iterate_unbounded_streams(
        step, np.zeros((3, 2)), stream, listeners=[pub]
    )
    assert reg.versions() == [1, 2]

    eng = SwapRecorder()
    pub2 = SnapshotPublisher(reg, _kmeans_model, every_n_epochs=2,
                             publish_on_terminate=False, engine=eng)
    Iterations.iterate_unbounded_streams(
        step, np.zeros((3, 2)), stream, listeners=[pub2]
    )
    assert reg.versions() == [1, 2]  # all publishes resolved via dedupe
    assert eng.swaps == [1, 2]       # ...and the engine still swapped


def test_publisher_from_kmeans_stream(tmp_path):
    """The train_*_stream hook: a live Lloyd loop emits registry versions
    mid-stream, and the published centroids match the run's trajectory."""
    from flinkml_tpu.models.kmeans import train_kmeans_stream
    from flinkml_tpu.parallel import DeviceMesh

    rng = np.random.default_rng(3)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    batches = [{"x": x[i::4]} for i in range(4)]
    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = SnapshotPublisher(reg, _kmeans_model, every_n_epochs=2)
    final = train_kmeans_stream(
        batches, k=3, mesh=DeviceMesh(), max_iter=4, seed=0,
        listeners=[pub],
    )
    assert [e for e, _ in pub.published] == [1, 3]
    assert reg.versions() == [1, 2]
    _, last = reg.get()
    np.testing.assert_array_equal(np.asarray(last.centroids, np.float32),
                                  final)
