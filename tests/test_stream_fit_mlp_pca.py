"""Out-of-core streamed fit ITs for MLP and PCA — completing the uniform
out-of-core story across the catalog (round 4; reference replay parity
``ReplayOperator.java:62-250``; PCA needs no replay — it is one
accumulation pass).
"""

import numpy as np
import pytest

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.iteration.datacache import cache_stream
from flinkml_tpu.table import Table


def _crash_manager_cls(crash_at_epoch):
    class Crash(CheckpointManager):
        fired = False

        def save(self, state, epoch, extra=None, **kw):
            p = super().save(state, epoch, extra, **kw)
            if not Crash.fired and epoch >= crash_at_epoch:
                Crash.fired = True
                raise RuntimeError("injected crash")
            return p

    return Crash


# -- PCA ---------------------------------------------------------------------

def _pca_batches(n_batches=4, rows=64, d=6, seed=0):
    rng = np.random.default_rng(seed)
    basis = rng.normal(size=(3, d))
    out = []
    for _ in range(n_batches):
        z = rng.normal(size=(rows, 3)) * np.asarray([5.0, 2.0, 0.5])
        x = (z @ basis + rng.normal(scale=0.05, size=(rows, d))).astype(
            np.float32
        )
        out.append(x)
    return out


def test_pca_stream_matches_in_ram(mesh):
    from flinkml_tpu.models.pca import PCA

    batches = _pca_batches()
    x_all = np.concatenate(batches)
    in_ram = PCA(mesh=mesh).set_k(3).fit(Table({"input": x_all}))
    streamed = PCA(mesh=mesh).set_k(3).fit(
        iter(Table({"input": b}) for b in batches)
    )
    np.testing.assert_allclose(
        streamed.components, in_ram.components, rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        streamed.explained_variance, in_ram.explained_variance, rtol=1e-4
    )


def test_pca_stream_from_sealed_cache(mesh):
    from flinkml_tpu.models.pca import PCA

    batches = _pca_batches(seed=3)
    cache = cache_stream({"input": b} for b in batches)
    m = PCA(mesh=mesh).set_k(2).fit(cache)
    assert m.components.shape == (2, 6)
    assert np.isfinite(m.components).all()


def test_pca_stream_empty_raises(mesh):
    from flinkml_tpu.models.pca import PCA

    with pytest.raises(ValueError, match="empty"):
        PCA(mesh=mesh).set_k(2).fit(iter([]))


# -- MLP ---------------------------------------------------------------------

def _mlp_batches(n_batches=4, rows=64, d=6, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(rows, d)).astype(np.float32)
        y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
        out.append({"features": x, "label": y})
    return out


def _mlp(mesh, **kw):
    from flinkml_tpu.models.mlp import MLPClassifier

    return (
        MLPClassifier(mesh=mesh, **kw)
        .set_layers([6, 8, 2]).set_max_iter(6).set_global_batch_size(64)
        .set_learning_rate(0.05).set_tol(0.0).set_seed(0)
    )


def test_mlp_stream_spilled_matches_ram_exactly(tmp_path, mesh):
    batches = _mlp_batches()
    tables = lambda: iter(Table(b) for b in batches)
    ram = _mlp(mesh).fit(tables())
    spilled = _mlp(
        mesh, cache_dir=str(tmp_path / "mlp"), cache_memory_budget_bytes=1
    ).fit(tables())
    for a, b in zip(ram.get_model_data()[0].column_names,
                    ram.get_model_data()[0].column_names):
        assert a == b
    for wa, wb in zip(ram._weights, spilled._weights):
        np.testing.assert_array_equal(wa, wb)
    assert any((tmp_path / "mlp").glob("segment-*.bin"))


def test_mlp_stream_learns(mesh):
    batches = _mlp_batches(n_batches=6)
    model = _mlp(mesh).set_max_iter(25).fit(iter(Table(b) for b in batches))
    big_x = np.concatenate([b["features"] for b in batches])
    big_y = np.concatenate([b["label"] for b in batches])
    (out,) = model.transform(Table({"features": big_x}))
    acc = float((out.column("prediction") == big_y).mean())
    assert acc > 0.9, acc


def test_mlp_stream_resume_exact(tmp_path, mesh):
    batches = _mlp_batches()
    cache = cache_stream(
        {"x": b["features"],
         "y": b["label"].astype(np.int32),
         "w": np.ones(len(b["label"]), np.float32)}
        for b in batches
    )
    golden = _mlp(mesh).fit(cache)

    mgr = _crash_manager_cls(2)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        _mlp(mesh, checkpoint_manager=mgr, checkpoint_interval=2).fit(cache)
    assert mgr.latest_epoch() == 2

    rec = _mlp(mesh, checkpoint_manager=mgr, checkpoint_interval=2,
               resume=True).fit(cache)
    for wa, wb in zip(golden._weights, rec._weights):
        np.testing.assert_array_equal(wa, wb)


def test_mlp_stream_resume_requires_durable_cache(tmp_path, mesh):
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    with pytest.raises(ValueError, match="durable DataCache"):
        _mlp(mesh, checkpoint_manager=mgr, resume=True).fit(
            iter(Table(b) for b in _mlp_batches())
        )


def _fm(mesh, **kw):
    from flinkml_tpu.models.fm import FMClassifier

    return (
        FMClassifier(mesh=mesh, **kw)
        .set_factor_size(4).set_max_iter(6).set_global_batch_size(64)
        .set_learning_rate(0.05).set_reg(0.001).set_tol(0.0).set_seed(0)
    )


def test_fm_stream_spilled_matches_ram_exactly(tmp_path, mesh):
    batches = _mlp_batches()
    tables = lambda: iter(Table(b) for b in batches)
    ram = _fm(mesh).fit(tables())
    spilled = _fm(
        mesh, cache_dir=str(tmp_path / "fm"), cache_memory_budget_bytes=1
    ).fit(tables())
    g, r = ram.get_model_data()[0], spilled.get_model_data()[0]
    for col in g.column_names:
        np.testing.assert_array_equal(
            np.asarray(g.column(col)), np.asarray(r.column(col))
        )
    assert any((tmp_path / "fm").glob("segment-*.bin"))


def test_fm_stream_resume_exact(tmp_path, mesh):
    batches = _mlp_batches()
    cache = cache_stream(
        {"x": b["features"], "y": b["label"].astype(np.float32),
         "w": np.ones(len(b["label"]), np.float32)}
        for b in batches
    )
    golden = _fm(mesh).fit(cache)

    mgr = _crash_manager_cls(2)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        _fm(mesh, checkpoint_manager=mgr, checkpoint_interval=2).fit(cache)
    assert mgr.latest_epoch() == 2

    rec = _fm(mesh, checkpoint_manager=mgr, checkpoint_interval=2,
              resume=True).fit(cache)
    g, r = golden.get_model_data()[0], rec.get_model_data()[0]
    for col in g.column_names:
        np.testing.assert_array_equal(
            np.asarray(g.column(col)), np.asarray(r.column(col))
        )


def test_fm_stream_learns(mesh):
    batches = _mlp_batches(n_batches=6)
    model = _fm(mesh).set_max_iter(25).fit(iter(Table(b) for b in batches))
    big_x = np.concatenate([b["features"] for b in batches])
    big_y = np.concatenate([b["label"] for b in batches])
    (out,) = model.transform(Table({"features": big_x}))
    acc = float((out.column("prediction") == big_y).mean())
    assert acc > 0.85, acc


def test_mlp_in_ram_rejects_checkpoint_knobs(mesh):
    b = _mlp_batches(n_batches=1)[0]
    with pytest.raises(ValueError, match="streamed fits only"):
        _mlp(mesh, checkpoint_manager=CheckpointManager("/tmp/x")).fit(
            Table(b)
        )


def test_pca_stream_extracts_each_batch_once(mesh, monkeypatch):
    """The streamed pass materializes each batch's feature matrix exactly
    once (extraction is fused with validation) — re-extracting in the
    check, payload, and loop body would triple the host cost of a pure
    accumulation pass."""
    import flinkml_tpu.models.pca as pca_mod

    real = pca_mod.features_matrix
    calls = []

    def counting(table, col):
        calls.append(1)
        return real(table, col)

    monkeypatch.setattr(pca_mod, "features_matrix", counting)
    batches = _pca_batches()
    pca_mod.PCA(mesh=mesh).set_k(2).fit(
        iter(Table({"input": b}) for b in batches)
    )
    assert len(calls) == len(batches)
