"""Catalog integrity: every exported model-family symbol is importable,
instantiable, and param-sound.

Guards the breadth of the library as a whole: a broken export, an
abstract leftover, a param whose default violates its own validator, or
a Model subclass without persistence hooks would silently narrow the
catalog.
"""

import inspect

import flinkml_tpu.models as M
from flinkml_tpu.api import Model, Stage


def _exported_classes():
    out = []
    for name in M.__all__:
        obj = getattr(M, name)
        if inspect.isclass(obj):
            out.append((name, obj))
    return out


def test_all_exports_exist_and_are_stages():
    for name in M.__all__:
        assert hasattr(M, name), f"{name} in __all__ but not importable"
    classes = _exported_classes()
    assert len(classes) >= 108   # the catalog should only grow
    for name, cls in classes:
        assert issubclass(cls, Stage), f"{name} is not a Stage"


def test_every_class_instantiates_with_defaults():
    for name, cls in _exported_classes():
        obj = cls()          # every stage must be no-arg constructible
        assert isinstance(obj, Stage)


def test_params_roundtrip_via_json():
    for name, cls in _exported_classes():
        obj = cls()
        encoded = obj.get_param_map_json()
        clone = cls()
        clone.load_param_map_json(encoded)
        assert clone.get_param_map_json() == encoded, name


def test_estimator_model_pairing_convention():
    """Every FooModel export has a Foo estimator/operator sibling or is
    itself standalone; every Estimator's fit returns a Model subclass
    annotation-wise (spot check on naming only — behavior is covered by
    per-family tests)."""
    names = {n for n, _ in _exported_classes()}
    for name, cls in _exported_classes():
        if name.endswith("Model") and name != "Model":
            base = name[: -len("Model")]
            assert base in names or base in ("IndexToString",), (
                f"{name} has no visible estimator counterpart"
            )


def test_models_have_persistence_hooks():
    for name, cls in _exported_classes():
        if issubclass(cls, Model):
            # Identity check against Stage's generic hooks (MRO-shape
            # independent): a Model must override both or it would drop
            # its model data on persistence.
            assert cls.save is not Stage.save, (
                f"{name} relies on the bare Stage.save"
            )
            assert inspect.unwrap(cls.load.__func__) is not inspect.unwrap(
                Stage.load.__func__
            ), f"{name} relies on the bare Stage.load"
