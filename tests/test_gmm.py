"""GaussianMixture vs sklearn: recovery, likelihood, persistence."""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score
from sklearn.mixture import GaussianMixture as SkGMM

from flinkml_tpu.models import GaussianMixture, GaussianMixtureModel
from flinkml_tpu.table import Table


def _blobs(seed=0, n_per=150):
    rng = np.random.default_rng(seed)
    comps = [
        (np.asarray([0.0, 0.0]), np.asarray([[1.0, 0.6], [0.6, 1.0]])),
        (np.asarray([6.0, 0.0]), np.asarray([[0.5, 0.0], [0.0, 2.0]])),
        (np.asarray([0.0, 6.0]), np.asarray([[1.5, -0.5], [-0.5, 0.7]])),
    ]
    xs, ys = [], []
    for i, (m, c) in enumerate(comps):
        xs.append(rng.multivariate_normal(m, c, size=n_per))
        ys.append(np.full(n_per, i))
    return np.concatenate(xs), np.concatenate(ys)


def _gmm(k=3, cov="full", iters=100, seed=1):
    return (
        GaussianMixture().set_k(k).set_covariance_type(cov)
        .set_max_iter(iters).set_tol(1e-7).set_seed(seed)
    )


def test_full_covariance_recovers_components():
    x, y = _blobs()
    t = Table({"features": x})
    model = _gmm().fit(t)
    (out,) = model.transform(t)
    assert adjusted_rand_score(y, out["prediction"]) > 0.9
    # Mixture weights near 1/3 each; responsibilities sum to 1.
    np.testing.assert_allclose(model.weights.sum(), 1.0, rtol=1e-9)
    assert model.weights.min() > 0.25
    np.testing.assert_allclose(
        out["rawPrediction"].sum(axis=1), 1.0, rtol=1e-9
    )


def test_likelihood_close_to_sklearn():
    x, _ = _blobs(seed=2)
    t = Table({"features": x})
    model = _gmm(seed=3).fit(t)
    sk = SkGMM(n_components=3, covariance_type="full", random_state=0,
               n_init=3).fit(x)
    # Our average log-likelihood should be within noise of sklearn's.
    from flinkml_tpu.models.gmm import _log_prob
    import jax.numpy as jnp
    from jax.scipy.special import logsumexp

    ours = float(np.mean(np.asarray(logsumexp(_log_prob(
        jnp.asarray(x, jnp.float32), jnp.asarray(model.weights, jnp.float32),
        jnp.asarray(model.means, jnp.float32),
        jnp.asarray(model.covariances, jnp.float32), "full"), axis=1))))
    theirs = float(sk.score(x))
    assert ours > theirs - 0.05, (ours, theirs)


def test_diag_covariance_mode():
    rng = np.random.default_rng(4)
    x = np.concatenate([
        rng.normal(size=(200, 3)) * np.asarray([0.5, 2.0, 1.0]),
        rng.normal(size=(200, 3)) + 5.0,
    ])
    y = np.repeat([0, 1], 200)
    t = Table({"features": x})
    model = _gmm(k=2, cov="diag").fit(t)
    assert model.covariances.shape == (2, 3)
    (out,) = model.transform(t)
    assert adjusted_rand_score(y, out["prediction"]) > 0.95


def test_save_load_and_model_data(tmp_path):
    x, _ = _blobs(seed=5, n_per=60)
    t = Table({"features": x})
    model = _gmm(iters=20).fit(t)
    model.save(str(tmp_path / "gmm"))
    loaded = GaussianMixtureModel.load(str(tmp_path / "gmm"))
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_allclose(p2["rawPrediction"], p1["rawPrediction"])
    clone = GaussianMixtureModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    (p3,) = clone.transform(t)
    np.testing.assert_allclose(p3["prediction"], p1["prediction"])


def test_validation_and_determinism():
    x, _ = _blobs(seed=6, n_per=40)
    t = Table({"features": x})
    with pytest.raises(ValueError, match="n_rows"):
        _gmm(k=1000).fit(t)
    m1 = _gmm(iters=10, seed=7).fit(t)
    m2 = _gmm(iters=10, seed=7).fit(t)
    np.testing.assert_array_equal(m1.means, m2.means)


def test_large_mean_offset_no_cancellation():
    # +1e4 offset: naive f32 E[xx] - mm^T sufficient statistics go
    # non-PSD and NaN-poison the Cholesky; centered EM must recover.
    x, y = _blobs(seed=8, n_per=100)
    x = x + 10_000.0
    t = Table({"features": x})
    model = _gmm(seed=9).fit(t)
    assert np.isfinite(model.means).all()
    assert np.isfinite(model.covariances).all()
    (out,) = model.transform(t)
    from sklearn.metrics import adjusted_rand_score as _ari

    assert _ari(y, out["prediction"]) > 0.9
    # Means live in the original (offset) space.
    assert model.means.min() > 9_000


def test_duplicate_points_do_not_crash_seeding():
    x = np.ones((20, 2))
    x[10:] = 2.0
    t = Table({"features": x})
    model = _gmm(k=2, iters=5, seed=10).fit(t)
    assert np.isfinite(model.means).all()
