"""Elastic resharded resume (ISSUE 6): a snapshot committed at world N
resumes at world M.

The acceptance contract (ROADMAP item 4): Dataset-fed training killed at
world 4 resumes at world 2 AND world 8 with a bit-identical model where
the math is world-independent (replicated carries + the global-order
ElasticFeed — all three online trainers, shuffle order preserved), a
documented bounded-divergence contract where it is not (world-grouped
updates), and loud typed errors — RescaleError /
CursorShardMismatchError — for genuinely rank-entangled state. The old
same-world resume paths stay bit-exact.
"""

import logging
import os

import numpy as np
import pytest

from flinkml_tpu import faults
from flinkml_tpu.data import (
    Cursor,
    CursorShardMismatchError,
    Dataset,
    ElasticFeed,
)
from flinkml_tpu.iteration import (
    CheckpointManager,
    RescaleError,
    RescalePolicy,
    reshard_rank_state,
)
from flinkml_tpu.models import OnlineKMeans, OnlineLogisticRegression
from flinkml_tpu.models.online_scaler import OnlineStandardScaler
from flinkml_tpu.table import Table
from flinkml_tpu.utils.preemption import PreemptionWatchdog

B = 12          # global batches
KILL_EPOCH = 7  # rank loss fires here
INTERVAL = 3    # checkpoint cadence

DIM = 5
_TRUE = np.arange(1.0, DIM + 1.0)


def lr_batch(i, rng):
    x = rng.normal(size=(48, DIM))
    return Table({"features": x, "label": (x @ _TRUE > 0).astype(np.float64)})


def km_batch(i, rng):
    centers = np.arange(12.0).reshape(3, 4)
    assign = rng.integers(0, 3, size=40)
    return Table({"features": centers[assign]
                  + rng.normal(scale=0.4, size=(40, 4))})


def sc_batch(i, rng):
    return Table({"input": rng.normal(size=(32, 6)) * (1 + i)})


def lr_feed(world, shuffled=False, prefetched=False):
    feed = ElasticFeed(
        lambda shard: Dataset.synthetic(lr_batch, B, seed=7, shard=shard),
        world,
    )
    if shuffled:
        feed = feed.shuffle(4, seed=13)
    if prefetched:
        feed = feed.prefetch(depth=2)
    return feed


def _lr():
    return OnlineLogisticRegression().set_alpha(0.5).set_reg(0.01)


def _km():
    return OnlineKMeans().set_k(3).set_seed(11).set_decay_factor(0.9)


def _sc():
    return OnlineStandardScaler()


TRAINERS = {
    "lr": (
        _lr, lr_batch,
        lambda m: m.coefficient,
    ),
    "kmeans": (
        _km, km_batch,
        lambda m: m.centroids,
    ),
    "scaler": (
        _sc, sc_batch,
        lambda m: np.concatenate([m._mean, m._std]),
    ),
}


def _feed(make_batch, world):
    return ElasticFeed(
        lambda shard: Dataset.synthetic(make_batch, B, seed=7, shard=shard),
        world,
    )


def _kill_at_world(est_factory, feed, mgr, epoch=KILL_EPOCH, rank=2):
    """The failure half of the acceptance scenario: a peer rank dies at
    ``epoch`` (rank.lost seam -> watchdog), the loop stops cleanly at
    the boundary with a terminal snapshot."""
    wd = PreemptionWatchdog(signals=())
    with wd:
        with faults.armed(faults.FaultPlan(faults.RankLost(epoch=epoch,
                                                           rank=rank))):
            partial = est_factory().fit_stream(
                feed, checkpoint_manager=mgr, checkpoint_interval=INTERVAL,
            )
    assert wd.shrink_requested and wd.lost_ranks == [rank]
    assert mgr.latest_epoch() == epoch  # the preemption's final snapshot
    return wd, partial


# ---------------------------------------------------------------------------
# The ElasticFeed invariant: one canonical global order at every world
# ---------------------------------------------------------------------------

def test_elastic_feed_global_order_world_independent():
    def key_seq(world, shuffled=False):
        return [float(np.asarray(b.column("features"))[0, 0])
                for b in lr_feed(world, shuffled=shuffled)]

    plain = key_seq(1)
    assert len(plain) == B
    assert key_seq(4) == plain and key_seq(8) == plain
    shuffled = key_seq(1, shuffled=True)
    assert key_seq(4, shuffled=True) == shuffled
    assert key_seq(8, shuffled=True) == shuffled
    assert sorted(shuffled) == sorted(plain) and shuffled != plain


def test_elastic_feed_cursor_reshards_mid_stream():
    """A cursor cut mid-stream at world 4 resumes the EXACT tail at
    world 2 and world 8 — shuffle order included (the shuffle runs on
    the global sequence, so it is world-independent by construction)."""
    def heads(it, n):
        return [float(np.asarray(next(it).column("features"))[0, 0])
                for _ in range(n)]

    golden = heads(lr_feed(1, shuffled=True).iterate(), B)
    it4 = lr_feed(4, shuffled=True).iterate()
    head = heads(it4, 6)
    cur = it4.cursor()
    it4.close()
    assert cur.emitted == 6 and cur.num_shards == 4
    assert cur.shard_index is None  # global-scope cursor
    for world in (2, 8):
        it = lr_feed(world, shuffled=True).iterate(cur)
        tail = heads(it, B - 6)
        it.close()
        assert head + tail == golden


def test_elastic_feed_validates_shard_factory():
    with pytest.raises(ValueError, match="honor its shard argument"):
        next(iter(ElasticFeed(
            lambda shard: Dataset.synthetic(lr_batch, B, shard=(0, 1)), 4,
        )))


# ---------------------------------------------------------------------------
# THE acceptance criterion: kill at world 4, resume at world 2 AND 8,
# bit-identical — all three online trainers
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(TRAINERS))
def test_kill_world4_resume_world2_and_world8_bit_exact(tmp_path, name):
    est_factory, make_batch, extract = TRAINERS[name]
    golden = est_factory().fit_stream(_feed(make_batch, 1))

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10,
                            rescale="reshard")
    wd, partial = _kill_at_world(est_factory, _feed(make_batch, 4), mgr)
    assert partial.model_version == KILL_EPOCH

    # The survivors' plan: newest commonly-valid snapshot, shrunken world.
    plan = wd.plan_elastic_resume(mgr, world=4)
    assert plan.epoch == KILL_EPOCH and plan.old_world == 4
    assert plan.new_world == 3  # 4 ranks, 1 lost

    for world in (2, 8):
        m = CheckpointManager(str(tmp_path / f"ckpt-w{world}"),
                              max_to_keep=10, rescale="reshard")
        # Each resume starts from its own copy of the kill-time snapshot
        # state (the shared directory would otherwise be rewritten by
        # the first resume's terminal commit at ITS world).
        import shutil

        shutil.rmtree(str(tmp_path / f"ckpt-w{world}"))
        shutil.copytree(str(tmp_path / "ckpt"),
                        str(tmp_path / f"ckpt-w{world}"))
        recovered = est_factory().fit_stream(
            _feed(make_batch, world), checkpoint_manager=m,
            checkpoint_interval=INTERVAL, resume=True,
        )
        np.testing.assert_array_equal(extract(recovered), extract(golden))
        assert recovered.model_version == golden.model_version == B


def test_kill_world4_resume_world2_shuffled_dataset_fed(tmp_path):
    """The Dataset-fed variant with a SHUFFLED pipeline: shuffle order
    is preserved across the world change (global-order shuffle), so the
    resumed model is still bit-identical."""
    golden = _lr().fit_stream(lr_feed(1, shuffled=True))

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10,
                            rescale="reshard")
    _kill_at_world(_lr, lr_feed(4, shuffled=True), mgr)
    recovered = _lr().fit_stream(
        lr_feed(2, shuffled=True), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL, resume=True,
    )
    np.testing.assert_array_equal(recovered.coefficient, golden.coefficient)
    assert recovered.model_version == B
    cursor = mgr.last_restored_extra["data_cursor"]
    assert cursor["num_shards"] == 4 and cursor["shard_index"] is None
    assert cursor["shuffle"] is not None


@pytest.mark.no_retrace
def test_elasticity_smoke_prefetched_zero_retrace(tmp_path):
    """Tier-1 elasticity smoke: the full pipeline (synthetic source ->
    global merge -> bucket-padded device prefetch) killed at world 4 and
    resumed at world 2, bit-identical, with zero retraces (constant
    batch shapes land in one bucket)."""
    golden = _lr().fit_stream(lr_feed(1, prefetched=True))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10,
                            rescale="reshard")
    _kill_at_world(_lr, lr_feed(4, prefetched=True), mgr)
    recovered = _lr().fit_stream(
        lr_feed(2, prefetched=True), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL, resume=True,
    )
    np.testing.assert_array_equal(recovered.coefficient, golden.coefficient)


def test_same_world_resume_paths_stay_bit_exact(tmp_path):
    """The pre-elastic contract is untouched: kill+resume at the SAME
    world is bit-exact, and the cursor now records its shard count."""
    golden = _lr().fit_stream(lr_feed(4))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(KILL_EPOCH))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(lr_feed(4), checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL)
    recovered = _lr().fit_stream(lr_feed(4), checkpoint_manager=mgr,
                                 checkpoint_interval=INTERVAL, resume=True)
    np.testing.assert_array_equal(recovered.coefficient, golden.coefficient)
    cursor = mgr.last_restored_extra["data_cursor"]
    assert cursor["num_shards"] == 4


# ---------------------------------------------------------------------------
# The documented bounded-divergence contract: world-GROUPED updates
# ---------------------------------------------------------------------------

def test_world_grouped_updates_bounded_divergence(tmp_path):
    """When the update itself groups one batch per rank (the psum'd
    data-parallel composition), a world change alters the update
    granularity: the resumed model consumes the identical global data
    but is NOT bit-identical. The documented contract
    (docs/development/fault_tolerance.md, 'Elastic resume') is
    convergence-level equivalence; this pins it with an explicit
    tolerance."""
    def grouped(feed_iter, group):
        pending = []
        for batch in feed_iter:
            pending.append(batch)
            if len(pending) == group:
                out = pending[0]
                for t in pending[1:]:
                    out = out.concat(t)
                yield out
                pending = []
        if pending:
            out = pending[0]
            for t in pending[1:]:
                out = out.concat(t)
            yield out

    # Uninterrupted fixed-world-4 run: 12 global batches in groups of 4.
    golden = _lr().fit_stream(grouped(lr_feed(4).iterate(), 4))

    # Elastic run: groups of 4 until the kill after 2 updates (8 global
    # batches consumed), then resume grouped by the SHRUNKEN world 2
    # over the exact remaining global tail.
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10,
                            rescale="reshard")
    with faults.armed(faults.FaultPlan(faults.RaiseAtEpoch(2))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(grouped(lr_feed(4).iterate(), 4),
                             checkpoint_manager=mgr, checkpoint_interval=1)
    assert mgr.latest_epoch() == 2  # two grouped updates committed
    tail = lr_feed(2).iterate(Cursor(emitted=8, num_shards=2))
    recovered = _lr().fit_stream(
        grouped(tail, 2), checkpoint_manager=mgr, checkpoint_interval=1,
        resume=True, stream_resume="continue",
    )
    # Same global data, different grouping: equivalent to tolerance,
    # not to the bit.
    assert not np.array_equal(recovered.coefficient, golden.coefficient)
    np.testing.assert_allclose(recovered.coefficient, golden.coefficient,
                               rtol=0.35, atol=0.05)
    cos = np.dot(recovered.coefficient, golden.coefficient) / (
        np.linalg.norm(recovered.coefficient)
        * np.linalg.norm(golden.coefficient)
    )
    assert cos > 0.99


# ---------------------------------------------------------------------------
# Typed refusals: RescaleError (satellite 2) + CursorShardMismatchError
# (satellite 1)
# ---------------------------------------------------------------------------

def test_rescale_reject_error_carries_triage_context(tmp_path, caplog):
    mgr = CheckpointManager(str(tmp_path), world_size=4)
    mgr.save({"w": np.ones(3)}, 5)
    reader = CheckpointManager(str(tmp_path), world_size=2)
    with caplog.at_level(logging.ERROR, logger="flinkml_tpu.checkpoint"):
        with pytest.raises(RescaleError) as exc:
            reader.restore(5, like={"w": 0})
    msg = str(exc.value)
    # Fleet-log triage needs: which snapshot, which epoch, which worlds,
    # what the policy decided.
    assert os.path.join(str(tmp_path), "ckpt-5") in msg
    assert "epoch 5" in msg
    assert "world_size=4" in msg and "world_size=2" in msg
    assert "reject" in msg
    # ... and the same message through the rank-tagged logger.
    assert any("ckpt-5" in rec.message for rec in caplog.records)


def test_rescale_policy_layout_matrix(tmp_path):
    """reshard policy: replicated restores free; sharded revalidates
    divisibility; per_rank refuses; legacy allow skips validation."""
    state = {"coef": np.ones(3), "rows": np.arange(8.0)}
    writer = CheckpointManager(str(tmp_path), world_size=4)
    writer.save(state, 1, layouts={"coef": "replicated", "rows": "sharded:0"})

    ok = CheckpointManager(str(tmp_path), world_size=2, rescale="reshard")
    restored, epoch = ok.restore(1, like={"coef": 0, "rows": 0})
    assert epoch == 1
    np.testing.assert_array_equal(restored["rows"], np.arange(8.0))

    bad = CheckpointManager(str(tmp_path), world_size=3, rescale="reshard")
    with pytest.raises(RescaleError, match="does not divide"):
        bad.restore(1, like={"coef": 0, "rows": 0})

    writer.save({"m": np.arange(4.0)}, 2, layouts="per_rank")
    with pytest.raises(RescaleError, match="per_rank"):
        CheckpointManager(str(tmp_path), world_size=2,
                          rescale="reshard").restore(2, like={"m": 0})
    # The legacy escape hatch stays available (and unvalidated).
    relaxed = CheckpointManager(str(tmp_path), world_size=2, rescale="allow")
    relaxed.restore(2, like={"m": 0})
    assert relaxed.allow_rescale  # legacy property view

    with pytest.raises(ValueError, match="reject"):
        RescalePolicy("explode")
    with pytest.raises(ValueError, match="layout"):
        writer.save({"m": np.arange(4.0)}, 3, layouts="diagonal")


def test_reshard_rank_state_reassembles_and_resplits(tmp_path):
    like = {"w": 0, "rows": 0}
    for r in range(4):
        mgr = CheckpointManager(str(tmp_path / f"rank-{r}"), world_size=4)
        mgr.save({"w": np.full(3, 7.0), "rows": np.arange(4.0) + 10 * r}, 2,
                 layouts={"w": "replicated", "rows": "sharded:0"})
    # 4-way family -> 2 ranks of 8 rows, rank order preserved.
    st = reshard_rank_state(str(tmp_path), 2, like, new_shard=(1, 2))
    np.testing.assert_array_equal(st["w"], np.full(3, 7.0))
    np.testing.assert_array_equal(
        st["rows"], np.concatenate([np.arange(4.0) + 20, np.arange(4.0) + 30])
    )
    # Diverged "replicated" leaves are a broken family, not a restore.
    mgr0 = CheckpointManager(str(tmp_path / "rank-0"), world_size=4)
    mgr0.save({"w": np.full(3, 9.0), "rows": np.arange(4.0)}, 2,
              layouts={"w": "replicated", "rows": "sharded:0"})
    with pytest.raises(RescaleError, match="diverges"):
        reshard_rank_state(str(tmp_path), 2, like, new_shard=(0, 2))
    # A missing rank's shard cannot be reassembled.
    import shutil

    shutil.rmtree(str(tmp_path / "rank-2"))
    with pytest.raises(RescaleError, match="not contiguous"):
        reshard_rank_state(str(tmp_path), 2, like, new_shard=(0, 2))


def test_cursor_shard_mismatch_is_loud(tmp_path):
    """Satellite 1: a cursor from a 4-way feed must never silently
    fast-forward a 2-way feed to the wrong rows."""
    rows = np.arange(80.0).reshape(40, 2)

    def block_ds(shard):
        return Dataset.from_arrays(Table({"x": rows}), 4, shard=shard)

    # Per-shard Dataset, contiguous-block deal: entangled -> loud.
    it = block_ds((0, 4)).iterate()
    next(it)
    cur = it.cursor()
    it.close()
    assert cur.num_shards == 4 and cur.shard_index == 0
    with pytest.raises(CursorShardMismatchError, match="cannot reshard"):
        block_ds((0, 2)).iterate(cur)
    # Same world: fine (the pre-elastic path).
    it2 = block_ds((0, 4)).iterate(cur)
    assert it2.emitted == 1
    it2.close()

    # Round-robin synthetic deal: the reshard is legal and re-derived.
    syn4 = Dataset.synthetic(lr_batch, B, seed=7, shard=(1, 4))
    it = syn4.iterate()
    next(it)
    scur = it.cursor()
    it.close()
    syn2 = Dataset.synthetic(lr_batch, B, seed=7, shard=(1, 2))
    it = syn2.iterate(scur)
    # global watermark 1*4=4 -> shard 1 of 2 owns indices 1,3 -> skip 2
    assert it.emitted == 2
    it.close()

    # ElasticFeed over block shards: same-world resume fine, world
    # change loud.
    efeed4 = ElasticFeed(block_ds, 4)
    it = efeed4.iterate()
    [next(it) for _ in range(5)]
    gcur = it.cursor()
    it.close()
    it = efeed4.iterate(gcur)
    assert it.emitted == 5
    it.close()
    with pytest.raises(CursorShardMismatchError, match="not round-robin"):
        ElasticFeed(block_ds, 2).iterate(gcur)

    # Scope mixups are refused in both directions.
    with pytest.raises(CursorShardMismatchError, match="global-order"):
        block_ds((0, 4)).iterate(gcur)
    with pytest.raises(CursorShardMismatchError, match="per-shard"):
        efeed4.iterate(scur)


def test_cursor_json_roundtrip_carries_shards():
    c = Cursor(emitted=6, num_shards=4, shard_index=None, in_flight=1)
    d = c.to_json_dict()
    back = Cursor.from_json_dict(d)
    assert back == c and back.global_emitted == 6
    per = Cursor(emitted=3, num_shards=4, shard_index=2)
    assert per.global_emitted == 12  # lockstep: per-shard x world
    legacy = Cursor.from_json_dict({"emitted": 5})  # pre-elastic cursors
    assert legacy.num_shards is None and legacy.shard_index is None


# ---------------------------------------------------------------------------
# The survivors' rendezvous
# ---------------------------------------------------------------------------

def test_agree_resume_epoch_picks_newest_commonly_valid(tmp_path):
    from flinkml_tpu.parallel.distributed import agree_resume_epoch

    mgr = CheckpointManager(str(tmp_path), max_to_keep=10)
    for epoch in (2, 4, 6):
        mgr.save({"w": np.full(2, float(epoch))}, epoch)
    assert agree_resume_epoch(mgr) == 6
    faults.corrupt_latest(mgr, target="arrays")
    # The newest snapshot no longer verifies: survivors agree on 4.
    assert agree_resume_epoch(mgr) == 4
    empty = CheckpointManager(str(tmp_path / "none"))
    assert agree_resume_epoch(empty) is None


def test_rescale_rendezvous_seam_scriptable(tmp_path):
    wd = PreemptionWatchdog(signals=())
    wd.notify_rank_lost(3)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save({"w": np.ones(2)}, 1)
    with faults.armed(faults.FaultPlan(faults.FailRendezvous())) as plan:
        with pytest.raises(faults.FaultInjected, match="rendezvous"):
            wd.plan_elastic_resume(mgr, world=4)
    assert plan.log and plan.log[0][0] == "rendezvous.rescale"
    # Undisturbed, the plan carries the agreed epoch + shrunken world.
    plan2 = wd.plan_elastic_resume(mgr, world=4)
    assert (plan2.epoch, plan2.old_world, plan2.new_world) == (1, 4, 3)


def test_rank_lost_without_watchdog_is_a_hard_crash():
    with faults.armed(faults.FaultPlan(faults.RankLost(epoch=1, rank=0))):
        with pytest.raises(faults.FaultInjected, match="rank loss"):
            _lr().fit_stream(lr_feed(2))


def test_compact_rank_and_survivor_world():
    from flinkml_tpu.parallel.distributed import compact_rank

    assert compact_rank(0, [2]) == 0
    assert compact_rank(3, [2]) == 2
    assert compact_rank(2, [2]) is None
    assert compact_rank(5, [0, 3]) == 3
    wd = PreemptionWatchdog(signals=())
    wd.notify_rank_lost(1)
    wd.notify_rank_lost(1)  # idempotent
    assert wd.lost_ranks == [1] and wd.survivor_world(4) == 3
    assert wd.survivor_world(1) == 1  # floored: this host is alive


def test_chained_reshard_watermark_stays_exact():
    """A reshard whose global watermark does not divide the new world
    leaves UNEVEN per-shard skips; the cursor's recorded
    ``global_watermark`` keeps subsequent reshards exact where the
    lockstep product (emitted x num_shards) would overestimate and
    silently skip batches."""
    N = 60

    def ds(shard):
        return Dataset.synthetic(lr_batch, N, seed=7, shard=shard)

    # World 4, 7 lockstep rounds -> 28 global batches consumed.
    its4 = [ds((i, 4)).iterate() for i in range(4)]
    for _ in range(7):
        for it in its4:
            next(it)
    c4 = its4[0].cursor()
    for it in its4:
        it.close()
    assert c4.global_emitted == 28

    # Reshard rank 0 to world 8: skip ceil(28/8)=4, then ONE more
    # lockstep round -> global 36 (the product 5*8=40 would lie).
    it8 = ds((0, 8)).iterate(c4)
    assert it8.emitted == 4
    next(it8)
    c8 = it8.cursor()
    it8.close()
    assert c8.emitted == 5 and c8.global_emitted == 36

    # Second reshard to world 2 lands exactly at global batch 36.
    it2 = ds((0, 2)).iterate(c8)
    assert it2.emitted == 18  # shard 0 of 2 owns even indices < 36
    batch = next(it2)
    it2.close()
    rng = np.random.default_rng([7, 36])  # SyntheticSource's draw key
    expected = lr_batch(36, rng)
    np.testing.assert_array_equal(
        np.asarray(batch.column("features")),
        np.asarray(expected.column("features")),
    )


# ---------------------------------------------------------------------------
# Plan x elastic resume composition (ISSUE 7): FSDP-sharded state,
# plan-derived layout tags, world change through the rank.lost seam
# ---------------------------------------------------------------------------

def test_fsdp_plan_kill_world4_resume_world2_and_world8(tmp_path):
    """An FSDP-sharded SGD trainer (parameters + momentum sharded per
    the plan, snapshots tagged by ``save(plan=...)``) killed at world 4
    through the ``rank.lost`` seam resumes at world 2 AND world 8 — the
    plan-derived ``sharded:0`` tags are what make the cross-world
    re-layout legal, with no hand-written ``layouts=`` anywhere."""
    import json
    import shutil

    import jax

    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding import FSDP
    from flinkml_tpu.sharding.apply import train_linear_plan

    dim = 64
    rng = np.random.default_rng(3)
    x = rng.normal(size=(96, dim))
    y = (x @ np.arange(1.0, dim + 1.0) > 0).astype(x.dtype)

    def run(world, mgr=None, resume=False):
        mesh = DeviceMesh.for_plan(FSDP, devices=jax.devices()[:world])
        return train_linear_plan(
            x, y, None, FSDP, mesh, max_iter=B, learning_rate=0.5,
            checkpoint_manager=mgr, checkpoint_interval=INTERVAL,
            resume=resume,
        )

    golden = run(1)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10,
                            rescale="reshard")
    wd = PreemptionWatchdog(signals=())
    with wd:
        with faults.armed(faults.FaultPlan(
                faults.RankLost(epoch=KILL_EPOCH, rank=2))):
            run(4, mgr)
    assert wd.shrink_requested and wd.lost_ranks == [2]
    assert mgr.latest_epoch() == KILL_EPOCH  # the preemption's snapshot

    # The kill-time snapshot carries PLAN-derived tags at world 4.
    with open(tmp_path / "ckpt" / f"ckpt-{KILL_EPOCH}" / "meta.json") as fh:
        meta = json.load(fh)
    assert meta["layouts"] == ["sharded:0", "sharded:0"]  # coef, momentum
    assert meta["world_size"] == 4

    for world in (2, 8):
        shutil.copytree(str(tmp_path / "ckpt"), str(tmp_path / f"w{world}"))
        m = CheckpointManager(str(tmp_path / f"w{world}"), max_to_keep=10,
                              rescale="reshard")
        recovered = run(world, m, resume=True)
        np.testing.assert_allclose(recovered, golden, rtol=1e-9,
                                   atol=1e-12)
        # The resumed run's own terminal snapshot records ITS world.
        with open(tmp_path / f"w{world}" / f"ckpt-{B}" /
                  "meta.json") as fh:
            assert json.load(fh)["world_size"] == world


def test_verify_keeps_bool_contract_over_failed_async_write(tmp_path):
    """A parked async-write failure (the crash path verify exists for)
    must not leak out of the verification queries: the failure is
    drained+logged and the COMMITTED snapshots are still nominated —
    elastic planning falls back instead of crashing."""
    mgr = CheckpointManager(str(tmp_path), max_to_keep=10, async_write=True)
    mgr.save({"w": np.ones(2)}, 1)
    mgr.wait()
    with faults.armed(faults.FaultPlan(faults.TornWrite(2))):
        mgr.save({"w": np.full(2, 2.0)}, 2)  # background write will tear
        assert mgr.newest_valid_epoch() == 1  # drains quietly, no raise
    assert mgr.verify(1) and not mgr.verify(2)
    from flinkml_tpu.parallel.distributed import agree_resume_epoch

    assert agree_resume_epoch(mgr) == 1
