"""Tests for the observability utilities (SURVEY.md §5 tracing/metrics)."""

import time

import numpy as np

from flinkml_tpu.iteration import IterationConfig, TerminateOnMaxIter, iterate
from flinkml_tpu.utils import (
    EpochMetricsListener,
    MetricsRegistry,
    StepTimer,
    annotate,
    trace,
)


def test_counter_gauge_meter_history():
    reg = MetricsRegistry()
    g = reg.group("op")
    assert g.counter("records", 3) == 3
    assert g.counter("records", 2) == 5
    g.gauge("epoch", 7)
    g.record("loss", 0.5)
    g.record("loss", 0.25)
    m = g.meter("rows")
    m.mark(100, now=0.0)
    m.mark(100, now=1.0)
    snap = reg.snapshot()["op"]
    assert snap["counters"]["records"] == 5
    assert snap["gauges"]["epoch"] == 7
    assert snap["histories"]["loss"] == [0.5, 0.25]
    assert abs(snap["meters"]["rows"] - 100.0) < 1e-9


def test_registry_reuses_groups_and_dumps_json():
    reg = MetricsRegistry()
    assert reg.group("a") is reg.group("a")
    reg.group("a").counter("c")
    assert '"c": 1' in reg.dump_json().replace("1.0", "1")
    reg.reset()
    assert reg.snapshot() == {}


def test_epoch_metrics_listener_in_iterate():
    reg = MetricsRegistry()
    listener = EpochMetricsListener(
        group=reg.group("train"), samples_per_epoch=128
    )

    def step(state, epoch):
        return state + 1, None

    result = iterate(
        step, 0, config=IterationConfig(TerminateOnMaxIter(5)),
        listeners=[listener],
    )
    snap = reg.snapshot()["train"]
    assert result.epochs == 5
    assert snap["counters"]["epochs"] == 5
    assert len(snap["histories"]["epoch_seconds"]) == 5
    assert snap["gauges"]["total_seconds"] > 0
    assert snap["gauges"]["samples_per_sec"] > 0


def test_step_timer_blocks_and_records():
    import jax.numpy as jnp

    reg = MetricsRegistry()
    timer = StepTimer(group=reg.group("t"))
    for _ in range(3):
        with timer:
            out = jnp.ones((64, 64)) @ jnp.ones((64, 64))
            timer.observe(out)
    assert len(timer.times) == 3
    assert timer.mean > 0
    assert len(reg.snapshot()["t"]["histories"]["step_seconds"]) == 3


def test_trace_context_is_safe_without_profiler(tmp_path):
    # Must not raise even if the backend can't start a trace.
    with trace(str(tmp_path)):
        x = np.arange(10).sum()
    assert x == 45


def test_annotate_context():
    with annotate("my-region"):
        pass


# -- RowReservoir ------------------------------------------------------------

def test_row_reservoir_uniform_and_deterministic():
    from flinkml_tpu.utils.sampling import RowReservoir

    # Fill phase: capacity >= stream -> the sample IS the stream, in order.
    r = RowReservoir(100, seed=0)
    block = np.arange(30, dtype=np.float64).reshape(10, 3)
    r.add(block)
    np.testing.assert_array_equal(r.sample(), block)
    assert r.rows_seen == 10

    # Replacement phase: bounded size, deterministic for a fixed seed,
    # and approximately uniform over the stream.
    def run(seed):
        rr = RowReservoir(64, seed=seed)
        for s in range(50):
            rr.add(np.arange(s * 100, (s + 1) * 100, dtype=np.float64)[:, None])
        return rr.sample()

    a, b = run(1), run(1)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (64, 1)
    # Uniformity: the sample mean of row ids is near the stream mean.
    mean = float(np.mean(run(2)))
    assert abs(mean - 2499.5) < 600, mean


def test_render_text_prometheus_exposition():
    reg = MetricsRegistry()
    g = reg.group("serving.demo")
    g.counter("requests", 3)
    g.gauge("queue_depth", 2)
    g.gauge("label", "not-a-number")  # skipped: non-numeric
    m = g.meter("rows")
    m.mark(100, now=0.0)
    m.mark(100, now=1.0)
    reg.group("train.lr").counter("requests", 7)  # same metric, 2nd group
    text = reg.render_text()
    lines = text.splitlines()
    assert "# TYPE flinkml_requests counter" in lines
    assert 'flinkml_requests{group="serving.demo"} 3' in lines
    assert 'flinkml_requests{group="train.lr"} 7' in lines
    assert "# TYPE flinkml_queue_depth gauge" in lines
    assert 'flinkml_queue_depth{group="serving.demo"} 2' in lines
    assert any(l.startswith('flinkml_rows_rate{group="serving.demo"}')
               for l in lines)
    assert "not-a-number" not in text
    # TYPE lines precede their samples; output is deterministic.
    assert text == reg.render_text()
    assert reg.render_text().endswith("\n")


def test_render_text_sanitizes_names_and_default_registry():
    from flinkml_tpu.utils import default_registry, metrics

    assert default_registry() is metrics
    reg = MetricsRegistry()
    reg.group("g").counter("weird name-1.x", 1)
    text = reg.render_text()
    assert "flinkml_weird_name_1_x" in text
    assert reg.render_text() == text
    assert MetricsRegistry().render_text() == ""
    # Label VALUES escape quotes/backslashes/newlines (exposition format).
    reg2 = MetricsRegistry()
    reg2.group('serving.a"b\\c').counter("requests", 1)
    assert '{group="serving.a\\"b\\\\c"}' in reg2.render_text()


def test_render_text_replica_labels_aggregate():
    """Per-replica groups share ONE metric family distinguished by a
    ``replica`` label (the serving pool's exposition) instead of
    colliding in a flat namespace; label-less groups are unchanged."""
    reg = MetricsRegistry()
    for i, depth in enumerate((2, 5)):
        g = reg.group("serving.pool", labels={"replica": f"r{i}"})
        g.gauge("queue_depth", depth)
        g.counter("requests", 10 * (i + 1))
    reg.group("serving.pool").counter("requests", 7)  # pool-level, no label
    text = reg.render_text()
    lines = text.splitlines()
    assert lines.count("# TYPE flinkml_queue_depth gauge") == 1
    assert 'flinkml_queue_depth{group="serving.pool",replica="r0"} 2' in lines
    assert 'flinkml_queue_depth{group="serving.pool",replica="r1"} 5' in lines
    assert 'flinkml_requests{group="serving.pool",replica="r0"} 10' in lines
    assert 'flinkml_requests{group="serving.pool",replica="r1"} 20' in lines
    assert 'flinkml_requests{group="serving.pool"} 7' in lines
    # Distinct label sets are distinct groups; same set is the same one.
    a = reg.group("serving.pool", labels={"replica": "r0"})
    assert a is reg.group("serving.pool", labels={"replica": "r0"})
    assert a is not reg.group("serving.pool")
    # snapshot() keys label-qualified names; plain names stay plain.
    snap = reg.snapshot()
    assert snap['serving.pool{replica="r0"}']["gauges"]["queue_depth"] == 2
    assert snap["serving.pool"]["counters"]["requests"] == 7
    assert text == reg.render_text()  # deterministic


def test_render_text_full_precision_and_type_collisions():
    # Counters keep full precision (no %g truncation past 6 sig digits).
    reg = MetricsRegistry()
    reg.group("g").counter("requests", 1_234_567)
    assert 'flinkml_requests{group="g"} 1234567' in reg.render_text()
    # The same metric name as counter in one group, gauge in another:
    # one family per type (the later kind gets a kind-suffixed family),
    # never a mistyped sample under a single TYPE line.
    reg2 = MetricsRegistry()
    reg2.group("a").counter("depth", 2)
    reg2.group("b").gauge("depth", 5)
    text = reg2.render_text()
    assert "# TYPE flinkml_depth counter" in text
    assert 'flinkml_depth{group="a"} 2' in text
    assert "# TYPE flinkml_depth_gauge gauge" in text
    assert 'flinkml_depth_gauge{group="b"} 5' in text


# -- rank-tagged logging (ISSUE 4 satellite) ---------------------------------

def test_rank_tagged_logger(caplog):
    import logging

    from flinkml_tpu.utils import logging as flog

    log = flog.get_logger("testrank")
    with caplog.at_level(logging.INFO, logger="flinkml_tpu.testrank"):
        log.info("hello %s", "world")
    assert caplog.records[-1].getMessage() == "[rank 0/1] hello world"
    # Pinning the rank changes the tag; restore for other tests.
    try:
        flog.set_rank(3, 8)
        assert flog.rank_tag() == "[rank 3/8]"
    finally:
        flog._RANK = None
    assert flog.rank_tag() == "[rank 0/1]"


def test_logger_namespace_and_console_handler_idempotent():
    import logging

    from flinkml_tpu.utils import logging as flog

    assert flog.get_logger("x").logger.name == "flinkml_tpu.x"
    assert flog.get_logger("flinkml_tpu.y").logger.name == "flinkml_tpu.y"
    root = logging.getLogger("flinkml_tpu")
    before = list(root.handlers)
    try:
        h1 = flog.enable_console(logging.WARNING)
        h2 = flog.enable_console(logging.INFO)
        assert h1 is h2  # reused, not stacked
        assert h2.level == logging.INFO
    finally:
        root.handlers = before
        root.setLevel(logging.NOTSET)


def test_checkpoint_operations_emit_logs(tmp_path, caplog):
    import logging

    import numpy as np

    from flinkml_tpu.iteration import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), max_to_keep=1)
    with caplog.at_level(logging.INFO, logger="flinkml_tpu.checkpoint"):
        mgr.save({"w": np.ones(2)}, 1)
        mgr.save({"w": np.ones(2)}, 2)  # prunes epoch 1
    text = " ".join(r.getMessage() for r in caplog.records)
    assert "checkpoint committed: epoch 1" in text
    assert "pruning checkpoint epoch 1" in text
    assert "[rank 0/1]" in text
