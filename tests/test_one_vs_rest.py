"""OneVsRest over LogisticRegression and LinearSVC."""

import numpy as np
import pytest

from flinkml_tpu.models import (
    LinearSVC,
    LogisticRegression,
    OneVsRest,
    OneVsRestModel,
)
from flinkml_tpu.table import Table


def _three_class(n_per=120, seed=0):
    # Angularly separated clusters: the framework's linear models carry
    # no intercept (reference parity), so each one-vs-rest subproblem
    # must be separable by a halfspace THROUGH THE ORIGIN — three
    # clusters at 120-degree angles are.
    rng = np.random.default_rng(seed)
    centers = [
        (5.0, 0.0), (-2.5, 4.33), (-2.5, -4.33),
    ]
    x = np.concatenate([
        rng.normal(size=(n_per, 2)) * 0.6 + c for c in centers
    ])
    y = np.repeat([0.0, 1.0, 2.0], n_per)
    return x, y


def _lr():
    return (
        LogisticRegression().set_max_iter(60).set_global_batch_size(512)
        .set_learning_rate(1.0).set_seed(0)
    )


def test_ovr_multiclass_with_lr():
    x, y = _three_class()
    t = Table({"features": x, "label": y})
    model = OneVsRest(_lr()).fit(t)
    np.testing.assert_array_equal(model.classes, [0.0, 1.0, 2.0])
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.95
    assert out["rawPrediction"].shape == (len(y), 3)


def test_ovr_with_margin_classifier():
    x, y = _three_class(seed=1)
    t = Table({"features": x, "label": y})
    svc = (
        LinearSVC().set_max_iter(60).set_global_batch_size(512)
        .set_learning_rate(0.5).set_seed(0)
    )
    model = OneVsRest(svc).fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.9


def test_ovr_non_contiguous_class_ids():
    x, y = _three_class(seed=2)
    y = y * 3 + 5   # classes {5, 8, 11}
    t = Table({"features": x, "label": y})
    model = OneVsRest(_lr()).fit(t)
    (out,) = model.transform(t)
    assert set(np.unique(out["prediction"])) <= {5.0, 8.0, 11.0}
    assert (out["prediction"] == y).mean() > 0.95


def test_ovr_save_load(tmp_path):
    x, y = _three_class(n_per=60, seed=3)
    t = Table({"features": x, "label": y})
    model = OneVsRest(_lr()).fit(t)
    model.save(str(tmp_path / "ovr"))
    loaded = OneVsRestModel.load(str(tmp_path / "ovr"))
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_array_equal(p2["prediction"], p1["prediction"])
    np.testing.assert_allclose(p2["rawPrediction"], p1["rawPrediction"])


def test_ovr_validation():
    t = Table({"features": np.zeros((4, 2)), "label": np.zeros(4)})
    with pytest.raises(ValueError, match="classifier"):
        OneVsRest().fit(t)
    with pytest.raises(ValueError, match="2 classes"):
        OneVsRest(_lr()).fit(t)
    t2 = Table({"features": np.zeros((4, 2)),
                "label": np.asarray([0.5, 1.0, 0.5, 1.0])})
    with pytest.raises(ValueError, match="integral"):
        OneVsRest(_lr()).fit(t2)


def test_ovr_custom_label_col_propagates():
    x, y = _three_class(n_per=50, seed=4)
    t = Table({"features": x, "target": y})
    inner = _lr().set_label_col("target")
    model = OneVsRest(inner).set_label_col("target").fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.95


def test_ovr_margin_scores_used_for_ties():
    from flinkml_tpu.models import LinearSVC

    x, y = _three_class(seed=5)
    t = Table({"features": x, "label": y})
    svc = (
        LinearSVC().set_max_iter(60).set_global_batch_size(512)
        .set_learning_rate(0.5).set_seed(0)
    )
    model = OneVsRest(svc).fit(t)
    (out,) = model.transform(t)
    # Raw scores are continuous margins, not 0/1 fallbacks.
    raw = out["rawPrediction"]
    assert len(np.unique(raw)) > 10


def test_ovr_inner_custom_raw_prediction_col():
    x, y = _three_class(n_per=50, seed=6)
    t = Table({"features": x, "label": y})
    inner = _lr().set_raw_prediction_col("innerRaw")
    model = OneVsRest(inner).fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.95
    # Scores must be the inner model's continuous probabilities, not the
    # 0/1 prediction fallback (which also reaches high accuracy here).
    assert len(np.unique(out["rawPrediction"])) > 10


def test_ovr_composes_with_gbt():
    from flinkml_tpu.models import GBTClassifier

    rng = np.random.default_rng(7)
    x = rng.uniform(-2, 2, size=(450, 2))
    # Three nonlinear regions: |x| small / x0*x1 positive / negative.
    y = np.where(
        np.abs(x).sum(1) < 1.2, 0.0, np.where(x[:, 0] * x[:, 1] > 0, 1.0, 2.0)
    )
    t = Table({"features": x, "label": y})
    gbt = (
        GBTClassifier().set_num_trees(25).set_max_depth(4)
        .set_learning_rate(0.3).set_seed(0)
    )
    model = OneVsRest(gbt).fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.9
