"""Autoscaling + multi-tenant serving (ISSUE 15, ROADMAP item 3).

The acceptance contract:

  1. **Closed loop**: offered load triples against an undersized pool;
     the autoscaler grows replicas from the pool's own metrics and p99
     recovers WITHOUT operator action — and every scale-up replica warms
     through the compile-cache retarget-load path, so scaling pays zero
     new XLA compiles in-process.
  2. **Chaos composition**: killing a replica mid-spike composes with
     the scaling loop — the autoscaler replaces the retired replica
     (healthy count under ``min_replicas`` outranks hysteresis), the
     router's failover loses zero requests, and the pool converges.
  3. **Hysteresis**: scale events need decisive, sustained signals (the
     autotune 1.10x idiom) — noise cannot flap the replica count.
  4. **Leases**: a training slice lease is reclaimed via the revoke →
     release handshake before serving is placed on it; with reclaim
     disabled the scaler refuses rather than stealing the slice (the
     FML304 shape).
  5. **Multi-tenancy**: N models over one pool route correctly, roll
     their registries independently, and a batch-class job can never
     starve the interactive tier (class admission shares).
  6. Satellites: a fresh/revived replica's latency EWMA seeds from its
     healthy siblings' median; revive resets pre-failure health stats.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from flinkml_tpu import faults, pipeline_fusion
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.models.scalers import StandardScaler
from flinkml_tpu.parallel import dispatch as _dispatch
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.serving import (
    BATCH,
    INTERACTIVE,
    AutoscaleConfig,
    MultiModelPool,
    PoolAutoscaler,
    ReplicaHealth,
    ReplicaPool,
    ServingConfig,
    SLOAdmissionError,
    SLOClass,
)
from flinkml_tpu.table import Table


def _data(n=300, d=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return x, y


def _chain(x, y):
    train = Table({"features": x, "label": y})
    sc = (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "scaled")
        .fit(train)
    )
    (t2,) = sc.transform(train)
    lr = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, "scaled")
        .set(LogisticRegression.LABEL_COL, "label")
        .set_max_iter(3)
        .fit(t2)
    )
    return PipelineModel([sc, lr])


def _pool(source, x, n_replicas=1, name="as_pool", **cfg):
    config = ServingConfig(**{
        "max_batch_rows": 32,
        "max_queue_rows": 256,
        "max_wait_ms": 1.0,
        **cfg,
    })
    return ReplicaPool(
        source, Table({"features": x[:4]}), config=config,
        n_replicas=n_replicas, output_cols=("prediction",), name=name,
    )


def _fusion_counters():
    snap = pipeline_fusion.metrics.group("pipeline.fusion").snapshot()
    return snap["counters"]


@pytest.fixture(scope="module")
def scale_child_report():
    """The clean-process scale-up scenario (zero-new-XLA-compiles is
    serialization-dependent and the suite conftest's jax persistent
    cache poisons executable serialization process-wide — see
    ``tests/_autoscale_child.py``)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "_autoscale_child.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ,
             "PYTHONPATH": os.pathsep.join(
                 [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
                 + ([os.environ["PYTHONPATH"]]
                    if os.environ.get("PYTHONPATH") else [])
             )},
    )
    assert proc.returncode == 0, (
        f"autoscale child failed:\n{proc.stdout}\n{proc.stderr}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---------------------------------------------------------------------------
# 1. The closed-loop acceptance scenario
# ---------------------------------------------------------------------------

def test_scale_up_zero_new_xla_compiles_clean_process(scale_child_report):
    """The acceptance pin: scale-up replicas warm via compile-cache
    RETARGET LOADS — zero new XLA compiles in-process, and the scaled
    replicas' predictions are bitwise-identical to the originals'."""
    rep = scale_child_report
    assert rep["new_compiles_on_scale_up"] == 0, rep
    assert rep["aot_loads_on_scale_up"] > 0, rep
    assert rep["scaled_replica_parity_bitwise"] is True, rep


def test_closed_loop_load_triple_recovers_p99_without_operator():
    """Offered load triples against a 1-replica pool; the autoscaler
    (background control thread — no operator in the loop) scales up on
    the backlog signal, the pool's own scaling signal recovers below
    the threshold, zero requests are lost, and post-scale p99 holds
    within a 2x tripwire of the pre-scale spike.

    Why a tripwire and not strict improvement on THIS mesh: host-
    platform CPU "devices" share one XLA executor pool and the Python
    dispatchers share the GIL, so IN-PROCESS replicas cannot add real
    capacity (closed-loop p50 scales with 1/throughput — Little's law);
    the true p99-recovery number is the queued DEVICE bench stage's,
    where each replica owns a chip (the PR 8 precedent). The remedy
    for the single-process ceiling itself is the multi-process worker
    pool (``flinkml_tpu.cluster.ClusterPool`` — each replica a real
    process with its own GIL and executor pool; see
    ``tests/test_cluster.py`` and ci's ``cluster smoke`` stage), which
    this scenario deliberately does NOT use so the tripwire keeps
    watching the in-process path. The 2x bound is NOT vacuous: the
    unbounded per-(rows,bucket) pad-compile bug this PR fixed in
    ``Table.device_column_padded`` degraded exactly this scenario >10x.
    (The zero-compile half of the acceptance runs in the clean child
    process above — the suite conftest's jax pcache forces in-process
    scale-ups to degrade to compile-only.)"""
    x, y = _data()
    pm = _chain(x, y)
    pool = _pool(pm, x, n_replicas=1, name="loop_pool",
                 max_queue_rows=512).start()
    # up_consecutive x interval gives a ~1s measurable saturation window
    # BEFORE the first scale event — the "spike" the recovery is judged
    # against.
    scaler = PoolAutoscaler(pool, AutoscaleConfig(
        min_replicas=1, max_replicas=3, scale_up_backlog=0.05,
        up_consecutive=10, down_consecutive=10_000,  # no down mid-test
        cooldown_s=0.3, interval_s=0.1,
    )).start()
    stop = threading.Event()
    lat: list = []  # (t_completed, latency_ms)
    lat_lock = threading.Lock()
    errors: list = []

    def client(tid):
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            rows = int(rng.integers(8, 25))
            lo = int(rng.integers(0, x.shape[0] - rows))
            t0 = time.perf_counter()
            try:
                pool.predict({"features": x[lo:lo + rows]})
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return
            with lat_lock:
                lat.append((time.perf_counter(),
                            (time.perf_counter() - t0) * 1e3))

    def p99_window(t0, t1=None):
        with lat_lock:
            vals = [ms for (tc, ms) in lat
                    if tc >= t0 and (t1 is None or tc < t1)]
        return (float(np.percentile(vals, 99)), len(vals)) if vals \
            else (None, 0)

    try:
        # Phase 1: light load (2 clients) — the pool is sized for this.
        light = [threading.Thread(target=client, args=(i,))
                 for i in range(2)]
        for t in light:
            t.start()
        time.sleep(0.8)

        # Phase 2: offered load triples (6 clients total).
        spike_t0 = time.perf_counter()
        heavy = [threading.Thread(target=client, args=(10 + i,))
                 for i in range(4)]
        for t in heavy:
            t.start()

        # The control loop must react on its own.
        deadline = time.monotonic() + 40
        while time.monotonic() < deadline and len(pool.replicas) < 2:
            time.sleep(0.05)
        first_scale_t = time.perf_counter()
        assert len(pool.replicas) >= 2, (
            f"autoscaler never scaled up: {scaler.stats()}"
        )
        backlog_at_scale = scaler.stats()["backlog_ewma"]
        spike_p99, spike_n = p99_window(spike_t0, first_scale_t)
        # Let scaling settle: replica count stable for >= 1s (later
        # scale-ups pay in-process compiles that must not pollute the
        # recovery window).
        stable_since = time.monotonic()
        last_count = len(pool.replicas)
        while time.monotonic() < deadline:
            if len(pool.replicas) != last_count:
                last_count = len(pool.replicas)
                stable_since = time.monotonic()
            if time.monotonic() - stable_since >= 1.0:
                break
            time.sleep(0.05)
        settle_t0 = time.perf_counter()
        time.sleep(1.5)  # post-scale steady state under the SAME load
        recovered_p99, rec_n = p99_window(settle_t0)
        stop.set()
        for t in light + heavy:
            t.join(timeout=60)
    finally:
        stop.set()
        scaler.stop()
        pool.stop()
    assert not errors, errors[:3]
    st = scaler.stats()
    assert st["counters"].get("scale_events_total", 0) >= 1
    # The control loop's own signal recovered: scaling grew aggregate
    # queue capacity, so the backlog fraction fell decisively from its
    # at-scale-time level (a constant in-flight row count over 3x the
    # capacity).
    assert st["backlog_ewma"] is not None and backlog_at_scale is not None
    assert st["backlog_ewma"] <= backlog_at_scale * 0.75, (
        f"backlog signal never recovered: {backlog_at_scale:.3f} -> "
        f"{st['backlog_ewma']:.3f} ({st})"
    )
    # p99 tripwire (see docstring for why 2x, not strict improvement,
    # on a shared-executor CPU mesh).
    assert spike_p99 is not None and spike_n >= 5, (spike_p99, spike_n)
    assert recovered_p99 is not None and rec_n >= 5
    assert recovered_p99 <= spike_p99 * 2.0, (
        f"p99 catastrophically degraded after scale-up: spike "
        f"{spike_p99:.1f}ms ({spike_n} reqs) -> {recovered_p99:.1f}ms "
        f"({rec_n} reqs) ({st})"
    )


def test_scale_up_seeds_ewma_from_sibling_median():
    """Satellite regression: a replica added to a serving pool seeds
    its latency EWMA from the healthy siblings' median, so the router's
    deadline ordering treats it as a known quantity and it takes load
    immediately instead of settling late."""
    x, y = _data()
    pm = _chain(x, y)
    pool = _pool(pm, x, n_replicas=2, name="seed_pool").start()
    try:
        for i in range(6):
            pool.predict({"features": x[i:i + 3]})
        sib = [r.health.ewma_ms_per_row for r in pool.replicas]
        assert any(v is not None for v in sib)
        replica = pool.add_replica()
        expect = float(np.median([v for v in sib if v is not None]))
        assert replica.health.ewma_ms_per_row == pytest.approx(expect)
        # ...and it serves immediately.
        resp = pool.predict({"features": x[:3]})
        assert resp.columns["prediction"].shape == (3,)
    finally:
        pool.stop()


def test_scale_down_drains_without_losing_requests():
    x, y = _data()
    pm = _chain(x, y)
    pool = _pool(pm, x, n_replicas=3, name="down_pool").start()
    try:
        for i in range(9):
            pool.predict({"features": x[i:i + 2]})
        name = pool.remove_replica()
        assert len(pool.replicas) == 2
        assert all(r.name != name for r in pool.replicas)
        resp = pool.predict({"features": x[:2]})
        assert resp.columns["prediction"].shape == (2,)
        with pytest.raises(ValueError, match="last healthy"):
            pool.remove_replica()
            pool.remove_replica()
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# 2. Chaos composition: kill mid-spike, the scaler replaces
# ---------------------------------------------------------------------------

def test_chaos_kill_mid_spike_autoscaler_replaces_and_pool_converges():
    """Extends the PR 8 chaos contract to the scaling loop: killing 1 of
    2 replicas mid-load loses zero requests (router failover) AND the
    autoscaler replaces the retired replica (healthy < min_replicas
    outranks hysteresis), so capacity — and p99 — converge."""
    x, y = _data()
    pm = _chain(x, y)
    pool = _pool(pm, x, n_replicas=2, name="chaos_scale_pool").start()
    scaler = PoolAutoscaler(pool, AutoscaleConfig(
        min_replicas=2, max_replicas=4, scale_up_backlog=0.95,
        up_consecutive=10_000, down_consecutive=10_000,
        cooldown_s=0.1, interval_s=0.05,
    )).start()
    stop = threading.Event()
    errors: list = []
    served = [0]

    def client(tid):
        rng = np.random.default_rng(tid)
        try:
            while not stop.is_set():
                rows = int(rng.integers(1, 7))
                lo = int(rng.integers(0, x.shape[0] - rows))
                resp = pool.predict({"features": x[lo:lo + rows]})
                (ref,) = pm.transform(Table({"features": x[lo:lo + rows]}))
                np.testing.assert_array_equal(
                    np.asarray(ref.column("prediction")),
                    resp.column("prediction"),
                )
                served[0] += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    try:
        with faults.armed(faults.FaultPlan(
            faults.ReplicaDown("r1", at_batch=2)
        )):
            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(4)
            ]
            for t in threads:
                t.start()
            # Wait for the kill to land and the scaler to replace it
            # (the dead slot is PRUNED once the replacement joins, so
            # the observable end state is: r1 gone, r2 serving).
            deadline = time.monotonic() + 60
            replaced = False
            while time.monotonic() < deadline:
                st = pool.stats()
                if ("r2" in st["per_replica"] and st["healthy"] >= 2
                        and "r1" not in st["per_replica"]):
                    replaced = True
                    break
                time.sleep(0.05)
            served_at_replace = served[0]
            time.sleep(0.5)  # must keep serving on the replacement
            stop.set()
            for t in threads:
                t.join(timeout=60)
    finally:
        stop.set()
        scaler.stop()
        pool.stop()
    assert not errors, errors[:3]
    assert replaced, f"scaler never replaced the dead replica: {pool.stats()}"
    assert served[0] > served_at_replace, "pool stalled after replacement"
    assert scaler.stats()["counters"].get("replacements_total", 0) >= 1
    # The replacement is a NEW replica (r2), and the dead slot was
    # pruned (a flapping failure must not leak stopped engines).
    names = {r.name for r in pool.replicas}
    assert "r2" in names and "r1" not in names, names


# ---------------------------------------------------------------------------
# 3. Hysteresis
# ---------------------------------------------------------------------------

def test_hysteresis_needs_decisive_sustained_signal():
    """A single noisy sample (or a signal inside the 1.10x band) never
    scales; a sustained decisive one does — the autotune idiom."""
    x, y = _data()
    pm = _chain(x, y)
    pool = _pool(pm, x, n_replicas=1, name="hyst_pool",
                 max_queue_rows=100).start()
    try:
        cfg = AutoscaleConfig(
            min_replicas=1, max_replicas=3, scale_up_backlog=0.5,
            up_consecutive=2, cooldown_s=0.0, backlog_alpha=1.0,
        )
        scaler = PoolAutoscaler(pool, cfg)
        # Signal ABOVE threshold but inside the decisive band
        # (0.5 <= 0.52 < 0.55): never fires.
        pool.replicas[0].health.outstanding_rows = 52
        for _ in range(6):
            assert scaler.step() is None
        assert len(pool.replicas) == 1
        # Decisive (>= 0.55) but only ONE evaluation: still no event.
        pool.replicas[0].health.outstanding_rows = 90
        assert scaler.step() is None
        pool.replicas[0].health.outstanding_rows = 0
        assert scaler.step() is None  # streak broken
        # Decisive AND sustained: fires exactly once, then cooldown.
        pool.replicas[0].health.outstanding_rows = 90
        assert scaler.step() is None
        assert scaler.step() == "up"
        assert len(pool.replicas) == 2
        pool.replicas[0].health.outstanding_rows = 0
    finally:
        pool.stop()


def test_scale_down_needs_sustained_idle():
    x, y = _data()
    pm = _chain(x, y)
    pool = _pool(pm, x, n_replicas=2, name="idle_pool").start()
    try:
        scaler = PoolAutoscaler(pool, AutoscaleConfig(
            min_replicas=1, max_replicas=3, scale_up_backlog=0.5,
            down_consecutive=3, cooldown_s=0.0, backlog_alpha=1.0,
        ))
        assert scaler.step() is None
        assert scaler.step() is None
        assert scaler.step() == "down"
        assert len(pool.replicas) == 1
        # Never below min_replicas.
        for _ in range(10):
            scaler.step()
        assert len(pool.replicas) == 1
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# 4. Training slice leases
# ---------------------------------------------------------------------------

def _clear_foreign_leases(before):
    with _dispatch._LEASES_GUARD:
        for token in set(_dispatch._LEASES) - before:
            del _dispatch._LEASES[token]


def test_lease_reclaim_handshake_frees_devices_for_scale_up():
    """Every candidate device is leased to a 'trainer'; the autoscaler
    performs the reclaim handshake (request_revoke -> the trainer
    releases at its next safe boundary -> placement on the freed
    device). The trainer observes the revoke through the lease it
    polls."""
    import jax

    x, y = _data()
    pm = _chain(x, y)
    devices = jax.devices()[:2]
    leases_before = set(_dispatch._LEASES)
    pool = _pool(pm, x, n_replicas=1, name="lease_pool")
    pool._device_universe = list(devices)
    pool.start()
    lease = _dispatch.lease_devices(devices, holder="trainer")
    released_by_trainer = threading.Event()

    def trainer():
        # The cooperating holder: poll at "epoch boundaries".
        while not lease.revoke_requested():
            time.sleep(0.01)
        lease.release()
        released_by_trainer.set()

    t = threading.Thread(target=trainer)
    t.start()
    try:
        scaler = PoolAutoscaler(pool, AutoscaleConfig(
            min_replicas=1, max_replicas=3, scale_up_backlog=0.1,
            up_consecutive=1, cooldown_s=0.0, backlog_alpha=1.0,
            reclaim_leases=True, lease_reclaim_timeout_s=10.0,
        ))
        pool.replicas[0].health.outstanding_rows = 200
        assert scaler.step() == "up"
        pool.replicas[0].health.outstanding_rows = 0
        assert released_by_trainer.is_set()
        assert not lease.active
        assert lease.revoke_reason and "lease_pool" in lease.revoke_reason
        assert len(pool.replicas) == 2
        assert scaler.stats()["counters"].get("lease_reclaims_total") == 1
    finally:
        t.join(timeout=10)
        lease.release()
        _clear_foreign_leases(leases_before)
        pool.stop()


def test_scaler_refuses_leased_placement_without_reclaim():
    """reclaim_leases=False: the scaler must NOT place serving work on
    a leased slice (the FML304 shape) — it skips the scale-up loudly
    and proceeds once the lease is gone."""
    import jax

    x, y = _data()
    pm = _chain(x, y)
    devices = jax.devices()[:2]
    leases_before = set(_dispatch._LEASES)
    pool = _pool(pm, x, n_replicas=1, name="nolease_pool")
    pool._device_universe = list(devices)
    pool.start()
    lease = _dispatch.lease_devices(devices, holder="trainer")
    try:
        scaler = PoolAutoscaler(pool, AutoscaleConfig(
            min_replicas=1, max_replicas=3, scale_up_backlog=0.1,
            up_consecutive=1, cooldown_s=0.0, backlog_alpha=1.0,
            reclaim_leases=False,
        ))
        pool.replicas[0].health.outstanding_rows = 200
        assert scaler.step() is None  # refused, not placed on the lease
        assert len(pool.replicas) == 1
        assert lease.active and not lease.revoke_requested()
        lease.release()
        assert scaler.step() == "up"  # the streak survived the refusal
        assert len(pool.replicas) == 2
        pool.replicas[0].health.outstanding_rows = 0
    finally:
        lease.release()
        _clear_foreign_leases(leases_before)
        pool.stop()


# ---------------------------------------------------------------------------
# 5. Multi-model multiplexing + SLO-weighted admission
# ---------------------------------------------------------------------------

def _mm_pool(x, pm_a, pm_b, name="mm_pool", batch_share=0.5):
    mm = MultiModelPool(
        Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=32, max_queue_rows=64,
                             max_wait_ms=1.0),
        name=name,
    )
    mm.add_model("rank", pm_a, slo=INTERACTIVE, n_replicas=2)
    mm.add_model("offline", pm_b, slo=SLOClass(
        "batch", weight=1.0, deadline_ms=30_000.0,
        max_queue_share=batch_share,
    ), n_replicas=1)
    return mm


def test_multimodel_routing_parity_and_output_cols():
    x, y = _data()
    pm_a, pm_b = _chain(x, y), _chain(x, 1.0 - y)
    mm = _mm_pool(x, pm_a, pm_b).start()
    try:
        ra = mm.predict("rank", {"features": x[:5]})
        rb = mm.predict("offline", {"features": x[:5]})
        (ref_a,) = pm_a.transform(Table({"features": x[:5]}))
        (ref_b,) = pm_b.transform(Table({"features": x[:5]}))
        np.testing.assert_array_equal(
            np.asarray(ref_a.column("prediction")), ra.column("prediction")
        )
        np.testing.assert_array_equal(
            np.asarray(ref_b.column("prediction")), rb.column("prediction")
        )
        with pytest.raises(KeyError, match="no model"):
            mm.predict("absent", {"features": x[:2]})
        # Replicas are model-tagged and the router filtered by them.
        st = mm.stats()
        assert st["models"]["rank"]["replicas"] == ["r0", "r1"]
        assert st["models"]["offline"]["replicas"] == ["r2"]
    finally:
        mm.stop()


def test_batch_class_admission_cap_is_the_starvation_guarantee():
    """The deterministic half of 'batch can never starve interactive':
    with the batch class's full capacity share in flight, further batch
    requests are refused with the TYPED class error while interactive
    admission (its own share untouched) proceeds — so the interactive
    tier always has headroom by construction."""
    x, y = _data()
    pm_a, pm_b = _chain(x, y), _chain(x, 1.0 - y)
    mm = _mm_pool(x, pm_a, pm_b, name="starve_pool").start()
    try:
        capacity = sum(r.engine.config.max_queue_rows for r in mm.replicas)
        ledger = mm._ledgers["batch"]
        ledger.outstanding_rows = int(0.5 * capacity)  # share exhausted
        with pytest.raises(SLOAdmissionError, match="batch"):
            mm.predict("offline", {"features": x[:4]})
        # Interactive is untouched by the batch class's spent budget.
        resp = mm.predict("rank", {"features": x[:4]})
        assert resp.columns["prediction"].shape == (4,)
        ledger.outstanding_rows = 0
        st = mm.stats()["classes"]
        assert st["batch"]["counters"]["budget_rejections"] == 1
        assert st["interactive"]["counters"]["admitted_requests"] >= 1
    finally:
        mm.stop()


def test_batch_saturation_live_interactive_stays_served():
    """The live half: batch clients hammer their model continuously
    (accepting their typed budget refusals); every interactive request
    completes within its deadline budget — zero interactive failures."""
    x, y = _data()
    pm_a, pm_b = _chain(x, y), _chain(x, 1.0 - y)
    mm = _mm_pool(x, pm_a, pm_b, name="live_starve_pool",
                  batch_share=0.25).start()
    stop = threading.Event()
    interactive_errors: list = []
    batch_rejections = [0]

    def batch_client(tid):
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            rows = int(rng.integers(16, 33))
            lo = int(rng.integers(0, x.shape[0] - rows))
            try:
                mm.predict("offline", {"features": x[lo:lo + rows]})
            except SLOAdmissionError:
                batch_rejections[0] += 1  # working as designed: back off
                time.sleep(0.002)
            except Exception:  # noqa: BLE001 — pool stopping
                return

    def interactive_client(tid):
        rng = np.random.default_rng(100 + tid)
        try:
            for _ in range(30):
                rows = int(rng.integers(1, 5))
                lo = int(rng.integers(0, x.shape[0] - rows))
                mm.predict("rank", {"features": x[lo:lo + rows]},
                           timeout_ms=10_000.0)
        except BaseException as e:  # noqa: BLE001
            interactive_errors.append(e)

    try:
        batchers = [threading.Thread(target=batch_client, args=(i,))
                    for i in range(4)]
        for t in batchers:
            t.start()
        time.sleep(0.3)  # batch pressure established
        inter = [threading.Thread(target=interactive_client, args=(i,))
                 for i in range(2)]
        for t in inter:
            t.start()
        for t in inter:
            t.join(timeout=120)
        stop.set()
        for t in batchers:
            t.join(timeout=60)
    finally:
        stop.set()
        mm.stop()
    assert not interactive_errors, interactive_errors[:3]
    # Per-class latency families exist for the dashboards.
    gauges = mm.stats()["classes"]["interactive"]["gauges"]
    assert "p99_ms" in gauges


def test_multimodel_registries_roll_independently(tmp_path):
    from flinkml_tpu.serving import ModelRegistry

    x, y = _data()
    pm_a, pm_b = _chain(x, y), _chain(x, 1.0 - y)
    reg_a = ModelRegistry(str(tmp_path / "a"))
    reg_b = ModelRegistry(str(tmp_path / "b"))
    reg_a.publish(pm_a)
    reg_b.publish(pm_b)
    mm = MultiModelPool(
        Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=32, max_queue_rows=64,
                             max_wait_ms=1.0),
        name="roll_mm",
    )
    mm.add_model("a", reg_a, slo=INTERACTIVE, n_replicas=2)
    mm.add_model("b", reg_b, slo=BATCH, n_replicas=1)
    mm.start()
    mm.follow_registries()
    try:
        assert mm.predict("a", {"features": x[:2]}).version == 1
        reg_a.publish(_chain(x, y))  # v2 for model a ONLY
        versions = {
            r.name: r.engine.active_version for r in mm.replicas
        }
        assert versions == {"r0": 2, "r1": 2, "r2": 1}, versions
        assert mm.predict("a", {"features": x[:2]}).version == 2
        assert mm.predict("b", {"features": x[:2]}).version == 1
    finally:
        mm.stop()


def test_multimodel_scale_target_is_slo_weighted():
    x, y = _data()
    pm_a, pm_b = _chain(x, y), _chain(x, 1.0 - y)
    mm = _mm_pool(x, pm_a, pm_b, name="target_pool").start()
    try:
        # Equal per-model backlog: interactive's 3x weight wins.
        for r in mm.replicas:
            r.health.outstanding_rows = 20
        assert mm.scale_target()["model_id"] == "rank"
        # Batch backlog 10x: batch outweighs the weight handicap.
        for r in mm.replicas:
            r.health.outstanding_rows = (
                60 if r.model_id == "offline" else 2
            )
        assert mm.scale_target()["model_id"] == "offline"
        # The scaler plumbs the target through add_replica(model_id=).
        scaler = PoolAutoscaler(mm, AutoscaleConfig(
            min_replicas=1, max_replicas=6, scale_up_backlog=0.1,
            up_consecutive=1, cooldown_s=0.0, backlog_alpha=1.0,
        ))
        assert scaler.step() == "up"
        assert [r.model_id for r in mm.replicas].count("offline") == 2
        for r in mm.replicas:
            r.health.outstanding_rows = 0
        # Scale-down never removes a model's last replica.
        victim = mm._scale_down_victim()
        assert victim.model_id in ("rank", "offline")
        per_model = [r.model_id for r in mm.replicas]
        assert per_model.count(victim.model_id) >= 2
    finally:
        mm.stop()


# ---------------------------------------------------------------------------
# 6. Satellites: EWMA seeding + revive reset
# ---------------------------------------------------------------------------

def test_replica_health_revive_resets_latency_and_backlog():
    """Satellite regression: revive() must clear the retired replica's
    pre-failure EWMA and outstanding rows — stale history must not rank
    the revived replica."""
    h = ReplicaHealth("rX")
    h.submit(40)
    h.on_success(40, 400.0)  # ewma 10 ms/row
    h.on_error(RuntimeError("boom"))
    assert h.state.value == "unhealthy"
    assert h.ewma_ms_per_row is not None
    h.revive()
    assert h.state.value == "healthy"
    assert h.ewma_ms_per_row is None
    assert h.outstanding_rows == 0
    # seed_ewma fills the blank but never clobbers a real observation.
    h.seed_ewma(3.0)
    assert h.ewma_ms_per_row == 3.0
    h.seed_ewma(99.0)
    assert h.ewma_ms_per_row == 3.0


def test_pool_revive_reseeds_from_siblings():
    x, y = _data()
    pm = _chain(x, y)
    pool = _pool(pm, x, n_replicas=2, name="revive_seed_pool").start()
    try:
        for i in range(6):
            pool.predict({"features": x[i:i + 3]})
        with faults.armed(faults.FaultPlan(faults.ReplicaDown("r0"))):
            pool.predict({"features": x[:2]})  # retires r0
        assert pool.stats()["per_replica"]["r0"]["state"] == "unhealthy"
        # Pollute the dead replica's ledger as its death throes would.
        pool.replicas[0].health.ewma_ms_per_row = 1e6
        pool.replicas[0].health.outstanding_rows = 999
        pool.revive("r0")
        h = pool.replicas[0].health
        assert h.outstanding_rows == 0
        sibling = pool.replicas[1].health.ewma_ms_per_row
        assert h.ewma_ms_per_row == sibling  # median of 1 sibling
        resp = pool.predict({"features": x[:2]})
        assert resp.columns["prediction"].shape == (2,)
    finally:
        pool.stop()


def test_multimodel_revive_is_model_aware(tmp_path):
    """Regression: MultiModelPool.revive used to inherit the base
    pool's registry re-sync, which dereferences the pool-level registry
    — always None for a multi-model pool — and crashed with
    AttributeError after follow_registries(); the revived replica must
    instead re-sync through its OWN model's registry."""
    from flinkml_tpu.serving import ModelRegistry

    x, y = _data()
    pm = _chain(x, y)
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(pm)
    mm = MultiModelPool(
        Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=32, max_queue_rows=64,
                             max_wait_ms=1.0),
        name="revive_mm",
    )
    mm.add_model("m", reg, slo=INTERACTIVE, n_replicas=2)
    mm.start()
    mm.follow_registries()
    try:
        with faults.armed(faults.FaultPlan(faults.ReplicaDown("r0"))):
            mm.predict("m", {"features": x[:2]})  # retires r0
        assert mm.replicas[0].health.state.value == "unhealthy"
        reg.publish(_chain(x, 1.0 - y))  # v2 rolls only the live replica
        mm.revive("r0")  # used to raise AttributeError here
        assert mm.replicas[0].health.state.value == "healthy"
        # Re-synced through ITS model's registry to the current version.
        assert mm.replicas[0].engine.active_version == 2
        resp = mm.predict("m", {"features": x[:2]})
        assert resp.version == 2
    finally:
        mm.stop()
