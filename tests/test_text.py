"""Tokenizer / RegexTokenizer / HashingTF / CountVectorizer / IDF:
semantics vs sklearn and end-to-end sparse text classification."""

import numpy as np
import pytest
from sklearn.feature_extraction.text import (
    CountVectorizer as SkCount,
    TfidfTransformer,
)

from flinkml_tpu.models import (
    CountVectorizer,
    CountVectorizerModel,
    HashingTF,
    IDF,
    IDFModel,
    RegexTokenizer,
    Tokenizer,
)
from flinkml_tpu.table import Table

DOCS = [
    "the cat sat on the mat",
    "the dog ate the cat",
    "dogs and cats are friends",
    "the mat was red",
]


def _docs_table():
    return Table({"text": np.asarray(DOCS)})


def test_tokenizer_lowercase_split():
    t = Table({"text": np.asarray(["Hello World", "  a  B c "])})
    (out,) = Tokenizer().set_input_col("text").set_output_col("tok").transform(t)
    assert out["tok"][0] == ["hello", "world"]
    assert out["tok"][1] == ["a", "b", "c"]


def test_regex_tokenizer_gaps_and_tokens():
    t = Table({"text": np.asarray(["foo,bar;;baz", "One-Two"])})
    (gaps,) = (
        RegexTokenizer().set_input_col("text").set_output_col("tok")
        .set_pattern(r"[,;]+").transform(t)
    )
    assert gaps["tok"][0] == ["foo", "bar", "baz"]
    (toks,) = (
        RegexTokenizer().set_input_col("text").set_output_col("tok")
        .set_pattern(r"\w+").set_gaps(False).set_to_lowercase(False)
        .transform(t)
    )
    assert toks["tok"][1] == ["One", "Two"]
    (minlen,) = (
        RegexTokenizer().set_input_col("text").set_output_col("tok")
        .set_pattern(r"\w+").set_gaps(False).set_min_token_length(4)
        .transform(t)
    )
    assert minlen["tok"][0] == []


def _tokenized():
    (out,) = Tokenizer().set_input_col("text").set_output_col("tok").transform(
        _docs_table()
    )
    return out


def test_hashing_tf_counts_and_determinism():
    tokens = _tokenized()
    tf = HashingTF().set_input_col("tok").set_output_col("tf").set_num_features(64)
    (out,) = tf.transform(tokens)
    v0 = out["tf"][0]
    assert v0.size() == 64
    # "the" appears twice in doc 0 — some bucket holds 2.0.
    assert 2.0 in v0.values.tolist()
    assert float(v0.values.sum()) == 6.0  # six tokens in doc 0
    # Deterministic across instances (crc32, not salted hash()).
    (out2,) = (
        HashingTF().set_input_col("tok").set_output_col("tf")
        .set_num_features(64).transform(tokens)
    )
    assert out2["tf"][0] == v0
    # Binary mode: presence only.
    (binary,) = (
        HashingTF().set_input_col("tok").set_output_col("tf")
        .set_num_features(64).set_binary(True).transform(tokens)
    )
    assert set(binary["tf"][0].values.tolist()) == {1.0}


def test_count_vectorizer_matches_sklearn():
    tokens = _tokenized()
    model = (
        CountVectorizer().set_input_col("tok").set_output_col("tf").fit(tokens)
    )
    sk = SkCount(analyzer=str.split, lowercase=False).fit(DOCS)
    assert set(model.vocabulary.tolist()) == set(sk.get_feature_names_out())
    (out,) = model.transform(tokens)
    ref = sk.transform(DOCS).toarray()
    # Same counts after aligning vocab orders.
    ours_order = {t: i for i, t in enumerate(model.vocabulary)}
    perm = [ours_order[t] for t in sk.get_feature_names_out()]
    got = np.stack([v.to_array() for v in out["tf"]])[:, perm]
    np.testing.assert_array_equal(got, ref)
    # Vocabulary is ordered by corpus count desc ("the" is most frequent).
    assert model.vocabulary[0] == "the"


def test_count_vectorizer_df_bounds_and_vocab_size():
    tokens = _tokenized()
    # minDF=2 docs: keeps only terms in >= 2 documents.
    m = (
        CountVectorizer().set_input_col("tok").set_output_col("tf")
        .set_min_d_f(2.0).fit(tokens)
    )
    assert set(m.vocabulary.tolist()) == {"the", "cat", "mat"}
    # maxDF as fraction: drop terms in > 50% of docs ("the" is in 3/4).
    m2 = (
        CountVectorizer().set_input_col("tok").set_output_col("tf")
        .set_max_d_f(0.5).fit(tokens)
    )
    assert "the" not in m2.vocabulary.tolist()
    # vocabularySize keeps the top terms.
    m3 = (
        CountVectorizer().set_input_col("tok").set_output_col("tf")
        .set_vocabulary_size(2).fit(tokens)
    )
    assert len(m3.vocabulary) == 2 and m3.vocabulary[0] == "the"


def test_count_vectorizer_min_tf_and_binary():
    tokens = _tokenized()
    m = (
        CountVectorizer().set_input_col("tok").set_output_col("tf")
        .set_min_t_f(2.0).fit(tokens)
    )
    (out,) = m.transform(tokens)
    # Doc 0: only "the" (count 2) survives minTF=2.
    assert out["tf"][0].values.tolist() == [2.0]
    m2 = (
        CountVectorizer().set_input_col("tok").set_output_col("tf")
        .set_binary(True).fit(tokens)
    )
    (bout,) = m2.transform(tokens)
    assert set(bout["tf"][0].values.tolist()) == {1.0}


def test_count_vectorizer_save_load(tmp_path):
    tokens = _tokenized()
    model = CountVectorizer().set_input_col("tok").set_output_col("tf").fit(tokens)
    model.save(str(tmp_path / "cv"))
    loaded = CountVectorizerModel.load(str(tmp_path / "cv"))
    np.testing.assert_array_equal(loaded.vocabulary, model.vocabulary)
    assert loaded.transform(tokens)[0]["tf"][2] == model.transform(tokens)[0]["tf"][2]


def test_idf_matches_sklearn_formula(tmp_path):
    tokens = _tokenized()
    cv = CountVectorizer().set_input_col("tok").set_output_col("tf").fit(tokens)
    (tf_table,) = cv.transform(tokens)
    idf_model = IDF().set_input_col("tf").set_output_col("tfidf").fit(tf_table)
    # sklearn's smooth_idf uses log((n+1)/(df+1)) + 1; ours omits the +1.
    sk = SkCount(analyzer=str.split, lowercase=False).fit(DOCS)
    counts = sk.transform(DOCS)
    sk_idf = TfidfTransformer(smooth_idf=True, norm=None).fit(counts).idf_ - 1.0
    ours_order = {t: i for i, t in enumerate(cv.vocabulary)}
    perm = [ours_order[t] for t in sk.get_feature_names_out()]
    np.testing.assert_allclose(idf_model.idf[perm], sk_idf, rtol=1e-12)
    # Transform scales counts by idf.
    (out,) = idf_model.transform(tf_table)
    got = np.stack([v.to_array() for v in out["tfidf"]])[:, perm]
    ref = counts.toarray() * sk_idf
    np.testing.assert_allclose(got, ref, rtol=1e-12)
    # Persistence.
    idf_model.save(str(tmp_path / "idf"))
    loaded = IDFModel.load(str(tmp_path / "idf"))
    np.testing.assert_array_equal(loaded.idf, idf_model.idf)


def test_idf_min_doc_freq_and_dense_input():
    x = np.asarray([[1.0, 0.0, 3.0], [2.0, 0.0, 0.0], [0.0, 1.0, 0.0]])
    t = Table({"tf": x})
    model = IDF().set_input_col("tf").set_output_col("o").set_min_doc_freq(2).fit(t)
    # df = [2, 1, 1]: features 1 and 2 get idf 0.
    assert model.idf[1] == 0.0 and model.idf[2] == 0.0 and model.idf[0] > 0
    (out,) = model.transform(t)
    np.testing.assert_allclose(out["o"][:, 1:], 0.0)


def test_text_pipeline_trains_sparse_lr():
    from flinkml_tpu.models import LogisticRegression
    from flinkml_tpu.pipeline import Pipeline

    rng = np.random.default_rng(0)
    pos_words = ["good", "great", "excellent", "love"]
    neg_words = ["bad", "awful", "terrible", "hate"]
    filler = ["the", "movie", "was", "a", "film", "it"]
    docs, labels = [], []
    for _ in range(120):
        y = rng.integers(0, 2)
        pool = pos_words if y else neg_words
        words = list(rng.choice(pool, 3)) + list(rng.choice(filler, 4))
        rng.shuffle(words)
        docs.append(" ".join(words))
        labels.append(float(y))
    t = Table({"text": np.asarray(docs), "label": np.asarray(labels)})
    pipe = Pipeline([
        Tokenizer().set_input_col("text").set_output_col("tok"),
        HashingTF().set_input_col("tok").set_output_col("features")
        .set_num_features(256),
        LogisticRegression().set_max_iter(60).set_global_batch_size(120)
        .set_learning_rate(1.0).set_seed(0),
    ])
    pm = pipe.fit(t)
    (pred,) = pm.transform(t)
    assert (pred["prediction"] == t["label"]).mean() > 0.95


def test_hashing_tf_num_features_change_rehashes():
    tokens = _tokenized()
    tf = HashingTF().set_input_col("tok").set_output_col("tf")
    (big,) = tf.set_num_features(1024).transform(tokens)
    (small,) = tf.set_num_features(8).transform(tokens)
    for v in small["tf"]:
        assert v.size() == 8
        assert v.indices.max(initial=0) < 8
    assert big["tf"][0].size() == 1024
