"""flinkml_tpu.features — hash front end + incremental delta publishes.

Pins, by area:

- **hash contract** — murmur3_x86_32 against published reference
  vectors, the committed golden vectors (``tests/golden_hash_vectors.
  json`` — a diff there is a model-breaking change), vectorized ==
  scalar bit parity, and cross-process determinism under different
  ``PYTHONHASHSEED`` values (``tests/_hash_child.py``).
- **FML505** — the buckets-vs-vocab gate, live (``check_hash_vocab`` /
  model construction) and as an analysis fixture pass
  (``bad_hash_fml505_bucket_vocab_mismatch.features.json``).
- **row patch** — ``EmbeddingTable.apply_row_delta`` /
  ``clone_with_row_delta``: sharded == unsharded == fresh placement,
  bitwise.
- **delta chain** — publish/resolve parity with a full snapshot,
  pruned-base and corrupted-mid-chain regressions raising
  :class:`DeltaChainError` naming the broken link, compaction at
  ``max_depth``.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from flinkml_tpu.data import ArraySource, Dataset
from flinkml_tpu.embeddings.table import EmbeddingTable
from flinkml_tpu.features import (
    CollisionTracker,
    DeltaPublisher,
    HashedFMModel,
    HashedFeature,
    HashVocabMismatchError,
    ModelDelta,
    StreamingHashedFMTrainer,
    check_hash_vocab,
    expected_collision_fraction,
    hash_buckets,
    murmur3_32,
)
from flinkml_tpu.features.hashing import _hash_ints_vectorized, _key_bytes
from flinkml_tpu.io.read_write import content_fingerprint
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.serving.errors import DeltaChainError
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.sharding.plan import EMBEDDING
from flinkml_tpu.table import Table
from flinkml_tpu.utils.metrics import metrics

_HERE = os.path.dirname(__file__)
_GOLDEN = os.path.join(_HERE, "golden_hash_vectors.json")


# ---------------------------------------------------------------------------
# Hash contract
# ---------------------------------------------------------------------------

def test_murmur3_published_reference_vectors():
    """The scalar reference implements murmur3_x86_32 exactly — pinned
    against independently published vectors, not our own output."""
    vectors = [
        (b"", 0, 0x00000000),
        (b"", 1, 0x514E28B7),
        (b"", 0xFFFFFFFF, 0x81F16F39),
        (b"hello", 0, 0x248BFA47),
        (b"hello, world", 0, 0x149BBB7F),
        (b"The quick brown fox jumps over the lazy dog",
         0x9747B28C, 0x2FA826CD),
        (b"abc", 0, 0xB3DD93FA),
    ]
    for data, seed, want in vectors:
        assert murmur3_32(data, seed) == want, (data, seed)


def test_golden_vectors_committed():
    """Recompute every committed golden vector: a mismatch means the
    hash changed and every trained row id with it — that must be a loud
    diff, never a silent rehash."""
    with open(_GOLDEN) as f:
        golden = json.load(f)
    for seed_s, entries in golden["hashes"].items():
        for key_repr, want in entries.items():
            key = eval(key_repr)  # noqa: S307 — our own committed reprs
            assert murmur3_32(_key_bytes(key), int(seed_s)) == want, (
                seed_s, key_repr)
    for buckets_s, entries in golden["buckets"].items():
        for key_repr, want in entries.items():
            key = eval(key_repr)  # noqa: S307
            got = int(hash_buckets([key], seed=42,
                                   num_buckets=int(buckets_s))[0])
            assert got == want, (buckets_s, key_repr)


def test_vectorized_int_path_bitwise_matches_scalar():
    keys = np.array([0, 1, -1, 7, 2**31, -(2**31), 123456789,
                     2**63 - 1, -(2**63)], np.int64)
    vec = _hash_ints_vectorized(keys, 42)
    scalar = [murmur3_32(_key_bytes(int(k)), 42) for k in keys]
    assert [int(v) for v in vec] == [int(s) for s in scalar]


def test_hash_buckets_range_padding_and_types():
    ids = hash_buckets(["a", "b", 17, b"raw"], seed=3, num_buckets=100)
    assert ids.dtype == np.int32
    assert ((ids >= 0) & (ids < 100)).all()
    padded = hash_buckets(["a", "", "b"], seed=3, num_buckets=100,
                          pad_key="")
    assert padded[1] == -1 and padded[0] == ids[0]
    # str and the bytes of its utf-8 encoding hash identically (one
    # canonical encoding), while int 7 and str "7" do NOT (different
    # canonical bytes).
    assert int(hash_buckets(["xy"], seed=1, num_buckets=1000)[0]) == int(
        hash_buckets([b"xy"], seed=1, num_buckets=1000)[0])
    assert int(hash_buckets([7], seed=1, num_buckets=10**9)[0]) != int(
        hash_buckets(["7"], seed=1, num_buckets=10**9)[0])


def test_hash_determinism_across_processes_and_hashseed():
    """The hardening pin: two fresh interpreters with DIFFERENT
    ``PYTHONHASHSEED`` values produce bit-identical row ids, both equal
    to the committed golden vectors — proving no ``hash()`` anywhere in
    the path."""
    reports = []
    for seed in ("0", "424242"):
        proc = subprocess.run(
            [sys.executable, os.path.join(_HERE, "_hash_child.py")],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "PYTHONHASHSEED": seed,
                 "PYTHONPATH": os.pathsep.join(
                     [os.path.dirname(_HERE)]
                     + ([os.environ["PYTHONPATH"]]
                        if os.environ.get("PYTHONPATH") else []))},
        )
        assert proc.returncode == 0, (
            f"hash child (PYTHONHASHSEED={seed}) failed:\n"
            f"{proc.stdout}\n{proc.stderr}"
        )
        reports.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    a, b = reports
    assert a["python_hash_seed"] == "0" and b["python_hash_seed"] == "424242"
    assert a["hashes"] == b["hashes"]
    assert a["buckets"] == b["buckets"]
    assert a["vectorized_matches_scalar"] is True
    with open(_GOLDEN) as f:
        golden = json.load(f)
    assert a["hashes"] == golden["hashes"]
    assert a["buckets"] == golden["buckets"]


def test_collision_tracker_counts_and_birthday_estimate():
    tracker = CollisionTracker("clicks", num_buckets=8, seed=5)
    keys = [f"user:{i}" for i in range(64)]
    tracker.observe(keys, hash_buckets(keys, seed=5, num_buckets=8))
    snap = metrics.group("features.hash",
                         labels={"feature": "clicks"}).snapshot()["gauges"]
    assert snap["keys_seen"] == 64
    assert snap["collisions"] > 0  # 64 distinct keys into 8 buckets
    assert 0.0 < snap["collision_rate"] <= 1.0
    # Birthday bound sanity: tiny load → near 0; heavy load → near 1.
    assert expected_collision_fraction(2, 10**6) < 1e-3
    assert expected_collision_fraction(10**4, 8) > 0.99


def test_hashed_feature_as_map_and_stage_and_dataset_op():
    feature = HashedFeature(9, 128, input_col="keys",
                            output_col="hashed_ids")
    t = Table({"keys": np.array(["a", "b", "c", "a"])})
    out = feature(t)
    ids = np.asarray(out.column("hashed_ids"))
    assert ids.shape == (4,) and ids[0] == ids[3]
    (out2,) = feature.transform(t)
    assert np.array_equal(np.asarray(out2.column("hashed_ids")), ids)
    # Dataset op form: 1:1 (skip-transparent) and identical ids.
    ds = Dataset.from_source(
        ArraySource({"keys": np.array([["a"], ["b"], ["c"], ["a"]])},
                    batch_size=2)
    ).hash_column("keys", seed=9, num_buckets=128)
    assert ds.skip_transparent
    batches = list(ds)
    got = np.concatenate(
        [np.asarray(b.column("hashed_ids")).reshape(-1) for b in batches])
    assert np.array_equal(got, ids)


# ---------------------------------------------------------------------------
# FML505
# ---------------------------------------------------------------------------

def test_fml505_live_gate():
    check_hash_vocab(64, 64)  # matching sizes pass
    with pytest.raises(HashVocabMismatchError, match="FML505"):
        check_hash_vocab(64, 128, where="test")
    with pytest.raises(HashVocabMismatchError, match="FML505"):
        HashedFMModel.from_arrays(
            np.zeros(1), np.zeros((32, 1)), np.zeros((32, 4)),
            num_buckets=64,
        )


def test_fml505_fixture_fails_analysis_gate():
    from flinkml_tpu.analysis.features_check import check_features_file

    fixture = os.path.join(
        _HERE, "analysis_fixtures",
        "bad_hash_fml505_bucket_vocab_mismatch.features.json")
    findings = check_features_file(fixture)
    assert findings and all(f.rule == "FML505" for f in findings)
    assert any("4096" in f.message and "2048" in f.message
               for f in findings)
    # A matching config passes clean.
    good = {"hash": {"seed": 1, "numBuckets": 256},
            "table": {"vocab": 256, "dim": 8}}
    path = os.path.join(_HERE, "analysis_fixtures")
    import tempfile
    with tempfile.NamedTemporaryFile(
            "w", suffix=".features.json", delete=False) as f:
        json.dump(good, f)
    try:
        assert check_features_file(f.name) == []
    finally:
        os.unlink(f.name)
    assert os.path.isdir(path)


# ---------------------------------------------------------------------------
# EmbeddingTable row patch
# ---------------------------------------------------------------------------

def _patch_case():
    rng = np.random.default_rng(0)
    rows = rng.standard_normal((100, 8)).astype(np.float32)
    ids = np.array([0, 5, 13, 57, 99], np.int32)
    vals = rng.standard_normal((5, 8)).astype(np.float32)
    want = rows.copy()
    want[ids] = vals
    return rows, ids, vals, want


def test_apply_row_delta_unsharded():
    rows, ids, vals, want = _patch_case()
    t = EmbeddingTable("p0", 100, 8, rows=rows)
    clone = t.clone_with_row_delta(ids, vals)
    assert np.array_equal(clone.to_host(), want)
    assert np.array_equal(t.to_host(), rows), "clone mutated the original"
    t.apply_row_delta(ids, vals)
    assert np.array_equal(t.to_host(), want)


def test_apply_row_delta_sharded_bitwise_equals_fresh_placement():
    """The acceptance anchor: a sharded in-place patch must be bitwise
    what a full re-placement of the patched snapshot would produce — a
    SET on the owning shard, not an arithmetic trick."""
    rows, ids, vals, want = _patch_case()
    mesh = DeviceMesh.for_plan(EMBEDDING)
    t = EmbeddingTable("p1", 100, 8, mesh=mesh, plan=EMBEDDING, rows=rows)
    assert t.sharded and t.n_shards == 8
    clone = t.clone_with_row_delta(ids, vals)
    assert np.array_equal(clone.to_host(), want)
    assert np.array_equal(t.to_host(), rows)
    fresh = EmbeddingTable("p2", 100, 8, mesh=mesh, plan=EMBEDDING,
                           rows=want)
    assert np.array_equal(np.asarray(clone.rows), np.asarray(fresh.rows))
    assert np.array_equal(np.asarray(clone.lookup(ids)), vals)


def test_apply_row_delta_validation():
    t = EmbeddingTable("p3", 10, 4, rows=np.zeros((10, 4), np.float32))
    with pytest.raises(ValueError, match="duplicate"):
        t.apply_row_delta([1, 1], np.zeros((2, 4), np.float32))
    with pytest.raises(ValueError, match="out of range"):
        t.apply_row_delta([10], np.zeros((1, 4), np.float32))
    with pytest.raises(ValueError, match="shape"):
        t.apply_row_delta([1], np.zeros((1, 3), np.float32))


# ---------------------------------------------------------------------------
# ModelDelta + HashedFMModel
# ---------------------------------------------------------------------------

def test_model_delta_build_roundtrip(tmp_path):
    ids = np.array([2, 7], np.int32)
    vals = np.arange(8, dtype=np.float32).reshape(2, 4)
    delta = ModelDelta.build(
        base_version=3, base_fingerprint="aa", result_fingerprint="bb",
        watermark=17, depth=2,
        row_deltas={"v": (ids, vals)},
        dense_deltas={"w0": np.array([0.5], np.float32)},
    )
    path = str(tmp_path / "delta")
    delta.save(path)
    loaded = ModelDelta.load(path)
    assert loaded.base_version == 3 and loaded.depth == 2
    assert loaded.watermark == 17
    assert loaded.base_fingerprint == "aa"
    assert loaded.result_fingerprint == "bb"
    (got_ids, got_vals) = loaded.row_deltas()["v"]
    assert np.array_equal(got_ids, ids)
    assert np.array_equal(got_vals, vals)
    assert np.array_equal(loaded.dense_deltas()["w0"], [0.5])
    with pytest.raises(TypeError, match="not servable"):
        loaded.transform(Table({"x": np.zeros(1)}))
    with pytest.raises(ValueError, match="unique"):
        ModelDelta.build(
            base_version=1, base_fingerprint="", result_fingerprint="",
            watermark=0, depth=1,
            row_deltas={"v": (np.array([1, 1]), np.zeros((2, 4)))},
        )


def test_hashed_fm_model_save_load_and_margin(tmp_path):
    rng = np.random.default_rng(1)
    w0 = np.array([0.3], np.float32)
    w = rng.standard_normal((32, 1)).astype(np.float32)
    v = rng.standard_normal((32, 4)).astype(np.float32)
    model = HashedFMModel.from_arrays(w0, w, v, num_buckets=32, hash_seed=9)
    ids = np.array([[1, 5, -1], [3, 3, 7]], np.int64)
    (out,) = model.transform(Table({"ids": ids}))
    margin = np.asarray(out.column("rawPrediction"))
    # Hand-computed FM identity for row 0 ({1, 5}; -1 masked):
    sv = v[1] + v[5]
    want0 = (w0[0] + w[1, 0] + w[5, 0]
             + 0.5 * ((sv * sv) - v[1] ** 2 - v[5] ** 2).sum())
    np.testing.assert_allclose(margin[0], want0, rtol=1e-5)
    prob = np.asarray(out.column("prediction"))
    np.testing.assert_allclose(prob, 1.0 / (1.0 + np.exp(-margin)),
                               rtol=1e-6)
    path = str(tmp_path / "m")
    model.save(path)
    loaded = HashedFMModel.load(path)
    (out2,) = loaded.transform(Table({"ids": ids}))
    assert np.array_equal(np.asarray(out2.column("rawPrediction")), margin)


def test_apply_delta_returns_new_model_and_rejects_unknown_leaves():
    model = HashedFMModel.from_arrays(
        np.zeros(1), np.zeros((8, 1), np.float32),
        np.zeros((8, 4), np.float32), num_buckets=8)
    delta = ModelDelta.build(
        base_version=1, base_fingerprint="", result_fingerprint="",
        watermark=1, depth=1,
        row_deltas={"v": (np.array([2]), np.ones((1, 4), np.float32))},
        dense_deltas={"w0": np.array([1.5], np.float32)},
    )
    patched = model.apply_delta(delta)
    assert patched is not model
    assert model.v[2].sum() == 0.0, "apply_delta mutated the base"
    assert np.array_equal(patched.v[2], np.ones(4, np.float32))
    assert patched.w0[0] == 1.5
    bad = ModelDelta.build(
        base_version=1, base_fingerprint="", result_fingerprint="",
        watermark=1, depth=1,
        row_deltas={"nope": (np.array([0]), np.zeros((1, 4)))},
    )
    with pytest.raises(KeyError, match="nope"):
        model.apply_delta(bad)


# ---------------------------------------------------------------------------
# Registry delta chain
# ---------------------------------------------------------------------------

def _trained(n_batches=6, num_buckets=32, key_range=200, **kwargs):
    rng = np.random.default_rng(7)
    tr = StreamingHashedFMTrainer(num_buckets=num_buckets, factor_size=4,
                                  learning_rate=0.1, **kwargs)

    def feed(k):
        for _ in range(k):
            keys = rng.integers(0, key_range, size=(16, 3))
            ids = hash_buckets(keys.reshape(-1), seed=1,
                               num_buckets=num_buckets).reshape(16, 3)
            tr.fit_batch(ids, (keys.sum(axis=1) % 2).astype(np.float32))
    feed(n_batches)
    return tr, feed


def test_delta_publish_resolves_bitwise_to_full_snapshot(tmp_path):
    tr, feed = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = DeltaPublisher(reg, tr, every_n_batches=1, max_depth=10)
    # serving.registry is one process-global metrics group; count from
    # here so the assertions hold in any suite order.
    base = dict(reg._metrics.snapshot()["counters"])
    pub.publish_now()                    # base snapshot
    feed(3)
    pub.publish_now()
    feed(2)
    v = pub.publish_now()
    assert reg.versions() == [1, 2, 3] and v == 3
    assert pub.chain_depth == 2
    got_v, resolved = reg.get()
    assert got_v == 3
    full = tr.make_model()
    for name, arr in full.delta_state().items():
        assert np.array_equal(resolved.delta_state()[name], arr), name
    ids = np.array([[1, 5, 9], [2, 2, -1]], np.int64)
    t = Table({"hashed_ids": ids})
    (a,) = resolved.transform(t)
    (b,) = full.transform(t)
    assert np.array_equal(np.asarray(a.column("prediction")),
                          np.asarray(b.column("prediction")))
    # Watermarks rode each publish atomically.
    assert reg.watermark_of(1) == 6
    assert reg.watermark_of(3) == 11 == reg.latest_watermark()
    # delta_chain finds the suffix (and refuses a non-chain).
    assert len(reg.delta_chain(1, 3)) == 2
    assert len(reg.delta_chain(2, 3)) == 1
    assert reg.delta_chain(3, 3) is None
    assert reg.delta_chain(2, 1) is None
    snap = reg._metrics.snapshot()["counters"]
    assert snap["delta_publishes"] - base.get("delta_publishes", 0) == 2
    assert snap["full_publishes"] - base.get("full_publishes", 0) == 1
    assert snap["delta_loads"] - base.get("delta_loads", 0) >= 1


def test_delta_chain_pruned_base_raises_named_error(tmp_path):
    tr, feed = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = DeltaPublisher(reg, tr, every_n_batches=1, max_depth=10)
    pub.publish_now()
    feed(1)
    pub.publish_now()
    feed(1)
    pub.publish_now()
    shutil.rmtree(reg.path_of(2))        # prune the mid-chain base
    with pytest.raises(DeltaChainError) as exc:
        reg.get(3)
    msg = str(exc.value)
    assert "3" in msg and "2" in msg and "pruned" in msg
    # NOT a silent fresh start: version 1 still resolves fine.
    _, base = reg.get(1)
    assert isinstance(base, HashedFMModel)


def test_delta_chain_corrupted_mid_chain_fingerprint(tmp_path):
    """Regression: a mid-chain delta whose base fingerprint does not
    match the state it claims to patch is refused with the exact broken
    link named — never silently applied onto the wrong base."""
    tr, feed = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = DeltaPublisher(reg, tr, every_n_batches=1, max_depth=10)
    pub.publish_now()                    # v1 base
    feed(1)
    ids = tr.drain_touched()
    corrupted = ModelDelta.build(
        base_version=1,
        base_fingerprint="0" * 64,       # wrong on purpose
        result_fingerprint=tr.state_fingerprint(),
        watermark=tr.watermark, depth=1,
        row_deltas={name: (ids, vals)
                    for name, vals in tr.rows_for(ids).items()},
        dense_deltas={"w0": np.asarray(tr.w0)},
    )
    reg.publish(corrupted, watermark=tr.watermark)   # v2
    with pytest.raises(DeltaChainError) as exc:
        reg.get(2)
    msg = str(exc.value)
    assert "version 2" in msg and "base 1" in msg and "fingerprint" in msg
    # A result-fingerprint lie is caught the same way.
    feed(1)
    ids = tr.drain_touched()
    lying = ModelDelta.build(
        base_version=1,
        base_fingerprint=content_fingerprint(reg.get(1)[1].delta_state()),
        result_fingerprint="f" * 64,     # wrong on purpose
        watermark=tr.watermark, depth=1,
        row_deltas={name: (ids, vals)
                    for name, vals in tr.rows_for(ids).items()},
        dense_deltas={"w0": np.asarray(tr.w0)},
    )
    v = reg.publish(lying, watermark=tr.watermark)
    with pytest.raises(DeltaChainError, match="result fingerprint"):
        reg.get(v)


def test_publisher_compacts_at_max_depth_and_prices_bytes(tmp_path):
    # A sparse-touch regime (few hot keys in a big bucket space): the
    # whole point of a delta is that it ships only the touched rows.
    tr, feed = _trained(num_buckets=1024, key_range=8)
    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = DeltaPublisher(reg, tr, every_n_batches=1, max_depth=2,
                         name="compact")
    pub.publish_now()                    # v1 full (depth 0)
    for _ in range(4):
        feed(1)
        pub.publish_now()                # d1, d2, full (compaction), d1
    assert pub.chain_depth == 1
    snap = metrics.group("features.publisher",
                         labels={"publisher": "compact"}).snapshot()
    assert snap["counters"]["compactions"] == 1
    assert snap["counters"]["full_publishes"] == 2
    assert snap["counters"]["delta_publishes"] == 3
    # Deltas must be (much) smaller than the full state they stand for.
    assert 0.0 < snap["gauges"]["delta_ratio"] < 1.0
    # The compacted version resolves directly (no chain walk).
    _, model = reg.get(4)
    assert isinstance(model, HashedFMModel)
